"""Continuous-batching serving engine over the stacked KV ring cache.

Capability parity: the serving loop the reference's AnalysisPredictor +
fused_multi_transformer stack is deployed behind (and the Orca/vLLM-style
slot scheduling production LLM serving converged on), realized TPU-style
on top of FusedDecoder's machinery:

  * ONE decode step is compiled for a fixed shape — B cache slots over
    the stacked ring buffer [L, 2, B, H, Smax, D] — and stays hot while
    requests churn through the slots. Admission, completion, and slot
    reuse are pure DATA (per-slot `cache_lens`, active masks, per-slot
    sampling params all ride in as arrays), so request churn causes ZERO
    retraces and zero recompiles after warmup.
  * Each slot decodes at its OWN depth: the per-row position path in
    generation.py (vector `t`) drives the same Pallas flash-decode
    kernels, which always took per-row `cache_lens`.
  * In-slot prefill: a freed slot is overwritten by the next queued
    request via the chunked prefill scan with a per-row WRITE MASK —
    non-admitted rows' live cache rows are untouchable by construction
    (masked rows scatter out of bounds and are dropped).
  * Slot eviction = resetting `cache_lens[b]` host-side; nothing is
    zeroed. The decode_attention write kernels' `cache_lens < Smax`
    invariant (enforced at submit: prompt + max_new_tokens <= Smax)
    guarantees a dead slot can never write out of its row.

Host control happens only at chunk boundaries: every `decode_chunk`
tokens the engine harvests per-slot streams, completes finished
requests, admits from the queue, and emits a metrics record (tokens/s,
TTFT, queue depth, slot occupancy, step latency, trace count).

Automatic prefix caching (`prefix_cache_blocks=` / a shared
`PrefixCache`): admission first splats the longest PUBLISHED prefix of
the prompt into the slot's cache row — one compiled block gather-copy
over a pow-2 chain-length ladder, write-masked like in-slot prefill —
and only the uncached suffix runs through the chunked prefill scan; as
prefill lands, the prompt's full `prefill_cap`-sized blocks are
committed back to the pool (copy-out, dedup'd) so later shared-prompt
requests hit. See prefix_cache.py for the radix store / COW invariants.

Paged KV cache (default; `PADDLE_SERVING_PAGED=0` keeps the dense
per-slot ring for parity testing): ONE BlockPool
`[L, 2, NBtotal, H, Bt, D]` holds every KV block — slots, prefix-cache
entries, and spec-verify writes — and each slot's sequence is a block
TABLE `[Smax/Bt]` of pool indices living here as pure data
(paged_kv.py). Decode/verify attention gathers through the table
(paged Pallas kernels / gather-dense fallback), K/V writes scatter
through it under the same `cache_lens < Smax` clamp discipline, prefix
hits become index writes (zero-copy adopt, zero-copy publish), blocks
map lazily as `lens` grows and free on eviction, and copy-on-write
makes `fork_slot` (parallel sampling) nearly free. Slot capacity is
bounded by the POOL, not `B x Smax`: `kv_pool_blocks=` /
`PADDLE_SERVING_KV_BLOCKS` states a memory budget (explicitly sized
pools shed honestly with `AdmissionFull` when commitments exceed it);
the default sizing `B x Smax/Bt` equals the dense HBM footprint and
never sheds. `metrics()` exposes `kv_blocks_used/free/total`.

Token-budget scheduling (default; `token_budget=` /
`PADDLE_SERVING_TOKEN_BUDGET`, 0 restores the legacy phase-prefill
scheduler): every compiled step spends a fixed token budget mixing
decode rows (one input token + any draft claim each) with prefill
chunks from admitted-but-unprefilled slots — Sarathi-style chunked
prefill. Admission is pure bookkeeping (slots enter a `prefilling`
state; the budget packer advances them through spare step capacity),
so one long prompt can no longer hold the whole decode gang hostage
and TTFT p99 stays flat under load. The ONE [B, C]-column budget core
(generation._build_budget_core) generalizes the spec-verify block to
per-row segment lengths: segments, drafts, prefill cursors are all
data, so every packing the scheduler can emit reuses one executable.
Sampled mode draws each token from fold_in(request_seed, position)
(generation._sample_rows), making sampled outputs EXACTLY invariant
to the scheduler — the chunked-vs-phase parity tests pin token
equality in both greedy and sampled mode.

Token-FLATTENED budget layout (`PADDLE_SERVING_FLAT_BUDGET=1` /
`flat_budget=True`; row-aligned stays the default): the [B, C] block
computes every masked column — a lone long prefill wastes (B-1) x C
positions per dispatch. Flat mode packs the SAME work as ONE ragged
[T] token stream (a B-wide decode region plus back-to-back segments
with eighth-octave ladder width) with per-token (slot, pos) indices,
so T real tokens cost ~T computed positions (`budget_padding_tokens`
~ 0) and one prefill segment can span the whole spare budget, not C
columns; prefill chunks attend via a block-flash Pallas kernel
(decode_attention_paged_flat) with the gather-dense fallback as the
parity path. Token outputs are EXACTLY the row layout's, greedy and
sampled (tests/test_flat_budget.py).

Telemetry (telemetry.py; `telemetry_ring=` / `PADDLE_TELEMETRY_RING`,
0 disables collection): per-request lifecycle spans and a per-dispatch
step timeline in bounded rings, TTFT/latency/tokens-per-step as
fixed-size log-bucketed histograms (the `metrics()` percentile source —
no unbounded scans), `metrics_prometheus()` text exposition with
counters monotonic across `reset_metrics`, `telemetry_snapshot()` as
the cluster-router payload, and
`telemetry.export_chrome_tracing(engine, path)` for Perfetto. All of
it is host bookkeeping: telemetry on adds ZERO device dispatches and
leaves the zero-retrace contract untouched.

Speculative decoding (`spec_k=` / `PADDLE_SERVING_SPEC_K`): a per-slot
model-free n-gram drafter (spec_decode.py) proposes up to K tokens per
step from the request's own context; ONE compiled K+1-position verify
step (generation._build_verify_core) scores them all, and
acceptance/rollback runs here as pure data over the returned logits —
greedy outputs stay token-identical to spec off, sampled outputs keep
the exact target distribution via rejection sampling. Slots with no
usable draft ride along all-masked (the step degrades to a normal
decode step for them), and a thin-draft scheduler heuristic falls back
to the plain decode chunk — both executables are warm, so churn stays
zero-retrace either way.
"""
from __future__ import annotations

import itertools
import os
import time
from collections import deque

import jax
import jax.numpy as jnp
import numpy as np

from ..core.rng import next_key
from ..tensor.tensor import Tensor, no_grad
from .generation import (FusedDecoder, _absmax_int8, _host_seed,
                         _sample_rows, dispatch_kind)
from .telemetry import (COUNTER_FOLD_KEYS, DEFAULT_QOS_SHARES,
                        DEFAULT_RING, QOS_CLASSES, QOS_DEFAULT, QOS_RANK,
                        SloPolicy, Telemetry)

__all__ = ["ServingEngine", "ServedRequest", "AdmissionFull",
           "QOS_CLASSES"]


class AdmissionFull(RuntimeError):
    """submit() rejected: the pending queue is at max_pending (overload
    shedding — the caller backs off or routes elsewhere; the engine never
    buffers unboundedly)."""


class ServedRequest:
    """One request's lifecycle record. States: queued -> running ->
    finished | expired. Times come from the engine clock (injectable for
    virtual-time benchmarking); `ttft_s`/`latency_s` are measured from
    submit."""

    __slots__ = ("rid", "prompt", "max_new_tokens", "eos_token_id",
                 "min_length", "repetition_penalty", "state", "slot",
                 "tokens", "t_submit", "t_admit", "t_first", "t_done",
                 "deadline_s", "seed", "trace_id", "attempt", "priority")

    def __init__(self, rid, prompt, max_new_tokens, eos_token_id,
                 min_length, repetition_penalty, t_submit,
                 deadline_s=None, seed=0, trace_id=None, attempt=1,
                 priority=QOS_DEFAULT):
        self.rid = rid
        self.prompt = prompt                      # np.int32 [S]
        self.max_new_tokens = int(max_new_tokens)
        self.eos_token_id = eos_token_id
        self.min_length = int(min_length)
        self.repetition_penalty = float(repetition_penalty)
        self.state = "queued"
        self.slot = None
        self.tokens = []                          # generated token ids
        self.t_submit = t_submit
        self.t_admit = None                       # slot entry time
        self.t_first = None                       # first token time
        self.t_done = None
        self.deadline_s = None if deadline_s is None else float(deadline_s)
        # per-request sampling seed: the engine's sampled mode draws
        # each generated token from fold_in(PRNGKey(seed), position),
        # so outputs are invariant to scheduling (see _sample_rows)
        self.seed = int(seed)
        # cluster trace context: the gateway/router thread one trace id
        # through every placement of one client request; attempt
        # increments across failover re-submits (telemetry.RequestTrace
        # carries both, so cross-replica spans join on the trace id)
        self.trace_id = None if trace_id is None else str(trace_id)
        self.attempt = int(attempt)
        # QoS class (telemetry.QOS_CLASSES, best first): drives the
        # admission order, the weighted-fair budget shares, and
        # preemption-victim selection — all pure host data
        self.priority = priority

    @property
    def ttft_s(self):
        return (None if self.t_first is None
                else self.t_first - self.t_submit)

    @property
    def latency_s(self):
        return (None if self.t_done is None
                else self.t_done - self.t_submit)

    def result(self):
        return {"rid": self.rid, "tokens": np.asarray(self.tokens,
                                                      np.int32),
                "ttft_s": self.ttft_s, "latency_s": self.latency_s,
                "expired": self.state == "expired"}


class ServingEngine:
    """Slot-based continuous batching over FusedDecoder's compiled step.

    API sketch::

        eng = ServingEngine(fmt, embed, head, num_slots=8,
                            max_seq_len=1024)
        rid = eng.submit(prompt_ids, max_new_tokens=64, eos_token_id=2)
        eng.run()                       # drive until queue + slots drain
        out = eng.results[rid]["tokens"]
        eng.metrics()                   # aggregate engine counters

    `results` retains the most recent `telemetry_ring` finished
    requests (default 2048, `PADDLE_TELEMETRY_RING`) — a long-lived
    service must harvest each result promptly rather than index
    arbitrarily old rids; aggregate totals survive in `metrics()` and
    the Prometheus lifetime counters.

    Streaming readers (the cluster gateway's SSE path) must NOT race
    that cap: `track(rid)` registers an incremental cursor, and a
    tracked request's record is RETAINED past the results cap until
    `harvest_new_tokens(rid)` has returned `done=True` (or
    `release(rid)` drops the cursor). Call `track` before the request
    can finish — registering only after finish falls back to the
    bounded `results` dict, which may already have evicted the entry
    (KeyError, the documented race). `poll(rid)` is the non-destructive
    status read; neither API moves any counter.

    Sampling mode (greedy / top-k / top-p / temperature) is ENGINE
    config — it is baked into the one compiled step. Per-REQUEST knobs
    (eos_token_id, max_new_tokens, min_length, repetition_penalty) are
    data: [B] arrays the compiled step reads, so they never retrace.
    repetition_penalty needs the [B, V] presence-mask carry; enable it
    at construction (`enable_repetition_penalty=True`) — the flag is
    static trace structure. `spec_k=K` turns on speculative decoding
    (see the module docstring): K, like the sampling mode, is baked
    into the ONE compiled verify step; drafts and acceptance are data.
    """

    def __init__(self, fmt, embed, head, num_slots, max_seq_len,
                 do_sample=False, top_k=0, top_p=1.0, temperature=1.0,
                 decode_chunk=None, use_rotary=False,
                 enable_repetition_penalty=False, clock=None,
                 max_pending=None, prefill_cap=None,
                 prefix_cache_blocks=0, prefix_cache=None, spec_k=None,
                 paged=None, kv_pool=None, kv_pool_blocks=None,
                 token_budget=None, flat_budget=None,
                 telemetry_ring=None, slo=None, role=None,
                 weight_quant=None, kv_quant=None):
        # first-class quant config rides the decoder ctor: explicit
        # args win, None defers to the PADDLE_TPU_DECODE_* env knobs;
        # FusedDecoder fail-fasts unknown modes and int4-unpackable
        # model axes (see its ctor / _validate_int4_dims)
        self.dec = FusedDecoder(fmt, embed, head, max_seq_len,
                                use_rotary=use_rotary,
                                weight_quant=weight_quant,
                                kv_quant=kv_quant)
        self.num_slots = int(num_slots)
        # disaggregated serving role (PADDLE_ROLE): "mixed" (default)
        # is today's behavior — prefill and decode share this engine.
        # "prefill" runs prompt processing only: a slot whose prompt
        # completes (first token sampled) is HELD as state "prefilled"
        # (active=False, KV + slot resident) until the cluster router
        # ships it to a decode replica via export_slot/import_slot —
        # the DistServe/Splitwise split that keeps long prompts from
        # stalling decode inter-token latency. "decode" engines run
        # normally (role enforcement is placement-side: the router
        # never routes fresh prompts at them); their import path is
        # the handoff landing zone.
        role = (role if role is not None
                else os.environ.get("PADDLE_ROLE", "mixed"))
        if role not in ("prefill", "decode", "mixed"):
            raise ValueError(
                f"role must be one of ('prefill', 'decode', 'mixed'), "
                f"got {role!r}")
        self.role = role
        self.smax = self.dec.smax
        self.do_sample = bool(do_sample)
        self.top_k, self.top_p = top_k, top_p
        self.temperature = temperature
        self.decode_chunk = int(decode_chunk or
                                os.environ.get("PADDLE_TPU_SERVE_CHUNK",
                                               "4"))
        # pow-2 prefill ladder cap — ONE knob tunes both the prefill
        # chunk ladder and the prefix-cache block size (blocks are
        # prefill-chunk-aligned by construction)
        cap = int(prefill_cap if prefill_cap is not None
                  else os.environ.get("PADDLE_SERVING_PREFILL_CAP", "64"))
        if cap < 1 or cap & (cap - 1):
            raise ValueError(
                f"prefill_cap must be a power of two >= 1, got {cap} "
                "(the prefill ladder and the prefix-block ladder both "
                "key their bounded executable sets on it)")
        self.prefill_cap = cap
        # PAGED KV cache (default; PADDLE_SERVING_PAGED=0 keeps the
        # dense per-slot ring for parity testing): ONE BlockPool
        # [L, 2, NBtotal, H, Bt, D] shared by slots, prefixes, and
        # spec-verify writes, addressed through per-slot block tables
        # [B, Smax/Bt] that live here as pure data. Block size Bt IS
        # prefill_cap — the one knob. Slot capacity is bounded by the
        # POOL (actual token residency), not B x Smax; blocks map
        # lazily as lens grows and free on eviction. A shared dense
        # PrefixCache object forces dense mode (its pool is separate
        # storage). Under an active mp mesh the pool shards by HEAD on
        # the 'mp' axis (init_paged_cache lays it out with a
        # NamedSharding); the allocator, block tables and every
        # scheduler decision stay replicated host data, so paged mode
        # runs under a mesh with zero extra retraces — the only hard
        # requirement is num_heads % mp == 0.
        env_paged = os.environ.get("PADDLE_SERVING_PAGED", "1") != "0"
        want_paged = env_paged if paged is None else bool(paged)
        if want_paged and prefix_cache is not None:
            if paged:
                raise ValueError(
                    "a shared dense PrefixCache cannot back a paged "
                    "engine (its blocks live in separate storage; a "
                    "paged engine's prefix blocks ARE kv pool blocks) "
                    "— pass prefix_cache_blocks= instead, or "
                    "paged=False")
            want_paged = False
        _mesh = self.dec._mesh_mp()
        if want_paged and _mesh is not None:
            mp = dict(_mesh.shape)["mp"]
            nh_ = self.dec.fmt.num_heads
            if nh_ % mp:
                if paged:
                    # only the env/auto default may downgrade silently
                    # — an EXPLICIT paged=True must not quietly hand
                    # back a dense engine (fork_slot would then fail,
                    # the kv gate would never exist)
                    raise ValueError(
                        f"paged=True under an mp={mp} mesh needs "
                        f"num_heads % mp == 0 to shard the pool by "
                        f"head, got num_heads={nh_} — use a divisible "
                        "mesh degree or drop paged= to accept the "
                        "dense fallback")
                import warnings
                warnings.warn(
                    f"serving: paged KV pool disabled — num_heads="
                    f"{nh_} is not divisible by the mesh's mp degree "
                    f"{mp}, so the head-sharded pool layout is "
                    "unavailable; falling back to the dense ring",
                    RuntimeWarning, stacklevel=2)
                want_paged = False
        if _mesh is not None and self.dec._weight_shard_mesh() is None \
                and os.environ.get("PADDLE_SERVING_MESH_WEIGHTS",
                                   "1") != "0":
            # weight sharding wanted (mesh up, knob not opted out) but
            # the model axes don't divide mp: surface the replicated
            # downgrade at bring-up, not as a quiet HBM surprise
            import warnings
            mp = dict(_mesh.shape)["mp"]
            ff_ = int(self.dec.fmt.ffn1_weights[0]._data.shape[-1])
            warnings.warn(
                f"serving: weight sharding disabled — num_heads="
                f"{self.dec.fmt.num_heads} / ffn_dim={ff_} must both "
                f"divide the mesh's mp degree {mp} to shard the "
                "qkv/proj/FFN stacks; weights stay replicated per "
                "device (init_serving_mesh(mp, num_heads=, ffn_dim=) "
                "rejects this layout up front)",
                RuntimeWarning, stacklevel=2)
        self.paged = want_paged
        if weight_quant == "int4" and not self.paged:
            # explicit int4 is a serving-memory commitment: the dense
            # per-slot ring is the parity/bring-up layout (B x Smax HBM
            # regardless of residency), so pairing it with packed
            # weights states two contradictory memory intents — refuse
            # rather than ship a half-quantized engine silently. (The
            # env knob on a dense engine still works for parity runs;
            # only the EXPLICIT ctor pairing fails.)
            raise ValueError(
                "weight_quant='int4' with a dense KV ring: this engine "
                "resolved to the dense layout (PADDLE_SERVING_PAGED=0, "
                "paged=False, a shared dense prefix cache, or an "
                "indivisible head count under a mesh) — int4 packed "
                "weights are a paged-serving memory feature; use "
                "paged=True or drop weight_quant")
        if not self.paged and (kv_pool is not None
                               or kv_pool_blocks is not None):
            raise ValueError(
                "kv_pool/kv_pool_blocks state a paged-pool memory "
                "budget, but this engine resolved to the DENSE layout "
                "(PADDLE_SERVING_PAGED=0, paged=False, a shared dense "
                "prefix cache, or an indivisible head count under an "
                "active mp mesh) — refusing to drop the budget "
                "silently")
        self.pool = None
        self._kv_gate = False
        self._kv_reserved = 0            # running worst-case blocks
        self._kv_committed = 0           # queued + running worst case
        self._cow_copies = 0
        if self.paged:
            from .paged_kv import BlockPool
            nb_env = os.environ.get("PADDLE_SERVING_KV_BLOCKS")
            if kv_pool is not None:
                if kv_pool.block_tokens != cap:
                    raise ValueError(
                        f"BlockPool has block_tokens="
                        f"{kv_pool.block_tokens} but prefill_cap={cap} "
                        "— the pool block, the prefix block, and the "
                        "prefill chunk ladder are ONE knob and must "
                        "agree")
                if kv_pool.used:
                    # the engine owns the pool's DEVICE arrays; an
                    # allocator with live blocks belongs to another
                    # engine's storage (cross-engine pool sharing needs
                    # shared device buffers — not built yet)
                    raise ValueError(
                        "kv_pool already has allocated blocks — one "
                        "BlockPool serves one engine")
                self.pool = kv_pool
            else:
                nb = int(kv_pool_blocks if kv_pool_blocks is not None
                         else nb_env if nb_env
                         else self.num_slots * (self.smax // cap))
                self.pool = BlockPool(nb, cap, self.smax)
            # an EXPLICITLY sized pool is an operator-stated memory
            # budget: submit() sheds honestly (AdmissionFull) when
            # commitments exceed it. The default sizing (B x Smax/Bt ==
            # dense HBM) can always hold every admissible request, so
            # no gate — exact behavioral parity with the dense engine.
            self._kv_gate = (kv_pool is not None
                             or kv_pool_blocks is not None
                             or bool(nb_env))
        # automatic prefix caching: pass a shared PrefixCache (e.g. the
        # one oneshot generate() calls use) or a block budget to build a
        # private one; 0/None = off (legacy behavior, no new dispatches).
        # In paged mode the budget builds a PagedPrefixCache over the
        # SAME pool: adopt/commit become block-table index writes
        # (zero-copy hits) instead of compiled gather/splat copies.
        if prefix_cache is not None:
            from .prefix_cache import PrefixCache
            if not isinstance(prefix_cache, PrefixCache):
                # a PagedPrefixCache is engine-PRIVATE (its blocks live
                # in one engine's pool and tables) — accepting it here
                # would die later with an AttributeError in _admit
                raise ValueError(
                    f"prefix_cache= takes a shareable dense PrefixCache"
                    f", got {type(prefix_cache).__name__} — paged "
                    "engines build their own via prefix_cache_blocks=")
            if prefix_cache.block_tokens != self.prefill_cap:
                raise ValueError(
                    f"shared prefix cache has block_tokens="
                    f"{prefix_cache.block_tokens} but prefill_cap="
                    f"{self.prefill_cap} — the block and prefill ladders "
                    "must align")
            self.prefix_cache = prefix_cache
        elif prefix_cache_blocks:
            if self.paged:
                from .paged_kv import PagedPrefixCache
                self.prefix_cache = PagedPrefixCache(
                    int(prefix_cache_blocks), self.prefill_cap,
                    self.pool)
            else:
                from .prefix_cache import PrefixCache
                self.prefix_cache = PrefixCache(int(prefix_cache_blocks),
                                                self.prefill_cap)
        else:
            self.prefix_cache = None
        self._prefix_hits = 0
        self._prefix_misses = 0
        self._pc_mesh_warned = False
        self._prefill_tokens_saved = 0
        self._prefill_tokens_computed = 0
        self._rep_on = bool(enable_repetition_penalty)
        self.clock = clock or time.perf_counter
        # telemetry subsystem (telemetry.py): per-request lifecycle
        # spans + the step timeline live in a bounded ring
        # (`telemetry_ring=` / PADDLE_TELEMETRY_RING, default 2048;
        # 0 disables collection — one branch per event, no timestamp
        # calls); the TTFT/latency/tokens-per-step histograms stay on
        # regardless (they are metrics()' percentile source and are
        # fixed-size). All timestamps ride the ENGINE clock, so spans
        # line up exactly with ttft_s/latency_s under a virtual clock.
        self.telemetry = Telemetry(telemetry_ring, clock=self.clock)
        # SLO/goodput layer (telemetry.SloPolicy; `slo=` or the
        # PADDLE_SLO_* knobs): every FINISHED request is classified at
        # _finish against the declared objectives — ok, violated by
        # queueing, or violated by slow service. With no objectives set
        # everything is ok, so slo_ok + slo_violated_queue +
        # slo_violated_service == requests_finished holds always (the
        # conftest reconciliation pins it).
        self._slo = slo if slo is not None else SloPolicy.from_env()
        self._slo_ok = 0
        self._slo_violated_queue = 0
        self._slo_violated_service = 0
        # results is BOUNDED at the telemetry ring size (the old
        # unbounded dict leaked one entry per finished request for the
        # engine's lifetime); total counts survive in the window
        # counters + the Prometheus lifetime base
        self._results_cap = self.telemetry.ring or DEFAULT_RING
        self._prom_base = {}          # lifetime counter base (reset folds)
        # speculative decoding: K draft tokens per verify step (ONE
        # compiled K+1-position executable replaces the decode chunk;
        # slots with no usable draft ride in all-masked and degrade to
        # a normal decode step). K is static trace structure — pow-2
        # validated like prefill_cap; 0 disables (legacy decode path).
        from .spec_decode import NGramDrafter, validate_spec_k
        self.spec_k = validate_spec_k(
            spec_k if spec_k is not None
            else os.environ.get("PADDLE_SERVING_SPEC_K", "0"))
        self._drafters = ([NGramDrafter(self.spec_k)
                           for _ in range(int(num_slots))]
                          if self.spec_k else None)
        # dispatch heuristic (PHASE mode only — DEPRECATED): a verify
        # step only beats `decode_chunk` plain steps when enough draft
        # tokens ride along to amortize its K+1-position pass — below
        # `spec_min_draft` average drafts per active slot the phase
        # engine runs the (equally warm) decode chunk instead. The
        # token-budget scheduler subsumes this with budget arithmetic
        # (drafts are just another claim on the step budget; the
        # dispatch that processes more real tokens wins), so in chunked
        # mode the env is ignored.
        self._spec_min_draft = float(os.environ.get(
            "PADDLE_SERVING_SPEC_MIN_DRAFT", "2"))
        self._spec_rng = None            # lazy: sampled-mode acceptance
        self._draft_proposed = 0
        self._draft_accepted = 0
        self._decode_steps = 0           # per-ROW sample events

        # TOKEN-BUDGET scheduler (default ON): every compiled step
        # spends `token_budget` tokens mixing decode rows (1 input
        # token + any draft claim each) with prefill chunks from
        # admitted-but-unprefilled slots — admission no longer runs a
        # blocking prefill phase, so one long prompt can't hold the
        # decode gang hostage (Sarathi-style chunked prefill).
        # token_budget=0 restores the legacy PHASE-prefill scheduler
        # (blocking bulk/scan prefill at admission) — kept as the A/B
        # baseline and for `bench_serving.py --chunked`.
        # default: C = max(4 x decode_chunk, spec_k + 1) columns per
        # row — wide enough that a classic-length prompt (and a full
        # draft) lands in ONE dispatch; measured on the classic CPU
        # bench this beats the phase scheduler's bulk admission by
        # ~15% tokens/s where the ISSUE's leaner B x decode_chunk
        # (C = chunk) cost 15% (8 block steps per 32-token prompt)
        tb_env = os.environ.get("PADDLE_SERVING_TOKEN_BUDGET")
        tb = int(token_budget if token_budget is not None
                 else tb_env if tb_env
                 else self.num_slots * max(4 * self.decode_chunk,
                                           self.spec_k + 1))
        if tb < 0:
            raise ValueError(f"token_budget must be >= 0, got {tb}")
        if tb and tb < self.num_slots:
            raise ValueError(
                f"token_budget={tb} < num_slots={num_slots}: every "
                "active decode row claims one mandatory token per step, "
                "so the budget must cover at least the slot count "
                "(token_budget=0 disables chunked scheduling entirely)")
        self.token_budget = tb
        # the compiled budget step's column count C: per-row segment
        # cap, static shape. ceil(budget/B) rounds the shape to the
        # budget; a full draft (spec_k + the input token) must also fit
        # one row. pow-2 like every other ladder knob.
        cw = max(-(-tb // self.num_slots) if tb else 1, self.spec_k + 1)
        self._budget_cols = 1 << (cw - 1).bit_length()
        if tb and self.spec_k and \
                os.environ.get("PADDLE_SERVING_SPEC_MIN_DRAFT") is not None:
            import warnings
            warnings.warn(
                "PADDLE_SERVING_SPEC_MIN_DRAFT is deprecated and "
                "ignored under the token-budget scheduler (drafts are "
                "budget claims; the dispatch choice is budget "
                "arithmetic). Set token_budget=0 for the legacy phase "
                "scheduler if you need the old heuristic.",
                DeprecationWarning, stacklevel=2)
        # TOKEN-FLATTENED budget dispatch (PADDLE_SERVING_FLAT_BUDGET=1
        # / flat_budget=True; row-aligned stays the default until the
        # bench A/B gate flips it): the budget step becomes ONE ragged
        # [T] token stream — a B-wide decode region plus back-to-back
        # segments with eighth-octave ladder width — instead of the [B, C]
        # block, so T real tokens cost ~T computed positions
        # (budget_padding_tokens ~ 0) where the row layout paid B x C
        # (a lone long prefill wasted (B-1) x C per dispatch), and one
        # prefill segment can span the whole spare budget instead of C
        # columns. Token parity with the row layout is exact (greedy
        # AND sampled — sampling is keyed fold_in(seed, nt), never by
        # layout); tests/test_flat_budget.py pins it.
        flat_env = os.environ.get("PADDLE_SERVING_FLAT_BUDGET", "0")
        self._flat_budget = (bool(flat_budget)
                             if flat_budget is not None
                             else flat_env == "1")
        if self._flat_budget and not tb:
            raise ValueError(
                "flat_budget needs the token-budget scheduler "
                "(token_budget > 0): the flat [T] stream IS the budget "
                "dispatch — token_budget=0 selects the legacy phase "
                "scheduler, which has no budget step to flatten")
        # prefill progress: prompt tokens still to feed per slot (> 0
        # marks an admitted-but-unprefilled "prefilling" slot the
        # budget packer advances, oldest request first)
        self._pf_left = np.zeros(int(num_slots), np.int64)
        self._budget_steps = 0
        self._budget_tokens_used = 0
        self._budget_prefill_tokens = 0
        self._budget_decode_tokens = 0
        self._budget_draft_tokens = 0
        # masked/pad positions the budget dispatches actually computed
        # (row: B x C - packed; flat: (B + T_seg) - packed) — the
        # wasted-FLOPs ledger the flat layout exists to flatten;
        # utilization = used / (used + padding) by construction
        self._budget_padding_tokens = 0

        b = self.num_slots
        fmt.eval()
        if self.paged:
            self._caches = self.dec.init_paged_cache(self.pool)
            # per-slot block tables: position s of slot b lives at
            # pool[.., tables[b, s // Bt], .., s % Bt, ..]; the sentinel
            # num_blocks marks unmapped entries (writes through it drop)
            self._tables = np.full((b, self.smax // self.prefill_cap),
                                   self.pool.num_blocks, np.int32)
        else:
            self._caches = self.dec.init_cache(b)
            self._tables = None
        # host-side slot state (tiny [B] vectors; device arrays would buy
        # nothing — they cross the boundary once per chunk anyway)
        self._lens = np.zeros(b, np.int32)       # current decode position
        self._active = np.zeros(b, bool)
        self._nt = np.zeros(b, np.int32)         # tokens generated so far
        self._max_nt = np.ones(b, np.int32)
        self._eos = np.full(b, -1, np.int32)     # -1: no eos for the slot
        self._min_len = np.zeros(b, np.int32)
        self._rep_pen = np.ones(b, np.float32)
        self._tok = np.zeros(b, np.int32)        # next step's input token
        self._rseed = np.zeros(b, np.int64)      # per-request sample seed
        self._slot_req = [None] * b              # slot -> ServedRequest
        self._presence = None                    # [B, V] bool when rep_on

        # live-migration counters: a migrated-in request enters a slot
        # WITHOUT an admission (no prefix lookup, no prefill — its KV
        # arrived as pool blocks), a migrated-out one leaves without a
        # finish verdict; both are first-class window counters so the
        # conftest reconciliations stay exact
        self._migrated_in = 0
        self._migrated_out = 0
        # disaggregated-handoff counters + staging area: shipped counts
        # every KV block serialized OFF this engine (export_slot and
        # the streamed export_kv_prefix), adopted every block written
        # INTO this engine's pool from a shipped payload (import_slot
        # uploads and stage_kv_blocks). Cluster-wide, lossless handoff
        # conserves sum(shipped) == sum(adopted); preemption-to-host
        # serializes inline and never touches either. _staged maps a
        # router-chosen tag -> pool block ids received AHEAD of the
        # final export (streamed handoff overlapping the prefill tail)
        self._kv_blocks_shipped = 0
        self._kv_blocks_adopted = 0
        self._staged = {}

        # QoS: one FIFO per class, admitted best-class-first (all-default
        # workloads collapse to the old single FIFO, token-identically);
        # the parking lot holds preempted slot state dicts (host RAM —
        # export_slot already serializes everything, kv blocks included)
        self._queues = {c: deque() for c in QOS_CLASSES}
        self._parked = {}                 # rid -> export_slot state dict
        self._preempted = 0
        self._resumed = 0
        self._class_admitted = {c: 0 for c in QOS_CLASSES}
        self._class_tokens = {c: 0 for c in QOS_CLASSES}
        self._slo_vq_class = {c: 0 for c in QOS_CLASSES}
        # weighted-fair prefill shares (host data only — the packer
        # changes WHICH rows fill the same fixed-shape budget, never the
        # shapes, so zero retraces by construction)
        self.qos_shares = self._parse_qos_shares(
            os.environ.get("PADDLE_QOS_SHARES", ""))
        self.results = {}
        # streaming-harvest bookkeeping: every queued/running request is
        # reachable by rid (bounded by queue + slots); a FINISHED request
        # stays indexed only while a track() cursor holds it — the
        # incremental SSE reader's guarantee against the results cap
        self._req_index = {}              # rid -> ServedRequest
        self._harvest = {}                # rid -> tokens already read
        self._rid = itertools.count()
        self._jit_cache = {}
        self._trace_count = 0                    # the retrace spy
        # per-chunk metric records, bounded: a server driving step()
        # forever must not leak one dict per chunk (metrics() reads the
        # aggregate counters, never this log — it is observability only)
        self.chunk_log = deque(maxlen=int(os.environ.get(
            "PADDLE_TPU_SERVE_CHUNK_LOG", "4096")))
        self._tokens_emitted = 0
        self._busy_s = 0.0
        # EWMA of WORKING step duration (snapshot v6 "health" block):
        # the replica-local slowness signal the cluster router's
        # median-relative health scorer compares across replicas
        self._step_ewma_s = 0.0
        self._admitted = 0
        self._forked = 0
        # window counter (was recomputed from the results dict, which is
        # bounded now — an unbounded scan AND an unbounded dict at
        # service lifetimes); expired requests never count here
        self._finished = 0
        # overload shedding: 0 = unbounded (legacy behavior)
        self.max_pending = int(max_pending if max_pending is not None
                               else os.environ.get(
                                   "PADDLE_TPU_SERVE_MAX_PENDING", "0"))
        self._rejected = 0
        self._expired = 0

    # ------------------------------------------------------------- public
    def submit(self, prompt, max_new_tokens=20, eos_token_id=None,
               min_length=0, repetition_penalty=1.0, deadline_s=None,
               trace_id=None, attempt=1, priority=QOS_DEFAULT):
        """Queue one request; returns its id. The slot-eviction invariant
        is enforced HERE: a request may never be able to push its slot's
        cache_lens to Smax (the write kernels' documented invariant).
        prompt + max_new_tokens == Smax is allowed: cache_lens peaks at
        Smax - 1, because a slot that deactivates (nt hit
        max_new_tokens) stops INCREMENTING lens. The decode scan still
        runs unmasked for inactive rows (a write mask would demote the
        fused write+attend kernel), so the last sampled token's K/V IS
        written — at the frozen lens == Smax - 1, rewritten with the
        same value each subsequent chunk while the slot idles. In-bounds
        by the check below, overwritten by the next admission's prefill;
        do NOT snapshot a finished slot's cache row expecting it frozen
        as of the final active step."""
        ids = prompt._data if isinstance(prompt, Tensor) else prompt
        ids = np.asarray(ids, np.int32).reshape(-1)
        if ids.size < 1:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if ids.size + int(max_new_tokens) > self.smax:
            raise ValueError(
                f"prompt ({ids.size}) + max_new_tokens ({max_new_tokens})"
                f" exceeds the ring capacity Smax={self.smax} — the slot "
                "could fill its cache row (cache_lens < Smax invariant)")
        if repetition_penalty != 1.0 and not self._rep_on:
            raise ValueError(
                "repetition_penalty needs enable_repetition_penalty=True "
                "at engine construction (the presence-mask carry is "
                "static trace structure)")
        if attempt < 1:
            raise ValueError(f"attempt must be >= 1, got {attempt}")
        if priority not in QOS_CLASSES:
            raise ValueError(
                f"priority must be one of {QOS_CLASSES}, got {priority!r}")
        if self.max_pending and self._queue_len() >= self.max_pending:
            self._rejected += 1
            if self.telemetry.enabled:
                self.telemetry.req_rejected(self.clock(),
                                            trace_id=trace_id,
                                            attempt=attempt)
            raise AdmissionFull(
                f"pending queue full ({self._queue_len()}/"
                f"{self.max_pending}) — request shed at admission")
        if self.paged:
            need = self._blocks_needed(ids.size, max_new_tokens)
            if need > self.pool.num_blocks:
                raise ValueError(
                    f"request needs {need} kv blocks but the pool holds "
                    f"{self.pool.num_blocks} total — it can never be "
                    "admitted (grow kv_pool_blocks or shrink the "
                    "request)")
            if self._kv_gate and \
                    self._kv_committed + need > self.pool.num_blocks:
                # the POOL (not the slot count) is exhausted: honest
                # shedding against the operator's stated memory budget
                # — finished/expired requests release their commitment,
                # so the caller's backoff-and-retry recovers
                self._rejected += 1
                if self.telemetry.enabled:
                    self.telemetry.req_rejected(self.clock(),
                                                trace_id=trace_id,
                                                attempt=attempt)
                raise AdmissionFull(
                    f"kv pool exhausted ({self._kv_committed}/"
                    f"{self.pool.num_blocks} blocks committed to "
                    f"queued+running requests; this one needs {need}) "
                    "— request shed at admission")
            self._kv_committed += need
        req = ServedRequest(next(self._rid), ids, max_new_tokens,
                            eos_token_id, min_length, repetition_penalty,
                            self.clock(), deadline_s=deadline_s,
                            seed=self._fresh_seed(), trace_id=trace_id,
                            attempt=attempt, priority=priority)
        self._queues[priority].append(req)
        self._req_index[req.rid] = req
        self.telemetry.req_queued(req.rid, req.t_submit,
                                  trace_id=req.trace_id,
                                  attempt=req.attempt)
        return req.rid

    def _fresh_seed(self):
        """One per-request sampling seed off the global key stream
        (greedy engines skip the draw: submit order then can't perturb
        unrelated consumers of the global key)."""
        return _host_seed(next_key()) if self.do_sample else 0

    # ------------------------------------------------- per-class queues
    # The admission order is strict priority across classes (best class
    # first), FIFO within a class — these four helpers are the ONLY code
    # that touches the per-class deques, so the old single-FIFO call
    # sites read unchanged.
    @staticmethod
    def _parse_qos_shares(spec):
        """Parse ``high=4,normal=2,low=1`` into a share dict; unknown
        classes reject loudly, missing ones keep the default weight."""
        shares = dict(DEFAULT_QOS_SHARES)
        for part in str(spec).split(","):
            part = part.strip()
            if not part:
                continue
            cls, _, w = part.partition("=")
            if cls not in QOS_CLASSES:
                raise ValueError(
                    f"PADDLE_QOS_SHARES: unknown class {cls!r} "
                    f"(classes: {QOS_CLASSES})")
            w = int(w)
            if w < 1:
                raise ValueError(
                    f"PADDLE_QOS_SHARES: share for {cls!r} must be "
                    f">= 1, got {w}")
            shares[cls] = w
        return shares

    def _queue_len(self):
        return sum(len(q) for q in self._queues.values())

    def _queue_head(self):
        for c in QOS_CLASSES:
            if self._queues[c]:
                return self._queues[c][0]
        return None

    def _queue_popleft(self):
        for c in QOS_CLASSES:
            if self._queues[c]:
                return self._queues[c].popleft()
        raise IndexError("pop from empty queue")

    def _queue_remove(self, req):
        self._queues[req.priority].remove(req)

    def queue_depths(self):
        """Per-class pending depths (host dict; snapshot v4 surface)."""
        return {c: len(self._queues[c]) for c in QOS_CLASSES}

    @property
    def has_work(self):
        return (bool(self._queue_len()) or bool(self._active.any())
                or bool((self._pf_left > 0).any())
                or bool(self._parked))

    @property
    def queue_depth(self):
        return self._queue_len()

    @property
    def occupancy(self):
        if not self.num_slots:
            return 0.0
        # a slot mid-prefill is occupied even though it isn't decoding
        return float((self._active | (self._pf_left > 0)).mean())

    @no_grad()
    def step(self):
        """One scheduler iteration. Token-budget mode (default): admit
        waiting requests into free slots as PURE BOOKKEEPING (they
        enter `prefilling` — no blocking prefill phase), then run one
        budget-packed dispatch mixing decode rows and prefill chunks.
        Phase mode (token_budget=0): the legacy blocking-prefill
        admission + decode chunk. Emits one chunk_log record; returns
        the number of tokens emitted this step."""
        t0 = self.clock()
        # gray-failure chaos hook: PADDLE_FI_SLOW_POINT=serve_step slows
        # THIS engine's scheduler loop (per-process env = per-replica in
        # an rpc cluster) while its heartbeat keeps beating — the
        # router's health scoring, not death detection, must notice.
        # After t0, so the injected delay lands in the step-duration
        # EWMA the snapshot health block reports. Free when disarmed
        # (inject() gates on any PADDLE_FI_* set).
        from ..testing import fault
        fault.inject("serve_step")
        had_work = self.has_work
        self._expire_deadlines(t0)
        # QoS pass BEFORE admission: resume parked requests when pressure
        # cleared, preempt the lowest-class running slot when a better-
        # class head is blocked — so this step's admission sees the slot
        self._qos_schedule()
        if self.token_budget:
            self._admit_chunked()
            emitted = self._budget_step()
        else:
            admitted = self._admit()
            emitted = len(admitted)
            # phase-mode hold runs BETWEEN admission (which already
            # sampled the first token) and the decode chunk — a
            # prefill worker must never spend a decode dispatch on a
            # request that is about to ship out
            if self.role == "prefill":
                self._hold_prefilled()
            if self._active.any():
                emitted += (self._spec_decode_step() if self.spec_k
                            else self._decode_one_chunk())
        if self.role == "prefill":
            # budget-mode hold: a slot whose prompt completed in this
            # dispatch (first token sampled, decoding would start next
            # step) parks as "prefilled" awaiting the KV handoff
            self._hold_prefilled()
        # re-check AFTER the dispatch: a deadline that lapsed while the
        # step ran (or while admission waits on a head-of-line block
        # reservation) must expire now, not one full step later — a
        # queued request behind a pool-exhausted admission otherwise
        # sits past its deadline for a whole extra dispatch
        self._expire_deadlines(self.clock())
        dt = self.clock() - t0
        self._busy_s += dt
        self._tokens_emitted += emitted
        if had_work:
            # smoothed WORKING-step duration (idle steps would dilute
            # the gray-failure signal toward zero on a lulled replica)
            self._step_ewma_s = (dt if self._step_ewma_s == 0.0
                                 else 0.8 * self._step_ewma_s + 0.2 * dt)
            # tokens-per-step distribution (0 is a real value: a pure-
            # prefill budget step emits nothing and that IS the story)
            self.telemetry.observe_step_tokens(emitted)
        self.chunk_log.append({
            "step_s": dt, "new_tokens": emitted,
            "occupancy": self.occupancy, "queue_depth": self.queue_depth,
            "traces": self._traces_total(),
        })
        return emitted

    def run(self):
        """Drive until the queue and all slots drain."""
        while self.has_work:
            self.step()
        return self.results

    def _hold_prefilled(self):
        """Role "prefill" only: park every slot whose prompt finished
        (first token sampled, decode would start next dispatch) as
        state ``prefilled`` — active=False, KV blocks and slot stay
        RESIDENT awaiting export_slot to a decode replica. The request
        rides the streaming harvest as (tokens, done=False,
        "prefilled"), which is the router's handoff trigger. Requests
        that finished ON their first token (eos / max_new_tokens == 1)
        were already completed by the dispatch harvest and never reach
        here. A held slot drops out of ``has_work`` on purpose: the
        prefill worker idles (or admits the next prompt into other
        slots) while the router drives the transfer."""
        for s in range(self.num_slots):
            req = self._slot_req[s]
            if (req is not None and req.state == "running"
                    and self._active[s] and not self._pf_left[s]
                    and self._nt[s] >= 1):
                req.state = "prefilled"
                self._active[s] = False
                if self.telemetry.enabled:
                    self.telemetry.req_event(req.rid, "prefill_hold",
                                             self.clock())

    # ------------------------------------------------- streaming harvest
    def _lookup_req(self, rid):
        """(tokens, done, state) for a rid, or None if unknown: live
        requests read their ServedRequest, finished untracked ones fall
        back to the bounded results record."""
        req = self._req_index.get(rid)
        if req is not None:
            return (req.tokens, req.state in ("finished", "expired"),
                    req.state)
        r = self.results.get(rid)
        if r is not None:
            return (r["tokens"], True,
                    "expired" if r["expired"] else "finished")
        return None

    def track(self, rid):
        """Register an incremental-harvest cursor for ``rid``. A tracked
        request's record is retained past the bounded ``results`` cap
        until the reader drains it — call BEFORE the request can finish
        (the replica wrappers do it under the same lock as submit) or
        the registration races the cap like any late ``results`` read."""
        if rid in self._harvest:
            return
        if self._lookup_req(rid) is None:
            raise KeyError(
                f"request {rid} is unknown (never submitted, or it "
                "finished and was evicted from the bounded results cap "
                "before track() — register the cursor at submit time)")
        self._harvest[rid] = 0

    def poll(self, rid):
        """Non-destructive status read: ``{"rid", "state", "n_tokens",
        "ttft_s", "latency_s"}``, or None for an unknown rid. Moves no
        cursor and no counter — safe to call at any rate."""
        req = self._req_index.get(rid)
        if req is not None:
            return {"rid": rid, "state": req.state,
                    "n_tokens": len(req.tokens), "ttft_s": req.ttft_s,
                    "latency_s": req.latency_s}
        r = self.results.get(rid)
        if r is None:
            return None
        return {"rid": rid,
                "state": "expired" if r["expired"] else "finished",
                "n_tokens": int(np.asarray(r["tokens"]).size),
                "ttft_s": r["ttft_s"], "latency_s": r["latency_s"]}

    def harvest_new_tokens(self, rid):
        """Incremental token harvest: ``(new_tokens, done, state)`` —
        the tokens generated since the previous call (first call
        auto-registers a cursor at 0 and returns everything so far).
        When ``done`` the cursor is dropped and the retained record
        released; a later call raises KeyError like any unknown rid.
        This is the SSE streaming primitive: a tracked reader can be
        arbitrarily slow without losing a finished request to the
        results cap (the untracked `results` dict can — documented)."""
        if rid not in self._harvest:
            self.track(rid)
        got = self._lookup_req(rid)
        if got is None:                  # evicted between track and now:
            self._harvest.pop(rid, None)  # only possible for a cursor
            raise KeyError(              # registered post-finish
                f"request {rid} was evicted from the results cap before "
                "its first harvest — track() at submit time to pin it")
        tokens, done, state = got
        cur = self._harvest[rid]
        new = [int(t) for t in tokens[cur:]]
        if done:
            self.release(rid)
        else:
            self._harvest[rid] = cur + len(new)
        return new, done, state

    def release(self, rid):
        """Drop a streaming cursor (and the retained record, if the
        request already finished). Idempotent."""
        self._harvest.pop(rid, None)
        req = self._req_index.get(rid)
        if req is not None and req.state in ("finished", "expired"):
            self._req_index.pop(rid, None)

    def _window_counters(self):
        """The raw window-counter surface, keyed like metrics(). Kept in
        ONE place so reset_metrics' lifetime-base folding (Prometheus
        counters must be monotonic across resets) can assert it covers
        exactly telemetry.COUNTER_FOLD_KEYS — a new counter that skips
        either side fails loudly here, not silently in a dashboard."""
        return {
            "tokens_emitted": self._tokens_emitted,
            "busy_s": self._busy_s,
            "requests_finished": self._finished,
            "requests_admitted": self._admitted,
            "requests_forked": self._forked,
            "requests_rejected": self._rejected,
            "requests_expired": self._expired,
            "requests_migrated_in": self._migrated_in,
            "requests_migrated_out": self._migrated_out,
            "kv_blocks_shipped": self._kv_blocks_shipped,
            "kv_blocks_adopted": self._kv_blocks_adopted,
            "requests_preempted": self._preempted,
            "requests_resumed": self._resumed,
            "requests_admitted_high": self._class_admitted["high"],
            "requests_admitted_normal": self._class_admitted["normal"],
            "requests_admitted_low": self._class_admitted["low"],
            "tokens_emitted_high": self._class_tokens["high"],
            "tokens_emitted_normal": self._class_tokens["normal"],
            "tokens_emitted_low": self._class_tokens["low"],
            "prefix_hits": self._prefix_hits,
            "prefix_misses": self._prefix_misses,
            "prefill_tokens_saved": self._prefill_tokens_saved,
            "prefill_tokens_computed": self._prefill_tokens_computed,
            "decode_steps": self._decode_steps,
            "draft_proposed": self._draft_proposed,
            "draft_accepted": self._draft_accepted,
            "kv_cow_copies": self._cow_copies,
            "budget_steps": self._budget_steps,
            "budget_tokens_used": self._budget_tokens_used,
            "budget_prefill_tokens": self._budget_prefill_tokens,
            "budget_decode_tokens": self._budget_decode_tokens,
            "budget_draft_tokens": self._budget_draft_tokens,
            "budget_padding_tokens": self._budget_padding_tokens,
            "slo_ok": self._slo_ok,
            "slo_violated_queue": self._slo_violated_queue,
            "slo_violated_service": self._slo_violated_service,
        }

    def reset_metrics(self, keep_results=True):
        """Zero the aggregate counters (benchmarks call this after a
        warmup phase so the measured window excludes compiles). The
        trace counter is NOT reset — retraces-after-warmup is exactly
        `metrics()['traces']` before vs after the measured phase.
        Every window counter folds into the Prometheus lifetime base
        first (metrics_prometheus() counters never move backwards), and
        the telemetry rings/histograms start a fresh window (the next
        export_chrome_tracing covers exactly the measured window)."""
        window = self._window_counters()
        assert set(window) == set(COUNTER_FOLD_KEYS), (
            "window-counter surface drifted from telemetry."
            "COUNTER_FOLD_KEYS: "
            f"{set(window) ^ set(COUNTER_FOLD_KEYS)}")
        for k, v in window.items():
            self._prom_base[k] = self._prom_base.get(k, 0) + v
        self.telemetry.reset()
        self.chunk_log.clear()
        self._tokens_emitted = 0
        self._busy_s = 0.0
        self._admitted = 0
        self._forked = 0
        self._finished = 0
        self._rejected = 0
        self._expired = 0
        self._migrated_in = 0
        self._migrated_out = 0
        self._kv_blocks_shipped = 0
        self._kv_blocks_adopted = 0
        self._preempted = 0
        self._resumed = 0
        self._class_admitted = {c: 0 for c in QOS_CLASSES}
        self._class_tokens = {c: 0 for c in QOS_CLASSES}
        self._slo_vq_class = {c: 0 for c in QOS_CLASSES}
        self._prefix_hits = 0
        self._prefix_misses = 0
        self._prefill_tokens_saved = 0
        self._prefill_tokens_computed = 0
        self._draft_proposed = 0
        self._draft_accepted = 0
        self._decode_steps = 0
        self._cow_copies = 0
        self._budget_steps = 0
        self._budget_tokens_used = 0
        self._budget_prefill_tokens = 0
        self._budget_decode_tokens = 0
        self._budget_draft_tokens = 0
        self._budget_padding_tokens = 0
        self._slo_ok = 0
        self._slo_violated_queue = 0
        self._slo_violated_service = 0
        if not keep_results:
            self.results = {}

    def metrics(self):
        # percentiles come from the telemetry subsystem's BOUNDED
        # log-bucketed histograms (estimates within one bucket width of
        # exact), not a scan over per-request records: the old
        # done-list walk grew without bound at service lifetimes, and
        # the results dict it walked is capped now. Expired requests
        # are SHED, not finished — they never reach the histograms
        # (their "latency" is an eviction time) and never count in
        # requests_finished (else finished + expired double-counts).
        tele = self.telemetry
        looked = self._prefix_hits + self._prefix_misses
        _w_dev, _w_repl = self._weight_bytes()
        m = {
            "tokens_emitted": self._tokens_emitted,
            "busy_s": round(self._busy_s, 4),
            # zero-elapsed guard: a frozen/coarse clock can leave
            # busy_s == 0.0 with tokens already emitted (first-step
            # metrics call) — report a throughput of 0.0, never divide
            "tokens_per_sec": (
                round(self._tokens_emitted / self._busy_s, 2)
                if self._busy_s > 0
                else (0.0 if self._tokens_emitted else None)),
            "requests_finished": self._finished,
            "requests_admitted": self._admitted,
            "requests_forked": self._forked,
            "requests_rejected": self._rejected,
            "requests_expired": self._expired,
            # live-migration window counters (0 unless a cluster drain
            # moved sessions): migrated_in entered a slot with KV blocks
            # shipped from another engine (no admission, no prefill);
            # migrated_out left mid-flight with their state
            "requests_migrated_in": self._migrated_in,
            "requests_migrated_out": self._migrated_out,
            # disaggregation surface: the engine's pool role (static
            # config — "mixed" runs today's combined behavior) plus the
            # KV-handoff window counters. Shipped counts blocks this
            # engine read out for another engine (export_slot payloads
            # + streamed export_kv_prefix chunks); adopted counts
            # blocks written INTO this pool from another engine
            # (import_slot payloads + stage_kv_blocks uploads).
            "role": self.role,
            "kv_blocks_shipped": self._kv_blocks_shipped,
            "kv_blocks_adopted": self._kv_blocks_adopted,
            # QoS window counters: preempted running slots parked to
            # host RAM, resumed re-imported; parked is a live gauge.
            # Per-class admissions/tokens sum to the totals (all-default
            # traffic lands entirely in "normal") — conftest pins it.
            "requests_preempted": self._preempted,
            "requests_resumed": self._resumed,
            "requests_parked": len(self._parked),
            "requests_admitted_high": self._class_admitted["high"],
            "requests_admitted_normal": self._class_admitted["normal"],
            "requests_admitted_low": self._class_admitted["low"],
            "tokens_emitted_high": self._class_tokens["high"],
            "tokens_emitted_normal": self._class_tokens["normal"],
            "tokens_emitted_low": self._class_tokens["low"],
            "queue_depth": self.queue_depth,
            "occupancy": self.occupancy,
            "traces": self._traces_total(),
            "ttft_p50_s": tele.hist_ttft.percentile(50),
            "ttft_p90_s": tele.hist_ttft.percentile(90),
            "ttft_p99_s": tele.hist_ttft.percentile(99),
            "latency_p50_s": tele.hist_latency.percentile(50),
            "latency_p99_s": tele.hist_latency.percentile(99),
            # prefix-cache window counters (all zero with caching off):
            # hits + misses == requests_admitted by construction; saved +
            # computed == total prompt tokens admitted this window
            "prefix_hits": self._prefix_hits,
            "prefix_misses": self._prefix_misses,
            "prefix_hit_rate": (round(self._prefix_hits / looked, 4)
                                if looked else None),
            "prefill_tokens_saved": self._prefill_tokens_saved,
            "prefill_tokens_computed": self._prefill_tokens_computed,
            # speculative-decoding window counters (spec_k=0 keeps
            # proposed/accepted at 0 and tokens_per_step at exactly 1):
            # decode_steps counts per-ROW sample events (the admit
            # first-token sample + each decode/verify row-step), so
            # tokens_emitted == decode_steps + draft_accepted always —
            # the conftest reconciliation pins it
            "decode_steps": self._decode_steps,
            "draft_proposed": self._draft_proposed,
            "draft_accepted": self._draft_accepted,
            "acceptance_rate": (
                round(self._draft_accepted / self._draft_proposed, 4)
                if self._draft_proposed else None),
            "tokens_per_step": (
                round(self._tokens_emitted / self._decode_steps, 4)
                if self._decode_steps else None),
            # paged-pool accounting (dense mode: total/used/free None):
            # used + free == total always — a refcounted block shared
            # by N slots and the prefix store is ONE physical block,
            # counted once. kv_cow_copies is a window counter (0 in
            # the steady flow; forks pay one per diverged block).
            "kv_blocks_total": (self.pool.num_blocks if self.paged
                                else None),
            "kv_blocks_used": self.pool.used if self.paged else None,
            "kv_blocks_free": (self.pool.free_count if self.paged
                               else None),
            "kv_cow_copies": self._cow_copies,
            # mesh-sharded pool layout gauges (static config, so they
            # survive reset_metrics unchanged without an exemption;
            # dense mode: all None): shard_count is the mesh's mp
            # degree (1 when a paged engine runs unsharded),
            # shard_heads the per-device head count, and
            # shard_pool_bytes the PER-DEVICE kv(+scales) bytes —
            # shard_count x shard_pool_bytes == the full pool, i.e.
            # per-device residency is dense/mp
            "kv_shard_count": self._kv_shard_count(),
            "kv_shard_heads": self._kv_shard_heads(),
            "kv_shard_pool_bytes": self._kv_shard_pool_bytes(),
            # tensor-parallel WEIGHT placement gauges (static config,
            # reset-stable like the kv_shard trio, but never None —
            # every engine has weights): shard_count is the weight-
            # shard mp degree (1 = replicated / no mesh),
            # weight_bytes_per_device the per-chip bytes of the exact
            # arrays the step dispatches (stacked layer pytree + embed
            # + LM head, int8 mirrors at their quantized size), and
            # weight_bytes_replicated the per-chip share that stays
            # replicated (LN/bias/scale mirrors, embed, an indivisible
            # LM head). The identity
            #   (per_device - replicated) * shard_count + replicated
            #     == dense total bytes
            # holds exactly on every engine (conftest pins it).
            "weight_shard_count": self._weight_shard_count(),
            "weight_bytes_per_device": _w_dev,
            "weight_bytes_replicated": _w_repl,
            # token-budget window counters (all zero in phase mode):
            # used = the REAL tokens packed into budget dispatches
            # (prefill + decode + draft parts sum to it exactly — the
            # conftest reconciliation pins the split); padding = the
            # masked/pad positions those dispatches actually COMPUTED
            # (row-aligned: B x C - used per step; flat: the decode
            # region's idle rows + alignment/ladder tail — the flat
            # layout's whole point is driving this to ~0). Utilization
            # is used / (used + padding): the denominator is each
            # dispatch's real compute width (B x C row-aligned, T
            # flat), so the gauge stays in (0, 1] under BOTH layouts.
            # Plain decode-chunk dispatches the budget arithmetic
            # falls back to are NOT budget steps and don't count here.
            "budget_steps": self._budget_steps,
            "budget_tokens_used": self._budget_tokens_used,
            "budget_prefill_tokens": self._budget_prefill_tokens,
            "budget_decode_tokens": self._budget_decode_tokens,
            "budget_draft_tokens": self._budget_draft_tokens,
            "budget_padding_tokens": self._budget_padding_tokens,
            "budget_utilization": (
                round(self._budget_tokens_used
                      / (self._budget_tokens_used
                         + self._budget_padding_tokens), 4)
                if self._budget_steps and self._budget_tokens_used
                else None),
            # SLO/goodput window counters (SloPolicy; objectives unset
            # = everything ok): ok + violated_queue + violated_service
            # == requests_finished by construction — every finished
            # request gets exactly one verdict at _finish
            "slo_ok": self._slo_ok,
            "slo_violated_queue": self._slo_violated_queue,
            "slo_violated_service": self._slo_violated_service,
            # queue-wait vs service-time decomposition percentiles
            # (the cause-attribution signal, same bounded histograms
            # discipline as ttft/latency)
            "queue_p50_s": tele.hist_queue.percentile(50),
            "queue_p99_s": tele.hist_queue.percentile(99),
            "service_p50_s": tele.hist_service.percentile(50),
            "service_p99_s": tele.hist_service.percentile(99),
        }
        if self.prefix_cache is not None:
            m["prefix_store"] = self.prefix_cache.store.stats()
        return m

    def _kv_shard_count(self):
        """Number of pool shards: the mesh's mp degree, 1 for an
        unsharded paged engine, None in dense mode (no pool)."""
        if not self.paged:
            return None
        mesh = self.dec._mesh_mp()
        return dict(mesh.shape)["mp"] if mesh is not None else 1

    def _kv_shard_heads(self):
        n = self._kv_shard_count()
        return None if n is None else self.dec.fmt.num_heads // n

    def _kv_shard_pool_bytes(self):
        """Per-device pool residency: kv(+scales) bytes / shard count.
        The head axis divides exactly (enforced at construction), so
        this is the precise per-chip HBM the pool costs — dense/mp."""
        n = self._kv_shard_count()
        if n is None:
            return None
        total = int(self._caches["kv"].nbytes)
        if "sc" in self._caches:
            total += int(self._caches["sc"].nbytes)
        return total // n

    def _weight_arrays(self):
        """The EXACT device arrays the serving step dispatches with:
        the stacked layer pytree, the embedding params, and the
        (possibly quantized / vocab-sharded) LM-head arrays. One list
        so the weight gauges, the conftest identity reconciliation and
        bench_serving's --mesh-weights A/B all account the same
        bytes."""
        dec = self.dec
        arrs = list(dec._stacked().values())
        arrs += [p._data for p in dec._embed_params]
        arrs += list(dec._maybe_quant_head(
            [p._data for p in dec._head_params]))
        return arrs

    def _weight_shard_count(self):
        """Weight-shard degree: the mesh's mp when the stacks shard,
        1 when weights are replicated (no mesh, opt-out, or an
        indivisible head/FFN axis)."""
        mesh = self.dec._weight_shard_mesh()
        return dict(mesh.shape)["mp"] if mesh is not None else 1

    def _weight_bytes(self):
        """(per_device, replicated) weight bytes. per_device sums each
        array's LOCAL shard footprint (sharding.shard_shape — the full
        shape for replicated arrays, shape/mp on the sharded axis
        otherwise); replicated sums only the arrays whose local shard
        IS the full array. With n = _weight_shard_count(),
        (per_device - replicated) * n + replicated recovers the dense
        byte total exactly."""
        import math
        per_dev = repl = 0
        for a in self._weight_arrays():
            shape = tuple(a.shape)
            shard = tuple(a.sharding.shard_shape(shape)) if hasattr(
                a, "sharding") else shape
            b = math.prod(shard) * a.dtype.itemsize
            per_dev += b
            if shard == shape:
                repl += b
        return per_dev, repl

    def metrics_prometheus(self):
        """Prometheus text-format exposition: every metrics() key under
        a stable name (telemetry.PROMETHEUS_NAMES), counters monotonic
        across reset_metrics (lifetime base + window), the bounded
        TTFT/latency/tokens-per-step histograms, pool/prefix gauges,
        and the distributed-runtime section (watchdog heartbeat ages,
        supervisor generation, rpc latency)."""
        from .telemetry import render_prometheus
        return render_prometheus(self)

    def telemetry_snapshot(self):
        """JSON-serializable state snapshot — the routing payload a
        cluster front-end polls per replica (queue depth + occupancy +
        pool headroom + histogram percentiles in one cheap read)."""
        from .telemetry import snapshot
        return snapshot(self)

    def _traces_total(self):
        """Engine traces + the prefix cache's copy-path traces: the
        zero-retrace-after-warmup contract covers the adopt/commit
        executables too (a shared PrefixCache may also accrue traces
        from oneshot generate() calls — still honest: any trace hits
        the same compile stall)."""
        n = self._trace_count
        if self.prefix_cache is not None:
            n += self.prefix_cache.trace_count
        if self.pool is not None:
            n += self.pool.trace_count       # the COW copy executable
        return n

    # ------------------------------------------------------- jitted steps
    def _counted_jit(self, key, build, donate=()):
        """jit with a retrace spy (paged_kv.counted_jit is the one
        owner): the counter bumps at TRACE time, so
        `metrics()['traces']` counts executable builds, not calls — the
        engine's zero-retrace-after-warmup contract is asserted against
        exactly this number."""
        from .paged_kv import counted_jit
        return counted_jit(self._jit_cache, key, build,
                           self._bump_traces, donate)

    def _bump_traces(self):
        self._trace_count += 1

    def _run_dispatch(self, key, build, donate, args, rows=0, **fields):
        """Every compiled dispatch goes through here: resolves the
        jitted executable (trace-spied as before) and, when the
        telemetry ring is on, logs ONE step-timeline event — kind from
        generation.dispatch_kind(key), dispatch-side elapsed, trace-spy
        delta (a compile mid-flight shows as traces_delta >= 1), and
        gauge snapshots for the counter tracks. Returns (out, event);
        the caller attaches harvest results via Telemetry.finish_step.
        Telemetry off = exactly the old call (no clock reads)."""
        fn = self._counted_jit(key, build, donate=donate)
        tele = self.telemetry
        if not tele.enabled:
            return fn(*args), None
        t0 = self.clock()
        tr0 = self._traces_total()
        out = fn(*args)
        t1 = self.clock()
        ev = tele.step_event(
            dispatch_kind(key), t0, t1 - t0, rows=rows,
            traces_delta=self._traces_total() - tr0,
            queue_depth=self.queue_depth,
            kv_blocks_used=(self.pool.used if self.paged else None),
            **fields)
        return out, ev

    def _core(self):
        core = getattr(self, "_core_cache", None)
        if core is None:
            core = self.dec._build_step_core(
                self.do_sample, self.top_k, self.top_p, self.temperature)
            self._core_cache = core
        return core

    # ------------------------------------------------------ paged plumbing
    def _cache_arg(self):
        """The compiled-step cache operand: dense -> the ring buffer
        as-is; paged -> the pool dict plus this dispatch's block tables
        (tiny [B, Smax/Bt] int32, re-uploaded from host state per call
        — block ids are DATA, so table churn never retraces)."""
        if not self.paged:
            return self._caches
        return dict(self._caches, tbl=jnp.asarray(self._tables))

    def _keep_caches(self, out):
        if not self.paged:
            self._caches = out
        else:
            self._caches = {k: v for k, v in out.items() if k != "tbl"}

    def _blocks_needed(self, plen, max_new):
        """Worst-case pool blocks for one request: every position in
        [0, plen + max_new) mapped. The submit-time Smax bound keeps
        this <= Smax/Bt."""
        return -(-(int(plen) + int(max_new)) // self.prefill_cap)

    def _alloc_kv_blocks(self, n):
        got = self.pool.alloc(n)
        if got is None:
            store = getattr(self.prefix_cache, "store", None)
            if store is not None and hasattr(store, "reclaim"):
                # prefix blocks are CACHE: evict cold ones under memory
                # pressure before touching the reservation guarantees
                store.reclaim(n - self.pool.free_count)
            got = self.pool.alloc(n)
        if got is None:
            raise RuntimeError(
                f"kv block pool over-committed: need {n} blocks, "
                f"{self.pool.free_count} free after reclaim — the "
                "admission-time reservation accounting should make "
                "this unreachable")
        return got

    def _map_blocks(self, slot, hi):
        """Lazily map pool blocks so the slot's table covers positions
        [0, hi) — called as lens grows (admission covers the prompt;
        each decode/verify dispatch covers its write window)."""
        row = self._tables[slot]
        nb = self.pool.num_blocks
        need = [j for j in range(-(-int(hi) // self.prefill_cap))
                if row[j] == nb]
        if need:
            row[need] = self._alloc_kv_blocks(len(need))

    def _budget_pos(self, slot):
        """One-past the slot's LAST possible write position: lens peaks
        at plen + max_new - 1 (the submit-time bound), and every
        masked/dropped write targets a position below it too — so the
        write-window mapping must never touch a block past this, or a
        tightly sized pool would be asked for blocks beyond the
        admission-time worst-case reservation."""
        return (int(self._lens[slot]) - int(self._nt[slot])
                + int(self._max_nt[slot]))

    def _ensure_writable(self, slot, lo, hi):
        """COW guard + lazy mapping for the write window [lo, hi): an
        unmapped block allocates; a SHARED block (refcount > 1 — prefix
        blocks another slot/the store also references, or a fork twin)
        is copied-on-write first, so a write can never leak into
        someone else's view. In the steady serving flow writes land
        strictly past every shared block (adoption/publication are
        block-aligned below plen), so the copy only ever fires for
        forked slots."""
        hi = min(int(hi), self.smax)
        if hi <= lo:
            return
        row = self._tables[slot]
        nb = self.pool.num_blocks
        bt = self.prefill_cap
        for j in range(int(lo) // bt, (hi - 1) // bt + 1):
            blk = int(row[j])
            if blk == nb:
                row[j] = self._alloc_kv_blocks(1)[0]
            elif int(self.pool.refcounts[blk]) > 1:
                new = self._alloc_kv_blocks(1)[0]
                self._caches = self.pool.copy_block(self._caches, blk,
                                                    new)
                row[j] = new
                self.pool.deref([blk])
                self._cow_copies += 1

    def _free_slot_blocks(self, slot):
        row = self._tables[slot]
        nb = self.pool.num_blocks
        mapped = [int(x) for x in row[row < nb]]
        if mapped:
            self.pool.deref(mapped)
        row[:] = nb

    def fork_slot(self, rid, max_new_tokens=None):
        """Copy-on-write FORK of a running request (paged mode): clone
        its decode state into a free slot, sharing every KV block
        through the block table (pool refcounts; ZERO data movement).
        The twins then decode independently — the first write into a
        still-shared block triggers the copy-on-write of just that
        block. This is the parallel-sampling / N-best primitive the
        paged layout gives for free; returns the child's request id.

        The child inherits the parent's generated-so-far tokens and
        budget (``max_new_tokens`` overrides the remaining total)."""
        if not self.paged:
            raise ValueError("fork_slot needs the paged KV cache "
                             "(PADDLE_SERVING_PAGED=0 disables it)")
        src = None
        for r in self._slot_req:
            if r is not None and r.rid == rid:
                src = r
        if src is None or src.state != "running":
            raise ValueError(f"request {rid} is not running in a slot")
        free = self._free_slots()
        if not free:
            # shed like submit() sheds: the rejection must show up in
            # the overload metric, not vanish
            self._rejected += 1
            if self.telemetry.enabled:
                self.telemetry.req_rejected(self.clock())
            raise AdmissionFull("no free slot to fork into")
        s0, s1 = src.slot, free[0]
        mnt = int(max_new_tokens if max_new_tokens is not None
                  else src.max_new_tokens)
        if src.prompt.size + mnt > self.smax:
            raise ValueError("fork budget exceeds the ring capacity")
        need = self._blocks_needed(src.prompt.size, mnt)
        if self._kv_reserved + need > self.pool.num_blocks:
            self._rejected += 1
            if self.telemetry.enabled:
                self.telemetry.req_rejected(self.clock())
            raise AdmissionFull(
                f"kv pool exhausted: fork needs {need} blocks, "
                f"{self.pool.num_blocks - self._kv_reserved} unreserved")
        child = ServedRequest(next(self._rid), src.prompt, mnt,
                              src.eos_token_id, src.min_length,
                              src.repetition_penalty, self.clock(),
                              seed=self._fresh_seed(),
                              trace_id=src.trace_id, attempt=src.attempt,
                              priority=src.priority)
        child.state = "running"
        child.slot = s1
        child.t_admit = child.t_submit    # a clone never queues
        child.tokens = list(src.tokens)
        child.t_first = src.t_first
        self._slot_req[s1] = child
        self._req_index[child.rid] = child
        self._kv_reserved += need
        self._kv_committed += need
        # a fork is a CLONE, not an admission: it performs no prefix
        # lookup, so counting it as admitted would break the
        # hits + misses == admitted reconciliation conftest pins
        self._forked += 1
        if self.telemetry.enabled:
            self.telemetry.req_queued(child.rid, child.t_submit,
                                      trace_id=child.trace_id,
                                      attempt=child.attempt)
            self.telemetry.req_admitted(child.rid, s1, child.t_submit)
            self.telemetry.req_event(child.rid, "forked", child.t_submit)
        # share the parent's blocks: table row copy + one ref each
        row = self._tables[s0]
        mapped = [int(x) for x in row[row < self.pool.num_blocks]]
        self.pool.ref(mapped)
        self._tables[s1] = row
        for vec in (self._lens, self._nt, self._eos, self._min_len,
                    self._rep_pen, self._tok):
            vec[s1] = vec[s0]
        self._max_nt[s1] = mnt
        # the child samples from its OWN seed stream: under the
        # scheduling-invariant per-request sampling discipline, twins
        # sharing the parent's seed would decode IDENTICAL suffixes —
        # the whole point of a fork is divergent continuations
        self._rseed[s1] = child.seed
        # a mid-prefill parent forks cleanly: the child inherits the
        # prefill cursor and streams the remaining prompt through the
        # budget packer like any prefilling slot (its writes trigger
        # COW on the still-shared prompt blocks)
        self._pf_left[s1] = self._pf_left[s0]
        self._active[s1] = self._active[s0] and self._nt[s1] < mnt
        if self._drafters is not None:
            self._drafters[s1].reset(src.prompt)
            self._drafters[s1].update(child.tokens)
        if self._rep_on:
            p = self._presence_init()
            self._presence = p.at[s1].set(p[s0])
        if not self._active[s1] and not self._pf_left[s1]:
            self._finish(child, self.clock())
        return child.rid

    # ------------------------------------------------------ live migration
    # The cluster-drain primitive: a live request's COMPLETE decode state
    # — committed KV blocks (host bytes via BlockPool.read_block), lens /
    # nt / next input token / prefill cursor, per-request sampler seed,
    # and the request contract (prompt, budget, eos, penalties, trace
    # context) — detaches from this engine and resumes MID-STREAM on
    # another one. Drafter n-gram maps and the repetition-penalty
    # presence row are NOT shipped: both are deterministic functions of
    # prompt + generated tokens and are rebuilt at import, byte-
    # equivalent to the live state (the drafter inserts incrementally in
    # exactly the order update() replays; presence is the one-hot union).
    # Greedy continuations are token-identical by construction; plain
    # sampled mode is too (the seed moves and every draw is
    # fold_in(seed, nt)); spec-decode sampled mode redraws its host
    # rejection RNG — the documented caveat.
    MIGRATION_FMT = "paddle-slot-v1"

    def export_slot(self, rid, skip_blocks=0):
        """Detach request ``rid`` (queued, running, or a held
        ``prefilled`` slot on a prefill-role engine) into a
        JSON/pickle-able migration state dict and free everything it
        held here (slot, block references, reservations). The request's
        record leaves this engine as state ``migrated`` — it is neither
        finished nor expired, so no latency/SLO verdict is recorded.
        Paged engines only (the payload IS pool blocks).

        ``skip_blocks`` supports the STREAMED handoff: the first N
        blocks are assumed already staged on the importing engine
        (export_kv_prefix -> stage_kv_blocks while prefill was still
        running), so they are neither re-read nor re-shipped — the
        state dict records ``kv_skip`` and import_slot splices the
        staged blocks back in. A held ``prefilled`` slot exports with
        ``active=True``: its first token is sampled but decode has not
        started, and the importer must resume decoding, not
        instant-finish at the boundary."""
        if not self.paged:
            raise ValueError("export_slot needs the paged KV cache "
                             "(the migration payload is pool blocks; "
                             "PADDLE_SERVING_PAGED=0 disables it)")
        req = self._req_index.get(rid)
        if req is None or req.state not in ("queued", "running",
                                            "prefilled"):
            raise ValueError(f"request {rid} is not live on this engine")
        now = self.clock()
        skip_blocks = int(skip_blocks)
        state = {
            "fmt": self.MIGRATION_FMT,
            "prompt": np.asarray(req.prompt, np.int32),
            "tokens": [int(t) for t in req.tokens],
            "max_new_tokens": req.max_new_tokens,
            "eos_token_id": req.eos_token_id,
            "min_length": req.min_length,
            "repetition_penalty": req.repetition_penalty,
            "deadline_s": req.deadline_s,
            "seed": req.seed,
            "trace_id": req.trace_id,
            "attempt": req.attempt,
            "priority": req.priority,
            "prefill_cap": self.prefill_cap,
            "lens": 0, "nt": 0, "tok": 0, "active": False,
            "pf_left": int(req.prompt.size),
            "kv_skip": 0,
            "kv": [],
        }
        need = self._blocks_needed(req.prompt.size, req.max_new_tokens)
        if req.state == "queued":
            self._queue_remove(req)
            self._kv_committed -= need
        else:
            s = req.slot
            state.update(
                lens=int(self._lens[s]), nt=int(self._nt[s]),
                tok=int(self._tok[s]),
                # a held prefilled slot was deactivated only to park it
                # — the importer must treat it as mid-decode (there are
                # tokens left to generate by construction: a request
                # finishing on its first token never parks)
                active=(bool(self._active[s])
                        or req.state == "prefilled"),
                pf_left=int(self._pf_left[s]))
            if req.state == "prefilled" and req.tokens:
                # dispatches batched AFTER the hold overwrite the
                # per-slot sampled-token vector for inactive rows —
                # the request's own emit history is the durable copy
                # of the token decode resumes from
                state["tok"] = int(req.tokens[-1])
            # KV entries written so far live in [0, lens) — the next
            # token's K/V lands at `lens` on the IMPORTING engine
            # (write-then-attend), so the partial tail block travels
            # as-is and decode resumes seamlessly
            row = self._tables[s]
            total = -(-state["lens"] // self.prefill_cap)
            if not 0 <= skip_blocks <= total:
                raise ValueError(
                    f"skip_blocks={skip_blocks} outside the request's "
                    f"committed block count [0, {total}]")
            state["kv_skip"] = skip_blocks
            for j in range(skip_blocks, total):
                state["kv"].append(
                    self.pool.read_block(self._caches, int(row[j])))
            self._kv_committed -= need
            self._kv_reserved -= need
            self._slot_req[s] = None
            self._active[s] = False
            self._pf_left[s] = 0
            self._free_slot_blocks(s)
        req.state = "migrated"
        self._req_index.pop(rid, None)
        self._harvest.pop(rid, None)
        self._migrated_out += 1
        if state["kv"]:
            self._kv_blocks_shipped += len(state["kv"])
            self.telemetry.observe_handoff(_kv_payload_bytes(state["kv"]))
        if self.telemetry.enabled:
            self.telemetry.req_event(rid, "migrate_out", now)
        self.telemetry.req_done(rid, "migrated", now)
        return state

    def import_slot(self, state, staged=None):
        """Resume an exported request on THIS engine: allocate fresh
        pool blocks, upload the KV bytes, restore the decode vectors,
        and rebuild the derived per-slot state (drafter, presence) from
        the token history. Returns the request's NEW rid here. Sheds
        honestly with ``AdmissionFull`` when no slot or no pool headroom
        can take it — the caller (router drain) falls back to classic
        failover. A never-prefilled export (queued, zero KV) re-enters
        the queue instead of claiming a slot.

        ``staged`` names a stage_kv_blocks tag whose blocks arrived
        AHEAD of this import (streamed handoff): they must cover
        exactly the export's ``kv_skip`` leading blocks and are spliced
        in as the slot's leading table entries — already resident, so
        only the remainder uploads here and the import cost overlaps
        the prefill tail instead of serializing after it. A shed import
        leaves the staged blocks in place (the caller retries or
        abort_stage()s them)."""
        if not self.paged:
            raise ValueError("import_slot needs the paged KV cache")
        if not isinstance(state, dict) or \
                state.get("fmt") != self.MIGRATION_FMT:
            raise ValueError(
                f"not a migration state dict (fmt="
                f"{None if not isinstance(state, dict) else state.get('fmt')!r}"
                f", expected {self.MIGRATION_FMT!r})")
        if int(state["prefill_cap"]) != self.prefill_cap:
            raise ValueError(
                f"migration state has prefill_cap={state['prefill_cap']}"
                f" but this engine uses {self.prefill_cap} — the KV "
                "blocks are prefill_cap-sized and cannot be re-chunked")
        prompt = np.asarray(state["prompt"], np.int32).reshape(-1)
        max_new = int(state["max_new_tokens"])
        if prompt.size + max_new > self.smax:
            raise ValueError(
                f"migrated request needs {prompt.size} + {max_new} "
                f"positions but this engine's Smax is {self.smax}")
        lens = int(state["lens"])
        if not 0 <= lens <= prompt.size + max_new:
            # without this bound a corrupt payload with a huge lens
            # (and a matching kv list) would pass the count check below
            # and allocate blocks past the admission-time reservation —
            # breaking the pool's over-commit invariant mid-serving
            # instead of shedding the one bad import here
            raise ValueError(
                f"migration state has lens={lens} outside its own "
                f"request budget [0, {prompt.size} + {max_new}] — "
                "corrupt or mismatched payload")
        blocks = state["kv"]
        kv_skip = int(state.get("kv_skip", 0))
        staged_ids = []
        if staged is not None:
            got = self._staged.get(staged)
            if got is None:
                raise ValueError(
                    f"no staged kv blocks under tag {staged!r}")
            staged_ids = got
        if len(staged_ids) != kv_skip:
            raise ValueError(
                f"export skips {kv_skip} leading kv blocks but "
                f"{len(staged_ids)} are staged under "
                f"{staged!r} — the streamed prefix must cover the skip "
                "exactly")
        total_blocks = -(-lens // self.prefill_cap)
        if kv_skip + len(blocks) != total_blocks:
            raise ValueError(
                f"migration state ships {len(blocks)} kv blocks "
                f"(+{kv_skip} staged) but lens={lens} needs "
                f"{total_blocks}")
        kv_shape = self._caches["kv"].shape      # [L, 2, NB, H, Bt, D]
        want = (kv_shape[0], 2, 1, kv_shape[3], kv_shape[4], kv_shape[5])
        for blk in blocks:
            if tuple(blk["kv"].shape) != want:
                raise ValueError(
                    f"migrated kv block shape {tuple(blk['kv'].shape)} "
                    f"does not match this pool's {want} — the engines' "
                    "model/layout configs must agree")
            if ("sc" in self._caches) != ("sc" in blk):
                raise ValueError(
                    "migrated block cache flavor (int8 scales) does not "
                    "match this engine's")
        now = self.clock()
        need = self._blocks_needed(prompt.size, max_new)
        tokens = [int(t) for t in state["tokens"]]
        req = ServedRequest(next(self._rid), prompt, max_new,
                            state["eos_token_id"],
                            int(state["min_length"]),
                            float(state["repetition_penalty"]), now,
                            deadline_s=state["deadline_s"],
                            seed=int(state["seed"]),
                            trace_id=state["trace_id"],
                            attempt=int(state["attempt"]),
                            priority=state.get("priority", QOS_DEFAULT))
        if not blocks and not staged_ids and not tokens \
                and int(state["nt"]) == 0:
            # never prefilled: the import is a plain (re-)queue — it
            # will be ADMITTED normally later (prefix lookup included)
            if self.max_pending and self._queue_len() >= self.max_pending:
                self._rejected += 1
                if self.telemetry.enabled:
                    self.telemetry.req_rejected(
                        now, trace_id=req.trace_id, attempt=req.attempt)
                raise AdmissionFull(
                    f"pending queue full ({self._queue_len()}/"
                    f"{self.max_pending}) — migrated request shed")
            if self._kv_gate and \
                    self._kv_committed + need > self.pool.num_blocks:
                self._rejected += 1
                if self.telemetry.enabled:
                    self.telemetry.req_rejected(
                        now, trace_id=req.trace_id, attempt=req.attempt)
                raise AdmissionFull("kv pool exhausted — migrated "
                                    "request shed at import")
            self._kv_committed += need
            if staged is not None:
                self._staged.pop(staged, None)   # empty tag, consumed
            self._queues[req.priority].append(req)
            self._req_index[req.rid] = req
            self._migrated_in += 1
            self.telemetry.req_queued(req.rid, now,
                                      trace_id=req.trace_id,
                                      attempt=req.attempt)
            if self.telemetry.enabled:
                self.telemetry.req_event(req.rid, "migrate_in", now)
            return req.rid
        free = self._free_slots()
        if not free:
            self._rejected += 1
            if self.telemetry.enabled:
                self.telemetry.req_rejected(now, trace_id=req.trace_id,
                                            attempt=req.attempt)
            raise AdmissionFull("no free slot to import the migrated "
                                "session into")
        if self._kv_reserved - len(staged_ids) + need \
                > self.pool.num_blocks:
            # staged blocks already hold their own reservation (made at
            # stage_kv_blocks) — it transfers into this request's
            # worst-case reservation on success, so only the DELTA is
            # checked here
            self._rejected += 1
            if self.telemetry.enabled:
                self.telemetry.req_rejected(now, trace_id=req.trace_id,
                                            attempt=req.attempt)
            raise AdmissionFull(
                f"kv pool exhausted: migrated session needs {need} "
                f"blocks, {self.pool.num_blocks - self._kv_reserved} "
                "unreserved")
        s = free[0]
        req.state = "running"
        req.slot = s
        req.t_admit = now                  # queue time on THIS engine: 0
        # TTFT belongs to the attempt that produced the first token —
        # a stream that already emitted keeps t_first unset here (the
        # TTFT histogram legitimately sees fewer entries than finished)
        req.tokens = tokens
        if staged_ids:
            # consume the staged prefix: its standalone reservation
            # folds into the request's, and the blocks become the
            # slot's leading table entries — no re-upload
            del self._staged[staged]
            self._kv_reserved -= len(staged_ids)
        self._kv_committed += need
        self._kv_reserved += need
        new_ids = self._alloc_kv_blocks(len(blocks)) if blocks else []
        for blk, dst in zip(blocks, new_ids):
            self._caches = self.pool.write_block(self._caches, blk, dst)
        self._kv_blocks_adopted += len(blocks)
        ids = list(staged_ids) + list(new_ids)
        row = self._tables[s]
        row[:] = self.pool.num_blocks
        row[:len(ids)] = ids
        self._lens[s] = lens
        self._nt[s] = int(state["nt"])
        self._tok[s] = int(state["tok"])
        self._max_nt[s] = max_new
        self._eos[s] = (-1 if req.eos_token_id is None
                        else int(req.eos_token_id))
        self._min_len[s] = req.min_length
        self._rep_pen[s] = req.repetition_penalty
        self._rseed[s] = req.seed
        self._active[s] = bool(state["active"])
        self._pf_left[s] = int(state["pf_left"])
        if self._drafters is not None:
            # the n-gram maps are a pure function of the token history:
            # reset + update replays exactly the live insert order
            self._drafters[s].reset(prompt)
            self._drafters[s].update(tokens)
        if self._rep_on:
            vocab = self._presence_init().shape[1]
            rowv = np.zeros(vocab, bool)
            rowv[prompt] = True
            if tokens:
                rowv[np.asarray(tokens, np.int64)] = True
            self._presence = self._presence_init().at[s].set(
                jnp.asarray(rowv))
        self._slot_req[s] = req
        self._req_index[req.rid] = req
        self._migrated_in += 1
        self.telemetry.req_queued(req.rid, now, trace_id=req.trace_id,
                                  attempt=req.attempt)
        self.telemetry.req_admitted(req.rid, s, now)
        if self.telemetry.enabled:
            self.telemetry.req_event(req.rid, "migrate_in", now)
        if not self._active[s] and not self._pf_left[s] and tokens:
            # exported at the exact finish boundary: complete instantly
            self._finish(req, now)
        elif (self.role == "prefill" and self._active[s]
                and not self._pf_left[s] and self._nt[s] >= 1):
            # a prompt-complete session landing on a prefill worker
            # (handoff bounce-back after a decode-pool shed race)
            # re-holds immediately — a prefill engine never decodes
            req.state = "prefilled"
            self._active[s] = False
        return req.rid

    # ------------------------------------------------- streamed KV handoff
    def export_kv_prefix(self, rid, start_block=0, min_blocks=1):
        """Read the COMMITTED full KV blocks of a live request without
        detaching it — the streamed-handoff source primitive. Returns
        ``(blocks, n_full)`` where blocks covers pool block indices
        [start_block, n_full) of the slot's table (n_full = lens //
        prefill_cap: only FULL blocks ship early; the partial tail
        block travels with the final export_slot). The request keeps
        running — the router overlaps stage_kv_blocks on the decode
        target with the remaining prefill, so the final transfer is
        just the tail + bookkeeping and TTFT ~ prefill time. Blocks in
        [start_block, n_full) ship exactly once per cursor advance;
        the caller owns the cursor."""
        if not self.paged:
            raise ValueError("export_kv_prefix needs the paged KV cache")
        req = self._req_index.get(rid)
        if req is None or req.state not in ("running", "prefilled") \
                or req.slot is None:
            raise ValueError(f"request {rid} is not resident in a slot")
        s = req.slot
        n_full = int(self._lens[s]) // self.prefill_cap
        start_block = int(start_block)
        if not 0 <= start_block <= n_full:
            raise ValueError(
                f"start_block={start_block} outside [0, {n_full}]")
        if n_full - start_block < max(1, int(min_blocks)):
            # below the caller's chunk threshold: answer without
            # reading so the shipped counter stays exact (every
            # counted block left the pool exactly once per cursor)
            return [], n_full
        row = self._tables[s]
        blocks = [self.pool.read_block(self._caches, int(row[j]))
                  for j in range(start_block, n_full)]
        if blocks:
            self._kv_blocks_shipped += len(blocks)
            self.telemetry.observe_handoff(_kv_payload_bytes(blocks))
            if self.telemetry.enabled:
                self.telemetry.req_event(rid, "kv_ship", self.clock())
        return blocks, n_full

    def stage_kv_blocks(self, tag, blocks):
        """Receive streamed KV blocks AHEAD of their session's import:
        allocate pool blocks (under a staging reservation — the
        admission guarantee that every lazy mapping is satisfiable
        must hold with staged blocks resident), upload the payloads,
        and file the ids under ``tag`` for import_slot(staged=tag) to
        splice in. Repeat calls append (one tag accumulates a prefix
        block-by-block as prefill commits them). Sheds with
        ``AdmissionFull`` when the pool cannot take the blocks — the
        staged prefix so far stays put. Returns the total staged count
        under the tag."""
        if not self.paged:
            raise ValueError("stage_kv_blocks needs the paged KV cache")
        blocks = list(blocks)
        kv_shape = self._caches["kv"].shape      # [L, 2, NB, H, Bt, D]
        want = (kv_shape[0], 2, 1, kv_shape[3], kv_shape[4], kv_shape[5])
        for blk in blocks:
            if tuple(blk["kv"].shape) != want:
                raise ValueError(
                    f"staged kv block shape {tuple(blk['kv'].shape)} "
                    f"does not match this pool's {want}")
            if ("sc" in self._caches) != ("sc" in blk):
                raise ValueError(
                    "staged block cache flavor (int8 scales) does not "
                    "match this engine's")
        if blocks and self._kv_reserved + len(blocks) \
                > self.pool.num_blocks:
            raise AdmissionFull(
                f"kv pool exhausted: staging {len(blocks)} blocks, "
                f"{self.pool.num_blocks - self._kv_reserved} unreserved")
        if blocks:
            self._kv_reserved += len(blocks)
            ids = self._alloc_kv_blocks(len(blocks))
            for blk, dst in zip(blocks, ids):
                self._caches = self.pool.write_block(self._caches, blk,
                                                     dst)
            self._kv_blocks_adopted += len(blocks)
            self._staged.setdefault(tag, []).extend(ids)
        elif tag not in self._staged:
            self._staged[tag] = []
        return len(self._staged[tag])

    def abort_stage(self, tag):
        """Drop a staging tag: free its pool blocks + reservation (the
        handoff fell through — target raced a shed, source died, the
        session finished on the prefill worker). Idempotent; returns
        the number of blocks released."""
        ids = self._staged.pop(tag, None)
        if not ids:
            return 0
        self.pool.deref(ids)
        self._kv_reserved -= len(ids)
        return len(ids)

    # ----------------------------------------------------- QoS preemption
    # Preemption-to-host reuses the migration serialization (the state
    # dict IS a MIGRATION_FMT payload) but keeps the request FIRST-CLASS
    # on this engine: same rid, same _req_index entry (state
    # "preempted"), same tokens list and streaming-harvest cursor — so a
    # tracked reader sees one continuous exactly-once stream across the
    # park/resume legs with zero router involvement. _kv_committed stays
    # held while parked (the request still intends to run here; releasing
    # it would let submit() overcommit the pool against a request that
    # WILL come back); only the running-worst-case reservation
    # (_kv_reserved) and the physical blocks are released.
    def preempt_to_host(self, rid):
        """Preempt a RUNNING request into the host-RAM parking lot:
        serialize its full decode state (KV bytes included), free the
        slot + physical blocks, and keep the request indexed as
        ``preempted``. resume_from_host() restores it token-identically
        (greedy AND plain-sampled — the seed rides the state and every
        draw is fold_in(seed, nt)). Paged engines only."""
        if not self.paged:
            raise ValueError("preempt_to_host needs the paged KV cache "
                             "(the parked payload is pool blocks; "
                             "PADDLE_SERVING_PAGED=0 disables it)")
        req = self._req_index.get(rid)
        if req is None or req.state != "running":
            raise ValueError(f"request {rid} is not running in a slot")
        now = self.clock()
        s = req.slot
        state = {
            "fmt": self.MIGRATION_FMT,
            "prompt": np.asarray(req.prompt, np.int32),
            "tokens": [int(t) for t in req.tokens],
            "max_new_tokens": req.max_new_tokens,
            "eos_token_id": req.eos_token_id,
            "min_length": req.min_length,
            "repetition_penalty": req.repetition_penalty,
            "deadline_s": req.deadline_s,
            "seed": req.seed,
            "trace_id": req.trace_id,
            "attempt": req.attempt,
            "priority": req.priority,
            "prefill_cap": self.prefill_cap,
            "lens": int(self._lens[s]), "nt": int(self._nt[s]),
            "tok": int(self._tok[s]), "active": bool(self._active[s]),
            "pf_left": int(self._pf_left[s]),
            "kv": [],
        }
        row = self._tables[s]
        for j in range(-(-state["lens"] // self.prefill_cap)):
            state["kv"].append(
                self.pool.read_block(self._caches, int(row[j])))
        need = self._blocks_needed(req.prompt.size, req.max_new_tokens)
        self._kv_reserved -= need
        self._slot_req[s] = None
        self._active[s] = False
        self._pf_left[s] = 0
        self._free_slot_blocks(s)
        req.slot = None
        req.state = "preempted"
        # the injected-fault window: slot freed, parking insert pending.
        # A raise here loses the parked copy — the replica dies and the
        # router's CLASSIC failover (delivered-prefix skip) replays the
        # stream exactly-once elsewhere; pinned by test.
        from ..testing import fault
        fault.inject("preempt")
        self._parked[rid] = state
        self._preempted += 1
        if self.telemetry.enabled:
            self.telemetry.req_event(rid, "preempt", now)
        return rid

    def resume_from_host(self, rid):
        """Re-import a parked request into a free slot (fresh physical
        blocks, KV bytes re-uploaded, drafter/presence rebuilt from the
        token history). Sheds with ``AdmissionFull`` when no slot or no
        reservation headroom can take it — the parked copy stays put and
        a later pass retries. t_submit/t_admit/deadline are UNTOUCHED:
        the deadline clock keeps running while parked (park time is
        queue-attributed delay, never a budget refill)."""
        state = self._parked.get(rid)
        req = self._req_index.get(rid)
        if state is None or req is None or req.state != "preempted":
            raise ValueError(f"request {rid} is not parked here")
        free = self._free_slots()
        if not free:
            raise AdmissionFull("no free slot to resume the parked "
                                "request into")
        need = self._blocks_needed(req.prompt.size, req.max_new_tokens)
        if self._kv_reserved + need > self.pool.num_blocks:
            raise AdmissionFull(
                f"kv pool exhausted: resume needs {need} blocks, "
                f"{self.pool.num_blocks - self._kv_reserved} unreserved")
        now = self.clock()
        s = free[0]
        del self._parked[rid]
        blocks = state["kv"]
        self._kv_reserved += need          # committed never left
        ids = self._alloc_kv_blocks(len(blocks)) if blocks else []
        for blk, dst in zip(blocks, ids):
            self._caches = self.pool.write_block(self._caches, blk, dst)
        row = self._tables[s]
        row[:] = self.pool.num_blocks
        row[:len(ids)] = ids
        self._lens[s] = int(state["lens"])
        self._nt[s] = int(state["nt"])
        self._tok[s] = int(state["tok"])
        self._max_nt[s] = req.max_new_tokens
        self._eos[s] = (-1 if req.eos_token_id is None
                        else int(req.eos_token_id))
        self._min_len[s] = req.min_length
        self._rep_pen[s] = req.repetition_penalty
        self._rseed[s] = req.seed
        self._active[s] = bool(state["active"])
        self._pf_left[s] = int(state["pf_left"])
        if self._drafters is not None:
            self._drafters[s].reset(req.prompt)
            self._drafters[s].update(req.tokens)
        if self._rep_on:
            vocab = self._presence_init().shape[1]
            rowv = np.zeros(vocab, bool)
            rowv[req.prompt] = True
            if req.tokens:
                rowv[np.asarray(req.tokens, np.int64)] = True
            self._presence = self._presence_init().at[s].set(
                jnp.asarray(rowv))
        req.slot = s
        req.state = "running"
        self._slot_req[s] = req
        self._resumed += 1
        if self.telemetry.enabled:
            self.telemetry.req_event(rid, "resume", now)
        if not self._active[s] and not self._pf_left[s] and req.tokens:
            self._finish(req, now)
        return rid

    def _qos_schedule(self):
        """One scheduling pass per step (paged engines only): resume
        parked requests best-class-first while there is headroom, then —
        when a strictly better-class queue head is blocked on slots or
        on the kv reservation — preempt the single worst (lowest-class,
        youngest) running victim to the parking lot. At most one
        preemption per step keeps the pass O(slots) and lets the freed
        capacity be re-measured before the next eviction."""
        if not self.paged:
            return
        # resume pass: parked requests compete in class order; stop at
        # the first one that doesn't fit (FIFO-within-class fairness),
        # and never jump ahead of a strictly better queued head
        for rid in sorted(self._parked,
                          key=lambda r: (QOS_RANK[
                              self._parked[r]["priority"]], r)):
            head = self._queue_head()
            if head is not None and QOS_RANK[head.priority] < \
                    QOS_RANK[self._parked[rid]["priority"]]:
                break
            try:
                self.resume_from_host(rid)
            except AdmissionFull:
                break
        head = self._queue_head()
        if head is None:
            return
        need = self._blocks_needed(head.prompt.size,
                                   head.max_new_tokens)
        blocked = (not self._free_slots()
                   or self._kv_reserved + need > self.pool.num_blocks)
        if not blocked:
            return
        victims = [r for r in self._slot_req
                   if r is not None and r.state == "running"
                   and QOS_RANK[r.priority] > QOS_RANK[head.priority]]
        if not victims:
            return
        victim = max(victims,
                     key=lambda r: (QOS_RANK[r.priority], r.rid))
        self.preempt_to_host(victim.rid)

    def _prefill_allocations(self, pf_rows, budget, col_cap=None):
        """Weighted-fair split of this dispatch's prefill budget across
        QoS classes — pure host arithmetic over which rows advance their
        prefill cursors, so the dispatch shapes (and therefore the
        executables) never change. Two passes: (1) proportional — each
        class with waiting prefill work gets floor(budget * share /
        total_shares) tokens, spent FCFS-by-rid within the class; (2)
        work-conserving spill — leftover budget (idle classes, floors,
        capped rows) goes to remaining demand in (class-rank, rid)
        order. With a SINGLE class present pass 1 is skipped and the
        result is exactly the old FCFS packing — token-identical to the
        pre-QoS scheduler. Returns ([(slot, n), ...] ordered by
        (class-rank, rid), remaining_budget)."""
        order = sorted(pf_rows,
                       key=lambda s: (QOS_RANK[self._slot_req[s].priority],
                                      self._slot_req[s].rid))
        cap = budget if col_cap is None else col_cap
        want = {s: min(int(self._pf_left[s]), cap) for s in order}
        alloc = {s: 0 for s in order}
        classes = {self._slot_req[s].priority for s in order}
        if len(classes) > 1:
            total = sum(self.qos_shares[c] for c in classes)
            for c in classes:
                fair = budget * self.qos_shares[c] // total
                for s in order:
                    if self._slot_req[s].priority != c:
                        continue
                    n = min(want[s] - alloc[s], fair)
                    alloc[s] += n
                    fair -= n
        spent = sum(alloc.values())
        left = budget - spent
        for s in order:
            if left <= 0:
                break
            n = min(want[s] - alloc[s], left)
            alloc[s] += n
            left -= n
        return [(s, alloc[s]) for s in order if alloc[s] > 0], left

    def _build_decode_chunk(self):
        """The ONE compiled decode step: decode_chunk tokens per dispatch
        over all B slots, each at its own depth (the scan length comes
        from the `keys` argument the caller builds, one key per token).
        Finish bookkeeping (per-slot eos / max_new_tokens) runs on-device
        inside the scan; the host only sees the per-step (token,
        emitted-mask) ys at the chunk boundary."""
        core = self._core()
        hidden, head_logits = core.hidden, core.head_logits
        rep_on = self._rep_on
        do_sample = self.do_sample
        top_k, top_p, temp = self.top_k, self.top_p, self.temperature
        chunk = self.decode_chunk

        def decode_chunk(stk, e_arrays, h_arrays, caches, tok, lens,
                         active, nt, max_nt, eos_ids, min_len, rep_pen,
                         presence, seeds):
            def body(carry, _):
                tok, caches, lens, active, nt, presence = carry
                x, caches = hidden(stk, e_arrays, caches, tok, lens)
                logits = head_logits(h_arrays, x)
                logits = logits.reshape(logits.shape[0], -1)
                logits = _penalize_slots(
                    logits, presence if rep_on else None, rep_pen, nt,
                    min_len, eos_ids)
                # per-row keys fold (request seed, nt): sampling is
                # invariant to chunk boundaries and scheduling
                nxt = _sample_rows(logits, do_sample, top_k, top_p,
                                   temp, seeds, nt)
                emitted = active
                hit_eos = (eos_ids >= 0) & (nxt == eos_ids)
                step = active.astype(jnp.int32)
                nt = nt + step
                lens = lens + step
                active = active & ~hit_eos & (nt < max_nt)
                tok = jnp.where(emitted, nxt, tok)
                if rep_on:
                    presence = presence.at[
                        jnp.arange(nxt.shape[0]), nxt].max(emitted)
                carry = (tok, caches, lens, active, nt, presence)
                return carry, (nxt, emitted)
            carry, ys = jax.lax.scan(
                body, (tok, caches, lens, active, nt, presence), None,
                length=chunk)
            tok, caches, lens, active, nt, presence = carry
            return caches, tok, lens, active, nt, presence, ys
        return decode_chunk

    def _build_prefill_chunk(self, chunk):
        """In-slot prefill: `chunk` teacher-forced tokens, per-row start
        positions and per-row valid counts. Rows outside their valid
        range (and slots not being admitted, n_valid == 0) are write-
        masked — their cache rows cannot be touched. Each admitted row's
        LAST valid hidden state is captured into last_x."""
        hidden = self._core().hidden

        def prefill(stk, e_arrays, caches, toks, t0, n_valid, last_x):
            def body(carry, xs):
                caches, last_x = carry
                tok_i, i = xs
                mask = i < n_valid
                x, caches = hidden(stk, e_arrays, caches, tok_i, t0 + i,
                                   mask)
                last_x = jnp.where(mask[:, None, None], x, last_x)
                return (caches, last_x), None
            (caches, last_x), _ = jax.lax.scan(
                body, (caches, last_x),
                (toks, jnp.arange(chunk, dtype=jnp.int32)))
            return last_x, caches
        return prefill

    def _build_admit_sample(self):
        """First-token sample on the prefill hidden states (TTFT): the
        per-slot logit controls apply at nt=0 for the admitted rows;
        non-admitted rows' outputs are discarded by the host."""
        head_logits = self._core().head_logits
        rep_on = self._rep_on
        do_sample = self.do_sample
        top_k, top_p, temp = self.top_k, self.top_p, self.temperature

        def admit_sample(h_arrays, last_x, seeds, eos_ids, min_len,
                         rep_pen, presence):
            logits = head_logits(h_arrays, last_x)
            logits = logits.reshape(logits.shape[0], -1)
            nt0 = jnp.zeros(logits.shape[0], jnp.int32)
            logits = _penalize_slots(
                logits, presence if rep_on else None, rep_pen, nt0,
                min_len, eos_ids)
            return _sample_rows(logits, do_sample, top_k, top_p, temp,
                                seeds, nt0)
        return admit_sample

    def _build_bulk_admit(self, sb):
        """In-slot BULK prefill: one causal-flash pass over a single
        padded prompt row [1, sb] (parallel over positions — no scan),
        then one scatter of its K/V into the slot's cache row. Garbage
        K/V at padded positions [plen, sb) is safe: decode writes the
        real token's K/V at position `lens` BEFORE attending it
        (write-then-attend), so a garbage position is always overwritten
        the step it would first become attendable."""
        core = self._core()
        bulk_hidden = core.bulk_hidden
        int8 = self.dec._int8_cache()
        cache_dtype = self.dec.fmt.qkv_weights[0]._data.dtype

        def bulk_admit(stk, e_arrays, caches, toks, slot, plen):
            x, kv_all = bulk_hidden(stk, e_arrays, toks)
            # the row's OWN last real token's hidden state (ragged pad)
            last = jax.lax.dynamic_slice_in_dim(x, plen - 1, 1, 1)
            kv = kv_all[:, :, 0]                      # [L, 2, H, sb, D]
            if isinstance(caches, dict):
                # paged: scatter the prompt's K/V through the slot's
                # block table. Positions >= plen (the pow-2 pad) go OUT
                # OF BOUNDS and drop — unlike the dense path they never
                # land as garbage, so the pad needs no pool blocks and
                # the write-then-attend overwrite argument isn't even
                # needed.
                pool_kv, tbl = caches["kv"], caches["tbl"]
                nb = pool_kv.shape[2]
                bt = pool_kv.shape[4]
                row = jax.lax.dynamic_index_in_dim(tbl, slot, 0,
                                                   keepdims=False)
                pos = jnp.arange(sb, dtype=jnp.int32)
                blk = jnp.where(pos < plen, jnp.take(row, pos // bt), nb)
                off = pos % bt
                if int8:
                    qi, sc = _absmax_int8(kv, -1)
                    kvq = pool_kv.at[:, :, blk, :, off, :].set(
                        jnp.transpose(qi, (3, 0, 1, 2, 4)), mode="drop")
                    scq = caches["sc"].at[:, :, blk, :, 0, off].set(
                        jnp.transpose(sc[..., 0], (3, 0, 1, 2)),
                        mode="drop")
                    caches = dict(caches, kv=kvq, sc=scq)
                else:
                    caches = dict(caches, kv=pool_kv.at[
                        :, :, blk, :, off, :].set(
                        jnp.transpose(kv, (3, 0, 1, 2, 4)).astype(
                            pool_kv.dtype), mode="drop"))
            elif int8:
                qi, sc = _absmax_int8(kv, -1)
                ci8 = caches[0].at[:, :, slot, :, :sb, :].set(qi)
                scs = caches[1].at[:, :, slot, :, 0, :sb].set(sc[..., 0])
                caches = (ci8, scs)
            else:
                caches = caches.at[:, :, slot, :, :sb, :].set(
                    kv.astype(cache_dtype))
            return caches, last
        return bulk_admit

    def _bulk_admit_row(self, stk, e_arrays, req, last_x):
        plen = req.prompt.size
        sb = min(1 << (int(plen) - 1).bit_length(), self.smax)
        toks = np.zeros((1, sb), np.int32)
        toks[0, :plen] = req.prompt
        (out, row_x), _ = self._run_dispatch(
            ("bulk_admit", sb),
            lambda s=sb: self._build_bulk_admit(s), (2,),
            (stk, e_arrays, self._cache_arg(), jnp.asarray(toks),
             jnp.asarray(req.slot, jnp.int32),
             jnp.asarray(plen, jnp.int32)),
            rows=1, tokens=int(plen))
        self._keep_caches(out)
        return last_x.at[req.slot].set(row_x[0])

    # --------------------------------------------------------- scheduling
    def _free_slots(self):
        return [i for i in range(self.num_slots)
                if not self._active[i] and self._slot_req[i] is None]

    def _prefix_cache_for_dispatch(self):
        """The prefix cache this dispatch may use, or None. The PAGED
        cache is pure host index bookkeeping over the (head-sharded)
        block pool — adopt writes table entries, commit pins the
        slot's own blocks — so it participates under a mesh unchanged.
        The DENSE cache's compiled adopt/commit gather/splat copies
        assume an unsharded ring: that is the one genuinely
        unsupported config left, so under a mesh it stays off (warned
        ONCE, naming why) and every admission counts as a miss —
        hits + misses == admitted still reconciles and the dead cache
        is visible as hit_rate == 0."""
        if self.prefix_cache is None:
            return None
        if self.paged or self.dec._mesh_mp() is None:
            return self.prefix_cache
        if not self._pc_mesh_warned:
            import warnings
            warnings.warn(
                "serving: dense prefix cache disabled under an active "
                "mp mesh — its compiled adopt/commit copies assume an "
                "unsharded ring cache, so every admission counts as a "
                "miss. The paged engine (the default) shards its pool "
                "by head and keeps prefix caching on under a mesh.",
                RuntimeWarning, stacklevel=3)
            self._pc_mesh_warned = True
        return None

    def _admit(self):
        """Move queued requests into free slots: batched in-slot prefill
        (chunked, write-masked) + one first-token sample. Returns the
        list of admitted requests (each just emitted its first token)."""
        free = self._free_slots()
        batch = []
        while free and self._queue_len():
            if self.paged:
                # pool-bounded admission: a request enters a slot only
                # with its WORST-CASE block reservation covered (sum of
                # running reservations <= NBtotal keeps every lazy
                # allocation satisfiable — shared blocks only add
                # slack). Otherwise it waits; eviction frees blocks.
                head = self._queue_head()
                need = self._blocks_needed(head.prompt.size,
                                           head.max_new_tokens)
                if self._kv_reserved + need > self.pool.num_blocks:
                    break
                self._kv_reserved += need
            req = self._queue_popleft()
            slot = free.pop(0)
            req.slot = slot
            req.state = "running"
            self._slot_req[slot] = req
            batch.append(req)
        if not batch:
            return []
        self._admitted += len(batch)
        for r in batch:
            self._class_admitted[r.priority] += 1
        tele = self.telemetry
        # t_admit is ALWAYS stamped (ring on or off): the SLO layer's
        # queue/service decomposition reads it at _finish
        t_adm = self.clock()
        for r in batch:
            r.t_admit = t_adm
            tele.req_admitted(r.rid, r.slot, t_adm)
        b = self.num_slots
        stk = self.dec._stacked()
        e_arrays = [p._data for p in self.dec._embed_params]
        h_arrays = self.dec._maybe_quant_head(
            [p._data for p in self.dec._head_params])

        if self._rep_on:
            # reset the admitted rows' presence to their prompt one-hots
            vocab = self._presence_init().shape[1]
            admit_mask = np.zeros(b, bool)
            rows = np.zeros((b, vocab), bool)
            for r in batch:
                admit_mask[r.slot] = True
                rows[r.slot, r.prompt] = True
            self._presence = jnp.where(
                jnp.asarray(admit_mask)[:, None], jnp.asarray(rows),
                self._presence_init())

        # E from the embedding table; dtype from the stack
        e_dim = int(e_arrays[0].shape[-1]) if e_arrays else \
            int(self.dec.fmt.qkv_weights[0]._data.shape[-1])
        dt = self.dec.fmt.qkv_weights[0]._data.dtype
        last_x = jnp.zeros((b, 1, e_dim), dt)

        # Two in-slot prefill flavors:
        #  * bulk (default, no mesh): ONE causal-flash pass over the
        #    single admitted row, padded to a pow-2 bucket, then one
        #    scatter into that slot's cache row. Prefill compute is per
        #    ROW — the masked batch scan below runs every step over all
        #    B rows to fill one, which made admission cost ~B x static
        #    batching's shared prefill on the serving bench.
        #  * masked scan (mesh / opt-out PADDLE_TPU_SERVE_BULK=0): the
        #    chunked prefill scan with a per-row write mask.
        mesh_on = self.dec._mesh_mp() is not None
        use_bulk = (not mesh_on and
                    os.environ.get("PADDLE_TPU_SERVE_BULK", "1") != "0")
        # Prefix-cache admission: the longest published block chain is
        # splatted into the slot's cache row by ONE compiled gather-copy
        # dispatch (pow-2 ladder over chain length), and only the
        # uncached suffix goes through prefill. The paged cache (host
        # index writes over the shared pool) also runs under a mesh;
        # only the dense flavor sits out there — see
        # _prefix_cache_for_dispatch for the miss-counting contract.
        pc = self._prefix_cache_for_dispatch()
        if pc is None and self.prefix_cache is not None:
            self._prefix_misses += len(batch)
        base = np.zeros(b, np.int32)          # adopted tokens per slot
        published = set()                     # slots published this admit
        for r in batch:
            if pc is not None:
                # lookup + (miss-path bulk prefill + publish) run PER
                # REQUEST, in order: a cold gang of same-template
                # requests admitted in one batch would otherwise ALL
                # miss — row 1's publish lets rows 2..B adopt the
                # template inside the same admission
                nodes = pc.lookup(r.prompt)
                if nodes:
                    if self.paged:
                        # THE zero-copy hit: the matched chain's pool
                        # indices are written into the slot's block
                        # table (+refcount) — no gather, no dispatch
                        base[r.slot] = pc.adopt_into(self._tables,
                                                     r.slot, nodes)
                    else:
                        pc.store.acquire(nodes)   # pin across the copy
                        try:
                            self._caches = pc.adopt(self._caches,
                                                    r.slot, nodes)
                        finally:
                            pc.store.release(nodes)
                        base[r.slot] = len(nodes) * pc.block_tokens
                    self._prefix_hits += 1
                    self._prefill_tokens_saved += int(base[r.slot])
                    tele.req_event(r.rid, "prefix_adopt", t_adm)
                else:
                    self._prefix_misses += 1
            if self.prefix_cache is not None:
                self._prefill_tokens_computed += (r.prompt.size
                                                  - int(base[r.slot]))
            if self.paged:
                # map the prompt's remaining blocks (adopted entries
                # already point into the pool); the decode window maps
                # lazily chunk by chunk
                self._map_blocks(r.slot, r.prompt.size)
            if use_bulk and not base[r.slot]:
                last_x = self._bulk_admit_row(stk, e_arrays, r, last_x)
                tele.req_event(r.rid, "prefill_chunk", t_adm)
                if pc is not None:
                    if self.paged:
                        pc.publish_from(self._tables, r.slot, r.prompt)
                    else:
                        pc.publish(self._caches, r.slot, r.prompt)
                    published.add(r.slot)
        # a prefix hit always takes the masked-scan path for its suffix:
        # the bulk flash pass has no way to attend the adopted prefix
        # K/V, while the per-token scan attends the whole cache row up
        # to each position by construction
        scan_batch = [r for r in batch if not use_bulk or base[r.slot]]
        if scan_batch:
            maxp = max(r.prompt.size - int(base[r.slot])
                       for r in scan_batch)
            chunks = self._prefill_chunks(maxp)
            prompts = np.zeros((b, sum(chunks)), np.int32)
            n_left = np.zeros(b, np.int32)
            for r in scan_batch:
                sfx = r.prompt[int(base[r.slot]):]
                prompts[r.slot, :sfx.size] = sfx
                n_left[r.slot] = sfx.size
            pos = 0
            for chunk in chunks:
                toks = jnp.asarray(
                    np.ascontiguousarray(prompts[:, pos:pos + chunk].T))
                t0 = np.where(n_left > 0, base + pos, self._lens).astype(
                    np.int32)
                n_valid = np.clip(n_left - pos, 0, chunk).astype(
                    np.int32)
                (last_x, out), _ = self._run_dispatch(
                    ("prefill", chunk),
                    lambda c=chunk: self._build_prefill_chunk(c), (2,),
                    (stk, e_arrays, self._cache_arg(), toks,
                     jnp.asarray(t0), jnp.asarray(n_valid), last_x),
                    rows=int((n_valid > 0).sum()),
                    tokens=int(n_valid.sum()))
                self._keep_caches(out)
                pos += chunk
            for r in scan_batch:
                self.telemetry.req_event(r.rid, "prefill_chunk", t_adm)
        # commit-on-prefill for the rows whose prefill just landed via
        # the scan (bulk-miss rows published inline above): publish each
        # prompt's full blocks back to the pool under their token keys.
        # Adopted blocks re-resolve to their existing nodes (dedup, no
        # copy); only genuinely new blocks are copied out of the slot
        # row (dense) or referenced in place (paged: publication takes
        # a store ref on the slot's OWN blocks — zero-copy commit).
        # COW is structural either way: decode only writes slot-private
        # positions >= plen, strictly past every published full block.
        if pc is not None:
            for r in batch:
                if r.slot not in published:
                    if self.paged:
                        pc.publish_from(self._tables, r.slot, r.prompt)
                    else:
                        pc.publish(self._caches, r.slot, r.prompt)

        # per-slot params refresh for the admitted rows
        for r in batch:
            s = r.slot
            self._lens[s] = r.prompt.size
            self._nt[s] = 0
            self._max_nt[s] = r.max_new_tokens
            self._eos[s] = (-1 if r.eos_token_id is None
                            else int(r.eos_token_id))
            self._min_len[s] = r.min_length
            self._rep_pen[s] = r.repetition_penalty
            self._rseed[s] = r.seed
            if self._drafters is not None:
                self._drafters[s].reset(r.prompt)

        out, _ = self._run_dispatch(
            ("admit_sample",), self._build_admit_sample, (),
            (h_arrays, last_x, jnp.asarray(self._rseed, jnp.int32),
             jnp.asarray(self._eos), jnp.asarray(self._min_len),
             jnp.asarray(self._rep_pen), self._presence_arg()),
            rows=len(batch), tokens=len(batch))
        nxt = np.asarray(out)

        now = self.clock()
        self._decode_steps += len(batch)     # one sample event per row
        for r in batch:
            s = r.slot
            tok0 = int(nxt[s])
            r.t_first = now
            tele.req_event(r.rid, "first_token", now)
            r.tokens.append(tok0)
            self._class_tokens[r.priority] += 1
            self._nt[s] = 1
            self._tok[s] = tok0
            if self._drafters is not None:
                self._drafters[s].update([tok0])
            hit_eos = (r.eos_token_id is not None
                       and tok0 == int(r.eos_token_id))
            self._active[s] = not hit_eos and r.max_new_tokens > 1
            if self._rep_on:
                self._presence = self._presence.at[s, tok0].set(True)
            if not self._active[s]:
                self._finish(r, now)
        return batch

    def _admit_chunked(self):
        """Token-budget admission: move queued requests into free slots
        as pure BOOKKEEPING — prefix-cache lookup/adopt plus slot-state
        reset. No prefill dispatch happens here: the slot enters
        `prefilling` (pf_left > 0) and the budget packer streams its
        prompt through spare step capacity, so a long prompt can never
        stall the decode gang. Publication back to the prefix store
        happens when the prompt completes (commit-on-prefill, the same
        dedup as the phase path — cold same-template gangs admitted
        together all miss, unlike phase admission's serialized
        publish-then-lookup; the store converges one prompt later)."""
        free = self._free_slots()
        batch = []
        while free and self._queue_len():
            if self.paged:
                # pool-bounded admission, same reservation rule as the
                # phase path: worst-case blocks covered or the head
                # waits (deadline expiry still runs every step)
                head = self._queue_head()
                need = self._blocks_needed(head.prompt.size,
                                           head.max_new_tokens)
                if self._kv_reserved + need > self.pool.num_blocks:
                    break
                self._kv_reserved += need
            req = self._queue_popleft()
            slot = free.pop(0)
            req.slot = slot
            req.state = "running"
            self._slot_req[slot] = req
            batch.append(req)
        if not batch:
            return []
        self._admitted += len(batch)
        for r in batch:
            self._class_admitted[r.priority] += 1
        tele = self.telemetry
        # always stamped (SLO queue/service decomposition reads it)
        t_adm = self.clock()
        for r in batch:
            r.t_admit = t_adm
            tele.req_admitted(r.rid, r.slot, t_adm)
        if self._rep_on:
            # presence seeds with the FULL prompt at admission (the
            # budget core's penalty at the first-token sample needs it;
            # teacher-forced prefill columns never consume it)
            vocab = self._presence_init().shape[1]
            admit_mask = np.zeros(self.num_slots, bool)
            rows = np.zeros((self.num_slots, vocab), bool)
            for r in batch:
                admit_mask[r.slot] = True
                rows[r.slot, r.prompt] = True
            self._presence = jnp.where(
                jnp.asarray(admit_mask)[:, None], jnp.asarray(rows),
                self._presence_init())
        pc = self._prefix_cache_for_dispatch()
        if pc is None and self.prefix_cache is not None:
            self._prefix_misses += len(batch)
        for r in batch:
            s = r.slot
            base = 0
            if pc is not None:
                nodes = pc.lookup(r.prompt)
                if nodes:
                    if self.paged:
                        base = pc.adopt_into(self._tables, s, nodes)
                    else:
                        pc.store.acquire(nodes)   # pin across the copy
                        try:
                            self._caches = pc.adopt(self._caches, s,
                                                    nodes)
                        finally:
                            pc.store.release(nodes)
                        base = len(nodes) * pc.block_tokens
                    self._prefix_hits += 1
                    self._prefill_tokens_saved += int(base)
                    tele.req_event(r.rid, "prefix_adopt", t_adm)
                else:
                    self._prefix_misses += 1
            if self.prefix_cache is not None:
                self._prefill_tokens_computed += (r.prompt.size
                                                  - int(base))
            # lens IS the prefill cursor: KV entries written so far
            # (adopted prefix now, streamed chunks as they land)
            self._lens[s] = base
            self._pf_left[s] = r.prompt.size - int(base)
            self._nt[s] = 0
            self._max_nt[s] = r.max_new_tokens
            self._eos[s] = (-1 if r.eos_token_id is None
                            else int(r.eos_token_id))
            self._min_len[s] = r.min_length
            self._rep_pen[s] = r.repetition_penalty
            self._rseed[s] = r.seed
            self._active[s] = False          # decoding starts at finish
            if self._drafters is not None:
                self._drafters[s].reset(r.prompt)
        return batch

    def _get_spec_rng(self):
        if self._spec_rng is None:
            self._spec_rng = np.random.RandomState(
                _host_seed(next_key()))
        return self._spec_rng

    def _budget_step(self):
        """ONE token-budget dispatch: pack decode rows (1 mandatory
        input token + any draft claim each) and prefill chunks into the
        compiled [B, C] budget core, then harvest per-row. Pure-decode
        steps fall back to the (equally warm) decode-chunk scan when
        IT moves more tokens per dispatch — the budget arithmetic that
        subsumes the deprecated thin-draft heuristic. Returns tokens
        emitted. Flat mode (PADDLE_SERVING_FLAT_BUDGET) swaps the
        [B, C] block for the token-flattened [T] stream — same
        contracts, ~zero padding (see _flat_budget_step)."""
        if self._flat_budget:
            return self._flat_budget_step()
        from .spec_decode import propose_claims
        b = self.num_slots
        c = self._budget_cols
        dec_rows = [s for s in range(b) if self._active[s]]
        pf_rows = [s for s in range(b) if self._pf_left[s] > 0]
        if not dec_rows and not pf_rows:
            return 0
        k = self.spec_k
        if k:
            # a row's whole segment (input + drafts) must fit the C
            # columns; the bonus-token budget cap lives in the helper
            drafts, dlen = propose_claims(self._drafters, dec_rows, k,
                                          self._max_nt - self._nt,
                                          col_cap=c)
        else:
            drafts = np.zeros((b, 1), np.int32)
            dlen = np.zeros(b, np.int32)
        if not pf_rows and len(dec_rows) + int(dlen.sum()) < \
                len(dec_rows) * self.decode_chunk:
            # budget arithmetic: the block step processes
            # len(dec) + sum(dlen) real tokens, the chunk scan
            # len(dec) * decode_chunk — dispatch whichever moves more
            return self._decode_one_chunk()
        # ---- pack: decode inputs are mandatory, prefill chunks fill
        # spare capacity (rotating start so concurrent prefills share
        # the budget), drafts claim what is left
        budget = self.token_budget - len(dec_rows)
        toks = np.zeros((b, c), np.int32)
        seg = np.zeros(b, np.int32)
        gen0 = np.full(b, c, np.int32)
        pf_n = np.zeros(b, np.int32)
        for s in dec_rows:
            toks[s, 0] = self._tok[s]
            seg[s] = 1
            gen0[s] = 0
        if pf_rows:
            # weighted-fair packing: each QoS class PRESENT in the
            # prefilling set gets its proportional share of the spare
            # budget, spent FCFS (Sarathi's order) within the class,
            # leftovers spill work-conserving in class order. With one
            # class present this is exactly the old pure-FCFS packing —
            # the oldest prompt takes the whole spare budget first
            # (round-robin would stretch every concurrent TTFT tail).
            allocs, budget = self._prefill_allocations(pf_rows, budget,
                                                       col_cap=c)
            for s, n in allocs:
                req = self._slot_req[s]
                p0 = req.prompt.size - int(self._pf_left[s])
                toks[s, :n] = req.prompt[p0:p0 + n]
                seg[s] = n
                pf_n[s] = n
                if n == int(self._pf_left[s]):
                    # finishing this dispatch: the last prompt token's
                    # logits sample the request's FIRST generated token
                    gen0[s] = n - 1
        if k:
            for s in dec_rows:
                m = min(int(dlen[s]), budget)
                dlen[s] = m
                if m > 0:
                    toks[s, 1:1 + m] = drafts[s, :m]
                    seg[s] = 1 + m
                    budget -= m
        tail = 0 if k else max(self.decode_chunk - 1, 0)
        if self.paged:
            # cover every packed row's write window before dispatch
            # (lazy mapping + the COW guard): the block's segment,
            # plus the trailing decode scan's window for rows that
            # will be decoding after the block (active rows and
            # prefill rows finishing here), clamped to the
            # admission-time reservation `plen + max_new`
            for s in range(b):
                if not seg[s]:
                    continue
                decodes = bool(self._active[s]) or \
                    (pf_n[s] and pf_n[s] == self._pf_left[s])
                hi = (int(self._lens[s]) + int(seg[s])
                      + (tail if decodes else 0))
                req = self._slot_req[s]
                cap_pos = req.prompt.size + int(self._max_nt[s])
                self._ensure_writable(s, int(self._lens[s]),
                                      min(hi, cap_pos))
        stk = self.dec._stacked()
        e_arrays = [p._data for p in self.dec._embed_params]
        h_arrays = self.dec._maybe_quant_head(
            [p._data for p in self.dec._head_params])
        full_logits = bool(self.do_sample and k)
        res, ev = self._run_dispatch(
            ("budget", c),
            lambda: self.dec._build_budget_core(
                c, self._rep_on, self.do_sample, self.top_k, self.top_p,
                self.temperature, full_logits=full_logits,
                chain=bool(k), scan_tail=tail),
            (3,),
            (stk, e_arrays, h_arrays, self._cache_arg(),
             jnp.asarray(toks), jnp.asarray(self._lens),
             jnp.asarray(seg), jnp.asarray(gen0), jnp.asarray(self._nt),
             jnp.asarray(self._max_nt), jnp.asarray(self._eos),
             jnp.asarray(self._min_len), jnp.asarray(self._rep_pen),
             self._presence_arg(), jnp.asarray(self._rseed, jnp.int32)),
            rows=int((seg > 0).sum()),
            budget_used=int(seg.sum()),
            budget_wasted=b * c - int(seg.sum()),
            drafts=int(dlen.sum()))
        self._keep_caches(res[0])
        self._budget_steps += 1
        self._budget_tokens_used += int(seg.sum())
        self._budget_prefill_tokens += int(pf_n.sum())
        self._budget_decode_tokens += len(dec_rows)
        self._budget_draft_tokens += int(dlen.sum())
        # the row layout COMPUTES every one of the B x C positions —
        # the masked remainder is the wasted-FLOPs ledger the flat
        # layout drives to ~0
        self._budget_padding_tokens += b * c - int(seg.sum())
        if not k:
            return self._harvest_budget_plain(res, ev, pf_n, tail)
        # per-slot chain views into the [B, C] block outputs: slot s's
        # segment occupies columns [0, seg[s]) of its row
        out = np.asarray(res[1])
        if full_logits:
            out = out.astype(np.float32)
        chain_out = {s: out[s, :int(seg[s])]
                     for s in range(b) if seg[s]}
        return self._harvest_budget_chain(chain_out, ev, pf_n, dec_rows,
                                          drafts, dlen, full_logits)

    def _harvest_budget_plain(self, res, ev, pf_n, tail):
        """Non-spec budget harvest, shared by the row-aligned and flat
        dispatches (both cores return the same advanced-state tuple):
        the core advanced ALL row state on device (block sample +
        trailing decode scan); the host walks tokens and finish
        events. Returns tokens emitted."""
        b = self.num_slots
        tele = self.telemetry
        now = self.clock()
        pc = self._prefix_cache_for_dispatch()
        (_, tok0, emit0, (ys_t, ys_e), tokc, lensc, activec, ntc,
         presc) = res
        tok0 = np.asarray(tok0)
        emit0 = np.asarray(emit0)
        ys_t = np.asarray(ys_t)          # [tail, B]
        ys_e = np.asarray(ys_e)
        prev_active = self._active.copy()
        self._tok = np.array(tokc)
        self._lens = np.array(lensc)
        self._nt = np.array(ntc)
        still_active = np.array(activec)
        if self._rep_on:
            self._presence = presc
        n_emitted = 0
        for s in range(b):
            req = self._slot_req[s]
            if req is None:
                continue
            if pf_n[s]:
                self._pf_left[s] -= int(pf_n[s])
                tele.req_event(req.rid, "prefill_chunk", now)
                if self._pf_left[s] == 0 and pc is not None:
                    # commit-on-prefill publication: decode writes
                    # (including this dispatch's trailing scan)
                    # land strictly past every published full
                    # block, so publishing at harvest is safe
                    if self.paged:
                        pc.publish_from(self._tables, s, req.prompt)
                    else:
                        pc.publish(self._caches, s, req.prompt)
            if not emit0[s] and not prev_active[s]:
                continue                 # idle or still prefilling
            row_toks = []
            if emit0[s]:
                row_toks.append(int(tok0[s]))
                if pf_n[s]:              # the prompt finished HERE
                    req.t_first = now
                    tele.req_event(req.rid, "first_token", now)
            if tail:
                hits = ys_e[:, s]
                row_toks.extend(int(t) for t in ys_t[hits, s])
            if row_toks and prev_active[s]:
                tele.req_event(req.rid, "decode", now)
            req.tokens.extend(row_toks)
            self._class_tokens[req.priority] += len(row_toks)
            n_emitted += len(row_toks)
            self._decode_steps += len(row_toks)
            if not still_active[s]:
                self._finish(req, now)
        self._active = still_active
        tele.finish_step(ev, self.clock() if ev is not None else 0.0,
                         tokens=n_emitted)
        return n_emitted

    def _harvest_budget_chain(self, chain_out, ev, pf_n, dec_rows,
                              drafts, dlen, full_logits):
        """Spec budget harvest, shared by the row-aligned and flat
        dispatches: block-only (accepted drafts already make the step
        multi-token); acceptance/rollback on host, as in the legacy
        verify step. ``chain_out`` maps each packed slot to ITS
        segment's outputs — argmax chain [seg] or penalized logits
        [seg, V] — so the two layouts' different block shapes never
        leak into the acceptance logic. Returns tokens emitted."""
        from .spec_decode import (filtered_probs, greedy_accept,
                                  rejection_sample, truncate_emitted)
        tele = self.telemetry
        now = self.clock()
        pc = self._prefix_cache_for_dispatch()
        n_emitted = 0
        new_rows, new_cols = [], []
        # FCFS (rid) order, exactly the packer's: publication order
        # into the bounded prefix store is part of its eviction state
        pf_order = sorted((s for s in range(self.num_slots) if pf_n[s]),
                          key=lambda s: self._slot_req[s].rid)
        for s in pf_order:
            n = int(pf_n[s])
            req = self._slot_req[s]
            self._pf_left[s] -= n
            self._lens[s] += n
            tele.req_event(req.rid, "prefill_chunk", now)
            if self._pf_left[s] > 0:
                continue
            # prompt complete: commit-on-prefill publication, then the
            # first token (TTFT is measured to exactly this event)
            if pc is not None:
                if self.paged:
                    pc.publish_from(self._tables, s, req.prompt)
                else:
                    pc.publish(self._caches, s, req.prompt)
            arr = chain_out[s]
            if full_logits:
                p = filtered_probs(arr[-1][None], self.top_k,
                                   self.top_p, self.temperature)
                tok0 = int(self._get_spec_rng().choice(p.shape[-1],
                                                       p=p[0]))
            else:
                tok0 = int(arr[-1])                   # greedy chain
            req.t_first = now
            tele.req_event(req.rid, "first_token", now)
            req.tokens.append(tok0)
            self._class_tokens[req.priority] += 1
            self._nt[s] = 1
            self._tok[s] = tok0
            self._decode_steps += 1      # one sample event for the row
            n_emitted += 1
            if self._drafters is not None:
                self._drafters[s].update([tok0])
            if self._rep_on:
                new_rows.append(s)
                new_cols.append(tok0)
            hit_eos = (req.eos_token_id is not None
                       and tok0 == int(req.eos_token_id))
            self._active[s] = not hit_eos and req.max_new_tokens > 1
            if not self._active[s]:
                self._finish(req, now)
        for s in dec_rows:
            req = self._slot_req[s]
            if req is None or not self._active[s]:
                continue
            m = int(dlen[s])
            arr = chain_out[s]
            if full_logits:
                probs = filtered_probs(arr[:m + 1], self.top_k,
                                       self.top_p, self.temperature)
                kept, _ = rejection_sample(drafts[s, :m], probs,
                                           self._get_spec_rng())
            else:
                kept, _ = greedy_accept(drafts[s, :m], arr[:m + 1])
            eos = None if self._eos[s] < 0 else int(self._eos[s])
            emitted, hit_eos = truncate_emitted(
                kept, int(self._max_nt[s] - self._nt[s]), eos)
            self._nt[s] += len(emitted)
            req.tokens.extend(emitted)
            self._class_tokens[req.priority] += len(emitted)
            n_emitted += len(emitted)
            self._lens[s] += len(emitted)
            self._tok[s] = emitted[-1]
            self._decode_steps += 1
            self._draft_proposed += m
            self._draft_accepted += len(emitted) - 1
            tele.req_event(req.rid, "verify", now)
            if self._drafters is not None:
                self._drafters[s].update(emitted)
            if self._rep_on:
                new_rows.extend([s] * len(emitted))
                new_cols.extend(emitted)
            if hit_eos or self._nt[s] >= self._max_nt[s]:
                self._active[s] = False
                self._finish(req, now)
        if self._rep_on and new_rows:
            # the budget core's speculative presence was discarded —
            # only tokens that actually landed join the carry
            self._presence = self._presence.at[
                jnp.asarray(new_rows), jnp.asarray(new_cols)].set(True)
        tele.finish_step(ev, self.clock() if ev is not None else 0.0,
                         tokens=n_emitted)
        return n_emitted

    def _flat_budget_step(self):
        """ONE token-FLATTENED budget dispatch (the Sarathi
        token-flattened batch, PADDLE_SERVING_FLAT_BUDGET): instead of
        the [B, C] row-aligned block, the packer emits ONE ragged [T]
        stream — a B-wide DECODE REGION (token i is slot i's input when
        it decodes draft-free; idle slots ride the sentinel) followed
        by SEGMENTS (spec claims, prefill chunks) packed back-to-back
        with starts aligned to the flat kernel's chunk size, total
        segment width from an eighth-octave ladder. Per-token
        (slot, pos) index
        vectors drive the compiled flat core
        (generation._build_flat_budget_core); a prefill segment can
        span the whole spare budget (no C cap), so long prompts stream
        budget-sized chunks and budget_padding_tokens stays ~0 where
        the row layout computed (B-1) x C masked positions. All stream
        layout is DATA — only the ladder width is trace structure, so
        churn retraces nothing once the ladder is warm. Token outputs
        are EXACTLY the row dispatch's (shared harvests, shared
        sampling keyed fold_in(seed, nt)). Returns tokens emitted."""
        from ..ops.pallas.decode_attention import FLAT_CHUNK
        from .spec_decode import propose_claims
        b = self.num_slots
        dec_rows = [s for s in range(b) if self._active[s]]
        pf_rows = [s for s in range(b) if self._pf_left[s] > 0]
        if not dec_rows and not pf_rows:
            return 0
        k = self.spec_k
        if k:
            drafts, dlen = propose_claims(self._drafters, dec_rows, k,
                                          self._max_nt - self._nt)
        else:
            drafts = np.zeros((b, 1), np.int32)
            dlen = np.zeros(b, np.int32)
        if not pf_rows and len(dec_rows) + int(dlen.sum()) < \
                len(dec_rows) * self.decode_chunk:
            # same budget arithmetic as the row dispatch: pure-decode
            # steps run whichever warm executable moves more tokens
            return self._decode_one_chunk()
        # ---- pack: decode inputs are mandatory; prefill chunks (FCFS,
        # uncapped by any column count) fill spare capacity FIRST and
        # drafts claim what is left — the row packer's priority order,
        # so saturated decoders with fat drafts can never starve a
        # pending prefill (TTFT) in flat mode either
        budget = self.token_budget - len(dec_rows)
        segs = []                    # [slot, tokens, is_decode_claim]
        pf_n = np.zeros(b, np.int64)
        if pf_rows:
            # weighted-fair packing, same allocator as the row path
            # (FCFS within a class; single-class == old pure FCFS) —
            # no column cap, so a segment can span the whole share
            allocs, budget = self._prefill_allocations(pf_rows, budget)
            for s, n in allocs:
                req = self._slot_req[s]
                p0 = req.prompt.size - int(self._pf_left[s])
                segs.append([s, req.prompt[p0:p0 + n].astype(np.int32),
                             False])
                pf_n[s] = n
        if k:
            for s in dec_rows:
                m = min(int(dlen[s]), budget)
                dlen[s] = m
                if m > 0:
                    segs.append([s, np.concatenate(
                        ([self._tok[s]], drafts[s, :m])).astype(
                        np.int32), True])
                    budget -= m
        regd = [s for s in dec_rows if not (k and dlen[s] > 0)]
        # ---- layout: segment starts aligned to FLAT_CHUNK (the flat
        # kernel's single-slot query-chunk contract), total segment
        # width from an EIGHTH-OCTAVE ladder: round up to the next
        # multiple of next_pow2(need)/8 — ladder tail <= ~12% of the
        # stream (a plain pow-2 ladder wasted up to 2x on long prompt
        # chunks, re-creating a chunk of the row padding this layout
        # exists to kill) at <= 8 widths per octave, all bounded by
        # the token budget; the width is the ONLY trace structure
        align = FLAT_CHUNK
        starts = []
        cursor = 0
        for e in segs:
            starts.append(cursor)
            cursor = -(-(cursor + len(e[1])) // align) * align
        if segs:
            need = max(cursor, align)
            step = max((1 << (need - 1).bit_length()) // 8, align)
            ts = -(-need // step) * step
        else:
            ts = 0
        t_total = b + ts
        nc = ts // align
        toks = np.zeros(t_total, np.int32)
        tslot = np.full(t_total, b, np.int32)       # b == pad sentinel
        tpos = np.zeros(t_total, np.int32)
        tcol = np.zeros(t_total, np.int32)
        tstart = np.zeros(t_total, np.int32)
        cslot = np.zeros(nc, np.int32)
        cbase = np.zeros(nc, np.int32)
        cn = np.zeros(nc, np.int32)
        last_idx = np.zeros(b, np.int32)
        emit0 = np.zeros(b, bool)
        adv = np.zeros(b, np.int32)
        gen0 = np.zeros(b, np.int32)
        for s in regd:
            toks[s] = self._tok[s]
            tslot[s] = s
            tpos[s] = self._lens[s]
            tstart[s] = s
            last_idx[s] = s
            emit0[s] = True
            adv[s] = 1
        for e, st in zip(segs, starts):
            s, tk, is_dec = e
            n = len(tk)
            sl = slice(b + st, b + st + n)
            base = int(self._lens[s])
            toks[sl] = tk
            tslot[sl] = s
            tpos[sl] = base + np.arange(n)
            tcol[sl] = np.arange(n)
            tstart[sl] = b + st
            last_idx[s] = b + st + n - 1
            adv[s] = n
            if is_dec:
                emit0[s] = True
            else:
                fin = pf_n[s] == self._pf_left[s]
                emit0[s] = bool(fin)
                # the last prompt token's logits sample the request's
                # FIRST generated token; mid-prompt chunks never emit
                gen0[s] = n - 1 if fin else (1 << 30)
            for ci in range(st // align, (st + n - 1) // align + 1):
                cslot[ci] = s
                cbase[ci] = base + (ci * align - st)
                cn[ci] = min(n - (ci * align - st), align)
        used = len(regd) + sum(len(e[1]) for e in segs)
        computed = t_total
        tail = 0 if k else max(self.decode_chunk - 1, 0)
        if self.paged:
            # cover every packed slot's write window before dispatch
            # (lazy mapping + the COW guard), clamped to the
            # admission-time reservation — same rule as the row path
            for s in range(b):
                if not adv[s]:
                    continue
                decodes = bool(self._active[s]) or \
                    (pf_n[s] and pf_n[s] == self._pf_left[s])
                hi = (int(self._lens[s]) + int(adv[s])
                      + (tail if decodes else 0))
                req = self._slot_req[s]
                cap_pos = req.prompt.size + int(self._max_nt[s])
                self._ensure_writable(s, int(self._lens[s]),
                                      min(hi, cap_pos))
        stk = self.dec._stacked()
        e_arrays = [p._data for p in self.dec._embed_params]
        h_arrays = self.dec._maybe_quant_head(
            [p._data for p in self.dec._head_params])
        full_logits = bool(self.do_sample and k)
        res, ev = self._run_dispatch(
            ("flat_budget", ts),
            lambda: self.dec._build_flat_budget_core(
                ts, b, self._rep_on, self.do_sample, self.top_k,
                self.top_p, self.temperature, full_logits=full_logits,
                chain=bool(k), scan_tail=tail),
            (3,),
            (stk, e_arrays, h_arrays, self._cache_arg(),
             jnp.asarray(toks), jnp.asarray(tslot), jnp.asarray(tpos),
             jnp.asarray(cslot), jnp.asarray(cbase), jnp.asarray(cn),
             jnp.asarray(tcol), jnp.asarray(tstart), jnp.asarray(gen0),
             jnp.asarray(self._tok), jnp.asarray(last_idx),
             jnp.asarray(emit0), jnp.asarray(adv),
             jnp.asarray(self._lens), jnp.asarray(self._nt),
             jnp.asarray(self._max_nt), jnp.asarray(self._eos),
             jnp.asarray(self._min_len), jnp.asarray(self._rep_pen),
             self._presence_arg(), jnp.asarray(self._rseed, jnp.int32)),
            rows=int((adv > 0).sum()),
            budget_used=used,
            budget_wasted=computed - used,
            drafts=int(dlen.sum()))
        self._keep_caches(res[0])
        self._budget_steps += 1
        self._budget_tokens_used += used
        self._budget_prefill_tokens += int(pf_n.sum())
        self._budget_decode_tokens += len(dec_rows)
        self._budget_draft_tokens += int(dlen.sum())
        self._budget_padding_tokens += computed - used
        if not k:
            return self._harvest_budget_plain(res, ev, pf_n, tail)
        out = np.asarray(res[1])
        if full_logits:
            out = out.astype(np.float32)
        chain_out = {s: out[s:s + 1] for s in regd}
        for e, st in zip(segs, starts):
            chain_out[e[0]] = out[b + st: b + st + len(e[1])]
        return self._harvest_budget_chain(chain_out, ev, pf_n, dec_rows,
                                          drafts, dlen, full_logits)

    def _decode_one_chunk(self):
        chunk = self.decode_chunk
        stk = self.dec._stacked()
        e_arrays = [p._data for p in self.dec._embed_params]
        h_arrays = self.dec._maybe_quant_head(
            [p._data for p in self.dec._head_params])
        if self.paged:
            # cover this chunk's write window before dispatch (lazy
            # mapping as lens grows + the COW guard for forked slots)
            for s in range(self.num_slots):
                if self._active[s]:
                    self._ensure_writable(
                        s, int(self._lens[s]),
                        min(int(self._lens[s]) + chunk,
                            self._budget_pos(s)))
        res, ev = self._run_dispatch(
            ("decode", chunk), self._build_decode_chunk, (3,),
            (stk, e_arrays, h_arrays, self._cache_arg(),
             jnp.asarray(self._tok), jnp.asarray(self._lens),
             jnp.asarray(self._active), jnp.asarray(self._nt),
             jnp.asarray(self._max_nt), jnp.asarray(self._eos),
             jnp.asarray(self._min_len), jnp.asarray(self._rep_pen),
             self._presence_arg(), jnp.asarray(self._rseed, jnp.int32)),
            rows=int(self._active.sum()))
        (out, tok, lens, active, nt, presence, (toks, emitted)) = res
        self._keep_caches(out)
        if self._rep_on:
            self._presence = presence
        toks = np.asarray(toks)                  # [chunk, B]
        emitted = np.asarray(emitted)            # [chunk, B] bool
        # np.array (not asarray): host slot state stays WRITABLE — jax
        # outputs view as read-only numpy
        self._tok = np.array(tok)
        self._lens = np.array(lens)
        self._nt = np.array(nt)
        still_active = np.array(active)

        n_emitted = 0
        now = self.clock()
        for s in range(self.num_slots):
            req = self._slot_req[s]
            if req is None or not self._active[s]:
                continue
            hits = emitted[:, s]
            req.tokens.extend(int(t) for t in toks[hits, s])
            self._class_tokens[req.priority] += int(hits.sum())
            if hits.any():
                self.telemetry.req_event(req.rid, "decode", now)
            if self._drafters is not None:
                # spec engines reach here through the thin-draft
                # fallback: the drafter context must track every
                # emitted token or later proposals go stale
                self._drafters[s].update(toks[hits, s])
            n_emitted += int(hits.sum())
            if not still_active[s]:
                self._finish(req, now)
        self._active = still_active
        self._decode_steps += n_emitted      # 1 row-step per token here
        self.telemetry.finish_step(
            ev, self.clock() if ev is not None else 0.0,
            tokens=n_emitted)
        return n_emitted

    def _spec_decode_step(self):
        """One speculative decode iteration over ALL slots: per-slot
        n-gram draft proposals ride into ONE compiled K+1-position
        verify step as pure data, and acceptance/rollback happen here
        on the returned logits — greedy exact-match (token-identical to
        the normal decode path) or rejection sampling with the
        bonus-token resample. A slot's cache_lens advances by
        accepted+1 only; rejected positions' K/V were write-masked or
        are overwritten before ever becoming attendable
        (write-then-attend at the advanced lens). Slots without a
        usable draft ship dlen == 0 and degrade to a normal one-token
        step inside the SAME executable — zero retraces across churn,
        counted by the usual trace spy."""
        from .spec_decode import (filtered_probs, greedy_accept,
                                  propose_claims, rejection_sample,
                                  truncate_emitted)
        k = self.spec_k
        b = self.num_slots
        stk = self.dec._stacked()
        e_arrays = [p._data for p in self.dec._embed_params]
        h_arrays = self.dec._maybe_quant_head(
            [p._data for p in self.dec._head_params])
        drafts, dlen = propose_claims(
            self._drafters, [s for s in range(b) if self._active[s]],
            k, self._max_nt - self._nt)
        if int(dlen.sum()) < self._spec_min_draft * self._active.sum():
            # thin-draft phase (cold contexts, non-repetitive spans):
            # the plain decode chunk emits decode_chunk tokens/row per
            # dispatch — cheaper than a near-empty verify step. Both
            # executables are warm, so the switch is pure scheduling.
            return self._decode_one_chunk()
        toks = np.zeros((b, k + 1), np.int32)
        toks[:, 0] = self._tok
        toks[:, 1:] = drafts
        if self.paged:
            # cover the verify block's write window [lens, lens+K]
            # before dispatch — accepted positions become attendable
            # next step, so every VALID draft write must land (an
            # unmapped entry would silently drop it)
            for s in range(self.num_slots):
                if self._active[s]:
                    self._ensure_writable(
                        s, int(self._lens[s]),
                        min(int(self._lens[s]) + k + 1,
                            self._budget_pos(s)))
        (caches_out, out), ev = self._run_dispatch(
            ("verify", k),
            lambda: self.dec._build_verify_core(
                k, self._rep_on, greedy_out=not self.do_sample),
            (3,),
            (stk, e_arrays, h_arrays, self._cache_arg(),
             jnp.asarray(toks), jnp.asarray(self._lens),
             jnp.asarray(dlen), jnp.asarray(self._active),
             jnp.asarray(self._nt), jnp.asarray(self._eos),
             jnp.asarray(self._min_len), jnp.asarray(self._rep_pen),
             self._presence_arg()),
            rows=int(self._active.sum()), drafts=int(dlen.sum()))
        self._keep_caches(caches_out)
        if self.do_sample:
            logits = np.asarray(out).astype(np.float32)  # [B, K+1, V]
            self._get_spec_rng()
        else:
            # greedy: the step returns just the [B, K+1] argmax chain —
            # the only thing exact-match acceptance reads
            argmax = np.asarray(out)
        n_emitted = 0
        now = self.clock()
        new_rows, new_cols = [], []
        for s in range(self.num_slots):
            req = self._slot_req[s]
            if req is None or not self._active[s]:
                continue
            m = int(dlen[s])
            if self.do_sample:
                probs = filtered_probs(logits[s, :m + 1], self.top_k,
                                       self.top_p, self.temperature)
                kept, _ = rejection_sample(drafts[s, :m], probs,
                                           self._spec_rng)
            else:
                kept, _ = greedy_accept(drafts[s, :m],
                                        argmax[s, :m + 1])
            eos = None if self._eos[s] < 0 else int(self._eos[s])
            emitted, hit_eos = truncate_emitted(
                kept, int(self._max_nt[s] - self._nt[s]), eos)
            self._nt[s] += len(emitted)
            req.tokens.extend(emitted)
            self._class_tokens[req.priority] += len(emitted)
            n_emitted += len(emitted)
            self._lens[s] += len(emitted)
            self._tok[s] = emitted[-1]
            # per-row accounting: 1 verify row-step emitted
            # len(emitted) tokens, len(emitted)-1 of them drafts —
            # tokens == steps + accepted reconciles by construction
            self._decode_steps += 1
            self._draft_proposed += m
            self._draft_accepted += len(emitted) - 1
            self.telemetry.req_event(req.rid, "verify", now)
            self._drafters[s].update(emitted)
            if self._rep_on:
                new_rows.extend([s] * len(emitted))
                new_cols.extend(emitted)
            if hit_eos or self._nt[s] >= self._max_nt[s]:
                self._active[s] = False
                self._finish(req, now)
        if self._rep_on and new_rows:
            # rollback is structural: the verify step's speculative
            # presence carry was DISCARDED — only accepted tokens join
            self._presence = self._presence.at[
                jnp.asarray(new_rows), jnp.asarray(new_cols)].set(True)
        self.telemetry.finish_step(
            ev, self.clock() if ev is not None else 0.0,
            tokens=n_emitted)
        return n_emitted

    def _expire_deadlines(self, now):
        """Evict every request past its deadline_s — queued requests are
        shed before they ever cost a prefill; RUNNING ones release their
        slot through the normal eviction machinery (_finish resets the
        slot bookkeeping; the cache row needs no zeroing)."""
        for q in self._queues.values():
            for req in [r for r in q
                        if r.deadline_s is not None
                        and now - r.t_submit > r.deadline_s]:
                q.remove(req)
                self._finish(req, now, expired=True)
        for s in range(self.num_slots):
            req = self._slot_req[s]
            if (req is not None and req.deadline_s is not None
                    and now - req.t_submit > req.deadline_s):
                self._finish(req, now, expired=True)
        # parked requests age too: the deadline clock never pauses in
        # the parking lot (park time is queue-attributed delay) — an
        # expired one is shed HERE, releasing its kv commitment exactly
        # once through the normal _finish path (slot is already None)
        for rid in [r for r, st in self._parked.items()
                    if st["deadline_s"] is not None
                    and now - self._req_index[r].t_submit
                    > st["deadline_s"]]:
            self._finish(self._req_index[rid], now, expired=True)

    def _finish(self, req, now, expired=False):
        req.state = "expired" if expired else "finished"
        req.t_done = now
        if expired:
            self._expired += 1
        else:
            self._finished += 1
            # queue-time vs service-time decomposition + SLO verdict:
            # queue = submit -> admitted (0 for forked clones), service
            # = admitted -> finished; the mean inter-token gap stands
            # in for the per-request ITL objective (tokens harvest in
            # batches — there are no per-token timestamps to p99 over)
            t_adm = req.t_admit if req.t_admit is not None else now
            queue_s = max(t_adm - req.t_submit, 0.0)
            service_s = max(now - t_adm, 0.0)
            n = len(req.tokens)
            itl_s = (max(req.t_done - req.t_first, 0.0) / (n - 1)
                     if n > 1 and req.t_first is not None else 0.0)
            verdict = self._slo.classify(queue_s, service_s, req.ttft_s,
                                         itl_s, req.latency_s)
            if verdict == "ok":
                self._slo_ok += 1
            elif verdict == "queue":
                self._slo_violated_queue += 1
                # per-class queue-violation attribution: the autoscaler
                # reads the HIGH-class series (scale on premium pain
                # only) and the gateway's shed logic reads the split
                self._slo_vq_class[req.priority] += 1
            else:
                self._slo_violated_service += 1
            # histogram observation happens HERE, not at the first
            # token: expired requests must stay out of the percentiles
            # (their "latency" is an eviction time), same contract the
            # old done-list scan enforced
            self.telemetry.observe_request(req.ttft_s, req.latency_s,
                                           queue_s, service_s)
        self.telemetry.req_done(req.rid, req.state, now)
        self.results[req.rid] = req.result()
        # bounded results (the telemetry ring size): a long-lived engine
        # must not leak one dict per finished request — totals live in
        # the window counters + the Prometheus lifetime base, recent
        # results stay retrievable
        while len(self.results) > self._results_cap:
            self.results.pop(next(iter(self.results)))
        # a tracked request's record outlives the cap until its reader
        # drains it (harvest_new_tokens done=True / release); untracked
        # requests drop from the index now — results keeps the bounded
        # record, exactly the old lifecycle
        if req.rid not in self._harvest:
            self._req_index.pop(req.rid, None)
        # a parked request finishing (deadline expiry) drops its host
        # copy; its blocks/reservation were already released at preempt
        self._parked.pop(req.rid, None)
        if self.paged:
            self._kv_committed -= self._blocks_needed(req.prompt.size,
                                                      req.max_new_tokens)
        s = req.slot
        if s is None:                # shed from the queue, never admitted
            return
        self._slot_req[s] = None
        self._active[s] = False
        self._pf_left[s] = 0             # a mid-prefill eviction stops
        if self.paged:
            # eviction frees the slot's block REFERENCES: blocks the
            # prefix store (or a fork twin) still holds stay resident,
            # everything else returns to the pool free list. The table
            # row resets to the sentinel, so the unmasked idle-row
            # rewrite at the frozen lens drops instead of landing.
            self._kv_reserved -= self._blocks_needed(req.prompt.size,
                                                     req.max_new_tokens)
            self._free_slot_blocks(s)
        # slot eviction IS this bookkeeping: the cache row is left as-is
        # (positions >= cache_lens are never attendable; the next
        # admission's masked prefill overwrites [0, plen) in place)

    # ------------------------------------------------------------ helpers
    def _prefill_chunks(self, maxp):
        """Prefill dispatch sizes for a prompt of length maxp: full
        `prefill_cap` chunks, then ONE chunk rounded UP to the next
        power of two (bounded variant set, like the decode ladder — but
        up, not down). One admission is one prefill dispatch for any
        prompt <= cap; the tail steps are write-masked no-ops. Serving
        is dispatch-bound at admission time: a 3-dispatch 4+2+1 ladder
        walk per admitted request measurably beat the masked tail's
        wasted compute on the serving bench."""
        out, pos = [], 0
        while pos < maxp:
            rem = maxp - pos
            c = (self.prefill_cap if rem >= self.prefill_cap
                 else 1 << (rem - 1).bit_length())
            out.append(c)
            pos += c
        return out

    def _presence_init(self):
        if self._presence is None:
            vocab = int(self.dec._head_params[0].shape[1])
            self._presence = jnp.zeros((self.num_slots, vocab), bool)
        return self._presence

    def _presence_arg(self):
        if not self._rep_on:
            # a [B, 1] placeholder keeps the compiled signature stable
            return jnp.zeros((self.num_slots, 1), bool)
        return self._presence_init()


def _kv_payload_bytes(blocks):
    """Wire size of a KV handoff payload: the kv tensors plus int8
    scales when present — what a cross-host transport would move."""
    total = 0
    for blk in blocks:
        total += int(blk["kv"].nbytes)
        if "sc" in blk:
            total += int(blk["sc"].nbytes)
    return total


def _penalize_slots(logits, presence, rep_pen, nt, min_len, eos_ids):
    """Vectorized-over-slots logit controls (reference: generation's
    logit processors, here with PER-SLOT parameters as data):
    repetition_penalty divides positive / multiplies negative logits of
    context tokens, per row (rows at 1.0 are exact no-ops); min_length
    suppresses each row's OWN eos column while that row has generated
    fewer than its min_length tokens. eos_ids < 0 means no eos."""
    if presence is not None:
        pen = rep_pen[:, None]
        logits = jnp.where(
            presence,
            jnp.where(logits > 0, logits / pen, logits * pen),
            logits)
    cols = jnp.arange(logits.shape[1])[None, :]
    is_eos = cols == eos_ids[:, None]
    suppress = is_eos & (nt < min_len)[:, None]
    return jnp.where(suppress, -1e30, logits)
