"""Speculative decoding: model-free n-gram drafting + acceptance math.

The drafter is prompt-lookup / n-gram speculation (Saxena, "Prompt
Lookup Decoding", 2023): the cheapest possible draft model is the
request's OWN context — summarization, RAG, code-edit and chatty
multi-turn workloads copy long spans of their input (or of their own
earlier output), so the continuation of the most recent earlier
occurrence of the current suffix n-gram is a strong K-token guess. It
is pure host data (a suffix map over prompt + generated tokens,
updated incrementally on accept), which makes it a perfect fit for the
zero-retrace serving engine: proposals ride into the ONE compiled
verify step as `[B, K]` arrays, and a slot with no usable draft simply
ships an all-masked draft (the step degrades to a normal decode step).

Acceptance is the standard speculative-sampling rule (Leviathan et
al., "Fast Inference from Transformers via Speculative Decoding",
2023), specialized for a DETERMINISTIC drafter (q is a point mass on
the drafted token):

  * greedy: exact-match — accept the longest draft prefix that equals
    the verify step's argmax chain, then emit the first disagreeing
    argmax as the bonus token (token-identical to spec-off greedy by
    construction);
  * sampled: accept draft d_j with probability min(1, p_j(d_j)); on
    the first rejection resample from the residual max(p - q, 0)
    renormalized (= p with d_j zeroed); if every draft survives, the
    bonus token samples from the last position's p. With a point-mass
    q the emitted marginal is EXACTLY p at every position — enabling
    speculation never changes the output distribution.

Host-side numpy throughout — acceptance/rollback is pure data over the
verify step's returned logits; nothing here traces.

Cache layouts: the verify step's K+1 write-masked K/V scatters ride
whatever cache the engine runs — the dense per-slot ring, or (default)
the paged block pool where each position resolves through the slot's
block table (paged_kv.py; masked positions target the sentinel block
and drop, the same `cache_lens < Smax` clamp discipline either way).
"""
from __future__ import annotations

import numpy as np

__all__ = ["NGramDrafter", "greedy_accept", "rejection_sample",
           "filtered_probs", "truncate_emitted", "validate_spec_k",
           "propose_claims"]


def propose_claims(drafters, rows, k, remaining, col_cap=None):
    """ONE owner for the serving schedulers' draft-claim proposal (the
    row-aligned budget packer, the FLAT budget packer, and the legacy
    phase verify step all capped drafts with hand-copied arithmetic):
    for each slot in ``rows``, propose up to ``k`` draft tokens and cap
    the claim at the row's remaining generation budget MINUS ONE (the
    bonus token always ships, so at most remaining-1 drafts are useful
    — this is also what keeps every landed draft write under the
    submit-time ``prompt + max_new <= Smax`` bound) and, when
    ``col_cap`` is given, at the dispatch's per-row column capacity.

    drafters: per-slot NGramDrafter list; rows: slot ids to draft for;
    remaining: [B] ints (max_new_tokens - nt per slot). Returns
    (drafts [B, max(k, 1)] int32, dlen [B] int32)."""
    b = len(drafters)
    drafts = np.zeros((b, max(int(k), 1)), np.int32)
    dlen = np.zeros(b, np.int32)
    if not k:
        return drafts, dlen
    for s in rows:
        d = drafters[s].propose()
        m = min(int(d.size), int(remaining[s]) - 1)
        if col_cap is not None:
            m = min(m, int(col_cap) - 1)
        if m > 0:
            drafts[s, :m] = d[:m]
            dlen[s] = m
    return drafts, dlen


def truncate_emitted(kept, remaining, eos):
    """Apply a row's emission limits to an accepted-token chain: stop
    at the row's eos or after `remaining` tokens (its max_new budget).
    Returns (emitted, hit_eos). ONE owner for the truncation contract —
    the serving engine and the oneshot generate() drive both walk
    accepted tokens through this, so greedy on/off parity and the
    `tokens == decode_steps + draft_accepted` reconciliation cannot
    drift between them."""
    emitted = []
    hit_eos = False
    for t in kept:
        emitted.append(int(t))
        if eos is not None and int(t) == eos:
            hit_eos = True
            break
        if len(emitted) >= remaining:
            break
    return emitted, hit_eos


def validate_spec_k(k):
    """K is static trace structure (the verify step runs K+1 positions),
    so it is validated like `prefill_cap`: a power of two keeps the
    compiled-executable set bounded and predictable. 0 disables."""
    k = int(k)
    if k < 0 or (k and k & (k - 1)):
        raise ValueError(
            f"spec_k must be 0 (disabled) or a power of two, got {k} "
            "(K is baked into the ONE compiled verify step — the pow-2 "
            "rule keeps the executable set bounded, like prefill_cap)")
    return k


class NGramDrafter:
    """Suffix map over one request's context (prompt + generated).

    `maps[n]` stores, for every n-gram in the context THAT HAS a
    continuation, the start index of its most recent occurrence — an
    n-gram ending at position i-1 is inserted when token i lands, so a
    lookup can never match the context's own tail (which has nothing
    after it to propose). propose() scans n from `max_ngram` down to
    `min_ngram` (longest-match-first, the standard prompt-lookup order)
    and returns up to K continuation tokens of the first hit; no match
    returns an empty proposal. update() appends accepted tokens and
    extends the maps incrementally — O(accepted * ngrams) per step,
    never a rescan."""

    def __init__(self, k, max_ngram=3, min_ngram=1):
        self.k = int(k)
        if self.k < 1:
            raise ValueError(f"NGramDrafter needs k >= 1, got {k}")
        self.max_ngram = int(max_ngram)
        self.min_ngram = int(min_ngram)
        if not 1 <= self.min_ngram <= self.max_ngram:
            raise ValueError(
                f"need 1 <= min_ngram <= max_ngram, got "
                f"{min_ngram}..{max_ngram}")
        self._toks = []
        self._maps = {}
        # telemetry: drafter-level lookup effectiveness (how often
        # propose() had ANYTHING to offer — upstream of the engine's
        # acceptance_rate, which only sees proposals that shipped).
        # Per-drafter lifetime counters: reset() starts a new CONTEXT,
        # not a new measurement window, so they survive re-admission.
        self.propose_calls = 0
        self.propose_hits = 0
        self.reset(())

    def reset(self, prompt):
        """Start a fresh context (slot re-admission): rebuild the suffix
        map over the new prompt."""
        self._toks = []
        self._maps = {n: {} for n in
                      range(self.min_ngram, self.max_ngram + 1)}
        self.update(prompt)

    def update(self, accepted):
        """Append accepted tokens; every n-gram that just GAINED a
        continuation (it ends right before a newly landed token) is
        (re-)indexed at its start."""
        toks = self._toks
        for t in accepted:
            i = len(toks)              # index the new token will take
            toks.append(int(t))
            for n, m in self._maps.items():
                j = i - n              # n-gram ending at i-1
                if j >= 0:
                    m[tuple(toks[j:i])] = j

    def propose(self):
        """Up to K draft tokens continuing the most recent earlier
        occurrence of the context's suffix; empty when no n-gram
        matches (the caller ships an all-masked draft)."""
        toks = self._toks
        length = len(toks)
        self.propose_calls += 1
        for n in range(self.max_ngram, self.min_ngram - 1, -1):
            if length < n:
                continue
            j = self._maps[n].get(tuple(toks[-n:]))
            if j is None:
                continue
            # j + n < length by construction (only n-grams with a
            # continuation are indexed), so there is >= 1 draft token
            self.propose_hits += 1
            return np.asarray(toks[j + n: j + n + self.k], np.int32)
        return np.zeros((0,), np.int32)

    @property
    def context_len(self):
        return len(self._toks)


def greedy_accept(draft, greedy_tokens):
    """Greedy exact-match acceptance. draft: [m] proposed tokens;
    greedy_tokens: [>= m+1] the verify step's argmax at positions
    0..m (position j's argmax is the model's token AFTER consuming
    draft tokens 1..j). Returns (tokens_out, n_accepted): the accepted
    draft prefix plus the first disagreeing argmax as the bonus token —
    exactly the chain sequential greedy decode would emit."""
    draft = np.asarray(draft)
    a = 0
    while a < draft.size and int(draft[a]) == int(greedy_tokens[a]):
        a += 1
    return [int(t) for t in draft[:a]] + [int(greedy_tokens[a])], a


def filtered_probs(logits, top_k=0, top_p=1.0, temperature=1.0):
    """Numpy mirror of generation._filter_logits + softmax: temperature
    scale, top-k floor, nucleus cutoff — the target distributions p_j
    the rejection sampler accepts against. logits: [P, V] -> [P, V]
    float64 probabilities (rows sum to 1)."""
    lg = np.asarray(logits, np.float64) / max(float(temperature), 1e-6)
    if top_k and top_k > 0:
        kth = np.sort(lg, axis=-1)[:, -top_k][:, None]
        lg = np.where(lg < kth, -1e30, lg)
    if top_p and top_p < 1.0:
        srt = np.sort(lg, axis=-1)[:, ::-1]
        e = np.exp(srt - srt.max(-1, keepdims=True))
        cum = np.cumsum(e / e.sum(-1, keepdims=True), axis=-1)
        cutoff_idx = np.sum(cum < top_p, axis=-1, keepdims=True)
        kth = np.take_along_axis(srt, cutoff_idx, axis=-1)
        lg = np.where(lg < kth, -1e30, lg)
    e = np.exp(lg - lg.max(-1, keepdims=True))
    return e / e.sum(-1, keepdims=True)


def rejection_sample(draft, probs, rng):
    """Speculative rejection sampling for a point-mass drafter. draft:
    [m] proposed tokens; probs: [m+1, V] target distributions (position
    j's p is conditioned on the draft tokens before it); rng: a
    np.random.RandomState. Returns (tokens_out, n_accepted).

    Accept d_j w.p. p_j(d_j); first rejection resamples from the
    residual (p_j with d_j zeroed, renormalized — max(p - q, 0) for a
    point-mass q) and stops; all-accepted samples the bonus token from
    p_m. Emitted marginal per position is exactly p_j: accept
    contributes p(d) at d, reject contributes (1-p(d)) * p(x)/(1-p(d))
    everywhere else."""
    probs = np.asarray(probs, np.float64)
    out = []
    for j, d in enumerate(np.asarray(draft, np.int64)):
        p = probs[j]
        if rng.uniform() < p[d]:
            out.append(int(d))
            continue
        r = p.copy()
        r[d] = 0.0
        s = float(r.sum())
        if s <= 0.0:
            # p IS the point mass on d (filtered to one token): the
            # accept branch has probability 1 up to float round-off
            out.append(int(d))
        else:
            out.append(int(rng.choice(r.size, p=r / s)))
        return out, j
    m = len(out)
    p = probs[m]
    out.append(int(rng.choice(p.size, p=p / p.sum())))
    return out, m
