"""paddle.inference — predictor API over jitted forward functions.

Capability parity: paddle/fluid/inference/api/analysis_predictor.cc ::
AnalysisPredictor + paddle_inference_api.h (Config, create_predictor,
input/output handles with copy_from_cpu/copy_to_cpu).

TPU-native design: the reference loads a serialized ProgramDesc, runs an IR
pass pipeline (fusion passes, TensorRT subgraph carve-out), and interprets
the optimized program. Here the "optimized program" IS the XLA executable:
jit.load restores the params, the model's forward is traced once per input
shape and compiled by XLA (which performs the same class of fusions the
reference's pass pipeline hand-codes — fc_fuse, multihead_matmul_fuse — and
targets the MXU), with optional bf16 weight conversion standing in for the
reference's half-precision inference config. Batch-shape bucketing replaces
TensorRT dynamic-shape profiles.
"""
from __future__ import annotations

import os
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["Config", "Predictor", "create_predictor", "PlaceType",
           "PrecisionType", "ServingEngine", "ServedRequest",
           "AdmissionFull", "PrefixCache", "PrefixStore", "NGramDrafter",
           "BlockPool", "PagedPrefixCache", "PagedPrefixStore",
           "Telemetry", "LogHistogram", "export_chrome_tracing",
           "parse_prometheus"]


def __getattr__(name):
    # lazy: the serving engine drags the nn layer stack in via
    # generation.py; importing paddle_tpu.inference must stay light
    if name in ("ServingEngine", "ServedRequest", "AdmissionFull"):
        from . import serving
        return getattr(serving, name)
    if name in ("Telemetry", "LogHistogram", "export_chrome_tracing",
                "parse_prometheus"):
        from . import telemetry
        return getattr(telemetry, name)
    if name in ("PrefixCache", "PrefixStore"):
        from . import prefix_cache
        return getattr(prefix_cache, name)
    if name in ("BlockPool", "PagedPrefixCache", "PagedPrefixStore"):
        from . import paged_kv
        return getattr(paged_kv, name)
    if name == "NGramDrafter":
        from . import spec_decode
        return spec_decode.NGramDrafter
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


class PrecisionType:
    Float32 = "float32"
    Half = "float16"
    Bfloat16 = "bfloat16"
    Int8 = "int8"


class PlaceType:
    CPU = "cpu"
    GPU = "gpu"
    TPU = "tpu"
    XPU = "xpu"


class Config:
    """Parity: paddle_infer.Config — model path + device/precision knobs."""

    def __init__(self, prog_file: Optional[str] = None,
                 params_file: Optional[str] = None):
        # reference takes (model.pdmodel, model.pdiparams); ours takes the
        # jit.save path prefix in either slot
        self.model_path = (prog_file or params_file or "").replace(
            ".pdmodel", "").replace(".pdparams", "")
        self.precision = PrecisionType.Float32
        self.device = PlaceType.TPU
        self.device_id = 0
        self._model_obj = None
        self._memory_pool_mb = 0

    # --- reference API surface ---
    def set_model(self, prog_file: str, params_file: str = ""):
        self.model_path = prog_file.replace(".pdmodel", "")

    def set_model_obj(self, layer):
        """TPU extension: pass a live nn.Layer (reference AnalysisPredictor
        always loads from disk; we allow both)."""
        self._model_obj = layer

    def enable_use_gpu(self, memory_pool_mb=100, device_id=0):
        self.device = PlaceType.TPU        # gpu config maps to the TPU chip
        self.device_id = device_id
        self._memory_pool_mb = memory_pool_mb

    def enable_xpu(self, *a, **k):
        self.device = PlaceType.TPU

    def disable_gpu(self):
        self.device = PlaceType.CPU

    def enable_memory_optim(self, *a, **k):
        pass                               # XLA buffer assignment does this

    def switch_ir_optim(self, flag=True):
        pass                               # XLA fusion is always on

    def enable_tensorrt_engine(self, workspace_size=1 << 30, max_batch_size=1,
                               min_subgraph_size=3, precision_mode=None,
                               use_static=False, use_calib_mode=False):
        # TensorRT subgraphs ≙ XLA compilation (whole graph); keep precision
        if precision_mode is not None:
            self.precision = precision_mode

    def set_cpu_math_library_num_threads(self, n):
        pass

    def precision_mode(self):
        return self.precision

    def enable_bf16(self):
        self.precision = PrecisionType.Bfloat16


def _int8_twin(linear):
    """Weight-only int8 twin of an nn.Layer Linear (AnalysisPredictor's int8
    precision mode, realized TPU-style).

    Per-output-channel absmax quantization: qw = round(w / s), s =
    absmax(w[:, j]) / 127. The twin copies only qw/s/bias — it must NOT
    retain the original Linear, or the swapped-out fp32 weight stays alive
    in the persistent registry (WeakSet) for the predictor's lifetime."""
    w = np.asarray(linear.weight._data, np.float32)       # [in, out]
    s = np.abs(w).max(axis=0) / 127.0
    s = np.where(s == 0.0, 1.0, s).astype(np.float32)
    qw = np.clip(np.round(w / s), -127, 127).astype(np.int8)
    return _Int8Linear(qw, s, linear.bias)


_INT8_CLS = None


def _Int8Linear(qw, scale, bias):
    # class defined lazily: importing paddle_tpu.inference must not drag
    # the nn layer stack in eagerly
    global _INT8_CLS
    if _INT8_CLS is None:
        from ..nn.layer.layers import Layer
        from ..tensor.tensor import Parameter, apply_op

        class _Int8LinearImpl(Layer):
            """matmul(x, qw.astype(x)) * s: the column scale commutes out
            of the contraction, so the int8→bf16 convert fuses into the
            dot's operand read and weight HBM traffic halves vs bf16."""

            def __init__(self, qw, scale, bias):
                super().__init__()
                self.qweight = Parameter(jnp.asarray(qw), trainable=False)
                self.w_scale = Parameter(jnp.asarray(scale), trainable=False)
                self.bias = bias

            def forward(self, x):
                def f(a, q, sc, *b):
                    y = jnp.matmul(a, q.astype(a.dtype)) * sc.astype(a.dtype)
                    if b:
                        y = y + b[0].astype(y.dtype)
                    return y
                args = [x, self.qweight, self.w_scale]
                if self.bias is not None:
                    args.append(self.bias)
                return apply_op(f, *args)

        _INT8_CLS = _Int8LinearImpl
    return _INT8_CLS(qw, scale, bias)


def _quantize_int8(model):
    """Swap every nn.Linear sublayer for its weight-only-int8 twin."""
    from ..nn.layer.common import Linear
    swapped = 0
    for layer in [model] + list(model.sublayers()):
        for name, sub in list(getattr(layer, "_sub_layers", {}).items()):
            if type(sub) is Linear:
                setattr(layer, name, _int8_twin(sub))
                swapped += 1
    return swapped


class _IOHandle:
    """Parity: paddle_infer.Tensor (input/output handle)."""

    def __init__(self, name: str):
        self.name = name
        self._arr: Optional[np.ndarray] = None

    def reshape(self, shape):
        pass                               # shapes come from copy_from_cpu

    def copy_from_cpu(self, arr: np.ndarray):
        self._arr = np.asarray(arr)

    def copy_to_cpu(self) -> np.ndarray:
        return np.asarray(self._arr)

    def shape(self):
        return list(self._arr.shape) if self._arr is not None else []


class Predictor:
    """Parity: AnalysisPredictor — handle-based run loop.

    run() jits the model forward per input-shape bucket; repeated calls with
    the same shapes reuse the compiled executable (the analogue of the
    reference's warmed-up predictor).
    """

    def __init__(self, config: Config):
        self.config = config
        self._model = config._model_obj
        if self._model is None and config.model_path:
            from ..jit import load as jit_load
            self._model = jit_load(config.model_path)
        if self._model is None:
            raise ValueError("Config has neither a model path nor object")
        if config.precision == PrecisionType.Bfloat16 and \
                hasattr(self._model, "bfloat16"):
            self._model.bfloat16()
        elif config.precision == PrecisionType.Int8:
            import warnings
            from ..nn.layer.layers import Layer
            if isinstance(self._model, Layer):
                n = _quantize_int8(self._model)
                if n == 0:
                    warnings.warn("int8 precision requested but the model "
                                  "has no nn.Linear sublayers to quantize")
            else:
                warnings.warn(
                    "int8 precision requires a live nn.Layer "
                    "(Config.set_model_obj); a path-loaded model bundle "
                    "runs at full precision")
        self._inputs: dict[str, _IOHandle] = {}
        self._outputs: dict[str, _IOHandle] = {}
        self._input_names: list[str] = ["x"]
        self._output_names: list[str] = ["out"]
        self._compiled: dict[tuple, Callable] = {}

    # --- handles ---
    def get_input_names(self):
        return list(self._input_names)

    def get_output_names(self):
        return list(self._output_names)

    def get_input_handle(self, name: str) -> _IOHandle:
        if name not in self._inputs:
            self._inputs[name] = _IOHandle(name)
            if name not in self._input_names:
                self._input_names.append(name)
        return self._inputs[name]

    def get_output_handle(self, name: str) -> _IOHandle:
        return self._outputs.setdefault(name, _IOHandle(name))

    # --- execution ---
    def _forward_fn(self):
        from ..nn.layer.layers import Layer
        m = self._model
        if isinstance(m, Layer):
            m.eval()
            return lambda *xs: m(*xs)
        return m                            # TranslatedLayer / callable

    def _compiled_forward(self, arrs):
        """Jit the forward per input-shape/dtype bucket; repeated runs with
        the same shapes reuse the compiled executable. Model params are
        passed as jit arguments (not baked as constants) so re-loading
        weights into the same Layer keeps the cache valid."""
        import jax
        from ..nn.layer.layers import Layer, substitute_param_arrays
        from ..tensor.tensor import Tensor, no_grad, _tape

        key = tuple((tuple(a.shape), str(a.dtype)) for a in arrs)
        entry = self._compiled.get(key)
        if entry is None:
            forward = self._forward_fn()
            m = self._model
            params = list(m.parameters()) if isinstance(m, Layer) else []

            def pure(param_arrays, input_arrays):
                try:
                    with substitute_param_arrays(params, param_arrays), \
                            no_grad():
                        outs = forward(*[Tensor(a) for a in input_arrays])
                finally:
                    _tape.nodes.clear()
                if not isinstance(outs, (list, tuple)):
                    outs = [outs]
                return [o._data if isinstance(o, Tensor) else jnp.asarray(o)
                        for o in outs]

            entry = (jax.jit(pure), params)
            self._compiled[key] = entry
        jitted, params = entry
        return jitted([p._data for p in params],
                      [jnp.asarray(a) for a in arrs])

    def run(self, inputs: Optional[list] = None):
        """Either handle-style (copy_from_cpu then run()) or direct
        (run([np arrays]) -> list of np arrays, the paddle_infer v2 API)."""
        if inputs is not None:
            arrs = [np.asarray(a) for a in inputs]
        else:
            arrs = [self._inputs[n].copy_to_cpu()
                    for n in self._input_names if n in self._inputs]
        outs = self._compiled_forward(arrs)
        np_outs = [np.asarray(o) for o in outs]
        self._output_names = [f"out_{i}" if len(np_outs) > 1 else "out"
                              for i in range(len(np_outs))]
        for n, a in zip(self._output_names, np_outs):
            self.get_output_handle(n).copy_from_cpu(a)
        return np_outs if inputs is not None else None

    def try_shrink_memory(self):
        pass

    def clear_intermediate_tensor(self):
        pass


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)
