"""Automatic prefix caching over the stacked KV ring cache.

Capability parity: vLLM's PagedAttention block reuse / SGLang's
RadixAttention, realized against this repo's stacked fixed-shape cache
[L, 2, B, H, Smax, D]: a cached prefix is pure DATA — its K/V blocks are
splatted into a slot's cache row by one compiled gather-copy instead of
being recomputed by the prefill stack. Two halves:

  * ``PrefixStore`` — a host-side radix tree over CHUNK-ALIGNED token
    spans (chunk = the serving engine's ``prefill_cap``, so the prefill
    ladder and the prefix-block ladder are tuned from one knob). Each
    node owns exactly one device pool block and is keyed by that block's
    exact token tuple under its parent (dict hashing of the tuple IS the
    token-hash key, with exact-match verification for free — no
    collision hazard). Ref-counting pins a chain while a copy is in
    flight; eviction is LRU over refcount-0 LEAVES only (an inner node's
    children are reachable only through it, so evicting a parent first
    would leak its subtree).
  * ``PrefixCache`` — the store plus a DEVICE block pool
    [L, 2, NB, H, Bt, D] (mirrored int8+scales when the engine runs the
    int8 KV cache) and the two compiled copy paths:

      - ``adopt``: the longest matched chain is gathered by block index
        and scattered into the target slot's cache row in ONE compiled
        dispatch. The executable is fixed-shape over a pow-2 ladder of
        chain lengths (same bounded-variant discipline as the prefill
        ladder) with the tail write-masked exactly like in-slot prefill:
        invalid ladder positions are sent out of bounds and dropped
        (``mode="drop"``), so a neighbouring slot's live row is
        untouchable by construction and every landed write stays at a
        position < plen <= Smax - max_new — inside the
        ``cache_lens < Smax`` clamp the decode_attention write kernels
        document.
      - ``commit``: as a slot's prefill lands, each FULL block of its
        prompt is copied out of the slot row into a free pool block and
        published under its token key. Copy-on-write is structural: the
        pool is separate storage, a slot only ever copies IN at
        admission and OUT at commit — decode continues into slot-private
        rows and can never mutate a published block.

Both copy paths are plain XLA gathers/scatters (no new kernels): the
blocks move HBM->HBM once per admission, which is orders of magnitude
cheaper than re-running the L-layer prefill stack over the same tokens.

PAGED twin: under the serving engine's default paged KV cache
(paged_kv.py), this module's radix machinery is reused by
``PagedPrefixStore``/``PagedPrefixCache`` against the ONE shared
``BlockPool`` — adopt becomes writing the chain's pool indices into
the slot's block table and commit becomes referencing the slot's own
blocks, so a hit costs zero device copies. The dense ``PrefixCache``
here remains the cross-engine-shareable flavor (oneshot
``generate(prefix_cache=...)`` uses it) and the layout the engine
falls back to with ``PADDLE_SERVING_PAGED=0``.
"""
from __future__ import annotations

import numpy as np

__all__ = ["PrefixStore", "PrefixCache", "PrefixNode",
           "lookup_adoptable"]


def lookup_adoptable(store, block_tokens, tokens):
    """Longest ADOPTABLE chain for a prompt — ONE owner for the cap +
    counter rules shared by the dense PrefixCache and the paged twin
    (paged_kv.PagedPrefixCache): the raw radix match is capped so at
    least one prompt token always goes through real prefill (the
    first-token sample needs the last prompt token's hidden state,
    which only prefill produces — a fully-cached prompt drops its
    final block; vLLM does the same), and the hit/miss counters bump
    HERE, post-cap, so store- and engine-level hit rates can never
    disagree."""
    t = np.asarray(tokens).reshape(-1)
    nodes = store.match(t)
    nodes = nodes[:(t.size - 1) // block_tokens]
    if nodes:
        store.match_hits += 1
    else:
        store.match_misses += 1
    return nodes


class PrefixNode:
    """One radix-tree node == one published KV block. ``tokens`` is the
    block's exact token tuple (the edge label from ``parent``); ``block``
    is its device pool index."""

    __slots__ = ("tokens", "parent", "children", "block", "refcount",
                 "last_use")

    def __init__(self, tokens, parent, block):
        self.tokens = tokens
        self.parent = parent
        self.children = {}               # token tuple -> PrefixNode
        self.block = block
        self.refcount = 0
        self.last_use = 0

    def __repr__(self):                  # debugging aid only
        return (f"PrefixNode(block={self.block}, ref={self.refcount}, "
                f"children={len(self.children)})")


class PrefixStore:
    """Host-side radix store over fixed-size token blocks with a capacity
    budget in KV blocks, ref-counting, and LRU leaf eviction. Pure host
    bookkeeping — no device arrays — so it unit-tests without jax."""

    def __init__(self, num_blocks, block_tokens):
        self.num_blocks = int(num_blocks)
        self.block_tokens = int(block_tokens)
        if self.num_blocks < 1:
            raise ValueError("num_blocks must be >= 1")
        if self.block_tokens < 1:
            raise ValueError("block_tokens must be >= 1")
        self._root = PrefixNode((), None, -1)
        self._free = list(range(self.num_blocks))
        # refcount-0 LEAVES, maintained at every transition (create /
        # link / acquire / release / evict): eviction picks min-last_use
        # from this set instead of walking the whole tree — allocation
        # under a full pool is O(evictable), not O(nodes x chain), on
        # the TTFT-critical admission path
        self._evictable = set()
        self._tick = 0
        # counters (raw store level; the serving engine keeps its own
        # per-admission hit/miss window counters)
        self.match_hits = 0
        self.match_misses = 0
        self.evictions = 0
        self.committed_blocks = 0

    # ------------------------------------------------------------- blocks
    def _blocks_of(self, tokens):
        """Full ``block_tokens``-sized tuples of ``tokens`` (the ragged
        tail never forms a block — it stays slot-private)."""
        t = np.asarray(tokens).reshape(-1)
        bt = self.block_tokens
        n = t.size // bt
        return [tuple(int(x) for x in t[i * bt:(i + 1) * bt])
                for i in range(n)]

    def _touch(self, node):
        self._tick += 1
        node.last_use = self._tick

    # -------------------------------------------------------------- match
    def match(self, tokens):
        """Longest chain of published blocks prefixing ``tokens``.
        Returns the node list root-first and bumps each node's LRU stamp
        (a matched chain is hot). Does NOT take refs — callers pin with
        acquire()/release() around the device copy. Hit/miss counters
        are bumped by PrefixCache.lookup() AFTER its final-block cap, so
        store- and engine-level hit counts can never disagree."""
        chain = []
        node = self._root
        for key in self._blocks_of(tokens):
            child = node.children.get(key)
            if child is None:
                break
            self._touch(child)
            chain.append(child)
            node = child
        return chain

    def _update_evictable(self, node):
        if node is self._root:
            return
        if node.children or node.refcount:
            self._evictable.discard(node)
        else:
            self._evictable.add(node)

    def acquire(self, nodes):
        for n in nodes:
            n.refcount += 1
            self._evictable.discard(n)

    def release(self, nodes):
        for n in nodes:
            if n.refcount <= 0:
                raise RuntimeError("prefix block refcount underflow")
            n.refcount -= 1
            self._update_evictable(n)

    # ------------------------------------------------------------ insert
    def insert(self, tokens):
        """Publish ``tokens``' full blocks: walk/extend the radix chain,
        allocating a pool block for every node that does not exist yet
        (evicting cold blocks if the budget is exhausted). Returns
        ``[(node, is_new), ...]`` root-first — the caller device-copies
        the K/V of every ``is_new`` block; existing nodes are dedup hits
        and need no copy. Publication stops early (prefix of the chain
        only) when no block can be allocated; partial chains are valid —
        matching is per-block."""
        out = []
        node = self._root
        try:
            for key in self._blocks_of(tokens):
                child = node.children.get(key)
                if child is None:
                    blk = self._alloc()
                    if blk is None:      # budget exhausted, nothing cold
                        break
                    child = PrefixNode(key, node, blk)
                    node.children[key] = child
                    self._update_evictable(node)   # parent: now inner
                    self.committed_blocks += 1
                    out.append((child, True))
                else:
                    out.append((child, False))
                self._touch(child)
                # pin the chain UNDER CONSTRUCTION: without this, a
                # chain longer than the free budget would evict its own
                # freshly-created tail (a refcount-0 leaf) to allocate
                # the next block, orphaning the subtree. acquire() (not
                # a raw refcount bump) so the pinned node also leaves
                # the evictable set — a dedup'd leaf left there would be
                # picked as the LRU victim and trip _evict's guard
                self.acquire((child,))
                node = child
        finally:
            self.release(n for n, _ in out)
        return out

    def _alloc(self):
        if self._free:
            return self._free.pop()
        victim = self._lru_evictable_leaf()
        if victim is None:
            return None
        return self._evict(victim)

    def _lru_evictable_leaf(self):
        """Oldest refcount-0 LEAF (no children), from the maintained
        evictable set."""
        return min(self._evictable, key=lambda n: n.last_use,
                   default=None)

    def _evict(self, node):
        """Unlink ``node`` and recycle its pool block id. Only refcount-0
        leaves are evictable — enforced, not assumed."""
        if node.children or node.refcount:
            raise RuntimeError("evicting a pinned or inner prefix block")
        del node.parent.children[node.tokens]
        self._evictable.discard(node)
        self._update_evictable(node.parent)    # may have become a leaf
        self.evictions += 1
        return node.block

    # ------------------------------------------------------------- stats
    def _count_nodes(self):
        n, stack = 0, list(self._root.children.values())
        while stack:
            node = stack.pop()
            n += 1
            stack.extend(node.children.values())
        return n

    def stats(self):
        used = self._count_nodes()
        return {
            "blocks_capacity": self.num_blocks,
            "blocks_used": used,
            # the REAL free list, not capacity - used: a leaked block id
            # (allocated but never attached, or evicted but not
            # recycled) shows up as used + free != capacity
            "blocks_free": len(self._free),
            "match_hits": self.match_hits,
            "match_misses": self.match_misses,
            "evictions": self.evictions,
            "committed_blocks": self.committed_blocks,
        }


class PrefixCache:
    """The device half: block pool + compiled adopt/commit copies. One
    PrefixCache can be SHARED between a ServingEngine and oneshot
    ``FusedDecoder.generate(prefix_cache=...)`` calls — the pool layout
    depends only on (L, H, D, cache flavor), not on the cache batch, so
    the same published blocks serve both (executables are cached per
    cache signature; a second signature costs one extra trace, counted
    by the same spy)."""

    def __init__(self, num_blocks, block_tokens):
        self.store = PrefixStore(num_blocks, block_tokens)
        self.num_blocks = int(num_blocks)
        self.block_tokens = int(block_tokens)
        self._pool = None                # device blocks (array or tuple)
        self._pool_sig = None            # (L, H, D, dtype-ish, int8?)
        self._jit_cache = {}
        self.trace_count = 0             # retrace spy, engine-compatible

    # ---------------------------------------------------------- plumbing
    def _counted_jit(self, key, build, donate=()):
        """Trace-spy jit (paged_kv.counted_jit is the one owner of the
        spy/donation rules): the counter bumps at trace time only, so
        zero-retrace-after-warmup contracts can assert over engine
        traces + this counter. Imported lazily — this module stays
        importable without jax for the host-only store tests."""
        from .paged_kv import counted_jit
        return counted_jit(self._jit_cache, key, build,
                           self._bump_traces, donate)

    def _bump_traces(self):
        self.trace_count += 1

    @staticmethod
    def _sig_of(caches):
        quant = isinstance(caches, tuple)
        stack = caches[0] if quant else caches
        L, _, _, H, _, D = stack.shape
        return (L, H, D, str(stack.dtype), quant)

    def _ensure_pool(self, caches):
        """Build (or validate) the pool against this cache's layout. The
        pool is [L, 2, NB, H, Bt, D] (+ [L, 2, NB, H, 1, Bt] scales in
        int8 mode) — block index rides the cache's batch axis so adopt
        and commit are pure gathers/updates along one axis."""
        import jax.numpy as jnp
        sig = self._sig_of(caches)
        if self._pool is not None:
            if sig != self._pool_sig:
                raise ValueError(
                    f"prefix cache pool was built for {self._pool_sig}, "
                    f"got caches with {sig} — one PrefixCache serves one "
                    "model/cache flavor")
            return
        L, H, D, dt, quant = sig
        shape = (L, 2, self.num_blocks, H, self.block_tokens, D)
        if quant:
            self._pool = (jnp.zeros(shape, jnp.int8),
                          jnp.zeros(shape[:4] + (1, self.block_tokens),
                                    jnp.float32))
        else:
            self._pool = jnp.zeros(shape, dt)
        self._pool_sig = sig

    # ------------------------------------------------------------ lookup
    def lookup(self, tokens):
        """Longest ADOPTABLE chain for a prompt (see
        lookup_adoptable — the shared owner of the cap/counter rules)."""
        return lookup_adoptable(self.store, self.block_tokens, tokens)

    # ------------------------------------------------------------- adopt
    def _build_adopt(self, K, quant):
        import jax.numpy as jnp
        Bt = self.block_tokens

        def adopt(caches, pool_s, pool_sc, idx, slot, nblk):
            # idx: [K] pool block ids (tail-padded); nblk: valid count.
            # Ladder tail positions are sent OUT OF BOUNDS (index Smax)
            # so mode="drop" skips them — identical write-mask discipline
            # to the in-slot prefill scatter; every landed position is
            # < nblk*Bt <= plen - 1 < Smax, inside the write kernels'
            # cache_lens < Smax clamp.
            stack = caches[0] if quant else caches
            smax = stack.shape[4]
            pos = jnp.arange(K * Bt, dtype=jnp.int32)
            pos = jnp.where(pos < nblk * Bt, pos, smax)
            blocks = jnp.take(pool_s, idx, axis=2)     # [L,2,K,H,Bt,D]
            vals = jnp.transpose(blocks, (2, 4, 0, 1, 3, 5))
            vals = vals.reshape((K * Bt,) + vals.shape[2:])
            if quant:
                ci8 = caches[0].at[:, :, slot, :, pos, :].set(
                    vals, mode="drop")
                scs = jnp.take(pool_sc, idx, axis=2)   # [L,2,K,H,1,Bt]
                sv = jnp.transpose(scs, (2, 5, 0, 1, 3, 4))
                sv = sv.reshape((K * Bt,) + sv.shape[2:])[..., 0]
                scl = caches[1].at[:, :, slot, :, 0, pos].set(
                    sv, mode="drop")
                return ci8, scl
            return caches.at[:, :, slot, :, pos, :].set(vals, mode="drop")
        return adopt

    def adopt(self, caches, slot, nodes):
        """Splat ``nodes``' pool blocks into ``caches``' row ``slot`` at
        positions [0, len(nodes)*Bt) in one compiled dispatch. Returns
        the updated caches. Caller holds refs on ``nodes`` across the
        call (host-synchronous dispatch: once issued, the pool buffer is
        captured by data dependency and the refs may drop)."""
        import jax.numpy as jnp
        self._ensure_pool(caches)
        quant = isinstance(caches, tuple)
        n = len(nodes)
        if n == 0:
            return caches
        K = 1 << (n - 1).bit_length()                  # pow-2 ladder
        idx = np.zeros(K, np.int32)
        idx[:n] = [nd.block for nd in nodes]
        sig = self._sig_of(caches)
        # donate ONLY the caches (arg 0): the pool is read, not consumed —
        # it must stay live for every later adopt/commit
        fn = self._counted_jit(
            ("adopt", K, sig),
            lambda k=K, q=quant: self._build_adopt(k, q), donate=(0,))
        pool = self._pool
        ps, psc = (pool if quant else (pool, None))
        if psc is None:
            psc = jnp.zeros((1,), jnp.float32)         # signature filler
        return fn(caches, ps, psc, jnp.asarray(idx),
                  jnp.asarray(slot, jnp.int32), jnp.asarray(n, jnp.int32))

    # ------------------------------------------------------------ commit
    def _build_commit(self, quant):
        import jax
        import jax.numpy as jnp
        Bt = self.block_tokens

        def commit(pool_s, pool_sc, caches, slot, t0, dst):
            stack = caches[0] if quant else caches
            L, _, _, H, _, D = stack.shape
            blk = jax.lax.dynamic_slice(
                stack, (0, 0, slot, 0, t0, 0), (L, 2, 1, H, Bt, D))
            pool_s = jax.lax.dynamic_update_slice(
                pool_s, blk.astype(pool_s.dtype), (0, 0, dst, 0, 0, 0))
            if quant:
                sc = jax.lax.dynamic_slice(
                    caches[1], (0, 0, slot, 0, 0, t0), (L, 2, 1, H, 1, Bt))
                pool_sc = jax.lax.dynamic_update_slice(
                    pool_sc, sc, (0, 0, dst, 0, 0, 0))
            return pool_s, pool_sc
        return commit

    def commit_block(self, caches, slot, t0, dst_block):
        """Copy the Bt-token block at ``caches[.., slot, .., t0:t0+Bt, ..]``
        into pool block ``dst_block`` (one fixed-shape dispatch; slot, t0
        and dst are data). Called strictly AFTER the block's prefill
        chunks landed in the slot — ordering is by jax data dependency on
        the caches buffer, no sync needed."""
        import jax.numpy as jnp
        self._ensure_pool(caches)
        quant = isinstance(caches, tuple)
        sig = self._sig_of(caches)
        fn = self._counted_jit(
            ("commit", sig),
            lambda q=quant: self._build_commit(q), donate=(0, 1))
        pool = self._pool
        ps, psc = (pool if quant else (pool, None))
        if psc is None:
            psc = jnp.zeros((1,), jnp.float32)
        ps, psc = fn(ps, psc, caches, jnp.asarray(slot, jnp.int32),
                     jnp.asarray(t0, jnp.int32),
                     jnp.asarray(dst_block, jnp.int32))
        self._pool = (ps, psc) if quant else ps

    def publish(self, caches, slot, tokens):
        """Commit-on-prefill: publish every full block of ``tokens`` that
        is not already in the store, copying its K/V out of the slot row.
        Blocks the prompt ADOPTED at admission re-resolve to their
        existing nodes (dedup — no copy). Returns #new blocks."""
        plan = self.store.insert(tokens)
        new = 0
        for i, (node, is_new) in enumerate(plan):
            if is_new:
                self.commit_block(caches, slot, i * self.block_tokens,
                                  node.block)
                new += 1
        return new
