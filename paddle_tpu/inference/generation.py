"""Autoregressive generation loops.

Capability parity: the decode driver around
fused_multi_transformer_op.cu (paddle/fluid/operators/fused/) and
PaddleNLP-style `generate()` (greedy / sampling / top-k / top-p).

Two paths:
  * generate(model, ...)        — model-agnostic: re-runs the forward on the
    growing prefix each step (correct for any causal LM; XLA caches one
    executable per prefix-length bucket).
  * generate_fused(fmt, ...)    — FusedMultiTransformer decode: static-shape
    KV ring cache + the Pallas flash-decode kernel
    (paddle_tpu/ops/pallas/decode_attention.py), one compiled step reused
    for every position — the reference's fused decode loop, TPU-style.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.rng import next_key
from ..tensor.tensor import Tensor, no_grad

__all__ = ["generate"]


def _sample_next(logits, do_sample, top_k, top_p, temperature):
    """logits: [B, V] jnp array -> [B] int32 token ids."""
    if not do_sample:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / jnp.maximum(temperature, 1e-6)
    if top_k and top_k > 0:
        kth = jnp.sort(logits, axis=-1)[:, -top_k][:, None]
        logits = jnp.where(logits < kth, -1e30, logits)
    if top_p and top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        cutoff_idx = jnp.sum(cum < top_p, axis=-1, keepdims=True)
        kth = jnp.take_along_axis(sorted_logits, cutoff_idx, axis=-1)
        logits = jnp.where(logits < kth, -1e30, logits)
    return jax.random.categorical(next_key(), logits, axis=-1).astype(
        jnp.int32)


@no_grad()
def generate(model, input_ids, max_new_tokens: int = 20,
             eos_token_id: Optional[int] = None, do_sample: bool = False,
             top_k: int = 0, top_p: float = 1.0, temperature: float = 1.0):
    """Causal-LM generation; input_ids [B, S] Tensor/ndarray -> [B, S+T].

    Greedy by default; sampling with top-k/top-p/temperature when
    do_sample=True. Stops early only when every sequence emitted eos.
    """
    model.eval()
    ids = input_ids._data if isinstance(input_ids, Tensor) else \
        jnp.asarray(np.asarray(input_ids))
    finished = jnp.zeros((ids.shape[0],), bool)
    for _ in range(max_new_tokens):
        logits = model(Tensor(ids))
        logits = logits._data if isinstance(logits, Tensor) else logits
        nxt = _sample_next(logits[:, -1], do_sample, top_k, top_p,
                           temperature)
        if eos_token_id is not None:
            nxt = jnp.where(finished, eos_token_id, nxt)
            finished = finished | (nxt == eos_token_id)
        ids = jnp.concatenate([ids, nxt[:, None]], axis=1)
        if eos_token_id is not None and bool(jnp.all(finished)):
            break
    return Tensor(ids)
