"""Autoregressive generation loops.

Capability parity: the decode driver around
fused_multi_transformer_op.cu (paddle/fluid/operators/fused/) and
PaddleNLP-style `generate()` (greedy / sampling / top-k / top-p).

Two paths:
  * generate(model, ...)        — model-agnostic: re-runs the forward on the
    growing prefix each step (correct for any causal LM; XLA caches one
    executable per prefix-length bucket).
  * generate_fused(fmt, ...)    — FusedMultiTransformer decode: static-shape
    KV ring cache + the Pallas flash-decode kernel
    (paddle_tpu/ops/pallas/decode_attention.py), one compiled step reused
    for every position — the reference's fused decode loop, TPU-style.
"""
from __future__ import annotations

import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec

from ..core.rng import next_key
from ..tensor.tensor import Tensor, no_grad

__all__ = ["generate", "generate_fused", "FusedDecoder",
           "dispatch_kind", "DISPATCH_KINDS", "STACKED_PARAM_SPECS"]

# ---- dispatch-kind vocabulary (serving telemetry) ---------------------
# Every compiled executable the serving stack can dispatch is built
# here (or keyed to a core built here), and the telemetry step timeline
# labels each dispatch with ONE canonical kind. Keeping the vocabulary
# next to the core builders means a new executable kind cannot reach
# the engine without naming itself for the timeline.
DISPATCH_KINDS = {
    "bulk_admit": "prefill",      # one-row causal-flash prompt pass
    "prefill": "prefill",         # masked chunked prefill scan
    "admit_sample": "admit",      # first-token sample on prefill hiddens
    "decode": "decode",           # the decode-chunk scan
    "verify": "verify",           # the K+1-position spec-verify block
    "budget": "budget",           # the [B, C] token-budget core
    "flat_budget": "budget",      # the token-flattened [T] budget core
}


def dispatch_kind(jit_key):
    """Canonical telemetry kind for a serving jit-cache key (keys are
    tuples whose head names the executable family; shape parameters
    follow). Unknown families pass through as their own name so a new
    dispatch is visible — just unclassified — rather than dropped."""
    return DISPATCH_KINDS.get(jit_key[0], str(jit_key[0]))


# ---- stacked-weight sharding table (tensor parallel over 'mp') --------
# Every key _stacked() can emit MUST have an explicit entry here —
# sharded on 'mp' or declared-replicated with P() — enforced twice:
# placement raises on an unknown key, and tools/check_sharding_spec.py
# (tier-1) rebuilds both weight flavors and diffs the keys against this
# table, so a new param key cannot silently replicate.
#
# Layout (Megatron-style; the KV pool/rings shard by head on the same
# 'mp' axis, see init_paged_cache / shard_caches):
#   * qkv_w is pre-fused HEAD-MAJOR at stack time — [L, nh*3*hd, E]
#     with nh outermost in the fused axis — so sharding the fused axis
#     IS the head shard and the in-trace (B,S,F)->(B,S,nh,3,hd) unfuse
#     stays GSPMD-representable (the raw (3,nh,..) layout sharded on
#     nh would gather the full weight at every dispatch).
#   * column-parallel (output-axis) shards: qkv_w/qkv_b, f1_w/f1_b —
#     no cross-device reduction, each device computes its own heads /
#     FFN columns exactly.
#   * row-parallel (contracting-axis) shards: lin_w, f2_w — GSPMD
#     psums the partial products inside the step core; their biases
#     and per-OUT-channel int8 scales (lin_w_s/f2_w_s) apply to the
#     summed [*, E] result, hence declared-replicated.
#   * qkv_w_s / f1_w_s scale a column-parallel output axis: they shard
#     WITH their weight (a replicated mirror would gather the sharded
#     dot result to apply it — the int8 flavor's silent-gather trap).
#   * LN params are tiny and feed every shard: replicated.
# PartitionSpec pads missing trailing dims with None, so one entry per
# key covers both the fp and int8 array ranks.
STACKED_PARAM_SPECS = {
    "ln_s": PartitionSpec(), "ln_b": PartitionSpec(),
    "fln_s": PartitionSpec(), "fln_b": PartitionSpec(),
    "qkv_w": PartitionSpec(None, "mp"),    # [L, nh*3*hd, E] fused col
    "qkv_b": PartitionSpec(None, "mp"),    # [L, nh*3*hd]
    "qkv_w_s": PartitionSpec(None, None, "mp"),  # [L, 1, nh*3*hd]
    "lin_w": PartitionSpec(None, "mp"),    # [L, nh*hd, E] row shard
    "lin_b": PartitionSpec(),              # applies post-psum
    "lin_w_s": PartitionSpec(),            # per-out-channel of the psum
    "f1_w": PartitionSpec(None, None, "mp"),     # [L, E, FF] col
    "f1_b": PartitionSpec(None, "mp"),     # [L, FF]
    "f1_w_s": PartitionSpec(None, None, "mp"),   # [L, 1, FF]
    "f2_w": PartitionSpec(None, "mp"),     # [L, FF, E] row shard
    "f2_b": PartitionSpec(),
    "f2_w_s": PartitionSpec(),
}


def _absmax_int8(w, axis):
    """Per-slice absmax int8 quantization (ONE recipe for every absmax
    site: weight-only layer stacks + LM head, and the int8 KV-cache
    writes in prefill / decode / serving bulk-admit — the i8 write
    kernel documents its in-kernel quant as bit-identical to this):
    scales = absmax/127 over the reduced axis with a zero-slice guard;
    values clip/round to int8. Returns (int8 array, fp32 scales with
    the reduced axis kept)."""
    a = w.astype(jnp.float32)
    s = jnp.max(jnp.abs(a), axis=axis, keepdims=True) / 127.0
    q = jnp.clip(jnp.round(a / jnp.maximum(s, 1e-8)),
                 -127, 127).astype(jnp.int8)
    return q, s


def _absmax_int4(w, axis):
    """int4 flavor of _absmax_int8 — SAME recipe, 4-bit range: scales =
    absmax/7 over the reduced axis (zero-slice guarded), values
    clip/round to [-7, 7] held in int8 nibbles pending _pack_int4.
    Returns (int8 array of int4-valued entries, fp32 scales with the
    reduced axis kept)."""
    a = w.astype(jnp.float32)
    s = jnp.max(jnp.abs(a), axis=axis, keepdims=True) / 7.0
    q = jnp.clip(jnp.round(a / jnp.maximum(s, 1e-8)),
                 -7, 7).astype(jnp.int8)
    return q, s


def _pack_int4(q, axis):
    """Pack adjacent pairs of int4-valued int8 entries along ``axis``
    into single bytes: the LOW nibble holds the even index, the HIGH
    nibble the odd one (both sign-extended on unpack via arithmetic
    shifts — see ops.pallas.fused_dequant_matmul). The axis must be
    even-length; halving it is what halves the int8 flavor's bytes."""
    axis = axis % q.ndim
    if q.shape[axis] % 2:
        raise ValueError(
            f"_pack_int4: axis {axis} has odd length {q.shape[axis]} — "
            "int4 packing pairs adjacent contracted elements")
    lo = jax.lax.slice_in_dim(q, 0, None, 2, axis)
    hi = jax.lax.slice_in_dim(q, 1, None, 2, axis)
    return ((lo & jnp.int8(0x0F))
            | jnp.left_shift(hi, 4).astype(jnp.int8)).astype(jnp.int8)


def _filter_logits(logits, do_sample, top_k, top_p, temperature):
    if not do_sample:
        return logits
    logits = logits / jnp.maximum(temperature, 1e-6)
    if top_k and top_k > 0:
        kth = jnp.sort(logits, axis=-1)[:, -top_k][:, None]
        logits = jnp.where(logits < kth, -1e30, logits)
    if top_p and top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        cutoff_idx = jnp.sum(cum < top_p, axis=-1, keepdims=True)
        kth = jnp.take_along_axis(sorted_logits, cutoff_idx, axis=-1)
        logits = jnp.where(logits < kth, -1e30, logits)
    return logits


def _penalize(logits, presence, repetition_penalty, nt, min_length, eos):
    """Reference generate() logit controls (PaddleNLP GenerationMixin):
    repetition_penalty divides positive / multiplies negative logits of
    every token already in the context (prompt + generated), and
    min_length suppresses eos until `nt` generated tokens exist. Pure
    jnp — usable inside compiled decode steps."""
    if repetition_penalty != 1.0 and presence is not None:
        logits = jnp.where(
            presence,
            jnp.where(logits > 0, logits / repetition_penalty,
                      logits * repetition_penalty),
            logits)
    if min_length and eos is not None:
        logits = logits.at[:, eos].set(
            jnp.where(nt < min_length, -1e30, logits[:, eos]))
    return logits


def _host_seed(key):
    """Fold a jax PRNG key (typed or raw uint32) into a numpy
    RandomState seed — the host-side acceptance sampler of speculative
    decoding draws from numpy, seeded off the same stream the device
    samplers advance."""
    data = np.asarray(jax.random.key_data(key)).ravel()
    return int(data[-1]) & 0x7FFFFFFF


def _presence_from(ids, vocab):
    p = jnp.zeros((ids.shape[0], vocab), bool)
    rows = jnp.arange(ids.shape[0])[:, None]
    return p.at[rows, ids].set(True)


def _sample_next(logits, do_sample, top_k, top_p, temperature, key=None):
    """logits: [B, V] jnp array -> [B] int32 token ids."""
    if not do_sample:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = _filter_logits(logits, do_sample, top_k, top_p, temperature)
    return jax.random.categorical(key if key is not None else next_key(),
                                  logits, axis=-1).astype(jnp.int32)


def _sample_rows(logits, do_sample, top_k, top_p, temperature, seeds, nt):
    """Scheduling-invariant per-row sampling for the serving engine:
    row b draws from fold_in(PRNGKey(seeds[b]), nt[b]) — the randomness
    behind a request's nt-th generated token depends ONLY on (request
    seed, position), never on which dispatch produced it. That makes
    sampled outputs identical across schedulers (phase-prefill vs the
    token-budget step, any chunk boundary, any slot assignment), which
    is what lets the chunked-vs-phase parity tests assert EXACT sampled
    token equality. Stateless by construction: a discarded sample (a
    masked row, a teacher-forced prefill position) consumes nothing.
    logits: [B, V]; seeds, nt: [B] int32 -> [B] int32 token ids."""
    if not do_sample:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = _filter_logits(logits, do_sample, top_k, top_p, temperature)

    def one(seed, n, lg):
        key = jax.random.fold_in(jax.random.PRNGKey(seed), n)
        return jax.random.categorical(key, lg)
    return jax.vmap(one)(seeds, nt, logits).astype(jnp.int32)


def _make_budget_tail(hidden, head_logits, penalize_slots, rep_on,
                      do_sample, top_k, top_p, temperature, nscan):
    """The budget cores' TRAILING decode scan (the decode-chunk body
    verbatim): after the block samples, rows that are decoding keep
    emitting `nscan` tokens in the SAME dispatch so mixed steps never
    slow decode below the plain chunk. ONE owner shared by the
    row-aligned [B, C] core and the flat [T] core — the two layouts'
    tail iterations cannot drift numerically."""
    def run(stk, e_arrays, h_arrays, tok, caches, lens, active, nt,
            presence, max_nt, eos_ids, min_len, rep_pen, seeds):
        def body(carry, _):
            tok, caches, lens, active, nt, presence = carry
            xs, caches = hidden(stk, e_arrays, caches, tok, lens)
            lg = head_logits(h_arrays, xs)
            lg = lg.reshape(lg.shape[0], -1)
            lg = penalize_slots(
                lg, presence if rep_on else None, rep_pen, nt,
                min_len, eos_ids)
            nxt = _sample_rows(lg, do_sample, top_k, top_p,
                               temperature, seeds, nt)
            emitted = active
            h_eos = (eos_ids >= 0) & (nxt == eos_ids)
            step_ = active.astype(jnp.int32)
            nt2 = nt + step_
            lens2 = lens + step_
            act2 = active & ~h_eos & (nt2 < max_nt)
            tok2 = jnp.where(emitted, nxt, tok)
            if rep_on:
                presence = presence.at[
                    jnp.arange(nxt.shape[0]), nxt].max(emitted)
            return (tok2, caches, lens2, act2, nt2,
                    presence), (nxt, emitted)
        return jax.lax.scan(
            body, (tok, caches, lens, active, nt, presence), None,
            length=nscan)
    return run


@no_grad()
def generate(model, input_ids, max_new_tokens: int = 20,
             eos_token_id: Optional[int] = None, do_sample: bool = False,
             top_k: int = 0, top_p: float = 1.0, temperature: float = 1.0,
             num_beams: int = 1, length_penalty: float = 1.0,
             min_length: int = 0, repetition_penalty: float = 1.0,
             no_repeat_ngram_size: int = 0):
    """Causal-LM generation; input_ids [B, S] Tensor/ndarray -> [B, S+T].

    Greedy by default; sampling with top-k/top-p/temperature when
    do_sample=True; beam search when num_beams > 1 (reference:
    generation's beam_search decode strategy / fluid beam_search op —
    length-penalized GNMT scoring, finished beams frozen on eos). Stops
    early only when every sequence (or every beam) emitted eos.
    """
    model.eval()
    ids = input_ids._data if isinstance(input_ids, Tensor) else \
        jnp.asarray(np.asarray(input_ids))
    if num_beams > 1:
        if do_sample:
            raise ValueError("beam search (num_beams>1) is deterministic; "
                             "do_sample=True is not supported with it")
        if min_length or repetition_penalty != 1.0:
            raise NotImplementedError(
                "min_length/repetition_penalty with beam search is not "
                "supported; use greedy/sampling generation")
        return _beam_search(model, ids, max_new_tokens, eos_token_id,
                            num_beams, length_penalty)
    finished = jnp.zeros((ids.shape[0],), bool)
    presence = None
    eos_i = None if eos_token_id is None else int(eos_token_id)
    rep_on = repetition_penalty != 1.0
    for nt in range(max_new_tokens):
        logits = model(Tensor(ids))
        logits = (logits._data if isinstance(logits, Tensor)
                  else logits)[:, -1]
        if min_length or rep_on:
            if rep_on and presence is None:
                presence = _presence_from(ids, logits.shape[-1])
            logits = _penalize(logits, presence, repetition_penalty,
                               nt, min_length, eos_i)
        if no_repeat_ngram_size:
            # reference no_repeat_ngram logits processor: ban every token
            # that would complete an already-seen n-gram. Host-side (this
            # path re-runs the forward per step anyway); the fused decoder
            # documents it as unsupported.
            n = int(no_repeat_ngram_size)
            ids_np = np.asarray(ids)
            if ids_np.shape[1] >= n - 1:
                banned = np.zeros(logits.shape, bool)
                for b_ in range(ids_np.shape[0]):
                    row = ids_np[b_].tolist()
                    tail = tuple(row[len(row) - (n - 1):]) if n > 1 else ()
                    for s_ in range(len(row) - n + 1):
                        if tuple(row[s_:s_ + n - 1]) == tail:
                            banned[b_, row[s_ + n - 1]] = True
                logits = jnp.where(jnp.asarray(banned), -1e30, logits)
        nxt = _sample_next(logits, do_sample, top_k, top_p,
                           temperature)
        if eos_token_id is not None:
            nxt = jnp.where(finished, eos_token_id, nxt)
            finished = finished | (nxt == eos_token_id)
        if presence is not None:
            presence = presence.at[jnp.arange(nxt.shape[0]), nxt].set(True)
        ids = jnp.concatenate([ids, nxt[:, None]], axis=1)
        if eos_token_id is not None and bool(jnp.all(finished)):
            break
    return Tensor(ids)


def _beam_search(model, ids, max_new_tokens, eos_token_id, num_beams,
                 length_penalty):
    """Model-agnostic beam search: re-runs the forward on the growing
    prefix (correct for any causal LM; XLA caches one executable per
    prefix length, shared across steps since all beams batch together).
    Finished beams are frozen: they may only continue with eos at zero
    added score. Final selection is GNMT length-penalized."""
    b, s0 = ids.shape
    k = int(num_beams)
    eos = None if eos_token_id is None else int(eos_token_id)
    beams = jnp.repeat(ids[:, None], k, axis=1)          # [B, K, S]
    # only beam 0 is live at step one, else K identical top picks
    scores = jnp.full((b, k), -1e9, jnp.float32).at[:, 0].set(0.0)
    finished = jnp.zeros((b, k), bool)
    gen_len = jnp.zeros((b, k), jnp.int32)               # generated length
    # separate FINISHED pool (standard beam search): a completed
    # hypothesis must survive even if live continuations transiently
    # out-score it and evict it from the top-k — track the best
    # length-penalized finished sequence per batch row, eos-padded to the
    # current length each step
    best_fin_score = jnp.full((b,), -jnp.inf, jnp.float32)
    best_fin_seq = beams[:, 0]                           # [B, S] placeholder

    for _ in range(max_new_tokens):
        flat = beams.reshape(b * k, beams.shape[-1])
        logits = model(Tensor(flat))
        logits = (logits._data if isinstance(logits, Tensor)
                  else logits)[:, -1]
        v = logits.shape[-1]
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        logp = logp.reshape(b, k, v)
        if eos is not None:
            only_eos = jnp.where(jnp.arange(v)[None, None, :] == eos,
                                 0.0, -jnp.inf)
            logp = jnp.where(finished[..., None], only_eos, logp)
        cand = scores[..., None] + logp                  # [B, K, V]
        top_scores, top_idx = jax.lax.top_k(cand.reshape(b, k * v), k)
        beam_idx = top_idx // v                          # [B, K]
        tok = (top_idx % v).astype(beams.dtype)
        beams = jnp.take_along_axis(beams, beam_idx[..., None], axis=1)
        beams = jnp.concatenate([beams, tok[..., None]], axis=-1)
        finished = jnp.take_along_axis(finished, beam_idx, axis=1)
        gen_len = jnp.take_along_axis(gen_len, beam_idx, axis=1)
        gen_len = jnp.where(finished, gen_len, gen_len + 1)
        scores = top_scores
        if eos is not None:
            newly = ~finished & (tok == eos)
            finished = finished | newly
            # admit newly finished hypotheses into the finished pool
            pen = jnp.maximum(gen_len, 1).astype(jnp.float32) \
                ** length_penalty
            cand_fin = jnp.where(newly, scores / pen, -jnp.inf)
            row_best = jnp.argmax(cand_fin, axis=1)              # [B]
            row_score = jnp.take_along_axis(
                cand_fin, row_best[:, None], axis=1)[:, 0]
            better = row_score > best_fin_score
            best_fin_seq = jnp.concatenate(                       # pad
                [best_fin_seq,
                 jnp.full((b, 1), eos, beams.dtype)], axis=-1)
            chosen = jnp.take_along_axis(
                beams, row_best[:, None, None], axis=1)[:, 0]
            best_fin_seq = jnp.where(better[:, None], chosen,
                                     best_fin_seq)
            best_fin_score = jnp.maximum(best_fin_score, row_score)
            if bool(jnp.all(finished)):
                break

    lp = jnp.maximum(gen_len, 1).astype(jnp.float32) ** length_penalty
    norm = scores / lp
    best = jnp.argmax(norm, axis=1)                      # [B]
    live_score = jnp.take_along_axis(norm, best[:, None], axis=1)[:, 0]
    out = jnp.take_along_axis(
        beams, best[:, None, None], axis=1)[:, 0]
    if eos is not None:
        # pad the finished pool to the final length and take the winner
        pad = out.shape[-1] - best_fin_seq.shape[-1]
        if pad > 0:
            best_fin_seq = jnp.concatenate(
                [best_fin_seq, jnp.full((b, pad), eos, beams.dtype)],
                axis=-1)
        use_fin = best_fin_score > live_score
        out = jnp.where(use_fin[:, None], best_fin_seq, out)
    return Tensor(out)


class FusedDecoder:
    """Compiled multi-layer KV-cache decode around FusedMultiTransformer.

    Parity: the decode driver of fused_multi_transformer_op.cu ::
    FusedMultiTransformerOp — all decoder layers batched into ONE compiled
    step per token. TPU-native realization:
      * the KV cache is a layer-stacked static ring buffer
        [L, 2, B, H, Smax, D] in kernel layout (no per-step transposes or
        reallocation; position is data, so one executable serves every t);
      * the cache is IN-PLACE: it rides the layer scan as carry with one
        tiny dynamic_update_slice per layer (the reference's in-place
        per-step cache write in fused_multi_transformer_op.cu), and the
        Pallas flash-decode kernel reads layer l's blocks straight out of
        the stacked buffer via a scalar-prefetch layer index
        (decode_attention_stacked) — the full stack is never copied per
        token;
      * the layer loop is a lax.scan over stacked layer params — the
        kernel compiles once and streams KV blocks for each layer;
      * under an active mesh with mp >= 2 the stacked kernel runs
        TP-sharded via shard_map over 'mp' (reference: mp-sharded heads
        in fused_multi_transformer_op.cu): heads are the sharded dim, so
        each device streams its local head blocks through the SAME
        kernel with no collectives; caches are annotated
        P(None,None,None,'mp',None,None). The int8 cache composes (stack
        and scales both shard on the head axis). Shapes the kernel can't
        tile fall back to a dense masked form GSPMD shards over 'mp'.

    embed / head are the model's surrounding Layers (token embedding and
    LM head); their params are passed as jit arguments, not baked in.
    """

    def __init__(self, fmt, embed, head, max_seq_len, use_rotary=False,
                 rope_base=10000.0, weight_quant=None, kv_quant=None):
        from ..nn.layer.layers import Layer
        # first-class quant config: an explicit ctor arg WINS over the
        # env knobs (PADDLE_TPU_DECODE_INT4_WEIGHTS /
        # PADDLE_TPU_DECODE_INT8_WEIGHTS / PADDLE_TPU_DECODE_INT8_CACHE
        # stay as deploy-time fallbacks); None defers to the env.
        # Explicit config fails FAST — an unknown mode or an int4 model
        # whose contracted axes cannot pack is a ValueError here, not a
        # first-dispatch surprise.
        if weight_quant not in (None, "none", "int8", "int4"):
            raise ValueError(
                f"weight_quant={weight_quant!r}: expected 'none', "
                "'int8' or 'int4'")
        if kv_quant not in (None, "none", "int8"):
            raise ValueError(
                f"kv_quant={kv_quant!r}: expected 'none' or 'int8' — "
                "the KV pool has no int4 flavor (per-row absmax at 4 "
                "bits clips decode tails; weights are where int4 pays)")
        self._weight_quant_arg = weight_quant
        self._kv_quant_arg = kv_quant
        self.fmt = fmt
        self.embed = embed
        self.head = head
        # ring capacity rounds up to a 128-multiple: the stacked-cache
        # Pallas kernel tiles Smax exactly (padding the stacked buffer
        # per call would copy every layer), and extra capacity only means
        # a slightly longer ring — callers still get >= max_seq_len
        self.smax = -(-int(max_seq_len) // 128) * 128
        self.use_rotary = use_rotary
        if use_rotary and float(rope_base) != 10000.0:
            raise NotImplementedError(
                "FusedDecoder prefill uses the fused stack's default rotary "
                "base (10000); plumb rotary_emb_base through "
                "fused_multi_transformer before changing it")
        self.rope_base = rope_base
        self._embed_params = list(embed.parameters()) if isinstance(
            embed, Layer) else []
        self._head_params = list(head.parameters()) if isinstance(
            head, Layer) else []
        self._scan_cache = {}      # (sample cfg, mesh, chunk, eos) -> jitted scan
        self._stk_cache = None
        if self._weight_quant_mode() == "int4":
            self._validate_int4_dims()

    # ------------------------------------------------------------ stacking
    def _weight_quant_mode(self) -> str:
        """The serving weight flavor: 'none' | 'int8' | 'int4'. An
        explicit ctor weight_quant wins; otherwise the env knobs decide
        (INT4 outranks INT8 when both are set — the more aggressive
        opt-in is the intended one)."""
        if self._weight_quant_arg is not None:
            return ("none" if self._weight_quant_arg == "none"
                    else self._weight_quant_arg)
        if os.environ.get("PADDLE_TPU_DECODE_INT4_WEIGHTS") == "1":
            return "int4"
        if os.environ.get("PADDLE_TPU_DECODE_INT8_WEIGHTS") == "1":
            return "int8"
        return "none"

    def _validate_int4_dims(self):
        """int4 packs TWO adjacent contracted-axis elements per byte, so
        every contracted axis of the stacked weights must be even:
        embed_dim (qkv_w / f1_w contract E), num_heads*head_dim (lin_w
        contracts the concatenated head axis) and ffn_dim (f2_w).
        Raises up front — the packed stack cannot be built otherwise."""
        f = self.fmt
        e = int(f.qkv_weights[0]._data.shape[-1])
        ff = int(f.ffn1_weights[0]._data.shape[-1])
        heads = f.num_heads * f.head_dim
        bad = [n for n, v in (("embed_dim", e),
                              ("num_heads*head_dim", heads),
                              ("ffn_dim", ff)) if v % 2]
        if bad:
            raise ValueError(
                "weight_quant='int4' needs even contracted axes to pack "
                f"two nibbles per byte; odd: {', '.join(bad)} "
                f"(embed_dim={e}, num_heads*head_dim={heads}, "
                f"ffn_dim={ff})")

    def _weight_shard_mesh(self):
        """The mesh the stacked weights (and a Linear LM head) shard
        over, or None (replicated — the pre-sharding behavior).
        Sharding is ON by default under an active mp mesh; opt out
        with PADDLE_SERVING_MESH_WEIGHTS=0. Falls back to None when
        the head / FFN axes do not divide mp — the engine surfaces
        that downgrade as a bring-up warning, and init_serving_mesh
        rejects it up front when given the model dims."""
        mesh = self._mesh_mp()
        if mesh is None or os.environ.get(
                "PADDLE_SERVING_MESH_WEIGHTS", "1") == "0":
            return None
        mp = dict(mesh.shape)["mp"]
        ff = int(self.fmt.ffn1_weights[0]._data.shape[-1])
        if self.fmt.num_heads % mp or ff % mp:
            return None
        if self._weight_quant_mode() == "int4":
            # the row-parallel stacks shard their PACKED contracted axis
            # (lin_w [L, nh*hd/2, E], f2_w [L, FF/2, E]): a byte-shard
            # boundary must land on a whole byte, so the HALF lengths
            # must divide mp too — else fall back to replicated weights
            # (init_serving_mesh rejects this up front when given dims)
            if (self.fmt.num_heads * self.fmt.head_dim // 2) % mp \
                    or (ff // 2) % mp:
                return None
        return mesh

    def _stacked(self):
        f = self.fmt
        # identity anchors are WEAK references: a dead weakref reads None
        # and never matches a live array, so the identity comparison is
        # sound (no recycled-id false match) without keeping the previous
        # parameter arrays alive — a strong hold meant a weight swap (new
        # checkpoint into the same decoder) pinned a full dead model copy
        # in HBM until the next restack completed (r4 verdict weak #7).
        import weakref
        version = [p._data for p in f.parameters()]
        # trace-time quant mode (ctor arg or env, see
        # _weight_quant_mode) and the weight-shard placement (mesh /
        # PADDLE_SERVING_MESH_WEIGHTS) are part of the cache identity:
        # flipping either must rebuild the stack, not reuse it — a
        # stack placed for the wrong mesh would silently reshard on
        # every dispatch
        mode = self._weight_quant_mode()
        env_sig = (mode, self._weight_shard_mesh())
        if self._stk_cache is not None and \
                self._stk_cache[2] == env_sig and \
                len(self._stk_cache[0]) == len(version) and \
                all(r() is b for r, b in zip(self._stk_cache[0], version)):
            return self._stk_cache[1]
        # drop stale stacked copies BEFORE building new ones so the two
        # stack generations never coexist in HBM
        self._stk_cache = None

        def stk(plist):
            return jnp.stack([p._data for p in plist])
        # qkv is pre-fused HEAD-MAJOR for BOTH weight flavors: the raw
        # per-layer [3, nh, hd, E] stacks become [L, nh*3*hd, E] (bias
        # [L, nh*3*hd]) with the head axis OUTERMOST in the fused dim.
        # Channel order is irrelevant to correctness (per-out-channel
        # dots and absmax scales commute with any output permutation —
        # qkv_of un-fuses with the matching (nh, 3, hd) reshape), but
        # it is what makes tensor parallel representable: sharding the
        # fused axis 'mp'-ways IS a head shard, and stays a head shard
        # through the in-trace unfuse reshape.
        qkv5 = stk(f.qkv_weights)              # [L, 3, nh, hd, E]
        qkvb4 = stk(f.qkv_biases)              # [L, 3, nh, hd]
        nl = qkv5.shape[0]
        out = {
            "ln_s": stk(f.ln_scales), "ln_b": stk(f.ln_biases),
            "qkv_w": jnp.swapaxes(qkv5, 1, 2).reshape(
                nl, -1, qkv5.shape[-1]),
            "qkv_b": jnp.swapaxes(qkvb4, 1, 2).reshape(nl, -1),
            "lin_w": stk(f.linear_weights), "lin_b": stk(f.linear_biases),
            "fln_s": stk(f.ffn_ln_scales), "fln_b": stk(f.ffn_ln_biases),
            "f1_w": stk(f.ffn1_weights), "f1_b": stk(f.ffn1_biases),
            "f2_w": stk(f.ffn2_weights), "f2_b": stk(f.ffn2_biases),
        }
        if mode == "int8":
            # weight-only int8 decode (reference: Predictor's weight-only
            # mode applied to the fused decode stack): at decode batch
            # sizes the step is WEIGHT-traffic bound (~2 bytes/param/token
            # in bf16 — ~250 MB/token for GPT-2-124M), so int8 storage
            # halves the dominant HBM stream. Per-(layer, out-channel)
            # absmax scales over the contracted axis; dequant is applied
            # AFTER each dot as a per-column scale (exact factoring: the
            # int values are exact in bf16, products accumulate fp32), so
            # no dequantized weight copy ever materializes. LN params,
            # biases, embed and LM head stay fp.
            def q_left(w3):          # used as h @ W.T: [L, O, I]
                q, s = _absmax_int8(w3, -1)
                return q, jnp.swapaxes(s, -1, -2)     # [L, 1, O]

            def q_right(w3):         # used as h @ W: [L, I, O]
                return _absmax_int8(w3, 1)            # scales [L, 1, O]

            out["qkv_w"], out["qkv_w_s"] = q_left(out["qkv_w"])
            out["lin_w"], out["lin_w_s"] = q_right(out["lin_w"])
            out["f1_w"], out["f1_w_s"] = q_right(out["f1_w"])
            out["f2_w"], out["f2_w_s"] = q_right(out["f2_w"])
        elif mode == "int4":
            # weight-only int4 (reference: Predictor's weight-only int4
            # mode): absmax/7 per (layer, out-channel), two adjacent
            # CONTRACTED-axis nibbles per byte — quartering the int8
            # flavor's dominant stream again. Packing happens AFTER the
            # head-major qkv fuse above, and always along the reduced
            # axis of the absmax, so the pack never straddles a
            # STACKED_PARAM_SPECS 'mp' split: qkv_w/f1_w pack the
            # UNsharded E axis, and lin_w/f2_w shard the packed axis in
            # whole bytes (validated in _weight_shard_mesh /
            # init_serving_mesh). The packed arrays keep the int8
            # flavor's key names, so the sharding table and every
            # downstream consumer (mm_p, tools) see one vocabulary.
            # mm_p never unpacks to a full fp copy: single-device it
            # runs the fused dequant-matmul Pallas kernel, under a mesh
            # a nibble-split XLA dot (see mm_p).
            self._validate_int4_dims()

            def q4_left(w3):         # used as h @ W.T: [L, O, I]
                q, s = _absmax_int4(w3, -1)
                return _pack_int4(q, -1), jnp.swapaxes(s, -1, -2)

            def q4_right(w3):        # used as h @ W: [L, I, O]
                q, s = _absmax_int4(w3, 1)            # scales [L, 1, O]
                return _pack_int4(q, 1), s

            out["qkv_w"], out["qkv_w_s"] = q4_left(out["qkv_w"])
            out["lin_w"], out["lin_w_s"] = q4_right(out["lin_w"])
            out["f1_w"], out["f1_w_s"] = q4_right(out["f1_w"])
            out["f2_w"], out["f2_w_s"] = q4_right(out["f2_w"])
        mesh = env_sig[1]
        if mesh is not None:
            # tensor-parallel placement: commit every stacked array to
            # its declared layout so each device holds ~1/mp of the
            # sharded weight bytes from first dispatch on (no lazy
            # reshard inside the step). An unknown key is a hard error
            # — the runtime twin of tools/check_sharding_spec.py.
            from jax.sharding import NamedSharding
            from ..parallel import _valid_spec
            for k in out:
                spec = STACKED_PARAM_SPECS.get(k)
                if spec is None:
                    raise ValueError(
                        f"stacked param {k!r} has no entry in "
                        "STACKED_PARAM_SPECS — every stacked key needs "
                        "an explicit PartitionSpec (sharded or the "
                        "replicated P()); see "
                        "tools/check_sharding_spec.py")
                if not _valid_spec(out[k], spec, mesh):
                    spec = PartitionSpec()      # indivisible: replicate
                out[k] = jax.device_put(out[k],
                                        NamedSharding(mesh, spec))
        try:
            anchors = [weakref.ref(a) for a in version]
        except TypeError:
            # non-weakrefable leaves (shouldn't happen for jax arrays):
            # degrade to always-rebuild rather than pin
            anchors = [(lambda: None)] * len(version)
        self._stk_cache = (anchors, out, env_sig)
        return out

    def _maybe_quant_head(self, h_arrays):
        """LM-head preparation for plain Linear heads (non-Linear heads
        pass through untouched — call_layerlike path): optional int8
        quant (PADDLE_TPU_DECODE_INT8_HEAD=1 → [W_int8, scales(, bias)]
        with per-out-channel absmax scales, dequant applied after the
        dot by head_logits), then tensor-parallel placement — under a
        weight-shard mesh the weight [E, V], int8 scales [1, V] and
        bias [V] all shard the VOCAB axis, so logits leave the head
        vocab-sharded and GSPMD gathers them only at the argmax /
        sampling reduction. An indivisible vocab stays replicated (the
        per-key fallback, same policy as the layer stack). Cached on
        (quant flag, mesh, weight identity)."""
        from ..nn.layer.common import Linear
        if type(self.head) is not Linear or not h_arrays:
            return h_arrays
        quant = os.environ.get("PADDLE_TPU_DECODE_INT8_HEAD") == "1"
        mesh = self._weight_shard_mesh()
        if not quant and mesh is None:
            return h_arrays
        import weakref
        sig = (quant, mesh)
        cached = getattr(self, "_head_q_cache", None)
        if cached is not None and cached[2] == sig and \
                len(cached[0]) == len(h_arrays) and \
                all(r() is a for r, a in zip(cached[0], h_arrays)):
            return cached[1]
        if quant:
            q, s = _absmax_int8(h_arrays[0], 0)        # weight [E, V]
            out = [q, s] + list(h_arrays[1:])
        else:
            out = list(h_arrays)
        if mesh is not None:
            from jax.sharding import NamedSharding
            from ..parallel import _valid_spec
            placed = []
            for a in out:
                # vocab is the LAST axis of every Linear-head array:
                # weight [E, V], int8 scales [1, V], bias [V]
                spec = PartitionSpec(*([None] * (a.ndim - 1) + ["mp"]))
                if not _valid_spec(a, spec, mesh):
                    spec = PartitionSpec()
                placed.append(jax.device_put(
                    a, NamedSharding(mesh, spec)))
            out = placed
        # key on EVERY source array (a bias-only swap must invalidate,
        # not serve the stale cached bias)
        self._head_q_cache = ([weakref.ref(a) for a in h_arrays], out,
                              sig)
        return out

    def _int8_cache(self) -> bool:
        """Opt-in int8 KV cache (reference: fused_multi_transformer's
        cache_kv int8 serving mode). Decode is bandwidth-bound — int8
        halves the cache bytes streamed per token; rows are absmax-
        quantized per (layer, kv, batch, head, position) with fp32
        scales, dequantized in VMEM by the stacked kernels (row AND
        flat flavors). An explicit ctor kv_quant wins; None defers to
        PADDLE_TPU_DECODE_INT8_CACHE."""
        if self._kv_quant_arg is not None:
            return self._kv_quant_arg == "int8"
        return os.environ.get("PADDLE_TPU_DECODE_INT8_CACHE") == "1"

    def init_cache(self, batch, dtype=None):
        f = self.fmt
        dtype = dtype or self.fmt.qkv_weights[0]._data.dtype
        shape = (f.num_layers, 2, batch, f.num_heads, self.smax,
                 f.head_dim)
        if self._int8_cache():
            # scales keep positions on the LAST axis ([..., 1, Smax]) so
            # the kernel streams them as [1, bk] lane-major blocks
            # (Mosaic-legal; a [bk, 1] lane-1 block is a compile risk).
            # Composes with mp>=2: the shard_map'd stacked kernel reads
            # each device's local heads of both the int8 stack and the
            # scales (r5; previously int8 was refused under a mesh).
            return (jnp.zeros(shape, jnp.int8),
                    jnp.zeros(shape[:4] + (1, self.smax),
                              jnp.float32))
        return jnp.zeros(shape, dtype)

    def init_paged_cache(self, pool, dtype=None):
        """Device arrays for a paged_kv.BlockPool: the ONE kv pool
        {"kv": [L, 2, NB, H, Bt, D]} (+ {"sc": [L, 2, NB, H, 1, Bt]}
        mirrored int8 scales in cache-quant mode). The caller (the
        serving engine) adds the per-slot block tables as "tbl" per
        dispatch — tables are host state, rebuilt from numpy each call,
        while the pool arrays ride donation like the dense cache.

        Under an active mp mesh the pool is laid out head-sharded on
        the 'mp' axis (NamedSharding; axis 3 of both kv and sc) so each
        device holds pool_bytes / mp — the block allocator, tables and
        all scheduler metadata stay replicated host data, so paged
        churn is invisible to the partitioner."""
        f = self.fmt
        dtype = dtype or self.fmt.qkv_weights[0]._data.dtype
        if getattr(pool, "smax", self.smax) != self.smax:
            raise ValueError(
                f"BlockPool was sized for max_seq_len={pool.smax} but "
                f"this decoder's ring capacity is Smax={self.smax} — "
                "the block table has Smax/Bt entries, the two must "
                "agree")
        shape = (f.num_layers, 2, pool.num_blocks, f.num_heads,
                 pool.block_tokens, f.head_dim)
        mesh = self._mesh_mp()
        sharding = None
        if mesh is not None:
            mp = dict(mesh.shape)["mp"]
            if f.num_heads % mp:
                raise ValueError(
                    f"paged KV pool cannot shard: num_heads="
                    f"{f.num_heads} is not divisible by the mesh's mp "
                    f"degree {mp} — the pool shards by head on the "
                    "'mp' axis")
            from jax.sharding import NamedSharding, PartitionSpec as P
            sharding = NamedSharding(
                mesh, P(None, None, None, "mp", None, None))

        def _zeros(shp, dt):
            z = jnp.zeros(shp, dt)
            return jax.device_put(z, sharding) if sharding is not None \
                else z
        if self._int8_cache():
            return {"kv": _zeros(shape, jnp.int8),
                    "sc": _zeros(shape[:4] + (1, pool.block_tokens),
                                 jnp.float32)}
        return {"kv": _zeros(shape, dtype)}

    # ------------------------------------------------------------ the step
    def _mesh_mp(self):
        from ..parallel import current_mesh
        mesh = current_mesh()
        if mesh is not None and dict(mesh.shape).get("mp", 1) >= 2:
            return mesh
        return None

    def _build_scan_step(self, do_sample, top_k, top_p, temperature,
                         chunk, eos, min_length=0, repetition_penalty=1.0):
        """chunk tokens per device program: lax.scan over the per-token
        step, KV cache + last token + finished mask in the carry. One host
        dispatch per chunk instead of per token — the decode-side analogue
        of jit.run_steps (the tunnel backend pays a round-trip per
        dispatch). eos is static (baked into the trace): finished rows keep
        emitting eos on-device. min_length / repetition_penalty apply
        inside the compiled step (reference: generation's logit
        processors); ONLY repetition_penalty needs the [B, V]
        context-presence mask in the carry — min_length alone just
        compares the generated count against the eos column."""
        core = self._build_step_core(do_sample, top_k, top_p, temperature)
        rep_on = repetition_penalty != 1.0
        pen_on = bool(min_length) or rep_on
        hidden, head_logits = core.hidden, core.head_logits

        def next_token(stk, e_arrays, h_arrays, caches, tok, t, key,
                       presence, nt):
            if not pen_on:
                return core(stk, e_arrays, h_arrays, caches, tok, t, key)
            x, caches = hidden(stk, e_arrays, caches, tok, t)
            logits = head_logits(h_arrays, x)
            logits = logits.reshape(logits.shape[0], -1)
            logits = _penalize(logits, presence if rep_on else None,
                               repetition_penalty, nt, min_length, eos)
            return _sample_next(logits, do_sample, top_k, top_p,
                                temperature, key), caches

        def scan_step(stk, e_arrays, h_arrays, caches, tok, t0, keys,
                      finished, presence=None, nt0=None):
            carry0 = (tok, caches, finished) + (
                (presence,) if rep_on else ())

            def body(carry, xs):
                tok, caches, finished = carry[:3]
                presence = carry[3] if rep_on else None
                i, key = xs
                nxt, caches = next_token(
                    stk, e_arrays, h_arrays, caches, tok, t0 + i, key,
                    presence, (nt0 + i) if pen_on else None)
                if eos is not None:
                    nxt = jnp.where(finished, eos, nxt)
                    finished = finished | (nxt == eos)
                out = (nxt, caches, finished)
                if rep_on:
                    out += (presence.at[jnp.arange(nxt.shape[0]),
                                        nxt].set(True),)
                return out, nxt
            carry, toks = jax.lax.scan(
                body, carry0, (jnp.arange(chunk, dtype=jnp.int32), keys))
            if rep_on:
                return toks, carry[1], carry[2], carry[3]
            return toks, carry[1], carry[2]
        # donate the KV cache (in-place ring update, no per-token copy of
        # the [L,2,B,H,Smax,D] buffer) — except through the axon tunnel,
        # where buffer donation is observed to hang (see BASELINE.md r2)
        tunneled = bool(os.environ.get("PALLAS_AXON_POOL_IPS"))
        return jax.jit(scan_step, donate_argnums=() if tunneled else (3,))

    def _build_prefill_scan(self, chunk):
        """Compiled prefill: scan the HIDDEN core (embed + layers + cache
        write, no LM head / sampling) over `chunk` teacher-forced prompt
        tokens starting at traced offset t0. Returns the last token's
        hidden state + updated caches; the caller applies the head once
        after the final chunk. Replaces the old eager fused-stack prefill,
        which paid a tunnel RPC per op — measured r3 s4: ~8.8 s of the
        8.9 s decode bench was eager prefill dispatch, not compute. Chunk
        sizes come from the same power-of-two ladder as decode so
        arbitrary prompt lengths reuse a bounded set of compiled
        variants."""
        hidden = self._build_step_core(False, 0, 1.0, 1.0).hidden

        def prefill(stk, e_arrays, caches, toks, t0):
            # toks: [chunk, B] int32 (time-major for the scan)
            def body(carry, xs):
                caches = carry
                tok_i, i = xs
                x, caches = hidden(stk, e_arrays, caches, tok_i, t0 + i)
                return caches, x
            caches, xs_out = jax.lax.scan(
                body, caches, (toks, jnp.arange(chunk, dtype=jnp.int32)))
            return xs_out[-1], caches
        tunneled = bool(os.environ.get("PALLAS_AXON_POOL_IPS"))
        return jax.jit(prefill, donate_argnums=() if tunneled else (2,))

    def _build_bulk_prefill(self):
        """Whole-prompt prefill (PADDLE_TPU_BULK_PREFILL=1): ONE jitted
        call embeds the prompt, runs the stack with causal flash, and
        builds the ring cache by PADDING the per-layer K/V scan output to
        Smax — the cache is born in its final buffer (no DUS, no carry,
        nothing for copy-insertion to get wrong). One executable per
        exact prompt length (serving should bucket prompts; the chunked
        per-token prefill remains the default). Composes with the int8
        cache (vectorized absmax quant of the whole stack) and int8
        weight stacks (mm handles them)."""
        bulk_hidden = self._build_step_core(False, 0, 1.0, 1.0).bulk_hidden
        smax = self.smax
        cache_dtype = self.fmt.qkv_weights[0]._data.dtype
        int8 = self._int8_cache()

        def prefill(stk, e_arrays, toks):
            x_all, kv_all = bulk_hidden(stk, e_arrays, toks)
            last_x = x_all[:, -1:]
            S = toks.shape[1]
            pad = [(0, 0)] * 4 + [(0, smax - S), (0, 0)]
            if int8:
                q_i8, sc = _absmax_int8(kv_all, -1)
                caches = (jnp.pad(q_i8, pad),
                          jnp.pad(jnp.swapaxes(sc, -1, -2),
                                  [(0, 0)] * 5 + [(0, smax - S)]))
            else:
                caches = jnp.pad(kv_all.astype(cache_dtype), pad)
            return last_x, caches
        return jax.jit(prefill)

    def _build_head_sample(self, do_sample, top_k, top_p, temperature,
                           eos=None, min_length=0,
                           repetition_penalty=1.0):
        """Jitted LM head + filter + sample on one hidden state [B,1,E];
        with penalties active the logit controls apply at nt=0 (prompt
        presence only when repetition_penalty is on). min_length is
        consumed as a BOOL here — nt is baked to 0, so every positive
        value behaves identically (callers key their cache that way to
        avoid gratuitous recompiles)."""
        core = self._build_step_core(do_sample, top_k, top_p, temperature)
        rep_on = repetition_penalty != 1.0
        if not min_length and not rep_on:
            return jax.jit(core.sample_head)
        head_logits = core.head_logits

        def head_sample(h_arrays, x, key, presence=None):
            logits = head_logits(h_arrays, x)
            logits = logits.reshape(logits.shape[0], -1)
            logits = _penalize(logits, presence if rep_on else None,
                               repetition_penalty, 0,
                               1 if min_length else 0, eos)
            return _sample_next(logits, do_sample, top_k, top_p,
                                temperature, key)
        return jax.jit(head_sample)

    # ------------------------------------------------- beam over the cache
    # Reference: fluid beam_search op driving generation against
    # fused_multi_transformer's decode cache. The old generate(num_beams)
    # re-ran the full forward on the growing prefix every step (O(S^2)
    # forwards, one executable per prefix length); here the beams SHARE
    # the prefill cache (prefilled once at batch B, then replicated to
    # B*K on the beam axis) and each step's beam reorder is ONE gather on
    # the batch*beam dim of the cache inside the compiled step — one
    # executable total, no prefix re-forward. Sequences are reconstructed
    # host-side by backtracking the recorded (token, parent-beam) lineage
    # (the compiled step never carries the growing sequence).

    def _build_beam_init(self, k, eos, length_penalty):
        """Jitted step 1: prefill hidden state -> logits -> first top-k.
        Mirrors _beam_search's first iteration (scores [0, -inf...] make
        the K picks come from beam 0's distribution)."""
        core = self._build_step_core(False, 0, 1.0, 1.0)
        head_logits = core.head_logits

        def init(h_arrays, last_x):
            logits = head_logits(h_arrays, last_x)
            logits = logits.reshape(logits.shape[0], -1)
            b, v = logits.shape
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
            scores0 = jnp.full((b, k), -1e9, jnp.float32).at[:, 0].set(0.0)
            cand = scores0[..., None] + logp[:, None, :]     # [B, K, V]
            top_scores, top_idx = jax.lax.top_k(cand.reshape(b, k * v), k)
            tok = (top_idx % v).astype(jnp.int32)            # [B, K]
            gen_len = jnp.ones((b, k), jnp.int32)
            if eos is not None:
                newly = tok == eos
                pen = gen_len.astype(jnp.float32) ** length_penalty
                fin_score = jnp.where(newly, top_scores / pen, -jnp.inf)
                finished = newly
            else:
                fin_score = jnp.full((b, k), -jnp.inf, jnp.float32)
                finished = jnp.zeros((b, k), bool)
            beam_idx = jnp.zeros((b, k), jnp.int32)
            return (tok, beam_idx, fin_score, finished, top_scores,
                    gen_len)
        return jax.jit(init)

    def _build_beam_scan(self, k, chunk, eos, length_penalty, split=0):
        """chunk beam steps per device program. Carry: (caches, flat tok
        [B*K], scores/finished/gen_len [B,K]); ys: the per-step lineage +
        bookkeeping snapshots the host backtracks over. Semantics match
        _beam_search step-for-step (finished beams continue only with eos
        at zero added score; GNMT length penalty at finish admission).

        split (static): the prompt's KV region [0, split) is IDENTICAL
        across the beams of a batch row forever (written at prefill,
        before beam replication, never re-written), so reordering it is a
        semantic no-op — the per-step beam gather only touches positions
        >= split and writes them back in place (dynamic_update_slice on
        the donated buffer). For long prompts that removes most of the
        reorder's HBM traffic. split is a pow-2 bucket of the prompt
        length so executables stay bounded."""
        core = self._build_step_core(False, 0, 1.0, 1.0)
        hidden = core.hidden
        head_logits = core.head_logits

        def beam_chunk(stk, e_arrays, h_arrays, caches, tok_flat, t0,
                       scores, finished, gen_len):
            b, kk = scores.shape

            def body(carry, i):
                caches, tok_flat, scores, finished, gen_len = carry
                x, caches = hidden(stk, e_arrays, caches, tok_flat,
                                   t0 + i)
                logits = head_logits(h_arrays, x)
                logits = logits.reshape(b * kk, -1)
                v = logits.shape[-1]
                logp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
                logp = logp.reshape(b, kk, v)
                if eos is not None:
                    only_eos = jnp.where(
                        jnp.arange(v)[None, None, :] == eos, 0.0, -jnp.inf)
                    logp = jnp.where(finished[..., None], only_eos, logp)
                cand = scores[..., None] + logp
                top_scores, top_idx = jax.lax.top_k(
                    cand.reshape(b, kk * v), kk)
                beam_idx = top_idx // v                      # [B, K]
                tok = (top_idx % v).astype(jnp.int32)
                # THE cache gather: reorder the batch*beam axis to each
                # winner's parent (both stack and int8 scales), touching
                # only positions >= split (the shared-prompt region needs
                # no reorder — identical rows)
                flat_src = (jnp.arange(b)[:, None] * kk
                            + beam_idx).reshape(-1)

                def reorder(c, pos_axis):
                    if not split:
                        return jnp.take(c, flat_src, axis=2)
                    tail = jax.lax.slice_in_dim(
                        c, split, c.shape[pos_axis], axis=pos_axis)
                    tail = jnp.take(tail, flat_src, axis=2)
                    starts = [0] * c.ndim
                    starts[pos_axis] = split
                    return jax.lax.dynamic_update_slice(
                        c, tail, tuple(starts))
                if isinstance(caches, tuple):
                    # stack positions ride axis 4; scale positions axis 5
                    caches = (reorder(caches[0], 4),
                              reorder(caches[1], 5))
                else:
                    caches = reorder(caches, 4)
                finished = jnp.take_along_axis(finished, beam_idx, 1)
                gen_len = jnp.take_along_axis(gen_len, beam_idx, 1)
                gen_len = jnp.where(finished, gen_len, gen_len + 1)
                scores = top_scores
                if eos is not None:
                    newly = ~finished & (tok == eos)
                    pen = jnp.maximum(gen_len, 1).astype(
                        jnp.float32) ** length_penalty
                    fin_score = jnp.where(newly, scores / pen, -jnp.inf)
                    finished = finished | newly
                else:
                    fin_score = jnp.full((b, kk), -jnp.inf, jnp.float32)
                ys = (tok, beam_idx, fin_score, finished, scores, gen_len)
                return (caches, tok.reshape(-1), scores, finished,
                        gen_len), ys
            (caches, tok_flat, scores, finished, gen_len), ys = \
                jax.lax.scan(
                    body,
                    (caches, tok_flat, scores, finished, gen_len),
                    jnp.arange(chunk, dtype=jnp.int32))
            return caches, tok_flat, scores, finished, gen_len, ys
        tunneled = bool(os.environ.get("PALLAS_AXON_POOL_IPS"))
        return jax.jit(beam_chunk,
                       donate_argnums=() if tunneled else (3,))

    def _build_step_core(self, do_sample, top_k, top_p, temperature):
        f = self.fmt
        eps = f.epsilon
        pre_ln = f.normalize_before
        nh, hd = f.num_heads, f.head_dim
        act = f.activation
        smax = self.smax
        use_rotary = self.use_rotary
        rope_base = self.rope_base
        mesh = self._mesh_mp()
        from ..nn.layer.layers import substitute_param_arrays

        def ln(x, s, b):
            mu = jnp.mean(x.astype(jnp.float32), -1, keepdims=True)
            var = jnp.var(x.astype(jnp.float32), -1, keepdims=True)
            out = (x.astype(jnp.float32) - mu) * jax.lax.rsqrt(var + eps)
            return (out * s + b).astype(x.dtype)

        def rope_block(x, tv2):
            # x: [B, Sq, H, D] at per-(row, position) absolute positions
            # tv2 [B, Sq] — ONE rotary implementation for every decode
            # flavor (rope1 below is a rank adapter over it), so the
            # per-token, serving vector-t, and spec-verify block paths
            # cannot drift numerically
            inv = 1.0 / (rope_base ** (jnp.arange(0, hd, 2,
                                                  dtype=jnp.float32) / hd))
            fr = tv2.astype(jnp.float32)[..., None] * inv   # [B, Sq, D/2]
            s = jnp.concatenate([jnp.sin(fr), jnp.sin(fr)], axis=-1)
            c = jnp.concatenate([jnp.cos(fr), jnp.cos(fr)], axis=-1)
            ss = s[:, :, None, :]
            cc = c[:, :, None, :]
            x1 = x[..., : hd // 2]
            x2 = x[..., hd // 2:]
            rot = jnp.concatenate([-x2, x1], axis=-1)
            return (x * cc.astype(x.dtype) + rot * ss.astype(x.dtype))

        def rope1(x, t):
            # x: [B, 1, H, D] at absolute position t — scalar (every row
            # at the same position, the classic decode step) or [B]
            # (per-row positions, the serving engine's ragged slots)
            tv = jnp.asarray(t).astype(jnp.int32)
            tv2 = jnp.broadcast_to(tv.reshape(-1, 1) if tv.ndim
                                   else tv[None, None], (x.shape[0], 1))
            return rope_block(x, tv2)

        def attend(q, caches, l, t):
            # q: [B, Sq, H, D] (Sq == 1 for the classic decode step; the
            # spec-decode verify step passes the whole K+1 block);
            # caches: [L, 2, B, H, Smax, D] (full stack — the kernel
            # addresses layer l via scalar prefetch, zero-copy), (int8
            # stack, fp32 scales) in cache-quant mode, or the PAGED dict
            # {"kv": [L, 2, NB, H, Bt, D](, "sc"), "tbl": [B, Smax/Bt]}
            # — one block pool, per-slot block tables (paged_kv.py).
            # t: scalar OR [B] per-row BASE positions — query row j
            # attends cache positions <= t + j (the stacked kernels'
            # native block-causal semantics: "new tokens attend causally
            # among themselves and fully to the prefix"; the dense
            # fallback builds the same mask per row).
            sq = q.shape[1]
            qt = jnp.swapaxes(q, 1, 2)                  # [B, H, Sq, D]
            tb = jnp.broadcast_to(jnp.asarray(t).astype(jnp.int32),
                                  (q.shape[0],))
            paged = isinstance(caches, dict)
            quant = isinstance(caches, tuple) or (paged and
                                                  "sc" in caches)
            if paged:
                pool_kv, tbl = caches["kv"], caches["tbl"]
                nb = pool_kv.shape[2]
                # the paged kernel gathers K/V through the block table
                # (table rides as scalar prefetch — block ids are data);
                # under a mesh the pool shards by HEAD on 'mp' while the
                # table stays replicated, so each device runs the same
                # kernel over its local heads against the full table
                if (os.environ.get("PADDLE_TPU_STACKED_KERNEL", "1")
                        != "0"):
                    from ..ops.pallas.decode_attention import (
                        decode_attention_paged, decode_attention_paged_i8,
                        paged_i8_is_supported, paged_is_supported)
                    mp = (1 if mesh is None
                          else dict(mesh.shape).get("mp", 1))
                    if mesh is not None and mp >= 2 and nh % mp == 0 \
                            and pool_kv.shape[3] % mp == 0:
                        # head-sharded paged kernel: attention is
                        # embarrassingly parallel over heads, and the
                        # block table addresses the (replicated) NB axis
                        # only, so shard_map over 'mp' needs no
                        # collectives — same escape-from-GSPMD the dense
                        # stacked path uses below
                        lshape = (pool_kv.shape[:3]
                                  + (pool_kv.shape[3] // mp,)
                                  + pool_kv.shape[4:])
                        ok = (paged_i8_is_supported(
                                  (q.shape[0], sq, nh // mp, hd), lshape,
                                  q.dtype) if quant else
                              paged_is_supported(
                                  (q.shape[0], sq, nh // mp, hd), lshape,
                                  q.dtype, cache_dtype=pool_kv.dtype))
                        if ok:
                            from jax import shard_map
                            from jax.sharding import PartitionSpec as SP
                            hsp = SP(None, "mp", None, None)
                            psp = SP(None, None, None, "mp", None, None)
                            if quant:
                                fn = shard_map(
                                    decode_attention_paged_i8, mesh=mesh,
                                    in_specs=(hsp, psp, psp, SP(), SP(),
                                              SP()),
                                    out_specs=hsp, check_vma=False)
                                o = fn(qt, pool_kv, caches["sc"], tbl, l,
                                       tb)
                            else:
                                fn = shard_map(
                                    decode_attention_paged, mesh=mesh,
                                    in_specs=(hsp, psp, SP(), SP(),
                                              SP()),
                                    out_specs=hsp, check_vma=False)
                                o = fn(qt, pool_kv, tbl, l, tb)
                            return jnp.swapaxes(o, 1, 2)
                    if mesh is None and quant and paged_i8_is_supported(
                            (q.shape[0], sq, nh, hd), pool_kv.shape,
                            q.dtype):
                        o = decode_attention_paged_i8(
                            qt, pool_kv, caches["sc"], tbl, l, tb)
                        return jnp.swapaxes(o, 1, 2)
                    if mesh is None and not quant and paged_is_supported(
                            (q.shape[0], sq, nh, hd), pool_kv.shape,
                            q.dtype, cache_dtype=pool_kv.dtype):
                        o = decode_attention_paged(qt, pool_kv, tbl, l,
                                                   tb)
                        return jnp.swapaxes(o, 1, 2)
                # gather-through-table dense fallback: materialize the
                # row view [2, B, H, Smax, D] from the pool (sentinel
                # entries clamp to an arbitrary block — their positions
                # are >= the row's lens and masked below, exactly like
                # the dense path's stale ring positions)
                pool_l = jax.lax.dynamic_index_in_dim(pool_kv, l, 0,
                                                      keepdims=False)
                tc = jnp.minimum(tbl, nb - 1)
                kvg = jnp.take(pool_l, tc, axis=1)  # [2, B, Nblk, H, Bt, D]
                kvg = jnp.transpose(kvg, (0, 1, 3, 2, 4, 5)).reshape(
                    2, tbl.shape[0], nh, smax, hd)
                if quant:
                    sc_l = jax.lax.dynamic_index_in_dim(
                        caches["sc"], l, 0, keepdims=False)
                    scg = jnp.take(sc_l, tc, axis=1)  # [2,B,Nblk,H,1,Bt]
                    scg = jnp.transpose(scg, (0, 1, 3, 4, 2, 5)).reshape(
                        2, tbl.shape[0], nh, 1, smax)
                    cache = kvg.astype(jnp.float32) * jnp.swapaxes(
                        scg, -1, -2)
                else:
                    cache = kvg
                s = jnp.einsum("bhqd,bhsd->bhqs", qt.astype(jnp.float32),
                               cache[0].astype(jnp.float32)) * (hd ** -0.5)
                mask = (jnp.arange(smax)[None, None, None, :]
                        <= (tb[:, None, None, None]
                            + jnp.arange(sq)[None, None, :, None]))
                s = jnp.where(mask, s, -1e30)
                p = jax.nn.softmax(s, axis=-1)
                o = jnp.einsum("bhqs,bhsd->bhqd", p,
                               cache[1].astype(jnp.float32))
                return jnp.swapaxes(o, 1, 2).astype(q.dtype)
            # escape hatch: PADDLE_TPU_STACKED_KERNEL=0 forces the dense
            # path — the stacked kernels' first on-chip Mosaic compile
            # happens inside a driver bench window; a compile failure
            # there must be recoverable without a code change
            if os.environ.get("PADDLE_TPU_STACKED_KERNEL", "1") != "0":
                from ..ops.pallas.decode_attention import (
                    decode_attention_stacked, decode_attention_stacked_i8,
                    stacked_i8_is_supported, stacked_is_supported)
                mp = (1 if mesh is None
                      else dict(mesh.shape).get("mp", 1))
                lens = tb
                cshape = (caches[0] if quant else caches).shape
                if mesh is not None and mp >= 2 and nh % mp == 0 \
                        and cshape[3] % mp == 0:
                    # TP-sharded kernel decode (reference: mp-sharded
                    # heads in fused_multi_transformer_op.cu): attention
                    # is embarrassingly parallel over heads, so shard_map
                    # over 'mp' runs the SAME stacked kernel on each
                    # device's local heads — no collectives, no dense
                    # fallback. A pallas_call can't live under GSPMD
                    # auto-partitioning; shard_map is the manual escape.
                    lshape = cshape[:3] + (cshape[3] // mp,) + cshape[4:]
                    ok = (stacked_i8_is_supported(
                              (q.shape[0], sq, nh // mp, hd), lshape,
                              q.dtype) if quant else
                          stacked_is_supported(
                              (q.shape[0], sq, nh // mp, hd), lshape,
                              q.dtype, cache_dtype=caches.dtype))
                    if ok:
                        from jax import shard_map
                        from jax.sharding import PartitionSpec as SP
                        hsp = SP(None, "mp", None, None)
                        csp = SP(None, None, None, "mp", None, None)
                        # check_vma=False: interpret-mode pallas inside
                        # shard_map trips a jax-0.9 check_vma limit
                        # (same workaround the ring path documents); the
                        # kernel has no collectives, so vma checking
                        # buys nothing here
                        if quant:
                            fn = shard_map(
                                decode_attention_stacked_i8, mesh=mesh,
                                in_specs=(hsp, csp, csp, SP(), SP()),
                                out_specs=hsp, check_vma=False)
                            o = fn(qt, caches[0], caches[1], l, lens)
                        else:
                            fn = shard_map(
                                decode_attention_stacked, mesh=mesh,
                                in_specs=(hsp, csp, SP(), SP()),
                                out_specs=hsp, check_vma=False)
                            o = fn(qt, caches, l, lens)
                        return jnp.swapaxes(o, 1, 2)
                if mesh is None and quant and stacked_i8_is_supported(
                        (q.shape[0], sq, nh, hd), caches[0].shape,
                        q.dtype):
                    o = decode_attention_stacked_i8(qt, caches[0],
                                                    caches[1], l, lens)
                    return jnp.swapaxes(o, 1, 2)
                if mesh is None and not quant and stacked_is_supported(
                        (q.shape[0], sq, nh, hd), caches.shape, q.dtype,
                        cache_dtype=caches.dtype):
                    o = decode_attention_stacked(qt, caches, l, lens)
                    return jnp.swapaxes(o, 1, 2)
            # dense masked fallback — under a mesh the head dim ('mp')
            # shards this einsum Megatron-style; the layer slice fuses
            # into the einsum operand read (no materialized copy)
            if quant:
                ci = jax.lax.dynamic_index_in_dim(caches[0], l, 0,
                                                  keepdims=False)
                sc = jax.lax.dynamic_index_in_dim(caches[1], l, 0,
                                                  keepdims=False)
                # scales are [2, B, H, 1, Smax]; transpose the trailing
                # axes to broadcast per-position over D
                cache = ci.astype(jnp.float32) * jnp.swapaxes(sc, -1, -2)
            else:
                cache = jax.lax.dynamic_index_in_dim(caches, l, 0,
                                                     keepdims=False)
            s = jnp.einsum("bhqd,bhsd->bhqs", qt.astype(jnp.float32),
                           cache[0].astype(jnp.float32)) * (hd ** -0.5)
            # block-causal: query row j (token at position t + j) sees
            # cache cols <= t + j; for Sq == 1 this is the classic mask
            mask = (jnp.arange(smax)[None, None, None, :]
                    <= (tb[:, None, None, None]
                        + jnp.arange(sq)[None, None, :, None]))
            s = jnp.where(mask, s, -1e30)
            p = jax.nn.softmax(s, axis=-1)
            o = jnp.einsum("bhqs,bhsd->bhqd", p,
                           cache[1].astype(jnp.float32))
            return jnp.swapaxes(o, 1, 2).astype(q.dtype)

        def mm_p(a, w, s=None):
            # weight-only int8: dot on the exact int-valued weights
            # (bf16-exact in [-127, 127], fp32 accumulation), then
            # the per-out-channel dequant scale on the [B, O] result.
            # int4 arrives PACKED (two contracted nibbles per int8
            # byte), unambiguous by shape: a packed weight's contracted
            # axis is HALF the activation's — an unpacked int8 weight
            # always matches it exactly.
            if s is not None and w.dtype == jnp.int8 \
                    and 2 * w.shape[0] == a.shape[-1]:
                k2 = w.shape[0]
                if mesh is None:
                    from ..ops.pallas.fused_dequant_matmul import (
                        fused_dequant_matmul,
                        fused_dequant_matmul_is_supported)
                    m_rows = 1
                    for d_ in a.shape[:-1]:
                        m_rows *= d_
                    if fused_dequant_matmul_is_supported(
                            m_rows, a.shape[-1], w.shape[1]):
                        # fused dequant-matmul: bytes stream packed,
                        # nibbles unpack in VMEM, scales fold into the
                        # fp32 accumulator — no unpacked weight copy
                        return fused_dequant_matmul(
                            a, w, s.reshape(1, -1), out_dtype=a.dtype)
                # nibble-split XLA dot (mesh path — a pallas_call
                # cannot live under GSPMD auto-partitioning — and the
                # unsupported-shape fallback): two half-K dots on the
                # sign-extended nibble planes. The activation splits by
                # a [..., K/2, 2] reshape (GSPMD-representable on a
                # row-sharded axis; a stride-2 slice is not), the
                # weight stays packed — still no full unpacked copy at
                # rest, only the in-fusion nibble views.
                lo = jnp.right_shift(jnp.left_shift(w, 4), 4)
                hi = jnp.right_shift(w, 4)
                ar = a.reshape(a.shape[:-1] + (k2, 2))
                out_ = (ar[..., 0] @ lo.astype(a.dtype)
                        + ar[..., 1] @ hi.astype(a.dtype))
                return out_ * s.astype(a.dtype)
            out_ = a @ w.astype(a.dtype)
            return out_ * s.astype(a.dtype) if s is not None else out_

        def qkv_of(h, p):
            # [B, T, E] -> q, k, v [B, T, nh, hd]. Both weight flavors
            # arrive pre-fused HEAD-MAJOR from _stacked ([F, E] with
            # F = nh*3*hd, nh outermost), so one branch serves fp and
            # int8, and the unfuse reshape below keeps the head axis
            # outermost — under tensor parallel the fused axis carries
            # the 'mp' head shard straight through to q/k/v without a
            # weight gather.
            qkv = mm_p(h, p["qkv_w"].T, p.get("qkv_w_s")) + \
                p["qkv_b"].astype(h.dtype)
            qkv = qkv.reshape(h.shape[0], h.shape[1], nh, 3, hd)
            return qkv[:, :, :, 0], qkv[:, :, :, 1], qkv[:, :, :, 2]

        def proj_ffn_tail(residual, attn_flat, p):
            # shared post-attention half of a layer: out-proj + residual
            # + (post-)LN + FFN — shape-agnostic over the token dim, so
            # the per-token step and bulk prefill cannot diverge
            attn = mm_p(attn_flat, p["lin_w"], p.get("lin_w_s")) + \
                p["lin_b"].astype(attn_flat.dtype)
            x = residual + attn
            if not pre_ln:
                x = ln(x, p["ln_s"], p["ln_b"])
            residual = x
            h = ln(x, p["fln_s"], p["fln_b"]) if pre_ln else x
            h = mm_p(h, p["f1_w"], p.get("f1_w_s")) + \
                p["f1_b"].astype(h.dtype)
            h = getattr(jax.nn, act)(h)
            h = mm_p(h, p["f2_w"], p.get("f2_w_s")) + \
                p["f2_b"].astype(h.dtype)
            x = residual + h
            if not pre_ln:
                x = ln(x, p["fln_s"], p["fln_b"])
            return x

        def _write_targets(t, b, write_mask):
            # per-row write positions ([B] int32). Masked-out rows are
            # sent OUT OF BOUNDS (index Smax) so the scatter with
            # mode="drop" skips them entirely — a neighbouring slot's
            # live cache row cannot be touched by construction (the
            # serving engine's in-slot prefill depends on this).
            tv = jnp.broadcast_to(jnp.asarray(t).astype(jnp.int32), (b,))
            if write_mask is not None:
                tv = jnp.where(write_mask, tv, smax)
            return tv

        def _paged_blk_off(tbl, tv, nb):
            # resolve positions tv ([B] or [B, Sq]) through the block
            # table: OOB positions (== smax, the write-mask discipline)
            # and unmapped sentinel entries both land on block `nb` —
            # out of bounds for the pool's block axis, so the scatter
            # with mode="drop" skips them. This is the FIFTH client of
            # the decode_attention `cache_lens < Smax` clamp inventory.
            bt = smax // tbl.shape[1]
            nblk = tbl.shape[1]
            ji = tv // bt
            safe = ji < nblk
            jc = jnp.minimum(ji, nblk - 1)
            if tv.ndim == 1:
                blk = jnp.take_along_axis(tbl, jc[:, None], axis=1)[:, 0]
            else:
                blk = jnp.take_along_axis(tbl, jc, axis=1)
            return jnp.where(safe, blk, nb), tv % bt

        def paged_write(caches, l, tv, kv_new):
            # scatter the new K/V rows through the block table. kv_new:
            # [2, B, H, Sq, D]; tv: [B] (Sq == 1, per-token step) or
            # [B, Sq] (spec-verify block). Same value layouts as the
            # dense per-row scatters, with (block, offset) replacing
            # the ring position.
            pool_kv, tbl = caches["kv"], caches["tbl"]
            nb = pool_kv.shape[2]
            blk, off = _paged_blk_off(tbl, tv, nb)
            if "sc" in caches:
                q_new, sc_new = _absmax_int8(kv_new, -1)
                if tv.ndim == 1:
                    kvq = pool_kv.at[l, :, blk, :, off, :].set(
                        jnp.swapaxes(q_new[:, :, :, 0], 0, 1),
                        mode="drop")
                    scq = caches["sc"].at[l, :, blk, :, 0, off].set(
                        jnp.swapaxes(sc_new[:, :, :, 0, 0], 0, 1),
                        mode="drop")
                else:
                    kvq = pool_kv.at[l, :, blk, :, off, :].set(
                        jnp.transpose(q_new, (1, 3, 0, 2, 4)),
                        mode="drop")
                    scq = caches["sc"].at[l, :, blk, :, 0, off].set(
                        jnp.transpose(sc_new[..., 0], (1, 3, 0, 2)),
                        mode="drop")
                return dict(caches, kv=kvq, sc=scq)
            vals = (jnp.swapaxes(kv_new[:, :, :, 0], 0, 1)
                    if tv.ndim == 1
                    else jnp.transpose(kv_new, (1, 3, 0, 2, 4)))
            return dict(caches, kv=pool_kv.at[l, :, blk, :, off, :].set(
                vals.astype(pool_kv.dtype), mode="drop"))

        def layer_step(x, p, caches, l, t, write_mask=None):
            # one gate for both cache flavors' fused write+attend branch.
            # A masked write (serving's in-slot prefill: only admitted
            # rows may land K/V) always takes the scatter path — the
            # write kernels land every row unconditionally.
            kw_on = (os.environ.get("PADDLE_TPU_KERNEL_CACHE_WRITE",
                                    "0") == "1"
                     and os.environ.get("PADDLE_TPU_STACKED_KERNEL",
                                        "1") != "0"
                     and mesh is None and write_mask is None)
            residual = x
            h = ln(x, p["ln_s"], p["ln_b"]) if pre_ln else x
            b = h.shape[0]
            q, k, v = qkv_of(h, p)
            if use_rotary:
                q = rope1(q, t)
                k = rope1(k, t)
            # write-then-attend: ONE tiny [1, 2, B, H, 1, D] in-place
            # update at (l, :, :, :, t, :) on the scan-carried buffer —
            # the full stack is never copied per step (the old layout
            # emitted the updated cache as stacked scan ys, rewriting the
            # entire [L, 2, B, H, Smax, D] buffer every token)
            kv_new = jnp.stack([jnp.swapaxes(k, 1, 2),
                                jnp.swapaxes(v, 1, 2)])  # [2, B, H, 1, D]
            if isinstance(caches, dict):
                # paged: the K/V row scatters through the slot's block
                # table (write-then-attend, like every other flavor);
                # the fused write+attend kernels stay dense-only — the
                # paged read kernel gathers through the table instead
                caches = paged_write(caches, l,
                                     _write_targets(t, b, write_mask),
                                     kv_new)
                attn = attend(q, caches, l, t)
            elif isinstance(caches, tuple):
                attn = None
                if kw_on:
                    # fused write+attend, int8 flavor: quantizes the new
                    # row IN KERNEL (bit-identical recipe) and lands row
                    # + scale in place — no XLA DUS on either carried
                    # buffer (see the fp branch below for why)
                    from ..ops.pallas.decode_attention import (
                        decode_attention_stacked_i8_write,
                        stacked_i8_write_is_supported)
                    if stacked_i8_write_is_supported(
                            (q.shape[0], 1, nh, hd), caches[0].shape,
                            q.dtype):
                        lens_ = jnp.broadcast_to(
                            jnp.asarray(t).astype(jnp.int32),
                            (q.shape[0],))
                        ci8, scs, o = decode_attention_stacked_i8_write(
                            jnp.swapaxes(q, 1, 2), kv_new, caches[0],
                            caches[1], l, lens_)
                        caches = (ci8, scs)
                        attn = jnp.swapaxes(o, 1, 2)
                if attn is None:
                    # cache-quant write: per-row absmax int8 + fp32 scale
                    q_new, sc_new = _absmax_int8(kv_new, -1)
                    if jnp.ndim(t) == 0 and write_mask is None:
                        ci8 = jax.lax.dynamic_update_slice(
                            caches[0], q_new[None], (l, 0, 0, 0, t, 0))
                        # scale layout is [L, 2, B, H, 1, Smax]: position
                        # on the last axis, so this token's scales land
                        # at [..., 0, t]
                        scs = jax.lax.dynamic_update_slice(
                            caches[1], sc_new[None], (l, 0, 0, 0, 0, t))
                    else:
                        # per-row positions (serving): one scatter of B
                        # rows; masked/OOB rows are dropped
                        tv = _write_targets(t, b, write_mask)
                        bi = jnp.arange(b)
                        ci8 = caches[0].at[l, :, bi, :, tv, :].set(
                            jnp.swapaxes(q_new[:, :, :, 0], 0, 1),
                            mode="drop")
                        scs = caches[1].at[l, :, bi, :, 0, tv].set(
                            jnp.swapaxes(sc_new[:, :, :, 0, 0], 0, 1),
                            mode="drop")
                    caches = (ci8, scs)
                    attn = attend(q, caches, l, t)
            else:
                attn = None
                if kw_on:
                    # fused write+attend: the kernel lands the new K/V
                    # row in place (input_output_aliases) and attends in
                    # one pass — no XLA-side dynamic_update_slice on the
                    # scan carry, so copy-insertion can never
                    # materialize a full-cache copy
                    from ..ops.pallas.decode_attention import (
                        decode_attention_stacked_write,
                        stacked_write_is_supported)
                    if stacked_write_is_supported(
                            (q.shape[0], 1, nh, hd), caches.shape,
                            q.dtype, cache_dtype=caches.dtype):
                        lens_ = jnp.broadcast_to(
                            jnp.asarray(t).astype(jnp.int32),
                            (q.shape[0],))
                        caches, o = decode_attention_stacked_write(
                            jnp.swapaxes(q, 1, 2),
                            kv_new.astype(caches.dtype), caches, l,
                            lens_)
                        attn = jnp.swapaxes(o, 1, 2)
                if attn is None:
                    if jnp.ndim(t) == 0 and write_mask is None:
                        caches = jax.lax.dynamic_update_slice(
                            caches, kv_new[None].astype(caches.dtype),
                            (l, 0, 0, 0, t, 0))
                    else:
                        tv = _write_targets(t, b, write_mask)
                        caches = caches.at[
                            l, :, jnp.arange(b), :, tv, :].set(
                            jnp.swapaxes(kv_new[:, :, :, 0], 0, 1).astype(
                                caches.dtype), mode="drop")
                    attn = attend(q, caches, l, t)
            return proj_ffn_tail(residual, attn.reshape(b, 1, nh * hd),
                                 p), caches

        def spec_layer_step(x, p, caches, l, lens, wmask):
            # one layer of the speculative-decoding VERIFY block: Sq =
            # K+1 tokens land their K/V at per-(row, offset) positions
            # lens[b] + j (write-then-attend, like the per-token step),
            # then ONE block-causal attend covers prefix + draft — the
            # whole block costs one weight stream instead of K+1 scan
            # iterations. wmask [B, Sq]: masked positions scatter out of
            # bounds and are dropped (same discipline as the masked-scan
            # prefill), so a draft past the ring clamp or an inactive
            # slot can never write; their garbage logits are discarded
            # by the host and their cache positions are rewritten before
            # ever becoming attendable (write-then-attend at the next
            # step's advanced lens).
            residual = x
            h = ln(x, p["ln_s"], p["ln_b"]) if pre_ln else x
            b, kp = h.shape[0], h.shape[1]
            q, k, v = qkv_of(h, p)
            offs = jnp.arange(kp, dtype=jnp.int32)[None, :]
            t2 = lens[:, None] + offs                       # [B, Sq]
            if use_rotary:
                q = rope_block(q, t2)
                k = rope_block(k, t2)
            kv_new = jnp.stack([jnp.swapaxes(k, 1, 2),
                                jnp.swapaxes(v, 1, 2)])  # [2, B, H, Sq, D]
            tv = jnp.where(wmask, t2, smax)              # OOB -> dropped
            bi = jnp.arange(b)[:, None]
            if isinstance(caches, dict):
                # paged verify writes: the whole K+1 block scatters
                # through the block table (masked positions -> the
                # sentinel block, dropped — same discipline as dense)
                caches = paged_write(caches, l, tv, kv_new)
                attn = attend(q, caches, l, lens)
                return proj_ffn_tail(
                    residual, attn.reshape(b, kp, nh * hd), p), caches
            if isinstance(caches, tuple):
                q_new, sc_new = _absmax_int8(kv_new, -1)
                ci8 = caches[0].at[l, :, bi, :, tv, :].set(
                    jnp.transpose(q_new, (1, 3, 0, 2, 4)), mode="drop")
                scs = caches[1].at[l, :, bi, :, 0, tv].set(
                    jnp.transpose(sc_new[..., 0], (1, 3, 0, 2)),
                    mode="drop")
                caches = (ci8, scs)
            else:
                caches = caches.at[l, :, bi, :, tv, :].set(
                    jnp.transpose(kv_new, (1, 3, 0, 2, 4)).astype(
                        caches.dtype), mode="drop")
            attn = attend(q, caches, l, lens)
            return proj_ffn_tail(residual, attn.reshape(b, kp, nh * hd),
                                 p), caches

        def flat_write(caches, l, tslot, tpos, kv_new, b):
            # scatter the flat stream's K/V rows to (slot, pos) — the
            # SEVENTH `cache_lens < Smax` clamp client (see
            # decode_attention.py's inventory): pad tokens carry the
            # slot SENTINEL b, which resolves to an out-of-bounds batch
            # index (dense) or the pool's sentinel block (paged), and
            # mode="drop" skips them; real positions are < Smax by the
            # packer's budget arithmetic. kv_new: [2, 1, H, T, D].
            vals = jnp.transpose(kv_new[:, 0], (2, 0, 1, 3))  # [T,2,H,D]
            if isinstance(caches, dict):
                pool_kv, tbl = caches["kv"], caches["tbl"]
                nb = pool_kv.shape[2]
                bt = pool_kv.shape[4]
                nblk = tbl.shape[1]
                ji = tpos // bt
                safe = (tslot < b) & (ji < nblk)
                rows = jnp.take(tbl, jnp.minimum(tslot, b - 1), axis=0)
                blk = jnp.take_along_axis(
                    rows, jnp.minimum(ji, nblk - 1)[:, None],
                    axis=1)[:, 0]
                blk = jnp.where(safe, blk, nb)
                off = tpos % bt
                if "sc" in caches:
                    q_new, sc_new = _absmax_int8(kv_new, -1)
                    kvq = pool_kv.at[l, :, blk, :, off, :].set(
                        jnp.transpose(q_new[:, 0], (2, 0, 1, 3)),
                        mode="drop")
                    scq = caches["sc"].at[l, :, blk, :, 0, off].set(
                        jnp.transpose(sc_new[:, 0, :, :, 0], (2, 0, 1)),
                        mode="drop")
                    return dict(caches, kv=kvq, sc=scq)
                return dict(caches, kv=pool_kv.at[
                    l, :, blk, :, off, :].set(
                    vals.astype(pool_kv.dtype), mode="drop"))
            sl = jnp.minimum(tslot, b - 1)
            tv = jnp.where(tslot < b, tpos, smax)    # OOB -> dropped
            if isinstance(caches, tuple):
                q_new, sc_new = _absmax_int8(kv_new, -1)
                ci8 = caches[0].at[l, :, sl, :, tv, :].set(
                    jnp.transpose(q_new[:, 0], (2, 0, 1, 3)),
                    mode="drop")
                scs = caches[1].at[l, :, sl, :, 0, tv].set(
                    jnp.transpose(sc_new[:, 0, :, :, 0], (2, 0, 1)),
                    mode="drop")
                return (ci8, scs)
            return caches.at[l, :, sl, :, tv, :].set(
                vals.astype(caches.dtype), mode="drop")

        def flat_attend_seg(q_s, caches, l, sslot, spos, cmeta, b):
            # the SEGMENT region's ragged block-flash attend: q_s
            # [Ts, H, D] — aligned single-slot chunks of prefill /
            # draft segments; each token attends its OWN slot's cache
            # positions <= its position. Paged pools take the flat
            # Pallas kernel in BOTH flavors — fp pools the fp kernel,
            # int8 pools decode_attention_paged_flat_i8 (in-kernel
            # dequant of the pool + its mirrored scales; per-chunk
            # metadata rides as scalar prefetch, and under a mesh
            # either flavor runs per-shard via shard_map over the head
            # axis); everything else (dense rings, unsupported shapes,
            # opt-out) goes through the gather-through-table dense
            # fallback — the parity path.
            ts_ = q_s.shape[0]
            paged = isinstance(caches, dict)
            quant = isinstance(caches, tuple) or (paged and
                                                  "sc" in caches)
            if paged:
                pool_kv, tbl = caches["kv"], caches["tbl"]
                if (os.environ.get("PADDLE_TPU_STACKED_KERNEL", "1")
                        != "0"):
                    from ..ops.pallas.decode_attention import (
                        decode_attention_paged_flat,
                        decode_attention_paged_flat_i8,
                        paged_flat_i8_is_supported,
                        paged_flat_is_supported)
                    mp = (1 if mesh is None
                          else dict(mesh.shape).get("mp", 1))
                    if mesh is not None and mp >= 2 and nh % mp == 0 \
                            and pool_kv.shape[3] % mp == 0:
                        # head-sharded flat kernel: per-chunk metadata
                        # and the block table are replicated, the pool
                        # (and in cache-quant mode its scales) shards
                        # by head — shard_map over 'mp' with no
                        # collectives (see attend() for the rationale)
                        lshape = (pool_kv.shape[:3]
                                  + (pool_kv.shape[3] // mp,)
                                  + pool_kv.shape[4:])
                        ok = (paged_flat_i8_is_supported(
                                  ts_, nh // mp, hd, lshape, q_s.dtype)
                              if quant else
                              paged_flat_is_supported(
                                  ts_, nh // mp, hd, lshape, q_s.dtype,
                                  cache_dtype=pool_kv.dtype))
                        if ok:
                            cslot, cbase, cn = cmeta
                            from jax import shard_map
                            from jax.sharding import PartitionSpec as SP
                            qsp = SP(None, "mp", None)
                            psp = SP(None, None, None, "mp", None, None)
                            if quant:
                                fn = shard_map(
                                    decode_attention_paged_flat_i8,
                                    mesh=mesh,
                                    in_specs=(qsp, psp, psp, SP(), SP(),
                                              SP(), SP(), SP()),
                                    out_specs=qsp, check_vma=False)
                                return fn(q_s, pool_kv, caches["sc"],
                                          tbl, jnp.minimum(cslot, b - 1),
                                          cbase, cn, l)
                            fn = shard_map(
                                decode_attention_paged_flat, mesh=mesh,
                                in_specs=(qsp, psp,
                                          SP(), SP(), SP(), SP(), SP()),
                                out_specs=qsp,
                                check_vma=False)
                            o = fn(q_s, pool_kv, tbl,
                                   jnp.minimum(cslot, b - 1), cbase, cn,
                                   l)
                            return o
                    if mesh is None and quant and \
                            paged_flat_i8_is_supported(
                                ts_, nh, hd, pool_kv.shape, q_s.dtype):
                        cslot, cbase, cn = cmeta
                        return decode_attention_paged_flat_i8(
                            q_s, pool_kv, caches["sc"], tbl,
                            jnp.minimum(cslot, b - 1), cbase, cn, l)
                    if mesh is None and not quant and \
                            paged_flat_is_supported(
                                ts_, nh, hd, pool_kv.shape, q_s.dtype,
                                cache_dtype=pool_kv.dtype):
                        cslot, cbase, cn = cmeta
                        o = decode_attention_paged_flat(
                            q_s, pool_kv, tbl,
                            jnp.minimum(cslot, b - 1), cbase, cn, l)
                        return o
                from .paged_kv import flat_gather_view
                pool_l = jax.lax.dynamic_index_in_dim(pool_kv, l, 0,
                                                      keepdims=False)
                sc_l = (jax.lax.dynamic_index_in_dim(
                    caches["sc"], l, 0, keepdims=False)
                    if quant else None)
                kvg = flat_gather_view(pool_l, tbl,
                                       jnp.minimum(sslot, b - 1),
                                       smax, sc_l)  # [2,Ts,H,Smax,D]
            else:
                sl = jnp.minimum(sslot, b - 1)
                if quant:
                    ci = jax.lax.dynamic_index_in_dim(caches[0], l, 0,
                                                      keepdims=False)
                    sc = jax.lax.dynamic_index_in_dim(caches[1], l, 0,
                                                      keepdims=False)
                    kvg = (jnp.take(ci, sl, axis=1).astype(jnp.float32)
                           * jnp.swapaxes(jnp.take(sc, sl, axis=1),
                                          -1, -2))
                else:
                    cache_l = jax.lax.dynamic_index_in_dim(
                        caches, l, 0, keepdims=False)
                    kvg = jnp.take(cache_l, sl, axis=1).astype(
                        jnp.float32)
            s_ = jnp.einsum("thd,thsd->ths",
                            q_s.astype(jnp.float32), kvg[0]) \
                * (hd ** -0.5)
            mask = (jnp.arange(smax)[None, None, :]
                    <= spos[:, None, None])
            s_ = jnp.where(mask, s_, -1e30)
            p = jax.nn.softmax(s_, axis=-1)
            o = jnp.einsum("ths,thsd->thd", p, kvg[1])
            return o.astype(q_s.dtype)

        def flat_layer_step(x, p, caches, l, tslot, tpos, cmeta, b):
            # one layer of the FLAT budget core: the whole ragged [T]
            # stream runs the dense ops as one [1, T, E] pass (T real
            # tokens cost T positions — no [B, C] row padding), K/V
            # scatters to (slot, pos), then attention splits by region:
            # tokens [0, b) are the DECODE region (token i IS slot i —
            # the existing per-token kernels serve it unchanged), the
            # rest are aligned segments through flat_attend_seg.
            residual = x
            h = ln(x, p["ln_s"], p["ln_b"]) if pre_ln else x
            t_all = h.shape[1]
            q, k, v = qkv_of(h, p)                  # [1, T, H, D]
            if use_rotary:
                q = rope_block(q, tpos[None, :])
                k = rope_block(k, tpos[None, :])
            kv_new = jnp.stack([jnp.swapaxes(k, 1, 2),
                                jnp.swapaxes(v, 1, 2)])  # [2,1,H,T,D]
            caches = flat_write(caches, l, tslot, tpos, kv_new, b)
            qd = q[0, :b][:, None]                  # [b, 1, H, D]
            ad = attend(qd, caches, l, tpos[:b])    # [b, 1, H, D]
            parts = [jnp.swapaxes(ad, 0, 1).reshape(1, b, nh * hd)]
            if t_all > b:
                a_s = flat_attend_seg(q[0, b:], caches, l, tslot[b:],
                                      tpos[b:], cmeta, b)
                parts.append(a_s.reshape(1, t_all - b, nh * hd))
            attn = (jnp.concatenate(parts, axis=1)
                    if len(parts) > 1 else parts[0])
            return proj_ffn_tail(residual, attn, p), caches

        embed, head = self.embed, self.head
        e_params, h_params = self._embed_params, self._head_params

        def call_layerlike(fn, params, arrays, x_arr):
            # no_grad: inference-only — must not record onto (or clear!) a
            # caller's pending autograd tape
            with substitute_param_arrays(params, arrays), no_grad():
                out = fn(Tensor(x_arr))
            return out._data if isinstance(out, Tensor) else out

        def shard_caches(caches):
            # pin the carried cache sharding under a mesh so the
            # scan-carried buffer (and its donation round-trip) keeps a
            # stable layout: dense rings / int8 stacks AND the paged
            # pool's kv/sc shard by HEAD on 'mp' (axis 3 in every
            # layout); the paged block table is replicated host
            # metadata re-uploaded per dispatch
            if mesh is None:
                return caches
            from jax.sharding import NamedSharding, PartitionSpec as P
            sh = NamedSharding(mesh,
                               P(None, None, None, "mp", None, None))
            if isinstance(caches, dict):
                out = dict(caches)
                out["kv"] = jax.lax.with_sharding_constraint(
                    caches["kv"], sh)
                if "sc" in caches:
                    out["sc"] = jax.lax.with_sharding_constraint(
                        caches["sc"], sh)
                if "tbl" in caches:
                    out["tbl"] = jax.lax.with_sharding_constraint(
                        caches["tbl"], NamedSharding(mesh, P()))
                return out
            if isinstance(caches, tuple):
                return tuple(jax.lax.with_sharding_constraint(c, sh)
                             for c in caches)
            return jax.lax.with_sharding_constraint(caches, sh)

        def hidden(stk, e_arrays, caches, tok, t, write_mask=None):
            # tok: [B] int32; t: scalar int32 OR [B] per-row positions
            # (serving: each slot decodes at its own depth); caches:
            # [L, 2, B, H, Smax, D] -> (x [B, 1, E], caches) with caches
            # updated at position t (rows where write_mask is False are
            # skipped — attention still runs, the K/V write is dropped).
            # The cache rides the layer scan as CARRY (in-place dynamic
            # updates on one buffer), not as xs->ys (which rewrote the
            # whole stack per token — the r3 decode profile's ~10 ms/token
            # vs ~1 ms bandwidth-floor gap).
            x = call_layerlike(embed, e_params, e_arrays, tok[:, None])
            caches = shard_caches(caches)

            def body(carry, xs):
                x, caches = carry
                p, l = xs
                x, caches = layer_step(x, p, caches, l, t, write_mask)
                return (x, caches), None
            nl = (caches["kv"] if isinstance(caches, dict)
                  else caches[0] if isinstance(caches, tuple)
                  else caches).shape[0]
            (x, caches), _ = jax.lax.scan(
                body, (x, caches), (stk, jnp.arange(nl, dtype=jnp.int32)))
            return x, caches

        def spec_hidden(stk, e_arrays, caches, toks, lens, write_mask):
            # toks: [B, Sq] int32 (position 0 the current input token,
            # 1..K the draft); lens: [B] per-row base positions;
            # write_mask: [B, Sq]. Returns (x [B, Sq, E], caches) — the
            # verify-step hidden core: ONE pass of the layer stack over
            # the whole K+1 block (see spec_layer_step).
            x = call_layerlike(embed, e_params, e_arrays, toks)
            caches = shard_caches(caches)

            def body(carry, xs):
                x, caches = carry
                p, l = xs
                x, caches = spec_layer_step(x, p, caches, l, lens,
                                            write_mask)
                return (x, caches), None
            nl = (caches["kv"] if isinstance(caches, dict)
                  else caches[0] if isinstance(caches, tuple)
                  else caches).shape[0]
            (x, caches), _ = jax.lax.scan(
                body, (x, caches), (stk, jnp.arange(nl, dtype=jnp.int32)))
            return x, caches

        def flat_hidden(stk, e_arrays, caches, toks, tslot, tpos, cmeta,
                        b):
            # toks/tslot/tpos: [T] — the flat budget core's ragged
            # token stream ([0, b) decode region + aligned segments);
            # cmeta: per-chunk (slot, base, n) scalar-prefetch metadata
            # for the flat Pallas kernel. Returns (x [1, T, E], caches)
            # with every valid token's K/V landed at (slot, pos).
            x = call_layerlike(embed, e_params, e_arrays, toks[None, :])
            caches = shard_caches(caches)

            def body(carry, xs):
                x, caches = carry
                p, l = xs
                x, caches = flat_layer_step(x, p, caches, l, tslot,
                                            tpos, cmeta, b)
                return (x, caches), None
            nl = (caches["kv"] if isinstance(caches, dict)
                  else caches[0] if isinstance(caches, tuple)
                  else caches).shape[0]
            (x, caches), _ = jax.lax.scan(
                body, (x, caches), (stk, jnp.arange(nl, dtype=jnp.int32)))
            return x, caches

        def head_logits(h_arrays, x_arr):
            # weight-only int8 LM head (PADDLE_TPU_DECODE_INT8_HEAD):
            # h_arrays arrives as [W_int8, scales(, bias...)] from
            # _maybe_quant_head — detect by dtype (trace-time python,
            # retraced per pytree structure) and apply the same
            # dequant-after-dot factoring as the layer stacks. The head
            # read (~[E, V], 77 MB/token bf16 for GPT-2) is the largest
            # single stream of the decode step.
            if h_arrays and getattr(h_arrays[0], "dtype", None) == \
                    jnp.int8:
                w_q, s = h_arrays[0], h_arrays[1]
                out = (x_arr @ w_q.astype(x_arr.dtype)) * \
                    s.astype(x_arr.dtype)
                if len(h_arrays) > 2:
                    out = out + h_arrays[2].astype(out.dtype)
                return out
            return call_layerlike(head, h_params, h_arrays, x_arr)

        def sample_head(h_arrays, x, key):
            logits = head_logits(h_arrays, x)
            logits = logits.reshape(logits.shape[0], -1)
            logits = _filter_logits(logits, do_sample, top_k, top_p,
                                    temperature)
            if do_sample:
                nxt = jax.random.categorical(key, logits, axis=-1)
            else:
                nxt = jnp.argmax(logits, axis=-1)
            return nxt.astype(jnp.int32)

        def rope_bulk(x, pos):
            # x: [B, S, H, D] at absolute positions pos [S] — the
            # vectorized twin of rope1 (identical math, so bulk prefill
            # writes bit-identical K to the per-token path)
            inv = 1.0 / (rope_base ** (jnp.arange(0, hd, 2,
                                                  dtype=jnp.float32) / hd))
            fr = pos.astype(jnp.float32)[:, None] * inv[None, :]  # [S,D/2]
            s = jnp.concatenate([jnp.sin(fr), jnp.sin(fr)], axis=-1)
            c = jnp.concatenate([jnp.cos(fr), jnp.cos(fr)], axis=-1)
            ss = s[None, :, None, :]
            cc = c[None, :, None, :]
            x1 = x[..., : hd // 2]
            x2 = x[..., hd // 2:]
            rot = jnp.concatenate([-x2, x1], axis=-1)
            return (x * cc.astype(x.dtype) + rot * ss.astype(x.dtype))

        def bulk_hidden(stk, e_arrays, toks):
            """Whole-prompt prefill: embed [B, S], run the layer stack
            with CAUSAL FLASH attention over the full sequence (MXU-fed
            [B,S,E] matmuls instead of the per-token scan's [B,1,E]
            slivers), and return (hidden states [B,S,E],
            kv_all [L,2,B,H,S,D]). The K/V stack comes out as scan ys —
            never a carried buffer — so the caller builds the ring cache
            with ONE pad, no DUS and no aliasing hazard at all. ALL
            positions' hidden states come back (not just the last): the
            serving engine's in-slot bulk admission pads ragged prompts
            to a pow-2 bucket and gathers each row's hidden at its OWN
            last real token."""
            from ..ops.pallas import flash_attention as fa
            x = call_layerlike(embed, e_params, e_arrays, toks)
            S = toks.shape[1]
            pos = jnp.arange(S, dtype=jnp.int32)

            def body(x, p):
                residual = x
                h = ln(x, p["ln_s"], p["ln_b"]) if pre_ln else x
                bsz = h.shape[0]
                q, k, v = qkv_of(h, p)
                if use_rotary:
                    q = rope_bulk(q, pos)
                    k = rope_bulk(k, pos)
                # causal self-attention over the prompt ([B, S, H, D]
                # layout is the flash kernel's own)
                if fa.is_supported(q.shape, q.dtype):
                    o = fa.flash_attention(q, k, v, causal=True)
                else:
                    s_ = jnp.einsum(
                        "bqhd,bkhd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * (hd ** -0.5)
                    m_ = jnp.tril(jnp.ones((S, S), bool))
                    s_ = jnp.where(m_[None, None], s_, -1e30)
                    o = jnp.einsum("bhqk,bkhd->bqhd",
                                   jax.nn.softmax(s_, axis=-1),
                                   v.astype(jnp.float32)).astype(q.dtype)
                x = proj_ffn_tail(residual, o.reshape(bsz, S, nh * hd),
                                  p)
                kv = jnp.stack([jnp.swapaxes(k, 1, 2),
                                jnp.swapaxes(v, 1, 2)])  # [2, B, H, S, D]
                return x, kv

            x, kv_all = jax.lax.scan(body, x, stk)
            return x, kv_all

        def step(stk, e_arrays, h_arrays, caches, tok, t, key):
            x, caches = hidden(stk, e_arrays, caches, tok, t)
            return sample_head(h_arrays, x, key), caches

        step.hidden = hidden
        step.spec_hidden = spec_hidden
        step.flat_hidden = flat_hidden
        step.bulk_hidden = bulk_hidden
        step.sample_head = sample_head
        step.call_layerlike = call_layerlike
        step.head_logits = head_logits
        return step

    # ------------------------------------------------ speculative decoding
    def _build_verify_core(self, k, rep_on=False, greedy_out=False):
        """The speculative-decoding VERIFY step (Leviathan et al. 2023;
        drafts come from the model-free n-gram lookup in spec_decode.py):
        ONE compiled fixed-shape step runs K+1 positions per row through
        the stack — position 0 is the row's current input token, 1..K the
        draft — using the per-row-position vector-t + write-masked KV
        path (same discipline as the masked-scan prefill; every landed
        write stays under the `cache_lens < Smax` clamp documented in
        decode_attention.py because masked positions scatter out of
        bounds and drop). It returns the PENALIZED logits at all K+1
        positions so acceptance/rollback is pure host data: rejected
        positions' K/V are never attendable (the next step's writes land
        at the advanced lens BEFORE those positions are read —
        write-then-attend), and `cache_lens` advances by accepted+1
        only, entirely host-side.

        Per-slot eos / min_length / repetition_penalty vectorize across
        the block: position j is penalized as the (nt+j)-th generated
        token, with the presence mask speculatively extended by the
        draft tokens consumed at positions <= j (the host discards the
        speculative presence and re-applies only accepted tokens).

        A row with no usable draft rides in as a padded all-masked draft
        (dlen == 0) and the step degrades to the normal decode step for
        that row — one executable for every draft pattern, zero retraces
        across churn. Signature (all [B] unless noted): (stk, e_arrays,
        h_arrays, caches, toks [B, K+1], lens, dlen, active, nt,
        eos_ids, min_len, rep_pen, presence [B, V] or placeholder) ->
        (caches, logits [B, K+1, V]).

        greedy_out=True: greedy acceptance only consumes the argmax
        chain, so the step returns [B, K+1] int32 argmax instead of the
        logits — at production vocab sizes that drops the per-step
        device-to-host transfer from ~MBs to bytes."""
        core = self._build_step_core(False, 0, 1.0, 1.0)
        spec_hidden, head_logits = core.spec_hidden, core.head_logits
        smax = self.smax
        kp = int(k) + 1

        def verify(stk, e_arrays, h_arrays, caches, toks, lens, dlen,
                   active, nt, eos_ids, min_len, rep_pen, presence):
            offs = jnp.arange(kp, dtype=jnp.int32)[None, :]     # [1, Kp]
            t2 = lens[:, None] + offs                           # [B, Kp]
            valid = (active[:, None] & (offs <= dlen[:, None])
                     & (t2 < smax))
            x, caches = spec_hidden(stk, e_arrays, caches, toks, lens,
                                    valid)
            logits = head_logits(h_arrays, x)
            logits = logits.reshape(logits.shape[0], kp, -1)
            v = logits.shape[-1]
            if rep_on:
                # speculative presence: position j's context includes
                # the draft tokens consumed at positions <= j (cumulative
                # one-hot OR, masked to valid positions) on top of the
                # carried presence — matches the sequential step's
                # token-by-token presence updates exactly
                oh = (jax.nn.one_hot(toks, v, dtype=jnp.int32)
                      * valid[..., None].astype(jnp.int32))
                seen = (jnp.cumsum(oh, axis=1) > 0) | presence[:, None, :]
                pen = rep_pen[:, None, None]
                logits = jnp.where(
                    seen,
                    jnp.where(logits > 0, logits / pen, logits * pen),
                    logits)
            cols = jnp.arange(v)[None, None, :]
            is_eos = cols == eos_ids[:, None, None]
            suppress = is_eos & ((nt[:, None] + offs)
                                 < min_len[:, None])[..., None]
            logits = jnp.where(suppress, -1e30, logits)
            if greedy_out:
                return caches, jnp.argmax(logits, axis=-1).astype(
                    jnp.int32)
            return caches, logits
        return verify

    # ------------------------------------------------ token-budget step
    def _build_budget_core(self, c, rep_on=False, do_sample=False,
                           top_k=0, top_p=1.0, temperature=1.0,
                           full_logits=False, chain=False, scan_tail=0):
        """The unified TOKEN-BUDGET step (Sarathi-style chunked prefill:
        every dispatch spends a fixed token budget mixing decode tokens
        and prefill chunks, so a long prompt streams through spare
        capacity instead of holding the decode gang hostage): ONE
        compiled [B, C]-column pass generalizing the spec-verify core to
        per-row SEGMENT lengths. Row b processes `seg[b]` real tokens
        starting at its own base position `lens[b]` — a decode row's
        segment is its current input token plus any draft tokens (spec
        decoding is just another claim on the budget), a prefilling
        row's segment is its next prompt chunk (teacher-forced), an idle
        row ships seg == 0 and rides all-masked. Everything per-row is
        DATA, so one executable covers every packing the scheduler can
        emit — zero retraces across admission/prefill/decode/draft
        churn.

        `gen0[b]` is the column index at which row b's GENERATION
        starts: 0 for decode rows, seg-1 for a prefill row finishing its
        prompt this dispatch (the last prompt token's logits sample the
        first generated token), C (never) for a mid-prompt chunk.
        Position j is penalized as the (nt + max(0, j - gen0))-th
        generated token; columns before gen0 are teacher-forced and
        their outputs are discarded by the host.

        Write discipline is the verify core's: K/V for the whole block
        scatters through `valid = (col < seg) & (pos < Smax)` — masked
        positions go out of bounds and drop (the `cache_lens < Smax`
        clamp inventory in decode_attention.py; this step rides the
        same spec_hidden path as the verify core), then one block-causal
        attend covers prefix + segment.

        Output (by static engine config): without spec (chain=False)
        the ONLY block logits any consumer reads are each row's LAST
        valid column's, so the core gathers that one hidden state per
        row BEFORE the LM head ([B, E] through the head instead of
        [B, C, V] — the head is the largest stream of the step, and at
        C columns the full-chain head would cost C x the decode
        step's), samples [B] tokens (argmax, or _sample_rows in
        sampled mode — scheduling-invariant), and then runs
        `scan_tail` TRAILING DECODE iterations in the SAME dispatch
        (the decode-chunk scan body verbatim): rows that are decoding
        — including a row whose prompt just finished in this very
        block — keep emitting `decode_chunk` tokens per dispatch while
        prefill streams, so mixed steps never slow decode below the
        plain chunk. Returns (caches, tok0 [B], emit0 [B] bool,
        ys (toks, emitted) [scan_tail, B], lens, active, nt, presence)
        with ALL row state advanced on device, like the decode chunk.
        With spec (chain=True) draft acceptance needs all segment
        positions: greedy -> the [B, C] argmax chain, sampled
        (full_logits=True) -> penalized logits [B, C, V] for host-side
        rejection sampling (no trailing scan — accepted drafts already
        make the block multi-token)."""
        from .serving import _penalize_slots
        core = self._build_step_core(False, 0, 1.0, 1.0)
        spec_hidden, head_logits = core.spec_hidden, core.head_logits
        hidden = core.hidden
        smax = self.smax
        c = int(c)
        nscan = int(scan_tail)
        tail = _make_budget_tail(hidden, head_logits, _penalize_slots,
                                 rep_on, do_sample, top_k, top_p,
                                 temperature, nscan)

        def budget(stk, e_arrays, h_arrays, caches, toks, lens, seg,
                   gen0, nt, max_nt, eos_ids, min_len, rep_pen,
                   presence, seeds):
            offs = jnp.arange(c, dtype=jnp.int32)[None, :]      # [1, C]
            t2 = lens[:, None] + offs                           # [B, C]
            valid = (offs < seg[:, None]) & (t2 < smax)
            x, caches = spec_hidden(stk, e_arrays, caches, toks, lens,
                                    valid)
            if not chain:
                # per-row gather at the last valid column, THEN the
                # head: position seg-1 is a row's only consumed block
                # output (its generated-token count there is exactly
                # nt, so the per-slot penalty helper applies verbatim —
                # the head being per-position linear, gather-then-head
                # is bit-identical to head-then-gather)
                last = jnp.maximum(seg - 1, 0)
                xl = jnp.take_along_axis(x, last[:, None, None],
                                         axis=1)
                logits = head_logits(h_arrays, xl)
                logits = logits.reshape(logits.shape[0], -1)
                logits = _penalize_slots(
                    logits, presence if rep_on else None, rep_pen, nt,
                    min_len, eos_ids)
                tok0 = _sample_rows(logits, do_sample, top_k, top_p,
                                    temperature, seeds, nt)
                # block bookkeeping, all vectorized: a row emitted iff
                # its segment reached generation (decode rows always;
                # a prefill row only when the prompt finished here)
                emit0 = (seg > 0) & (gen0 < seg)
                hit_eos = (eos_ids >= 0) & (tok0 == eos_ids)
                lens = lens + seg                # consumed positions
                nt = nt + emit0.astype(jnp.int32)
                active = emit0 & ~hit_eos & (nt < max_nt)
                tok = jnp.where(emit0, tok0, toks[:, 0])
                if rep_on:
                    presence = presence.at[
                        jnp.arange(tok0.shape[0]), tok0].max(emit0)

                (tok, caches, lens, active, nt, presence), ys = tail(
                    stk, e_arrays, h_arrays, tok, caches, lens, active,
                    nt, presence, max_nt, eos_ids, min_len, rep_pen,
                    seeds)
                return (caches, tok0, emit0, ys, tok, lens, active, nt,
                        presence)
            logits = head_logits(h_arrays, x)
            logits = logits.reshape(logits.shape[0], c, -1)
            v = logits.shape[-1]
            if rep_on:
                # speculative presence, as in the verify core: position
                # j's context adds the segment tokens consumed at
                # columns <= j (prompt tokens are already in the carried
                # presence — admission seeds it with the full prompt —
                # so the cumulative OR only really adds draft tokens)
                oh = (jax.nn.one_hot(toks, v, dtype=jnp.int32)
                      * valid[..., None].astype(jnp.int32))
                seen = (jnp.cumsum(oh, axis=1) > 0) | presence[:, None, :]
                pen = rep_pen[:, None, None]
                logits = jnp.where(
                    seen,
                    jnp.where(logits > 0, logits / pen, logits * pen),
                    logits)
            nt_eff = nt[:, None] + jnp.maximum(offs - gen0[:, None], 0)
            cols = jnp.arange(v)[None, None, :]
            is_eos = cols == eos_ids[:, None, None]
            suppress = is_eos & (nt_eff < min_len[:, None])[..., None]
            logits = jnp.where(suppress, -1e30, logits)
            if full_logits:
                return caches, logits
            return caches, jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return budget

    # --------------------------------------------- flat token-budget step
    def _build_flat_budget_core(self, ts, b, rep_on=False,
                                do_sample=False, top_k=0, top_p=1.0,
                                temperature=1.0, full_logits=False,
                                chain=False, scan_tail=0):
        """The TOKEN-FLATTENED budget step (sibling of
        _build_budget_core, Sarathi's token-flattened batch): instead
        of the row-aligned [B, C] block — which computes every masked
        column, wasting (B-1) x C positions on a lone long prefill —
        the dispatch is ONE ragged [T] token stream: T = b + ts, where
        tokens [0, b) are the DECODE REGION (token i is slot i's
        current input when the slot decodes draft-free this dispatch;
        idle slots ride the SENTINEL b) and tokens [b, b+ts) are
        SEGMENTS (prefill chunks, spec draft claims) packed
        back-to-back with starts aligned to decode_attention.FLAT_CHUNK
        so the flat Pallas kernel's query chunks are single-slot. Every
        per-token datum — (slot, pos), segment columns, chunk metadata
        — is DATA; ts comes from the packer's eighth-octave ladder, so
        the executable set is bounded and churn retraces nothing after the
        ladder warms.

        A prefill segment is NOT capped at C columns: one segment can
        span the whole remaining budget, so a long prompt streams
        budget-sized chunks per dispatch instead of C-sized ones — the
        flat layout's second win beyond dropping the row padding.

        K/V writes scatter per token to (slot, pos) with the sentinel/
        OOB drop discipline (the SEVENTH `cache_lens < Smax` clamp
        client — decode_attention.py's inventory); sampling gathers
        each slot's LAST valid hidden state (`last_idx`, the PR 7
        gather-then-head trick generalized from per-row to
        per-segment) before the LM head and draws via _sample_rows
        keyed on fold_in(seed, nt) — per-token, never per-layout, so
        flat outputs are EXACTLY the row core's, greedy and sampled.
        Without spec the same trailing decode scan (`scan_tail`,
        shared builder) follows; with spec (chain=True) the core
        returns the whole stream's argmax chain (or penalized logits
        [T, V] with full_logits) and the host slices each slot's
        segment for acceptance — draft claims are just flat segments.

        Signature (operands beyond the row core's: tslot/tpos [T] the
        per-token indices, cslot/cbase/cn [T/FLAT_CHUNK] the kernel's
        chunk metadata, tcol/tstart [T] per-token segment columns and
        segment-start stream indices for the chain penalties, tok_in/
        last_idx/emit0/adv [B] the per-slot harvest vectors)."""
        from .serving import _penalize_slots
        core = self._build_step_core(False, 0, 1.0, 1.0)
        flat_hidden, head_logits = core.flat_hidden, core.head_logits
        hidden = core.hidden
        b = int(b)
        nscan = int(scan_tail)
        tail = _make_budget_tail(hidden, head_logits, _penalize_slots,
                                 rep_on, do_sample, top_k, top_p,
                                 temperature, nscan)

        def flat_budget(stk, e_arrays, h_arrays, caches, toks, tslot,
                        tpos, cslot, cbase, cn, tcol, tstart, gen0,
                        tok_in, last_idx, emit0, adv, lens, nt, max_nt,
                        eos_ids, min_len, rep_pen, presence, seeds):
            x, caches = flat_hidden(stk, e_arrays, caches, toks, tslot,
                                    tpos, (cslot, cbase, cn), b)
            if not chain:
                # gather-then-head at each slot's last valid stream
                # index (bit-identical to head-then-gather: the head is
                # per-position linear), then the row core's block
                # bookkeeping verbatim — emit0/adv arrive as data from
                # the packer instead of being derived from seg/gen0
                xl = jnp.take(x[0], last_idx, axis=0)[:, None]
                logits = head_logits(h_arrays, xl)
                logits = logits.reshape(logits.shape[0], -1)
                logits = _penalize_slots(
                    logits, presence if rep_on else None, rep_pen, nt,
                    min_len, eos_ids)
                tok0 = _sample_rows(logits, do_sample, top_k, top_p,
                                    temperature, seeds, nt)
                hit_eos = (eos_ids >= 0) & (tok0 == eos_ids)
                lens = lens + adv
                nt = nt + emit0.astype(jnp.int32)
                active = emit0 & ~hit_eos & (nt < max_nt)
                tok = jnp.where(emit0, tok0, tok_in)
                if rep_on:
                    presence = presence.at[
                        jnp.arange(tok0.shape[0]), tok0].max(emit0)
                (tok, caches, lens, active, nt, presence), ys = tail(
                    stk, e_arrays, h_arrays, tok, caches, lens, active,
                    nt, presence, max_nt, eos_ids, min_len, rep_pen,
                    seeds)
                return (caches, tok0, emit0, ys, tok, lens, active, nt,
                        presence)
            # chain: per-token outputs over the whole stream for
            # host-side draft acceptance / prefill first-token reads
            logits = head_logits(h_arrays, x)
            logits = logits.reshape(-1, logits.shape[-1])   # [T, V]
            v = logits.shape[-1]
            cl = jnp.minimum(tslot, b - 1)
            valid = tslot < b
            if rep_on:
                # speculative presence, segment-local: the global
                # cumsum minus its value just before each token's
                # segment start isolates the segment's own tokens
                # (counts are monotone), matching the row core's
                # per-row cumulative OR exactly
                oh = (jax.nn.one_hot(toks, v, dtype=jnp.int32)
                      * valid[:, None].astype(jnp.int32))
                cs = jnp.cumsum(oh, axis=0)
                prev = jnp.where(
                    (tstart > 0)[:, None],
                    jnp.take(cs, jnp.maximum(tstart - 1, 0), axis=0),
                    0)
                seen = ((cs - prev) > 0) | jnp.take(presence, cl,
                                                    axis=0)
                pen = jnp.take(rep_pen, cl)[:, None]
                logits = jnp.where(
                    seen,
                    jnp.where(logits > 0, logits / pen, logits * pen),
                    logits)
            nt_eff = jnp.take(nt, cl) + jnp.maximum(
                tcol - jnp.take(gen0, cl), 0)
            cols = jnp.arange(v)[None, :]
            is_eos = cols == jnp.take(eos_ids, cl)[:, None]
            suppress = is_eos & (nt_eff
                                 < jnp.take(min_len, cl))[:, None]
            logits = jnp.where(suppress, -1e30, logits)
            if full_logits:
                return caches, logits
            return caches, jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return flat_budget

    def _generate_beam(self, ids, last_x, caches, stk, e_arrays, h_arrays,
                       max_new_tokens, eos_token_id, k, length_penalty,
                       mesh_now, sk_flag, prompt):
        """Host drive for cache-backed beam search: jitted init (step 1)
        + compiled chunked beam scans; sequence reconstruction and final
        GNMT selection happen here by backtracking the recorded lineage.
        Selection semantics replicate _beam_search exactly (finished pool
        with strict-> admission, live beams length-penalized at the first
        all-finished step)."""
        b = ids.shape[0]
        eos = None if eos_token_id is None else int(eos_token_id)
        ikey = ("beam_init", k, eos, length_penalty, mesh_now)
        init = self._scan_cache.get(ikey)
        if init is None:
            init = self._build_beam_init(k, eos, length_penalty)
            self._scan_cache[ikey] = init
        ys0 = init(h_arrays, last_x)
        tok1, _, _, finished, scores, gen_len = ys0
        # beams share the prefill cache: replicate B -> B*K on the batch
        # axis (row b*K + j is beam j of batch row b)
        rep = lambda c: jnp.repeat(c, k, axis=2)            # noqa: E731
        caches = (tuple(rep(c) for c in caches)
                  if isinstance(caches, tuple) else rep(caches))
        hist = [tuple(np.asarray(a)[None] if a.ndim == 2 else
                      np.asarray(a) for a in ys0)]
        last_flat = tok1.reshape(-1)
        # the first generated token's KV is written when it is consumed
        # as the next step's INPUT at slot `prompt` (same convention as
        # the greedy drive) — prompt+1 here would leave slot `prompt`
        # all-zeros yet attendable and clamp the final write off the end
        t0 = prompt
        remaining = max_new_tokens - 1
        cap = int(os.environ.get("PADDLE_TPU_DECODE_CHUNK", "0")) or (
            8 if eos is not None else 64)
        # static shared-prefix split: largest power of two <= prompt
        # (bounded executable variants); below 64 the saving is noise
        split = 0
        if prompt >= 64:
            split = 1 << (int(prompt).bit_length() - 1)
        while remaining > 0:
            if eos is not None and bool(jnp.all(finished)):
                break
            chunk = cap
            while chunk > remaining:
                chunk //= 2
            key = ("beam", k, chunk, eos, length_penalty, mesh_now,
                   sk_flag, split)
            step = self._scan_cache.get(key)
            if step is None:
                step = self._build_beam_scan(k, chunk, eos,
                                             length_penalty, split)
                self._scan_cache[key] = step
            caches, last_flat, scores, finished, gen_len, ys = step(
                stk, e_arrays, h_arrays, caches, last_flat,
                jnp.asarray(t0, jnp.int32), scores, finished, gen_len)
            hist.append(tuple(np.asarray(a) for a in ys))
            t0 += chunk
            remaining -= chunk
        toks, bidx, fin_sc, fin_fl, sc_h, gl_h = (
            np.concatenate([h[i] for h in hist]) for i in range(6))
        T = toks.shape[0]
        all_fin = fin_fl.all(axis=(1, 2))
        t_stop = int(np.argmax(all_fin)) if all_fin.any() else T - 1

        def backtrack(t, row, beam):
            seq = np.empty(t + 1, np.int64)
            cur = beam
            for s in range(t, -1, -1):
                seq[s] = toks[s, row, cur]
                cur = bidx[s, row, cur]
            return seq

        norm = (sc_h[t_stop] /
                np.maximum(gl_h[t_stop], 1).astype(np.float32)
                ** length_penalty)
        ids_np = np.asarray(ids)
        out = np.empty((b, prompt + t_stop + 1), ids_np.dtype)
        out[:, :prompt] = ids_np
        for row in range(b):
            best = int(np.argmax(norm[row]))
            seq = backtrack(t_stop, row, best)
            if eos is not None:
                pool = fin_sc[:t_stop + 1, row]            # [T', K]
                if pool.max() > norm[row, best]:
                    t_f, k_f = np.unravel_index(int(np.argmax(pool)),
                                                pool.shape)
                    fin = backtrack(t_f, row, k_f)
                    seq = np.concatenate(
                        [fin, np.full(t_stop - t_f, eos, np.int64)])
            out[row, prompt:] = seq
        return Tensor(jnp.asarray(out))

    def _generate_spec(self, ids, caches, stk, e_arrays, h_arrays, first,
                       max_new_tokens, eos, do_sample, top_k, top_p,
                       temperature, min_length, repetition_penalty,
                       presence, k, prompt, mesh_now, sk_flag):
        """Host drive for speculative decoding over the compiled verify
        core: per-row NGramDrafter proposals -> ONE fixed-shape K+1
        verify step -> host acceptance (greedy exact-match / rejection
        sampling with the bonus-token resample) -> rollback as pure
        data. Rows accept independently, so per-row positions diverge —
        all bookkeeping is host vectors over the vector-t step, and the
        output is assembled with the chunked path's semantics (rows
        that finish early are eos-padded to the last finisher)."""
        from .spec_decode import (NGramDrafter, filtered_probs,
                                  greedy_accept, rejection_sample,
                                  truncate_emitted)
        b = ids.shape[0]
        rep_on = repetition_penalty != 1.0
        prompt_np = np.asarray(ids)
        first = np.asarray(first)
        rows = [[int(first[r])] for r in range(b)]
        drafters = []
        for r in range(b):
            d = NGramDrafter(k)
            d.reset(prompt_np[r])
            d.update(rows[r])
            drafters.append(d)
        lens = np.full(b, prompt, np.int32)
        nt = np.ones(b, np.int32)
        finished = ((first == eos) if eos is not None
                    else np.zeros(b, bool))
        eos_vec = jnp.full(b, -1 if eos is None else eos, jnp.int32)
        min_vec = jnp.full(b, int(min_length), jnp.int32)
        rp_vec = jnp.full(b, float(repetition_penalty), jnp.float32)
        vkey = ("verify", k, rep_on, do_sample, mesh_now, sk_flag)
        vstep = self._scan_cache.get(vkey)
        if vstep is None:
            tunneled = bool(os.environ.get("PALLAS_AXON_POOL_IPS"))
            vstep = jax.jit(
                self._build_verify_core(k, rep_on,
                                        greedy_out=not do_sample),
                donate_argnums=() if tunneled else (3,))
            self._scan_cache[vkey] = vstep
        rng = None
        if do_sample:
            rng = np.random.RandomState(_host_seed(next_key()))
        while True:
            act = ~finished & (nt < max_new_tokens)
            if not act.any():
                break
            drafts = np.zeros((b, k), np.int32)
            dlen = np.zeros(b, np.int32)
            toks = np.zeros((b, k + 1), np.int32)
            for r in range(b):
                toks[r, 0] = rows[r][-1]
                if not act[r]:
                    continue
                d = drafters[r].propose()
                # never speculate past the row's remaining budget: the
                # bonus token always ships, so at most remaining-1
                # drafts are useful — this also keeps every landed
                # write < prompt + max_new_tokens <= Smax
                m = min(int(d.size), int(max_new_tokens - nt[r]) - 1)
                if m > 0:
                    drafts[r, :m] = d[:m]
                    dlen[r] = m
            toks[:, 1:] = drafts
            caches, out = vstep(
                stk, e_arrays, h_arrays, caches, jnp.asarray(toks),
                jnp.asarray(lens), jnp.asarray(dlen), jnp.asarray(act),
                jnp.asarray(nt), eos_vec, min_vec, rp_vec,
                presence if rep_on else jnp.zeros((b, 1), bool))
            # greedy steps return just the [B, K+1] argmax chain (the
            # only thing exact-match acceptance reads); sampling needs
            # the full logits for the rejection test
            out = (np.asarray(out).astype(np.float32) if do_sample
                   else np.asarray(out))
            new_rows, new_cols = [], []
            for r in range(b):
                if not act[r]:
                    continue
                m = int(dlen[r])
                if do_sample:
                    probs = filtered_probs(out[r, :m + 1], top_k, top_p,
                                           temperature)
                    kept, _ = rejection_sample(drafts[r, :m], probs, rng)
                else:
                    kept, _ = greedy_accept(drafts[r, :m],
                                            out[r, :m + 1])
                emitted, hit_eos = truncate_emitted(
                    kept, int(max_new_tokens - nt[r]), eos)
                nt[r] += len(emitted)
                rows[r].extend(emitted)
                lens[r] += len(emitted)
                if hit_eos:
                    finished[r] = True
                drafters[r].update(emitted)
                if rep_on:
                    new_rows.extend([r] * len(emitted))
                    new_cols.extend(emitted)
            if rep_on and new_rows:
                presence = presence.at[jnp.asarray(new_rows),
                                       jnp.asarray(new_cols)].set(True)
        width = max(len(t) for t in rows)
        pad = eos if eos is not None else 0
        out = np.full((b, prompt + width), pad, prompt_np.dtype)
        out[:, :prompt] = prompt_np
        for r in range(b):
            out[r, prompt:prompt + len(rows[r])] = rows[r]
        return Tensor(jnp.asarray(out))

    # --------------------------------------------------------------- drive
    @no_grad()
    def generate(self, input_ids, max_new_tokens=20, eos_token_id=None,
                 do_sample=False, top_k=0, top_p=1.0, temperature=1.0,
                 num_beams=1, length_penalty=1.0, min_length=0,
                 repetition_penalty=1.0, prefix_cache=None, spec_k=0):
        """Prefill the prompt via compiled chunked scans of the hidden
        core (LM head applied once at the end), then run the compiled
        chunked decode. Every device dispatch is a jitted scan — the
        tunnel backend pays a host RPC per dispatch, so nothing runs
        eagerly here. num_beams > 1 runs beam search AGAINST the decode
        cache (see the beam builders above). min_length /
        repetition_penalty apply INSIDE the compiled steps via a [B, V]
        context-presence carry.

        prefix_cache: a ``paddle_tpu.inference.PrefixCache`` (the SAME
        object a ServingEngine may use). The longest published prefix of
        each row is block-copied into the fresh cache instead of being
        recomputed, prefill starts at the adopted offset, and the
        prompt's full blocks are committed back after prefill — repeated
        eval prompts skip their shared-prefix FLOPs across generate()
        calls too. Prefill starts at the MIN adopted length across rows
        (the chunked scan walks one scalar position for the whole
        batch); ignored under an active mesh (the pool carries no
        sharding annotations).

        spec_k: speculative decoding with the model-free n-gram drafter
        (spec_decode.py) and the compiled K+1-position verify step —
        pow-2 validated, 0 disables. Greedy outputs are token-identical
        to spec_k=0; composes with prefix_cache= (prefill is untouched).
        Batch eval loops with repetitive outputs (summarize/echo) emit
        several tokens per verify step."""
        from .spec_decode import validate_spec_k
        spec_k = validate_spec_k(spec_k)
        if spec_k and num_beams > 1:
            raise ValueError(
                "spec_k composes with greedy/sampling generation, not "
                "beam search (a draft has no beam lineage to verify)")
        if num_beams > 1 and do_sample:
            raise ValueError("beam search (num_beams>1) is deterministic; "
                             "do_sample=True is not supported with it")
        rep_on = repetition_penalty != 1.0
        pen_on = bool(min_length) or rep_on
        if pen_on and num_beams > 1:
            raise NotImplementedError(
                "min_length/repetition_penalty with beam search is not "
                "supported; use greedy/sampling generation")
        if rep_on:
            # only the repetition penalty needs the [B, V] presence mask
            # (and therefore a known vocab size); min_length alone works
            # with any head
            from ..nn.layer.common import Linear
            if type(self.head) is not Linear:
                raise NotImplementedError(
                    "repetition_penalty needs a Linear LM head (vocab "
                    "size must be known for the presence mask)")
        ids = input_ids._data if isinstance(input_ids, Tensor) else \
            jnp.asarray(np.asarray(input_ids))
        b, prompt = ids.shape
        assert prompt + max_new_tokens <= self.smax, (
            f"max_seq_len {self.smax} < prompt {prompt} + {max_new_tokens}")
        f = self.fmt
        f.eval()

        # ---- compiled prefill: chunked scans of the hidden core over the
        # prompt (pow-2 chunk ladder, same bounded-compile discipline as
        # decode), then ONE jitted head+sample on the final hidden state
        stk = self._stacked()
        e_arrays = [p._data for p in self._embed_params]
        h_arrays = self._maybe_quant_head(
            [p._data for p in self._head_params])
        toks_tm = jnp.swapaxes(ids.astype(jnp.int32), 0, 1)  # [S, B]
        mesh_now = self._mesh_mp()
        # the stacked-kernel escape hatch is trace-time state: it must be
        # part of every compiled-step cache key, or flipping it after a
        # compile failure would silently reuse the failing trace
        sk_flag = (os.environ.get("PADDLE_TPU_STACKED_KERNEL", "1")
                   + "/kw" + os.environ.get(
                       "PADDLE_TPU_KERNEL_CACHE_WRITE", "0"))
        pc = prefix_cache if mesh_now is None else None
        adopt_len, chains = 0, None
        ids_pc = (np.asarray(ids).astype(np.int32)
                  if pc is not None else None)
        if pc is not None and prompt > 1:
            ms = [pc.lookup(ids_pc[r]) for r in range(b)]
            # one scalar prefill position serves the whole batch, so the
            # adoptable length is the min across rows (b == 1 — the
            # repeated-eval-prompt case — loses nothing)
            n = min(len(mt) for mt in ms)
            if n:
                chains = [mt[:n] for mt in ms]
                adopt_len = n * pc.block_tokens
        if (os.environ.get("PADDLE_TPU_BULK_PREFILL", "0") == "1"
                and mesh_now is None and prompt > 1 and not adopt_len):
            # whole-prompt prefill: causal flash over [B, S], cache built
            # by padding the K/V scan output (see _build_bulk_prefill).
            # One executable per exact prompt length.
            # param dtype is part of the key: a weight swap to a new
            # dtype must rebuild (cache_dtype is baked at build time)
            pkey = ("bulkprefill", prompt, self._int8_cache(),
                    str(self.fmt.qkv_weights[0]._data.dtype))
            pstep = self._scan_cache.get(pkey)
            if pstep is None:
                pstep = self._build_bulk_prefill()
                self._scan_cache[pkey] = pstep
            last_x, caches = pstep(stk, e_arrays, ids.astype(jnp.int32))
            pos = prompt
        else:
            caches = self.init_cache(b)
            pos, last_x = 0, None
            if chains is not None:
                # splat the published prefix blocks into each row, then
                # start the chunked prefill at the adopted offset —
                # lookup() guarantees adopt_len <= prompt - 1, so the
                # loop below always runs and last_x is always produced
                for r, chain in enumerate(chains):
                    pc.store.acquire(chain)
                    try:
                        caches = pc.adopt(caches, r, chain)
                    finally:
                        pc.store.release(chain)
                pos = adopt_len
        while pos < prompt:
            chunk = 64
            while chunk > prompt - pos:
                chunk //= 2
            pkey = ("prefill", mesh_now, chunk, sk_flag)
            pstep = self._scan_cache.get(pkey)
            if pstep is None:
                pstep = self._build_prefill_scan(chunk)
                self._scan_cache[pkey] = pstep
            last_x, caches = pstep(stk, e_arrays, caches,
                                   toks_tm[pos:pos + chunk],
                                   jnp.asarray(pos, jnp.int32))
            pos += chunk
        if pc is not None and prompt >= pc.block_tokens:
            # commit-on-prefill, oneshot flavor: publish each row's full
            # blocks before decode touches (and donates) the cache buffer
            for r in range(b):
                pc.publish(caches, r, ids_pc[r])
        if num_beams > 1:
            return self._generate_beam(
                ids, last_x, caches, stk, e_arrays, h_arrays,
                max_new_tokens, eos_token_id, int(num_beams),
                float(length_penalty), mesh_now, sk_flag, prompt)
        eos_i = None if eos_token_id is None else int(eos_token_id)
        presence = None
        if rep_on:
            vocab = int(self._head_params[0].shape[1])
            presence = _presence_from(ids.astype(jnp.int32), vocab)
        # the head step bakes nt=0, so min_length enters as a BOOL (every
        # positive value compiles identically — avoid recompile churn)
        hkey = ("head", do_sample, top_k, top_p, temperature, mesh_now,
                eos_i if pen_on else None, bool(min_length),
                repetition_penalty)
        hstep = self._scan_cache.get(hkey)
        if hstep is None:
            hstep = self._build_head_sample(do_sample, top_k, top_p,
                                            temperature, eos_i,
                                            bool(min_length),
                                            repetition_penalty)
            self._scan_cache[hkey] = hstep
        hkey_rng = next_key() if do_sample else jax.random.PRNGKey(0)
        if pen_on:
            nxt = hstep(h_arrays, last_x, hkey_rng, presence)
            if rep_on:
                presence = presence.at[jnp.arange(b), nxt].set(True)
        else:
            nxt = hstep(h_arrays, last_x, hkey_rng)

        if spec_k:
            return self._generate_spec(
                ids, caches, stk, e_arrays, h_arrays, nxt,
                max_new_tokens, eos_i, do_sample, top_k, top_p,
                temperature, min_length, repetition_penalty, presence,
                spec_k, prompt, mesh_now, sk_flag)

        # ---- compiled decode: CHUNKED scan dispatch. Without eos, all
        # remaining tokens run in one device program; with eos, fixed-size
        # chunks with on-device finished-masking and a host early-exit
        # check between chunks. Cache key includes the active mesh
        # (entering/leaving an mp mesh must rebuild) and the chunk size.
        # host-side accumulation: ONE [chunk, B] device->host transfer per
        # chunk (not per token); only the last token stays on device as the
        # next dispatch's input
        host_parts = [np.asarray(nxt)[:, None]]
        last_tok = nxt
        finished = jnp.zeros((b,), bool)
        eos = None if eos_token_id is None else int(eos_token_id)
        remaining = max_new_tokens - 1
        if eos is not None:
            finished = finished | (nxt == eos)
            if bool(jnp.all(finished)):
                remaining = 0                 # everything ended at prefill
        # chunk sizes come from a power-of-two ladder so arbitrary
        # max_new_tokens values reuse a bounded set of compiled scan
        # variants (a fresh scan length would otherwise recompile inside
        # the generation loop). eos runs cap the chunk for early exit.
        chunk_env = int(os.environ.get("PADDLE_TPU_DECODE_CHUNK", "0"))
        cap = chunk_env or (8 if eos is not None else 64)
        t0 = prompt
        while remaining > 0:
            chunk = cap
            while chunk > remaining:
                chunk //= 2
            key = (do_sample, top_k, top_p, temperature,
                   self._mesh_mp(), chunk, eos, sk_flag,
                   min_length, repetition_penalty)
            step = self._scan_cache.get(key)
            if step is None:
                step = self._build_scan_step(*key[:4], chunk, eos,
                                             min_length,
                                             repetition_penalty)
                self._scan_cache[key] = step
            # one split per chunk: per-token subkeys ride the scan xs
            base = next_key() if do_sample else jax.random.PRNGKey(0)
            keys = jax.random.split(base, chunk)
            if rep_on:
                ck, caches, finished, presence = step(
                    stk, e_arrays, h_arrays, caches, last_tok,
                    jnp.asarray(t0, jnp.int32), keys, finished,
                    presence,
                    jnp.asarray(t0 - prompt + 1, jnp.int32))
            elif pen_on:
                ck, caches, finished = step(
                    stk, e_arrays, h_arrays, caches, last_tok,
                    jnp.asarray(t0, jnp.int32), keys, finished, None,
                    jnp.asarray(t0 - prompt + 1, jnp.int32))
            else:
                ck, caches, finished = step(
                    stk, e_arrays, h_arrays, caches, last_tok,
                    jnp.asarray(t0, jnp.int32), keys, finished)
            host_parts.append(np.asarray(ck).T)        # [B, chunk]
            last_tok = ck[-1]
            t0 += chunk
            remaining -= chunk
            if eos is not None and bool(jnp.all(finished)):
                break
        out = np.concatenate([np.asarray(ids)] + host_parts, axis=1)
        if eos is not None and bool(jnp.all(finished)):
            # per-token early-stop semantics (matches generate()): the
            # output ends at the step where the LAST row emitted its first
            # eos; any later all-eos padding the chunk produced is trimmed
            gen = out[:, prompt:]
            first_eos = np.argmax(gen == eos, axis=1)   # rows all have one
            out = out[:, : prompt + int(first_eos.max()) + 1]
        return Tensor(out)


def generate_fused(fmt, input_ids, embed, head, max_new_tokens=20,
                   max_seq_len=None, eos_token_id=None, do_sample=False,
                   top_k=0, top_p=1.0, temperature=1.0, use_rotary=False,
                   num_beams=1, length_penalty=1.0, min_length=0,
                   repetition_penalty=1.0, prefix_cache=None, spec_k=0):
    """One-shot driver over FusedDecoder (see class docstring)."""
    ids = input_ids._data if isinstance(input_ids, Tensor) else \
        jnp.asarray(np.asarray(input_ids))
    smax = max_seq_len or ids.shape[1] + max_new_tokens
    dec = FusedDecoder(fmt, embed, head, smax, use_rotary=use_rotary)
    return dec.generate(input_ids, max_new_tokens, eos_token_id, do_sample,
                        top_k, top_p, temperature, num_beams=num_beams,
                        length_penalty=length_penalty,
                        min_length=min_length,
                        repetition_penalty=repetition_penalty,
                        prefix_cache=prefix_cache, spec_k=spec_k)
