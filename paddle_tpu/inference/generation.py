"""Autoregressive generation loops.

Capability parity: the decode driver around
fused_multi_transformer_op.cu (paddle/fluid/operators/fused/) and
PaddleNLP-style `generate()` (greedy / sampling / top-k / top-p).

Two paths:
  * generate(model, ...)        — model-agnostic: re-runs the forward on the
    growing prefix each step (correct for any causal LM; XLA caches one
    executable per prefix-length bucket).
  * generate_fused(fmt, ...)    — FusedMultiTransformer decode: static-shape
    KV ring cache + the Pallas flash-decode kernel
    (paddle_tpu/ops/pallas/decode_attention.py), one compiled step reused
    for every position — the reference's fused decode loop, TPU-style.
"""
from __future__ import annotations

import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.rng import next_key
from ..tensor.tensor import Tensor, no_grad

__all__ = ["generate", "generate_fused", "FusedDecoder"]


def _filter_logits(logits, do_sample, top_k, top_p, temperature):
    if not do_sample:
        return logits
    logits = logits / jnp.maximum(temperature, 1e-6)
    if top_k and top_k > 0:
        kth = jnp.sort(logits, axis=-1)[:, -top_k][:, None]
        logits = jnp.where(logits < kth, -1e30, logits)
    if top_p and top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        cutoff_idx = jnp.sum(cum < top_p, axis=-1, keepdims=True)
        kth = jnp.take_along_axis(sorted_logits, cutoff_idx, axis=-1)
        logits = jnp.where(logits < kth, -1e30, logits)
    return logits


def _sample_next(logits, do_sample, top_k, top_p, temperature, key=None):
    """logits: [B, V] jnp array -> [B] int32 token ids."""
    if not do_sample:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = _filter_logits(logits, do_sample, top_k, top_p, temperature)
    return jax.random.categorical(key if key is not None else next_key(),
                                  logits, axis=-1).astype(jnp.int32)


@no_grad()
def generate(model, input_ids, max_new_tokens: int = 20,
             eos_token_id: Optional[int] = None, do_sample: bool = False,
             top_k: int = 0, top_p: float = 1.0, temperature: float = 1.0,
             num_beams: int = 1, length_penalty: float = 1.0):
    """Causal-LM generation; input_ids [B, S] Tensor/ndarray -> [B, S+T].

    Greedy by default; sampling with top-k/top-p/temperature when
    do_sample=True; beam search when num_beams > 1 (reference:
    generation's beam_search decode strategy / fluid beam_search op —
    length-penalized GNMT scoring, finished beams frozen on eos). Stops
    early only when every sequence (or every beam) emitted eos.
    """
    model.eval()
    ids = input_ids._data if isinstance(input_ids, Tensor) else \
        jnp.asarray(np.asarray(input_ids))
    if num_beams > 1:
        if do_sample:
            raise ValueError("beam search (num_beams>1) is deterministic; "
                             "do_sample=True is not supported with it")
        return _beam_search(model, ids, max_new_tokens, eos_token_id,
                            num_beams, length_penalty)
    finished = jnp.zeros((ids.shape[0],), bool)
    for _ in range(max_new_tokens):
        logits = model(Tensor(ids))
        logits = logits._data if isinstance(logits, Tensor) else logits
        nxt = _sample_next(logits[:, -1], do_sample, top_k, top_p,
                           temperature)
        if eos_token_id is not None:
            nxt = jnp.where(finished, eos_token_id, nxt)
            finished = finished | (nxt == eos_token_id)
        ids = jnp.concatenate([ids, nxt[:, None]], axis=1)
        if eos_token_id is not None and bool(jnp.all(finished)):
            break
    return Tensor(ids)


def _beam_search(model, ids, max_new_tokens, eos_token_id, num_beams,
                 length_penalty):
    """Model-agnostic beam search: re-runs the forward on the growing
    prefix (correct for any causal LM; XLA caches one executable per
    prefix length, shared across steps since all beams batch together).
    Finished beams are frozen: they may only continue with eos at zero
    added score. Final selection is GNMT length-penalized."""
    b, s0 = ids.shape
    k = int(num_beams)
    eos = None if eos_token_id is None else int(eos_token_id)
    beams = jnp.repeat(ids[:, None], k, axis=1)          # [B, K, S]
    # only beam 0 is live at step one, else K identical top picks
    scores = jnp.full((b, k), -1e9, jnp.float32).at[:, 0].set(0.0)
    finished = jnp.zeros((b, k), bool)
    gen_len = jnp.zeros((b, k), jnp.int32)               # generated length
    # separate FINISHED pool (standard beam search): a completed
    # hypothesis must survive even if live continuations transiently
    # out-score it and evict it from the top-k — track the best
    # length-penalized finished sequence per batch row, eos-padded to the
    # current length each step
    best_fin_score = jnp.full((b,), -jnp.inf, jnp.float32)
    best_fin_seq = beams[:, 0]                           # [B, S] placeholder

    for _ in range(max_new_tokens):
        flat = beams.reshape(b * k, beams.shape[-1])
        logits = model(Tensor(flat))
        logits = (logits._data if isinstance(logits, Tensor)
                  else logits)[:, -1]
        v = logits.shape[-1]
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        logp = logp.reshape(b, k, v)
        if eos is not None:
            only_eos = jnp.where(jnp.arange(v)[None, None, :] == eos,
                                 0.0, -jnp.inf)
            logp = jnp.where(finished[..., None], only_eos, logp)
        cand = scores[..., None] + logp                  # [B, K, V]
        top_scores, top_idx = jax.lax.top_k(cand.reshape(b, k * v), k)
        beam_idx = top_idx // v                          # [B, K]
        tok = (top_idx % v).astype(beams.dtype)
        beams = jnp.take_along_axis(beams, beam_idx[..., None], axis=1)
        beams = jnp.concatenate([beams, tok[..., None]], axis=-1)
        finished = jnp.take_along_axis(finished, beam_idx, axis=1)
        gen_len = jnp.take_along_axis(gen_len, beam_idx, axis=1)
        gen_len = jnp.where(finished, gen_len, gen_len + 1)
        scores = top_scores
        if eos is not None:
            newly = ~finished & (tok == eos)
            finished = finished | newly
            # admit newly finished hypotheses into the finished pool
            pen = jnp.maximum(gen_len, 1).astype(jnp.float32) \
                ** length_penalty
            cand_fin = jnp.where(newly, scores / pen, -jnp.inf)
            row_best = jnp.argmax(cand_fin, axis=1)              # [B]
            row_score = jnp.take_along_axis(
                cand_fin, row_best[:, None], axis=1)[:, 0]
            better = row_score > best_fin_score
            best_fin_seq = jnp.concatenate(                       # pad
                [best_fin_seq,
                 jnp.full((b, 1), eos, beams.dtype)], axis=-1)
            chosen = jnp.take_along_axis(
                beams, row_best[:, None, None], axis=1)[:, 0]
            best_fin_seq = jnp.where(better[:, None], chosen,
                                     best_fin_seq)
            best_fin_score = jnp.maximum(best_fin_score, row_score)
            if bool(jnp.all(finished)):
                break

    lp = jnp.maximum(gen_len, 1).astype(jnp.float32) ** length_penalty
    norm = scores / lp
    best = jnp.argmax(norm, axis=1)                      # [B]
    live_score = jnp.take_along_axis(norm, best[:, None], axis=1)[:, 0]
    out = jnp.take_along_axis(
        beams, best[:, None, None], axis=1)[:, 0]
    if eos is not None:
        # pad the finished pool to the final length and take the winner
        pad = out.shape[-1] - best_fin_seq.shape[-1]
        if pad > 0:
            best_fin_seq = jnp.concatenate(
                [best_fin_seq, jnp.full((b, pad), eos, beams.dtype)],
                axis=-1)
        use_fin = best_fin_score > live_score
        out = jnp.where(use_fin[:, None], best_fin_seq, out)
    return Tensor(out)


class FusedDecoder:
    """Compiled multi-layer KV-cache decode around FusedMultiTransformer.

    Parity: the decode driver of fused_multi_transformer_op.cu ::
    FusedMultiTransformerOp — all decoder layers batched into ONE compiled
    step per token. TPU-native realization:
      * the KV cache is a layer-stacked static ring buffer
        [L, 2, B, H, Smax, D] in kernel layout (no per-step transposes or
        reallocation; position is data, so one executable serves every t);
      * the cache is IN-PLACE: it rides the layer scan as carry with one
        tiny dynamic_update_slice per layer (the reference's in-place
        per-step cache write in fused_multi_transformer_op.cu), and the
        Pallas flash-decode kernel reads layer l's blocks straight out of
        the stacked buffer via a scalar-prefetch layer index
        (decode_attention_stacked) — the full stack is never copied per
        token;
      * the layer loop is a lax.scan over stacked layer params — the
        kernel compiles once and streams KV blocks for each layer;
      * under an active mesh with mp >= 2 the attention falls back to a
        dense masked form whose head dimension GSPMD shards over 'mp'
        (TP-sharded decode; the manual shard_map kernel path is a
        follow-up), with caches annotated P(None,None,None,'mp',None,None).

    embed / head are the model's surrounding Layers (token embedding and
    LM head); their params are passed as jit arguments, not baked in.
    """

    def __init__(self, fmt, embed, head, max_seq_len, use_rotary=False,
                 rope_base=10000.0):
        from ..nn.layer.layers import Layer
        self.fmt = fmt
        self.embed = embed
        self.head = head
        # ring capacity rounds up to a 128-multiple: the stacked-cache
        # Pallas kernel tiles Smax exactly (padding the stacked buffer
        # per call would copy every layer), and extra capacity only means
        # a slightly longer ring — callers still get >= max_seq_len
        self.smax = -(-int(max_seq_len) // 128) * 128
        self.use_rotary = use_rotary
        if use_rotary and float(rope_base) != 10000.0:
            raise NotImplementedError(
                "FusedDecoder prefill uses the fused stack's default rotary "
                "base (10000); plumb rotary_emb_base through "
                "fused_multi_transformer before changing it")
        self.rope_base = rope_base
        self._embed_params = list(embed.parameters()) if isinstance(
            embed, Layer) else []
        self._head_params = list(head.parameters()) if isinstance(
            head, Layer) else []
        self._scan_cache = {}      # (sample cfg, mesh, chunk, eos) -> jitted scan
        self._stk_cache = None

    # ------------------------------------------------------------ stacking
    def _stacked(self):
        f = self.fmt
        # hold the source arrays themselves: comparing by identity is only
        # sound while we keep them alive (freed ids get recycled)
        version = [p._data for p in f.parameters()]
        if self._stk_cache is not None and                 len(self._stk_cache[0]) == len(version) and                 all(a is b for a, b in zip(self._stk_cache[0], version)):
            return self._stk_cache[1]

        def stk(plist):
            return jnp.stack([p._data for p in plist])
        out = {
            "ln_s": stk(f.ln_scales), "ln_b": stk(f.ln_biases),
            "qkv_w": stk(f.qkv_weights), "qkv_b": stk(f.qkv_biases),
            "lin_w": stk(f.linear_weights), "lin_b": stk(f.linear_biases),
            "fln_s": stk(f.ffn_ln_scales), "fln_b": stk(f.ffn_ln_biases),
            "f1_w": stk(f.ffn1_weights), "f1_b": stk(f.ffn1_biases),
            "f2_w": stk(f.ffn2_weights), "f2_b": stk(f.ffn2_biases),
        }
        self._stk_cache = (version, out)
        return out

    @staticmethod
    def _int8_cache() -> bool:
        """Opt-in int8 KV cache (reference: fused_multi_transformer's
        cache_kv int8 serving mode). Decode is bandwidth-bound — int8
        halves the cache bytes streamed per token; rows are absmax-
        quantized per (layer, kv, batch, head, position) with fp32
        scales, dequantized in VMEM by the stacked kernel."""
        return os.environ.get("PADDLE_TPU_DECODE_INT8_CACHE") == "1"

    def init_cache(self, batch, dtype=None):
        f = self.fmt
        dtype = dtype or self.fmt.qkv_weights[0]._data.dtype
        shape = (f.num_layers, 2, batch, f.num_heads, self.smax,
                 f.head_dim)
        if self._int8_cache():
            if self._mesh_mp() is not None:
                # the int8 win is the stacked KERNEL streaming half the
                # bytes; the mp path runs the dense fallback, where int8
                # would add quantization noise with zero bandwidth gain
                import warnings
                warnings.warn(
                    "PADDLE_TPU_DECODE_INT8_CACHE ignored under an mp "
                    "mesh: the sharded decode path is dense (kernel-only "
                    "feature) — using the fp cache", UserWarning,
                    stacklevel=2)
            else:
                return (jnp.zeros(shape, jnp.int8),
                        jnp.zeros(shape[:-1] + (1,), jnp.float32))
        return jnp.zeros(shape, dtype)

    # ------------------------------------------------------------ the step
    def _mesh_mp(self):
        from ..parallel import current_mesh
        mesh = current_mesh()
        if mesh is not None and dict(mesh.shape).get("mp", 1) >= 2:
            return mesh
        return None

    def _build_scan_step(self, do_sample, top_k, top_p, temperature,
                         chunk, eos):
        """chunk tokens per device program: lax.scan over the per-token
        step, KV cache + last token + finished mask in the carry. One host
        dispatch per chunk instead of per token — the decode-side analogue
        of jit.run_steps (the tunnel backend pays a round-trip per
        dispatch). eos is static (baked into the trace): finished rows keep
        emitting eos on-device."""
        core = self._build_step_core(do_sample, top_k, top_p, temperature)

        def scan_step(stk, e_arrays, h_arrays, caches, tok, t0, keys,
                      finished):
            def body(carry, xs):
                tok, caches, finished = carry
                i, key = xs
                nxt, caches = core(stk, e_arrays, h_arrays, caches, tok,
                                   t0 + i, key)
                if eos is not None:
                    nxt = jnp.where(finished, eos, nxt)
                    finished = finished | (nxt == eos)
                return (nxt, caches, finished), nxt
            (tok, caches, finished), toks = jax.lax.scan(
                body, (tok, caches, finished),
                (jnp.arange(chunk, dtype=jnp.int32), keys))
            return toks, caches, finished
        # donate the KV cache (in-place ring update, no per-token copy of
        # the [L,2,B,H,Smax,D] buffer) — except through the axon tunnel,
        # where buffer donation is observed to hang (see BASELINE.md r2)
        tunneled = bool(os.environ.get("PALLAS_AXON_POOL_IPS"))
        return jax.jit(scan_step, donate_argnums=() if tunneled else (3,))

    def _build_prefill_scan(self, chunk):
        """Compiled prefill: scan the HIDDEN core (embed + layers + cache
        write, no LM head / sampling) over `chunk` teacher-forced prompt
        tokens starting at traced offset t0. Returns the last token's
        hidden state + updated caches; the caller applies the head once
        after the final chunk. Replaces the old eager fused-stack prefill,
        which paid a tunnel RPC per op — measured r3 s4: ~8.8 s of the
        8.9 s decode bench was eager prefill dispatch, not compute. Chunk
        sizes come from the same power-of-two ladder as decode so
        arbitrary prompt lengths reuse a bounded set of compiled
        variants."""
        hidden = self._build_step_core(False, 0, 1.0, 1.0).hidden

        def prefill(stk, e_arrays, caches, toks, t0):
            # toks: [chunk, B] int32 (time-major for the scan)
            def body(carry, xs):
                caches = carry
                tok_i, i = xs
                x, caches = hidden(stk, e_arrays, caches, tok_i, t0 + i)
                return caches, x
            caches, xs_out = jax.lax.scan(
                body, caches, (toks, jnp.arange(chunk, dtype=jnp.int32)))
            return xs_out[-1], caches
        tunneled = bool(os.environ.get("PALLAS_AXON_POOL_IPS"))
        return jax.jit(prefill, donate_argnums=() if tunneled else (2,))

    def _build_head_sample(self, do_sample, top_k, top_p, temperature):
        """Jitted LM head + filter + sample on one hidden state [B,1,E]."""
        core = self._build_step_core(do_sample, top_k, top_p, temperature)
        return jax.jit(core.sample_head)

    def _build_step_core(self, do_sample, top_k, top_p, temperature):
        f = self.fmt
        eps = f.epsilon
        pre_ln = f.normalize_before
        nh, hd = f.num_heads, f.head_dim
        act = f.activation
        smax = self.smax
        use_rotary = self.use_rotary
        rope_base = self.rope_base
        mesh = self._mesh_mp()
        from ..nn.layer.layers import substitute_param_arrays

        def ln(x, s, b):
            mu = jnp.mean(x.astype(jnp.float32), -1, keepdims=True)
            var = jnp.var(x.astype(jnp.float32), -1, keepdims=True)
            out = (x.astype(jnp.float32) - mu) * jax.lax.rsqrt(var + eps)
            return (out * s + b).astype(x.dtype)

        def rope1(x, t):
            # x: [B, 1, H, D] at absolute position t
            inv = 1.0 / (rope_base ** (jnp.arange(0, hd, 2,
                                                  dtype=jnp.float32) / hd))
            fr = t.astype(jnp.float32) * inv            # [D/2]
            s, c = jnp.sin(fr), jnp.cos(fr)
            ss = jnp.concatenate([s, s])[None, None, None, :]
            cc = jnp.concatenate([c, c])[None, None, None, :]
            x1 = x[..., : hd // 2]
            x2 = x[..., hd // 2:]
            rot = jnp.concatenate([-x2, x1], axis=-1)
            return (x * cc.astype(x.dtype) + rot * ss.astype(x.dtype))

        def attend(q, caches, l, t):
            # q: [B, 1, H, D]; caches: [L, 2, B, H, Smax, D] (full stack —
            # the kernel addresses layer l via scalar prefetch, zero-copy)
            # or (int8 stack, fp32 scales) in cache-quant mode
            qt = jnp.swapaxes(q, 1, 2)                  # [B, H, 1, D]
            quant = isinstance(caches, tuple)
            # escape hatch: PADDLE_TPU_STACKED_KERNEL=0 forces the dense
            # path — the stacked kernels' first on-chip Mosaic compile
            # happens inside a driver bench window; a compile failure
            # there must be recoverable without a code change
            if mesh is None and os.environ.get(
                    "PADDLE_TPU_STACKED_KERNEL", "1") != "0":
                from ..ops.pallas.decode_attention import (
                    decode_attention_stacked, decode_attention_stacked_i8,
                    stacked_i8_is_supported, stacked_is_supported)
                if quant and stacked_i8_is_supported(
                        (q.shape[0], 1, nh, hd), caches[0].shape, q.dtype):
                    lens = jnp.full((q.shape[0],), t, jnp.int32)
                    o = decode_attention_stacked_i8(qt, caches[0],
                                                    caches[1], l, lens)
                    return jnp.swapaxes(o, 1, 2)
                if not quant and stacked_is_supported(
                        (q.shape[0], 1, nh, hd), caches.shape, q.dtype,
                        cache_dtype=caches.dtype):
                    lens = jnp.full((q.shape[0],), t, jnp.int32)
                    o = decode_attention_stacked(qt, caches, l, lens)
                    return jnp.swapaxes(o, 1, 2)
            # dense masked fallback — under a mesh the head dim ('mp')
            # shards this einsum Megatron-style; the layer slice fuses
            # into the einsum operand read (no materialized copy)
            if quant:
                ci = jax.lax.dynamic_index_in_dim(caches[0], l, 0,
                                                  keepdims=False)
                sc = jax.lax.dynamic_index_in_dim(caches[1], l, 0,
                                                  keepdims=False)
                cache = ci.astype(jnp.float32) * sc
            else:
                cache = jax.lax.dynamic_index_in_dim(caches, l, 0,
                                                     keepdims=False)
            s = jnp.einsum("bhqd,bhsd->bhqs", qt.astype(jnp.float32),
                           cache[0].astype(jnp.float32)) * (hd ** -0.5)
            mask = jnp.arange(smax)[None, None, None, :] <= t
            s = jnp.where(mask, s, -1e30)
            p = jax.nn.softmax(s, axis=-1)
            o = jnp.einsum("bhqs,bhsd->bhqd", p,
                           cache[1].astype(jnp.float32))
            return jnp.swapaxes(o, 1, 2).astype(q.dtype)

        def layer_step(x, p, caches, l, t):
            residual = x
            h = ln(x, p["ln_s"], p["ln_b"]) if pre_ln else x
            emb = h.shape[-1]
            w = p["qkv_w"].reshape(3 * nh * hd, emb).T
            qkv = h @ w.astype(h.dtype) + \
                p["qkv_b"].reshape(-1).astype(h.dtype)
            b = h.shape[0]
            qkv = qkv.reshape(b, 1, 3, nh, hd)
            q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
            if use_rotary:
                q = rope1(q, t)
                k = rope1(k, t)
            # write-then-attend: ONE tiny [1, 2, B, H, 1, D] in-place
            # update at (l, :, :, :, t, :) on the scan-carried buffer —
            # the full stack is never copied per step (the old layout
            # emitted the updated cache as stacked scan ys, rewriting the
            # entire [L, 2, B, H, Smax, D] buffer every token)
            kv_new = jnp.stack([jnp.swapaxes(k, 1, 2),
                                jnp.swapaxes(v, 1, 2)])  # [2, B, H, 1, D]
            if isinstance(caches, tuple):
                # cache-quant write: per-row absmax int8 + fp32 scale
                kv32 = kv_new.astype(jnp.float32)
                amax = jnp.max(jnp.abs(kv32), axis=-1, keepdims=True)
                sc_new = amax / 127.0
                q_new = jnp.clip(
                    jnp.round(kv32 / jnp.maximum(sc_new, 1e-8)),
                    -127, 127).astype(jnp.int8)
                ci8 = jax.lax.dynamic_update_slice(
                    caches[0], q_new[None], (l, 0, 0, 0, t, 0))
                scs = jax.lax.dynamic_update_slice(
                    caches[1], sc_new[None], (l, 0, 0, 0, t, 0))
                caches = (ci8, scs)
            else:
                caches = jax.lax.dynamic_update_slice(
                    caches, kv_new[None].astype(caches.dtype),
                    (l, 0, 0, 0, t, 0))
            attn = attend(q, caches, l, t)
            attn = attn.reshape(b, 1, nh * hd)
            attn = attn @ p["lin_w"].astype(attn.dtype) + \
                p["lin_b"].astype(attn.dtype)
            x = residual + attn
            if not pre_ln:
                x = ln(x, p["ln_s"], p["ln_b"])
            residual = x
            h = ln(x, p["fln_s"], p["fln_b"]) if pre_ln else x
            h = h @ p["f1_w"].astype(h.dtype) + p["f1_b"].astype(h.dtype)
            h = getattr(jax.nn, act)(h)
            h = h @ p["f2_w"].astype(h.dtype) + p["f2_b"].astype(h.dtype)
            x = residual + h
            if not pre_ln:
                x = ln(x, p["fln_s"], p["fln_b"])
            return x, caches

        embed, head = self.embed, self.head
        e_params, h_params = self._embed_params, self._head_params

        def call_layerlike(fn, params, arrays, x_arr):
            # no_grad: inference-only — must not record onto (or clear!) a
            # caller's pending autograd tape
            with substitute_param_arrays(params, arrays), no_grad():
                out = fn(Tensor(x_arr))
            return out._data if isinstance(out, Tensor) else out

        def hidden(stk, e_arrays, caches, tok, t):
            # tok: [B] int32; t: scalar int32; caches: [L, 2, B, H, Smax, D]
            # -> (x [B, 1, E], caches) with caches updated at position t.
            # The cache rides the layer scan as CARRY (in-place dynamic
            # updates on one buffer), not as xs->ys (which rewrote the
            # whole stack per token — the r3 decode profile's ~10 ms/token
            # vs ~1 ms bandwidth-floor gap).
            x = call_layerlike(embed, e_params, e_arrays, tok[:, None])
            if mesh is not None:
                from jax.sharding import NamedSharding, PartitionSpec as P
                sh = NamedSharding(mesh,
                                   P(None, None, None, "mp", None, None))
                if isinstance(caches, tuple):
                    caches = tuple(jax.lax.with_sharding_constraint(c, sh)
                                   for c in caches)
                else:
                    caches = jax.lax.with_sharding_constraint(caches, sh)

            def body(carry, xs):
                x, caches = carry
                p, l = xs
                x, caches = layer_step(x, p, caches, l, t)
                return (x, caches), None
            nl = (caches[0] if isinstance(caches, tuple)
                  else caches).shape[0]
            (x, caches), _ = jax.lax.scan(
                body, (x, caches), (stk, jnp.arange(nl, dtype=jnp.int32)))
            return x, caches

        def sample_head(h_arrays, x, key):
            logits = call_layerlike(head, h_params, h_arrays, x)
            logits = logits.reshape(logits.shape[0], -1)
            logits = _filter_logits(logits, do_sample, top_k, top_p,
                                    temperature)
            if do_sample:
                nxt = jax.random.categorical(key, logits, axis=-1)
            else:
                nxt = jnp.argmax(logits, axis=-1)
            return nxt.astype(jnp.int32)

        def step(stk, e_arrays, h_arrays, caches, tok, t, key):
            x, caches = hidden(stk, e_arrays, caches, tok, t)
            return sample_head(h_arrays, x, key), caches

        step.hidden = hidden
        step.sample_head = sample_head
        return step

    # --------------------------------------------------------------- drive
    @no_grad()
    def generate(self, input_ids, max_new_tokens=20, eos_token_id=None,
                 do_sample=False, top_k=0, top_p=1.0, temperature=1.0):
        """Prefill the prompt via compiled chunked scans of the hidden
        core (LM head applied once at the end), then run the compiled
        chunked decode. Every device dispatch is a jitted scan — the
        tunnel backend pays a host RPC per dispatch, so nothing runs
        eagerly here."""
        ids = input_ids._data if isinstance(input_ids, Tensor) else \
            jnp.asarray(np.asarray(input_ids))
        b, prompt = ids.shape
        assert prompt + max_new_tokens <= self.smax, (
            f"max_seq_len {self.smax} < prompt {prompt} + {max_new_tokens}")
        f = self.fmt
        f.eval()

        # ---- compiled prefill: chunked scans of the hidden core over the
        # prompt (pow-2 chunk ladder, same bounded-compile discipline as
        # decode), then ONE jitted head+sample on the final hidden state
        stk = self._stacked()
        e_arrays = [p._data for p in self._embed_params]
        h_arrays = [p._data for p in self._head_params]
        caches = self.init_cache(b)
        toks_tm = jnp.swapaxes(ids.astype(jnp.int32), 0, 1)  # [S, B]
        mesh_now = self._mesh_mp()
        # the stacked-kernel escape hatch is trace-time state: it must be
        # part of every compiled-step cache key, or flipping it after a
        # compile failure would silently reuse the failing trace
        sk_flag = os.environ.get("PADDLE_TPU_STACKED_KERNEL", "1")
        pos, last_x = 0, None
        while pos < prompt:
            chunk = 64
            while chunk > prompt - pos:
                chunk //= 2
            pkey = ("prefill", mesh_now, chunk, sk_flag)
            pstep = self._scan_cache.get(pkey)
            if pstep is None:
                pstep = self._build_prefill_scan(chunk)
                self._scan_cache[pkey] = pstep
            last_x, caches = pstep(stk, e_arrays, caches,
                                   toks_tm[pos:pos + chunk],
                                   jnp.asarray(pos, jnp.int32))
            pos += chunk
        hkey = ("head", do_sample, top_k, top_p, temperature, mesh_now)
        hstep = self._scan_cache.get(hkey)
        if hstep is None:
            hstep = self._build_head_sample(do_sample, top_k, top_p,
                                            temperature)
            self._scan_cache[hkey] = hstep
        nxt = hstep(h_arrays, last_x,
                    next_key() if do_sample else jax.random.PRNGKey(0))

        # ---- compiled decode: CHUNKED scan dispatch. Without eos, all
        # remaining tokens run in one device program; with eos, fixed-size
        # chunks with on-device finished-masking and a host early-exit
        # check between chunks. Cache key includes the active mesh
        # (entering/leaving an mp mesh must rebuild) and the chunk size.
        # host-side accumulation: ONE [chunk, B] device->host transfer per
        # chunk (not per token); only the last token stays on device as the
        # next dispatch's input
        host_parts = [np.asarray(nxt)[:, None]]
        last_tok = nxt
        finished = jnp.zeros((b,), bool)
        eos = None if eos_token_id is None else int(eos_token_id)
        remaining = max_new_tokens - 1
        if eos is not None:
            finished = finished | (nxt == eos)
            if bool(jnp.all(finished)):
                remaining = 0                 # everything ended at prefill
        # chunk sizes come from a power-of-two ladder so arbitrary
        # max_new_tokens values reuse a bounded set of compiled scan
        # variants (a fresh scan length would otherwise recompile inside
        # the generation loop). eos runs cap the chunk for early exit.
        chunk_env = int(os.environ.get("PADDLE_TPU_DECODE_CHUNK", "0"))
        cap = chunk_env or (8 if eos is not None else 64)
        t0 = prompt
        while remaining > 0:
            chunk = cap
            while chunk > remaining:
                chunk //= 2
            key = (do_sample, top_k, top_p, temperature,
                   self._mesh_mp(), chunk, eos, sk_flag)
            step = self._scan_cache.get(key)
            if step is None:
                step = self._build_scan_step(*key[:4], chunk, eos)
                self._scan_cache[key] = step
            # one split per chunk: per-token subkeys ride the scan xs
            base = next_key() if do_sample else jax.random.PRNGKey(0)
            keys = jax.random.split(base, chunk)
            ck, caches, finished = step(
                stk, e_arrays, h_arrays, caches, last_tok,
                jnp.asarray(t0, jnp.int32), keys, finished)
            host_parts.append(np.asarray(ck).T)        # [B, chunk]
            last_tok = ck[-1]
            t0 += chunk
            remaining -= chunk
            if eos is not None and bool(jnp.all(finished)):
                break
        out = np.concatenate([np.asarray(ids)] + host_parts, axis=1)
        if eos is not None and bool(jnp.all(finished)):
            # per-token early-stop semantics (matches generate()): the
            # output ends at the step where the LAST row emitted its first
            # eos; any later all-eos padding the chunk produced is trimmed
            gen = out[:, prompt:]
            first_eos = np.argmax(gen == eos, axis=1)   # rows all have one
            out = out[:, : prompt + int(first_eos.max()) + 1]
        return Tensor(out)


def generate_fused(fmt, input_ids, embed, head, max_new_tokens=20,
                   max_seq_len=None, eos_token_id=None, do_sample=False,
                   top_k=0, top_p=1.0, temperature=1.0, use_rotary=False):
    """One-shot driver over FusedDecoder (see class docstring)."""
    ids = input_ids._data if isinstance(input_ids, Tensor) else \
        jnp.asarray(np.asarray(input_ids))
    smax = max_seq_len or ids.shape[1] + max_new_tokens
    dec = FusedDecoder(fmt, embed, head, smax, use_rotary=use_rotary)
    return dec.generate(input_ids, max_new_tokens, eos_token_id, do_sample,
                        top_k, top_p, temperature)
