"""Serving telemetry: request spans, step timeline, bounded histograms,
Prometheus/Perfetto export.

Observability as a SUBSYSTEM instead of a dict (parity target: the
reference stack's first-class profiler — python/paddle/profiler ::
Profiler/RecordEvent/export_chrome_tracing — and the per-step/per-request
timelines vLLM/Sarathi-style serving systems lean on to diagnose TTFT
tails and budget waste):

  * ``Telemetry`` — per-engine event collector. Per-request LIFECYCLE
    SPANS (queued -> admitted -> prefix-adopt -> prefill chunks ->
    first token -> decode/verify dispatches -> finished|expired|
    rejected, monotonic engine-clock timestamps) and a STEP TIMELINE
    (one event per compiled dispatch: kind admit/prefill/decode/verify/
    budget, rows packed, budget used/wasted, draft tokens, dispatch vs
    host-side elapsed, trace-spy deltas, gauge snapshots) both live in
    bounded rings sized by ``PADDLE_TELEMETRY_RING`` (default 2048
    entries; ``0`` disables span/step collection with near-zero
    overhead — ONE branch per event, no timestamp calls when off).
  * ``LogHistogram`` — fixed-size log2-bucketed streaming histograms
    for TTFT / per-request latency / tokens-per-step. These replace the
    old ``metrics()`` percentile scans over the grow-forever results
    list (a real leak at service lifetimes): O(1) memory, O(1) observe,
    p50/p90/p99 within one bucket width of exact, exact counts. The
    histograms stay on even when the ring is disabled (they are the
    ``metrics()`` percentile source and cost nothing).
  * ``export_chrome_tracing(engine, path)`` — renders the rings as
    Chrome-trace JSON via the ``paddle_tpu.profiler.ChromeTrace`` event
    model (one pid per engine, one tid per slot plus a dispatch-
    timeline tid, counter tracks for kv_blocks_used / queue depth /
    budget_utilization), so Perfetto shows the serving run next to
    jax.profiler's XLA timeline.
  * ``render_prometheus(engine)`` / ``parse_prometheus(text)`` —
    Prometheus text exposition with STABLE names (``PROMETHEUS_NAMES``
    maps every ``metrics()`` key; counters are monotonic across
    ``reset_metrics`` because the engine folds each window into a
    lifetime base), folding in distributed-runtime gauges: watchdog
    per-rank heartbeat age + peer-failure counts, supervisor restart
    generation, and the rpc call-latency histogram registered here via
    ``runtime_histogram``/``runtime_counter``.
  * ``snapshot(engine)`` — the JSON routing payload a cluster
    front-end consumes (queue depth, occupancy, pool headroom, prefix
    hit rate, histogram percentiles; v2 adds the SLO/goodput block +
    queue/service decomposition).
  * ``SloPolicy`` — declared latency objectives (``PADDLE_SLO_*``);
    the engine classifies every finished request at completion (ok /
    violated-by-queueing / violated-by-service) and the verdicts ride
    ``metrics()``, the exposition, and the snapshot.
  * ``trace_dump(engine)`` — the per-replica payload
    ``serving_cluster.trace.export_cluster_trace`` merges into ONE
    cluster-wide Perfetto trace (spans carry the gateway-minted
    ``trace_id``/``attempt`` context; wall/mono anchor pair included
    for cross-process rebasing).

This module must stay import-light (stdlib + numpy only): the
distributed runtime (rpc.py) records into the runtime registry and must
not drag jax in at module import.
"""
from __future__ import annotations

import json
import math
import os
import time
from collections import deque

import numpy as np

__all__ = ["LogHistogram", "Telemetry", "RequestTrace", "SloPolicy",
           "export_chrome_tracing", "render_prometheus",
           "parse_prometheus", "snapshot", "trace_dump",
           "runtime_histogram",
           "runtime_counter", "runtime_prometheus",
           "runtime_registry_snapshot", "PROMETHEUS_NAMES",
           "PROMETHEUS_EXEMPT_KEYS", "RESET_EXEMPT_KEYS", "DEFAULT_RING",
           "SNAPSHOT_SCHEMA_VERSION", "SNAPSHOT_REQUIRED_KEYS",
           "SNAPSHOT_OPTIONAL_KEYS", "SLO_ENV_VARS", "QOS_CLASSES",
           "QOS_DEFAULT", "QOS_RANK", "DEFAULT_QOS_SHARES"]

DEFAULT_RING = 2048

# ---- QoS priority classes -------------------------------------------
# The canonical class set, best-first: admission, preemption-victim
# selection, and the weighted-fair packer all rank by position in this
# tuple. It lives HERE (the import-light module) so the stdlib-only
# cluster protocol (serving_cluster/protocol.py) can validate the
# X-Priority header without dragging jax in.
QOS_CLASSES = ("high", "normal", "low")
QOS_DEFAULT = "normal"
QOS_RANK = {c: i for i, c in enumerate(QOS_CLASSES)}
# weighted-fair token-budget shares (PADDLE_QOS_SHARES overrides,
# "high=4,normal=2,low=1" syntax): a class's share of the SPARE prefill
# budget when several classes are prefilling at once — work-conserving,
# so an idle class's share spills to the hungry ones
DEFAULT_QOS_SHARES = {"high": 4, "normal": 2, "low": 1}

# ---- telemetry_snapshot() wire contract -----------------------------
# The snapshot IS a wire payload now: the cluster router
# (serving_cluster/router.py) reads it over rpc to place requests, so
# its key set is pinned structurally (tools/check_metrics_surface.py
# fails tier-1 on drift, the same discipline as PROMETHEUS_NAMES).
# Bump SNAPSHOT_SCHEMA_VERSION on any key addition/removal/semantic
# change — a router seeing an unknown version refuses to score the
# replica instead of silently misreading it.
# v2: added the "slo" block (declared objectives + goodput counters)
# and the queue_s/service_s decomposition histograms — the signals the
# autoscaling item consumes.
# v3: the "requests" block gains migrated_in/migrated_out (live session
# migration — the autoscaler's drain accounting).
# v4: per-class QoS — top-level "queue_depths" ({class: depth}, the
# router/gateway shed signal), the "requests" block gains
# preempted/resumed (preemption-to-host accounting), and the "slo"
# block gains "violated_queue_by_class" (the autoscaler scales up on
# HIGH-priority queue violations only; low-priority backlog is the QoS
# layer degrading gracefully, not a capacity signal).
# v5: disaggregated serving — top-level "role" (prefill|decode|mixed;
# the router's placement filter and the autoscaler's pool split) and
# the "handoff" block (kv_blocks_shipped/adopted — the streamed
# prefill->decode KV transfer accounting). Routers older than v5 must
# refuse rather than place decode traffic on a prefill-only replica.
# v6: gray-failure defense — top-level "do_sample" (engine sampling
# mode: the router's hedged-dispatch safety gate — only a GREEDY
# stream is bit-identical across replicas, so only do_sample=False
# traffic may hedge) and the "health" block (step_ewma_s — the
# engine's own smoothed step duration, the replica-local slowness
# signal the router's median-relative health scorer consumes).
# v7: tensor-parallel weights — the "weights" block (shard_count /
# bytes_per_device / bytes_replicated — the per-chip HBM residency of
# the serving step's weight arrays; (per_device - replicated) x
# shard_count + replicated == the dense byte total). The capacity
# planner's model-fits-here signal for mp-sharded replicas.
# v8: quantized serving — the "weights" block gains weight_quant
# ("none"|"int8"|"int4") and kv_quant ("none"|"int8"): the byte gauges
# already report QUANTIZED residency (packed arrays + scale mirrors at
# their true size), so without the mode fields a capacity planner
# cannot tell a small fp model from a quantized large one, and a
# router cannot refuse to mix quantized/fp replicas in a greedy-parity
# hedge pool.
SNAPSHOT_SCHEMA_VERSION = 8

# keys every snapshot carries, on every engine configuration
SNAPSHOT_REQUIRED_KEYS = frozenset({
    "schema_version", "queue_depth", "occupancy", "num_slots",
    "slots_free", "prefill_cap", "has_work", "tokens_per_sec",
    "requests", "histograms", "budget", "prefix", "spans_logged",
    "steps_logged", "telemetry_ring", "slo", "queue_depths",
    "role", "handoff", "do_sample", "health", "weights",
})

# keys present only on some configurations (paged pool / spec decode)
SNAPSHOT_OPTIONAL_KEYS = frozenset({"kv_blocks", "drafter"})


# ------------------------------------------------------------------ SLO
# Declared latency objectives (the goodput contract). Registered in
# paddle_tpu.testing.GW_ENV_VARS so the conftest leak guard covers them
# — a leaked objective silently flips every later engine's goodput
# counters.
SLO_ENV_VARS = ("PADDLE_SLO_TTFT_S", "PADDLE_SLO_ITL_S",
                "PADDLE_SLO_E2E_S")


class SloPolicy:
    """Declared per-request latency objectives (``PADDLE_SLO_*``):

      * ``ttft_s``  — time to first token (submit -> first token);
      * ``itl_s``   — MEAN inter-token latency over the request
        ((t_done - t_first) / (n - 1)); the fleet-level p99 the issue
        cares about is read off the latency histograms — per-token
        timestamps are not recorded (tokens harvest in batches), so a
        within-request p99 would be an invention, not a measurement;
      * ``e2e_s``   — end-to-end latency (submit -> finished).

    Unset objectives are never violated, so a no-knob engine counts
    every finished request as ``slo_ok`` and the reconciliation
    ``slo_ok + slo_violated_* == requests_finished`` holds universally.

    ``classify`` attributes a violation to where the request spent its
    time: ``queue`` when the queue wait (submit -> admitted) was at
    least the service time, else ``service`` — the split the
    autoscaler needs (queued-too-long = add replicas; slow-service =
    the engine itself is the bottleneck)."""

    __slots__ = ("ttft_s", "itl_s", "e2e_s")

    def __init__(self, ttft_s=None, itl_s=None, e2e_s=None):
        for name, v in (("ttft_s", ttft_s), ("itl_s", itl_s),
                        ("e2e_s", e2e_s)):
            if v is not None and float(v) <= 0:
                raise ValueError(f"SLO objective {name} must be > 0, "
                                 f"got {v}")
        self.ttft_s = None if ttft_s is None else float(ttft_s)
        self.itl_s = None if itl_s is None else float(itl_s)
        self.e2e_s = None if e2e_s is None else float(e2e_s)

    @classmethod
    def from_env(cls):
        def _f(name):
            v = os.environ.get(name)
            return None if v in (None, "") else float(v)
        return cls(_f("PADDLE_SLO_TTFT_S"), _f("PADDLE_SLO_ITL_S"),
                   _f("PADDLE_SLO_E2E_S"))

    @property
    def enabled(self):
        return (self.ttft_s is not None or self.itl_s is not None
                or self.e2e_s is not None)

    def objectives(self):
        return {"ttft_s": self.ttft_s, "itl_s": self.itl_s,
                "e2e_s": self.e2e_s}

    def classify(self, queue_s, service_s, ttft_s, itl_s, e2e_s):
        """``"ok" | "queue" | "service"`` for one finished request."""
        violated = (
            (self.ttft_s is not None and ttft_s is not None
             and ttft_s > self.ttft_s)
            or (self.itl_s is not None and itl_s is not None
                and itl_s > self.itl_s)
            or (self.e2e_s is not None and e2e_s is not None
                and e2e_s > self.e2e_s))
        if not violated:
            return "ok"
        return "queue" if queue_s >= service_s else "service"


# ---------------------------------------------------------------- histogram
class LogHistogram:
    """Fixed-size log2-bucketed streaming histogram.

    Buckets: one underflow bucket [0, lo), then ``buckets_per_octave``
    geometric buckets per factor-of-two up to ``hi``, then one overflow
    bucket. Percentile estimates interpolate linearly inside the target
    bucket, so they sit within ONE bucket width of the exact value —
    the accuracy/footprint trade the serving metrics need (memory is a
    few hundred int64s forever, vs one dict per finished request).

    Two layers of counts: the WINDOW (what ``percentile``/``count``
    read; ``reset()`` zeroes it) and a lifetime BASE ``reset()`` folds
    the window into — ``cumulative_counts()`` reads window + base, so
    Prometheus counters stay monotonic across ``reset_metrics``.
    """

    __slots__ = ("edges", "counts", "total", "sum",
                 "_base", "_base_total", "_base_sum", "bpo")

    def __init__(self, lo=1e-6, hi=1e4, buckets_per_octave=4):
        if not (0 < lo < hi):
            raise ValueError(f"need 0 < lo < hi, got {lo}, {hi}")
        self.bpo = int(buckets_per_octave)
        n = int(math.ceil(math.log2(hi / lo) * self.bpo))
        self.edges = lo * np.power(2.0, np.arange(n + 1) / self.bpo)
        self.counts = np.zeros(n + 2, np.int64)   # under + n + over
        self._base = np.zeros(n + 2, np.int64)
        self.total = 0
        self.sum = 0.0
        self._base_total = 0
        self._base_sum = 0.0

    @property
    def count(self):
        return self.total

    def observe(self, value):
        v = max(float(value), 0.0)
        # side="left": a value EXACTLY on a bucket edge belongs to the
        # bucket that edge closes (buckets are (lo, hi]) — Prometheus'
        # `le` boundaries are inclusive, so the text exposition's
        # cumulative count at le=edge must include edge-valued samples
        # (integer-valued series like tokens-per-step land exactly on
        # the pow-2 edges every time)
        i = int(np.searchsorted(self.edges, v, side="left"))
        self.counts[i] += 1
        self.total += 1
        self.sum += v

    def _bucket_bounds(self, i):
        """(lo, hi] of bucket index ``i`` (0 = underflow; the overflow
        bucket is clamped to its lower edge — an estimate can never
        exceed the histogram's stated range)."""
        n = self.edges.size
        lo = 0.0 if i == 0 else float(self.edges[i - 1])
        hi = float(self.edges[min(i, n - 1)])
        return lo, hi

    def percentile(self, q):
        """Estimated q-th percentile (linear interpolation inside the
        target bucket); None when the window is empty."""
        if self.total == 0:
            return None
        target = (q / 100.0) * self.total
        cum = 0
        for i, c in enumerate(self.counts):
            if c == 0:
                continue
            if cum + c >= target:
                lo, hi = self._bucket_bounds(i)
                frac = min(max((target - cum) / c, 0.0), 1.0)
                return float(lo + frac * (hi - lo))
            cum += c
        lo, hi = self._bucket_bounds(len(self.counts) - 1)
        return float(hi)

    def bucket_width_at(self, value):
        """Width of the bucket containing ``value`` — the documented
        bound on the percentile estimation error. Same edge rule as
        observe: a value on an edge belongs to the bucket it closes."""
        v = max(float(value), 0.0)
        i = int(np.searchsorted(self.edges, v, side="left"))
        lo, hi = self._bucket_bounds(i)
        return hi - lo

    def reset(self):
        """Zero the window, folding it into the lifetime base (the
        Prometheus exposition never moves backwards)."""
        self._base += self.counts
        self._base_total += self.total
        self._base_sum += self.sum
        self.counts[:] = 0
        self.total = 0
        self.sum = 0.0

    def cumulative_counts(self):
        """(bucket counts, total, sum) over the histogram's LIFETIME
        (window + every reset-folded window)."""
        return (self._base + self.counts, self._base_total + self.total,
                self._base_sum + self.sum)

    def snapshot(self):
        return {"count": int(self.total), "sum": round(float(self.sum), 6),
                "p50": self.percentile(50), "p90": self.percentile(90),
                "p99": self.percentile(99)}

    def prometheus_lines(self, name, help_text=""):
        """Prometheus histogram exposition over the LIFETIME counts.
        Bucket boundaries are decimated to one per octave (the full
        sub-octave resolution stays available to ``percentile``; the
        text format does not need 130 lines per histogram)."""
        counts, total, total_sum = self.cumulative_counts()
        lines = [f"# HELP {name} {help_text or name}",
                 f"# TYPE {name} histogram"]
        for i in range(0, self.edges.size, self.bpo):
            # le=edges[i] covers buckets 0..i (underflow + everything
            # strictly below that edge)
            cum = int(counts[: i + 1].sum())
            lines.append(f'{name}_bucket{{le="{self.edges[i]:.6g}"}} {cum}')
        lines.append(f'{name}_bucket{{le="+Inf"}} {int(total)}')
        lines.append(f"{name}_sum {float(total_sum):.9g}")
        lines.append(f"{name}_count {int(total)}")
        return lines


# ------------------------------------------------------------ request spans
class RequestTrace:
    """One request's lifecycle span: ordered (event, t) pairs on the
    engine clock. Lives in ``Telemetry._live`` while in flight, moves
    to the bounded ``spans`` ring at finish/expiry/rejection.

    ``trace_id``/``attempt`` are the CLUSTER trace context: the gateway
    mints one trace id per HTTP request and the router threads it
    through every placement (attempt increments across failover
    re-submits), so a kill-drill stream yields ONE joined trace across
    gateway, router, and both replicas."""

    __slots__ = ("rid", "slot", "state", "events", "trace_id", "attempt")

    def __init__(self, rid, slot=None, trace_id=None, attempt=1):
        self.rid = rid
        self.slot = slot
        self.state = "queued"
        self.events = []                  # [(name, t_monotonic), ...]
        self.trace_id = trace_id
        self.attempt = int(attempt)

    def t0(self):
        return self.events[0][1] if self.events else 0.0

    def t1(self):
        return self.events[-1][1] if self.events else 0.0


class Telemetry:
    """Per-engine telemetry collector (see the module docstring).

    Every ``req_*``/``step_event`` entry point starts with ONE enabled
    branch; call sites are expected to guard their own timestamp
    computation on ``self.enabled`` so a disabled ring costs no clock
    reads. The three histograms are independent of the ring and stay on
    (they are the ``metrics()`` percentile source)."""

    def __init__(self, ring=None, clock=None):
        if ring is None:
            ring = int(os.environ.get("PADDLE_TELEMETRY_RING",
                                      str(DEFAULT_RING)))
        if ring < 0:
            raise ValueError(f"telemetry ring must be >= 0, got {ring}")
        self.ring = int(ring)
        self.enabled = self.ring > 0
        self.clock = clock or time.perf_counter
        self.spans = deque(maxlen=max(self.ring, 1))
        self.steps = deque(maxlen=max(self.ring, 1))
        self._live = {}                   # rid -> RequestTrace
        self.hist_ttft = LogHistogram(1e-6, 1e4)
        self.hist_latency = LogHistogram(1e-6, 1e4)
        self.hist_step_tokens = LogHistogram(1.0, 1 << 16)
        # queue-time vs service-time decomposition (the SLO layer's
        # cause attribution + the autoscaler's queue-pressure signal);
        # like the other histograms these stay on with the ring off
        self.hist_queue = LogHistogram(1e-6, 1e4)
        self.hist_service = LogHistogram(1e-6, 1e4)
        # disaggregated-serving KV transfer sizes (bytes per handoff
        # payload: export_slot kv + streamed export_kv_prefix chunks) —
        # stays on with the ring off like the latency histograms
        self.hist_handoff = LogHistogram(64.0, 1e9)

    # ------------------------------------------------------- request spans
    def req_queued(self, rid, t, trace_id=None, attempt=1):
        if not self.enabled:
            return
        tr = RequestTrace(rid, trace_id=trace_id, attempt=attempt)
        tr.events.append(("queued", t))
        self._live[rid] = tr

    def req_admitted(self, rid, slot, t):
        if not self.enabled:
            return
        tr = self._live.get(rid)
        if tr is not None:
            tr.slot = slot
            tr.events.append(("admitted", t))

    def req_event(self, rid, name, t):
        if not self.enabled:
            return
        tr = self._live.get(rid)
        if tr is not None:
            tr.events.append((name, t))

    def req_done(self, rid, state, t):
        if not self.enabled:
            return
        tr = self._live.pop(rid, None)
        if tr is None:                    # never tracked (ring was off
            tr = RequestTrace(rid)        # at submit); synthesize
        tr.state = state
        tr.events.append((state, t))
        self.spans.append(tr)

    def req_rejected(self, t, rid=None, trace_id=None, attempt=1):
        """Sheds never get a rid — record a one-event span directly.
        ``attempt`` matters for failover re-submits that shed: the
        merged cluster trace must attribute the rejection to the
        placement attempt that actually hit this replica."""
        if not self.enabled:
            return
        tr = RequestTrace(rid, trace_id=trace_id, attempt=attempt)
        tr.state = "rejected"
        tr.events.append(("rejected", t))
        self.spans.append(tr)

    # ------------------------------------------------------- step timeline
    def step_event(self, kind, t, dur_s, rows=0, tokens=0,
                   traces_delta=0, **gauges):
        """One compiled dispatch on the timeline; returns the record so
        the caller can attach harvest results (tokens, host_s) once the
        host side finishes. None when disabled."""
        if not self.enabled:
            return None
        ev = {"kind": kind, "t": t, "dur_s": dur_s, "rows": int(rows),
              "tokens": int(tokens), "traces_delta": int(traces_delta)}
        ev.update(gauges)
        self.steps.append(ev)
        return ev

    @staticmethod
    def finish_step(ev, now, tokens=None):
        """Close a step record: host-side elapsed = everything between
        the dispatch returning and the harvest completing."""
        if ev is None:
            return
        if tokens is not None:
            ev["tokens"] = int(tokens)
        ev["host_s"] = round(max(0.0, now - ev["t"] - ev["dur_s"]), 9)

    # --------------------------------------------------------- histograms
    def observe_request(self, ttft_s, latency_s, queue_s=None,
                        service_s=None):
        if ttft_s is not None:
            self.hist_ttft.observe(ttft_s)
        if latency_s is not None:
            self.hist_latency.observe(latency_s)
        if queue_s is not None:
            self.hist_queue.observe(queue_s)
        if service_s is not None:
            self.hist_service.observe(service_s)

    def observe_step_tokens(self, n):
        self.hist_step_tokens.observe(n)

    def observe_handoff(self, nbytes):
        self.hist_handoff.observe(nbytes)

    def reset(self):
        """Window reset (rides ``engine.reset_metrics``): clears the
        rings so the next export covers exactly the measured window,
        folds the histograms' windows into their lifetime bases.
        In-flight spans survive — their requests are still live."""
        self.spans.clear()
        self.steps.clear()
        self.hist_ttft.reset()
        self.hist_latency.reset()
        self.hist_step_tokens.reset()
        self.hist_queue.reset()
        self.hist_service.reset()
        self.hist_handoff.reset()


# -------------------------------------------------------- runtime registry
# Process-global metrics the distributed runtime feeds (rpc call
# latency, error counts); folded into every engine's exposition and
# into runtime_prometheus() for engine-less processes.
_runtime_hists: dict = {}
_runtime_counters: dict = {}


def runtime_histogram(name, lo=1e-6, hi=1e3):
    h = _runtime_hists.get(name)
    if h is None:
        h = _runtime_hists[name] = LogHistogram(lo, hi)
    return h


def runtime_counter(name, inc=0):
    _runtime_counters[name] = _runtime_counters.get(name, 0) + inc
    return _runtime_counters[name]


def runtime_registry_snapshot():
    """JSON-able snapshot of the process-global runtime registry
    (counter values + histogram percentile summaries) — embedded in
    flight-recorder dumps and cluster snapshots so a post-mortem sees
    the rank's rpc/collective latency state without scraping
    Prometheus."""
    return {"counters": dict(sorted(_runtime_counters.items())),
            "histograms": {name: _runtime_hists[name].snapshot()
                           for name in sorted(_runtime_hists)}}


def runtime_prometheus():
    """Distributed-runtime gauges: supervisor restart generation,
    watchdog per-rank heartbeat age + peer-failure count, and whatever
    the runtime registry accumulated (rpc latency/errors)."""
    lines = []

    def gauge(name, value, help_text="", labels=""):
        lines.append(f"# HELP {name} {help_text or name}")
        lines.append(f"# TYPE {name} gauge")
        lines.append(f"{name}{labels} {value:g}")

    gen = int(os.environ.get("PADDLE_RESTART_COUNT", "0") or 0)
    gauge("paddle_runtime_restart_generation", gen,
          "gang supervisor restart generation (PADDLE_RESTART_COUNT)")
    try:
        from ..distributed.resilience.watchdog import current_watchdog
        wd = current_watchdog()
    except Exception:                     # import cycle / stripped build
        wd = None
    if wd is not None:
        g = wd.gauges()
        ages = g["heartbeat_age_s"]
        if ages:
            name = "paddle_runtime_watchdog_heartbeat_age_seconds"
            lines.append(f"# HELP {name} seconds since each peer's "
                         "heartbeat counter last progressed")
            lines.append(f"# TYPE {name} gauge")
            for peer in sorted(ages):
                lines.append(f'{name}{{peer="{peer}"}} {ages[peer]:.3f}')
        lines.append("# HELP paddle_runtime_watchdog_peer_failures_total "
                     "peer failures recorded by this rank's watchdog")
        lines.append("# TYPE paddle_runtime_watchdog_peer_failures_total "
                     "counter")
        lines.append("paddle_runtime_watchdog_peer_failures_total "
                     f"{g['peer_failures_total']}")
    for name in sorted(_runtime_counters):
        lines.append(f"# HELP {name} {name}")
        lines.append(f"# TYPE {name} counter")
        lines.append(f"{name} {_runtime_counters[name]}")
    for name in sorted(_runtime_hists):
        lines.extend(_runtime_hists[name].prometheus_lines(name))
    return lines


# --------------------------------------------------- prometheus exposition
# STABLE name (and type) for every key ServingEngine.metrics() can emit.
# tools/check_metrics_surface.py asserts the mapping is total: a future
# counter that skips this table fails tier-1 instead of silently missing
# from the exposition. Percentile keys map to their backing histogram.
PROMETHEUS_NAMES = {
    "tokens_emitted": ("paddle_serving_tokens_emitted_total", "counter"),
    "busy_s": ("paddle_serving_busy_seconds_total", "counter"),
    "tokens_per_sec": ("paddle_serving_tokens_per_sec", "gauge"),
    "requests_finished": ("paddle_serving_requests_finished_total",
                          "counter"),
    "requests_admitted": ("paddle_serving_requests_admitted_total",
                          "counter"),
    "requests_forked": ("paddle_serving_requests_forked_total", "counter"),
    "requests_rejected": ("paddle_serving_requests_rejected_total",
                          "counter"),
    "requests_expired": ("paddle_serving_requests_expired_total",
                         "counter"),
    "requests_migrated_in": (
        "paddle_serving_requests_migrated_in_total", "counter"),
    "requests_migrated_out": (
        "paddle_serving_requests_migrated_out_total", "counter"),
    # disaggregated KV handoff: blocks this engine read out for another
    # engine (export_slot / streamed export_kv_prefix) vs blocks
    # written into this pool from another engine (import_slot /
    # stage_kv_blocks) — the prefill->decode transfer volume
    "kv_blocks_shipped": ("paddle_serving_kv_blocks_shipped_total",
                          "counter"),
    "kv_blocks_adopted": ("paddle_serving_kv_blocks_adopted_total",
                          "counter"),
    # QoS preemption-to-host: preempted left their slot for the host-RAM
    # parking lot (same rid, stream intact), resumed re-entered a slot;
    # preempted >= resumed always (the difference is currently parked)
    "requests_preempted": ("paddle_serving_requests_preempted_total",
                           "counter"),
    "requests_resumed": ("paddle_serving_requests_resumed_total",
                         "counter"),
    "requests_parked": ("paddle_serving_requests_parked", "gauge"),
    # per-class QoS counters as LABELED series of one family (the three
    # entries share a family name; render_prometheus emits HELP/TYPE
    # once per family and one labeled sample per key, zero-initialized
    # so every class is discoverable before traffic arrives)
    "requests_admitted_high": (
        'paddle_serving_class_requests_admitted_total{class="high"}',
        "counter"),
    "requests_admitted_normal": (
        'paddle_serving_class_requests_admitted_total{class="normal"}',
        "counter"),
    "requests_admitted_low": (
        'paddle_serving_class_requests_admitted_total{class="low"}',
        "counter"),
    "tokens_emitted_high": (
        'paddle_serving_class_tokens_emitted_total{class="high"}',
        "counter"),
    "tokens_emitted_normal": (
        'paddle_serving_class_tokens_emitted_total{class="normal"}',
        "counter"),
    "tokens_emitted_low": (
        'paddle_serving_class_tokens_emitted_total{class="low"}',
        "counter"),
    "queue_depth": ("paddle_serving_queue_depth", "gauge"),
    "occupancy": ("paddle_serving_slot_occupancy", "gauge"),
    "traces": ("paddle_serving_compiled_traces_total", "counter"),
    "ttft_p50_s": ("paddle_serving_ttft_seconds", "histogram"),
    "ttft_p90_s": ("paddle_serving_ttft_seconds", "histogram"),
    "ttft_p99_s": ("paddle_serving_ttft_seconds", "histogram"),
    "latency_p50_s": ("paddle_serving_request_latency_seconds",
                      "histogram"),
    "latency_p99_s": ("paddle_serving_request_latency_seconds",
                      "histogram"),
    "prefix_hits": ("paddle_serving_prefix_hits_total", "counter"),
    "prefix_misses": ("paddle_serving_prefix_misses_total", "counter"),
    "prefix_hit_rate": ("paddle_serving_prefix_hit_rate", "gauge"),
    "prefill_tokens_saved": ("paddle_serving_prefill_tokens_saved_total",
                             "counter"),
    "prefill_tokens_computed": (
        "paddle_serving_prefill_tokens_computed_total", "counter"),
    "decode_steps": ("paddle_serving_decode_row_steps_total", "counter"),
    "draft_proposed": ("paddle_serving_draft_proposed_total", "counter"),
    "draft_accepted": ("paddle_serving_draft_accepted_total", "counter"),
    "acceptance_rate": ("paddle_serving_draft_acceptance_rate", "gauge"),
    "tokens_per_step": ("paddle_serving_tokens_per_step", "gauge"),
    "kv_blocks_total": ("paddle_serving_kv_blocks_total", "gauge"),
    "kv_blocks_used": ("paddle_serving_kv_blocks_used", "gauge"),
    "kv_blocks_free": ("paddle_serving_kv_blocks_free", "gauge"),
    "kv_cow_copies": ("paddle_serving_kv_cow_copies_total", "counter"),
    # mesh-sharded pool layout (static config gauges — constant for an
    # engine's lifetime, so reset-stable without an exemption):
    # shard_count x shard_pool_bytes == the whole pool, i.e.
    # per-device residency is dense/mp
    "kv_shard_count": ("paddle_serving_kv_shard_count", "gauge"),
    "kv_shard_heads": ("paddle_serving_kv_shard_heads", "gauge"),
    "kv_shard_pool_bytes": ("paddle_serving_kv_shard_pool_bytes",
                            "gauge"),
    # tensor-parallel weight placement (static config gauges, same
    # reset-stable discipline; never None — every engine has weights):
    # (bytes_per_device - bytes_replicated) x shard_count
    #   + bytes_replicated == the dense weight byte total
    "weight_shard_count": ("paddle_serving_weight_shard_count",
                           "gauge"),
    "weight_bytes_per_device": (
        "paddle_serving_weight_bytes_per_device", "gauge"),
    "weight_bytes_replicated": (
        "paddle_serving_weight_bytes_replicated", "gauge"),
    "budget_steps": ("paddle_serving_budget_steps_total", "counter"),
    "budget_tokens_used": ("paddle_serving_budget_tokens_used_total",
                           "counter"),
    "budget_prefill_tokens": (
        "paddle_serving_budget_prefill_tokens_total", "counter"),
    "budget_decode_tokens": (
        "paddle_serving_budget_decode_tokens_total", "counter"),
    "budget_draft_tokens": ("paddle_serving_budget_draft_tokens_total",
                            "counter"),
    # masked/pad positions the budget dispatches actually computed
    # (the flat layout's win gauge: row-aligned pays B x C - used per
    # step, the token-flattened stream ~0) — utilization is
    # used / (used + padding) by construction
    "budget_padding_tokens": (
        "paddle_serving_budget_padding_tokens_total", "counter"),
    "budget_utilization": ("paddle_serving_budget_utilization", "gauge"),
    # SLO/goodput layer: every finished request is classified against
    # the declared objectives (SloPolicy) — ok, violated-by-queueing,
    # or violated-by-slow-service; the three always sum to
    # requests_finished (conftest reconciliation)
    "slo_ok": ("paddle_serving_slo_ok_total", "counter"),
    "slo_violated_queue": ("paddle_serving_slo_violated_queue_total",
                           "counter"),
    "slo_violated_service": (
        "paddle_serving_slo_violated_service_total", "counter"),
    "queue_p50_s": ("paddle_serving_queue_time_seconds", "histogram"),
    "queue_p99_s": ("paddle_serving_queue_time_seconds", "histogram"),
    "service_p50_s": ("paddle_serving_service_time_seconds",
                      "histogram"),
    "service_p99_s": ("paddle_serving_service_time_seconds",
                      "histogram"),
}

# metrics() keys with no scalar Prometheus twin (nested dicts whose
# fields are exported under their own names below; "role" is a string
# — it exports as the labeled info gauge paddle_serving_role{role=..})
PROMETHEUS_EXEMPT_KEYS = {"prefix_store", "role"}

# metrics() keys reset_metrics legitimately does NOT restore to a fresh
# engine's values: the trace spy (documented: never reset, it IS the
# retrace contract) and allocator STATE (published prefix blocks stay
# resident across a window reset)
RESET_EXEMPT_KEYS = {"traces", "prefix_store", "kv_blocks_total",
                     "kv_blocks_used", "kv_blocks_free"}

# window counters the engine folds into its lifetime base at
# reset_metrics — exactly the counter-typed keys minus the never-reset
# trace spy
COUNTER_FOLD_KEYS = tuple(
    k for k, (_, t) in PROMETHEUS_NAMES.items()
    if t == "counter" and k != "traces")


def _fmt(v):
    return f"{float(v):.9g}"


def render_prometheus(engine):
    """Prometheus text exposition for one ServingEngine: every scalar
    metrics() key under its stable name (counters = lifetime base +
    current window, monotonic across reset_metrics), the three
    telemetry histograms, pool/prefix-store gauges, and the
    distributed-runtime section."""
    m = engine.metrics()
    base = getattr(engine, "_prom_base", {})
    lines = []
    seen = set()
    seen_fams = set()
    for key, (name, typ) in PROMETHEUS_NAMES.items():
        if typ == "histogram" or name in seen:
            continue
        v = m.get(key)
        if typ == "counter":
            v = base.get(key, 0) + (v or 0)
        elif v is None:
            continue                      # gauge with nothing to report
        seen.add(name)
        # labeled per-class series share ONE metric family: HELP/TYPE
        # are emitted once per family (label-stripped name — a TYPE
        # line naming `family{label}` is malformed text format), then
        # each labeled sample rides under it
        fam = name.split("{", 1)[0]
        if fam not in seen_fams:
            seen_fams.add(fam)
            lines.append(f"# HELP {fam} serving metric {key!r}")
            lines.append(f"# TYPE {fam} {typ}")
        lines.append(f"{name} {_fmt(v)}")
    tele = engine.telemetry
    lines.extend(tele.hist_ttft.prometheus_lines(
        "paddle_serving_ttft_seconds",
        "time to first token (submit -> first token), seconds"))
    lines.extend(tele.hist_latency.prometheus_lines(
        "paddle_serving_request_latency_seconds",
        "per-request latency (submit -> finished), seconds"))
    lines.extend(tele.hist_step_tokens.prometheus_lines(
        "paddle_serving_step_tokens",
        "tokens emitted per scheduler step"))
    lines.extend(tele.hist_queue.prometheus_lines(
        "paddle_serving_queue_time_seconds",
        "per-request queue wait (submit -> admitted), seconds"))
    lines.extend(tele.hist_service.prometheus_lines(
        "paddle_serving_service_time_seconds",
        "per-request service time (admitted -> finished), seconds"))
    lines.extend(tele.hist_handoff.prometheus_lines(
        "paddle_serving_handoff_bytes",
        "KV handoff payload size per transfer (kv + scales), bytes"))
    role = m.get("role")
    if role is not None:
        # info-style gauge: the role is a string, so it rides as a
        # label with a constant value of 1 (the Prometheus idiom for
        # enum state)
        name = "paddle_serving_role"
        lines.append(f"# HELP {name} replica role "
                     "(prefill|decode|mixed), exported as a label")
        lines.append(f"# TYPE {name} gauge")
        lines.append(f'{name}{{role="{role}"}} 1')
    if engine.pool is not None:
        g = engine.pool.gauges()
        name = "paddle_serving_kv_blocks_used_peak"
        lines.append(f"# HELP {name} kv pool residency high-water mark")
        lines.append(f"# TYPE {name} gauge")
        lines.append(f"{name} {g['kv_blocks_used_peak']}")
    if engine.prefix_cache is not None:
        st = engine.prefix_cache.store.stats()
        for k in ("blocks_used", "blocks_capacity"):
            if k not in st:
                continue
            name = f"paddle_serving_prefix_store_{k}"
            lines.append(f"# HELP {name} prefix store {k}")
            lines.append(f"# TYPE {name} gauge")
            lines.append(f"{name} {st[k]}")
    lines.extend(runtime_prometheus())
    return "\n".join(lines) + "\n"


def parse_prometheus(text):
    """Text-format parse back into ``{name{labels} or name: value}``.
    Strict enough for round-trip tests: every non-comment line must be
    ``<name>[{labels}] <float>``, and every sample must sit under a
    preceding # TYPE for its metric family."""
    samples = {}
    typed = set()
    for ln in text.splitlines():
        if not ln.strip():
            continue
        if ln.startswith("# TYPE "):
            parts = ln.split()
            if len(parts) != 4 or parts[3] not in ("counter", "gauge",
                                                   "histogram"):
                raise ValueError(f"malformed TYPE line: {ln!r}")
            typed.add(parts[2])
            continue
        if ln.startswith("#"):
            continue
        name_part, _, value = ln.rpartition(" ")
        if not name_part:
            raise ValueError(f"malformed sample line: {ln!r}")
        fam = name_part.split("{", 1)[0]
        for sfx in ("_bucket", "_sum", "_count", ""):
            if sfx and fam.endswith(sfx) and fam[: -len(sfx)] in typed:
                break
        else:
            if fam not in typed:
                raise ValueError(f"sample {fam!r} has no # TYPE line")
        samples[name_part] = float(value)
    return samples


# ------------------------------------------------------------------ export
def snapshot(engine):
    """JSON-serializable telemetry snapshot — the routing payload a
    cluster front-end polls per replica (load + affinity + headroom in
    one cheap read). Key set pinned by SNAPSHOT_REQUIRED_KEYS/
    SNAPSHOT_OPTIONAL_KEYS; bump SNAPSHOT_SCHEMA_VERSION on change."""
    m = engine.metrics()
    tele = engine.telemetry
    out = {
        "schema_version": SNAPSHOT_SCHEMA_VERSION,
        "queue_depth": m["queue_depth"],
        "occupancy": m["occupancy"],
        "num_slots": engine.num_slots,
        # free = admittable right now (neither decoding nor prefilling
        # nor parked finished): the router's slot-headroom signal
        "slots_free": len(engine._free_slots()),
        # the prefix-block alignment: the router's consistent-hash key
        # is the first prefill_cap-aligned prompt block, so every
        # replica's cap must agree and the router reads it from here
        "prefill_cap": engine.prefill_cap,
        "has_work": bool(engine.has_work),
        "tokens_per_sec": m["tokens_per_sec"],
        "requests": {k: m[f"requests_{k}"] for k in
                     ("admitted", "finished", "forked", "rejected",
                      "expired", "migrated_in", "migrated_out",
                      "preempted", "resumed")},
        # per-class queue depths (v4): the gateway's SLO-aware shed and
        # the router's placement read backlog BY CLASS — a deep
        # low-priority queue is graceful degradation, not overload
        "queue_depths": dict(engine.queue_depths()),
        "histograms": {
            "ttft_s": tele.hist_ttft.snapshot(),
            "latency_s": tele.hist_latency.snapshot(),
            "tokens_per_step": tele.hist_step_tokens.snapshot(),
            # queue-time vs service-time decomposition — the
            # autoscaler's "is the backlog queueing or slow service"
            # signal, per replica
            "queue_s": tele.hist_queue.snapshot(),
            "service_s": tele.hist_service.snapshot(),
        },
        # goodput accounting against the declared objectives (v2)
        "slo": {
            "objectives": engine._slo.objectives(),
            "ok": m["slo_ok"],
            "violated_queue": m["slo_violated_queue"],
            "violated_service": m["slo_violated_service"],
            # per-class queue-violation attribution (v4): the
            # autoscaler scales up on the HIGH class only — low-class
            # queueing under overload is the QoS layer working
            "violated_queue_by_class": dict(engine._slo_vq_class),
        },
        "budget": {k: m[f"budget_{k}"] for k in
                   ("steps", "tokens_used", "prefill_tokens",
                    "decode_tokens", "draft_tokens", "padding_tokens",
                    "utilization")},
        "prefix": {"hits": m["prefix_hits"], "misses": m["prefix_misses"],
                   "hit_rate": m["prefix_hit_rate"]},
        # v5: disaggregation — the router's placement filter (role) and
        # the KV transfer accounting the bench's zero-recompute gate
        # reconciles across the prefill/decode pools
        "role": m["role"],
        "handoff": {"kv_blocks_shipped": m["kv_blocks_shipped"],
                    "kv_blocks_adopted": m["kv_blocks_adopted"]},
        # v6: gray-failure defense — the hedge safety gate (ONLY greedy
        # streams are bit-identical across replicas, so only
        # do_sample=False traffic may hedge) and the engine's own
        # smoothed step duration (the replica-local slowness signal)
        "do_sample": bool(engine.do_sample),
        "health": {"step_ewma_s": float(
            getattr(engine, "_step_ewma_s", 0.0))},
        # v7: tensor-parallel weight placement — the per-chip HBM
        # residency of the step's weight arrays ((per_device -
        # replicated) x shard_count + replicated == dense total): the
        # capacity planner's model-fits-here signal
        # v8: + quant modes — the byte gauges report QUANTIZED
        # residency (packed stacks + scale mirrors), so the planner
        # needs the mode to size an fp replica of the same model, and
        # the router needs it to keep hedge pools mode-homogeneous
        "weights": {"shard_count": m["weight_shard_count"],
                    "bytes_per_device": m["weight_bytes_per_device"],
                    "bytes_replicated": m["weight_bytes_replicated"],
                    "weight_quant": engine.dec._weight_quant_mode(),
                    "kv_quant": ("int8" if engine.dec._int8_cache()
                                 else "none")},
        "spans_logged": len(tele.spans),
        "steps_logged": len(tele.steps),
        "telemetry_ring": tele.ring,
    }
    if engine.pool is not None:
        g = dict(engine.pool.gauges())
        # worst-case ADMISSION headroom (total minus running
        # reservations), not residency: import_slot sheds against the
        # reservation ledger, so a router deciding whether a decode
        # target can take a handoff must read this — kv_blocks_free
        # can be ample while every free block is already spoken for
        g["kv_blocks_unreserved"] = (engine.pool.num_blocks
                                     - engine._kv_reserved)
        out["kv_blocks"] = g
    if engine._drafters is not None:
        out["drafter"] = {
            "propose_calls": sum(d.propose_calls
                                 for d in engine._drafters),
            "propose_hits": sum(d.propose_hits
                                for d in engine._drafters),
        }
    return out


def trace_dump(engine):
    """JSON-serializable dump of one engine's telemetry rings — the
    per-replica payload the CLUSTER trace export merges
    (serving_cluster/trace.py): finished spans + still-live spans (a
    killed replica's stranded requests are exactly the interesting
    ones), the step timeline, and a (t_wall, t_mono) anchor pair so a
    cross-process merge can rebase every engine-clock timestamp to wall
    time — the same discipline as the flight recorder's dumps."""
    tele = engine.telemetry
    spans = []
    for sp in list(tele.spans) + list(tele._live.values()):
        spans.append({
            "rid": sp.rid, "slot": sp.slot, "state": sp.state,
            "trace_id": sp.trace_id, "attempt": sp.attempt,
            "events": [[n, float(t)] for n, t in sp.events],
        })
    return {
        "t_wall": time.time(),
        "t_mono": engine.clock(),
        "num_slots": engine.num_slots,
        "spans": spans,
        "steps": [dict(ev) for ev in tele.steps],
    }


def render_trace_dump(tr, pid, dump, us, process_name,
                      counters=False):
    """Render one engine ``trace_dump`` into ``tr`` (ChromeTrace) as
    process ``pid``: tid 0 = the dispatch timeline (one complete event
    per compiled step), tid 1..B = slots (complete span per request,
    instants for each lifecycle event), tid B+1 = requests shed from
    the queue. ONE implementation shared by ``export_chrome_tracing``
    and the cluster merge (serving_cluster/trace.py) so the
    single-engine and cluster exports cannot drift apart. ``us`` maps
    an engine-clock timestamp to trace microseconds (the caller owns
    rebasing/anchoring); ``counters=True`` adds the kv_blocks_used /
    queue_depth / budget_utilization counter tracks."""
    nslots = dump["num_slots"]
    tr.process(pid, process_name)
    tr.thread(pid, 0, "dispatch timeline")
    for s in range(nslots):
        tr.thread(pid, s + 1, f"slot {s}")
    tr.thread(pid, nslots + 1, "queue (never admitted)")
    for ev in dump["steps"]:
        args = {k: v for k, v in ev.items()
                if k not in ("kind", "t") and v is not None}
        tr.complete(ev["kind"], pid, 0, us(ev["t"]),
                    max(ev["dur_s"], 0.0) * 1e6, args=args)
        if not counters:
            continue
        t_us = us(ev["t"])
        if ev.get("kv_blocks_used") is not None:
            tr.counter("kv_blocks_used", pid, t_us,
                       {"blocks": ev["kv_blocks_used"]})
        if ev.get("queue_depth") is not None:
            tr.counter("queue_depth", pid, t_us,
                       {"requests": ev["queue_depth"]})
        if ev["kind"] == "budget":
            used = ev.get("budget_used", 0)
            cap = used + ev.get("budget_wasted", 0)
            if cap:
                tr.counter("budget_utilization", pid, t_us,
                           {"frac": round(used / cap, 4)})
    for sp in dump["spans"]:
        if not sp["events"]:
            continue
        tid = (sp["slot"] + 1 if sp["slot"] is not None
               else nslots + 1)
        t0, t1 = sp["events"][0][1], sp["events"][-1][1]
        args = {"state": sp["state"],
                "events": [[n, round(t - t0, 6)]
                           for n, t in sp["events"]]}
        if sp["trace_id"] is not None:
            args["trace_id"] = sp["trace_id"]
            args["attempt"] = sp["attempt"]
        tr.complete(f"req {sp['rid']} [{sp['state']}]", pid, tid,
                    us(t0), max(t1 - t0, 0.0) * 1e6, args=args)
        for name, t in sp["events"]:
            tr.instant(name, pid, tid, us(t))


def export_chrome_tracing(engine, path, pid=0):
    """Write the engine's telemetry rings as Chrome-trace JSON
    (chrome://tracing / Perfetto: File > Open), one pid per engine
    (``pid``) in the ``render_trace_dump`` layout with counter tracks.
    Still-live spans are included (via ``trace_dump`` — a wedged
    request is exactly the interesting one). Timestamps are the engine
    clock rebased to the earliest recorded event. Returns ``path``."""
    from ..profiler import ChromeTrace
    dump = trace_dump(engine)
    ts = [ev["t"] for ev in dump["steps"]]
    ts += [sp["events"][0][1] for sp in dump["spans"] if sp["events"]]
    base = min(ts) if ts else 0.0

    def us(t):
        return max((t - base) * 1e6, 0.0)

    tr = ChromeTrace()
    render_trace_dump(tr, pid, dump, us,
                      process_name="paddle_tpu ServingEngine",
                      counters=True)
    tr.write(path)
    return path


def validate_chrome_trace(path_or_dict):
    """Cheap structural validation of a Chrome-trace export (benches
    and tests assert on it): must json-parse, carry a traceEvents list,
    and every event must have the required ph/pid/ts fields."""
    if isinstance(path_or_dict, dict):
        doc = path_or_dict
    else:
        with open(path_or_dict) as f:
            doc = json.load(f)
    evs = doc.get("traceEvents")
    if not isinstance(evs, list):
        raise ValueError("chrome trace: no traceEvents list")
    for e in evs:
        if e.get("ph") not in ("X", "i", "C", "M"):
            raise ValueError(f"chrome trace: unknown phase in {e!r}")
        if e["ph"] != "M" and ("ts" not in e or e["ts"] < 0):
            raise ValueError(f"chrome trace: bad ts in {e!r}")
        if "pid" not in e:
            raise ValueError(f"chrome trace: missing pid in {e!r}")
    return doc
