"""Shape/layout ops. Parity: python/paddle/tensor/manipulation.py."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dtype import convert_dtype
from .tensor import Tensor, apply_op

__all__ = [
    "reshape", "reshape_", "flatten", "transpose", "moveaxis", "swapaxes",
    "squeeze", "squeeze_", "unsqueeze", "unsqueeze_", "concat", "stack",
    "split", "chunk", "tile", "expand", "expand_as", "broadcast_to",
    "flip", "fliplr", "flipud", "roll", "rot90", "gather", "gather_nd", "scatter", "scatter_nd",
    "scatter_nd_add", "index_select", "index_sample", "take_along_axis",
    "put_along_axis", "slice", "strided_slice", "unbind", "unstack",
    "repeat_interleave", "masked_select", "masked_fill", "where", "pad",
    "cast", "as_real", "as_complex", "tensordot", "unique",
    "unique_consecutive", "tolist", "crop", "shard_index", "view", "view_as",
]


def _int_tuple(v):
    if isinstance(v, Tensor):
        v = v.numpy().tolist()
    if isinstance(v, (int, np.integer)):
        return int(v)
    return tuple(int(i.item() if isinstance(i, Tensor) else i) for i in v)


def reshape(x, shape, name=None):
    shp = _int_tuple(shape)
    return apply_op(lambda a: jnp.reshape(a, shp), x)


def reshape_(x, shape, name=None):
    x._data = jnp.reshape(x._data, _int_tuple(shape))
    return x


def view(x, shape_or_dtype, name=None):
    if isinstance(shape_or_dtype, (list, tuple)):
        return reshape(x, shape_or_dtype)
    return x.astype(shape_or_dtype)


def view_as(x, other, name=None):
    return reshape(x, other.shape)


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    nd = x.ndim
    s = start_axis % nd if nd else 0
    e = stop_axis % nd if nd else 0
    shp = x.shape
    new = shp[:s] + [int(np.prod(shp[s:e + 1] or [1]))] + shp[e + 1:]
    return reshape(x, new)


def transpose(x, perm, name=None):
    p = _int_tuple(perm)
    return apply_op(lambda a: jnp.transpose(a, p), x)


def moveaxis(x, source, destination, name=None):
    return apply_op(lambda a: jnp.moveaxis(a, source, destination), x)


def swapaxes(x, axis0, axis1, name=None):
    return apply_op(lambda a: jnp.swapaxes(a, axis0, axis1), x)


def squeeze(x, axis=None, name=None):
    ax = _int_tuple(axis) if axis is not None else None
    if isinstance(ax, int):
        ax = (ax,)

    def f(a):
        if ax is None:
            return jnp.squeeze(a)
        keep = [d for d in ax if a.shape[d] == 1]
        return jnp.squeeze(a, axis=tuple(keep)) if keep else a
    return apply_op(f, x)


def squeeze_(x, axis=None, name=None):
    out = squeeze(x, axis)
    x._data = out._data
    return x


def unsqueeze(x, axis, name=None):
    ax = _int_tuple(axis)
    if isinstance(ax, int):
        ax = (ax,)

    def f(a):
        out = a
        for d in sorted(ax):
            out = jnp.expand_dims(out, d)
        return out
    return apply_op(f, x)


def unsqueeze_(x, axis, name=None):
    out = unsqueeze(x, axis)
    x._data = out._data
    return x


def concat(x, axis=0, name=None):
    tensors = list(x)
    ax = int(axis.item()) if isinstance(axis, Tensor) else int(axis)
    return apply_op(lambda *ts: jnp.concatenate(ts, axis=ax), *tensors)


def stack(x, axis=0, name=None):
    tensors = list(x)
    return apply_op(lambda *ts: jnp.stack(ts, axis=int(axis)), *tensors)


def split(x, num_or_sections, axis=0, name=None):
    ax = int(axis.item()) if isinstance(axis, Tensor) else int(axis)
    dim = x.shape[ax]
    if isinstance(num_or_sections, int):
        sizes = [dim // num_or_sections] * num_or_sections
    else:
        sizes = [int(s.item()) if isinstance(s, Tensor) else int(s)
                 for s in num_or_sections]
        n_neg = sum(1 for s in sizes if s < 0)
        if n_neg:
            rest = dim - sum(s for s in sizes if s >= 0)
            sizes = [rest if s < 0 else s for s in sizes]
    offsets = np.cumsum([0] + sizes[:-1]).tolist()
    outs = []
    for off, sz in zip(offsets, sizes):
        outs.append(apply_op(
            lambda a, o=off, s=sz: jax.lax.slice_in_dim(a, o, o + s, axis=ax), x))
    return outs


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis)


def tile(x, repeat_times, name=None):
    rt = _int_tuple(repeat_times)
    return apply_op(lambda a: jnp.tile(a, rt), x)


def expand(x, shape, name=None):
    shp = list(_int_tuple(shape))
    xs = x.shape
    full = [xs[i - (len(shp) - len(xs))] if s == -1 else s
            for i, s in enumerate(shp)]
    return apply_op(lambda a: jnp.broadcast_to(a, tuple(full)), x)


def expand_as(x, y, name=None):
    return expand(x, y.shape)


def broadcast_to(x, shape, name=None):
    return apply_op(lambda a: jnp.broadcast_to(a, _int_tuple(shape)), x)


def flip(x, axis, name=None):
    ax = _int_tuple(axis)
    return apply_op(lambda a: jnp.flip(a, axis=ax), x)


def fliplr(x, name=None):
    return apply_op(jnp.fliplr, x)


def flipud(x, name=None):
    return apply_op(jnp.flipud, x)


def roll(x, shifts, axis=None, name=None):
    return apply_op(lambda a: jnp.roll(a, shifts, axis=axis), x)


def rot90(x, k=1, axes=(0, 1), name=None):
    return apply_op(lambda a: jnp.rot90(a, k=k, axes=tuple(axes)), x)


def gather(x, index, axis=0, name=None):
    idx = index._data if isinstance(index, Tensor) else jnp.asarray(index)
    ax = int(axis.item()) if isinstance(axis, Tensor) else int(axis)
    return apply_op(lambda a: jnp.take(a, idx.reshape(-1) if idx.ndim else idx, axis=ax), x)


def gather_nd(x, index, name=None):
    idx = index._data if isinstance(index, Tensor) else jnp.asarray(index)

    def f(a):
        ind = tuple(jnp.moveaxis(idx, -1, 0))
        return a[ind]
    return apply_op(f, x)


def scatter(x, index, updates, overwrite=True, name=None):
    idx = index._data if isinstance(index, Tensor) else jnp.asarray(index)
    idx = idx.reshape(-1)

    def f(a, u):
        if overwrite:
            return a.at[idx].set(u)
        return a.at[idx].add(u)
    return apply_op(f, x, updates)


def scatter_nd(index, updates, shape, name=None):
    idx = index._data if isinstance(index, Tensor) else jnp.asarray(index)
    shp = _int_tuple(shape)

    def f(u):
        z = jnp.zeros(shp, u.dtype)
        ind = tuple(jnp.moveaxis(idx, -1, 0))
        return z.at[ind].add(u)
    return apply_op(f, updates)


def scatter_nd_add(x, index, updates, name=None):
    idx = index._data if isinstance(index, Tensor) else jnp.asarray(index)

    def f(a, u):
        ind = tuple(jnp.moveaxis(idx, -1, 0))
        return a.at[ind].add(u)
    return apply_op(f, x, updates)


def index_select(x, index, axis=0, name=None):
    return gather(x, index, axis)


def index_sample(x, index, name=None):
    idx = index._data if isinstance(index, Tensor) else jnp.asarray(index)
    return apply_op(lambda a: jnp.take_along_axis(a, idx, axis=1), x)


def take_along_axis(arr, indices, axis, broadcast=True, name=None):
    idx = indices._data if isinstance(indices, Tensor) else jnp.asarray(indices)
    return apply_op(lambda a: jnp.take_along_axis(a, idx, axis=axis), arr)


def put_along_axis(arr, indices, values, axis, reduce="assign", name=None):
    idx = indices._data if isinstance(indices, Tensor) else jnp.asarray(indices)

    def f(a, v):
        v = jnp.broadcast_to(v, idx.shape) if jnp.ndim(v) else jnp.full(idx.shape, v, a.dtype)
        dims = list(range(a.ndim))
        ind = []
        for d in dims:
            if d == axis:
                ind.append(idx)
            else:
                shape = [1] * a.ndim
                shape[d] = a.shape[d]
                ind.append(jnp.broadcast_to(
                    jnp.arange(a.shape[d]).reshape(shape), idx.shape))
        ind = tuple(ind)
        if reduce == "add":
            return a.at[ind].add(v)
        if reduce == "multiply" or reduce == "mul":
            return a.at[ind].multiply(v)
        return a.at[ind].set(v)
    if isinstance(values, Tensor):
        return apply_op(f, arr, values)
    return apply_op(lambda a: f(a, values), arr)


def slice(input, axes, starts, ends, name=None):
    starts = _int_tuple(starts)
    ends = _int_tuple(ends)

    def f(a):
        out = a
        for ax, s, e in zip(axes, starts, ends):
            n = a.shape[ax]
            s2 = max(s + n, 0) if s < 0 else min(s, n)
            e2 = max(e + n, 0) if e < 0 else min(e, n)
            out = jax.lax.slice_in_dim(out, s2, e2, axis=ax)
        return out
    return apply_op(f, input)


def strided_slice(x, axes, starts, ends, strides, name=None):
    def f(a):
        idx = [jnp.s_[:]] * a.ndim
        for ax, s, e, st in zip(axes, _int_tuple(starts), _int_tuple(ends), _int_tuple(strides)):
            idx[ax] = jnp.s_[s:e:st]
        return a[tuple(idx)]
    return apply_op(f, x)


def unbind(input, axis=0, name=None):
    n = input.shape[axis]
    return [apply_op(lambda a, i=i: jnp.take(a, i, axis=axis), input)
            for i in range(n)]


def unstack(x, axis=0, num=None, name=None):
    return unbind(x, axis)


def repeat_interleave(x, repeats, axis=None, name=None):
    r = repeats._data if isinstance(repeats, Tensor) else repeats
    return apply_op(lambda a: jnp.repeat(a, r, axis=axis), x)


def masked_select(x, mask, name=None):
    m = mask._data if isinstance(mask, Tensor) else jnp.asarray(mask)
    return Tensor(x._data[m])  # dynamic shape: not differentiable/jittable


def masked_fill(x, mask, value, name=None):
    m = mask._data if isinstance(mask, Tensor) else jnp.asarray(mask)
    v = value.item() if isinstance(value, Tensor) else value
    return apply_op(lambda a: jnp.where(m, jnp.asarray(v, a.dtype), a), x)


def where(condition, x=None, y=None, name=None):
    c = condition._data if isinstance(condition, Tensor) else jnp.asarray(condition)
    if x is None and y is None:
        nz = jnp.nonzero(c)
        return tuple(Tensor(i) for i in nz)
    if isinstance(x, Tensor) and isinstance(y, Tensor):
        return apply_op(lambda a, b: jnp.where(c, a, b), x, y)
    if isinstance(x, Tensor):
        return apply_op(lambda a: jnp.where(c, a, y), x)
    if isinstance(y, Tensor):
        return apply_op(lambda b: jnp.where(c, x, b), y)
    return Tensor(jnp.where(c, x, y))


def nonzero(x, as_tuple=False):
    nz = jnp.nonzero(x._data)
    if as_tuple:
        return tuple(Tensor(i) for i in nz)
    return Tensor(jnp.stack(nz, axis=-1))


__all__.append("nonzero")


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    p = _int_tuple(pad)

    def f(a):
        nd = a.ndim
        if len(p) == 2 * nd:
            widths = [(p[2 * i], p[2 * i + 1]) for i in range(nd)]
        else:
            # paddle convention: pad applies to the trailing dims, reversed pairs
            k = len(p) // 2
            widths = [(0, 0)] * (nd - k)
            if data_format.upper().startswith("NC") and len(p) in (2, 4, 6) and nd >= 3:
                spatial = [(p[2 * i], p[2 * i + 1]) for i in range(k)]
                widths = [(0, 0), (0, 0)] + spatial
                widths += [(0, 0)] * (nd - len(widths))
            else:
                widths += [(p[2 * i], p[2 * i + 1]) for i in range(k)]
        jmode = {"constant": "constant", "reflect": "reflect",
                 "replicate": "edge", "circular": "wrap"}[mode]
        if jmode == "constant":
            return jnp.pad(a, widths, mode=jmode, constant_values=value)
        return jnp.pad(a, widths, mode=jmode)
    return apply_op(f, x)


def cast(x, dtype):
    return x.astype(convert_dtype(dtype))


def as_real(x, name=None):
    return apply_op(lambda a: jnp.stack([jnp.real(a), jnp.imag(a)], axis=-1), x)


def as_complex(x, name=None):
    return apply_op(lambda a: a[..., 0] + 1j * a[..., 1], x)


def tensordot(x, y, axes=2, name=None):
    return apply_op(lambda a, b: jnp.tensordot(a, b, axes=axes), x, y)


def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None, dtype="int64", name=None):
    res = jnp.unique(x._data, return_index=return_index,
                     return_inverse=return_inverse,
                     return_counts=return_counts, axis=axis)
    if isinstance(res, tuple):
        return tuple(Tensor(r) for r in res)
    return Tensor(res)


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None,
                       dtype="int64", name=None):
    arr = np.asarray(x._data)
    if axis is None:
        arr = arr.reshape(-1)
    keep = np.ones(arr.shape[0], bool)
    keep[1:] = np.any(arr[1:] != arr[:-1], axis=tuple(range(1, arr.ndim))) \
        if arr.ndim > 1 else arr[1:] != arr[:-1]
    out = [Tensor(jnp.asarray(arr[keep]))]
    if return_inverse:
        inv = np.cumsum(keep) - 1
        out.append(Tensor(jnp.asarray(inv)))
    if return_counts:
        idx = np.flatnonzero(keep)
        counts = np.diff(np.append(idx, arr.shape[0]))
        out.append(Tensor(jnp.asarray(counts)))
    return out[0] if len(out) == 1 else tuple(out)


def tolist(x):
    return x.tolist()


def crop(x, shape=None, offsets=None, name=None):
    shp = _int_tuple(shape)
    off = _int_tuple(offsets) if offsets is not None else (0,) * x.ndim

    def f(a):
        sl = tuple(jnp.s_[o:o + s] for o, s in zip(off, shp))
        return a[sl]
    return apply_op(f, x)


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    size = (index_num + nshards - 1) // nshards

    def f(a):
        shard = a // size
        local = a % size
        return jnp.where(shard == shard_id, local, ignore_value)
    return apply_op(f, input)


def take(x, index, mode="raise", name=None):
    """Flattened-gather (paddle.take). mode: 'raise'/'wrap'/'clip'."""
    idx = index._data if isinstance(index, Tensor) else jnp.asarray(index)

    def f(a):
        flat = a.reshape(-1)
        n = flat.shape[0]
        i = idx
        if mode == "wrap":
            i = ((i % n) + n) % n
        else:               # 'clip' (and 'raise' — no host check under jit)
            i = jnp.clip(jnp.where(i < 0, i + n, i), 0, n - 1)
        return flat[i]
    return apply_op(f, x)


def msort(x, name=None):
    return apply_op(lambda a: jnp.sort(a, axis=0), x)


def diag_embed(input, offset=0, dim1=-2, dim2=-1, name=None):
    def f(a):
        n = a.shape[-1] + abs(int(offset))
        base = jnp.zeros(a.shape[:-1] + (n, n), a.dtype)
        rng = jnp.arange(a.shape[-1])
        r = rng + max(-int(offset), 0)
        c = rng + max(int(offset), 0)
        out = base.at[..., r, c].set(a)
        nd = out.ndim
        d1 = dim1 % nd
        d2 = dim2 % nd
        perm = [i for i in range(nd) if i not in (nd - 2, nd - 1)]
        # place the two new axes at dim1/dim2
        order = []
        src = {d1: nd - 2, d2: nd - 1}
        it = iter(perm)
        for pos in range(nd):
            order.append(src.get(pos, None) if pos in src else next(it))
        return jnp.transpose(out, order)
    return apply_op(f, input)


def unfold(x, axis, size, step, name=None):
    """Sliding windows along `axis` (torch.Tensor.unfold semantics, which
    paddle.unfold for tensors follows): returns windows stacked on a new
    trailing dim."""
    def f(a):
        ax = int(axis) % a.ndim
        n = (a.shape[ax] - size) // step + 1
        starts = jnp.arange(n) * step
        def take_win(s):
            return jax.lax.dynamic_slice_in_dim(a, s, size, axis=ax)
        wins = jax.vmap(take_win)(starts)          # [n, ..., size, ...]
        wins = jnp.moveaxis(wins, 0, ax)           # windows sit at `axis`
        return jnp.moveaxis(wins, ax + 1, -1)      # window content last
    return apply_op(f, x)


def index_add(x, index, axis, value, name=None):
    idx = index._data if isinstance(index, Tensor) else jnp.asarray(index)

    def f(a, v):
        moved = jnp.moveaxis(a, int(axis), 0)
        vm = jnp.moveaxis(v, int(axis), 0)
        out = moved.at[idx].add(vm.astype(moved.dtype))
        return jnp.moveaxis(out, 0, int(axis))
    if isinstance(value, Tensor):
        return apply_op(f, x, value)
    return apply_op(lambda a: f(a, jnp.asarray(value)), x)


def index_add_(x, index, axis, value, name=None):
    out = index_add(x, index, axis, value)
    x._data = out._data
    return x


def index_put(x, indices, value, accumulate=False, name=None):
    ids = tuple(i._data if isinstance(i, Tensor) else jnp.asarray(i)
                for i in indices)

    def f(a, v):
        ref = a.at[ids]
        v = v.astype(a.dtype)
        return ref.add(v) if accumulate else ref.set(v)
    if isinstance(value, Tensor):
        return apply_op(f, x, value)
    return apply_op(lambda a: f(a, jnp.asarray(value)), x)


def index_put_(x, indices, value, accumulate=False, name=None):
    out = index_put(x, indices, value, accumulate)
    x._data = out._data
    return x


__all__ += ["take", "msort", "diag_embed", "unfold", "index_add",
            "index_add_", "index_put", "index_put_"]


# ---- round-2 breadth: stack/split families + scatter views ----------------
import builtins as _builtins  # paddle's slice() op shadows the builtin here
# Parity: python/paddle/tensor/manipulation.py 2.6 additions (atleast_*,
# *_stack, *split, index_fill, masked_scatter, as_strided, unflatten,
# select/slice/diagonal_scatter).

def _seq(xs):
    return [x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))
            for x in (xs if isinstance(xs, (list, tuple)) else [xs])]


def atleast_1d(*inputs, name=None):
    outs = [apply_op(jnp.atleast_1d, x) for x in _seq(list(inputs))]
    return outs[0] if len(outs) == 1 else outs


def atleast_2d(*inputs, name=None):
    outs = [apply_op(jnp.atleast_2d, x) for x in _seq(list(inputs))]
    return outs[0] if len(outs) == 1 else outs


def atleast_3d(*inputs, name=None):
    outs = [apply_op(jnp.atleast_3d, x) for x in _seq(list(inputs))]
    return outs[0] if len(outs) == 1 else outs


def broadcast_tensors(inputs, name=None):
    ts = _seq(inputs)
    shape = jnp.broadcast_shapes(*[tuple(t.shape) for t in ts])
    return [apply_op(lambda a: jnp.broadcast_to(a, shape), t) for t in ts]


def block_diag(inputs, name=None):
    ts = _seq(inputs)
    return apply_op(lambda *as_: jax.scipy.linalg.block_diag(
        *[jnp.atleast_2d(a) for a in as_]), *ts)


def hstack(x, name=None):
    return apply_op(lambda *as_: jnp.hstack(as_), *_seq(x))


def vstack(x, name=None):
    return apply_op(lambda *as_: jnp.vstack(as_), *_seq(x))


def dstack(x, name=None):
    return apply_op(lambda *as_: jnp.dstack(as_), *_seq(x))


def column_stack(x, name=None):
    return apply_op(lambda *as_: jnp.column_stack(as_), *_seq(x))


def row_stack(x, name=None):
    return vstack(x)


def tensor_split(x, num_or_indices, axis=0, name=None):
    n = x.shape[axis] if hasattr(x, "shape") else None
    if isinstance(num_or_indices, int):
        k = num_or_indices
        base, extra = divmod(n, k)
        sizes = [base + (1 if i < extra else 0) for i in range(k)]
        bounds = list(np.cumsum(sizes))[:-1]  # empty chunks allowed (k > n)
    else:
        bounds = [int(b) for b in num_or_indices]
    outs = []
    prev = 0
    for b in bounds + [n]:
        sl = [_builtins.slice(None)] * len(x.shape)
        sl[axis] = _builtins.slice(prev, b)
        outs.append(apply_op(lambda a, s=tuple(sl): a[s], x))
        prev = b
    return outs


def hsplit(x, num_or_indices, name=None):
    assert len(x.shape) >= 1
    return tensor_split(x, num_or_indices,
                        axis=0 if len(x.shape) == 1 else 1)


def vsplit(x, num_or_indices, name=None):
    assert len(x.shape) >= 2, "vsplit needs ndim >= 2"
    return tensor_split(x, num_or_indices, axis=0)


def dsplit(x, num_or_indices, name=None):
    assert len(x.shape) >= 3, "dsplit needs ndim >= 3"
    return tensor_split(x, num_or_indices, axis=2)


def index_fill(x, index, axis, value, name=None):
    idx = index._data if isinstance(index, Tensor) else jnp.asarray(index)

    def f(a):
        moved = jnp.moveaxis(a, axis, 0)
        filled = moved.at[idx].set(jnp.asarray(value, a.dtype))
        return jnp.moveaxis(filled, 0, axis)
    return apply_op(f, x)


def masked_scatter(x, mask, value, name=None):
    """Fill x where mask with consecutive elements of value (row-major)."""
    m = mask._data if isinstance(mask, Tensor) else jnp.asarray(mask)
    n_true = int(jnp.broadcast_to(m, tuple(x.shape)).sum())
    v_size = int(np.prod(value.shape)) if hasattr(value, "shape") \
        else jnp.asarray(value).size
    if v_size < n_true:
        raise ValueError(
            f"masked_scatter: value has {v_size} elements but mask selects "
            f"{n_true} positions")

    def f(a, v):
        mb = jnp.broadcast_to(m, a.shape).ravel()
        flat = a.ravel()
        # slot i takes value[rank-of-i-among-true]; clip keeps gather static
        pos = jnp.cumsum(mb) - 1
        gathered = jnp.take(v.ravel(), jnp.clip(pos, 0, v.size - 1))
        return jnp.where(mb, gathered, flat).reshape(a.shape)
    if isinstance(value, Tensor):
        return apply_op(f, x, value)
    return apply_op(lambda a: f(a, jnp.asarray(value)), x)


def as_strided(x, shape, stride, offset=0, name=None):
    """Strided view over the flat buffer (gather realization: XLA has no
    aliasing views, so this materializes the gather — same numerics)."""
    shape = tuple(int(s) for s in shape)
    stride = tuple(int(s) for s in stride)
    idx = jnp.asarray(offset)
    for s, st in zip(shape, stride):
        idx = idx[..., None] + jnp.arange(s) * st
    return apply_op(lambda a: a.ravel()[idx], x)


def unflatten(x, axis, shape, name=None):
    shape = list(shape)
    ax = axis % len(x.shape)
    if -1 in shape:
        known = 1
        for s in shape:
            if s != -1:
                known *= s
        shape[shape.index(-1)] = x.shape[ax] // known
    new_shape = list(x.shape[:ax]) + shape + list(x.shape[ax + 1:])
    return apply_op(lambda a: a.reshape(new_shape), x)


def select_scatter(x, values, axis, index, name=None):
    def f(a, v):
        sl = [_builtins.slice(None)] * a.ndim
        sl[axis] = index
        return a.at[tuple(sl)].set(v.astype(a.dtype))
    if isinstance(values, Tensor):
        return apply_op(f, x, values)
    return apply_op(lambda a: f(a, jnp.asarray(values)), x)


def slice_scatter(x, value, axes, starts, ends, strides, name=None):
    def f(a, v):
        sl = [_builtins.slice(None)] * a.ndim
        for ax, st, en, sd in zip(axes, starts, ends, strides):
            sl[ax] = _builtins.slice(int(st), int(en), int(sd))
        return a.at[tuple(sl)].set(v.astype(a.dtype))
    if isinstance(value, Tensor):
        return apply_op(f, x, value)
    return apply_op(lambda a: f(a, jnp.asarray(value)), x)


def diagonal_scatter(x, y, offset=0, axis1=0, axis2=1, name=None):
    def f(a, v):
        moved = jnp.moveaxis(a, (axis1, axis2), (-2, -1))
        n, m = moved.shape[-2:]
        rows = jnp.arange(max(min(n, m - offset) if offset >= 0
                              else min(n + offset, m), 0))
        r = rows - min(offset, 0)
        c = rows + max(offset, 0)
        out = moved.at[..., r, c].set(v.astype(a.dtype))
        return jnp.moveaxis(out, (-2, -1), (axis1, axis2))
    if isinstance(y, Tensor):
        return apply_op(f, x, y)
    return apply_op(lambda a: f(a, jnp.asarray(y)), x)


__all__ += ["atleast_1d", "atleast_2d", "atleast_3d", "broadcast_tensors",
            "block_diag", "hstack", "vstack", "dstack", "column_stack",
            "row_stack", "tensor_split", "hsplit", "vsplit", "dsplit",
            "index_fill", "masked_scatter",
            "as_strided", "unflatten", "select_scatter",
            "slice_scatter", "diagonal_scatter"]


def argwhere(x, name=None):
    """Indices of nonzero elements, [n, ndim] (alias family of nonzero)."""
    return nonzero(x)


def combinations(x, r=2, with_replacement=False, name=None):
    import itertools as _it
    n = x.shape[0]
    gen = _it.combinations_with_replacement(range(n), r) \
        if with_replacement else _it.combinations(range(n), r)
    idx = np.asarray(list(gen), np.int32).reshape(-1, r)
    return apply_op(lambda a: a[jnp.asarray(idx)], x)


def cartesian_prod(x, name=None):
    """Cartesian product of 1-D tensors (reference: paddle.cartesian_prod).
    Returns [prod(len_i), n] (or 1-D for a single input)."""
    xs = x if isinstance(x, (list, tuple)) else [x]

    def fn(*arrs):
        if len(arrs) == 1:
            return arrs[0]
        grids = jnp.meshgrid(*arrs, indexing="ij")
        return jnp.stack([g.ravel() for g in grids], axis=-1)
    return apply_op(fn, *xs)


def matrix_transpose(x, name=None):
    from .linalg import t
    return t(x)


def nonzero_static(x, size, fill_value=-1, name=None):
    """Static-shape nonzero: first `size` indices, padded with fill_value
    (the jit-safe variant the reference added for dynamic-shape-free
    graphs — exactly the TPU-native contract). Output is ALWAYS
    [size, ndim], padding past numel too."""
    def fn(a):
        flat = (a != 0).ravel()
        order = jnp.argsort(~flat, stable=True)  # nonzeros first
        n = flat.shape[0]
        sel = jnp.pad(order, (0, max(size - n, 0)))[:size]
        coords = jnp.stack(jnp.unravel_index(sel, a.shape), axis=-1)
        in_range = jnp.arange(size) < n
        valid = (jnp.pad(flat[order], (0, max(size - n, 0)))[:size]
                 & in_range)[:, None]
        return jnp.where(valid, coords,
                         jnp.asarray(fill_value, coords.dtype))
    return apply_op(fn, x)


__all__ += ["argwhere", "cartesian_prod", "combinations",
            "matrix_transpose", "nonzero_static"]


def reverse(x, axis, name=None):
    """Legacy-compat alias of flip (reference: fluid.layers.reverse — the
    2.5-era name the migration docs map to paddle.flip)."""
    return flip(x, axis)


def unique_with_counts(x, dtype="int32", name=None):
    """Legacy 1.x API: (unique values, index-of-each-element, counts).
    Modern unique() covers it; kept for reference-corpus parity."""
    out, inverse, counts = unique(x, return_inverse=True,
                                  return_counts=True)
    return out, inverse.astype(dtype), counts.astype(dtype)


def flatten_(x, start_axis=0, stop_axis=-1, name=None):
    """In-place VIEW variant of flatten — same contract as the existing
    reshape_/squeeze_/unsqueeze_ family: a metadata-only edit outside the
    tape (the reference treats view in-place ops as always legal; use the
    out-of-place flatten when the reshape must be differentiated)."""
    out = flatten(x, start_axis, stop_axis)
    x._data = out._data
    return x


__all__ += ["reverse", "unique_with_counts", "flatten_"]


def shape(input, name=None):
    """Shape as an int32 tensor (modern paddle.shape op)."""
    return Tensor(jnp.asarray(np.asarray(input.shape), jnp.int32))


def rank(input, name=None):
    """Rank (ndim) as a 0-D int32 tensor (paddle.rank)."""
    return Tensor(jnp.asarray(len(input.shape), jnp.int32))


__all__ += ["shape", "rank"]
