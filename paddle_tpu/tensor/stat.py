"""Statistics ops. Parity: python/paddle/tensor/stat.py."""
from __future__ import annotations

import jax.numpy as jnp

from .tensor import Tensor, apply_op

__all__ = ["mean", "std", "var", "median", "nanmedian", "quantile",
           "nanquantile", "histogram", "bincount", "corrcoef", "cov"]


def _axis(axis):
    if axis is None:
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def mean(x, axis=None, keepdim=False, name=None):
    return apply_op(lambda a: jnp.mean(a, axis=_axis(axis), keepdims=keepdim), x)


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    ddof = 1 if unbiased else 0
    return apply_op(lambda a: jnp.std(a, axis=_axis(axis), ddof=ddof,
                                      keepdims=keepdim), x)


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    ddof = 1 if unbiased else 0
    return apply_op(lambda a: jnp.var(a, axis=_axis(axis), ddof=ddof,
                                      keepdims=keepdim), x)


def median(x, axis=None, keepdim=False, mode="avg", name=None):
    return apply_op(lambda a: jnp.median(a, axis=_axis(axis), keepdims=keepdim), x)


def nanmedian(x, axis=None, keepdim=False, name=None):
    return apply_op(lambda a: jnp.nanmedian(a, axis=_axis(axis), keepdims=keepdim), x)


def quantile(x, q, axis=None, keepdim=False, interpolation="linear", name=None):
    return apply_op(lambda a: jnp.quantile(a, jnp.asarray(q), axis=_axis(axis),
                                           keepdims=keepdim,
                                           method=interpolation), x)


def nanquantile(x, q, axis=None, keepdim=False, name=None):
    return apply_op(lambda a: jnp.nanquantile(a, jnp.asarray(q),
                                              axis=_axis(axis),
                                              keepdims=keepdim), x)


def histogram(input, bins=100, min=0, max=0, name=None):
    a = input._data
    if min == 0 and max == 0:
        lo, hi = a.min(), a.max()
    else:
        lo, hi = min, max
    h, _ = jnp.histogram(a, bins=bins, range=(lo, hi))
    return Tensor(h.astype(jnp.int64))


def bincount(x, weights=None, minlength=0, name=None):
    w = weights._data if weights is not None else None
    return Tensor(jnp.bincount(x._data.astype(jnp.int32), weights=w,
                               minlength=minlength))


def corrcoef(x, rowvar=True, name=None):
    return Tensor(jnp.corrcoef(x._data, rowvar=rowvar))


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    return apply_op(lambda a: jnp.cov(a, rowvar=rowvar,
                                      ddof=1 if ddof else 0), x)


# ---- round-2 breadth ------------------------------------------------------

def nanstd(x, axis=None, ddof=0, keepdim=False, name=None):
    return apply_op(
        lambda a: jnp.nanstd(a, axis=axis, ddof=ddof, keepdims=keepdim), x)


def nanvar(x, axis=None, ddof=0, keepdim=False, name=None):
    return apply_op(
        lambda a: jnp.nanvar(a, axis=axis, ddof=ddof, keepdims=keepdim), x)


__all__ += ["nanstd", "nanvar"]
