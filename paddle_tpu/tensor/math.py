"""Math ops. Parity: python/paddle/tensor/math.py (~the paddle.* math surface).

Every op is a thin differentiable wrapper over jnp via apply_op; XLA fuses the
elementwise chains into surrounding matmuls on TPU, so there is no per-op
kernel zoo to maintain (the reference's paddle/phi/kernels/gpu/ role is played
by XLA codegen here).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .tensor import Tensor, apply_op, no_grad

__all__: list = []


def _export(name, fn):
    globals()[name] = fn
    __all__.append(name)


def _unary(name, jfn):
    def op(x, name=None, **kw):
        return apply_op(jfn, x)
    op.__name__ = name
    _export(name, op)


def _binary(name, jfn):
    def op(x, y, name=None, **kw):
        if isinstance(y, Tensor):
            return apply_op(jfn, x, y)
        return apply_op(lambda a: jfn(a, y), x)
    op.__name__ = name
    _export(name, op)


for _n, _f in dict(
    abs=jnp.abs, acos=jnp.arccos, acosh=jnp.arccosh, asin=jnp.arcsin,
    asinh=jnp.arcsinh, atan=jnp.arctan, atanh=jnp.arctanh, ceil=jnp.ceil,
    cos=jnp.cos, cosh=jnp.cosh, deg2rad=jnp.deg2rad, digamma=jax.scipy.special.digamma,
    erf=jax.scipy.special.erf, erfinv=jax.scipy.special.erfinv, exp=jnp.exp,
    expm1=jnp.expm1, floor=jnp.floor, frac=lambda x: x - jnp.trunc(x),
    i0=jnp.i0, lgamma=jax.scipy.special.gammaln, log=jnp.log, log10=jnp.log10,
    log1p=jnp.log1p, log2=jnp.log2, neg=jnp.negative, rad2deg=jnp.rad2deg,
    reciprocal=jnp.reciprocal, round=jnp.round, rsqrt=jax.lax.rsqrt,
    sign=jnp.sign, sgn=jnp.sign, sin=jnp.sin, sinh=jnp.sinh, sqrt=jnp.sqrt,
    square=jnp.square, tan=jnp.tan, tanh=jnp.tanh, trunc=jnp.trunc,
    angle=jnp.angle, conj=jnp.conj, real=jnp.real, imag=jnp.imag,
    sigmoid=jax.nn.sigmoid, logit=jax.scipy.special.logit,
).items():
    _unary(_n, _f)

for _n, _f in dict(
    add=jnp.add, subtract=jnp.subtract, multiply=jnp.multiply,
    divide=jnp.divide, floor_divide=jnp.floor_divide, mod=jnp.mod,
    remainder=jnp.remainder, pow=jnp.power, atan2=jnp.arctan2,
    fmax=jnp.fmax, fmin=jnp.fmin, maximum=jnp.maximum, minimum=jnp.minimum,
    logaddexp=jnp.logaddexp, hypot=jnp.hypot, copysign=jnp.copysign,
    nextafter=jnp.nextafter, ldexp=lambda x, y: x * (2.0 ** y),
    heaviside=jnp.heaviside, gcd=jnp.gcd, lcm=jnp.lcm,
    bitwise_and=jnp.bitwise_and, bitwise_or=jnp.bitwise_or,
    bitwise_xor=jnp.bitwise_xor,
).items():
    _binary(_n, _f)


def bitwise_not(x, name=None):
    return apply_op(jnp.bitwise_not, x)


_export("bitwise_not", bitwise_not)
_export("bitwise_invert", bitwise_not)
_export("gammaln", lambda x, name=None: apply_op(
    jax.scipy.special.gammaln, x))


def _axis(axis):
    if axis is None:
        return None
    if isinstance(axis, Tensor):
        axis = axis.numpy().tolist()
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def _reduce(name, jfn, int_promote=False):
    def op(x, axis=None, keepdim=False, name=None, dtype=None, **kw):
        ax = _axis(axis)

        def f(a):
            out = jfn(a, axis=ax, keepdims=keepdim)
            return out
        return apply_op(f, x)
    op.__name__ = name
    _export(name, op)


for _n, _f in dict(
    sum=jnp.sum, mean=jnp.mean, prod=jnp.prod, max=jnp.max, min=jnp.min,
    amax=jnp.amax, amin=jnp.amin, nansum=jnp.nansum, nanmean=jnp.nanmean,
    logsumexp=jax.scipy.special.logsumexp,
    all=jnp.all, any=jnp.any,
).items():
    _reduce(_n, _f)


def count_nonzero(x, axis=None, keepdim=False, name=None):
    return Tensor(jnp.count_nonzero(x._data, axis=_axis(axis), keepdims=keepdim))


_export("count_nonzero", count_nonzero)


def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    def f(a, b):
        from ..amp.auto_cast import cast_if_amp
        a, b = cast_if_amp("matmul", a, b)
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2) if a.ndim > 1 else a
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2) if b.ndim > 1 else b
        return jnp.matmul(a, b)
    return apply_op(f, x, y)


_export("matmul", matmul)


def mm(x, y, name=None):
    return matmul(x, y)


_export("mm", mm)


def bmm(x, y, name=None):
    return apply_op(jnp.matmul, x, y)


_export("bmm", bmm)


def baddbmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    """beta*input + alpha*(x @ y) over batched matrices (reference
    baddbmm); one fused XLA dot + scaled add."""
    return apply_op(
        lambda i, a, b: beta * i + alpha * jnp.matmul(a, b), input, x, y)


_export("baddbmm", baddbmm)


def dot(x, y, name=None):
    return apply_op(lambda a, b: jnp.sum(a * b, axis=-1), x, y)


_export("dot", dot)


def inner(x, y, name=None):
    return apply_op(jnp.inner, x, y)


_export("inner", inner)


def outer(x, y, name=None):
    return apply_op(lambda a, b: jnp.outer(a, b), x, y)


_export("outer", outer)


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return apply_op(lambda i, a, b: beta * i + alpha * jnp.matmul(a, b), input, x, y)


_export("addmm", addmm)


def clip(x, min=None, max=None, name=None):
    lo = min.item() if isinstance(min, Tensor) else min
    hi = max.item() if isinstance(max, Tensor) else max
    return apply_op(lambda a: jnp.clip(a, lo, hi), x)


_export("clip", clip)


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    s = scale.item() if isinstance(scale, Tensor) else scale

    def f(a):
        out = a * s + bias if bias_after_scale else (a + bias) * s
        return out
    out = apply_op(f, x)
    if act:
        from ..nn import functional as F
        out = getattr(F, act)(out)
    return out


_export("scale", scale)


def increment(x, value=1.0, name=None):
    x._data = x._data + value
    return x


_export("increment", increment)


def cumsum(x, axis=None, dtype=None, name=None):
    def f(a):
        if axis is None:
            return jnp.cumsum(a.reshape(-1))
        return jnp.cumsum(a, axis=int(axis))
    return apply_op(f, x)


_export("cumsum", cumsum)


def cumprod(x, dim=None, dtype=None, name=None):
    return apply_op(lambda a: jnp.cumprod(a, axis=int(dim)), x)


_export("cumprod", cumprod)


def _cum_minmax_indices(arr, ax, is_min):
    """Indices of the running extremum, first occurrence on ties: an O(n)
    associative scan over (value, index) pairs — lexicographic min/max with
    the earlier index winning equal values."""
    idx = jax.lax.broadcasted_iota(jnp.int32, arr.shape, ax)

    def combine(l, r):
        lv, li = l
        rv, ri = r
        take_r = (rv < lv) if is_min else (rv > lv)
        return (jnp.where(take_r, rv, lv), jnp.where(take_r, ri, li))

    _, inds = jax.lax.associative_scan(combine, (arr, idx), axis=ax)
    return inds


def _cum_minmax(x, axis, is_min):
    def f(a):
        a = a.reshape(-1) if axis is None else a
        ax = 0 if axis is None else int(axis)
        return (jax.lax.cummin if is_min else jax.lax.cummax)(a, axis=ax)
    vals = apply_op(f, x)
    arr = x._data.reshape(-1) if axis is None else x._data
    ax = 0 if axis is None else int(axis)
    inds = _cum_minmax_indices(arr, ax, is_min)
    return vals, Tensor(inds.astype(jnp.int64))


def cummax(x, axis=None, dtype="int64", name=None):
    return _cum_minmax(x, axis, is_min=False)


_export("cummax", cummax)


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return apply_op(lambda a: jnp.trace(a, offset=offset, axis1=axis1, axis2=axis2), x)


_export("trace", trace)


def kron(x, y, name=None):
    return apply_op(jnp.kron, x, y)


_export("kron", kron)


def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    pre = prepend._data if isinstance(prepend, Tensor) else prepend
    app = append._data if isinstance(append, Tensor) else append
    return apply_op(lambda a: jnp.diff(a, n=n, axis=axis, prepend=pre, append=app), x)


_export("diff", diff)


def isnan(x, name=None):
    return Tensor(jnp.isnan(x._data))


def isinf(x, name=None):
    return Tensor(jnp.isinf(x._data))


def isfinite(x, name=None):
    return Tensor(jnp.isfinite(x._data))


for _n in ("isnan", "isinf", "isfinite"):
    _export(_n, globals()[_n])


def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    return apply_op(lambda a: jnp.nan_to_num(a, nan=nan, posinf=posinf, neginf=neginf), x)


_export("nan_to_num", nan_to_num)


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return apply_op(lambda a: scale_b * jnp.tanh(scale_a * a), x)


_export("stanh", stanh)


def multiply_(x, y, name=None):
    x._data = x._data * (y._data if isinstance(y, Tensor) else y)
    return x


def add_(x, y, name=None):
    x._data = x._data + (y._data if isinstance(y, Tensor) else y)
    return x


def subtract_(x, y, name=None):
    x._data = x._data - (y._data if isinstance(y, Tensor) else y)
    return x


def scale_(x, scale=1.0, bias=0.0, bias_after_scale=True, name=None):
    x._data = x._data * scale + bias if bias_after_scale else (x._data + bias) * scale
    return x


def clip_(x, min=None, max=None, name=None):
    x._data = jnp.clip(x._data, min, max)
    return x


for _n in ("multiply_", "add_", "subtract_", "scale_", "clip_"):
    _export(_n, globals()[_n])


def floor_mod(x, y, name=None):
    return globals()["mod"](x, y)


_export("floor_mod", floor_mod)


def divide_no_nan(x, y, name=None):
    return apply_op(lambda a, b: jnp.where(b == 0, 0.0, a / jnp.where(b == 0, 1.0, b)), x, y)


_export("divide_no_nan", divide_no_nan)


def lerp(x, y, weight, name=None):
    if isinstance(weight, Tensor):
        return apply_op(lambda a, b, w: a + w * (b - a), x, y, weight)
    return apply_op(lambda a, b: a + weight * (b - a), x, y)


_export("lerp", lerp)


def einsum(equation, *operands):
    return apply_op(functools.partial(jnp.einsum, equation), *operands)


_export("einsum", einsum)


def multi_dot(xs, name=None):
    return apply_op(lambda *ts: jnp.linalg.multi_dot(ts), *xs)


_export("multi_dot", multi_dot)


def broadcast_shape(x_shape, y_shape):
    return list(jnp.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


_export("broadcast_shape", broadcast_shape)


def cummin(x, axis=None, dtype="int64", name=None):
    """Parity: paddle.cummin — returns (values, indices of first min)."""
    return _cum_minmax(x, axis, is_min=True)


_export("cummin", cummin)


def logcumsumexp(x, axis=None, dtype=None, name=None):
    def f(a):
        if axis is None:
            return jax.lax.cumlogsumexp(a.reshape(-1))
        return jax.lax.cumlogsumexp(a, axis=int(axis))
    return apply_op(f, x)


_export("logcumsumexp", logcumsumexp)


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return apply_op(lambda a: jnp.diagonal(a, offset=offset, axis1=axis1,
                                           axis2=axis2), x)


_export("diagonal", diagonal)


def vander(x, n=None, increasing=False, name=None):
    return apply_op(lambda a: jnp.vander(a, N=n, increasing=increasing), x)


_export("vander", vander)


def renorm(x, p, axis, max_norm, name=None):
    """Renormalize slices along `axis` whose p-norm exceeds max_norm."""
    def f(a):
        moved = jnp.moveaxis(a, int(axis), 0)
        flat = moved.reshape(moved.shape[0], -1)
        norms = jnp.linalg.norm(flat, ord=p, axis=1)
        factor = jnp.where(norms > max_norm,
                           max_norm / jnp.maximum(norms, 1e-12), 1.0)
        out = flat * factor[:, None]
        return jnp.moveaxis(out.reshape(moved.shape), 0, int(axis))
    return apply_op(f, x)


_export("renorm", renorm)


def frexp(x, name=None):
    m, e = jnp.frexp(x._data if isinstance(x, Tensor) else jnp.asarray(x))
    return Tensor(m), Tensor(e.astype(jnp.int32))


_export("frexp", frexp)


def trapezoid(y, x=None, dx=None, axis=-1, name=None):
    if x is not None:
        if isinstance(x, Tensor):
            return apply_op(lambda a, b: jnp.trapezoid(a, b, axis=axis), y, x)
        return apply_op(lambda a: jnp.trapezoid(a, jnp.asarray(x),
                                                axis=axis), y)
    return apply_op(lambda a: jnp.trapezoid(a, dx=dx or 1.0, axis=axis), y)


_export("trapezoid", trapezoid)


def cumulative_trapezoid(y, x=None, dx=None, axis=-1, name=None):
    """Running trapezoid integral along axis; shape [..., n-1] (scipy
    semantics, no initial zero)."""
    def seg(a, xs):
        ax = int(axis) % a.ndim
        a0 = jax.lax.slice_in_dim(a, 0, a.shape[ax] - 1, axis=ax)
        a1 = jax.lax.slice_in_dim(a, 1, a.shape[ax], axis=ax)
        if xs is None:
            w = dx if dx is not None else 1.0
            segs = (a0 + a1) * 0.5 * w
        else:
            x0 = jax.lax.slice_in_dim(xs, 0, xs.shape[-1] - 1, axis=-1)
            x1 = jax.lax.slice_in_dim(xs, 1, xs.shape[-1], axis=-1)
            d = (x1 - x0)
            shape = [1] * a.ndim
            shape[ax] = d.shape[-1]
            segs = (a0 + a1) * 0.5 * d.reshape(shape)
        return jnp.cumsum(segs, axis=ax)
    if x is not None:
        xs = x._data if isinstance(x, Tensor) else jnp.asarray(x)
        return apply_op(lambda a: seg(a, xs), y)
    return apply_op(lambda a: seg(a, None), y)


_export("cumulative_trapezoid", cumulative_trapezoid)


def histogram_bin_edges(x, bins=100, min=0, max=0, name=None):
    arr = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    rng = None if (min == 0 and max == 0) else (min, max)
    import numpy as _np
    return Tensor(jnp.asarray(_np.histogram_bin_edges(
        _np.asarray(arr), bins=bins, range=rng).astype(_np.float32)))


_export("histogram_bin_edges", histogram_bin_edges)


# ---- round-2 breadth: special functions + bit ops + aliases ---------------
# Parity: python/paddle/tensor/math.py + ops.py additions in the 2.6 surface.

for _n, _f in dict(
    sinc=jnp.sinc, signbit=jnp.signbit, exp2=jnp.exp2,
    erfc=jax.scipy.special.erfc, expit=jax.scipy.special.expit,
    i0e=jax.scipy.special.i0e, i1=jax.scipy.special.i1,
    i1e=jax.scipy.special.i1e, positive=jnp.positive,
).items():
    _unary(_n, _f)

for _n, _f in dict(
    gammainc=jax.scipy.special.gammainc,
    gammaincc=jax.scipy.special.gammaincc,
    xlogy=jax.scipy.special.xlogy,
    true_divide=jnp.true_divide,
    bitwise_left_shift=jnp.left_shift,
    bitwise_right_shift=jnp.right_shift,
).items():
    _binary(_n, _f)


def polygamma(x, n, name=None):
    """n-th derivative of digamma at x (n=0 is digamma itself)."""
    return apply_op(lambda a: jax.scipy.special.polygamma(n, a), x)


_export("polygamma", polygamma)


def erfcx(x, name=None):
    """Scaled complementary error function exp(x^2)*erfc(x): direct form
    in the float32-safe range, two-term asymptotic series beyond it."""
    def fn(a):
        safe = jnp.exp(a * a) * jax.scipy.special.erfc(a)
        # erfcx(x) ~ (1 - 1/(2x^2) + 3/(4x^4)) / (x sqrt(pi)); at the x=9
        # switchover the 3-term series agrees with the direct form to ~1e-7
        inv2 = 1.0 / (a * a)
        tail = (1.0 - 0.5 * inv2 + 0.75 * inv2 * inv2) / (
            a * jnp.sqrt(jnp.pi))
        return jnp.where(a > 9.0, tail, safe)
    return apply_op(fn, x)


_export("erfcx", erfcx)


# ---- round-2 tranche 3: pairwise distances, fused add-mul, misc -----------

def addcmul(input, tensor1, tensor2, value=1.0, name=None):
    return apply_op(lambda i, a, b: i + value * a * b, input, tensor1,
                    tensor2)


def addcdiv(input, tensor1, tensor2, value=1.0, name=None):
    return apply_op(lambda i, a, b: i + value * a / b, input, tensor1,
                    tensor2)


def cdist(x, y, p=2.0, compute_mode="use_mm_for_euclid_dist_if_necessary",
          name=None):
    """Pairwise p-distance between row batches [..., N, D] × [..., M, D]
    → [..., N, M]. p=2 uses the MXU x·yᵀ expansion."""
    def fn(a, b):
        if p == 2.0:
            a2 = (a * a).sum(-1)[..., :, None]
            b2 = (b * b).sum(-1)[..., None, :]
            ab = jnp.matmul(a, jnp.swapaxes(b, -1, -2))
            return jnp.sqrt(jnp.maximum(a2 + b2 - 2 * ab, 0.0))
        diff = jnp.abs(a[..., :, None, :] - b[..., None, :, :])
        if p == 0:
            return (diff != 0).sum(-1).astype(a.dtype)
        if jnp.isinf(p):
            return diff.max(-1)
        return (diff ** p).sum(-1) ** (1.0 / p)
    return apply_op(fn, x, y)


def pdist(x, p=2.0, name=None):
    """Condensed pairwise distances of [N, D] rows → [N*(N-1)/2]."""
    import numpy as _np
    iu, ju = _np.triu_indices(x.shape[0], k=1)
    ii = jnp.asarray(iu.astype(_np.int32))
    jj = jnp.asarray(ju.astype(_np.int32))

    def fn(a):
        diff = jnp.abs(a[ii] - a[jj])
        if p == 0:
            return (diff != 0).sum(-1).astype(a.dtype)
        if p == 2.0:
            return jnp.sqrt((diff * diff).sum(-1))
        if jnp.isinf(p):
            return diff.max(-1)
        return (diff ** p).sum(-1) ** (1.0 / p)
    return apply_op(fn, x)


def dist(x, y, p=2.0, name=None):
    """p-norm of (x - y) (reference paddle.dist)."""
    def fn(a, b):
        d = jnp.abs(a - b).ravel()
        if p == 0:
            return (d != 0).sum().astype(a.dtype)
        if jnp.isinf(p):
            return d.max()
        return (d ** p).sum() ** (1.0 / p)
    return apply_op(fn, x, y)


def mv(x, vec, name=None):
    return apply_op(lambda a, v: a @ v, x, vec)


_binary("logaddexp2", jnp.logaddexp2)
_unary("isposinf", jnp.isposinf)
_unary("isneginf", jnp.isneginf)


def multigammaln(x, p, name=None):
    """Log multivariate gamma: sum_i lgamma(x + (1-i)/2) + const."""
    import math as _math

    def fn(a):
        c = 0.25 * p * (p - 1) * _math.log(_math.pi)
        total = c
        for i in range(1, p + 1):  # builtins.sum is shadowed by paddle.sum
            total = total + jax.scipy.special.gammaln(a + (1 - i) / 2.0)
        return total
    return apply_op(fn, x)


def reduce_as(x, target, name=None):
    """Sum-reduce x to target's shape (broadcast inverse)."""
    tshape = tuple(target.shape)

    def fn(a):
        out = a
        while out.ndim > len(tshape):
            out = out.sum(0)
        for i, (od, td) in enumerate(zip(out.shape, tshape)):
            if od != td:
                out = out.sum(i, keepdims=True)
        return out
    return apply_op(fn, x)


for _nm in ["addcmul", "addcdiv", "cdist", "pdist", "dist", "mv",
            "multigammaln", "reduce_as"]:
    _export(_nm, globals()[_nm])


# ---- round-3 tranche: remaining modern-API parity ops ---------------------
# Parity: python/paddle/tensor/math.py add_n/multiplex,
# manipulation.py fill_diagonal(_)/fill_diagonal_tensor(_).

def add_n(inputs, name=None):
    """Element-wise sum of a tensor list (reference: sum_op / add_n)."""
    if isinstance(inputs, Tensor):
        return inputs
    assert len(inputs) > 0, "add_n needs at least one input"
    return apply_op(lambda *arrs: functools.reduce(jnp.add, arrs), *inputs)


def multiplex(inputs, index, name=None):
    """Row-wise select: out[i] = inputs[index[i]][i] (reference:
    multiplex_op). index: [batch, 1] or [batch]."""
    idx = index._data if isinstance(index, Tensor) else jnp.asarray(index)
    idx = idx.reshape(-1).astype(jnp.int32)

    def f(*arrs):
        stacked = jnp.stack(arrs)                    # [K, batch, ...]
        rows = jnp.arange(stacked.shape[1])
        return stacked[idx, rows]
    return apply_op(f, *inputs)


def fill_diagonal(x, value, offset=0, wrap=False, name=None):
    """Out-of-place diagonal fill (basis of the reference's in-place op).
    wrap=True re-wraps the diagonal for tall 2-D matrices — numpy's rule:
    flat positions at stride m+1 (offset shifts the flat start)."""
    def f(a):
        if a.ndim == 2 and wrap and a.shape[0] > a.shape[1] + 1:
            n, m = a.shape
            flat = jnp.arange(n * m).reshape(n, m)
            sel = (flat - offset) % (m + 1) == 0
            if offset:
                sel = sel & (flat >= offset)
            return jnp.where(sel, jnp.asarray(value, a.dtype), a)
        if a.ndim > 2:
            # reference semantics: the HYPERCUBE diagonal a[i,i,...,i]
            # (all dims must be equal), not a batch of 2-D diagonals
            if len(set(a.shape)) != 1:
                raise ValueError(
                    "fill_diagonal on ndim>2 requires all dimensions "
                    f"equal, got {a.shape}")
            idx = jnp.arange(a.shape[0])
            return a.at[tuple([idx] * a.ndim)].set(
                jnp.asarray(value, a.dtype))
        i = jnp.arange(a.shape[-2])[:, None]
        j = jnp.arange(a.shape[-1])[None, :]
        sel = (j - i) == offset
        return jnp.where(sel, jnp.asarray(value, a.dtype), a)
    return apply_op(f, x)


def fill_diagonal_tensor(x, y, offset=0, dim1=0, dim2=1, name=None):
    """Write tensor y along the (dim1, dim2) diagonal (reference:
    fill_diagonal_tensor_op)."""
    import builtins as _b

    def f(a, v):
        # NB: bare min/max here would hit the module's exported reduce ops
        moved = jnp.moveaxis(a, (dim1, dim2), (-2, -1))
        n, m = moved.shape[-2:]
        rows = jnp.arange(_b.max(_b.min(n, m - offset) if offset >= 0
                                 else _b.min(n + offset, m), 0))
        r = rows - _b.min(offset, 0)
        c = rows + _b.max(offset, 0)
        out = moved.at[..., r, c].set(v.astype(a.dtype))
        return jnp.moveaxis(out, (-2, -1), (dim1, dim2))
    if isinstance(y, Tensor):
        return apply_op(f, x, y)
    return apply_op(lambda a: f(a, jnp.asarray(y)), x)


def fill_diagonal_(x, value, offset=0, wrap=False, name=None):
    from . import _inplace_grad_guard, _assign_inplace
    _inplace_grad_guard(x, "fill_diagonal_")
    with no_grad():
        out = fill_diagonal(x, value, offset=offset, wrap=wrap)
    return _assign_inplace(x, out, "fill_diagonal_")


def fill_diagonal_tensor_(x, y, offset=0, dim1=0, dim2=1, name=None):
    from . import _inplace_grad_guard, _assign_inplace
    _inplace_grad_guard(x, "fill_diagonal_tensor_")
    with no_grad():
        out = fill_diagonal_tensor(x, y, offset=offset, dim1=dim1, dim2=dim2)
    return _assign_inplace(x, out, "fill_diagonal_tensor_")


for _n in ("add_n", "multiplex", "fill_diagonal", "fill_diagonal_",
           "fill_diagonal_tensor", "fill_diagonal_tensor_"):
    _export(_n, globals()[_n])
