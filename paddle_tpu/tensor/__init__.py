"""Tensor package: ops + method attachment onto Tensor (paddle.Tensor.sum()...).

Parity: python/paddle/tensor/__init__.py's monkey-patch of tensor methods.
"""
from .tensor import (Tensor, Parameter, no_grad, enable_grad, is_grad_enabled,
                     set_grad_enabled, apply_op, clear_tape)
from . import creation, math, manipulation, search, logic, random, stat, linalg

from .creation import *  # noqa: F401,F403
from .math import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .search import *  # noqa: F401,F403
from .logic import *  # noqa: F401,F403
from .random import *  # noqa: F401,F403
from .stat import *  # noqa: F401,F403
from .linalg import *  # noqa: F401,F403

_METHOD_MODULES = (math, manipulation, search, logic, stat, linalg)
_SKIP = {"broadcast_shape", "is_tensor", "einsum"}


def _attach_methods():
    for mod in _METHOD_MODULES:
        for name in getattr(mod, "__all__", []):
            if name in _SKIP or name.startswith("_"):
                continue
            fn = getattr(mod, name)
            if callable(fn) and not hasattr(Tensor, name):
                setattr(Tensor, name, fn)
    # creation-style helpers that make sense as methods
    from .creation import clone as _clone  # noqa
    for nm, fn in dict(
        numel=lambda self: self.size,
    ).items():
        if not hasattr(Tensor, nm):
            setattr(Tensor, nm, fn)


_attach_methods()


# ---- in-place variants (round 2 tranche 3) --------------------------------
# Parity: python/paddle/tensor/ inplace ops (`x.abs_()` etc. — the reference
# generates these from the YAML; here they wrap the functional op and write
# back through _data, which under jit.to_static functionalizes like any
# other persistent-state write).

# dtype/shape-changing ops are deliberately EXCLUDED (equal/logical_*/
# signbit/norm/where): the reference rejects in-place forms that change
# dtype or shape, and writing a bool into a float tensor corrupts it
_INPLACE_BASES = [
    "abs", "acos", "acosh", "asin", "asinh", "atan", "atanh", "ceil",
    "cos", "cosh", "digamma", "erf", "exp", "expm1", "floor", "lgamma",
    "log", "log10", "log1p", "log2", "neg", "reciprocal", "round",
    "rsqrt", "sigmoid", "sign", "sin", "sinh", "sqrt", "square", "tan",
    "tanh", "trunc", "frac", "i0",
]
_INPLACE_BINARY_BASES = [
    "copysign", "gcd", "hypot", "lcm", "lerp", "nextafter", "pow",
    "remainder", "mod", "floor_divide", "heaviside", "masked_fill",
    "scatter", "put_along_axis", "renorm", "index_fill", "masked_scatter",
    "ldexp", "cumsum", "cumprod", "logit", "divide",
]


def _inplace_grad_guard(x, name):
    # house convention (add_/clip_/scale_): in-place ops are data edits
    # outside the tape. With grad recording active on x that would
    # silently sever the chain — refuse, like the reference's "can't use
    # inplace strategy" error, instead of producing wrong gradients.
    from .tensor import _tape
    if _tape.grad_enabled and not x.stop_gradient:
        raise RuntimeError(
            f"{name}(): in-place op on a tensor that requires grad is not "
            f"supported (gradients would not flow through the mutation); "
            f"use the out-of-place paddle.{name[:-1]} instead or wrap in "
            f"paddle.no_grad()")


def _assign_inplace(x, out, name):
    # the reference rejects in-place results that change shape or dtype
    # (broadcasting a (1,) tensor up, dtype promotion); enforce it
    if tuple(out._data.shape) != tuple(x._data.shape) or \
            out._data.dtype != x._data.dtype:
        raise ValueError(
            f"{name}(): in-place result would change shape/dtype "
            f"{tuple(x._data.shape)}/{x._data.dtype} -> "
            f"{tuple(out._data.shape)}/{out._data.dtype}")
    x._data = out._data
    return x


def _make_inplace(base_name, fn, binary):
    if binary:
        def inplace(x, *args, **kwargs):
            _inplace_grad_guard(x, base_name + "_")
            with no_grad():
                out = fn(x, *args, **kwargs)
            return _assign_inplace(x, out, base_name + "_")
    else:
        def inplace(x, name=None):
            _inplace_grad_guard(x, base_name + "_")
            with no_grad():
                out = fn(x)
            return _assign_inplace(x, out, base_name + "_")
    inplace.__name__ = base_name + "_"
    inplace.__doc__ = (f"In-place variant of paddle.{base_name} "
                       f"(data edit outside the autograd tape).")
    return inplace


def _gen_inplace():
    import sys as _s
    mod = _s.modules[__name__]
    made = []
    for base in _INPLACE_BASES + _INPLACE_BINARY_BASES:
        nm = base + "_"
        if hasattr(mod, nm):          # hand-written version wins
            continue
        fn = getattr(mod, base, None)
        if fn is None or not callable(fn):
            continue
        ip = _make_inplace(base, fn, base in _INPLACE_BINARY_BASES)
        setattr(mod, nm, ip)
        if not hasattr(Tensor, nm):
            setattr(Tensor, nm, ip)
        made.append(nm)
    # zero_: fill with zeros in place
    def zero_(x, name=None):
        _inplace_grad_guard(x, "zero_")
        import jax.numpy as _jnp
        x._data = _jnp.zeros_like(x._data)
        return x
    mod.zero_ = zero_
    if not hasattr(Tensor, "zero_"):
        Tensor.zero_ = zero_
    made.append("zero_")
    return made


_INPLACE_GENERATED = _gen_inplace()


def _attach_random_inplace():
    """Random in-place samplers are Tensor methods in the reference
    (x.exponential_(), x.bernoulli_() …) — random isn't in
    _METHOD_MODULES because its creation ops (rand/randn) take a shape,
    not self."""
    for nm in ("exponential_", "uniform_", "normal_", "log_normal_",
               "bernoulli_", "cauchy_", "geometric_"):
        fn = getattr(random, nm, None)
        if fn is not None and not hasattr(Tensor, nm):
            setattr(Tensor, nm, fn)


_attach_random_inplace()
