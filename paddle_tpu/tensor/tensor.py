"""Eager Tensor facade over jax.Array with an imperative autograd tape.

Capability parity target (reference: PaddlePaddle ~2.5/2.6):
  - ``paddle/fluid/eager/`` dygraph autograd engine (GradNodeBase, AutogradMeta,
    Backward()) — realized here as a flat Wengert tape of ``jax.vjp`` closures.
  - ``paddle.Tensor`` user API (stop_gradient, .grad, .backward(), hooks,
    numpy()/item()/clone()/detach(), operator overloads).

TPU-first design notes:
  * The underlying storage is always a ``jax.Array`` (or a tracer when the
    surrounding code runs under ``jax.jit`` — the same tape works while traced,
    which is how ``paddle.jit.to_static`` compiles a full train step).
  * Ops execute through ``jax.vjp`` only when gradients are required; otherwise
    they are plain jnp calls, so inference costs no residual memory.
  * No streams/events/allocators: XLA owns scheduling and memory on TPU.
"""
from __future__ import annotations

import contextlib
import itertools
import threading
import weakref
from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "Tensor",
    "Parameter",
    "no_grad",
    "enable_grad",
    "is_grad_enabled",
    "set_grad_enabled",
    "apply_op",
    "register_persistent",
    "unregister_persistent",
    "persistent_tensors",
    "clear_tape",
]

_uid = itertools.count()


class _TapeState(threading.local):
    def __init__(self):
        self.nodes: list[_TapeNode] = []
        self.grad_enabled: bool = True


_tape = _TapeState()


class _TapeNode:
    """One recorded op: output ids <- vjp_fn <- input tensors."""

    __slots__ = ("inputs", "output_ids", "vjp_fn", "outputs_meta",
                 "__weakref__")

    def __init__(self, inputs, output_ids, vjp_fn, outputs_meta):
        self.inputs = inputs            # list[Tensor] (differentiable inputs only)
        self.output_ids = output_ids    # list[int] tensor uids
        self.vjp_fn = vjp_fn            # cotangents -> input cotangents
        self.outputs_meta = outputs_meta  # list[(shape, dtype)] for zero-filling


def is_grad_enabled() -> bool:
    return _tape.grad_enabled


def set_grad_enabled(mode: bool) -> None:
    _tape.grad_enabled = bool(mode)


@contextlib.contextmanager
def no_grad():
    prev = _tape.grad_enabled
    _tape.grad_enabled = False
    try:
        yield
    finally:
        _tape.grad_enabled = prev


@contextlib.contextmanager
def enable_grad():
    prev = _tape.grad_enabled
    _tape.grad_enabled = True
    try:
        yield
    finally:
        _tape.grad_enabled = prev


def clear_tape() -> None:
    _tape.nodes.clear()


# Persistent-state registry: Parameters and optimizer accumulators register here
# so jit.to_static can functionalize hidden state (collect -> thread through the
# compiled function -> write back).
_persistent: "weakref.WeakSet[Tensor]" = weakref.WeakSet()


_persistent_uids: set = set()


def register_persistent(t: "Tensor") -> None:
    # O(1) identity-idempotence via a parallel uid set: adding a weakref
    # whose referent is already present would compare refs through
    # Tensor.__eq__ (elementwise) — and a linear scan would make bulk
    # registration quadratic
    if t._uid in _persistent_uids:
        return
    _persistent_uids.add(t._uid)
    weakref.finalize(t, _persistent_uids.discard, t._uid)
    _persistent.add(t)


def unregister_persistent(t: "Tensor") -> None:
    """Remove ``t`` from the persistent-state registry (rollback of a
    lazily-created tensor whose value never materialized — see
    jit.StaticFunction._execute's failed-trace rollback)."""
    unregister_persistent_many([t])


def unregister_persistent_many(ts) -> None:
    """Batch unregister: ONE sweep of the registry for any number of
    tensors (a failed first step of a big model rolls back ~4 slots per
    param — per-tensor scans would be O(registry²)).

    NOT WeakSet.discard(t): that compares candidates through
    Tensor.__eq__ (elementwise — and raises on tracer-valued data, the
    very state this rollback removes). Drop matching weakrefs by referent
    identity from the underlying ref set instead."""
    doomed = {id(t) for t in ts}
    if not doomed:
        return
    for t in ts:
        _persistent_uids.discard(t._uid)
    for ref in list(getattr(_persistent, "data", ())):
        if id(ref()) in doomed:
            _persistent.data.discard(ref)


def persistent_tensors() -> list["Tensor"]:
    return sorted(_persistent, key=lambda t: t._uid)


def _as_jax(value, dtype=None):
    if isinstance(value, Tensor):
        return value._data
    if isinstance(value, (jnp.ndarray, jax.Array)):
        return value if dtype is None else value.astype(dtype)
    return jnp.asarray(value, dtype=dtype)


class Tensor:
    """Paddle-shaped eager tensor. Wraps a jax.Array; autograd via the tape."""

    __slots__ = ("_data", "_uid", "stop_gradient", "grad", "name", "persistable",
                 "_hooks", "_is_leaf", "sharding_spec", "process_mesh",
                 "_grad_fn_ref", "__weakref__")

    def __init__(self, data, stop_gradient: bool = True, name: Optional[str] = None,
                 dtype=None):
        self._data = _as_jax(data, dtype)
        self._uid = next(_uid)
        self.stop_gradient = stop_gradient
        self.grad: Optional[Tensor] = None
        self.name = name or f"tensor_{self._uid}"
        self.persistable = False
        self._hooks: list[Callable] = []
        self._is_leaf = True
        self.sharding_spec = None   # jax PartitionSpec for pjit/fleet paths
        self.process_mesh = None

    # ---------------------------------------------------------------- props
    @property
    def shape(self) -> list:
        return list(self._data.shape)

    @property
    def ndim(self) -> int:
        return self._data.ndim

    @property
    def size(self) -> int:
        return int(np.prod(self._data.shape)) if self._data.shape else 1

    @property
    def dtype(self):
        return self._data.dtype

    @property
    def T(self) -> "Tensor":
        return self.transpose(list(range(self.ndim))[::-1])

    @property
    def mT(self) -> "Tensor":
        if self.ndim < 2:
            raise ValueError(
                "Tensor.mT requires at least 2 dimensions, got "
                f"{self.ndim}")
        from .linalg import t
        return t(self)

    @property
    def itemsize(self) -> int:
        return self._data.dtype.itemsize

    def element_size(self) -> int:
        """Bytes per element (the reference's METHOD spelling)."""
        return self._data.dtype.itemsize

    @property
    def nbytes(self) -> int:
        return int(self.size) * self._data.dtype.itemsize

    @property
    def grad_fn(self):
        """The tape node that produced this tensor (None for leaves) —
        parity with the reference's grad_fn introspection. O(1): apply_op
        stores a weakref to the producing node."""
        if self._is_leaf:
            return None
        ref = getattr(self, "_grad_fn_ref", None)
        return ref() if ref is not None else None

    @property
    def is_leaf(self) -> bool:
        return self._is_leaf

    @property
    def value(self):
        return self._data

    # ------------------------------------------------------------- plumbing
    def numpy(self) -> np.ndarray:
        return np.asarray(self._data)

    # ---- NumPy interop (VERDICT r2 #6: __array_ufunc__ interop) ----------
    # np.asarray(t) works via __array__; np.sin(t) / np.add(x, t) route
    # through __array_ufunc__ onto the DIFFERENTIABLE apply_op path (the
    # jnp ufunc of the same name), so mixing NumPy idioms with Tensors
    # neither breaks the tape nor silently drops to host math.
    __array_priority__ = 100  # beat ndarray in mixed binary ops

    def __array__(self, dtype=None):
        arr = np.asarray(self._data)
        return arr.astype(dtype) if dtype is not None else arr

    def __array_ufunc__(self, ufunc, method, *inputs, **kwargs):
        if method != "__call__" or kwargs.get("out") is not None:
            return NotImplemented
        import jax.numpy as _jnp
        jfn = getattr(_jnp, ufunc.__name__, None)
        if jfn is None:
            return NotImplemented
        tensors = [i for i in inputs if isinstance(i, Tensor)]

        def f(*arrs):
            it = iter(arrs)
            args = [next(it) if isinstance(i, Tensor) else i
                    for i in inputs]
            return jfn(*args, **kwargs)
        return apply_op(f, *tensors)

    def item(self):
        return self._data.item() if hasattr(self._data, "item") else np.asarray(self._data).item()

    def tolist(self):
        return np.asarray(self._data).tolist()

    def detach(self) -> "Tensor":
        return Tensor(self._data, stop_gradient=True, name=self.name + ".detach")

    def clone(self) -> "Tensor":
        return apply_op(lambda x: x + 0, self)

    def astype(self, dtype) -> "Tensor":
        from ..core.dtype import convert_dtype
        dt = convert_dtype(dtype)
        return apply_op(lambda x: x.astype(dt), self)

    def cast(self, dtype) -> "Tensor":
        return self.astype(dtype)

    def cpu(self) -> "Tensor":
        return self

    def cuda(self, *a, **k) -> "Tensor":  # API parity; devices are XLA-managed
        return self

    def to(self, *args, **kwargs) -> "Tensor":
        for a in args:
            if isinstance(a, (str, jnp.dtype, type(jnp.float32))) and not isinstance(a, bool):
                try:
                    return self.astype(a)
                except Exception:
                    pass
        return self

    def pin_memory(self) -> "Tensor":
        return self

    @property
    def place(self):
        from ..core.place import _current_place
        return _current_place()

    def block_until_ready(self):
        if hasattr(self._data, "block_until_ready"):
            self._data.block_until_ready()
        return self

    def set_value(self, value) -> None:
        """In-place value replacement (no tape record — optimizer/init use)."""
        new = _as_jax(value)
        if tuple(new.shape) != tuple(self._data.shape):
            raise ValueError(
                f"set_value shape mismatch: {new.shape} vs {self._data.shape}")
        self._data = new.astype(self._data.dtype)

    def _set_data(self, arr) -> None:
        self._data = arr

    def copy_(self, other, *a) -> "Tensor":
        self.set_value(other._data if isinstance(other, Tensor) else other)
        return self

    def _guard_inplace(self, name):
        # data edits live outside the tape: refuse while grad recording is
        # active on this tensor rather than silently severing the chain
        if _tape.grad_enabled and not self.stop_gradient:
            raise RuntimeError(
                f"{name}(): in-place op on a tensor that requires grad is "
                f"not supported; wrap in paddle.no_grad() or use the "
                f"out-of-place op")

    def fill_(self, v) -> "Tensor":
        self._guard_inplace("fill_")
        self._data = jnp.full_like(self._data, v)
        return self

    def zero_(self) -> "Tensor":
        self._guard_inplace("zero_")
        self._data = jnp.zeros_like(self._data)
        return self

    # ------------------------------------------------------------- autograd
    def register_hook(self, hook: Callable) -> Callable:
        self._hooks.append(hook)

        def _remove():
            if hook in self._hooks:
                self._hooks.remove(hook)
        return _remove

    def clear_grad(self) -> None:
        self.grad = None

    def clear_gradient(self, set_to_zero: bool = False) -> None:
        if set_to_zero and self.grad is not None:
            self.grad = Tensor(jnp.zeros_like(self.grad._data))
        else:
            self.grad = None

    def backward(self, grad_tensor: Optional["Tensor"] = None,
                 retain_graph: bool = False) -> None:
        from ..autograd.backward_engine import run_backward
        run_backward([self], [grad_tensor], retain_graph=retain_graph)

    @property
    def gradient(self):
        return None if self.grad is None else self.grad.numpy()

    # ------------------------------------------------------------ operators
    def _binary(self, other, fn):
        if isinstance(other, Tensor):
            return apply_op(fn, self, other)
        const = other
        return apply_op(lambda x: fn(x, const), self)

    def _rbinary(self, other, fn):
        const = other
        return apply_op(lambda x: fn(const, x), self)

    def __add__(self, o): return self._binary(o, jnp.add)
    def __radd__(self, o): return self._rbinary(o, jnp.add)
    def __sub__(self, o): return self._binary(o, jnp.subtract)
    def __rsub__(self, o): return self._rbinary(o, jnp.subtract)
    def __mul__(self, o): return self._binary(o, jnp.multiply)
    def __rmul__(self, o): return self._rbinary(o, jnp.multiply)
    def __truediv__(self, o): return self._binary(o, jnp.divide)
    def __rtruediv__(self, o): return self._rbinary(o, jnp.divide)
    def __floordiv__(self, o): return self._binary(o, jnp.floor_divide)
    def __mod__(self, o): return self._binary(o, jnp.mod)
    def __pow__(self, o): return self._binary(o, jnp.power)
    def __rpow__(self, o): return self._rbinary(o, jnp.power)
    def __matmul__(self, o): return self._binary(o, jnp.matmul)
    def __rmatmul__(self, o): return self._rbinary(o, jnp.matmul)
    def __neg__(self): return apply_op(jnp.negative, self)
    def __abs__(self): return apply_op(jnp.abs, self)

    def __eq__(self, o): return self._cmp(o, jnp.equal)
    def __ne__(self, o): return self._cmp(o, jnp.not_equal)
    def __lt__(self, o): return self._cmp(o, jnp.less)
    def __le__(self, o): return self._cmp(o, jnp.less_equal)
    def __gt__(self, o): return self._cmp(o, jnp.greater)
    def __ge__(self, o): return self._cmp(o, jnp.greater_equal)

    def _cmp(self, other, fn):
        if _capture_hook[0] is not None:
            # static build: route through apply_op so the comparison is
            # recorded into the Program (it would otherwise replay stale)
            if isinstance(other, Tensor):
                return apply_op(lambda a, b, f=fn: f(a, b), self, other)
            return apply_op(lambda a, o=other, f=fn: f(a, o), self)
        ov = other._data if isinstance(other, Tensor) else other
        return Tensor(fn(self._data, ov))

    def __hash__(self):
        return self._uid

    def __bool__(self):
        return bool(self._data)

    def __int__(self):
        return int(self._data)

    def __float__(self):
        return float(self._data)

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-d tensor")
        return self.shape[0]

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __getitem__(self, idx):
        idx = _unwrap_index(idx)
        return apply_op(lambda x: x[idx], self)

    def __setitem__(self, idx, value):
        idx = _unwrap_index(idx)
        v = _as_jax(value)
        if _capture_hook[0] is not None:
            # static build: record the scatter as an op producing a NEW
            # value for this tensor's uid, so Executor.run replays it
            if isinstance(value, Tensor):
                out = apply_op(
                    lambda a, vv, i=idx: a.at[i].set(vv.astype(a.dtype)),
                    self, value)
            else:
                out = apply_op(
                    lambda a, vv=v, i=idx: a.at[i].set(vv.astype(a.dtype)),
                    self)
            self._data = out._data
            # alias the new value back onto this tensor's uid for replay
            from ..static import _alias_capture_output
            _alias_capture_output(out, self)
            return
        self._data = self._data.at[idx].set(v.astype(self._data.dtype))

    def __repr__(self):
        sg = self.stop_gradient
        return (f"Tensor(shape={self.shape}, dtype={self.dtype}, "
                f"stop_gradient={sg},\n       {np.asarray(self._data)!r})")

    __str__ = __repr__

    # jax pytree-friendly conversion
    def __jax_array__(self):
        return self._data


def _unwrap_index(idx):
    if isinstance(idx, Tensor):
        return idx._data
    if isinstance(idx, tuple):
        return tuple(i._data if isinstance(i, Tensor) else i for i in idx)
    return idx


class Parameter(Tensor):
    """Trainable tensor: stop_gradient=False, registered persistent."""

    __slots__ = ("trainable", "optimize_attr", "regularizer", "is_distributed",
                 "split_axis")

    def __init__(self, data, name=None, trainable: bool = True, dtype=None):
        super().__init__(data, stop_gradient=not trainable, name=name, dtype=dtype)
        self.trainable = trainable
        self.persistable = True
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = None
        self.is_distributed = False
        self.split_axis = None       # tensor-parallel split axis (None = replicated)
        register_persistent(self)

    def __repr__(self):
        return (f"Parameter(name={self.name}, shape={self.shape}, "
                f"dtype={self.dtype}, trainable={self.trainable})")


# ------------------------------------------------------------------ op apply
def apply_op(jax_fn: Callable, *tensors: Tensor, n_outputs: int = 1):
    """Execute ``jax_fn(*arrays)`` recording a vjp tape node when needed.

    jax_fn must be a pure function of the positional arrays only (bind any
    non-tensor attrs with closures/partial before calling).
    """
    arrays = [t._data for t in tensors]
    need_grad = _tape.grad_enabled and any(not t.stop_gradient for t in tensors)

    if not need_grad:
        out = jax_fn(*arrays)
        if n_outputs == 1 and not isinstance(out, tuple):
            res = Tensor(out)
            _maybe_capture(jax_fn, tensors, (res,))
            return res
        res = tuple(Tensor(o) for o in out)
        _maybe_capture(jax_fn, tensors, res)
        return res

    primal_out, vjp_fn = jax.vjp(jax_fn, *arrays)
    multi = isinstance(primal_out, tuple)
    outs_raw = primal_out if multi else (primal_out,)
    outs = tuple(Tensor(o, stop_gradient=False) for o in outs_raw)
    for o in outs:
        o._is_leaf = False
    node = _TapeNode(
        inputs=list(tensors),
        output_ids=[o._uid for o in outs],
        vjp_fn=(vjp_fn if multi else (lambda g, f=vjp_fn: f(g[0]))),
        outputs_meta=[(tuple(o.shape), o.dtype) for o in outs],
    )
    _register_node(node, outs)
    _maybe_capture(jax_fn, tensors, outs)
    return outs if multi else outs[0]


def _register_node(node, outs) -> None:
    """Append a tape node and give each output its O(1) grad_fn backref —
    the single registration tail shared by apply_op, PyLayer and
    recompute."""
    for o in outs:
        o._grad_fn_ref = weakref.ref(node)
    _tape.nodes.append(node)


# static-graph capture hook: set by paddle_tpu.static when building a
# Program (enable_static); records (fn, inputs, outputs) so Executor.run can
# replay the graph with new feeds. None in eager mode — zero overhead.
_capture_hook = [None]


def _maybe_capture(jax_fn, inputs, outputs):
    hook = _capture_hook[0]
    if hook is not None:
        hook(jax_fn, inputs, outputs)


def tape_nodes():
    return _tape.nodes
