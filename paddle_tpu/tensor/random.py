"""Random ops over the global-seed key facade.

Parity: python/paddle/tensor/random.py. Every draw consumes a deterministic
fresh fold of the global key (paddle.seed), so runs replay exactly.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.dtype import convert_dtype, get_default_dtype
from ..core.rng import next_key, next_threefry_key
from .creation import _shape
from .tensor import Tensor

__all__ = ["rand", "randn", "normal", "standard_normal", "uniform", "randint",
           "randint_like", "randperm", "bernoulli", "multinomial", "poisson",
           "exponential_", "uniform_", "normal_", "rand_like", "randn_like",
           "gumbel_softmax"]


def rand(shape, dtype=None, name=None):
    dt = convert_dtype(dtype) or get_default_dtype()
    return Tensor(jax.random.uniform(next_key(), _shape(shape), dtype=dt))


def randn(shape, dtype=None, name=None):
    dt = convert_dtype(dtype) or get_default_dtype()
    return Tensor(jax.random.normal(next_key(), _shape(shape), dtype=dt))


def standard_normal(shape, dtype=None, name=None):
    return randn(shape, dtype)


def normal(mean=0.0, std=1.0, shape=None, name=None):
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m = mean._data if isinstance(mean, Tensor) else mean
        s = std._data if isinstance(std, Tensor) else std
        shp = jnp.broadcast_shapes(jnp.shape(m), jnp.shape(s))
        return Tensor(m + s * jax.random.normal(next_key(), shp,
                                                dtype=get_default_dtype()))
    shp = _shape(shape) if shape is not None else ()
    return Tensor(mean + std * jax.random.normal(next_key(), shp,
                                                 dtype=get_default_dtype()))


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):
    dt = convert_dtype(dtype) or get_default_dtype()
    return Tensor(jax.random.uniform(next_key(), _shape(shape), dtype=dt,
                                     minval=min, maxval=max))


def randint(low=0, high=None, shape=(1,), dtype="int64", name=None):
    if high is None:
        low, high = 0, low
    dt = convert_dtype(dtype)
    return Tensor(jax.random.randint(next_key(), _shape(shape), low, high).astype(dt))


def randint_like(x, low=0, high=None, dtype=None, name=None):
    return randint(low, high, tuple(x.shape), dtype or "int64")


def randperm(n, dtype="int64", name=None):
    return Tensor(jax.random.permutation(next_key(), int(n)).astype(convert_dtype(dtype)))


def bernoulli(x, name=None):
    return Tensor(jax.random.bernoulli(next_key(), x._data).astype(x.dtype))


def multinomial(x, num_samples=1, replacement=False, name=None):
    logits = jnp.log(jnp.clip(x._data, 1e-30, None))
    if replacement:
        out = jax.random.categorical(next_key(), logits, axis=-1,
                                     shape=(*x.shape[:-1], num_samples))
    else:
        g = jax.random.gumbel(next_key(), x.shape)
        _, out = jax.lax.top_k(logits + g, num_samples)
    return Tensor(out.astype(jnp.int64))


def poisson(x, name=None):
    return Tensor(jax.random.poisson(next_threefry_key(), x._data).astype(x.dtype))


def exponential_(x, lam=1.0, name=None):
    from . import _inplace_grad_guard
    _inplace_grad_guard(x, "exponential_")
    x._data = jax.random.exponential(next_key(), x._data.shape,
                                     x._data.dtype) / lam
    return x


def uniform_(x, min=-1.0, max=1.0, seed=0, name=None):
    from . import _inplace_grad_guard
    _inplace_grad_guard(x, "uniform_")
    x._data = jax.random.uniform(next_key(), x._data.shape, x._data.dtype,
                                 min, max)
    return x


def normal_(x, mean=0.0, std=1.0, name=None):
    from . import _inplace_grad_guard
    _inplace_grad_guard(x, "normal_")
    x._data = mean + std * jax.random.normal(next_key(), x._data.shape,
                                             x._data.dtype)
    return x


def rand_like(x, name=None):
    return rand(tuple(x.shape), x.dtype)


def randn_like(x, name=None):
    return randn(tuple(x.shape), x.dtype)


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    from .tensor import apply_op
    g = jax.random.gumbel(next_key(), x._data.shape, x._data.dtype)

    def f(a):
        y = jax.nn.softmax((a + g) / temperature, axis=axis)
        if hard:
            idx = jnp.argmax(y, axis=axis, keepdims=True)
            onehot = jnp.zeros_like(y).at[...].set(0)
            onehot = jnp.where(
                jnp.arange(y.shape[axis]).reshape(
                    [-1 if i == (axis % y.ndim) else 1 for i in range(y.ndim)]) == idx,
                1.0, 0.0).astype(y.dtype)
            return onehot + y - jax.lax.stop_gradient(y)
        return y
    return apply_op(f, x)


# ---- round-2 breadth ------------------------------------------------------

def log_normal(mean=1.0, std=2.0, shape=None, name=None):
    """Samples of exp(N(mean, std^2)). Parity: paddle.log_normal (2.6)."""
    shape = shape or [1]
    out = jax.random.normal(next_key(), tuple(shape)) * std + mean
    return Tensor(jnp.exp(out))


def log_normal_(x, mean=1.0, std=2.0, name=None):
    from . import _inplace_grad_guard
    _inplace_grad_guard(x, "log_normal_")
    arr = jax.random.normal(next_key(), tuple(x.shape),
                            dtype=x._data.dtype) * std + mean
    x._data = jnp.exp(arr)
    return x


def binomial(count, prob, name=None):
    """Binomial(count, prob) samples. Parity: paddle.binomial (2.6)."""
    c = count._data if isinstance(count, Tensor) else jnp.asarray(count)
    p = prob._data if isinstance(prob, Tensor) else jnp.asarray(prob)
    out = jax.random.binomial(next_key(), c.astype(jnp.float32),
                              p.astype(jnp.float32))
    # reference dtype is int64; without x64 JAX's widest int is int32, so
    # use the canonical int dtype to avoid a per-call truncation warning
    int_dtype = jnp.int64 if jax.config.jax_enable_x64 else jnp.int32
    return Tensor(out.astype(int_dtype))


def standard_gamma(alpha, name=None):
    a = alpha._data if isinstance(alpha, Tensor) else jnp.asarray(alpha)
    return Tensor(jax.random.gamma(next_key(), a))


__all__ += ["log_normal", "log_normal_", "binomial", "standard_gamma"]


def bernoulli_(x, p=0.5, name=None):
    """In-place Bernoulli(p) fill (reference: paddle.Tensor.bernoulli_)."""
    from . import _inplace_grad_guard
    _inplace_grad_guard(x, "bernoulli_")
    x._data = jax.random.bernoulli(
        next_key(), p, tuple(x.shape)).astype(x._data.dtype)
    return x


def cauchy_(x, loc=0, scale=1, name=None):
    """In-place Cauchy(loc, scale) fill (reference: paddle.Tensor.cauchy_)."""
    from . import _inplace_grad_guard
    _inplace_grad_guard(x, "cauchy_")
    x._data = (jax.random.cauchy(next_key(), tuple(x.shape),
                                 dtype=x._data.dtype) * scale + loc)
    return x


def geometric_(x, probs, name=None):
    """In-place Geometric(probs) fill (reference: paddle.Tensor.geometric_)."""
    from . import _inplace_grad_guard
    _inplace_grad_guard(x, "geometric_")
    u = jax.random.uniform(next_key(), tuple(x.shape),
                           minval=1e-7, maxval=1.0)
    p = probs._data if isinstance(probs, Tensor) else jnp.asarray(
        probs, jnp.float32)
    x._data = jnp.ceil(jnp.log1p(-u) / jnp.log1p(-p)).astype(
        x._data.dtype)
    return x


__all__ += ["bernoulli_", "cauchy_", "geometric_"]
