"""Linear algebra. Parity: python/paddle/tensor/linalg.py + paddle.linalg.*"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .tensor import Tensor, apply_op

__all__ = ["norm", "cond", "cholesky", "cholesky_solve", "det", "slogdet",
           "inv", "pinv", "matrix_power", "matrix_rank", "qr", "lu", "svd",
           "eig", "eigh", "eigvals", "eigvalsh", "solve", "lstsq",
           "triangular_solve", "cross", "histogramdd", "t", "transpose_last",
           "matrix_transpose", "pca_lowrank", "svd_lowrank"]


def norm(x, p=None, axis=None, keepdim=False, name=None):
    def f(a):
        if p == "fro" or p is None:
            if axis is None:
                return jnp.sqrt(jnp.sum(a * a))
            return jnp.linalg.norm(a, ord=None if p is None else p, axis=axis,
                                   keepdims=keepdim)
        if p == "nuc":
            return jnp.linalg.norm(a, ord="nuc", axis=axis, keepdims=keepdim)
        if p == float("inf"):
            return jnp.max(jnp.abs(a), axis=axis, keepdims=keepdim)
        if p == float("-inf"):
            return jnp.min(jnp.abs(a), axis=axis, keepdims=keepdim)
        if axis is None:
            return jnp.sum(jnp.abs(a) ** p) ** (1.0 / p)
        return jnp.linalg.norm(a, ord=p, axis=axis if not isinstance(axis, list)
                               else tuple(axis), keepdims=keepdim)
    return apply_op(f, x)


def cond(x, p=None, name=None):
    return Tensor(jnp.linalg.cond(x._data, p=p))


def cholesky(x, upper=False, name=None):
    def f(a):
        L = jnp.linalg.cholesky(a)
        return jnp.swapaxes(L, -1, -2) if upper else L
    return apply_op(f, x)


def cholesky_solve(x, y, upper=False, name=None):
    def f(b, L):
        Lm = jnp.swapaxes(L, -1, -2) if upper else L
        z = jax_solve_tri(Lm, b, lower=True)
        return jax_solve_tri(jnp.swapaxes(Lm, -1, -2), z, lower=False)
    return apply_op(f, x, y)


def jax_solve_tri(a, b, lower):
    import jax.scipy.linalg as jsl
    return jsl.solve_triangular(a, b, lower=lower)


def det(x, name=None):
    return apply_op(jnp.linalg.det, x)


def slogdet(x, name=None):
    s, ld = jnp.linalg.slogdet(x._data)
    return Tensor(jnp.stack([s, ld]))


def inv(x, name=None):
    return apply_op(jnp.linalg.inv, x)


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return apply_op(lambda a: jnp.linalg.pinv(a, rcond=rcond,
                                              hermitian=hermitian), x)


def matrix_power(x, n, name=None):
    return apply_op(lambda a: jnp.linalg.matrix_power(a, n), x)


def matrix_rank(x, tol=None, hermitian=False, name=None):
    return Tensor(jnp.linalg.matrix_rank(x._data, tol=tol))


def qr(x, mode="reduced", name=None):
    q, r = jnp.linalg.qr(x._data, mode=mode)
    return Tensor(q), Tensor(r)


def lu(x, pivot=True, get_infos=False, name=None):
    import jax.scipy.linalg as jsl
    lu_, piv = jsl.lu_factor(x._data)
    if get_infos:
        return Tensor(lu_), Tensor(piv.astype(jnp.int32)), Tensor(jnp.zeros((), jnp.int32))
    return Tensor(lu_), Tensor(piv.astype(jnp.int32))


def svd(x, full_matrices=False, name=None):
    u, s, vh = jnp.linalg.svd(x._data, full_matrices=full_matrices)
    return Tensor(u), Tensor(s), Tensor(jnp.swapaxes(vh, -1, -2).conj())


def matrix_transpose(x, name=None):
    return apply_op(lambda a: jnp.swapaxes(a, -1, -2), x)


def svd_lowrank(x, q=6, niter=2, M=None, name=None):
    """Rank-q randomized SVD (reference svd_lowrank / Halko et al.): a
    random range sketch refined by `niter` power iterations, then an exact
    SVD of the small projected matrix. All dense ops — MXU-friendly
    [m,n]x[n,q] dots; q stays static so XLA sees fixed shapes."""
    a = x._data if M is None else x._data - (
        M._data if isinstance(M, Tensor) else jnp.asarray(M))
    m, n = a.shape[-2], a.shape[-1]
    q = min(int(q), m, n)
    from ..core.rng import next_key
    omega = jax.random.normal(next_key(), a.shape[:-2] + (n, q), jnp.float32)
    y = a @ omega.astype(a.dtype)
    qm, _ = jnp.linalg.qr(y)
    for _ in range(int(niter)):
        z = jnp.swapaxes(a, -1, -2) @ qm
        z, _ = jnp.linalg.qr(z)
        y = a @ z
        qm, _ = jnp.linalg.qr(y)
    b = jnp.swapaxes(qm, -1, -2) @ a          # [q, n]
    ub, s, vh = jnp.linalg.svd(b, full_matrices=False)
    u = qm @ ub
    return Tensor(u), Tensor(s), Tensor(jnp.swapaxes(vh, -1, -2).conj())


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    """Principal components via randomized SVD on the (optionally centered)
    data (reference pca_lowrank)."""
    a = x._data
    m, n = a.shape[-2], a.shape[-1]
    if q is None:
        q = min(6, m, n)
    if center:
        a = a - jnp.mean(a, axis=-2, keepdims=True)
    return svd_lowrank(Tensor(a), q=q, niter=niter)


def eig(x, name=None):
    w, v = jnp.linalg.eig(x._data)
    return Tensor(w), Tensor(v)


def eigh(x, UPLO="L", name=None):
    w, v = jnp.linalg.eigh(x._data, UPLO=UPLO)
    return Tensor(w), Tensor(v)


def eigvals(x, name=None):
    return Tensor(jnp.linalg.eigvals(x._data))


def eigvalsh(x, UPLO="L", name=None):
    return Tensor(jnp.linalg.eigvalsh(x._data, UPLO=UPLO))


def solve(x, y, name=None):
    return apply_op(jnp.linalg.solve, x, y)


def lstsq(x, y, rcond=None, driver=None, name=None):
    sol, res, rank, sv = jnp.linalg.lstsq(x._data, y._data, rcond=rcond)
    return Tensor(sol), Tensor(res), Tensor(rank), Tensor(sv)


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False,
                     name=None):
    import jax.scipy.linalg as jsl

    def f(a, b):
        return jsl.solve_triangular(a, b, lower=not upper,
                                    trans=1 if transpose else 0,
                                    unit_diagonal=unitriangular)
    return apply_op(f, x, y)


def cross(x, y, axis=9, name=None):
    ax = axis if axis != 9 else next(
        (i for i, s in enumerate(x.shape) if s == 3), -1)
    return apply_op(lambda a, b: jnp.cross(a, b, axis=ax), x, y)


def histogramdd(x, bins=10, ranges=None, density=False, weights=None, name=None):
    h, edges = jnp.histogramdd(x._data, bins=bins, range=ranges,
                               density=density,
                               weights=None if weights is None else weights._data)
    return Tensor(h), [Tensor(e) for e in edges]


def t(x, name=None):
    if x.ndim < 2:
        return x.clone()
    return apply_op(lambda a: jnp.swapaxes(a, -1, -2), x)


def transpose_last(x):
    return t(x)


def svdvals(x, name=None):
    return apply_op(lambda a: jnp.linalg.svd(a, compute_uv=False), x)


def multi_dot(x, name=None):
    """Optimal-order chained matmul over a list of tensors."""
    return apply_op(lambda *arrs: jnp.linalg.multi_dot(arrs), *x)


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    fw = fweights._data if isinstance(fweights, Tensor) else fweights
    aw = aweights._data if isinstance(aweights, Tensor) else aweights
    return apply_op(lambda a: jnp.cov(a, rowvar=rowvar,
                                      ddof=1 if ddof else 0,
                                      fweights=fw, aweights=aw), x)


def corrcoef(x, rowvar=True, name=None):
    return apply_op(lambda a: jnp.corrcoef(a, rowvar=rowvar), x)


__all__ += ["svdvals", "multi_dot", "cov", "corrcoef"]


# ---- round-2 breadth: matrix_exp, householder_product, vecdot -------------

def matrix_exp(x, name=None):
    """Matrix exponential via jax.scipy.linalg.expm (Pade + scaling-and-
    squaring — the XLA-friendly fixed-iteration form)."""
    return apply_op(jax.scipy.linalg.expm, x)


def householder_product(x, tau, name=None):
    """Product of Householder reflectors (the Q of a geqrf factorization).
    Parity: paddle.linalg.householder_product."""
    return apply_op(
        lambda a, t: jax.lax.linalg.householder_product(a, t), x, tau)


def vecdot(x, y, axis=-1, name=None):
    return apply_op(lambda a, b: jnp.sum(a * b, axis=axis), x, y)


def cholesky_inverse(x, upper=False, name=None):
    """Inverse of A given its Cholesky factor: (LL^T)^-1 via two
    triangular solves against I."""
    def f(l):
        n = l.shape[-1]
        eye = jnp.eye(n, dtype=l.dtype)
        u = jnp.swapaxes(l, -1, -2) if not upper else l
        lo = l if not upper else jnp.swapaxes(l, -1, -2)
        z = jax.scipy.linalg.solve_triangular(lo, eye, lower=True)
        return jax.scipy.linalg.solve_triangular(u, z, lower=False)
    return apply_op(f, x)


__all__ += ["matrix_exp", "householder_product", "vecdot",
            "cholesky_inverse"]


def inverse(x, name=None):
    return inv(x)


def lu_unpack(lu_data, lu_pivots, unpack_ludata=True, unpack_pivots=True,
              name=None):
    """Unpack combined LU factors + pivots into (P, L, U); batched inputs
    produce batched P/L/U."""
    def fn_l(a):
        m = a.shape[-2]
        k = min(a.shape[-1], m)
        return jnp.tril(a[..., :, :k], -1) + jnp.eye(m, k, dtype=a.dtype)

    def fn_u(a):
        k = min(a.shape[-1], a.shape[-2])
        return jnp.triu(a[..., :k, :])

    L = apply_op(fn_l, lu_data) if unpack_ludata else None
    U = apply_op(fn_u, lu_data) if unpack_ludata else None
    P = None
    if unpack_pivots:
        piv = np.asarray(lu_pivots._data if isinstance(lu_pivots, Tensor)
                         else lu_pivots)
        m = int(lu_data.shape[-2])
        k = piv.shape[-1]
        batch_shape = piv.shape[:-1]
        flat = piv.reshape(-1, k)
        pms = np.zeros((flat.shape[0], m, m), np.float32)
        for b in range(flat.shape[0]):
            perm = np.arange(m)
            for i in range(min(k, m)):
                j = int(flat[b, i])
                perm[i], perm[j] = perm[j], perm[i]
            pms[b, perm, np.arange(m)] = 1.0
        P = Tensor(jnp.asarray(pms.reshape(*batch_shape, m, m)))
    return P, L, U


import numpy as np  # noqa: E402
__all__ += ["inverse", "lu_unpack"]


def vector_norm(x, p=2.0, axis=None, keepdim=False, name=None):
    """Vector p-norm over axis (reference paddle.linalg.vector_norm)."""
    def fn(a):
        ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
        d = jnp.abs(a)
        if p == 0:
            return (d != 0).astype(a.dtype).sum(axis=ax, keepdims=keepdim)
        if jnp.isinf(p):
            return d.max(axis=ax, keepdims=keepdim) if p > 0 else \
                d.min(axis=ax, keepdims=keepdim)
        return (d ** p).sum(axis=ax, keepdims=keepdim) ** (1.0 / p)
    return apply_op(fn, x)


def matrix_norm(x, p="fro", axis=(-2, -1), keepdim=False, name=None):
    """Matrix norm over the trailing two axes: 'fro', 'nuc', 1, -1, 2, -2,
    inf, -inf (reference paddle.linalg.matrix_norm)."""
    ax = tuple(axis)

    def _keep(out, a_ndim):
        # re-insert the reduced axes (normalized, ascending) as size-1 dims
        for axpos in sorted(d % a_ndim for d in ax):
            out = jnp.expand_dims(out, axpos)
        return out

    def fn(a):
        if p == "fro":
            return jnp.sqrt((a * a).sum(axis=ax, keepdims=keepdim))
        if p == "nuc":
            s = jnp.linalg.svd(jnp.moveaxis(a, ax, (-2, -1)),
                               compute_uv=False)
            out = s.sum(-1)
            return _keep(out, a.ndim) if keepdim else out
        if p in (2, -2):
            s = jnp.linalg.svd(jnp.moveaxis(a, ax, (-2, -1)),
                               compute_uv=False)
            out = s.max(-1) if p == 2 else s.min(-1)
            return _keep(out, a.ndim) if keepdim else out
        if p in (1, -1):
            col = jnp.abs(a).sum(axis=ax[0], keepdims=True)
            out = (col.max(axis=ax[1], keepdims=True) if p == 1
                   else col.min(axis=ax[1], keepdims=True))
        elif p in (jnp.inf, float("inf"), -jnp.inf, float("-inf")):
            row = jnp.abs(a).sum(axis=ax[1], keepdims=True)
            out = (row.max(axis=ax[0], keepdims=True)
                   if p > 0 else row.min(axis=ax[0], keepdims=True))
        else:
            raise ValueError(f"unsupported matrix norm order {p!r}")
        if not keepdim:
            out = out.squeeze(ax)
        return out
    return apply_op(fn, x)


def ormqr(input, tau, other, left=True, transpose=False, name=None):
    """Multiply `other` by the IMPLICIT full (m,m) Q of the reflectors.
    Thin inputs are zero-padded to square (zero-tau reflectors are the
    identity), so the product matches the reference for the usual
    m > k case; XLA fuses the Q formation into the matmul."""
    def fn(h, t, o):
        m = h.shape[-2]
        k = h.shape[-1]
        if k < m:
            pad_h = [(0, 0)] * (h.ndim - 1) + [(0, m - k)]
            h = jnp.pad(h, pad_h)
            pad_t = [(0, 0)] * (t.ndim - 1) + [(0, m - k)]
            t = jnp.pad(t, pad_t)
        q = jax.lax.linalg.householder_product(h, t)
        qq = jnp.swapaxes(q, -1, -2) if transpose else q
        return qq @ o if left else o @ qq
    return apply_op(fn, input, tau, other)


__all__ += ["vector_norm", "matrix_norm", "ormqr"]


def lu_solve(b, lu_data, lu_pivots, trans="N", name=None):
    """Solve A x = b from lu()'s packed factorization (reference:
    python/paddle/tensor/linalg.py :: lu_solve, 2.6)."""
    import jax.scipy.linalg as jsl
    t = {"N": 0, "T": 1, "H": 2}.get(trans, 0)

    def f(lu_, piv, rhs):
        return jsl.lu_solve((lu_, piv.astype(jnp.int32)), rhs, trans=t)
    return apply_op(f, lu_data, lu_pivots, b)


__all__ += ["lu_solve"]
