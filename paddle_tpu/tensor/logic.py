"""Logic ops. Parity: python/paddle/tensor/logic.py."""
from __future__ import annotations

import jax.numpy as jnp

from .tensor import Tensor

__all__ = ["equal", "not_equal", "less_than", "less_equal", "greater_than",
           "greater_equal", "logical_and", "logical_or", "logical_xor",
           "logical_not", "equal_all", "allclose", "isclose", "is_tensor",
           "is_empty"]


def _arr(v):
    return v._data if isinstance(v, Tensor) else v


def equal(x, y, name=None):
    return Tensor(jnp.equal(_arr(x), _arr(y)))


def not_equal(x, y, name=None):
    return Tensor(jnp.not_equal(_arr(x), _arr(y)))


def less_than(x, y, name=None):
    return Tensor(jnp.less(_arr(x), _arr(y)))


def less_equal(x, y, name=None):
    return Tensor(jnp.less_equal(_arr(x), _arr(y)))


def greater_than(x, y, name=None):
    return Tensor(jnp.greater(_arr(x), _arr(y)))


def greater_equal(x, y, name=None):
    return Tensor(jnp.greater_equal(_arr(x), _arr(y)))


def logical_and(x, y, out=None, name=None):
    return Tensor(jnp.logical_and(_arr(x), _arr(y)))


def logical_or(x, y, out=None, name=None):
    return Tensor(jnp.logical_or(_arr(x), _arr(y)))


def logical_xor(x, y, out=None, name=None):
    return Tensor(jnp.logical_xor(_arr(x), _arr(y)))


def logical_not(x, out=None, name=None):
    return Tensor(jnp.logical_not(_arr(x)))


def equal_all(x, y, name=None):
    return Tensor(jnp.array_equal(_arr(x), _arr(y)))


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return Tensor(jnp.allclose(_arr(x), _arr(y), rtol=rtol, atol=atol,
                               equal_nan=equal_nan))


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return Tensor(jnp.isclose(_arr(x), _arr(y), rtol=rtol, atol=atol,
                              equal_nan=equal_nan))


def is_tensor(x):
    return isinstance(x, Tensor)


def is_empty(x, name=None):
    return Tensor(jnp.asarray(x.size == 0))


# ---- round-2 breadth ------------------------------------------------------

from .tensor import apply_op  # noqa: E402


def isin(x, test_x, assume_unique=False, invert=False, name=None):
    t = test_x._data if isinstance(test_x, Tensor) else jnp.asarray(test_x)
    return apply_op(lambda a: jnp.isin(a, t, invert=invert), x)


def is_complex(x):
    return jnp.issubdtype(
        (x._data if isinstance(x, Tensor) else jnp.asarray(x)).dtype,
        jnp.complexfloating)


def is_floating_point(x):
    return jnp.issubdtype(
        (x._data if isinstance(x, Tensor) else jnp.asarray(x)).dtype,
        jnp.floating)


def is_integer(x):
    return jnp.issubdtype(
        (x._data if isinstance(x, Tensor) else jnp.asarray(x)).dtype,
        jnp.integer)


def isreal(x, name=None):
    return apply_op(jnp.isreal, x)


__all__ += ["isin", "is_complex", "is_floating_point", "is_integer",
            "isreal"]
