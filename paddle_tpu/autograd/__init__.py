"""paddle.autograd — backward(), grad(), PyLayer, saved-tensor hooks.

Parity: ``python/paddle/autograd/`` (py_layer.py, backward_mode.py).
"""
from __future__ import annotations

from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp

from ..tensor.tensor import (Tensor, no_grad, enable_grad, is_grad_enabled,
                             set_grad_enabled, apply_op, _tape)
from .backward_engine import run_backward

__all__ = ["backward", "grad", "no_grad", "enable_grad", "is_grad_enabled",
           "set_grad_enabled", "PyLayer", "PyLayerContext"]


def backward(tensors, grad_tensors=None, retain_graph: bool = False) -> None:
    if isinstance(tensors, Tensor):
        tensors = [tensors]
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    elif isinstance(grad_tensors, Tensor):
        grad_tensors = [grad_tensors]
    run_backward(tensors, grad_tensors, retain_graph=retain_graph)


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph: bool = False, only_inputs: bool = True,
         allow_unused: bool = False, no_grad_vars=None):
    """paddle.grad: gradients of outputs w.r.t. inputs without touching .grad."""
    outputs = [outputs] if isinstance(outputs, Tensor) else list(outputs)
    inputs = [inputs] if isinstance(inputs, Tensor) else list(inputs)
    if grad_outputs is None:
        grad_outputs = [None] * len(outputs)
    elif isinstance(grad_outputs, Tensor):
        grad_outputs = [grad_outputs]
    retain = True if retain_graph is None else retain_graph

    # stash current .grad, run engine, read, restore
    saved = [(t, t.grad) for t in inputs]
    for t in inputs:
        t.grad = None
    saved_sg = [(t, t.stop_gradient, t._is_leaf) for t in inputs]
    for t in inputs:
        t.stop_gradient = False
        t._is_leaf = True
    try:
        run_backward(outputs, grad_outputs, retain_graph=True)
        results = []
        for t in inputs:
            if t.grad is None:
                if not allow_unused:
                    raise RuntimeError(
                        f"input {t.name} unused in graph (allow_unused=False)")
                results.append(None)
            else:
                results.append(t.grad)
    finally:
        for t, sg, leaf in saved_sg:
            t.stop_gradient = sg
            t._is_leaf = leaf
        for t, g in saved:
            t.grad = g
        if not retain:
            _tape.nodes.clear()
    return results


class PyLayerContext:
    """Context handed to PyLayer.forward/backward (save_for_backward parity)."""

    def __init__(self):
        self._saved: tuple = ()
        self.materialize_grads = True

    def save_for_backward(self, *tensors):
        self._saved = tensors

    @property
    def saved_tensor(self):
        return self._saved

    def saved_tensors(self):
        return self._saved


class PyLayerMeta(type):
    def __call__(cls, *args, **kwargs):
        raise RuntimeError("PyLayer subclasses are applied via .apply(...)")


class PyLayer(metaclass=PyLayerMeta):
    """Custom autograd op: subclass with static forward(ctx, ...) / backward(ctx, *grads).

    Parity: ``python/paddle/autograd/py_layer.py :: PyLayer``.
    """

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        from ..tensor.tensor import _TapeNode, _tape as tape
        ctx = PyLayerContext()
        tensor_inputs = [a for a in args if isinstance(a, Tensor)]
        with no_grad():
            out = cls.forward(ctx, *args, **kwargs)
        multi = isinstance(out, (tuple, list))
        outs_raw = tuple(out) if multi else (out,)

        need_grad = is_grad_enabled() and any(
            not t.stop_gradient for t in tensor_inputs)
        if not need_grad:
            return out

        outs = tuple(Tensor(o._data if isinstance(o, Tensor) else o,
                            stop_gradient=False) for o in outs_raw)
        for o in outs:
            o._is_leaf = False

        def vjp_fn(cots):
            gts = tuple(Tensor(c) for c in cots)
            with no_grad():
                gin = cls.backward(ctx, *gts) if len(gts) > 1 else cls.backward(ctx, gts[0])
            gin = gin if isinstance(gin, (tuple, list)) else (gin,)
            res = []
            it = iter(gin)
            for a in args:
                if isinstance(a, Tensor):
                    g = next(it, None)
                    res.append(None if g is None else
                               (g._data if isinstance(g, Tensor) else g))
            return tuple(res)

        node = _TapeNode(
            inputs=tensor_inputs,
            output_ids=[o._uid for o in outs],
            vjp_fn=vjp_fn,
            outputs_meta=[(tuple(o.shape), o.dtype) for o in outs],
        )
        from ..tensor.tensor import _register_node
        _register_node(node, outs)
        return outs if multi else outs[0]


# functional transforms (reference: autograd.py jacobian/hessian + incubate
# jvp/vjp)
from .functional import jacobian, hessian, jvp, vjp  # noqa: E402
__all__ += ["jacobian", "hessian", "jvp", "vjp"]
