"""paddle.autograd functional transforms: jacobian, hessian, jvp, vjp.

Parity: python/paddle/autograd/autograd.py :: jacobian, hessian (2.6 lazy
Jacobian API exposed eagerly here) and python/paddle/incubate/autograd/
:: jvp, vjp. TPU-first: these are direct jax.jacfwd/jacrev/jvp/vjp over a
functionalized view of the user callable — one traced program instead of
the reference's per-row double-backward loops."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..tensor.tensor import Tensor

__all__ = ["jacobian", "hessian", "jvp", "vjp"]


def _wrap_func(func, n_inputs):
    """Lift a Tensor→Tensor(s) callable to arrays→arrays (pure)."""
    def fn(*arrays):
        outs = func(*[Tensor(a) for a in arrays])
        if isinstance(outs, (tuple, list)):
            return tuple(o._data for o in outs)
        return outs._data
    return fn


def _unpack(xs):
    if isinstance(xs, (tuple, list)):
        return [x._data if isinstance(x, Tensor) else jnp.asarray(x)
                for x in xs], True
    return [xs._data if isinstance(xs, Tensor) else jnp.asarray(xs)], False


def _tensorize(tree):
    return jax.tree.map(lambda a: Tensor(a), tree)


def jacobian(func, xs, is_batched: bool = False, mode: str = "rev"):
    """d func(xs) / d xs. mode='rev' (jacrev, tall Jacobians) or 'fwd'
    (jacfwd, wide Jacobians). Returns Tensor(s) mirroring the reference's
    [*out_shape, *in_shape] layout (batched: diagonal over axis 0)."""
    arrays, multi_in = _unpack(xs)
    fn = _wrap_func(func, len(arrays))
    jac_fn = jax.jacrev if mode == "rev" else jax.jacfwd
    # single input: argnums=0 so the result mirrors the OUTPUT structure
    # exactly (a tuple result then means multiple outputs, never argnums)
    argnums = tuple(range(len(arrays))) if multi_in else 0
    if is_batched:
        jac = jax.vmap(jac_fn(fn, argnums=argnums))(*arrays)
    else:
        jac = jac_fn(fn, argnums=argnums)(*arrays)
    return _tensorize(jac)


def hessian(func, xs, is_batched: bool = False):
    """d² scalar-func / d xs² via fwd-over-rev (the XLA-efficient
    composition)."""
    arrays, multi_in = _unpack(xs)
    fn = _wrap_func(func, len(arrays))
    argnums = tuple(range(len(arrays))) if multi_in else 0
    hess_fn = jax.jacfwd(jax.jacrev(fn, argnums=argnums), argnums=argnums)
    if is_batched:
        hess = jax.vmap(hess_fn)(*arrays)
    else:
        hess = hess_fn(*arrays)
    return _tensorize(hess)


def jvp(func, xs, v=None):
    """Forward-mode: returns (func(xs), J·v). v defaults to ones."""
    arrays, multi_in = _unpack(xs)
    fn = _wrap_func(func, len(arrays))
    if v is None:
        tangents = [jnp.ones_like(a) for a in arrays]
    else:
        tangents, _ = _unpack(v)
    primal, tangent = jax.jvp(fn, tuple(arrays), tuple(tangents))
    return _tensorize(primal), _tensorize(tangent)


def vjp(func, xs, v=None):
    """Reverse-mode: returns (func(xs), vᵀ·J). v defaults to ones."""
    arrays, multi_in = _unpack(xs)
    fn = _wrap_func(func, len(arrays))
    primal, vjp_fn = jax.vjp(fn, *arrays)
    if v is None:
        cot = jax.tree.map(jnp.ones_like, primal)
    else:
        cots, _ = _unpack(v)
        cot = tuple(cots) if isinstance(primal, tuple) else cots[0]
    grads = vjp_fn(cot)
    gout = _tensorize(grads)
    if not multi_in:
        gout = gout[0]
    return _tensorize(primal), gout
