"""paddle.distribution families beyond the core four.

Parity: python/paddle/distribution/{beta,dirichlet,exponential,gamma,
geometric,gumbel,laplace,lognormal,multinomial,poisson,student_t,binomial,
cauchy}.py. Sampling draws explicit PRNG keys (core.rng.next_key) and all
math is jnp — XLA-compiled elementwise chains, no host round trips."""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..core.rng import next_key, next_threefry_key
from ..tensor.tensor import Tensor

__all__ = ["Beta", "Dirichlet", "Exponential", "Gamma", "Geometric",
           "Gumbel", "Laplace", "LogNormal", "Multinomial", "Poisson",
           "StudentT", "Binomial", "Cauchy"]


from . import Distribution, _arr  # noqa: E402  (late: avoid import cycle)


class Beta(Distribution):
    def __init__(self, alpha, beta):
        self.alpha = _arr(alpha)
        self.beta = _arr(beta)

    @property
    def mean(self):
        return Tensor(self.alpha / (self.alpha + self.beta))

    @property
    def variance(self):
        s = self.alpha + self.beta
        return Tensor(self.alpha * self.beta / (s * s * (s + 1)))

    def sample(self, shape=()):
        shape = tuple(shape) + jnp.broadcast_shapes(self.alpha.shape,
                                                    self.beta.shape)
        return Tensor(jax.random.beta(next_key(), self.alpha, self.beta,
                                      shape))

    def log_prob(self, value):
        v = _arr(value)
        lbeta = (jax.scipy.special.gammaln(self.alpha)
                 + jax.scipy.special.gammaln(self.beta)
                 - jax.scipy.special.gammaln(self.alpha + self.beta))
        return Tensor((self.alpha - 1) * jnp.log(v)
                      + (self.beta - 1) * jnp.log1p(-v) - lbeta)

    def entropy(self):
        a, b = self.alpha, self.beta
        dig = jax.scipy.special.digamma
        lbeta = (jax.scipy.special.gammaln(a) + jax.scipy.special.gammaln(b)
                 - jax.scipy.special.gammaln(a + b))
        return Tensor(lbeta - (a - 1) * dig(a) - (b - 1) * dig(b)
                      + (a + b - 2) * dig(a + b))


class Dirichlet(Distribution):
    def __init__(self, concentration):
        self.concentration = _arr(concentration)

    @property
    def mean(self):
        c = self.concentration
        return Tensor(c / c.sum(-1, keepdims=True))

    def sample(self, shape=()):
        return Tensor(jax.random.dirichlet(next_key(), self.concentration,
                                           tuple(shape) +
                                           self.concentration.shape[:-1]))

    def log_prob(self, value):
        v = _arr(value)
        c = self.concentration
        norm = (jax.scipy.special.gammaln(c).sum(-1)
                - jax.scipy.special.gammaln(c.sum(-1)))
        return Tensor(((c - 1) * jnp.log(v)).sum(-1) - norm)

    def entropy(self):
        c = self.concentration
        c0 = c.sum(-1)
        k = c.shape[-1]
        dig = jax.scipy.special.digamma
        lnB = (jax.scipy.special.gammaln(c).sum(-1)
               - jax.scipy.special.gammaln(c0))
        return Tensor(lnB + (c0 - k) * dig(c0)
                      - ((c - 1) * dig(c)).sum(-1))


class Exponential(Distribution):
    def __init__(self, rate):
        self.rate = _arr(rate)

    @property
    def mean(self):
        return Tensor(1.0 / self.rate)

    @property
    def variance(self):
        return Tensor(1.0 / self.rate ** 2)

    def sample(self, shape=()):
        shape = tuple(shape) + self.rate.shape
        return Tensor(jax.random.exponential(next_key(), shape) / self.rate)

    def log_prob(self, value):
        v = _arr(value)
        return Tensor(jnp.log(self.rate) - self.rate * v)

    def entropy(self):
        return Tensor(1.0 - jnp.log(self.rate))


class Gamma(Distribution):
    def __init__(self, concentration, rate):
        self.concentration = _arr(concentration)
        self.rate = _arr(rate)

    @property
    def mean(self):
        return Tensor(self.concentration / self.rate)

    @property
    def variance(self):
        return Tensor(self.concentration / self.rate ** 2)

    def sample(self, shape=()):
        shape = tuple(shape) + jnp.broadcast_shapes(
            self.concentration.shape, self.rate.shape)
        g = jax.random.gamma(next_key(), self.concentration, shape)
        return Tensor(g / self.rate)

    def log_prob(self, value):
        v = _arr(value)
        a, b = self.concentration, self.rate
        return Tensor(a * jnp.log(b) + (a - 1) * jnp.log(v) - b * v
                      - jax.scipy.special.gammaln(a))

    def entropy(self):
        a, b = self.concentration, self.rate
        dig = jax.scipy.special.digamma
        return Tensor(a - jnp.log(b) + jax.scipy.special.gammaln(a)
                      + (1 - a) * dig(a))


class Geometric(Distribution):
    """P(X=k) = (1-p)^k p for k = 0, 1, 2, ... (failures before success)."""

    def __init__(self, probs):
        self.p = _arr(probs)

    @property
    def mean(self):
        return Tensor((1 - self.p) / self.p)

    @property
    def variance(self):
        return Tensor((1 - self.p) / self.p ** 2)

    def sample(self, shape=()):
        shape = tuple(shape) + self.p.shape
        u = jax.random.uniform(next_key(), shape, minval=1e-7, maxval=1.0)
        return Tensor(jnp.floor(jnp.log(u) / jnp.log1p(-self.p)))

    def log_prob(self, value):
        v = _arr(value)
        return Tensor(v * jnp.log1p(-self.p) + jnp.log(self.p))

    def entropy(self):
        p = self.p
        return Tensor(-((1 - p) * jnp.log1p(-p) + p * jnp.log(p)) / p)


class Gumbel(Distribution):
    def __init__(self, loc, scale):
        self.loc = _arr(loc)
        self.scale = _arr(scale)

    @property
    def mean(self):
        return Tensor(self.loc + self.scale * np.euler_gamma)

    @property
    def variance(self):
        return Tensor((math.pi ** 2 / 6) * self.scale ** 2
                      * jnp.ones_like(self.loc))

    def sample(self, shape=()):
        shape = tuple(shape) + jnp.broadcast_shapes(self.loc.shape,
                                                    self.scale.shape)
        return Tensor(self.loc
                      + self.scale * jax.random.gumbel(next_key(), shape))

    def log_prob(self, value):
        z = (_arr(value) - self.loc) / self.scale
        return Tensor(-(z + jnp.exp(-z)) - jnp.log(self.scale))

    def entropy(self):
        return Tensor(jnp.log(self.scale) + 1 + np.euler_gamma
                      + jnp.zeros_like(self.loc))


class Laplace(Distribution):
    def __init__(self, loc, scale):
        self.loc = _arr(loc)
        self.scale = _arr(scale)

    @property
    def mean(self):
        return Tensor(self.loc)

    @property
    def variance(self):
        return Tensor(2 * self.scale ** 2 * jnp.ones_like(self.loc))

    def sample(self, shape=()):
        shape = tuple(shape) + jnp.broadcast_shapes(self.loc.shape,
                                                    self.scale.shape)
        return Tensor(self.loc
                      + self.scale * jax.random.laplace(next_key(), shape))

    def log_prob(self, value):
        v = _arr(value)
        return Tensor(-jnp.abs(v - self.loc) / self.scale
                      - jnp.log(2 * self.scale))

    def entropy(self):
        return Tensor(1 + jnp.log(2 * self.scale)
                      + jnp.zeros_like(self.loc))


class LogNormal(Distribution):
    def __init__(self, loc, scale):
        self.loc = _arr(loc)
        self.scale = _arr(scale)

    @property
    def mean(self):
        return Tensor(jnp.exp(self.loc + self.scale ** 2 / 2))

    @property
    def variance(self):
        s2 = self.scale ** 2
        return Tensor((jnp.exp(s2) - 1) * jnp.exp(2 * self.loc + s2))

    def sample(self, shape=()):
        shape = tuple(shape) + jnp.broadcast_shapes(self.loc.shape,
                                                    self.scale.shape)
        eps = jax.random.normal(next_key(), shape)
        return Tensor(jnp.exp(self.loc + self.scale * eps))

    def log_prob(self, value):
        v = _arr(value)
        logv = jnp.log(v)
        var = self.scale ** 2
        return Tensor(-((logv - self.loc) ** 2) / (2 * var) - logv
                      - jnp.log(self.scale) - 0.5 * math.log(2 * math.pi))

    def entropy(self):
        return Tensor(self.loc + 0.5 + 0.5 * math.log(2 * math.pi)
                      + jnp.log(self.scale))


class Multinomial(Distribution):
    def __init__(self, total_count, probs):
        self.total_count = int(total_count)
        self.probs_arr = _arr(probs)

    @property
    def mean(self):
        return Tensor(self.total_count * self.probs_arr)

    def sample(self, shape=()):
        n = self.total_count
        k = self.probs_arr.shape[-1]
        logits = jnp.log(self.probs_arr)
        big = jnp.broadcast_to(
            logits, tuple(shape) + logits.shape[:-1] + (n, k))
        cats = jax.random.categorical(next_key(), big, axis=-1)
        counts = jax.nn.one_hot(cats, k).sum(-2)
        return Tensor(counts)

    def log_prob(self, value):
        v = _arr(value)
        logp = jnp.log(self.probs_arr)
        coeff = (jax.scipy.special.gammaln(
            jnp.asarray(self.total_count + 1.0))
            - jax.scipy.special.gammaln(v + 1.0).sum(-1))
        return Tensor(coeff + (v * logp).sum(-1))


class Poisson(Distribution):
    def __init__(self, rate):
        self.rate = _arr(rate)

    @property
    def mean(self):
        return Tensor(self.rate)

    @property
    def variance(self):
        return Tensor(self.rate)

    def sample(self, shape=()):
        shape = tuple(shape) + self.rate.shape
        return Tensor(jax.random.poisson(next_threefry_key(), self.rate,
                                         shape).astype(jnp.float32))

    def log_prob(self, value):
        v = _arr(value)
        return Tensor(v * jnp.log(self.rate) - self.rate
                      - jax.scipy.special.gammaln(v + 1.0))


class StudentT(Distribution):
    def __init__(self, df, loc=0.0, scale=1.0):
        self.df = _arr(df)
        self.loc = _arr(loc)
        self.scale = _arr(scale)

    @property
    def mean(self):
        return Tensor(jnp.where(self.df > 1, self.loc, jnp.nan))

    @property
    def variance(self):
        v = self.df
        var = jnp.where(v > 2, self.scale ** 2 * v / (v - 2), jnp.inf)
        return Tensor(jnp.where(v > 1, var, jnp.nan))

    def sample(self, shape=()):
        shape = tuple(shape) + jnp.broadcast_shapes(
            self.df.shape, self.loc.shape, self.scale.shape)
        t = jax.random.t(next_key(), self.df, shape)
        return Tensor(self.loc + self.scale * t)

    def log_prob(self, value):
        v = self.df
        z = (_arr(value) - self.loc) / self.scale
        lg = jax.scipy.special.gammaln
        return Tensor(lg((v + 1) / 2) - lg(v / 2)
                      - 0.5 * jnp.log(v * math.pi) - jnp.log(self.scale)
                      - (v + 1) / 2 * jnp.log1p(z ** 2 / v))


class Binomial(Distribution):
    def __init__(self, total_count, probs):
        self.total_count = _arr(total_count)
        self.p = _arr(probs)

    @property
    def mean(self):
        return Tensor(self.total_count * self.p)

    @property
    def variance(self):
        return Tensor(self.total_count * self.p * (1 - self.p))

    def sample(self, shape=()):
        shape = tuple(shape) + jnp.broadcast_shapes(
            self.total_count.shape, self.p.shape)
        return Tensor(jax.random.binomial(next_key(), self.total_count,
                                          self.p, shape=shape))

    def log_prob(self, value):
        v = _arr(value)
        n, p = self.total_count, self.p
        lg = jax.scipy.special.gammaln
        return Tensor(lg(n + 1) - lg(v + 1) - lg(n - v + 1)
                      + v * jnp.log(p) + (n - v) * jnp.log1p(-p))


class Cauchy(Distribution):
    def __init__(self, loc, scale):
        self.loc = _arr(loc)
        self.scale = _arr(scale)

    def sample(self, shape=()):
        shape = tuple(shape) + jnp.broadcast_shapes(self.loc.shape,
                                                    self.scale.shape)
        return Tensor(self.loc
                      + self.scale * jax.random.cauchy(next_key(), shape))

    def log_prob(self, value):
        z = (_arr(value) - self.loc) / self.scale
        return Tensor(-jnp.log(math.pi * self.scale * (1 + z ** 2)))

    def entropy(self):
        return Tensor(jnp.log(4 * math.pi * self.scale)
                      + jnp.zeros_like(self.loc))


class ExponentialFamily(Distribution):
    """Base for exponential-family distributions (reference:
    distribution/exponential_family.py): subclasses expose natural
    parameters + log-normalizer and inherit a Bregman-divergence entropy
    via autodiff of the log normalizer."""

    @property
    def _natural_parameters(self):
        raise NotImplementedError

    def _log_normalizer(self, *natural_params):
        raise NotImplementedError

    @property
    def _mean_carrier_measure(self):
        return 0.0

    def entropy(self):
        """H = logZ - sum(eta_i * d(logZ)/d(eta_i)) + E[carrier]."""
        nats = [jnp.asarray(n, jnp.float32) for n in self._natural_parameters]
        grads = jax.grad(
            lambda *ns: jnp.sum(self._log_normalizer(*ns)),
            argnums=tuple(range(len(nats))))(*nats)
        ent = self._log_normalizer(*nats) - self._mean_carrier_measure
        for n, g in zip(nats, grads):
            ent = ent - n * g
        return Tensor(ent)


class Chi2(Gamma):
    """Chi-squared with df degrees of freedom == Gamma(df/2, 1/2)
    (reference: distribution/chi2.py)."""

    def __init__(self, df):
        self.df = _arr(df).astype(jnp.float32)   # int df must not make
        super().__init__(self.df / 2.0,          # rate truncate to 0
                         jnp.full_like(self.df, 0.5))


class ContinuousBernoulli(Distribution):
    """CB(lambda) on [0, 1] (reference: continuous_bernoulli.py):
    p(x) = C(l) l^x (1-l)^(1-x) with the closed-form normalizer; the
    l == 0.5 removable singularity handled by a Taylor guard."""

    def __init__(self, probs, lims=(0.499, 0.501)):
        self.p = _arr(probs)
        self.lims = lims

    def _outside(self):
        return (self.p < self.lims[0]) | (self.p > self.lims[1])

    def _log_norm(self):
        # log C = log( 2 atanh(1-2l) / (1-2l) ) for l != 1/2, -> log 2
        p_safe = jnp.where(self._outside(), self.p, 0.25)
        x = 1 - 2 * p_safe
        out = jnp.log(2.0 * jnp.arctanh(x) / x)
        return jnp.where(self._outside(), out, jnp.log(2.0)
                         + jnp.log1p((1 - 2 * self.p) ** 2 / 3))

    @property
    def mean(self):
        p_safe = jnp.where(self._outside(), self.p, 0.25)
        m = p_safe / (2 * p_safe - 1) + \
            1 / (2 * jnp.arctanh(1 - 2 * p_safe))
        return Tensor(jnp.where(self._outside(), m,
                                0.5 + (self.p - 0.5) / 3))

    def sample(self, shape=()):
        shape = tuple(shape) + self.p.shape
        u = jax.random.uniform(next_key(), shape)
        return self.icdf(Tensor(u))

    def icdf(self, value):
        # F(x) = (1-l)(r^x - 1)/(2l-1) with r = l/(1-l); inverting:
        # x = log(1 + u(2l-1)/(1-l)) / log(l/(1-l))
        u = _arr(value)
        p_safe = jnp.where(self._outside(), self.p, 0.25)
        num = jnp.log1p(u * (2 * p_safe - 1)
                        / jnp.maximum(1 - p_safe, 1e-12))
        den = jnp.log(p_safe / jnp.maximum(1 - p_safe, 1e-12))
        out = num / den
        return Tensor(jnp.where(self._outside(), out, u))

    def log_prob(self, value):
        v = _arr(value)
        return Tensor(self._log_norm() + v * jnp.log(
            jnp.maximum(self.p, 1e-12)) + (1 - v) * jnp.log(
            jnp.maximum(1 - self.p, 1e-12)))


class MultivariateNormal(Distribution):
    """MVN(loc, covariance_matrix) (reference:
    distribution/multivariate_normal.py); scale_tril/precision accepted
    like the reference, internally Cholesky-parameterized."""

    def __init__(self, loc, covariance_matrix=None, precision_matrix=None,
                 scale_tril=None):
        self.loc = _arr(loc)
        given = [a is not None for a in (covariance_matrix,
                                         precision_matrix, scale_tril)]
        if sum(given) != 1:
            raise ValueError("exactly one of covariance_matrix, "
                             "precision_matrix, scale_tril required")
        if scale_tril is not None:
            self.scale_tril = _arr(scale_tril)
        elif covariance_matrix is not None:
            self.scale_tril = jnp.linalg.cholesky(_arr(covariance_matrix))
        else:
            prec = _arr(precision_matrix)
            self.scale_tril = jnp.linalg.cholesky(jnp.linalg.inv(prec))

    @property
    def mean(self):
        return Tensor(self.loc)

    @property
    def covariance_matrix(self):
        return Tensor(self.scale_tril @ jnp.swapaxes(self.scale_tril,
                                                     -1, -2))

    @property
    def variance(self):
        return Tensor(jnp.sum(self.scale_tril ** 2, axis=-1))

    def sample(self, shape=()):
        d = self.loc.shape[-1]
        shape = tuple(shape) + jnp.broadcast_shapes(
            self.loc.shape[:-1], self.scale_tril.shape[:-2]) + (d,)
        z = jax.random.normal(next_key(), shape)
        return Tensor(self.loc + jnp.einsum("...ij,...j->...i",
                                            self.scale_tril, z))

    rsample = sample

    def log_prob(self, value):
        v = _arr(value)
        d = self.loc.shape[-1]
        diff = v - self.loc
        # solve L y = diff; logdet from the Cholesky diagonal
        y = jax.scipy.linalg.solve_triangular(self.scale_tril, diff[..., None],
                                              lower=True)[..., 0]
        half_logdet = jnp.sum(jnp.log(jnp.diagonal(self.scale_tril,
                                                   axis1=-2, axis2=-1)),
                              axis=-1)
        return Tensor(-0.5 * jnp.sum(y * y, -1) - half_logdet
                      - 0.5 * d * jnp.log(2 * jnp.asarray(math.pi)))

    def entropy(self):
        d = self.loc.shape[-1]
        half_logdet = jnp.sum(jnp.log(jnp.diagonal(self.scale_tril,
                                                   axis1=-2, axis2=-1)),
                              axis=-1)
        return Tensor(0.5 * d * (1 + jnp.log(2 * jnp.asarray(math.pi)))
                      + half_logdet)


__all__ += ["ExponentialFamily", "Chi2", "ContinuousBernoulli",
            "MultivariateNormal"]
