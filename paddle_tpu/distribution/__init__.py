"""paddle.distribution — probability distributions.

Parity: python/paddle/distribution/ (Distribution base, Normal, Uniform,
Categorical, Bernoulli, kl_divergence). TPU-native: sampling draws explicit
PRNG keys from the global seed facade (core.rng) and all math is jnp.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..core.rng import next_key
from ..tensor.tensor import Tensor

__all__ = ["Distribution", "Normal", "Uniform", "Categorical", "Bernoulli",
           "kl_divergence"]


def _arr(x):
    if isinstance(x, Tensor):
        return x._data
    return jnp.asarray(np.asarray(x), jnp.float32)


class Distribution:
    def sample(self, shape=()):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def entropy(self):
        raise NotImplementedError

    def probs(self, value):
        return Tensor(jnp.exp(self.log_prob(value)._data))

    def rsample(self, shape=()):
        return self.sample(shape)


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _arr(loc)
        self.scale = _arr(scale)

    @property
    def mean(self):
        return Tensor(self.loc)

    @property
    def variance(self):
        return Tensor(self.scale ** 2)

    def sample(self, shape=()):
        shape = tuple(shape) + jnp.broadcast_shapes(self.loc.shape,
                                                    self.scale.shape)
        eps = jax.random.normal(next_key(), shape)
        return Tensor(self.loc + self.scale * eps)

    def log_prob(self, value):
        v = _arr(value)
        var = self.scale ** 2
        return Tensor(-((v - self.loc) ** 2) / (2 * var)
                      - jnp.log(self.scale) - 0.5 * math.log(2 * math.pi))

    def entropy(self):
        return Tensor(0.5 + 0.5 * math.log(2 * math.pi)
                      + jnp.log(self.scale)
                      + jnp.zeros_like(self.loc))

    def kl_divergence(self, other: "Normal"):
        var_ratio = (self.scale / other.scale) ** 2
        t1 = ((self.loc - other.loc) / other.scale) ** 2
        return Tensor(0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio)))


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = _arr(low)
        self.high = _arr(high)

    def sample(self, shape=()):
        shape = tuple(shape) + jnp.broadcast_shapes(self.low.shape,
                                                    self.high.shape)
        u = jax.random.uniform(next_key(), shape)
        return Tensor(self.low + (self.high - self.low) * u)

    def log_prob(self, value):
        v = _arr(value)
        inside = (v >= self.low) & (v < self.high)
        return Tensor(jnp.where(inside, -jnp.log(self.high - self.low),
                                -jnp.inf))

    def entropy(self):
        return Tensor(jnp.log(self.high - self.low))


class Categorical(Distribution):
    def __init__(self, logits, name=None):
        self.logits = _arr(logits)

    def sample(self, shape=()):
        n = int(np.prod(shape)) if shape else 1
        draws = jax.random.categorical(
            next_key(), self.logits, axis=-1,
            shape=tuple(shape) + self.logits.shape[:-1]) if shape else \
            jax.random.categorical(next_key(), self.logits, axis=-1)
        return Tensor(draws)

    def log_prob(self, value):
        v = _arr(value).astype(jnp.int32)
        logp = jax.nn.log_softmax(self.logits, axis=-1)
        return Tensor(jnp.take_along_axis(logp, v[..., None],
                                          axis=-1)[..., 0])

    def entropy(self):
        logp = jax.nn.log_softmax(self.logits, axis=-1)
        p = jnp.exp(logp)
        return Tensor(-(p * logp).sum(-1))


class Bernoulli(Distribution):
    def __init__(self, probs, name=None):
        self.p = _arr(probs)

    def sample(self, shape=()):
        shape = tuple(shape) + self.p.shape
        return Tensor(jax.random.bernoulli(next_key(), self.p,
                                           shape).astype(jnp.float32))

    def log_prob(self, value):
        v = _arr(value)
        eps = 1e-8
        return Tensor(v * jnp.log(self.p + eps)
                      + (1 - v) * jnp.log(1 - self.p + eps))

    def entropy(self):
        eps = 1e-8
        return Tensor(-(self.p * jnp.log(self.p + eps)
                        + (1 - self.p) * jnp.log(1 - self.p + eps)))


_KL_REGISTRY: dict = {}


def register_kl(cls_p, cls_q):
    """Decorator registering a KL(p||q) rule for a distribution pair
    (reference: python/paddle/distribution/kl.py :: register_kl)."""
    def decorator(fn):
        _KL_REGISTRY[(cls_p, cls_q)] = fn
        return fn
    return decorator


def kl_divergence(p: Distribution, q: Distribution):
    """Dispatch on the most-derived registered (type(p), type(q)) pair —
    MRO distance, exactly like single-dispatch resolution."""
    matches = [(cp, cq) for (cp, cq) in _KL_REGISTRY
               if isinstance(p, cp) and isinstance(q, cq)]
    if not matches:
        raise NotImplementedError(
            f"kl_divergence({type(p).__name__}, {type(q).__name__})")
    mro_p, mro_q = type(p).__mro__, type(q).__mro__
    matches.sort(key=lambda pair: (mro_p.index(pair[0]),
                                   mro_q.index(pair[1])))
    return _KL_REGISTRY[matches[0]](p, q)


@register_kl(Normal, Normal)
def _kl_normal_normal(p, q):
    return p.kl_divergence(q)


@register_kl(Categorical, Categorical)
def _kl_categorical(p, q):
    lp = jax.nn.log_softmax(p.logits, axis=-1)
    lq = jax.nn.log_softmax(q.logits, axis=-1)
    return Tensor((jnp.exp(lp) * (lp - lq)).sum(-1))


@register_kl(Bernoulli, Bernoulli)
def _kl_bernoulli(p, q):
    eps = 1e-8
    a, b = p.p, q.p
    return Tensor(a * (jnp.log(a + eps) - jnp.log(b + eps))
                  + (1 - a) * (jnp.log(1 - a + eps)
                               - jnp.log(1 - b + eps)))


@register_kl(Uniform, Uniform)
def _kl_uniform(p, q):
    # KL is finite only when support(p) ⊆ support(q)
    inside = (p.low >= q.low) & (p.high <= q.high)
    val = jnp.log((q.high - q.low) / (p.high - p.low))
    return Tensor(jnp.where(inside, val, jnp.inf))


# ---- extended families + transforms (separate modules) --------------------
from .families import (Beta, Dirichlet, Exponential, Gamma,  # noqa: E402
                       Geometric, Gumbel, Laplace, LogNormal, Multinomial,
                       Poisson, StudentT, Binomial, Cauchy,
                       ExponentialFamily, Chi2, ContinuousBernoulli,
                       MultivariateNormal)
from .transform import (Transform, AffineTransform, ExpTransform,  # noqa: E402
                        SigmoidTransform, TanhTransform, PowerTransform,
                        AbsTransform, ChainTransform,
                        TransformedDistribution)
from . import transform  # noqa: E402

__all__ += ["register_kl", "Beta", "Dirichlet", "Exponential", "Gamma",
            "Geometric", "Gumbel", "Laplace", "LogNormal", "Multinomial",
            "Poisson", "StudentT", "Binomial", "Cauchy", "Transform",
            "AffineTransform", "ExpTransform", "SigmoidTransform",
            "TanhTransform", "PowerTransform", "AbsTransform",
            "ChainTransform", "TransformedDistribution", "transform"]


@register_kl(Beta, Beta)
def _kl_beta(p, q):
    lg, dig = jax.scipy.special.gammaln, jax.scipy.special.digamma
    a1, b1, a2, b2 = p.alpha, p.beta, q.alpha, q.beta
    s1 = a1 + b1
    return Tensor((lg(a2) + lg(b2) - lg(a2 + b2))
                  - (lg(a1) + lg(b1) - lg(s1))
                  + (a1 - a2) * dig(a1) + (b1 - b2) * dig(b1)
                  + (a2 - a1 + b2 - b1) * dig(s1))


@register_kl(Gamma, Gamma)
def _kl_gamma(p, q):
    lg, dig = jax.scipy.special.gammaln, jax.scipy.special.digamma
    a1, b1, a2, b2 = p.concentration, p.rate, q.concentration, q.rate
    return Tensor((a1 - a2) * dig(a1) - lg(a1) + lg(a2)
                  + a2 * (jnp.log(b1) - jnp.log(b2))
                  + a1 * (b2 - b1) / b1)


@register_kl(Exponential, Exponential)
def _kl_exponential(p, q):
    r = q.rate / p.rate
    return Tensor(jnp.log(p.rate) - jnp.log(q.rate) + r - 1.0)


@register_kl(Laplace, Laplace)
def _kl_laplace(p, q):
    d = jnp.abs(p.loc - q.loc)
    return Tensor(jnp.log(q.scale / p.scale)
                  + (p.scale * jnp.exp(-d / p.scale) + d) / q.scale - 1.0)
