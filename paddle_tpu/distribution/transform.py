"""paddle.distribution.transform + TransformedDistribution.

Parity: python/paddle/distribution/transform.py :: Transform, AffineTransform,
ExpTransform, SigmoidTransform, TanhTransform, PowerTransform, AbsTransform,
ChainTransform, and transformed_distribution.py :: TransformedDistribution.
log_prob uses the change-of-variables formula with jnp log-det-jacobians."""
from __future__ import annotations

import jax.numpy as jnp

from ..tensor.tensor import Tensor

__all__ = ["Transform", "AffineTransform", "ExpTransform",
           "SigmoidTransform", "TanhTransform", "PowerTransform",
           "AbsTransform", "ChainTransform", "TransformedDistribution"]


from . import _arr  # noqa: E402  (shared helper; late: avoid import cycle)


class Transform:
    def forward(self, x):
        return Tensor(self._forward(_arr(x)))

    def inverse(self, y):
        return Tensor(self._inverse(_arr(y)))

    def forward_log_det_jacobian(self, x):
        return Tensor(self._fldj(_arr(x)))

    def inverse_log_det_jacobian(self, y):
        return Tensor(-self._fldj(self._inverse(_arr(y))))

    def __call__(self, x):
        return self.forward(x)


class AffineTransform(Transform):
    """y = loc + scale * x."""

    def __init__(self, loc, scale):
        self.loc = _arr(loc)
        self.scale = _arr(scale)

    def _forward(self, x):
        return self.loc + self.scale * x

    def _inverse(self, y):
        return (y - self.loc) / self.scale

    def _fldj(self, x):
        return jnp.broadcast_to(jnp.log(jnp.abs(self.scale)), x.shape)


class ExpTransform(Transform):
    def _forward(self, x):
        return jnp.exp(x)

    def _inverse(self, y):
        return jnp.log(y)

    def _fldj(self, x):
        return x


class SigmoidTransform(Transform):
    def _forward(self, x):
        return 1 / (1 + jnp.exp(-x))

    def _inverse(self, y):
        return jnp.log(y) - jnp.log1p(-y)

    def _fldj(self, x):
        return -jnp.logaddexp(0.0, -x) - jnp.logaddexp(0.0, x)


class TanhTransform(Transform):
    def _forward(self, x):
        return jnp.tanh(x)

    def _inverse(self, y):
        return jnp.arctanh(y)

    def _fldj(self, x):
        # log(1 - tanh^2 x) = 2(log 2 - x - softplus(-2x))
        return 2.0 * (jnp.log(2.0) - x - jnp.logaddexp(0.0, -2.0 * x))


class PowerTransform(Transform):
    def __init__(self, power):
        self.power = _arr(power)

    def _forward(self, x):
        return jnp.power(x, self.power)

    def _inverse(self, y):
        return jnp.power(y, 1.0 / self.power)

    def _fldj(self, x):
        return jnp.log(jnp.abs(self.power * jnp.power(x, self.power - 1)))


class AbsTransform(Transform):
    def _forward(self, x):
        return jnp.abs(x)

    def _inverse(self, y):
        return y  # one branch of the preimage (reference convention)

    def _fldj(self, x):
        return jnp.zeros_like(x)


class ChainTransform(Transform):
    def __init__(self, transforms):
        self.transforms = list(transforms)

    def _forward(self, x):
        for t in self.transforms:
            x = t._forward(x)
        return x

    def _inverse(self, y):
        for t in reversed(self.transforms):
            y = t._inverse(y)
        return y

    def _fldj(self, x):
        total = jnp.zeros_like(x)
        for t in self.transforms:
            total = total + t._fldj(x)
            x = t._forward(x)
        return total


class TransformedDistribution:
    """base distribution pushed through a transform; log_prob via the
    change-of-variables formula."""

    def __init__(self, base, transforms):
        from . import Distribution
        assert isinstance(base, Distribution)
        self.base = base
        if isinstance(transforms, Transform):
            self.transform = transforms
        else:
            ts = list(transforms)
            self.transform = ts[0] if len(ts) == 1 else ChainTransform(ts)

    def sample(self, shape=()):
        x = self.base.sample(shape)
        return self.transform.forward(x)

    def rsample(self, shape=()):
        x = self.base.rsample(shape)
        return self.transform.forward(x)

    def log_prob(self, value):
        y = _arr(value)
        x = self.transform._inverse(y)
        base_lp = _arr(self.base.log_prob(Tensor(x)))
        fldj = self.transform._fldj(x)
        # sum the log-det over event dims so shapes match the base density
        # (a multivariate base reduces its event axes inside log_prob)
        while fldj.ndim > base_lp.ndim:
            fldj = fldj.sum(-1)
        return Tensor(base_lp - fldj)
