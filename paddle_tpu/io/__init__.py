"""paddle.io — Dataset / DataLoader / samplers.

Parity: python/paddle/io/dataloader/ (Dataset, IterableDataset, TensorDataset,
BatchSampler, DistributedBatchSampler, DataLoader with multiprocess workers).

TPU-first: the loader yields host numpy batches collated to device arrays;
multi-worker uses a thread pool (XLA releases the GIL during compute, and
host→device transfer overlaps via async dispatch) — there are no CUDA pinned
buffers to manage. DistributedBatchSampler shards per data-parallel rank
exactly as the reference (padding to even length, optional shuffle by epoch).
"""
from __future__ import annotations

import bisect
import itertools
import math
import queue
import threading
from typing import Iterable, Iterator, Optional

import numpy as np

from ..tensor.tensor import Tensor

__all__ = ["Dataset", "IterableDataset", "TensorDataset", "ComposeDataset",
           "ChainDataset", "ConcatDataset", "Subset", "random_split",
           "Sampler", "SequenceSampler", "RandomSampler", "WeightedRandomSampler",
           "BatchSampler", "DistributedBatchSampler", "DataLoader",
           "get_worker_info", "default_collate_fn",
           "SubsetRandomSampler", "default_convert_fn"]


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset has no __getitem__")

    def __len__(self):
        raise RuntimeError("IterableDataset has no __len__")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __getitem__(self, idx):
        out = []
        for ds in self.datasets:
            item = ds[idx]
            out.extend(item if isinstance(item, (tuple, list)) else [item])
        return tuple(out)

    def __len__(self):
        return min(len(d) for d in self.datasets)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for ds in self.datasets:
            yield from ds


class ConcatDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        self.cumsizes = list(itertools.accumulate(len(d) for d in self.datasets))

    def __len__(self):
        return self.cumsizes[-1]

    def __getitem__(self, idx):
        if idx < 0:
            idx += len(self)
        ds_idx = bisect.bisect_right(self.cumsizes, idx)
        start = 0 if ds_idx == 0 else self.cumsizes[ds_idx - 1]
        return self.datasets[ds_idx][idx - start]


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    if all(isinstance(l, float) for l in lengths):
        n = len(dataset)
        lengths = [int(math.floor(n * l)) for l in lengths]
        lengths[-1] = n - sum(lengths[:-1])
    total = sum(lengths)
    perm = np.random.permutation(total)
    out = []
    off = 0
    for l in lengths:
        out.append(Subset(dataset, perm[off:off + l].tolist()))
        off += l
    return out


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))

    def __len__(self):
        return len(self.data_source)


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        if self.replacement:
            yield from np.random.randint(0, n, self.num_samples).tolist()
        else:
            yield from np.random.permutation(n).tolist()[:self.num_samples]

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        self.weights = np.asarray(weights, dtype=np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        yield from np.random.choice(len(self.weights), self.num_samples,
                                    replace=self.replacement, p=p).tolist()

    def __len__(self):
        return self.num_samples


class SubsetRandomSampler(Sampler):
    """Sample WITHOUT replacement from a fixed index subset (reference:
    io/sampler.py :: SubsetRandomSampler)."""

    def __init__(self, indices, generator=None):
        self.indices = list(indices)
        self.generator = generator

    def __iter__(self):
        rng = self.generator if isinstance(
            self.generator, np.random.Generator) else np.random
        perm = rng.permutation(len(self.indices))
        yield from (self.indices[i] for i in perm)

    def __len__(self):
        return len(self.indices)


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False,
                 batch_size=1, drop_last=False):
        self.batch_size = batch_size
        self.drop_last = drop_last
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Shard indices across data-parallel ranks.

    Parity: python/paddle/io/dataloader/batch_sampler.py ::
    DistributedBatchSampler — pads the index list so every rank sees the same
    number of batches, reshuffles per epoch via set_epoch.
    """

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        if num_replicas is None or rank is None:
            from ..distributed import get_world_size, get_rank
            num_replicas = num_replicas if num_replicas is not None else get_world_size()
            rank = rank if rank is not None else get_rank()
        self.nranks = num_replicas
        self.local_rank = rank
        self.epoch = 0
        self.num_samples = int(math.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def __iter__(self):
        n = len(self.dataset)
        indices = np.arange(n)
        if self.shuffle:
            rng = np.random.RandomState(self.epoch)
            rng.shuffle(indices)
        indices = indices.tolist()
        indices += indices[: (self.total_size - n)]
        assert len(indices) == self.total_size
        indices = indices[self.local_rank:self.total_size:self.nranks]
        batch = []
        for idx in indices:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size

    def set_epoch(self, epoch):
        self.epoch = epoch


class WorkerInfo:
    def __init__(self, id, num_workers, dataset):
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset


_worker_info = threading.local()
_proc_worker_info = [None]        # set in forked worker processes


def get_worker_info():
    return getattr(_worker_info, "info", None) or _proc_worker_info[0]


def _proc_worker_main(dataset, task_q, res_q, wid, num_workers,
                      worker_init_fn):
    """Forked worker: fetch raw sample lists; collate stays in the parent
    (a fork must not touch the accelerator client)."""
    import traceback
    _proc_worker_info[0] = WorkerInfo(wid, num_workers, dataset)
    if worker_init_fn:
        worker_init_fn(wid)
    while True:
        item = task_q.get()
        if item is None:
            return
        i, idx_batch = item
        try:
            samples = [dataset[j] for j in idx_batch]
            res_q.put((i, True, samples))
        except BaseException:
            res_q.put((i, False, traceback.format_exc()))


def default_convert_fn(batch):
    """Identity-structure conversion: ndarrays/scalars -> Tensors without
    batching (reference: dataloader/collate.py :: default_convert_fn)."""
    from ..tensor.tensor import Tensor
    if isinstance(batch, (list, tuple)):
        out = [default_convert_fn(b) for b in batch]
        if isinstance(batch, tuple):
            return type(batch)(*out) if hasattr(batch, "_fields") \
                else tuple(out)          # namedtuple vs plain tuple
        return out
    if isinstance(batch, dict):
        return {k: default_convert_fn(v) for k, v in batch.items()}
    if isinstance(batch, Tensor):
        return batch
    if isinstance(batch, (np.ndarray, np.generic, int, float)):
        return Tensor(np.asarray(batch))
    return batch


def default_collate_fn(batch):
    sample = batch[0]
    if isinstance(sample, Tensor):
        return Tensor(np.stack([np.asarray(b._data) for b in batch]))
    if isinstance(sample, np.ndarray):
        return Tensor(np.stack(batch))
    if isinstance(sample, (int, float, np.integer, np.floating)):
        return Tensor(np.asarray(batch))
    if isinstance(sample, (list, tuple)):
        transposed = list(zip(*batch))
        return [default_collate_fn(list(s)) for s in transposed]
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    return batch


class DataLoader:
    """Parity: python/paddle/io/dataloader/dataloader_iter.py — multi-worker
    prefetching loader (threads, not processes: jnp conversion is the only
    per-batch device work and XLA dispatch is async)."""

    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=0, worker_init_fn=None,
                 persistent_workers=False):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = prefetch_factor
        self.worker_init_fn = worker_init_fn
        self.use_buffer_reader = use_buffer_reader
        self.timeout = timeout
        self._fork_ok = None
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if batch_sampler is not None:
            self.batch_sampler = batch_sampler
            self.batch_size = batch_sampler.batch_size
        elif not self._iterable_mode:
            self.batch_size = batch_size
            self.batch_sampler = BatchSampler(dataset, shuffle=shuffle,
                                              batch_size=batch_size,
                                              drop_last=drop_last)
        else:
            self.batch_size = batch_size
            self.batch_sampler = None
            self.drop_last = drop_last

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset has no len()")
        return len(self.batch_sampler)

    def _fetch(self, idx_batch):
        return self.collate_fn([self.dataset[i] for i in idx_batch])

    def __iter__(self):
        gen = self._raw_iter()
        if self.use_buffer_reader:
            gen = self._device_prefetch(gen)
        yield from gen

    def _raw_iter(self):
        if self._iterable_mode:
            yield from self._iter_iterable()
            return
        if self.num_workers <= 0:
            for idx_batch in self.batch_sampler:
                yield self._fetch(idx_batch)
            return
        import os
        if os.environ.get("PADDLE_TPU_LOADER_THREADS") == "1" or \
                not self._fork_safe():
            yield from self._iter_workers()
        else:
            yield from self._iter_process_workers()

    def _fork_safe(self):
        """Process workers only when a probe sample contains no device
        arrays: a forked child must never touch the XLA client (fork-unsafe),
        and device-tensor datasets (TensorDataset) are trivial indexing
        where threads lose nothing. Host-data datasets — the decode/augment
        workloads processes exist for — pass the probe."""
        if self._fork_ok is None:
            def host_only(x):
                if isinstance(x, Tensor):
                    return isinstance(x._data, np.ndarray)
                if isinstance(x, (list, tuple)):
                    return all(host_only(i) for i in x)
                if isinstance(x, dict):
                    return all(host_only(v) for v in x.values())
                return not type(x).__module__.startswith("jax")
            try:
                self._fork_ok = host_only(self.dataset[0])
            except Exception:
                self._fork_ok = False
        return self._fork_ok

    # ----------------------------------------------------- device prefetch
    def _device_prefetch(self, gen):
        """Pin-memory-thread equivalent (reference: _DataLoaderIterMulti*'s
        pin-memory/buffer reader): a thread stays prefetch_factor batches
        ahead, converting to device arrays so host→device transfer overlaps
        the consumer's step. XLA's async dispatch makes device_put cheap to
        issue; the queue depth provides the double-buffering."""
        import jax

        def to_device(item):
            if isinstance(item, Tensor):
                if isinstance(item._data, np.ndarray):
                    return Tensor(jax.device_put(item._data))
                return item
            if isinstance(item, np.ndarray):
                return Tensor(jax.device_put(item))
            if isinstance(item, (list, tuple)):
                return type(item)(to_device(i) for i in item)
            if isinstance(item, dict):
                return {k: to_device(v) for k, v in item.items()}
            return item

        end = object()
        err_box = []
        q: "queue.Queue" = queue.Queue(maxsize=max(self.prefetch_factor, 1))
        stop = threading.Event()

        def feeder():
            try:
                for item in gen:
                    item = to_device(item)
                    while not stop.is_set():
                        try:
                            q.put(item, timeout=0.5)
                            break
                        except queue.Full:
                            continue
                    if stop.is_set():
                        return
            except BaseException as e:
                err_box.append(e)
            finally:
                while not stop.is_set():
                    try:
                        q.put(end, timeout=0.5)
                        break
                    except queue.Full:
                        continue

        t = threading.Thread(target=feeder, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is end:
                    if err_box:
                        raise err_box[0]
                    return
                yield item
        finally:
            stop.set()

    def _iter_iterable(self):
        batch = []
        for sample in self.dataset:
            batch.append(sample)
            if len(batch) == self.batch_size:
                yield self.collate_fn(batch)
                batch = []
        if batch and not getattr(self, "drop_last", False):
            yield self.collate_fn(batch)

    # --------------------------------------------------- process workers
    def _iter_process_workers(self):
        """Process-based workers (the reference's default multiprocess
        loader): dataset __getitem__ — decode/augment, the Python-heavy
        part — runs in forked children free of the parent's GIL; samples
        travel back pickled and the PARENT applies collate_fn (user collate
        may build device tensors, which must not happen in a fork that
        would re-initialize the accelerator client). Thread mode (the r1
        behavior) remains via PADDLE_TPU_LOADER_THREADS=1."""
        import multiprocessing as mp

        ctx = mp.get_context("fork")
        batches = list(self.batch_sampler)
        n_total = len(batches)
        task_q = ctx.Queue()
        res_q = ctx.Queue(maxsize=max(
            self.num_workers * self.prefetch_factor, 2))
        for item in enumerate(batches):
            task_q.put(item)
        for _ in range(self.num_workers):
            task_q.put(None)

        procs = [
            ctx.Process(target=_proc_worker_main,
                        args=(self.dataset, task_q, res_q, wid,
                              self.num_workers, self.worker_init_fn),
                        daemon=True)
            for wid in range(self.num_workers)
        ]
        for p in procs:
            p.start()

        pending: dict[int, object] = {}
        timeout = self.timeout or 5.0
        try:
            for want in range(n_total):
                while want not in pending:
                    try:
                        i, ok, payload = res_q.get(timeout=timeout)
                    except queue.Empty:
                        if not any(p.is_alive() for p in procs):
                            raise RuntimeError(
                                f"DataLoader worker processes died before "
                                f"batch {want}")
                        continue
                    if not ok:
                        raise RuntimeError(
                            f"DataLoader worker failed:\n{payload}")
                    pending[i] = payload
                yield self.collate_fn(pending.pop(want))
        finally:
            for p in procs:
                if p.is_alive():
                    p.terminate()
            for p in procs:
                p.join(timeout=2.0)
            for q_ in (task_q, res_q):
                q_.cancel_join_thread()
                q_.close()

    def _iter_workers(self):
        """Multi-worker prefetch. Workers share one scaffolding; the
        ready-batch handoff prefers the native bounded queue
        (csrc/runtime.cc — blocks in C with the GIL released, bounded
        capacity = prefetch back-pressure, the reference's buffered-reader
        behavior) and falls back to a Python condition variable. Worker
        exceptions propagate to the consumer; waiting never times out while
        any worker is alive."""
        try:
            from ..core.native import NativeQueue
            nq = NativeQueue(max(self.num_workers * self.prefetch_factor, 2))
        except Exception:
            nq = None

        idx_queue: "queue.Queue" = queue.Queue()
        out: dict[int, object] = {}
        out_cv = threading.Condition(threading.Lock())
        batches = list(self.batch_sampler)
        for i, b in enumerate(batches):
            idx_queue.put((i, b))
        n_total = len(batches)
        stop = threading.Event()

        class _WorkerError:
            def __init__(self, exc):
                self.exc = exc

        def publish(i, data):
            with out_cv:
                out[i] = data
                out_cv.notify_all()
            if nq is not None:
                while not stop.is_set():
                    if nq.put(i + 1, timeout_s=1.0):   # tokens are 1-based
                        break

        def worker(wid):
            _worker_info.info = WorkerInfo(wid, self.num_workers, self.dataset)
            if self.worker_init_fn:
                self.worker_init_fn(wid)
            while not stop.is_set():
                try:
                    i, b = idx_queue.get_nowait()
                except queue.Empty:
                    return
                try:
                    data = self._fetch(b)
                except BaseException as e:    # propagate to consumer
                    publish(i, _WorkerError(e))
                    return
                publish(i, data)

        threads = [threading.Thread(target=worker, args=(w,), daemon=True)
                   for w in range(self.num_workers)]
        for t in threads:
            t.start()

        def take(i):
            if nq is not None:
                while i not in take.ready:
                    tok = nq.get(timeout_s=1.0)
                    if tok is not None:
                        take.ready.add(tok - 1)
                    elif not any(t.is_alive() for t in threads) \
                            and i not in out:
                        raise RuntimeError(
                            f"DataLoader workers died before batch {i}")
                take.ready.discard(i)
                with out_cv:
                    return out.pop(i)
            with out_cv:
                while i not in out:
                    if not out_cv.wait(timeout=1.0) and \
                            not any(t.is_alive() for t in threads) \
                            and i not in out:
                        raise RuntimeError(
                            f"DataLoader workers died before batch {i}")
                return out.pop(i)
        take.ready = set()

        try:
            for i in range(n_total):
                data = take(i)
                if isinstance(data, _WorkerError):
                    raise data.exc
                yield data
        finally:
            stop.set()
            if nq is not None:
                nq.close()
                for t in threads:
                    t.join(timeout=5.0)
                if not any(t.is_alive() for t in threads):
                    nq.free()
                # else: a worker is still stuck inside user dataset code and
                # could call nq.put after free — leak the handle instead of
                # freeing under its feet (use-after-free)
