"""paddle.sparse.nn — layers over sparse tensors.

Parity surface: python/paddle/sparse/nn/ (ReLU, ReLU6, LeakyReLU, Softmax,
Conv3D, SubmConv3D, BatchNorm, MaxPool3D; CUDA kernels under
paddle/phi/kernels/sparse/gpu/conv_kernel.cu build a gather-scatter
"rulebook" then GEMM per kernel offset).

TPU-first realization of sparse conv: the rulebook (which input nnz feeds
which output nnz per kernel offset) is STRUCTURE, not data — build it on
host in numpy at call time, then run the per-offset gather → [pairs, Cin] ×
[Cin, Cout] MXU matmul → segment_sum scatter on device. Static pair counts
per offset keep XLA shapes fixed."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..nn.layer.layers import Layer
from ..tensor.tensor import Tensor, apply_op

__all__ = ["ReLU", "ReLU6", "LeakyReLU", "Softmax", "BatchNorm",
           "Conv3D", "SubmConv3D", "MaxPool3D"]


class ReLU(Layer):
    def forward(self, x):
        from . import relu
        return relu(x)


class ReLU6(Layer):
    def forward(self, x):
        from . import _unary_factory
        return _unary_factory("relu6", lambda v: jnp.clip(v, 0, 6))(x)


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01):
        super().__init__()
        self.negative_slope = float(negative_slope)

    def forward(self, x):
        from . import _unary_factory
        s = self.negative_slope
        return _unary_factory(
            "leaky_relu", lambda v: jnp.where(v >= 0, v, s * v))(x)


class Softmax(Layer):
    """Row-wise softmax over a 2-D CSR's stored values (reference:
    sparse softmax ignores implicit zeros — normalization runs over the
    stored entries of each row only)."""

    def __init__(self, axis=-1):
        super().__init__()
        assert axis == -1, "sparse softmax supports the last axis"

    def forward(self, x):
        from . import SparseCsrTensor
        assert isinstance(x, SparseCsrTensor), "Softmax expects CSR"
        rows = x._row_indices()
        n = x.shape[0]

        def rowsoft(v):
            mx = jax.ops.segment_max(v, rows, num_segments=n)
            e = jnp.exp(v - mx[rows])
            z = jax.ops.segment_sum(e, rows, num_segments=n)
            return e / z[rows]
        vals = apply_op(rowsoft, x.values)
        return SparseCsrTensor(x.crows, x.cols, vals, x.shape)


class BatchNorm(Layer):
    """BatchNorm over the dense channel dim of COO values [nnz, C]
    (reference: sparse BN normalizes over stored points per channel)."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5):
        super().__init__()
        from ..nn.initializer import Constant
        self.eps = float(epsilon)
        self.momentum = float(momentum)
        self.weight = self.create_parameter(
            [num_features], default_initializer=Constant(1.0))
        self.bias = self.create_parameter(
            [num_features], default_initializer=Constant(0.0))
        self._mean = jnp.zeros(num_features)
        self._var = jnp.ones(num_features)

    def forward(self, x):
        from . import SparseCooTensor
        assert isinstance(x, SparseCooTensor)
        eps = self.eps
        if self.training:
            def bn(v, w, b):
                m = v.mean(axis=0)
                var = v.var(axis=0)
                return (v - m) * jax.lax.rsqrt(var + eps) * w + b
            vals = apply_op(bn, x.values, self.weight, self.bias)
            vnp = np.asarray(x.values._data)
            self._mean = (self.momentum * self._mean
                          + (1 - self.momentum) * vnp.mean(axis=0))
            self._var = (self.momentum * self._var
                         + (1 - self.momentum) * vnp.var(axis=0))
        else:
            m, var = self._mean, self._var

            def bn(v, w, b):
                return (v - m) * jax.lax.rsqrt(var + eps) * w + b
            vals = apply_op(bn, x.values, self.weight, self.bias)
        return SparseCooTensor(x.indices, vals, x.shape,
                               coalesced=x._coalesced)


_RULEBOOK_CACHE: dict = {}   # key -> (rulebook, nbytes)
_RULEBOOK_CACHE_MAX = 16
# total-byte budget: training on fresh coords every step must not pin
# hundreds of MB of never-hit rulebooks; oversized entries skip the cache
_RULEBOOK_CACHE_MAX_BYTES = 32 << 20
_RULEBOOK_ENTRY_MAX_BYTES = 4 << 20
_rulebook_cache_bytes = [0]


def clear_rulebook_cache() -> None:
    """Reset the cache AND its byte counter together (clearing the dict
    alone would leave phantom bytes that starve future inserts)."""
    _RULEBOOK_CACHE.clear()
    _rulebook_cache_bytes[0] = 0


def _rulebook_nbytes(key, out):
    n = len(key[0])
    _, rules, _ = out
    for ins, outs in rules.values():
        n += ins.nbytes + outs.nbytes
    return n + out[0].nbytes


def _build_rulebook_cached(coords: np.ndarray, spatial, ksize, stride,
                           padding, subm: bool):
    """Memoized rulebook build: point-cloud pipelines reuse the same active
    site set across layers (every SubmConv3D on one input shares the
    structure), so key on the coordinate bytes + geometry and skip the
    O(nnz·k³) host walk on repeats. FIFO-bounded by entry count AND total
    bytes; entries too large to plausibly repay caching are not kept."""
    key = (coords.tobytes(), tuple(spatial), tuple(ksize), tuple(stride),
           tuple(padding), subm)
    hit = _RULEBOOK_CACHE.get(key)
    if hit is not None:
        return hit[0]
    out = _build_rulebook(coords, spatial, ksize, stride, padding, subm)
    size = _rulebook_nbytes(key, out)
    if size > _RULEBOOK_ENTRY_MAX_BYTES:
        return out
    while _RULEBOOK_CACHE and (
            len(_RULEBOOK_CACHE) >= _RULEBOOK_CACHE_MAX
            or _rulebook_cache_bytes[0] + size > _RULEBOOK_CACHE_MAX_BYTES):
        old_key = next(iter(_RULEBOOK_CACHE))  # FIFO (dict is ordered)
        _, old_size = _RULEBOOK_CACHE.pop(old_key)
        _rulebook_cache_bytes[0] -= old_size
    _RULEBOOK_CACHE[key] = (out, size)
    _rulebook_cache_bytes[0] += size
    return out


def _build_rulebook(coords: np.ndarray, spatial, ksize, stride, padding,
                    subm: bool):
    """Host-side rulebook: for each kernel offset, (input_slot, output_slot)
    pairs. coords: [nnz, 4] (batch, z, y, x). Returns (out_coords [m,4],
    rules {offset_idx: (in_idx array, out_idx array)})."""
    ks = np.array(ksize)
    st = np.array(stride)
    pad = np.array(padding)
    in_map = {tuple(c): i for i, c in enumerate(coords.tolist())}

    if subm:
        out_coords = coords
        out_map = in_map
    else:
        out_set = {}
        out_sp = tuple((np.array(spatial) + 2 * pad - ks) // st + 1)
        for c in coords:
            b, z, y, x = c
            for dz in range(ks[0]):
                for dy in range(ks[1]):
                    for dx in range(ks[2]):
                        oz, rz = divmod(z + pad[0] - dz, st[0])
                        oy, ry = divmod(y + pad[1] - dy, st[1])
                        ox, rx = divmod(x + pad[2] - dx, st[2])
                        if rz or ry or rx:
                            continue
                        if (0 <= oz < out_sp[0] and 0 <= oy < out_sp[1]
                                and 0 <= ox < out_sp[2]):
                            out_set.setdefault((b, oz, oy, ox),
                                               len(out_set))
        out_coords = np.array(sorted(out_set, key=out_set.get), np.int32)
        if len(out_coords) == 0:
            out_coords = out_coords.reshape(0, 4)
        out_map = {tuple(c): i for i, c in enumerate(out_coords.tolist())}
        spatial = out_sp

    rules = {}
    k_idx = 0
    for dz in range(ks[0]):
        for dy in range(ks[1]):
            for dx in range(ks[2]):
                ins, outs = [], []
                for oc, oi in out_map.items():
                    b, oz, oy, ox = oc
                    iz = oz * st[0] - pad[0] + dz if not subm else oz + dz - ks[0] // 2
                    iy = oy * st[1] - pad[1] + dy if not subm else oy + dy - ks[1] // 2
                    ix = ox * st[2] - pad[2] + dx if not subm else ox + dx - ks[2] // 2
                    ii = in_map.get((b, iz, iy, ix))
                    if ii is not None:
                        ins.append(ii)
                        outs.append(oi)
                if ins:
                    rules[k_idx] = (np.array(ins, np.int32),
                                    np.array(outs, np.int32))
                k_idx += 1
    return out_coords, rules, tuple(int(s) for s in spatial)


class _SparseConvBase(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, subm=False):
        super().__init__()
        def _3(v):
            return (v,) * 3 if isinstance(v, int) else tuple(v)
        self.ksize = _3(kernel_size)
        self.stride = _3(stride)
        self.padding = _3(padding)
        self.subm = subm
        from ..nn.initializer import Constant, Uniform
        kvol = int(np.prod(self.ksize))
        scale = 1.0 / np.sqrt(in_channels * kvol)
        self.weight = self.create_parameter(
            [kvol, in_channels, out_channels],
            default_initializer=Uniform(-scale, scale))
        self.bias = self.create_parameter(
            [out_channels], default_initializer=Constant(0.0))

    def forward(self, x):
        from . import SparseCooTensor
        assert isinstance(x, SparseCooTensor)
        assert x.indices.shape[0] == 4, \
            "sparse conv expects NDHWC coords [batch,z,y,x] + channel values"
        coords = np.asarray(x.indices).T  # [nnz, 4]
        spatial = x.shape[1:4]
        out_coords, rules, out_spatial = _build_rulebook_cached(
            coords, spatial, self.ksize, self.stride, self.padding,
            self.subm)
        m = len(out_coords)
        cout = self.weight.shape[-1]
        rule_items = sorted(rules.items())

        def conv(v, w, b):
            out = jnp.zeros((m, cout), v.dtype)
            for k, (ins, outs) in rule_items:
                gathered = jnp.take(v, jnp.asarray(ins), axis=0)
                contrib = gathered @ w[k]
                out = out + jax.ops.segment_sum(
                    contrib, jnp.asarray(outs), num_segments=m)
            return out + b
        vals = apply_op(conv, x.values, self.weight, self.bias)
        new_shape = (x.shape[0], *out_spatial, cout)
        return SparseCooTensor(out_coords.T, vals, new_shape,
                               coalesced=True)


class Conv3D(_SparseConvBase):
    """Sparse 3-D convolution over COO NDHWC point clouds."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, **kw):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, subm=False)


class SubmConv3D(_SparseConvBase):
    """Submanifold sparse conv: output support == input support (stride 1),
    preventing dilation of the active site set."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, **kw):
        assert (stride == 1 or tuple(np.atleast_1d(stride)) == (1, 1, 1)), \
            "SubmConv3D requires stride 1"
        super().__init__(in_channels, out_channels, kernel_size, 1,
                         padding, subm=True)


class MaxPool3D(Layer):
    """Sparse max pool over COO NDHWC: rulebook + segment_max."""

    def __init__(self, kernel_size, stride=None, padding=0):
        super().__init__()
        def _3(v):
            return (v,) * 3 if isinstance(v, int) else tuple(v)
        self.ksize = _3(kernel_size)
        self.stride = _3(stride if stride is not None else kernel_size)
        self.padding = _3(padding)

    def forward(self, x):
        from . import SparseCooTensor
        coords = np.asarray(x.indices).T
        out_coords, rules, out_spatial = _build_rulebook_cached(
            coords, x.shape[1:4], self.ksize, self.stride, self.padding,
            subm=False)
        m = len(out_coords)
        rule_items = sorted(rules.items())

        def pool(v):
            out = jnp.full((m, v.shape[-1]), -jnp.inf, v.dtype)
            for k, (ins, outs) in rule_items:
                g = jnp.take(v, jnp.asarray(ins), axis=0)
                out = jnp.maximum(out, jax.ops.segment_max(
                    g, jnp.asarray(outs), num_segments=m))
            return out
        vals = apply_op(pool, x.values)
        new_shape = (x.shape[0], *out_spatial, x.shape[-1])
        return SparseCooTensor(out_coords.T, vals, new_shape,
                               coalesced=True)
