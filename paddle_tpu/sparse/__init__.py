"""paddle.sparse — COO/CSR sparse tensors, TPU-first.

Parity surface: python/paddle/sparse/ (creation.py :: sparse_coo_tensor,
sparse_csr_tensor; unary.py; binary.py; multiary.py :: addmm;
matmul/masked_matmul in python/paddle/sparse/nn + paddle/phi/kernels/sparse/).

TPU-first design: there is no cuSPARSE analogue on TPU, and XLA has no sparse
HLOs — the hardware-native realization of sparse compute is gather/scatter +
segment reductions over STATIC-nnz index/value arrays, which XLA tiles onto
the VPU/MXU. So SparseCooTensor/SparseCsrTensor are lightweight containers
(static `nnz` per instance, indices as int32 arrays) whose ops lower to
jnp.take / scatter-add / jax.ops.segment_sum; `values` is a framework Tensor
so every sparse op participates in the autograd tape (grads flow to values
and to dense operands; indices are structure, not data)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..tensor.tensor import Tensor, apply_op

from . import nn  # noqa: E402  (submodule import at end of file in paddle)

__all__ = [
    "SparseCooTensor", "SparseCsrTensor", "sparse_coo_tensor",
    "sparse_csr_tensor", "is_same_shape", "coalesce", "transpose",
    "reshape", "sum", "add", "subtract", "multiply", "divide", "matmul",
    "masked_matmul", "addmm", "nn",
    # unary (values-wise, sparsity-preserving)
    "abs", "sin", "tan", "asin", "atan", "sinh", "tanh", "asinh", "atanh",
    "sqrt", "square", "log1p", "expm1", "neg", "pow", "cast", "rad2deg",
    "deg2rad", "relu",
]


def _as_array(x, dtype=None):
    if isinstance(x, Tensor):
        return x._data if dtype is None else x._data.astype(dtype)
    return jnp.asarray(x, dtype)


class SparseCooTensor:
    """COO: `indices` [sparse_ndim, nnz] int32, `values` [nnz, *dense_dims].

    Static nnz — the TPU contract: one compiled program per (shape, nnz)
    bucket, no data-dependent shapes inside jit."""

    def __init__(self, indices, values: Tensor, shape, *, coalesced=False):
        self.indices = _as_array(indices, jnp.int32)
        self.values = values if isinstance(values, Tensor) else Tensor(
            _as_array(values))
        self.shape = tuple(int(s) for s in shape)
        self._coalesced = bool(coalesced)
        assert self.indices.ndim == 2, "indices must be [sparse_ndim, nnz]"
        assert self.indices.shape[1] == self.values.shape[0]

    # --- paddle Tensor-protocol subset -----------------------------------
    @property
    def dtype(self):
        return self.values.dtype

    @property
    def nnz(self) -> int:
        return int(self.indices.shape[1])

    def is_sparse(self):
        return True

    def is_sparse_coo(self):
        return True

    def is_sparse_csr(self):
        return False

    def indices_tensor(self) -> Tensor:
        return Tensor(self.indices)

    def values_tensor(self) -> Tensor:
        return self.values

    def to_dense(self) -> Tensor:
        idx = self.indices
        shape = self.shape
        if idx.shape[0] == 0:
            # 0 sparse dims (e.g. sparse.sum full reduction): nnz==1 and
            # the dense dims ARE the whole shape — values[0] is the tensor
            return apply_op(lambda v: v.reshape(shape), self.values)

        def densify(v):
            return jnp.zeros(shape, v.dtype).at[tuple(idx)].add(v)
        return apply_op(densify, self.values)

    def to_sparse_csr(self) -> "SparseCsrTensor":
        assert len(self.shape) == 2, "CSR conversion supports 2-D tensors"
        coo = coalesce(self)
        rows, cols = np.asarray(coo.indices[0]), coo.indices[1]
        crows = np.zeros(self.shape[0] + 1, np.int32)
        np.add.at(crows, rows + 1, 1)
        crows = np.cumsum(crows).astype(np.int32)
        return SparseCsrTensor(crows, cols, coo.values, self.shape)

    def __repr__(self):
        return (f"SparseCooTensor(shape={self.shape}, nnz={self.nnz}, "
                f"dtype={self.dtype})")


class SparseCsrTensor:
    """CSR: `crows` [rows+1], `cols` [nnz], `values` [nnz]; 2-D (or batched
    3-D with shared structure per batch in the reference — 2-D here)."""

    def __init__(self, crows, cols, values: Tensor, shape):
        self.crows = _as_array(crows, jnp.int32)
        self.cols = _as_array(cols, jnp.int32)
        self.values = values if isinstance(values, Tensor) else Tensor(
            _as_array(values))
        self.shape = tuple(int(s) for s in shape)
        assert len(self.shape) == 2, "SparseCsrTensor is 2-D"

    @property
    def dtype(self):
        return self.values.dtype

    @property
    def nnz(self) -> int:
        return int(self.cols.shape[0])

    def is_sparse(self):
        return True

    def is_sparse_coo(self):
        return False

    def is_sparse_csr(self):
        return True

    def crows_tensor(self) -> Tensor:
        return Tensor(self.crows)

    def cols_tensor(self) -> Tensor:
        return Tensor(self.cols)

    def values_tensor(self) -> Tensor:
        return self.values

    def _row_indices(self) -> jnp.ndarray:
        counts = jnp.diff(self.crows)
        return jnp.repeat(jnp.arange(self.shape[0], dtype=jnp.int32), counts,
                          total_repeat_length=self.nnz)

    def to_sparse_coo(self) -> SparseCooTensor:
        idx = jnp.stack([self._row_indices(), self.cols])
        return SparseCooTensor(idx, self.values, self.shape, coalesced=True)

    def to_dense(self) -> Tensor:
        rows = self._row_indices()
        cols, shape = self.cols, self.shape

        def densify(v):
            return jnp.zeros(shape, v.dtype).at[rows, cols].add(v)
        return apply_op(densify, self.values)

    def __repr__(self):
        return (f"SparseCsrTensor(shape={self.shape}, nnz={self.nnz}, "
                f"dtype={self.dtype})")


# ---------------------------------------------------------------------------
# creation
# ---------------------------------------------------------------------------

def sparse_coo_tensor(indices, values, shape=None, dtype=None,
                      stop_gradient=True):
    """Build a COO tensor from [sparse_ndim, nnz] indices + nnz values."""
    idx = _as_array(indices, jnp.int32)
    vals = values if isinstance(values, Tensor) else Tensor(
        _as_array(values, dtype))
    if shape is None:
        shape = tuple(int(m) + 1 for m in np.asarray(idx.max(axis=1)))
    vals.stop_gradient = stop_gradient
    return SparseCooTensor(idx, vals, shape)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None,
                      stop_gradient=True):
    """Build a CSR tensor from compressed row pointers + cols + values."""
    vals = values if isinstance(values, Tensor) else Tensor(
        _as_array(values, dtype))
    vals.stop_gradient = stop_gradient
    return SparseCsrTensor(crows, cols, vals, shape)


def _dense_to_coo(x: Tensor, sparse_dim=None) -> SparseCooTensor:
    arr = np.asarray(x._data)
    nd = arr.ndim if sparse_dim is None else sparse_dim
    mask = np.asarray(np.abs(arr) != 0)
    while mask.ndim > nd:
        mask = mask.any(axis=-1)
    idx = np.stack(np.nonzero(mask)).astype(np.int32)
    gather = tuple(idx)

    def take(a):
        return a[gather]
    vals = apply_op(take, x)
    return SparseCooTensor(idx, vals, arr.shape, coalesced=True)


def _attach_tensor_methods():
    """paddle parity: dense Tensor gains to_sparse_coo/to_sparse_csr."""
    def to_sparse_coo(self, sparse_dim=None):
        return _dense_to_coo(self, sparse_dim)

    def to_sparse_csr(self):
        return _dense_to_coo(self).to_sparse_csr()

    Tensor.to_sparse_coo = to_sparse_coo
    Tensor.to_sparse_csr = to_sparse_csr
    Tensor.is_sparse = lambda self: False
    Tensor.is_sparse_coo = lambda self: False
    Tensor.is_sparse_csr = lambda self: False


_attach_tensor_methods()


def is_same_shape(x, y) -> bool:
    return tuple(x.shape) == tuple(y.shape)


def coalesce(x: SparseCooTensor) -> SparseCooTensor:
    """Sort indices lexicographically and sum duplicates. The output nnz is
    the number of UNIQUE cells (host-computed structure, like every index
    set here): each distinct support produces its own compiled program.
    Callers that need one-program steady state should keep supports fixed
    across steps — the framework's static-nnz contract is per-instance, and
    coalesce creates a new instance."""
    if x._coalesced:
        return x
    idx = np.asarray(x.indices)
    if idx.shape[0] == 0:
        # 0 sparse dims: every entry is a duplicate of the single empty
        # cell — sum all values into one slot
        vals = apply_op(lambda v: jnp.sum(v, axis=0, keepdims=True),
                        x.values)
        return SparseCooTensor(np.zeros((0, 1), np.int32), vals, x.shape,
                               coalesced=True)
    flat = np.ravel_multi_index(idx, x.shape[:idx.shape[0]])
    order = np.argsort(flat, kind="stable")
    sorted_flat = flat[order]
    uniq, first = np.unique(sorted_flat, return_index=True)
    seg = np.zeros(len(flat), np.int32)
    seg[first] = 1
    seg = np.cumsum(seg) - 1  # position → output slot
    n_out = len(uniq)
    new_idx = np.stack(np.unravel_index(uniq, x.shape[:idx.shape[0]]))
    order_j = jnp.asarray(order)
    seg_j = jnp.asarray(seg)

    def merge(v):
        return jax.ops.segment_sum(v[order_j], seg_j, num_segments=n_out)
    vals = apply_op(merge, x.values)
    return SparseCooTensor(new_idx.astype(np.int32), vals, x.shape,
                           coalesced=True)


# ---------------------------------------------------------------------------
# unary — sparsity-preserving values maps
# ---------------------------------------------------------------------------

def _unary_factory(name, jfn):
    def op(x, name_=None):
        if isinstance(x, SparseCooTensor):
            return SparseCooTensor(x.indices, apply_op(jfn, x.values),
                                   x.shape, coalesced=x._coalesced)
        if isinstance(x, SparseCsrTensor):
            return SparseCsrTensor(x.crows, x.cols, apply_op(jfn, x.values),
                                   x.shape)
        return apply_op(jfn, x)
    op.__name__ = name
    return op


abs = _unary_factory("abs", jnp.abs)  # noqa: A001
sin = _unary_factory("sin", jnp.sin)
tan = _unary_factory("tan", jnp.tan)
asin = _unary_factory("asin", jnp.arcsin)
atan = _unary_factory("atan", jnp.arctan)
sinh = _unary_factory("sinh", jnp.sinh)
tanh = _unary_factory("tanh", jnp.tanh)
asinh = _unary_factory("asinh", jnp.arcsinh)
atanh = _unary_factory("atanh", jnp.arctanh)
sqrt = _unary_factory("sqrt", jnp.sqrt)
square = _unary_factory("square", jnp.square)
log1p = _unary_factory("log1p", jnp.log1p)
expm1 = _unary_factory("expm1", jnp.expm1)
neg = _unary_factory("neg", jnp.negative)
relu = _unary_factory("relu", lambda v: jnp.maximum(v, 0))
rad2deg = _unary_factory("rad2deg", jnp.rad2deg)
deg2rad = _unary_factory("deg2rad", jnp.deg2rad)


def pow(x, factor):  # noqa: A001
    return _unary_factory("pow", lambda v: jnp.power(v, factor))(x)


def cast(x, index_dtype=None, value_dtype=None):
    from ..core.dtype import convert_dtype
    vd = convert_dtype(value_dtype)
    if isinstance(x, SparseCooTensor):
        idx = x.indices if index_dtype is None else x.indices.astype(
            convert_dtype(index_dtype))
        vals = x.values if vd is None else apply_op(
            lambda v: v.astype(vd), x.values)
        return SparseCooTensor(idx, vals, x.shape, coalesced=x._coalesced)
    crows = x.crows if index_dtype is None else x.crows.astype(
        convert_dtype(index_dtype))
    cols = x.cols if index_dtype is None else x.cols.astype(
        convert_dtype(index_dtype))
    vals = x.values if vd is None else apply_op(lambda v: v.astype(vd),
                                               x.values)
    return SparseCsrTensor(crows, cols, vals, x.shape)


# ---------------------------------------------------------------------------
# structure ops
# ---------------------------------------------------------------------------

def transpose(x: SparseCooTensor, perm):
    """Permute dims: sparse dims permute the index rows; dense (trailing)
    dims permute the values array axes."""
    assert isinstance(x, SparseCooTensor), "transpose: COO only"
    perm = list(perm)
    sd = x.indices.shape[0]
    assert sorted(perm) == list(range(len(x.shape))), "invalid perm"
    assert all(p < sd for p in perm[:sd]) and all(
        p >= sd for p in perm[sd:]), \
        "perm must not mix sparse and dense dims"
    new_idx = x.indices[jnp.asarray(perm[:sd])]
    new_shape = tuple(x.shape[p] for p in perm)
    vals = x.values
    if perm[sd:] != list(range(sd, len(x.shape))):
        vaxes = (0,) + tuple(1 + (p - sd) for p in perm[sd:])
        vals = apply_op(lambda v: jnp.transpose(v, vaxes), vals)
    return SparseCooTensor(new_idx, vals, new_shape)


def reshape(x: SparseCooTensor, shape):
    """Reshape the SPARSE dims; dense trailing dims must be unchanged (the
    reference's sparse reshape keeps the dense suffix too)."""
    assert isinstance(x, SparseCooTensor), "reshape: COO only"
    sd = x.indices.shape[0]
    dense_tail = x.shape[sd:]
    shape = tuple(int(s) for s in shape)
    if -1 in shape:
        known = int(np.prod([s for s in shape if s != -1]))
        total = int(np.prod(x.shape))
        shape = tuple(total // known if s == -1 else s for s in shape)
    assert shape[len(shape) - len(dense_tail):] == tuple(dense_tail), \
        f"reshape must preserve dense dims {dense_tail}"
    new_sparse = shape[:len(shape) - len(dense_tail)]
    flat = jnp.ravel_multi_index(tuple(x.indices), x.shape[:sd],
                                 mode="clip")
    new_idx = jnp.stack(jnp.unravel_index(flat, new_sparse)).astype(
        jnp.int32)
    return SparseCooTensor(new_idx, x.values, shape)


def sum(x, axis=None, dtype=None, keepdim=False):  # noqa: A001
    """Sum over axes; returns a SPARSE tensor like the reference
    (python/paddle/sparse/unary.py :: sum). Support is PRESERVED, never
    re-derived from values: a row whose entries cancel to exactly 0 stays
    a stored zero (segment-sum over the existing indices, no densify)."""
    was_csr = isinstance(x, SparseCsrTensor)
    if was_csr:
        x = x.to_sparse_coo()
    coo = coalesce(x)
    sd = coo.indices.shape[0]
    nd = len(coo.shape)

    def _cast(t):
        # cast BEFORE any segment-sum so accumulation runs in the target
        # dtype (sum(int32, dtype='int64') must not wrap in int32)
        return apply_op(lambda v: v.astype(dtype), t) if dtype else t

    if axis is None:
        n_dense = nd - sd
        if keepdim:
            vals = apply_op(
                lambda v: jnp.sum(v, dtype=dtype).reshape(
                    (1,) * (n_dense + 1)), coo.values)
            out = SparseCooTensor(jnp.zeros((sd, 1), jnp.int32),
                                  vals, (1,) * nd, coalesced=True)
        else:
            vals = apply_op(lambda v: jnp.sum(v, dtype=dtype).reshape(1),
                            coo.values)
            out = SparseCooTensor(jnp.zeros((0, 1), jnp.int32),
                                  vals, (), coalesced=True)
    else:
        if isinstance(axis, (list, tuple)):
            assert len(axis) == 1, "sparse.sum: one axis at a time"
            axis = axis[0]
        ax = axis % nd
        if ax < sd:
            # sparse axis: project it out of the index set; coalesce sums
            # the now-duplicate cells (keeping cancelled-to-zero support)
            if keepdim:
                idx = coo.indices.at[ax].set(0)
                shape = tuple(1 if i == ax else s
                              for i, s in enumerate(coo.shape))
                out = coalesce(SparseCooTensor(idx, _cast(coo.values),
                                               shape))
            else:
                idx = jnp.delete(coo.indices, ax, axis=0)
                shape = coo.shape[:ax] + coo.shape[ax + 1:]
                out = coalesce(SparseCooTensor(idx, _cast(coo.values),
                                               shape))
        else:
            # dense axis: reduce inside the values block; support unchanged
            vax = ax - sd + 1
            vals = apply_op(
                lambda v: jnp.sum(v, axis=vax, keepdims=keepdim,
                                  dtype=dtype), coo.values)
            shape = tuple(1 if i == ax else s
                          for i, s in enumerate(coo.shape)) if keepdim \
                else coo.shape[:ax] + coo.shape[ax + 1:]
            out = SparseCooTensor(coo.indices, _cast(vals), shape,
                                  coalesced=True)
    return out.to_sparse_csr() if was_csr and len(out.shape) == 2 else out


# ---------------------------------------------------------------------------
# binary
# ---------------------------------------------------------------------------

def _coo_elementwise(a: SparseCooTensor, b: SparseCooTensor, jfn, name):
    assert a.shape == b.shape, f"{name}: shape mismatch {a.shape}/{b.shape}"
    # union of supports: concatenate then coalesce; the combining op is
    # addition-like on the union (paddle semantics for add/subtract; mul/div
    # only defined where supports overlap — realized densely for exactness)
    idx = jnp.concatenate([a.indices, b.indices], axis=1)
    merged = SparseCooTensor(
        idx, apply_op(lambda va, vb: jnp.concatenate([jfn(va, jnp.zeros_like(
            va)), jfn(jnp.zeros_like(vb), vb)]), a.values, b.values),
        a.shape)
    return coalesce(merged)


def add(x, y, name=None):
    if isinstance(x, SparseCsrTensor):
        if isinstance(y, (SparseCooTensor, SparseCsrTensor)):
            yc = y.to_sparse_coo() if isinstance(y, SparseCsrTensor) else y
            return _coo_elementwise(x.to_sparse_coo(), yc, jnp.add,
                                    "add").to_sparse_csr()
        return apply_op(jnp.add, x.to_dense(), y)  # sparse + dense → dense
    if isinstance(x, SparseCooTensor) and isinstance(y, SparseCooTensor):
        return _coo_elementwise(x, y, jnp.add, "add")
    if isinstance(x, SparseCooTensor):
        return apply_op(jnp.add, x.to_dense(), y)
    if isinstance(y, (SparseCooTensor, SparseCsrTensor)):
        return apply_op(jnp.add, x, y.to_dense())
    return apply_op(jnp.add, x, y)


def subtract(x, y, name=None):
    if isinstance(y, (SparseCooTensor, SparseCsrTensor)):
        return add(x, _neg_sparse(y))
    return apply_op(jnp.subtract, x.to_dense() if isinstance(
        x, (SparseCooTensor, SparseCsrTensor)) else x, y)


def _neg_sparse(y):
    return neg(y)


def multiply(x, y, name=None):
    """Elementwise; sparse*scalar stays sparse. sparse*sparse keeps x's
    support (static nnz: entries where y is implicitly zero are stored
    zeros — dense semantics identical, shapes stable across batches)."""
    if isinstance(x, (SparseCooTensor, SparseCsrTensor)) and np.isscalar(y):
        return _unary_factory("scale", lambda v: v * y)(x)
    if isinstance(x, SparseCsrTensor):
        x = x.to_sparse_coo()
    if isinstance(y, SparseCsrTensor):
        y = y.to_sparse_coo()
    if isinstance(x, SparseCooTensor) and isinstance(y, SparseCooTensor):
        xc = coalesce(x)
        gather = tuple(xc.indices)
        yd = y.to_dense()
        vals = apply_op(lambda v, d: v * d[gather], xc.values, yd)
        return SparseCooTensor(xc.indices, vals, xc.shape, coalesced=True)
    return apply_op(jnp.multiply, x.to_dense() if isinstance(
        x, SparseCooTensor) else x, y)


def divide(x, y, name=None):
    if isinstance(x, (SparseCooTensor, SparseCsrTensor)) and np.isscalar(y):
        return _unary_factory("scale", lambda v: v / y)(x)
    if isinstance(x, SparseCsrTensor):
        x = x.to_sparse_coo()
    xd = x.to_dense() if isinstance(x, SparseCooTensor) else x
    yd = y.to_dense() if isinstance(y, (SparseCooTensor,
                                        SparseCsrTensor)) else y
    return apply_op(jnp.divide, xd, yd)


# ---------------------------------------------------------------------------
# matmul family — the TPU-relevant kernels (gather + segment_sum on MXU/VPU)
# ---------------------------------------------------------------------------

def matmul(x, y, name=None):
    """sparse @ dense → dense. COO: rows scatter-add of values[:,None] *
    y[cols]; CSR identically via expanded row ids. Reference kernels:
    paddle/phi/kernels/sparse/gpu/matmul_kernel.cu (cuSPARSE SpMM)."""
    if isinstance(x, SparseCsrTensor):
        rows, cols = x._row_indices(), x.cols
        shape = x.shape
        vals = x.values
    elif isinstance(x, SparseCooTensor):
        assert x.indices.shape[0] == 2, "matmul: 2-D sparse only"
        rows, cols = x.indices[0], x.indices[1]
        shape = x.shape
        vals = x.values
    else:  # dense @ sparse
        assert isinstance(y, (SparseCooTensor, SparseCsrTensor))
        # x @ S == (S^T @ x^T)^T ; S^T swaps rows/cols
        yt = y.to_sparse_coo() if isinstance(y, SparseCsrTensor) else y
        st = SparseCooTensor(jnp.stack([yt.indices[1], yt.indices[0]]),
                             yt.values, (yt.shape[1], yt.shape[0]))
        xt = apply_op(lambda a: jnp.swapaxes(a, -1, -2), x)
        return apply_op(lambda a: jnp.swapaxes(a, -1, -2), matmul(st, xt))

    n_rows = shape[0]
    y_nd = len(y.shape)
    assert y_nd == 2, (
        f"sparse.matmul: dense operand must be 2-D [K, N], got rank {y_nd} "
        f"(batched SpMM is not supported; vmap over the batch instead)")

    def spmm(v, d):
        gathered = jnp.take(d, cols, axis=0)          # [nnz, N]
        contrib = v[:, None] * gathered               # [nnz, N]
        return jax.ops.segment_sum(contrib, rows, num_segments=n_rows)
    return apply_op(spmm, vals, y)


def masked_matmul(x: Tensor, y: Tensor, mask, name=None):
    """(x @ y) sampled at mask's sparsity pattern (SDDMM). Reference:
    paddle/phi/kernels/sparse/gpu/masked_matmul_kernel.cu (cuSPARSE SDDMM).
    TPU realization: two gathers + a row-wise dot on the VPU."""
    if isinstance(mask, SparseCsrTensor):
        rows, cols = mask._row_indices(), mask.cols
        out_is_csr = True
    else:
        rows, cols = mask.indices[0], mask.indices[1]
        out_is_csr = False

    def sddmm(a, b):
        ar = jnp.take(a, rows, axis=0)                # [nnz, K]
        bc = jnp.take(b, cols, axis=1).T              # [nnz, K]
        return jnp.sum(ar * bc, axis=-1)
    vals = apply_op(sddmm, x, y)
    if out_is_csr:
        return SparseCsrTensor(mask.crows, mask.cols, vals, mask.shape)
    return SparseCooTensor(mask.indices, vals, mask.shape,
                           coalesced=mask._coalesced)


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    """beta * input + alpha * (x @ y) with sparse x (reference multiary.py)."""
    prod = matmul(x, y)
    inp = input.to_dense() if isinstance(
        input, (SparseCooTensor, SparseCsrTensor)) else input
    return apply_op(lambda i, p: beta * i + alpha * p, inp, prod)
