"""hapi callbacks. Parity: python/paddle/hapi/callbacks.py."""
from __future__ import annotations

import time

import numpy as np

__all__ = ["Callback", "ProgBarLogger", "ModelCheckpoint", "EarlyStopping",
           "LRScheduler", "ReduceLROnPlateau", "VisualDL",
           "config_callbacks"]


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        self.params = params or {}

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_eval_batch_begin(self, step, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass


class ProgBarLogger(Callback):
    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose
        self._t0 = None

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self._t0 = time.time()

    def on_train_batch_end(self, step, logs=None):
        if self.verbose and step % self.log_freq == 0:
            items = ", ".join(f"{k}: {v}" for k, v in (logs or {}).items())
            print(f"Epoch {self.epoch} step {step}: {items}")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            dt = time.time() - (self._t0 or time.time())
            print(f"Epoch {epoch} done in {dt:.1f}s: {logs}")


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and epoch % self.save_freq == 0:
            self.model.save(f"{self.save_dir}/{epoch}")


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.min_delta = min_delta
        self.best = None
        self.wait = 0
        self.stopped = False
        self.mode = "min" if mode in ("auto", "min") else "max"

    def on_eval_end(self, logs=None):
        val = (logs or {}).get(self.monitor)
        if val is None:
            return
        if isinstance(val, (list, tuple)):
            val = val[0]
        better = (self.best is None or
                  (val < self.best - self.min_delta if self.mode == "min"
                   else val > self.best + self.min_delta))
        if better:
            self.best = val
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.stopped = True
                if self.model is not None:
                    self.model.stop_training = True


class LRScheduler(Callback):
    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        from ..optimizer.lr import LRScheduler as Sched
        if opt is not None and isinstance(opt._learning_rate, Sched):
            return opt._learning_rate
        return None

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if s and self.by_step:
            s.step()

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if s and self.by_epoch:
            s.step()


class VisualDL(Callback):
    """Parity: paddle.callbacks.VisualDL — logs train/eval scalars to a
    LogWriter (TensorBoard event files; see paddle_tpu/visualdl.py)."""

    def __init__(self, log_dir):
        super().__init__()
        self.log_dir = log_dir
        self._writer = None
        self._step = 0

    def _w(self):
        if self._writer is None:
            from ..visualdl import LogWriter
            self._writer = LogWriter(logdir=self.log_dir)
        return self._writer

    def _log(self, prefix, logs, step):
        for k, v in (logs or {}).items():
            try:
                self._w().add_scalar(f"{prefix}/{k}", v, step)
            except (TypeError, ValueError, IndexError):
                continue

    def on_train_batch_end(self, step, logs=None):
        self._step += 1
        self._log("train", logs, self._step)

    def on_epoch_end(self, epoch, logs=None):
        self._log("train_epoch", logs, epoch)

    def on_eval_end(self, logs=None):
        self._log("eval", logs, self._step)

    def on_train_end(self, logs=None):
        if self._writer is not None:
            self._writer.close()
            self._writer = None          # a later fit() reopens cleanly


class ReduceLROnPlateau(Callback):
    """Shrink the optimizer LR when the monitored metric plateaus
    (reference: hapi/callbacks.py :: ReduceLROnPlateau)."""

    def __init__(self, monitor="loss", factor=0.1, patience=10, verbose=1,
                 mode="auto", min_delta=1e-4, cooldown=0, min_lr=0.0):
        super().__init__()
        self.monitor = monitor
        self.factor = float(factor)
        if self.factor >= 1.0:
            raise ValueError("ReduceLROnPlateau factor must be < 1.0")
        self.patience = patience
        self.verbose = verbose
        self.mode = "min" if mode in ("auto", "min") else "max"
        self.min_delta = min_delta
        self.cooldown = cooldown
        self.min_lr = min_lr
        self.best = None
        self.wait = 0
        self.cooldown_counter = 0

    def _reduce(self):
        opt = getattr(self.model, "_optimizer", None)
        if opt is None:
            return
        from ..optimizer.lr import LRScheduler as Sched
        lr = opt._learning_rate
        if isinstance(lr, Sched):
            new = max(lr.last_lr * self.factor, self.min_lr)
            lr.base_lr = new
            lr.last_lr = new
        else:
            opt.set_lr(max(float(lr) * self.factor, self.min_lr))
        if self.verbose:
            print(f"ReduceLROnPlateau: lr reduced by {self.factor}")

    def on_eval_end(self, logs=None):
        val = (logs or {}).get(self.monitor)
        if val is None:
            return
        if isinstance(val, (list, tuple)):
            val = val[0]
        if self.cooldown_counter > 0:
            # in cooldown: track the best but never count waits/reduce
            self.cooldown_counter -= 1
            self.wait = 0
            if (self.best is None or
                    (val < self.best - self.min_delta
                     if self.mode == "min"
                     else val > self.best + self.min_delta)):
                self.best = val
            return
        better = (self.best is None or
                  (val < self.best - self.min_delta if self.mode == "min"
                   else val > self.best + self.min_delta))
        if better:
            self.best = val
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self._reduce()
                self.cooldown_counter = self.cooldown
                self.wait = 0


def config_callbacks(callbacks=None, model=None, batch_size=None, epochs=None,
                     steps=None, log_freq=2, verbose=2, save_freq=1,
                     save_dir=None, metrics=None, mode="train"):
    cbks = list(callbacks or [])
    if not any(isinstance(c, ProgBarLogger) for c in cbks):
        cbks.append(ProgBarLogger(log_freq, verbose))
    if save_dir and not any(isinstance(c, ModelCheckpoint) for c in cbks):
        cbks.append(ModelCheckpoint(save_freq, save_dir))
    for c in cbks:
        c.set_model(model)
        c.set_params({"batch_size": batch_size, "epochs": epochs,
                      "steps": steps, "verbose": verbose, "metrics": metrics})
    return cbks
