"""paddle_tpu — a TPU-native framework with PaddlePaddle's capabilities.

Not a port: the compute path is JAX/XLA/Pallas, distribution is named-mesh
collectives over ICI/DCN, and the Paddle-shaped user surface (Tensor, nn.Layer,
optimizer, amp, fleet) sits on top. Blueprint: /root/repo/SURVEY.md.

Usage parity with the reference:
    import paddle_tpu as paddle
    x = paddle.to_tensor([1., 2.]); y = (x * 2).sum(); y.backward()
"""
from __future__ import annotations

__version__ = "0.1.0"

# jax version shims (installs jax.shard_map on 0.4.x) — must run before
# any submodule does `from jax import shard_map` at module scope
from . import _jax_compat  # noqa: F401

# core
from .core import dtype as _dtype_mod
from .core.dtype import (float16, bfloat16, float32, float64, int8, int16,
                         int32, int64, uint8, bool_, complex64, complex128,
                         set_default_dtype, get_default_dtype, iinfo, finfo)
from .core.place import (Place, CPUPlace, TPUPlace, XLAPlace, CUDAPlace,
                         set_device, get_device, device_count,
                         is_compiled_with_cuda, is_compiled_with_xpu,
                         is_compiled_with_tpu)
from .core.rng import seed, get_rng_state, set_rng_state
from .core.flags import get_flags, set_flags

# tensor + autograd
from .tensor import *  # noqa: F401,F403
from .tensor import Tensor, Parameter
from .tensor import linalg  # paddle.linalg namespace
from .tensor.tensor import no_grad, enable_grad, is_grad_enabled, set_grad_enabled
from . import autograd
from .autograd import grad

# subsystems (populated as the build proceeds)
from . import nn
from . import optimizer
from . import amp
from . import io
from . import jit
from . import static
from . import device
from . import distributed
from . import incubate
from . import vision
from . import profiler
from . import hapi
from . import metric
from . import regularizer
from . import distribution
from . import fft
from . import signal
from . import version
from . import inference
from . import text
from . import utils
from . import sparse
from . import audio
from . import geometric
from . import quantization
from . import sysconfig
from . import hub
from . import onnx
from . import fluid
from . import reader
from .reader import batch
from .hapi.model import Model
from .framework.io import save, load
from .framework.layer_helpers import DataParallel
from .nn.layer.layers import disable_static, enable_static, in_dynamic_mode

# expose F-style namespaces the way paddle does
from .nn import functional  # noqa: F401

# re-bind subpackage names the star-imports above shadowed
import sys as _sys
tensor = _sys.modules["paddle_tpu.tensor"]


def ones_like_(x):  # pragma: no cover - compat shim
    from .tensor.creation import ones_like
    return ones_like(x)
