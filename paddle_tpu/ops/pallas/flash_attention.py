"""Flash attention as a TPU Pallas (Mosaic) kernel.

Capability parity: paddle/phi/kernels/gpu/flash_attn_kernel.cu ::
FlashAttnKernel / flash_attn_grad_kernel.cu (FA-2 wrapper over
third_party/flashattn).  This is NOT a port of that CUDA: it is the
blockwise online-softmax algorithm laid out for the TPU memory hierarchy —
Q/K/V tiles staged in VMEM, the S = QK^T and P·V contractions on the MXU in
the INPUT dtype (bf16 runs at full MXU rate) with fp32 accumulation, the
softmax math and running stats (m, l) in fp32 VMEM scratch carried across
the KV-block grid dimension.

Layout convention follows the reference flash_attn API: [batch, seq,
num_heads, head_dim]; the wrapper transposes to [B, H, S, D] so the kernel
works on (seq, head_dim) tiles (last dim = lanes).

Supports: causal masking, GQA/MQA (kv_heads divides q_heads; realized in the
BlockSpec index_map — zero-copy), bf16/f32 inputs (dots in input dtype,
fp32 accumulate + softmax), seq
lengths not divisible by the block size (masked tail blocks).  Backward is
the standard two-kernel split: dKV (grid over KV blocks, scan Q) and dQ
(grid over Q blocks, scan KV), with delta = rowsum(dO * O) precomputed.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["flash_attention", "is_supported"]

NEG_INF = -1e30  # large-negative instead of -inf: keeps exp()/max() NaN-free


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def is_supported(q_shape, dtype) -> bool:
    """Wrapper-level gate: rank-4 [B,S,H,D], supported dtype, head_dim ≤ 256."""
    if len(q_shape) != 4:
        return False
    d = q_shape[-1]
    if d > 256:
        return False
    return jnp.dtype(dtype) in (jnp.float32, jnp.bfloat16, jnp.float16)


def _block_sizes(sq: int, sk: int):
    """1024-wide tiles (default cap): the [bq,d]x[d,bk] and [bq,bk]x[bk,d]
    dots must be large enough to fill the MXU pipeline — 128x128 tiles
    measure ~5-9 TFLOP/s on v5e, 512x512 ~12, 1024x1024 ~16 (r3 s4 sweep:
    fwd+bwd 4.76 -> 3.56 ms/layer at the GPT-2 headline shape; headline
    step 91.7 -> 86.6 ms). VMEM per program at 1024 tiles is ~6 MB
    (s/p [1024,1024] f32 + q/k/v/acc tiles), still < the ~16 MB budget."""
    def pick(n, cap):
        if n < cap:
            return max(8, 1 << (n - 1).bit_length())
        # n >= cap: prefer the block size that minimizes ceil-padding —
        # e.g. S=1536 under a 1024 cap would pad to 2048 (+78% masked
        # tile compute) while 512 tiles fit exactly; ties go to the
        # larger (more MXU-efficient) block
        cands = [c for c in (cap, cap // 2) if c >= 256] or [cap]
        return min(cands, key=lambda c: (math.ceil(n / c) * c, -c))

    import os

    def cap_from_env(var, default):
        # tuning knob: clamp to [8, 4096] and round down to a power of two
        # so a bad value degrades to a valid Mosaic block, never a crash
        try:
            v = int(os.environ.get(var, default))
        except ValueError:
            v = default
        v = min(max(v, 8), 4096)
        return 1 << (v.bit_length() - 1)

    return (pick(sq, cap_from_env("PADDLE_TPU_FLASH_BQ", 1024)),
            pick(sk, cap_from_env("PADDLE_TPU_FLASH_BK", 1024)))


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------

def _drop_tile(seed_ref, bi, hi, qi, ki, bq, bk, dropout_p):
    """Scaled keep multiplier generated in-kernel (TPU hardware PRNG, zero
    HBM traffic); seeded per (call, batch, head, q-block, k-block) so the
    backward kernels regenerate the identical mask. Mosaic takes at most 2
    seed words — fold the block coordinates into one."""
    nh = pl.num_programs(1)
    # q/k block counts differ between the three kernels' grids, but the
    # (qi, ki) pair itself is kernel-invariant; fold with fixed strides
    # large enough for any block count
    tile_id = ((bi * nh + hi) * 4096 + qi) * 4096 + ki
    pltpu.prng_seed(seed_ref[0], tile_id)
    bits = pltpu.prng_random_bits((bq, bk)).astype(jnp.uint32)
    thresh = jnp.uint32(min(int(dropout_p * 4294967296.0), 4294967295))
    return jnp.where(bits >= thresh, 1.0 / (1.0 - dropout_p), 0.0)


def _fwd_kernel(q_ref, k_ref, v_ref, *rest, scale, causal, sq, sk, bq, bk,
                drop_mode=0, dropout_p=0.0):
    # drop_mode: 0 = no dropout, 1 = mask input (interpret), 2 = in-kernel
    # PRNG (TPU). Mode 1/2 append dmask / SMEM seed to the inputs.
    if drop_mode == 1:
        dmask_ref, o_ref, lse_ref, acc_sc, m_sc, l_sc = rest
        seed_ref = None
    elif drop_mode == 2:
        seed_ref, o_ref, lse_ref, acc_sc, m_sc, l_sc = rest
        dmask_ref = None
    else:
        o_ref, lse_ref, acc_sc, m_sc, l_sc = rest
        dmask_ref = seed_ref = None
    # Causal uses bottom-right alignment (FA2 convention): row i attends
    # key j iff j <= i + sk - sq.
    offset = sk - sq
    qi = pl.program_id(2)
    ki = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ki == 0)
    def _():
        m_sc[:] = jnp.full_like(m_sc, NEG_INF)
        l_sc[:] = jnp.zeros_like(l_sc)
        acc_sc[:] = jnp.zeros_like(acc_sc)

    q_start = qi * bq
    k_start = ki * bk

    # Causal: skip blocks strictly above the (aligned) diagonal entirely.
    run = True
    if causal:
        run = q_start + bq - 1 + offset >= k_start

    @pl.when(run)
    def _():
        # dots run in the input dtype (bf16 MXU full rate) with f32
        # accumulation; only the softmax math is f32
        q = q_ref[0, 0]                               # [bq, d]
        k = k_ref[0, 0]                               # [bk, d]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # [bq, bk] f32

        rows = q_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        cols = k_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        mask = cols < sk                      # key-padding tail
        if causal:
            mask = mask & (cols <= rows + offset)
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_sc[:]                                   # [bq, 1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)                             # [bq, bk]
        p = jnp.where(mask, p, 0.0)

        l_sc[:] = l_sc[:] * alpha + jnp.sum(p, axis=1, keepdims=True)
        m_sc[:] = m_new
        # dropout on the softmax probs (post-normalization semantics: the
        # l denominator above uses the raw p)
        if dmask_ref is not None:
            p = p * dmask_ref[0, 0]
        elif seed_ref is not None:
            p = p * _drop_tile(seed_ref, pl.program_id(0), pl.program_id(1),
                               qi, ki, bq, bk, dropout_p)
        v = v_ref[0, 0]                                    # [bk, d]
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        acc_sc[:] = acc_sc[:] * alpha + pv

    @pl.when(ki == nk - 1)
    def _():
        l = l_sc[:]
        l_safe = jnp.where(l == 0.0, 1.0, l)     # padded q rows: garbage-free
        o_ref[0, 0] = (acc_sc[:] / l_safe).astype(o_ref.dtype)
        lse_ref[0, 0] = m_sc[:] + jnp.log(l_safe)      # [bq, 1]


def _fwd(q, k, v, drop=None, *, causal, scale, bq, bk):
    """q,k,v: [B,H,S,D] (kv may have fewer heads for GQA). Returns (o, lse).
    drop: None, ('mask', dmask [B,H,Sq_p,Sk_p] f32) or ('prng', seed, p)."""
    b, h, sq, d = q.shape
    hk = k.shape[1]
    group = h // hk
    sq_p = math.ceil(sq / bq) * bq
    sk_p = math.ceil(k.shape[2] / bk) * bk
    sk = k.shape[2]
    if sq_p != sq:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, sq_p - sq), (0, 0)))
    if sk_p != sk:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, sk_p - sk), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, sk_p - sk), (0, 0)))

    grid = (b, h, sq_p // bq, sk_p // bk)
    drop_mode = 0 if drop is None else (1 if drop[0] == "mask" else 2)
    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, sq=sq, sk=sk, bq=bq, bk=bk,
        drop_mode=drop_mode,
        dropout_p=drop[2] if drop_mode == 2 else 0.0)
    in_specs = [
        pl.BlockSpec((1, 1, bq, d), lambda b_, h_, i, j: (b_, h_, i, 0)),
        pl.BlockSpec((1, 1, bk, d),
                     lambda b_, h_, i, j, g=group: (b_, h_ // g, j, 0)),
        pl.BlockSpec((1, 1, bk, d),
                     lambda b_, h_, i, j, g=group: (b_, h_ // g, j, 0)),
    ]
    args = [q, k, v]
    if drop_mode == 1:
        in_specs.append(pl.BlockSpec((1, 1, bq, bk),
                                     lambda b_, h_, i, j: (b_, h_, i, j)))
        args.append(drop[1])
    elif drop_mode == 2:
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        args.append(jnp.reshape(drop[1].astype(jnp.int32), (1,)))
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda b_, h_, i, j: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, bq, 1), lambda b_, h_, i, j: (b_, h_, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, sq_p, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, sq_p, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
        ],
        interpret=_interpret(),
    )(*args)
    return o[:, :, :sq], lse[:, :, :sq]        # lse: [B, H, Sq, 1]


# ---------------------------------------------------------------------------
# Backward
# ---------------------------------------------------------------------------

def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    *rest, scale, causal, sq, sk, bq, bk, drop_mode=0,
                    dropout_p=0.0):
    if drop_mode == 1:
        dmask_ref, dk_ref, dv_ref, dk_sc, dv_sc = rest
        seed_ref = None
    elif drop_mode == 2:
        seed_ref, dk_ref, dv_ref, dk_sc, dv_sc = rest
        dmask_ref = None
    else:
        dk_ref, dv_ref, dk_sc, dv_sc = rest
        dmask_ref = seed_ref = None
    offset = sk - sq
    ki = pl.program_id(2)
    qi = pl.program_id(3)
    nq = pl.num_programs(3)

    @pl.when(qi == 0)
    def _():
        dk_sc[:] = jnp.zeros_like(dk_sc)
        dv_sc[:] = jnp.zeros_like(dv_sc)

    q_start = qi * bq
    k_start = ki * bk
    run = True
    if causal:
        run = q_start + bq - 1 + offset >= k_start

    @pl.when(run)
    def _():
        q = q_ref[0, 0]                                   # [bq, d]
        k = k_ref[0, 0]                                   # [bk, d]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0]                               # [bq, 1]
        delta = delta_ref[0, 0]                           # [bq, 1]

        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        rows = q_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        cols = k_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        mask = (cols < sk) & (rows < sq)
        if causal:
            mask = mask & (cols <= rows + offset)
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)        # [bq, bk] f32

        if dmask_ref is not None:
            dm = dmask_ref[0, 0]
        elif seed_ref is not None:
            # same (b, h, q-block, k-block) seeding as the forward kernel
            dm = _drop_tile(seed_ref, pl.program_id(0), pl.program_id(1),
                            qi, ki, bq, bk, dropout_p)
        else:
            dm = None
        # dv += (D∘P)^T dO
        pd = p * dm if dm is not None else p
        dv_sc[:] += jax.lax.dot_general(
            pd.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        # ds = P * (D∘(dO V^T) - delta) * scale   (delta = rowsum(dO∘O)
        # absorbs the dropout mask exactly — see derivation in _flash_bwd)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        if dm is not None:
            dp = dp * dm
        ds = p * (dp - delta) * scale
        # dk += dS^T Q
        dk_sc[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qi == nq - 1)
    def _():
        dk_ref[0, 0] = dk_sc[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_sc[:].astype(dv_ref.dtype)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   *rest, scale, causal, sq, sk, bq, bk, drop_mode=0,
                   dropout_p=0.0):
    if drop_mode == 1:
        dmask_ref, dq_ref, dq_sc = rest
        seed_ref = None
    elif drop_mode == 2:
        seed_ref, dq_ref, dq_sc = rest
        dmask_ref = None
    else:
        dq_ref, dq_sc = rest
        dmask_ref = seed_ref = None
    offset = sk - sq
    qi = pl.program_id(2)
    ki = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ki == 0)
    def _():
        dq_sc[:] = jnp.zeros_like(dq_sc)

    q_start = qi * bq
    k_start = ki * bk
    run = True
    if causal:
        run = q_start + bq - 1 + offset >= k_start

    @pl.when(run)
    def _():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0]                               # [bq, 1]
        delta = delta_ref[0, 0]                           # [bq, 1]

        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        rows = q_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        cols = k_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        mask = (cols < sk) & (rows < sq)
        if causal:
            mask = mask & (cols <= rows + offset)
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        if dmask_ref is not None:
            dp = dp * dmask_ref[0, 0]
        elif seed_ref is not None:
            dp = dp * _drop_tile(seed_ref, pl.program_id(0),
                                 pl.program_id(1), qi, ki, bq, bk, dropout_p)
        ds = p * (dp - delta) * scale
        dq_sc[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _():
        dq_ref[0, 0] = dq_sc[:].astype(dq_ref.dtype)


def _bwd_fused_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                      *rest, scale, causal, sq, sk, drop_mode=0,
                      dropout_p=0.0):
    """Single-block backward: when the whole (b, h) slice fits one
    (bq, bk) tile (the common S <= 1024 training shape), dq, dk and dv
    come out of ONE kernel — S and dP are computed once instead of once
    per split kernel (9 dots -> 7) and q/k/v/do are read once instead of
    twice. Measured r3 s4: attention fwd+bwd 32.1 -> ~24 ms/step on the
    GPT-2 headline."""
    if drop_mode == 1:
        dmask_ref, dq_ref, dk_ref, dv_ref = rest
        seed_ref = None
    elif drop_mode == 2:
        seed_ref, dq_ref, dk_ref, dv_ref = rest
        dmask_ref = None
    else:
        dq_ref, dk_ref, dv_ref = rest
        dmask_ref = seed_ref = None
    offset = sk - sq
    bq = q_ref.shape[2]
    bk = k_ref.shape[2]

    q = q_ref[0, 0]                                   # [bq, d]
    k = k_ref[0, 0]                                   # [bk, d]
    v = v_ref[0, 0]
    do = do_ref[0, 0]
    lse = lse_ref[0, 0]                               # [bq, 1]
    delta = delta_ref[0, 0]                           # [bq, 1]

    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    rows = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    mask = (cols < sk) & (rows < sq)
    if causal:
        mask = mask & (cols <= rows + offset)
    p = jnp.where(mask, jnp.exp(s - lse), 0.0)        # [bq, bk] f32

    if dmask_ref is not None:
        dm = dmask_ref[0, 0]
    elif seed_ref is not None:
        # same (b, h, q-block=0, k-block=0) seeding as the forward kernel
        dm = _drop_tile(seed_ref, pl.program_id(0), pl.program_id(1),
                        0, 0, bq, bk, dropout_p)
    else:
        dm = None
    pd = p * dm if dm is not None else p
    dv_ref[0, 0] = jax.lax.dot_general(
        pd.astype(do.dtype), do, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(dv_ref.dtype)
    dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    if dm is not None:
        dp = dp * dm
    ds = p * (dp - delta) * scale                     # [bq, bk] f32
    dk_ref[0, 0] = jax.lax.dot_general(
        ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(dk_ref.dtype)
    dq_ref[0, 0] = jax.lax.dot_general(
        ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(dq_ref.dtype)


def _bwd_fused(q_, k_, v_, do_, lse_, delta_, drop, drop_arg, *,
               causal, scale, sq, sk, group):
    """Single-block fused backward dispatch; inputs are pre-padded to one
    (bq, bk) = (sq_p, sk_p) block. Returns (dq, dk_perq, dv_perq) with dk/dv
    still per-q-head (GQA segment-sum happens in the caller)."""
    b, h, sq_p, d = q_.shape
    sk_p = k_.shape[2]
    drop_mode = 0 if drop is None else (1 if drop[0] == "mask" else 2)
    qspec = pl.BlockSpec((1, 1, sq_p, d), lambda b_, h_: (b_, h_, 0, 0))
    kspec = pl.BlockSpec((1, 1, sk_p, d),
                         lambda b_, h_, g=group: (b_, h_ // g, 0, 0))
    rowspec = pl.BlockSpec((1, 1, sq_p, 1), lambda b_, h_: (b_, h_, 0, 0))
    in_specs = [qspec, kspec, kspec, qspec, rowspec, rowspec]
    args = [q_, k_, v_, do_, lse_, delta_]
    if drop_mode == 1:
        in_specs.append(pl.BlockSpec((1, 1, sq_p, sk_p),
                                     lambda b_, h_: (b_, h_, 0, 0)))
        args.append(drop_arg())
    elif drop_mode == 2:
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        args.append(drop_arg())
    dq, dk, dv = pl.pallas_call(
        functools.partial(_bwd_fused_kernel, scale=scale, causal=causal,
                          sq=sq, sk=sk, drop_mode=drop_mode,
                          dropout_p=drop[2] if drop_mode == 2 else 0.0),
        grid=(b, h),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, sq_p, d), lambda b_, h_: (b_, h_, 0, 0)),
            pl.BlockSpec((1, 1, sk_p, d), lambda b_, h_: (b_, h_, 0, 0)),
            pl.BlockSpec((1, 1, sk_p, d), lambda b_, h_: (b_, h_, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, sq_p, d), q_.dtype),
            jax.ShapeDtypeStruct((b, h, sk_p, d), jnp.float32),
            jax.ShapeDtypeStruct((b, h, sk_p, d), jnp.float32),
        ],
        interpret=_interpret(),
    )(*args)
    return dq, dk, dv


def _bwd(q, k, v, o, lse, do, drop=None, *, causal, scale, bq, bk):
    b, h, sq, d = q.shape
    hk = k.shape[1]
    group = h // hk
    sk = k.shape[2]
    sq_p = math.ceil(sq / bq) * bq
    sk_p = math.ceil(sk / bk) * bk
    drop_mode = 0 if drop is None else (1 if drop[0] == "mask" else 2)
    drop_p = drop[2] if drop_mode == 2 else 0.0

    def drop_arg():
        if drop_mode == 1:
            return drop[1]
        return jnp.reshape(drop[1].astype(jnp.int32), (1,))

    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1, keepdims=True)              # [B, H, Sq, 1]

    def padq(x):
        return jnp.pad(x, ((0, 0), (0, 0), (0, sq_p - sq), (0, 0))) \
            if sq_p != sq else x

    def padk(x):
        return jnp.pad(x, ((0, 0), (0, 0), (0, sk_p - sk), (0, 0))) \
            if sk_p != sk else x

    q_, do_ = padq(q), padq(do)
    k_, v_ = padk(k), padk(v)
    lse_, delta_ = padq(lse), padq(delta)

    if sq_p == bq and sk_p == bk:
        # whole slice is one block: fused dq/dk/dv kernel (no S/dP
        # recompute, single read of q/k/v/do)
        import os
        if os.environ.get("PADDLE_TPU_FLASH_SPLIT_BWD") != "1":

            dq, dk, dv = _bwd_fused(
                q_, k_, v_, do_, lse_, delta_, drop, drop_arg,
                causal=causal, scale=scale, sq=sq, sk=sk, group=group)
            dq = dq[:, :, :sq]
            dk = dk[:, :, :sk]
            dv = dv[:, :, :sk]
            if group > 1:
                dk = dk.reshape(b, hk, group, sk, d).sum(axis=2)
                dv = dv.reshape(b, hk, group, sk, d).sum(axis=2)
            return dq, dk.astype(k.dtype), dv.astype(v.dtype)

    qspec = pl.BlockSpec((1, 1, bq, d), lambda b_, h_, j, i: (b_, h_, i, 0))
    kspec = pl.BlockSpec((1, 1, bk, d),
                         lambda b_, h_, j, i, g=group: (b_, h_ // g, j, 0))
    rowspec = pl.BlockSpec((1, 1, bq, 1), lambda b_, h_, j, i: (b_, h_, i, 0))

    # dK/dV: one [bk,d] accumulator pair per KV block; Q scanned innermost.
    # GQA: compute per-Q-head dk/dv (shape [B,H,...]) and segment-sum to
    # [B,Hk,...] outside the kernel — XLA turns that into a cheap reshape-sum.
    dkv_in = [qspec, kspec, kspec, qspec, rowspec, rowspec]
    dkv_args = [q_, k_, v_, do_, lse_, delta_]
    if drop_mode == 1:
        dkv_in.append(pl.BlockSpec((1, 1, bq, bk),
                                   lambda b_, h_, j, i: (b_, h_, i, j)))
        dkv_args.append(drop_arg())
    elif drop_mode == 2:
        dkv_in.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        dkv_args.append(drop_arg())
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          sq=sq, sk=sk, bq=bq, bk=bk, drop_mode=drop_mode,
                          dropout_p=drop_p),
        grid=(b, h, sk_p // bk, sq_p // bq),
        in_specs=dkv_in,
        out_specs=[
            pl.BlockSpec((1, 1, bk, d), lambda b_, h_, j, i: (b_, h_, j, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda b_, h_, j, i: (b_, h_, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, sk_p, d), jnp.float32),
            jax.ShapeDtypeStruct((b, h, sk_p, d), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, d), jnp.float32),
            pltpu.VMEM((bk, d), jnp.float32),
        ],
        interpret=_interpret(),
    )(*dkv_args)

    qspec2 = pl.BlockSpec((1, 1, bq, d), lambda b_, h_, i, j: (b_, h_, i, 0))
    kspec2 = pl.BlockSpec((1, 1, bk, d),
                          lambda b_, h_, i, j, g=group: (b_, h_ // g, j, 0))
    rowspec2 = pl.BlockSpec((1, 1, bq, 1), lambda b_, h_, i, j: (b_, h_, i, 0))
    dq_in = [qspec2, kspec2, kspec2, qspec2, rowspec2, rowspec2]
    dq_args = [q_, k_, v_, do_, lse_, delta_]
    if drop_mode == 1:
        dq_in.append(pl.BlockSpec((1, 1, bq, bk),
                                  lambda b_, h_, i, j: (b_, h_, i, j)))
        dq_args.append(drop_arg())
    elif drop_mode == 2:
        dq_in.append(pl.BlockSpec(memory_space=pltpu.SMEM))
        dq_args.append(drop_arg())
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          sq=sq, sk=sk, bq=bq, bk=bk, drop_mode=drop_mode,
                          dropout_p=drop_p),
        grid=(b, h, sq_p // bq, sk_p // bk),
        in_specs=dq_in,
        out_specs=pl.BlockSpec((1, 1, bq, d),
                               lambda b_, h_, i, j: (b_, h_, i, 0)),
        out_shape=jax.ShapeDtypeStruct((b, h, sq_p, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        interpret=_interpret(),
    )(*dq_args)

    dq = dq[:, :, :sq]
    dk = dk[:, :, :sk]
    dv = dv[:, :, :sk]
    if group > 1:
        dk = dk.reshape(b, hk, group, sk, d).sum(axis=2)
        dv = dv.reshape(b, hk, group, sk, d).sum(axis=2)
    return dq, dk.astype(k.dtype), dv.astype(v.dtype)


# ---------------------------------------------------------------------------
# Public API (custom_vjp; [B, S, H, D] layout like the reference flash_attn)
# ---------------------------------------------------------------------------

def _dropout_mask(seed, shape, dropout_p):
    """Scaled keep-mask [B,H,Sq_p,Sk_p] regenerated identically fwd/bwd from
    the int32 seed — the residual is the seed, not the O(S^2) mask (the
    philox-offset recompute trick of the reference FA2, done with the JAX
    PRNG at the XLA level)."""
    key = jax.random.PRNGKey(seed)
    keep = jax.random.bernoulli(key, 1.0 - dropout_p, shape)
    return keep.astype(jnp.float32) / (1.0 - dropout_p)


def _padded_sizes(sq, sk):
    bq, bk = _block_sizes(sq, sk)
    return bq, bk, math.ceil(sq / bq) * bq, math.ceil(sk / bk) * bk


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _flash(q, k, v, seed, causal, scale, dropout_p):
    o, _ = _core_fwd(q, k, v, seed, causal, scale, dropout_p)
    return o


def _make_drop(q, k, seed, dropout_p):
    """TPU: in-kernel PRNG (zero HBM mask traffic); interpret: explicit
    seed-regenerated mask array (prng_* primitives have no CPU lowering)."""
    if dropout_p <= 0.0:
        return None
    if not _interpret():
        return ("prng", seed, dropout_p)
    bq, bk, sq_p, sk_p = _padded_sizes(q.shape[2], k.shape[2])
    return ("mask",
            _dropout_mask(seed, (q.shape[0], q.shape[1], sq_p, sk_p),
                          dropout_p))


def _core_fwd(q, k, v, seed, causal, scale, dropout_p):
    bq, bk, _, _ = _padded_sizes(q.shape[2], k.shape[2])
    drop = _make_drop(q, k, seed, dropout_p)
    return _fwd(q, k, v, drop, causal=causal, scale=scale, bq=bq, bk=bk)


def _flash_fwd(q, k, v, seed, causal, scale, dropout_p):
    o, lse = _core_fwd(q, k, v, seed, causal, scale, dropout_p)
    return o, (q, k, v, o, lse, seed)


def _flash_bwd(causal, scale, dropout_p, res, g):
    q, k, v, o, lse, seed = res
    bq, bk, _, _ = _padded_sizes(q.shape[2], k.shape[2])
    drop = _make_drop(q, k, seed, dropout_p)
    dq, dk, dv = _bwd(q, k, v, o, lse, g, drop, causal=causal, scale=scale,
                      bq=bq, bk=bk)
    return dq, dk, dv, None


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, causal=False, scale=None, dropout_p=0.0,
                    dropout_seed=None):
    """q,k,v: [batch, seq, heads, head_dim] (kv heads may divide q heads).

    Returns [batch, seq, heads, head_dim]; differentiable (custom VJP with
    flash backward kernels). dropout_p > 0 applies attention-prob dropout
    (upscaled) with a seed-regenerated mask — pass dropout_seed (int32
    scalar, traced ok) for reproducibility.
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if q.shape[2] % k.shape[2] != 0:
        raise ValueError(
            f"q heads ({q.shape[2]}) must be a multiple of kv heads "
            f"({k.shape[2]}) for GQA flash attention")
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    if dropout_seed is None:
        dropout_seed = jnp.zeros((), jnp.int32)
    o = _flash(qt, kt, vt, dropout_seed, bool(causal), float(scale),
               float(dropout_p))
    return jnp.swapaxes(o, 1, 2)
