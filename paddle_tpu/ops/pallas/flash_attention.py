"""Flash attention as a TPU Pallas (Mosaic) kernel.

Capability parity: paddle/phi/kernels/gpu/flash_attn_kernel.cu ::
FlashAttnKernel / flash_attn_grad_kernel.cu (FA-2 wrapper over
third_party/flashattn).  This is NOT a port of that CUDA: it is the
blockwise online-softmax algorithm laid out for the TPU memory hierarchy —
Q/K/V tiles staged in VMEM, the S = QK^T and P·V contractions on the MXU in
fp32, and the softmax running stats (m, l) carried in VMEM scratch across
the KV-block grid dimension.

Layout convention follows the reference flash_attn API: [batch, seq,
num_heads, head_dim]; the wrapper transposes to [B, H, S, D] so the kernel
works on (seq, head_dim) tiles (last dim = lanes).

Supports: causal masking, GQA/MQA (kv_heads divides q_heads; realized in the
BlockSpec index_map — zero-copy), bf16/f32 inputs (compute fp32), seq
lengths not divisible by the block size (masked tail blocks).  Backward is
the standard two-kernel split: dKV (grid over KV blocks, scan Q) and dQ
(grid over Q blocks, scan KV), with delta = rowsum(dO * O) precomputed.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["flash_attention", "is_supported"]

NEG_INF = -1e30  # large-negative instead of -inf: keeps exp()/max() NaN-free


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def is_supported(q_shape, dtype) -> bool:
    """Wrapper-level gate: rank-4 [B,S,H,D], supported dtype, head_dim ≤ 256."""
    if len(q_shape) != 4:
        return False
    d = q_shape[-1]
    if d > 256:
        return False
    return jnp.dtype(dtype) in (jnp.float32, jnp.bfloat16, jnp.float16)


def _block_sizes(sq: int, sk: int):
    bq = min(128, max(8, 1 << (sq - 1).bit_length() if sq < 128 else 128))
    bk = min(128, max(128 if sk >= 128 else 1 << (sk - 1).bit_length(), 8))
    return bq, bk


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                acc_sc, m_sc, l_sc, *, scale, causal, sq, sk, bq, bk):
    # Causal uses bottom-right alignment (FA2 convention): row i attends
    # key j iff j <= i + sk - sq.
    offset = sk - sq
    qi = pl.program_id(2)
    ki = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ki == 0)
    def _():
        m_sc[:] = jnp.full_like(m_sc, NEG_INF)
        l_sc[:] = jnp.zeros_like(l_sc)
        acc_sc[:] = jnp.zeros_like(acc_sc)

    q_start = qi * bq
    k_start = ki * bk

    # Causal: skip blocks strictly above the (aligned) diagonal entirely.
    run = True
    if causal:
        run = q_start + bq - 1 + offset >= k_start

    @pl.when(run)
    def _():
        q = q_ref[0, 0].astype(jnp.float32)           # [bq, d]
        k = k_ref[0, 0].astype(jnp.float32)           # [bk, d]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # [bq, bk]

        rows = q_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        cols = k_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        mask = cols < sk                      # key-padding tail
        if causal:
            mask = mask & (cols <= rows + offset)
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_sc[:]                                   # [bq, 1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)                             # [bq, bk]
        p = jnp.where(mask, p, 0.0)

        l_sc[:] = l_sc[:] * alpha + jnp.sum(p, axis=1, keepdims=True)
        m_sc[:] = m_new
        v = v_ref[0, 0].astype(jnp.float32)                # [bk, d]
        pv = jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        acc_sc[:] = acc_sc[:] * alpha + pv

    @pl.when(ki == nk - 1)
    def _():
        l = l_sc[:]
        l_safe = jnp.where(l == 0.0, 1.0, l)     # padded q rows: garbage-free
        o_ref[0, 0] = (acc_sc[:] / l_safe).astype(o_ref.dtype)
        lse_ref[0, 0] = m_sc[:] + jnp.log(l_safe)      # [bq, 1]


def _fwd(q, k, v, *, causal, scale, bq, bk):
    """q,k,v: [B,H,S,D] (kv may have fewer heads for GQA). Returns (o, lse)."""
    b, h, sq, d = q.shape
    hk = k.shape[1]
    group = h // hk
    sq_p = math.ceil(sq / bq) * bq
    sk_p = math.ceil(k.shape[2] / bk) * bk
    sk = k.shape[2]
    if sq_p != sq:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, sq_p - sq), (0, 0)))
    if sk_p != sk:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, sk_p - sk), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, sk_p - sk), (0, 0)))

    grid = (b, h, sq_p // bq, sk_p // bk)
    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               sq=sq, sk=sk, bq=bq, bk=bk)
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda b_, h_, i, j: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, bk, d),
                         lambda b_, h_, i, j, g=group: (b_, h_ // g, j, 0)),
            pl.BlockSpec((1, 1, bk, d),
                         lambda b_, h_, i, j, g=group: (b_, h_ // g, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda b_, h_, i, j: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, bq, 1), lambda b_, h_, i, j: (b_, h_, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, sq_p, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, sq_p, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
        ],
        interpret=_interpret(),
    )(q, k, v)
    return o[:, :, :sq], lse[:, :, :sq]        # lse: [B, H, Sq, 1]


# ---------------------------------------------------------------------------
# Backward
# ---------------------------------------------------------------------------

def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_sc, dv_sc,
                    *, scale, causal, sq, sk, bq, bk):
    offset = sk - sq
    ki = pl.program_id(2)
    qi = pl.program_id(3)
    nq = pl.num_programs(3)

    @pl.when(qi == 0)
    def _():
        dk_sc[:] = jnp.zeros_like(dk_sc)
        dv_sc[:] = jnp.zeros_like(dv_sc)

    q_start = qi * bq
    k_start = ki * bk
    run = True
    if causal:
        run = q_start + bq - 1 + offset >= k_start

    @pl.when(run)
    def _():
        q = q_ref[0, 0].astype(jnp.float32)               # [bq, d]
        k = k_ref[0, 0].astype(jnp.float32)               # [bk, d]
        v = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0]                               # [bq, 1]
        delta = delta_ref[0, 0]                           # [bq, 1]

        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        rows = q_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        cols = k_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        mask = (cols < sk) & (rows < sq)
        if causal:
            mask = mask & (cols <= rows + offset)
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)        # [bq, bk]

        # dv += P^T dO
        dv_sc[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        # ds = P * (dO V^T - delta) * scale
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        # dk += dS^T Q
        dk_sc[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qi == nq - 1)
    def _():
        dk_ref[0, 0] = dk_sc[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_sc[:].astype(dv_ref.dtype)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, dq_sc, *, scale, causal, sq, sk, bq, bk):
    offset = sk - sq
    qi = pl.program_id(2)
    ki = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ki == 0)
    def _():
        dq_sc[:] = jnp.zeros_like(dq_sc)

    q_start = qi * bq
    k_start = ki * bk
    run = True
    if causal:
        run = q_start + bq - 1 + offset >= k_start

    @pl.when(run)
    def _():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0]                               # [bq, 1]
        delta = delta_ref[0, 0]                           # [bq, 1]

        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        rows = q_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        cols = k_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        mask = (cols < sk) & (rows < sq)
        if causal:
            mask = mask & (cols <= rows + offset)
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dq_sc[:] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _():
        dq_ref[0, 0] = dq_sc[:].astype(dq_ref.dtype)


def _bwd(q, k, v, o, lse, do, *, causal, scale, bq, bk):
    b, h, sq, d = q.shape
    hk = k.shape[1]
    group = h // hk
    sk = k.shape[2]
    sq_p = math.ceil(sq / bq) * bq
    sk_p = math.ceil(sk / bk) * bk

    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1, keepdims=True)              # [B, H, Sq, 1]

    def padq(x):
        return jnp.pad(x, ((0, 0), (0, 0), (0, sq_p - sq), (0, 0))) \
            if sq_p != sq else x

    def padk(x):
        return jnp.pad(x, ((0, 0), (0, 0), (0, sk_p - sk), (0, 0))) \
            if sk_p != sk else x

    q_, do_ = padq(q), padq(do)
    k_, v_ = padk(k), padk(v)
    lse_, delta_ = padq(lse), padq(delta)

    qspec = pl.BlockSpec((1, 1, bq, d), lambda b_, h_, j, i: (b_, h_, i, 0))
    kspec = pl.BlockSpec((1, 1, bk, d),
                         lambda b_, h_, j, i, g=group: (b_, h_ // g, j, 0))
    rowspec = pl.BlockSpec((1, 1, bq, 1), lambda b_, h_, j, i: (b_, h_, i, 0))

    # dK/dV: one [bk,d] accumulator pair per KV block; Q scanned innermost.
    # GQA: compute per-Q-head dk/dv (shape [B,H,...]) and segment-sum to
    # [B,Hk,...] outside the kernel — XLA turns that into a cheap reshape-sum.
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          sq=sq, sk=sk, bq=bq, bk=bk),
        grid=(b, h, sk_p // bk, sq_p // bq),
        in_specs=[qspec, kspec, kspec, qspec, rowspec, rowspec],
        out_specs=[
            pl.BlockSpec((1, 1, bk, d), lambda b_, h_, j, i: (b_, h_, j, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda b_, h_, j, i: (b_, h_, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, sk_p, d), jnp.float32),
            jax.ShapeDtypeStruct((b, h, sk_p, d), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, d), jnp.float32),
            pltpu.VMEM((bk, d), jnp.float32),
        ],
        interpret=_interpret(),
    )(q_, k_, v_, do_, lse_, delta_)

    qspec2 = pl.BlockSpec((1, 1, bq, d), lambda b_, h_, i, j: (b_, h_, i, 0))
    kspec2 = pl.BlockSpec((1, 1, bk, d),
                          lambda b_, h_, i, j, g=group: (b_, h_ // g, j, 0))
    rowspec2 = pl.BlockSpec((1, 1, bq, 1), lambda b_, h_, i, j: (b_, h_, i, 0))
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          sq=sq, sk=sk, bq=bq, bk=bk),
        grid=(b, h, sq_p // bq, sk_p // bk),
        in_specs=[qspec2, kspec2, kspec2, qspec2, rowspec2, rowspec2],
        out_specs=pl.BlockSpec((1, 1, bq, d),
                               lambda b_, h_, i, j: (b_, h_, i, 0)),
        out_shape=jax.ShapeDtypeStruct((b, h, sq_p, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        interpret=_interpret(),
    )(q_, k_, v_, do_, lse_, delta_)

    dq = dq[:, :, :sq]
    dk = dk[:, :, :sk]
    dv = dv[:, :, :sk]
    if group > 1:
        dk = dk.reshape(b, hk, group, sk, d).sum(axis=2)
        dv = dv.reshape(b, hk, group, sk, d).sum(axis=2)
    return dq, dk.astype(k.dtype), dv.astype(v.dtype)


# ---------------------------------------------------------------------------
# Public API (custom_vjp; [B, S, H, D] layout like the reference flash_attn)
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash(q, k, v, causal, scale):
    o, _ = _core_fwd(q, k, v, causal, scale)
    return o


def _core_fwd(q, k, v, causal, scale):
    bq, bk = _block_sizes(q.shape[2], k.shape[2])
    return _fwd(q, k, v, causal=causal, scale=scale, bq=bq, bk=bk)


def _flash_fwd(q, k, v, causal, scale):
    o, lse = _core_fwd(q, k, v, causal, scale)
    return o, (q, k, v, o, lse)


def _flash_bwd(causal, scale, res, g):
    q, k, v, o, lse = res
    bq, bk = _block_sizes(q.shape[2], k.shape[2])
    dq, dk, dv = _bwd(q, k, v, o, lse, g, causal=causal, scale=scale,
                      bq=bq, bk=bk)
    return dq, dk, dv


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, causal=False, scale=None):
    """q,k,v: [batch, seq, heads, head_dim] (kv heads may divide q heads).

    Returns [batch, seq, heads, head_dim]; differentiable (custom VJP with
    flash backward kernels).
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if q.shape[2] % k.shape[2] != 0:
        raise ValueError(
            f"q heads ({q.shape[2]}) must be a multiple of kv heads "
            f"({k.shape[2]}) for GQA flash attention")
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    o = _flash(qt, kt, vt, bool(causal), float(scale))
    return jnp.swapaxes(o, 1, 2)
