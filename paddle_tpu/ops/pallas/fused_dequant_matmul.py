"""Fused int4 dequant-matmul Pallas kernel for the serving step core.

The int4 stacked serving weights (PADDLE_TPU_DECODE_INT4_WEIGHTS, see
generation._stacked) pack two adjacent contracted-axis elements per int8
byte: the LOW nibble holds the even k index, the HIGH nibble the odd one,
both sign-extended 4-bit values in [-7, 7] scaled by a per-out-channel
absmax scale. A naive serving step would dequantize the whole packed
array back to fp before the dot — materializing the exact HBM copy the
quantization exists to avoid. This kernel keeps the weight packed end to
end: bytes stream from HBM, nibbles unpack in VMEM registers, and the
dot accumulates in fp32, so the weight-side HBM traffic of the step is
the packed byte stream plus the scale row (the
`fused_multi_transformer`-style weight-only fusion PAPER.md's Phi layer
names).

Nibble layout note: unpacking splits one sublane-axis byte into TWO
contracted elements, which Mosaic cannot interleave along the sublane
axis in-kernel. The wrapper therefore splits the ACTIVATION on the host
instead — `a_even = a[..., 0::2]`, `a_odd = a[..., 1::2]` — and the
kernel computes `a_even @ lo + a_odd @ hi`, which is exactly
`a @ unpacked` without any nibble shuffle.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["fused_dequant_matmul", "fused_dequant_matmul_is_supported"]

# fp32 sublane minimum for the activation block / output tile
_SUBLANE = 8


def _interpret() -> bool:
    """Pallas interpret mode everywhere but real TPU (same gate as
    decode_attention — CPU/GPU CI runs the kernel through the
    interpreter, so tests exercise the identical code path)."""
    return jax.default_backend() != "tpu"


def fused_dequant_matmul_is_supported(m, k, o) -> bool:
    """Whether the fused kernel can serve an [m, k] @ [k, o] contraction
    with the weight int4-packed along k. The pack itself only needs an
    even k; on real TPU the packed sublane axis additionally wants the
    int8 sublane minimum (K/2 % 32) and a lane-aligned out axis
    (O % 128). Interpret mode (CPU CI) has no tiling constraint."""
    if k % 2:
        return False
    if m <= 0 or o <= 0:
        return False
    if _interpret():
        return True
    return (k // 2) % 32 == 0 and o % 128 == 0


def _fused_dequant_mm_kernel(ae_ref, ao_ref, w_ref, s_ref, o_ref, acc_sc,
                             *, nk):
    ki = pl.program_id(0)

    @pl.when(ki == 0)
    def _():
        acc_sc[:] = jnp.zeros_like(acc_sc)

    w = w_ref[...]                                   # [bk2, O] int8 packed
    # sign-extending nibble unpack: arithmetic shifts on int8
    lo = jnp.right_shift(jnp.left_shift(w, 4), 4)    # even k
    hi = jnp.right_shift(w, 4)                       # odd k
    ae = ae_ref[...].astype(jnp.float32)
    ao = ao_ref[...].astype(jnp.float32)
    acc_sc[:] += (
        jax.lax.dot(ae, lo.astype(jnp.float32),
                    preferred_element_type=jnp.float32)
        + jax.lax.dot(ao, hi.astype(jnp.float32),
                      preferred_element_type=jnp.float32)
    )

    @pl.when(ki == nk - 1)
    def _():
        o_ref[...] = (acc_sc[:] * s_ref[...]).astype(o_ref.dtype)


def fused_dequant_matmul(a, w_packed, scales, *, out_dtype=None):
    """`a @ dequant(w_packed, scales)` without materializing the
    dequantized weight.

    a:        [..., K] activations (any float dtype; compute is fp32)
    w_packed: [K // 2, O] int8 — low nibble = even k, high nibble =
              odd k, sign-extended int4 in [-7, 7]
    scales:   [O] or [1, O] fp per-out-channel absmax scales
    returns:  [..., O] in ``out_dtype`` (default: a.dtype)
    """
    if w_packed.dtype != jnp.int8:
        raise ValueError("fused_dequant_matmul: packed weight must be int8")
    k = a.shape[-1]
    k2, o = w_packed.shape
    if k != 2 * k2:
        raise ValueError(
            f"fused_dequant_matmul: activation K={k} does not match "
            f"packed K/2={k2}")
    s2 = jnp.reshape(scales, (1, o)).astype(jnp.float32)
    if out_dtype is None:
        out_dtype = a.dtype

    lead = a.shape[:-1]
    a2 = jnp.reshape(a, (-1, k))
    m = a2.shape[0]
    # pad the token axis up to the fp32 sublane minimum
    mp = max(_SUBLANE, -(-m // _SUBLANE) * _SUBLANE)
    if mp != m:
        a2 = jnp.pad(a2, ((0, mp - m), (0, 0)))
    # host-side even/odd split — see module docstring
    a_even = a2[:, 0::2]                             # [mp, K2]
    a_odd = a2[:, 1::2]                              # [mp, K2]

    bk2 = k2
    for cand in (256, 128, 64, 32):
        if k2 > cand and k2 % cand == 0:
            bk2 = cand
            break
    nk = k2 // bk2

    out = pl.pallas_call(
        functools.partial(_fused_dequant_mm_kernel, nk=nk),
        grid=(nk,),
        in_specs=[
            pl.BlockSpec((mp, bk2), lambda ki: (0, ki)),
            pl.BlockSpec((mp, bk2), lambda ki: (0, ki)),
            pl.BlockSpec((bk2, o), lambda ki: (ki, 0)),
            pl.BlockSpec((1, o), lambda ki: (0, 0)),
        ],
        out_specs=pl.BlockSpec((mp, o), lambda ki: (0, 0)),
        scratch_shapes=[pltpu.VMEM((mp, o), jnp.float32)],
        out_shape=jax.ShapeDtypeStruct((mp, o), out_dtype),
        interpret=_interpret(),
    )(a_even, a_odd, w_packed, s2)
    if mp != m:
        out = out[:m]
    return jnp.reshape(out, lead + (o,))
