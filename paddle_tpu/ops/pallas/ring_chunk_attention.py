"""Blockwise (o, lse) attention chunk for ring attention, as Pallas kernels.

Capability parity: the per-step compute of ring-flash-attention (the
Paddle-ecosystem long-context variant SURVEY §5.7 names; upstream anchor
`sep` degree in python/paddle/distributed/fleet/base/topology.py). The
inter-chip ring (ppermute schedule, lse merge, remat) lives in
paddle_tpu/parallel/context_parallel.py — THIS module is the on-chip leg:
one Q chunk against one visiting KV chunk, returning the normalized chunk
output AND its per-row log-sum-exp so chunks merge exactly.

Differences from flash_attention.py (why a separate module, not a flag):

* the causal boundary is a TRACED offset, not a static one — in the ring,
  the same compiled kernel serves every (my_rank - src_rank) diagonal:
  row r attends col c iff c <= r + offset. offset >= Sk-1 degenerates to
  full attention, offset < 0 shifts the diagonal (zigzag schedules),
  offset <= -Sq masks everything (lse -> -inf rows that merge as zero
  weight). It rides in SMEM; the block-skip predicate stays traced.
* lse is a first-class OUTPUT with a gradient: ring merges weight chunks
  by lse, so the chunk vjp receives (dO, dlse). The lse cotangent folds
  into the standard FA backward exactly — d s = P∘(dP - delta + dlse)
  row-broadcast — so the backward kernels take delta_eff = rowsum(dO∘O)
  - dlse and are otherwise the textbook split dKV/dQ pair.
* no dropout (the reference's CP stack does not thread attention dropout
  through the ring either); GQA via the same index_map trick.

Layout: [B, H, S, D] (kernel layout; context_parallel transposes).
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .flash_attention import NEG_INF, _block_sizes, _interpret

__all__ = ["ring_chunk_attention", "is_supported"]


def is_supported(q_shape, k_shape, dtype) -> bool:
    if len(q_shape) != 4 or len(k_shape) != 4:
        return False
    if q_shape[-1] > 256:
        return False
    if q_shape[1] % k_shape[1] != 0:   # GQA: kv_heads | q_heads ([B,H,S,D])
        return False
    return jnp.dtype(dtype) in (jnp.float32, jnp.bfloat16, jnp.float16)


def _fwd_kernel(off_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
                acc_sc, m_sc, l_sc, *, scale, sq, sk, bq, bk):
    off = off_ref[0]
    qi = pl.program_id(2)
    ki = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ki == 0)
    def _():
        m_sc[:] = jnp.full_like(m_sc, NEG_INF)
        l_sc[:] = jnp.zeros_like(l_sc)
        acc_sc[:] = jnp.zeros_like(acc_sc)

    q_start = qi * bq
    k_start = ki * bk
    # block-skip on the traced diagonal: any row of this q block may see
    # the first col of this k block only if k_start <= q_end + off
    run = q_start + bq - 1 + off >= k_start

    @pl.when(run)
    def _():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        rows = q_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        cols = k_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        mask = (cols < sk) & (cols <= rows + off)
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_sc[:]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.where(mask, jnp.exp(s - m_new), 0.0)
        l_sc[:] = l_sc[:] * alpha + jnp.sum(p, axis=1, keepdims=True)
        m_sc[:] = m_new
        v = v_ref[0, 0]
        pv = jax.lax.dot_general(p.astype(v.dtype), v,
                                 (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        acc_sc[:] = acc_sc[:] * alpha + pv

    @pl.when(ki == nk - 1)
    def _():
        l = l_sc[:]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_sc[:] / l_safe).astype(o_ref.dtype)
        # fully-masked rows keep lse ~ NEG_INF so the ring merge gives
        # them zero weight (matches the composite _chunk_attn contract)
        lse_ref[0, 0] = jnp.where(l == 0.0, NEG_INF, m_sc[:] + jnp.log(l_safe))


def _bwd_dkv_kernel(off_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                    delta_ref, dk_ref, dv_ref, dk_sc, dv_sc,
                    *, scale, sq, sk, bq, bk):
    off = off_ref[0]
    ki = pl.program_id(2)
    qi = pl.program_id(3)
    nq = pl.num_programs(3)

    @pl.when(qi == 0)
    def _():
        dk_sc[:] = jnp.zeros_like(dk_sc)
        dv_sc[:] = jnp.zeros_like(dv_sc)

    q_start = qi * bq
    k_start = ki * bk
    run = q_start + bq - 1 + off >= k_start

    @pl.when(run)
    def _():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0]
        delta = delta_ref[0, 0]

        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        rows = q_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        cols = k_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        mask = (cols < sk) & (rows < sq) & (cols <= rows + off)
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)

        dv_sc[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dk_sc[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qi == nq - 1)
    def _():
        dk_ref[0, 0] = dk_sc[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_sc[:].astype(dv_ref.dtype)


def _bwd_dq_kernel(off_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                   delta_ref, dq_ref, dq_sc, *, scale, sq, sk, bq, bk):
    off = off_ref[0]
    qi = pl.program_id(2)
    ki = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ki == 0)
    def _():
        dq_sc[:] = jnp.zeros_like(dq_sc)

    q_start = qi * bq
    k_start = ki * bk
    run = q_start + bq - 1 + off >= k_start

    @pl.when(run)
    def _():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0]
        delta = delta_ref[0, 0]

        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        rows = q_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        cols = k_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        mask = (cols < sk) & (rows < sq) & (cols <= rows + off)
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dq_sc[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _():
        dq_ref[0, 0] = dq_sc[:].astype(dq_ref.dtype)


def _sds(shape, dtype, *likes):
    """ShapeDtypeStruct carrying the union of the inputs' varying-mesh-axes
    (vma) type — required when the kernel runs INSIDE shard_map (jax>=0.9
    check_vma: out_shape.vma must not be None there). Outside shard_map
    the inputs' vma is empty/absent and a plain struct is returned."""
    vma = frozenset()
    have = False
    for a in likes:
        v = getattr(jax.typeof(a), "vma", None)
        if v is not None:
            have = True
            vma |= frozenset(v)
    if have and vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def _pad_s(x, target):
    s = x.shape[2]
    return jnp.pad(x, ((0, 0), (0, 0), (0, target - s), (0, 0))) \
        if target != s else x


def _specs(bq, bk, d, group):
    qspec = pl.BlockSpec((1, 1, bq, d), lambda b_, h_, i, j: (b_, h_, i, 0))
    kspec = pl.BlockSpec((1, 1, bk, d),
                         lambda b_, h_, i, j, g=group: (b_, h_ // g, j, 0))
    rowspec = pl.BlockSpec((1, 1, bq, 1), lambda b_, h_, i, j: (b_, h_, i, 0))
    return qspec, kspec, rowspec


def _fwd(q, k, v, offset, scale):
    b, h, sq, d = q.shape
    hk = k.shape[1]
    group = h // hk
    sk = k.shape[2]
    bq, bk = _block_sizes(sq, sk)
    sq_p = math.ceil(sq / bq) * bq
    sk_p = math.ceil(sk / bk) * bk
    q_ = _pad_s(q, sq_p)
    k_, v_ = _pad_s(k, sk_p), _pad_s(v, sk_p)
    qspec, kspec, _ = _specs(bq, bk, d, group)
    o, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, sq=sq, sk=sk,
                          bq=bq, bk=bk),
        grid=(b, h, sq_p // bq, sk_p // bk),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM), qspec, kspec,
                  kspec],
        out_specs=[qspec,
                   pl.BlockSpec((1, 1, bq, 1),
                                lambda b_, h_, i, j: (b_, h_, i, 0))],
        out_shape=[
            _sds((b, h, sq_p, d), q.dtype, q_, k_, v_),
            _sds((b, h, sq_p, 1), jnp.float32, q_, k_, v_),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, d), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
        ],
        interpret=_interpret(),
    )(jnp.reshape(offset.astype(jnp.int32), (1,)), q_, k_, v_)
    return o[:, :, :sq], lse[:, :, :sq, 0]        # lse: [B, H, Sq]


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def _ring_chunk(q, k, v, offset, scale):
    return _fwd(q, k, v, offset, scale)


def _vjp_fwd(q, k, v, offset, scale):
    o, lse = _fwd(q, k, v, offset, scale)
    return (o, lse), (q, k, v, o, lse, offset)


def _vjp_bwd(scale, res, cts):
    do, dlse = cts
    q, k, v, o, lse, offset = res
    b, h, sq, d = q.shape
    hk = k.shape[1]
    group = h // hk
    sk = k.shape[2]
    bq, bk = _block_sizes(sq, sk)
    sq_p = math.ceil(sq / bq) * bq
    sk_p = math.ceil(sk / bk) * bk

    # the lse cotangent folds into the delta row-broadcast exactly:
    # ds = P∘(dP - rowsum(dO∘O) + dlse)
    delta_eff = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                        axis=-1, keepdims=True) - dlse[..., None]

    q_, do_ = _pad_s(q, sq_p), _pad_s(do, sq_p)
    k_, v_ = _pad_s(k, sk_p), _pad_s(v, sk_p)
    lse_ = _pad_s(lse[..., None], sq_p)
    delta_ = _pad_s(delta_eff, sq_p)
    off = jnp.reshape(offset.astype(jnp.int32), (1,))

    kvq = pl.BlockSpec((1, 1, bq, d), lambda b_, h_, j, i: (b_, h_, i, 0))
    kvk = pl.BlockSpec((1, 1, bk, d),
                       lambda b_, h_, j, i, g=group: (b_, h_ // g, j, 0))
    kvrow = pl.BlockSpec((1, 1, bq, 1), lambda b_, h_, j, i: (b_, h_, i, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, sq=sq, sk=sk,
                          bq=bq, bk=bk),
        grid=(b, h, sk_p // bk, sq_p // bq),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  kvq, kvk, kvk, kvq, kvrow, kvrow],
        out_specs=[
            pl.BlockSpec((1, 1, bk, d), lambda b_, h_, j, i: (b_, h_, j, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda b_, h_, j, i: (b_, h_, j, 0)),
        ],
        out_shape=[
            _sds((b, h, sk_p, d), jnp.float32, q_, k_, v_, do_),
            _sds((b, h, sk_p, d), jnp.float32, q_, k_, v_, do_),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, d), jnp.float32),
            pltpu.VMEM((bk, d), jnp.float32),
        ],
        interpret=_interpret(),
    )(off, q_, k_, v_, do_, lse_, delta_)

    qspec, kspec, rowspec = _specs(bq, bk, d, group)
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, sq=sq, sk=sk,
                          bq=bq, bk=bk),
        grid=(b, h, sq_p // bq, sk_p // bk),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  qspec, kspec, kspec, qspec, rowspec, rowspec],
        out_specs=qspec,
        out_shape=_sds((b, h, sq_p, d), q.dtype, q_, k_, v_, do_),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        interpret=_interpret(),
    )(off, q_, k_, v_, do_, lse_, delta_)

    dq = dq[:, :, :sq]
    dk = dk[:, :, :sk]
    dv = dv[:, :, :sk]
    if group > 1:
        dk = dk.reshape(b, hk, group, sk, d).sum(axis=2)
        dv = dv.reshape(b, hk, group, sk, d).sum(axis=2)
    return dq, dk.astype(k.dtype), dv.astype(v.dtype), None


_ring_chunk.defvjp(_vjp_fwd, _vjp_bwd)


def ring_chunk_attention(q, k, v, offset, scale=None):
    """One ring step: normalized chunk attention + lse, offset-masked.

    q: [B, H, Sq, D]; k, v: [B, Hk, Sk, D] (GQA: Hk | H); offset: traced
    int32 scalar — row r attends col c iff c <= r + offset (offset >=
    Sk-1 == full attention, offset <= -Sq == fully masked). Returns
    (o [B, H, Sq, D] in q.dtype, lse [B, H, Sq] fp32). Differentiable,
    including through lse (ring-merge weights).
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    return _ring_chunk(q, k, v, jnp.asarray(offset, jnp.int32),
                       float(scale))
