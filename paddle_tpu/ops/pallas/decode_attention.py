"""Flash-decode attention against a KV cache, as a TPU Pallas kernel.

Capability parity: the attention inner loop of
paddle/fluid/operators/fused/fused_multi_transformer_op.cu ::
FusedMultiTransformerOp (masked decode attention over the growing KV cache,
cuBLASLt + fmha_ref.h in the reference). NOT a port: this is the
online-softmax flash layout for TPU — the query tile (decode: a handful of
rows, padded to the 8-row sublane minimum) stays resident in VMEM while KV
cache blocks stream through, with per-batch valid-length masking read from
SMEM so one compiled kernel serves every step of the autoregressive loop
(static shapes: cache is a fixed ring buffer, the length is data).

q: [B, Sq, H, D] (Sq small — 1 for greedy decode), cache: [B, Smax, Hk, D]
(GQA: Hk | H), cache_lens: [B] int32 valid prefix lengths. New tokens at
positions cache_lens..cache_lens+Sq-1 attend causally among themselves and
fully to the cache prefix. Forward-only (inference).

The `cache_lens < Smax` invariant (write kernels clamp a full row's write
to a drop) has FIVE clients: the serving engine's eviction-as-data slot
reuse, the submit-time `prompt + max_new_tokens <= Smax` bound, the
prefix cache's block-granular adopt copy (inference/prefix_cache.py) —
adopted block writes land at positions < plen <= Smax - max_new_tokens
with the pow-2 ladder tail masked out of bounds and dropped, so a
block-granular splat can never push a row to (or past) Smax either —
the speculative-decoding verify step (inference/spec_decode.py +
generation._build_verify_core): its K+1 block writes at positions
lens..lens+K are per-position masked to `lens + j < Smax` (masked
positions scatter out of bounds and drop), and drafting caps K at the
row's remaining budget, so lens + dlen <= prompt + max_new - 1 < Smax —
and the PAGED write path (inference/paged_kv.py + the paged branches in
generation._build_step_core): every K/V write resolves position t to
(block_tables[b, t // Bt], t % Bt), a masked row's position Smax maps
to table index Smax/Bt which is re-pointed at the OUT-OF-BOUNDS
sentinel block `num_blocks` and dropped, and an unmapped table entry
holds the same sentinel — so a write past a slot's mapped blocks (or
any masked write) lands nowhere, exactly the dense clamp's discipline.
Smax % Bt == 0 is asserted at BlockPool construction with a clear
error, so the table arithmetic can never itself gather out of bounds.

A SIXTH client rides the verify step's discipline: the token-budget
scheduler's budget core (generation._build_budget_core, serving's
chunked prefill + decode packing) writes per-row SEGMENTS at positions
lens..lens+seg-1 through the same spec_hidden write-masked path —
validity is (col < seg) & (pos < Smax), decode segments stay under the
submit-time budget exactly like drafts, and prefill segments stay
under plen <= Smax - max_new by construction.

The SEVENTH client is the FLAT budget core
(generation._build_flat_budget_core, serving's
PADDLE_SERVING_FLAT_BUDGET token-flattened dispatch): every token of
the ragged [T] stream scatters to (slot[t], pos[t]) — a padding token
carries the slot SENTINEL B, which resolves to batch index B (dense
ring: out of bounds on the batch axis) or to the pool's sentinel
block `num_blocks` (paged), so mode="drop" skips it; real tokens
inherit the submit-time `prompt + max_new <= Smax` bound through the
packer (a segment's positions are lens..lens+seg-1, exactly the
budget core's window), so `pos < Smax` holds for every landed write.
Both flat READ kernels consume that discipline: the fp flavor
(decode_attention_paged_flat) and the int8 flavor
(decode_attention_paged_flat_i8, which dequants the quantized pool +
its mirrored scales in kernel) address blocks through the same
chunk-clamped table translation, so every position a flat chunk can
attend was landed under the packer's `pos < Smax` bound.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["decode_attention", "decode_attention_stacked",
           "decode_attention_stacked_i8", "decode_attention_stacked_write",
           "decode_attention_stacked_i8_write",
           "decode_attention_paged", "decode_attention_paged_i8",
           "decode_attention_paged_flat", "decode_attention_paged_flat_i8",
           "is_supported", "stacked_is_supported",
           "stacked_i8_is_supported", "stacked_write_is_supported",
           "stacked_i8_write_is_supported", "paged_is_supported",
           "paged_i8_is_supported", "paged_flat_is_supported",
           "paged_flat_i8_is_supported", "FLAT_CHUNK"]

NEG_INF = -1e30


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def is_supported(q_shape, cache_shape, dtype) -> bool:
    if len(q_shape) != 4 or len(cache_shape) != 4:
        return False
    if q_shape[-1] > 256 or q_shape[1] > 128:
        return False
    if q_shape[2] % cache_shape[2] != 0:
        return False
    return jnp.dtype(dtype) in (jnp.float32, jnp.bfloat16, jnp.float16)


def _online_softmax_block(q, k, v, n_valid, k_start, acc_sc, m_sc, l_sc,
                          *, scale, sq, bq, bk,
                          k_col_scale=None, v_col_scale=None,
                          exclusive=False):
    """One KV block's update of the running (acc, m, l) flash state —
    shared by the per-layer and stacked-cache kernels (the only thing
    that differs between them is how refs address their blocks).

    k_col_scale / v_col_scale ([1, bk] fp32, optional) are the int8
    cache's per-row dequant scales applied COLUMN-wise to the score
    matrix instead of row-wise to k/v: scales factor out of the dots
    (q·(c·k) == c·(q·k), p·(c·v) == (c·p)·v), and a [1, bk] lane-major
    operand is a Mosaic-legal layout whereas the previous [bk, 1]
    (lane dim 1) scale block was a known compile risk on real TPUs."""
    # dots in input dtype (bf16 MXU full rate), f32 accumulation/softmax
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    if k_col_scale is not None:
        s = s * k_col_scale          # [bq, bk] * [1, bk]
    rows = jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)  # q row
    cols = k_start + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    # row r is the token at global position n_valid + r: attends the
    # prefix (cols < n_valid) and itself/earlier new tokens (causal).
    # exclusive=True: prefix ONLY (cols < n_valid) — the write-kernel's
    # cache blocks hold stale bytes at the new token's slot; its
    # self-attention term enters via the seeded running stats instead.
    if exclusive:
        mask = (rows < sq) & (cols < n_valid)
    else:
        mask = (rows < sq) & (cols <= n_valid + rows)
    s = jnp.where(mask, s, NEG_INF)

    m_prev = m_sc[:]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
    alpha = jnp.exp(m_prev - m_new)
    p = jnp.where(mask, jnp.exp(s - m_new), 0.0)
    l_sc[:] = l_sc[:] * alpha + jnp.sum(p, axis=1, keepdims=True)
    m_sc[:] = m_new
    if v_col_scale is not None:
        p = p * v_col_scale          # fold v dequant into p (fp32)
    acc_sc[:] = acc_sc[:] * alpha + jax.lax.dot_general(
        p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


def _kernel(len_ref, q_ref, k_ref, v_ref, o_ref, acc_sc, m_sc, l_sc,
            *, scale, sq, bq, bk):
    ki = pl.program_id(2)
    nk = pl.num_programs(2)
    n_valid = len_ref[pl.program_id(0)]   # cache prefix length for this batch

    @pl.when(ki == 0)
    def _():
        m_sc[:] = jnp.full_like(m_sc, NEG_INF)
        l_sc[:] = jnp.zeros_like(l_sc)
        acc_sc[:] = jnp.zeros_like(acc_sc)

    k_start = ki * bk
    # skip blocks entirely past the last attendable position
    run = k_start < n_valid + sq

    @pl.when(run)
    def _():
        _online_softmax_block(q_ref[0, 0], k_ref[0, 0], v_ref[0, 0],
                              n_valid, k_start, acc_sc, m_sc, l_sc,
                              scale=scale, sq=sq, bq=bq, bk=bk)

    @pl.when(ki == nk - 1)
    def _():
        l = l_sc[:]
        o_ref[0, 0] = (acc_sc[:] /
                       jnp.where(l == 0.0, 1.0, l)).astype(o_ref.dtype)


def decode_attention(q, k_cache, v_cache, cache_lens, scale=None):
    """Returns [B, Sq, H, D] attention of the new queries over cache + self.

    The caches hold the prefix in positions [0, cache_lens[b]) and must
    already contain the new tokens' K/V at positions
    [cache_lens[b], cache_lens[b] + Sq) (standard write-then-attend decode
    step order).
    """
    qt = jnp.swapaxes(q, 1, 2)                       # [B, H, Sq, D]
    kt = jnp.swapaxes(k_cache, 1, 2)                 # [B, Hk, Smax, D]
    vt = jnp.swapaxes(v_cache, 1, 2)
    return jnp.swapaxes(
        decode_attention_bhsd(qt, kt, vt, cache_lens, scale), 1, 2)


def decode_attention_bhsd(qt, kt, vt, cache_lens, scale=None):
    """Same as decode_attention but in kernel layout [B, H, S, D] in AND
    out — the compiled multi-layer decode loop stores its KV cache in this
    layout so no per-step full-cache transpose is materialized."""
    b, h, sq, d = qt.shape
    smax = kt.shape[2]
    hk = kt.shape[1]
    group = h // hk
    if scale is None:
        scale = d ** -0.5
    # in-kernel dots run in the operand dtype: harmonize a mixed-precision
    # cache with the query dtype (bf16 q + f32 cache was accepted before
    # the bf16-dot change and must keep working)
    if kt.dtype != qt.dtype:
        kt = kt.astype(qt.dtype)
    if vt.dtype != qt.dtype:
        vt = vt.astype(qt.dtype)

    bq = max(8, 1 << (sq - 1).bit_length()) if sq < 128 else 128
    bk = min(256, smax) if smax % 256 == 0 or smax < 256 else 128
    sk_p = math.ceil(smax / bk) * bk
    if sk_p != smax:
        kt = jnp.pad(kt, ((0, 0), (0, 0), (0, sk_p - smax), (0, 0)))
        vt = jnp.pad(vt, ((0, 0), (0, 0), (0, sk_p - smax), (0, 0)))
    if bq != sq:
        qt = jnp.pad(qt, ((0, 0), (0, 0), (0, bq - sq), (0, 0)))

    lens = cache_lens.astype(jnp.int32).reshape(b)
    grid = (b, h, sk_p // bk)

    # Same last-valid-block clamp as the stacked kernels (see
    # _stacked_setup): blocks past n_valid + sq re-address the last valid
    # block so the pipeline elides their HBM copies — without it, a long
    # ring buffer with a short prefix streams mostly padding. lens rides
    # in as a scalar-prefetch operand so the index maps can read it.
    def _cl(j, len_r, b_):
        return jnp.minimum(j, (len_r[b_] + sq - 1) // bk)

    out = pl.pallas_call(
        functools.partial(_kernel, scale=float(scale), sq=sq, bq=bq, bk=bk),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, 1, bq, d),
                             lambda b_, h_, j, len_r: (b_, h_, 0, 0)),
                pl.BlockSpec((1, 1, bk, d),
                             lambda b_, h_, j, len_r, g=group:
                             (b_, h_ // g, _cl(j, len_r, b_), 0)),
                pl.BlockSpec((1, 1, bk, d),
                             lambda b_, h_, j, len_r, g=group:
                             (b_, h_ // g, _cl(j, len_r, b_), 0)),
            ],
            out_specs=pl.BlockSpec((1, 1, bq, d),
                                   lambda b_, h_, j, len_r: (b_, h_, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((bq, d), jnp.float32),
                pltpu.VMEM((bq, 1), jnp.float32),
                pltpu.VMEM((bq, 1), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((b, h, bq, d), qt.dtype),
        interpret=_interpret(),
    )(lens, qt, kt, vt)
    return out[:, :, :sq]


# ---------------------------------------------------------------------------
# Stacked-cache variant: the multi-layer decode loop's KV cache is ONE
# [L, 2, B, Hk, Smax, D] buffer carried through the layer scan. Slicing
# caches[l] on the host side materializes a full per-layer copy as the
# kernel operand every (token, layer); here the LAYER INDEX rides in as a
# scalar-prefetch argument and the BlockSpec index_map addresses layer l's
# blocks directly in the stacked buffer — zero-copy reads, which is what
# makes the carry-with-in-place-update cache design actually bandwidth-
# minimal (reference anchor: fused_multi_transformer_op.cu's per-step
# in-place cache write).
# ---------------------------------------------------------------------------

def _stacked_setup(qt, hk, smax, group):
    """Shared host-side setup for the stacked-cache kernels: block sizes,
    q padding, grid, and the layer/kv-addressed index maps. ONE owner for
    the tiling rules so the fp and int8 wrappers cannot diverge."""
    b, h, sq, d = qt.shape
    bq = max(8, 1 << (sq - 1).bit_length()) if sq < 128 else 128
    if smax % 256 == 0:
        bk = 256
    elif smax % 128 == 0:
        bk = 128
    else:
        raise ValueError(
            f"stacked decode kernels: Smax {smax} must be a multiple of "
            "128 (pad the ring buffer at init, not per call)")
    if bq != sq:
        qt = jnp.pad(qt, ((0, 0), (0, 0), (0, bq - sq), (0, 0)))
    grid = (b, h, smax // bk)

    # Clamp the sequence-block coordinate at this batch row's LAST valid
    # block. The kernel body already pl.when-skips compute for blocks past
    # n_valid + sq, but a monotone index map would still DMA every one of
    # the Smax//bk blocks from HBM — at serving shapes (short prefix,
    # Smax-sized ring) that is almost all padding traffic and decode is
    # bandwidth-bound. With the clamp, every grid step past the last valid
    # block re-addresses that same block, and the Pallas pipeline elides
    # copies whose block index is unchanged — only the valid prefix is
    # ever streamed (splash/paged-attention style).
    def _clamp(j, len_r, b_):
        return jnp.minimum(j, (len_r[b_] + sq - 1) // bk)

    # ONE kv-block operand: the (1, 2, 1, 1, bk, d) block spans BOTH the
    # K and V planes of the kv axis, so the cache rides in as a single
    # operand. Passing the same buffer twice (separate K and V specs) was
    # observed to defeat XLA's in-place aliasing of the scan-carried
    # cache update — the compiled decode step materialized TWO full-cache
    # copies per layer (HLO inspected 2026-08-01).
    kvidx = lambda b_, h_, j, lay_r, len_r, g=group: (  # noqa: E731
        lay_r[0], 0, b_, h_ // g, _clamp(j, len_r, b_), 0)
    qidx = lambda b_, h_, j, lay_r, len_r: (b_, h_, 0, 0)  # noqa: E731
    return qt, bq, bk, grid, kvidx, qidx, _clamp


def stacked_i8_is_supported(q_shape, caches_shape, dtype) -> bool:
    """Support predicate for decode_attention_stacked_i8: same layout and
    tiling rules as the fp stacked kernel, cache dtype is int8 by
    construction (scales ride separately), compute dtype is the query's."""
    return stacked_is_supported(q_shape, caches_shape, dtype,
                                cache_dtype=None)


def stacked_is_supported(q_shape, caches_shape, dtype,
                         cache_dtype=None) -> bool:
    """caches: [L, 2, B, Hk, Smax, D]; q: [B, Sq, H, D] (layout as
    decode_attention). The Smax axis must tile exactly (padding the
    stacked buffer would copy all layers), and q/cache dtypes must MATCH:
    unlike decode_attention_bhsd (which upcasts the cache to the query
    dtype), upcasting the stacked buffer would copy every layer — mixed
    precision goes to the unstacked or dense path instead."""
    if len(q_shape) != 4 or len(caches_shape) != 6:
        return False
    if q_shape[-1] > 256 or q_shape[1] > 128:
        return False
    if q_shape[2] % caches_shape[3] != 0:
        return False
    smax = caches_shape[4]
    if not any(smax % bk == 0 for bk in (256, 128)):
        return False
    if cache_dtype is not None and jnp.dtype(cache_dtype) != jnp.dtype(dtype):
        return False
    return jnp.dtype(dtype) in (jnp.float32, jnp.bfloat16, jnp.float16)


def _stacked_kernel(lay_ref, len_ref, q_ref, kv_ref, o_ref,
                    acc_sc, m_sc, l_sc, *, scale, sq, bq, bk):
    # same flash math as _kernel (shared _online_softmax_block); the
    # (1, 2, 1, 1, bk, d) kv block comes out of the stacked buffer
    # addressed by the prefetched layer scalar — K is plane 0, V plane 1
    ki = pl.program_id(2)
    nk = pl.num_programs(2)
    n_valid = len_ref[pl.program_id(0)]

    @pl.when(ki == 0)
    def _():
        m_sc[:] = jnp.full_like(m_sc, NEG_INF)
        l_sc[:] = jnp.zeros_like(l_sc)
        acc_sc[:] = jnp.zeros_like(acc_sc)

    k_start = ki * bk
    run = k_start < n_valid + sq

    @pl.when(run)
    def _():
        _online_softmax_block(q_ref[0, 0], kv_ref[0, 0, 0, 0],
                              kv_ref[0, 1, 0, 0], n_valid, k_start,
                              acc_sc, m_sc, l_sc,
                              scale=scale, sq=sq, bq=bq, bk=bk)

    @pl.when(ki == nk - 1)
    def _():
        l = l_sc[:]
        o_ref[0, 0] = (acc_sc[:] /
                       jnp.where(l == 0.0, 1.0, l)).astype(o_ref.dtype)


def decode_attention_stacked(qt, caches, layer, cache_lens, scale=None):
    """qt: [B, H, Sq, D] (kernel layout); caches: [L, 2, B, Hk, Smax, D]
    (kv axis: 0 = K, 1 = V); layer: scalar int32 (traced OK — it is a
    scalar-prefetch operand); cache_lens: [B] int32. Returns
    [B, H, Sq, D] — attention of the new queries over layer `layer`'s
    cache prefix + the just-written new positions."""
    b, h, sq, d = qt.shape
    hk, smax = caches.shape[3], caches.shape[4]
    group = h // hk
    if scale is None:
        scale = d ** -0.5
    if caches.dtype != qt.dtype:
        # downcasting q would silently lose dot/softmax precision and
        # upcasting the stacked cache would copy every layer — the mixed-
        # precision cases belong on decode_attention_bhsd (which upcasts
        # the single-layer cache) or the dense path
        raise ValueError(
            f"decode_attention_stacked: query dtype {qt.dtype} != cache "
            f"dtype {caches.dtype}; gate with stacked_is_supported(..., "
            "cache_dtype=...) and use the unstacked/dense path instead")
    out_dtype = qt.dtype

    qt, bq, bk, grid, kvidx, qidx, _ = _stacked_setup(qt, hk, smax,
                                                      group)
    lens = cache_lens.astype(jnp.int32).reshape(b)
    lay = jnp.asarray(layer, jnp.int32).reshape(1)
    out = pl.pallas_call(
        functools.partial(_stacked_kernel, scale=float(scale), sq=sq,
                          bq=bq, bk=bk),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, 1, bq, d), qidx),
                pl.BlockSpec((1, 2, 1, 1, bk, d), kvidx),
            ],
            out_specs=pl.BlockSpec((1, 1, bq, d), qidx),
            scratch_shapes=[
                pltpu.VMEM((bq, d), jnp.float32),
                pltpu.VMEM((bq, 1), jnp.float32),
                pltpu.VMEM((bq, 1), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((b, h, bq, d), caches.dtype),
        interpret=_interpret(),
    )(lay, lens, qt, caches)
    return out[:, :, :sq].astype(out_dtype)


# ---------------------------------------------------------------------------
# int8-quantized stacked cache: the serving-side cache-quant mode of
# fused_multi_transformer_op.cu (cache_kv int8). Decode is HBM-bandwidth
# bound — an int8 cache halves the bytes the kernel streams per token.
# K/V rows are quantized per (layer, kv, batch, head, position) with an
# fp32 absmax scale; the kernel dequantizes blocks in VMEM right before
# the dots (which still run in the query dtype on the MXU).
# ---------------------------------------------------------------------------

def _stacked_i8_kernel(lay_ref, len_ref, q_ref, kv_ref, kvs_ref,
                       o_ref, acc_sc, m_sc, l_sc,
                       *, scale, sq, bq, bk):
    ki = pl.program_id(2)
    nk = pl.num_programs(2)
    n_valid = len_ref[pl.program_id(0)]

    @pl.when(ki == 0)
    def _():
        m_sc[:] = jnp.full_like(m_sc, NEG_INF)
        l_sc[:] = jnp.zeros_like(l_sc)
        acc_sc[:] = jnp.zeros_like(acc_sc)

    k_start = ki * bk
    run = k_start < n_valid + sq

    @pl.when(run)
    def _():
        q = q_ref[0, 0]                                     # [bq, d]
        # int8 -> compute dtype conversion only (values in [-127, 127]
        # are exact in bf16); the per-row dequant scales are applied
        # column-wise to the SCORE matrix inside the softmax block,
        # where they arrive as Mosaic-legal [1, bk] lane-major tiles.
        # Like the fp kernel, cache and scales each ride in as ONE
        # operand whose block spans both kv planes (single-pass buffers
        # keep the scan-carry update aliasable).
        k = kv_ref[0, 0, 0, 0].astype(q.dtype)              # [bk, d]
        v = kv_ref[0, 1, 0, 0].astype(q.dtype)
        _online_softmax_block(q, k, v, n_valid, k_start,
                              acc_sc, m_sc, l_sc,
                              scale=scale, sq=sq, bq=bq, bk=bk,
                              k_col_scale=kvs_ref[0, 0, 0, 0],  # [1, bk]
                              v_col_scale=kvs_ref[0, 1, 0, 0])

    @pl.when(ki == nk - 1)
    def _():
        l = l_sc[:]
        o_ref[0, 0] = (acc_sc[:] /
                       jnp.where(l == 0.0, 1.0, l)).astype(o_ref.dtype)


def decode_attention_stacked_i8(qt, caches_i8, cache_scales, layer,
                                cache_lens, scale=None):
    """qt: [B, H, Sq, D] (query dtype = compute dtype); caches_i8:
    [L, 2, B, Hk, Smax, D] int8; cache_scales: [L, 2, B, Hk, 1, Smax]
    fp32 per-row absmax scales (positions on the LAST axis so scale
    blocks are [1, bk] lane-major — Mosaic-legal, unlike a [bk, 1]
    lane-1 block); layer: scalar int32 (scalar-prefetch).
    Returns [B, H, Sq, D] in the query dtype."""
    b, h, sq, d = qt.shape
    hk, smax = caches_i8.shape[3], caches_i8.shape[4]
    group = h // hk
    if scale is None:
        scale = d ** -0.5
    if caches_i8.dtype != jnp.int8:
        raise ValueError("decode_attention_stacked_i8: cache must be int8")

    if cache_scales.shape != caches_i8.shape[:4] + (1, smax):
        raise ValueError(
            "decode_attention_stacked_i8: scales must be "
            f"[L, 2, B, Hk, 1, Smax], got {cache_scales.shape}")

    out_dtype = qt.dtype
    qt, bq, bk, grid, kvidx, qidx, clamp = _stacked_setup(
        qt, hk, smax, group)
    kvsidx = lambda b_, h_, j, lay_r, len_r, g=group: (  # noqa: E731
        lay_r[0], 0, b_, h_ // g, 0, clamp(j, len_r, b_))
    lens = cache_lens.astype(jnp.int32).reshape(b)
    lay = jnp.asarray(layer, jnp.int32).reshape(1)
    out = pl.pallas_call(
        functools.partial(_stacked_i8_kernel, scale=float(scale), sq=sq,
                          bq=bq, bk=bk),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, 1, bq, d), qidx),
                pl.BlockSpec((1, 2, 1, 1, bk, d), kvidx),
                pl.BlockSpec((1, 2, 1, 1, 1, bk), kvsidx),
            ],
            out_specs=pl.BlockSpec((1, 1, bq, d), qidx),
            scratch_shapes=[
                pltpu.VMEM((bq, d), jnp.float32),
                pltpu.VMEM((bq, 1), jnp.float32),
                pltpu.VMEM((bq, 1), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((b, h, bq, d), out_dtype),
        interpret=_interpret(),
    )(lay, lens, qt, caches_i8, cache_scales)
    return out[:, :, :sq]


# ---------------------------------------------------------------------------
# Fused write+attend: the kernel updates the cache IN PLACE via
# input_output_aliases and attends in the same pass. This removes the
# XLA-side dynamic_update_slice on the scan-carried buffer entirely —
# the aliasing is declared at the custom-call level, so copy-insertion
# cannot conservatively materialize full-cache copies (the failure mode
# HLO-inspected on 2026-08-01: the carry update behind a kernel read
# copied the whole [L,2,B,Hk,Smax,D] buffer). Only the ONE block
# containing the write slot is ever written back; all other cache blocks
# are untouched HBM. (Reference anchor: fused_multi_transformer_op.cu's
# in-place cache write inside the attention kernel.)
# ---------------------------------------------------------------------------

def _stacked_write_kernel(lay_ref, len_ref, q_ref, kvn_ref, kv_ref,
                          kvo_ref, o_ref, acc_sc, m_sc, l_sc,
                          *, scale, bq, bk):
    ki = pl.program_id(2)
    nk = pl.num_programs(2)
    n_valid = len_ref[pl.program_id(0)]
    # block holding the write slot, clamped to the LAST real block: at a
    # full cache (n_valid == Smax — an eviction-invariant violation) the
    # unclamped jw would be nk, one past the grid, and the matching
    # output index map would address undefined HBM. Clamped, the write
    # row-select misses every row (off == bk) so the kernel copies the
    # last block through unchanged — the new token is DROPPED, never a
    # wild write.
    jw = jnp.minimum(n_valid // bk, nk - 1)

    @pl.when(ki == 0)
    def _():
        # seed the running flash stats with the NEW token's own column
        # (its k/v ride in via kvn_ref — the cache block's bytes at the
        # write slot are stale until this kernel writes them)
        q = q_ref[0, 0]                                  # [bq, d]
        kn = kvn_ref[0, 0, 0, 0]                         # [1, d]
        vn = kvn_ref[0, 1, 0, 0]                         # [1, d]
        s = jax.lax.dot_general(q, kn, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        rows = jax.lax.broadcasted_iota(jnp.int32, (bq, 1), 0)
        valid = rows < 1                                 # sq == 1
        m_sc[:] = jnp.where(valid, s, NEG_INF)
        l_sc[:] = jnp.where(valid, 1.0, 0.0)
        acc_sc[:] = jnp.where(valid, 1.0, 0.0) * vn.astype(jnp.float32)

    k_start = ki * bk

    @pl.when(k_start < n_valid)
    def _():
        _online_softmax_block(q_ref[0, 0], kv_ref[0, 0, 0, 0],
                              kv_ref[0, 1, 0, 0], n_valid, k_start,
                              acc_sc, m_sc, l_sc,
                              scale=scale, sq=1, bq=bq, bk=bk,
                              exclusive=True)

    @pl.when(ki == jw)
    def _():
        # copy-through the write block with the new token's row selected
        # in (row-mask select — one vector op per plane, no dynamic-
        # offset store for Mosaic to choke on). The output index map is
        # CONSTANT at jw, so this is the only cache block pallas ever
        # writes back; the copy is one VMEM-resident block, not HBM
        # traffic beyond the block itself.
        off = n_valid - jw * bk
        rows = jax.lax.broadcasted_iota(jnp.int32, (bk, 1), 0)
        hit = rows == off
        kvo_ref[0, 0, 0, 0] = jnp.where(hit, kvn_ref[0, 0, 0, 0],
                                        kv_ref[0, 0, 0, 0])
        kvo_ref[0, 1, 0, 0] = jnp.where(hit, kvn_ref[0, 1, 0, 0],
                                        kv_ref[0, 1, 0, 0])

    @pl.when(ki == nk - 1)
    def _():
        l = l_sc[:]
        o_ref[0, 0] = (acc_sc[:] /
                       jnp.where(l == 0.0, 1.0, l)).astype(o_ref.dtype)


def stacked_write_is_supported(q_shape, caches_shape, dtype,
                               cache_dtype=None) -> bool:
    """Same layout/tiling rules as the read-only stacked kernel, plus the
    write path's own restriction: exactly one new token per call (the
    chunked decode scans step one token at a time; a multi-row write
    could straddle two sequence blocks)."""
    return q_shape[1] == 1 and stacked_is_supported(
        q_shape, caches_shape, dtype, cache_dtype=cache_dtype)


def decode_attention_stacked_write(qt, kv_new, caches, layer, cache_lens,
                                   scale=None):
    """qt: [B, H, 1, D] (kernel layout); kv_new: [2, B, Hk, 1, D] — the
    new token's K/V for layer `layer`; caches: [L, 2, B, Hk, Smax, D],
    DONATED (aliased to the first output). Returns (caches, attn) where
    caches is the SAME buffer with the new rows landed at position
    cache_lens[b] and attn is [B, H, 1, D].

    The caller must NOT dynamic_update_slice the cache first — the write
    happens inside the kernel, and the new token's self-attention term is
    seeded from kv_new directly.

    INVARIANT: cache_lens[b] < Smax for every row — the ring must have a
    free slot (the serving engine's slot-eviction logic frees a row
    BEFORE re-admitting into it, maintaining exactly this). A full row
    (cache_lens[b] == Smax) cannot raise from traced code; instead both
    the in-kernel write block and the output index map clamp to the last
    sequence block, so the new token is dropped and the cache bytes are
    left untouched (attn still includes the new token's seeded
    self-attention term). Never rely on the drop: it exists to make an
    invariant violation non-corrupting, not to implement eviction."""
    b, h, sq, d = qt.shape
    hk, smax = caches.shape[3], caches.shape[4]
    group = h // hk
    if sq != 1:
        raise ValueError("decode_attention_stacked_write: one new token "
                         f"per call (got Sq={sq}); gate with "
                         "stacked_write_is_supported")
    if scale is None:
        scale = d ** -0.5
    if caches.dtype != qt.dtype:
        raise ValueError(
            f"decode_attention_stacked_write: query dtype {qt.dtype} != "
            f"cache dtype {caches.dtype}")
    out_dtype = qt.dtype

    qt, bq, bk, grid, kvidx, qidx, _clamp = _stacked_setup(
        qt, hk, smax, group)
    kvnidx = lambda b_, h_, j, lay_r, len_r, g=group: (  # noqa: E731
        0, 0, b_, h_ // g, 0, 0)
    # The OUTPUT map is the write-slot block UNCONDITIONALLY (constant in
    # j) — it must NOT reuse the read clamp min(j, jw): for j < jw that
    # addresses prefix blocks the kernel never stores to, and Pallas
    # would write their stale VMEM windows back over live cache. With a
    # constant map, exactly one block per (b, hk) is ever written back;
    # every other cache block stays untouched HBM through the alias.
    # min(..., nblk-1) mirrors the kernel's jw clamp: a full row
    # (cache_lens == Smax) must address the LAST block, not one past it
    # (see the invariant note in the docstring).
    nblk = smax // bk
    kvoidx = lambda b_, h_, j, lay_r, len_r, g=group, bk_=bk: (  # noqa: E731
        lay_r[0], 0, b_, h_ // g,
        jnp.minimum(len_r[b_] // bk_, nblk - 1), 0)
    kv_new = kv_new[None]                  # [1, 2, B, Hk, 1, D]
    lens = cache_lens.astype(jnp.int32).reshape(b)
    lay = jnp.asarray(layer, jnp.int32).reshape(1)
    caches_out, out = pl.pallas_call(
        functools.partial(_stacked_write_kernel, scale=float(scale),
                          bq=bq, bk=bk),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, 1, bq, d), qidx),
                pl.BlockSpec((1, 2, 1, 1, 1, d), kvnidx),
                pl.BlockSpec((1, 2, 1, 1, bk, d), kvidx),
            ],
            out_specs=[
                pl.BlockSpec((1, 2, 1, 1, bk, d), kvoidx),
                pl.BlockSpec((1, 1, bq, d), qidx),
            ],
            scratch_shapes=[
                pltpu.VMEM((bq, d), jnp.float32),
                pltpu.VMEM((bq, 1), jnp.float32),
                pltpu.VMEM((bq, 1), jnp.float32),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct(caches.shape, caches.dtype),
            jax.ShapeDtypeStruct((b, h, bq, d), out_dtype),
        ],
        input_output_aliases={4: 0},   # caches operand -> caches output
        interpret=_interpret(),
    )(lay, lens, qt, kv_new.astype(caches.dtype), caches)
    return caches_out, out[:, :, :sq].astype(out_dtype)


def _stacked_i8_write_kernel(lay_ref, len_ref, q_ref, kvn_ref, kv_ref,
                             kvs_ref, kvo_ref, kvso_ref, o_ref,
                             acc_sc, m_sc, l_sc, *, scale, bq, bk):
    ki = pl.program_id(2)
    nk = pl.num_programs(2)
    n_valid = len_ref[pl.program_id(0)]
    # same full-cache clamp as _stacked_write_kernel: at n_valid == Smax
    # the write row/lane selects miss (off == bk) and the last block +
    # scales copy through unchanged — token dropped, never a wild write
    jw = jnp.minimum(n_valid // bk, nk - 1)

    # the new row's quantization (per-row absmax, same recipe as the
    # host-side cache-quant write) — computed where needed; the seeded
    # self-attention term uses the DEQUANTIZED values so the kernel is
    # bit-consistent with the DUS-then-read int8 path
    def _quant(row):                                     # [1, d] fp
        r32 = row.astype(jnp.float32)
        amax = jnp.max(jnp.abs(r32), axis=-1, keepdims=True)
        sc = amax / 127.0
        qi = jnp.clip(jnp.round(r32 / jnp.maximum(sc, 1e-8)),
                      -127, 127)
        return qi, sc

    @pl.when(ki == 0)
    def _():
        # seed arithmetic MIRRORS the read kernel exactly (bit-for-bit
        # with the DUS-then-read path in every dtype): dot the RAW int
        # values in the query dtype (all of [-127, 127] is exact in
        # bf16), apply the k scale to the SCORE, fold the v scale into p
        # and cast p to the operand dtype before the v dot
        q = q_ref[0, 0]                                  # [bq, d]
        kq, ksc = _quant(kvn_ref[0, 0, 0, 0])
        vq, vsc = _quant(kvn_ref[0, 1, 0, 0])
        s = jax.lax.dot_general(q, kq.astype(q.dtype),
                                (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) \
            * scale * ksc
        rows = jax.lax.broadcasted_iota(jnp.int32, (bq, 1), 0)
        valid = rows < 1                                 # sq == 1
        m_sc[:] = jnp.where(valid, s, NEG_INF)
        l_sc[:] = jnp.where(valid, 1.0, 0.0)
        pv = (jnp.where(valid, 1.0, 0.0) * vsc).astype(q.dtype)
        acc_sc[:] = jax.lax.dot_general(
            pv, vq.astype(q.dtype), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    k_start = ki * bk

    @pl.when(k_start < n_valid)
    def _():
        q = q_ref[0, 0]
        k = kv_ref[0, 0, 0, 0].astype(q.dtype)
        v = kv_ref[0, 1, 0, 0].astype(q.dtype)
        _online_softmax_block(q, k, v, n_valid, k_start,
                              acc_sc, m_sc, l_sc,
                              scale=scale, sq=1, bq=bq, bk=bk,
                              k_col_scale=kvs_ref[0, 0, 0, 0],
                              v_col_scale=kvs_ref[0, 1, 0, 0],
                              exclusive=True)

    @pl.when(ki == jw)
    def _():
        off = n_valid - jw * bk
        rows = jax.lax.broadcasted_iota(jnp.int32, (bk, 1), 0)
        hit = rows == off
        kq, ksc = _quant(kvn_ref[0, 0, 0, 0])
        vq, vsc = _quant(kvn_ref[0, 1, 0, 0])
        kvo_ref[0, 0, 0, 0] = jnp.where(hit, kq.astype(jnp.int8),
                                        kv_ref[0, 0, 0, 0])
        kvo_ref[0, 1, 0, 0] = jnp.where(hit, vq.astype(jnp.int8),
                                        kv_ref[0, 1, 0, 0])
        # scales tile is [1, bk] lane-major: the write slot is a LANE
        # select at `off` (no dynamic store)
        lanes = jax.lax.broadcasted_iota(jnp.int32, (1, bk), 1)
        lhit = lanes == off
        kvso_ref[0, 0, 0, 0] = jnp.where(lhit, ksc.reshape(1, 1),
                                         kvs_ref[0, 0, 0, 0])
        kvso_ref[0, 1, 0, 0] = jnp.where(lhit, vsc.reshape(1, 1),
                                         kvs_ref[0, 1, 0, 0])

    @pl.when(ki == nk - 1)
    def _():
        l = l_sc[:]
        o_ref[0, 0] = (acc_sc[:] /
                       jnp.where(l == 0.0, 1.0, l)).astype(o_ref.dtype)


def stacked_i8_write_is_supported(q_shape, caches_shape, dtype) -> bool:
    """Gate for decode_attention_stacked_i8_write: the int8 read rules
    plus the write path's one-new-token restriction (same rationale as
    stacked_write_is_supported)."""
    return q_shape[1] == 1 and stacked_i8_is_supported(
        q_shape, caches_shape, dtype)


def decode_attention_stacked_i8_write(qt, kv_new, caches_i8, cache_scales,
                                      layer, cache_lens, scale=None):
    """int8 variant of decode_attention_stacked_write: quantizes the new
    token's K/V rows IN KERNEL (per-row absmax, bit-identical to the
    host-side cache-quant write), lands row + scale in place (both
    buffers aliased), and attends in the same pass. qt: [B, H, 1, D];
    kv_new: [2, B, Hk, 1, D] (fp); caches_i8: [L, 2, B, Hk, Smax, D]
    int8 DONATED; cache_scales: [L, 2, B, Hk, 1, Smax] fp32 DONATED.
    Returns (caches_i8, cache_scales, attn).

    INVARIANT: cache_lens[b] < Smax (see decode_attention_stacked_write);
    a full row clamps to the last block and drops the write — cache and
    scales come back byte-identical for that row, never corrupted."""
    b, h, sq, d = qt.shape
    hk, smax = caches_i8.shape[3], caches_i8.shape[4]
    group = h // hk
    if sq != 1:
        raise ValueError("decode_attention_stacked_i8_write: one new "
                         f"token per call (got Sq={sq})")
    if scale is None:
        scale = d ** -0.5
    if caches_i8.dtype != jnp.int8:
        raise ValueError("decode_attention_stacked_i8_write: cache must "
                         "be int8")
    if cache_scales.shape != caches_i8.shape[:4] + (1, smax):
        raise ValueError(
            "decode_attention_stacked_i8_write: scales must be "
            f"[L, 2, B, Hk, 1, Smax], got {cache_scales.shape}")
    out_dtype = qt.dtype

    qt, bq, bk, grid, kvidx, qidx, clamp = _stacked_setup(
        qt, hk, smax, group)
    kvnidx = lambda b_, h_, j, lay_r, len_r, g=group: (  # noqa: E731
        0, 0, b_, h_ // g, 0, 0)
    kvsidx = lambda b_, h_, j, lay_r, len_r, g=group: (  # noqa: E731
        lay_r[0], 0, b_, h_ // g, 0, clamp(j, len_r, b_))
    # constant-at-jw output maps, clamped to the last block exactly like
    # decode_attention_stacked_write (cache_lens < Smax invariant)
    nblk = smax // bk
    kvoidx = lambda b_, h_, j, lay_r, len_r, g=group, bk_=bk: (  # noqa: E731
        lay_r[0], 0, b_, h_ // g,
        jnp.minimum(len_r[b_] // bk_, nblk - 1), 0)
    kvsoidx = lambda b_, h_, j, lay_r, len_r, g=group, bk_=bk: (  # noqa: E731
        lay_r[0], 0, b_, h_ // g, 0,
        jnp.minimum(len_r[b_] // bk_, nblk - 1))
    kv_new = kv_new[None]                  # [1, 2, B, Hk, 1, D]
    lens = cache_lens.astype(jnp.int32).reshape(b)
    lay = jnp.asarray(layer, jnp.int32).reshape(1)
    caches_out, scales_out, out = pl.pallas_call(
        functools.partial(_stacked_i8_write_kernel, scale=float(scale),
                          bq=bq, bk=bk),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, 1, bq, d), qidx),
                pl.BlockSpec((1, 2, 1, 1, 1, d), kvnidx),
                pl.BlockSpec((1, 2, 1, 1, bk, d), kvidx),
                pl.BlockSpec((1, 2, 1, 1, 1, bk), kvsidx),
            ],
            out_specs=[
                pl.BlockSpec((1, 2, 1, 1, bk, d), kvoidx),
                pl.BlockSpec((1, 2, 1, 1, 1, bk), kvsoidx),
                pl.BlockSpec((1, 1, bq, d), qidx),
            ],
            scratch_shapes=[
                pltpu.VMEM((bq, d), jnp.float32),
                pltpu.VMEM((bq, 1), jnp.float32),
                pltpu.VMEM((bq, 1), jnp.float32),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct(caches_i8.shape, jnp.int8),
            jax.ShapeDtypeStruct(cache_scales.shape, jnp.float32),
            jax.ShapeDtypeStruct((b, h, bq, d), out_dtype),
        ],
        input_output_aliases={4: 0, 5: 1},
        interpret=_interpret(),
    )(lay, lens, qt, kv_new.astype(jnp.float32), caches_i8, cache_scales)
    return caches_out, scales_out, out[:, :, :sq].astype(out_dtype)


# ---------------------------------------------------------------------------
# Paged-cache variant: the KV cache is ONE shared block pool
# [L, 2, NBtotal, Hk, Bt, D] and each batch row's positions resolve
# through a per-slot block table [B, Smax/Bt] int32 (vLLM PagedAttention
# layout; see inference/paged_kv.py for the allocator). The table rides
# in as a SCALAR-PREFETCH operand so the kv BlockSpec index map can
# translate grid step j into the row's j-th pool block — the kernel
# streams exactly the blocks the row owns, in table order, and the
# last-valid-block clamp re-addresses past-the-end steps at the last
# valid block so the pipeline elides their HBM copies (same trick as
# the stacked kernels). The sequence-block size IS the pool's Bt, so
# one compiled kernel serves every slot/table content — block ids are
# data, never structure.
# ---------------------------------------------------------------------------

def _paged_sublane(dtype) -> int:
    """Minimum Mosaic sublane multiple for the pool's Bt axis: the kv
    block (1, 2, 1, 1, Bt, D) puts Bt on the second-to-minor dim."""
    d = jnp.dtype(dtype)
    if d == jnp.int8:
        return 32
    if d in (jnp.bfloat16, jnp.float16):
        return 16
    return 8


def paged_is_supported(q_shape, pool_shape, dtype,
                       cache_dtype=None) -> bool:
    """pool: [L, 2, NB, Hk, Bt, D]; q: [B, Sq, H, D]. Bt must satisfy
    the dtype's sublane tiling (fp32: 8, bf16/fp16: 16, int8: 32) —
    smaller block_tokens values fall back to the gather-dense path in
    generation.py. Like the stacked kernels, q and cache dtypes must
    MATCH (upcasting the pool would copy every block)."""
    if len(q_shape) != 4 or len(pool_shape) != 6:
        return False
    if q_shape[-1] > 256 or q_shape[1] > 128:
        return False
    if pool_shape[3] == 0 or q_shape[2] % pool_shape[3] != 0:
        return False
    bt = pool_shape[4]
    sub = _paged_sublane(cache_dtype if cache_dtype is not None else dtype)
    if bt < sub or bt % sub:
        return False
    if cache_dtype is not None and jnp.dtype(cache_dtype) != jnp.dtype(dtype):
        return False
    return jnp.dtype(dtype) in (jnp.float32, jnp.bfloat16, jnp.float16)


def paged_i8_is_supported(q_shape, pool_shape, dtype) -> bool:
    """int8 pool flavor: same layout rules with the int8 sublane
    minimum (Bt % 32 == 0); compute dtype is the query's."""
    if len(q_shape) != 4 or len(pool_shape) != 6:
        return False
    if q_shape[-1] > 256 or q_shape[1] > 128:
        return False
    if pool_shape[3] == 0 or q_shape[2] % pool_shape[3] != 0:
        return False
    bt = pool_shape[4]
    if bt < 32 or bt % 32:
        return False
    return jnp.dtype(dtype) in (jnp.float32, jnp.bfloat16, jnp.float16)


def _paged_setup(qt, bt, nblk, nb, group):
    """Shared host-side setup for the paged kernels: q padding, grid,
    and the table-translated index maps. Index-map signature:
    (b, h, j, lay_ref, len_ref, tbl_ref) — tables are the THIRD
    scalar-prefetch operand. Unmapped/sentinel table entries are
    clamped to block nb - 1 (their contents are never attendable: the
    kernel masks cols >= n_valid + sq, and the clamp below only
    re-addresses steps past the last valid block anyway)."""
    b, h, sq, d = qt.shape
    bq = max(8, 1 << (sq - 1).bit_length()) if sq < 128 else 128
    if bq != sq:
        qt = jnp.pad(qt, ((0, 0), (0, 0), (0, bq - sq), (0, 0)))
    grid = (b, h, nblk)

    def _clamp(j, len_r, b_):
        # same pipeline-copy-elision clamp as the stacked kernels:
        # steps past this row's last valid block re-address that block
        return jnp.minimum(j, (len_r[b_] + sq - 1) // bt)

    def _blk(j, len_r, tbl_r, b_):
        return jnp.minimum(tbl_r[b_, _clamp(j, len_r, b_)], nb - 1)

    kvidx = lambda b_, h_, j, lay_r, len_r, tbl_r, g=group: (  # noqa: E731
        lay_r[0], 0, _blk(j, len_r, tbl_r, b_), h_ // g, 0, 0)
    qidx = lambda b_, h_, j, lay_r, len_r, tbl_r: (  # noqa: E731
        b_, h_, 0, 0)
    return qt, bq, grid, kvidx, qidx, _blk


def _paged_kernel(lay_ref, len_ref, tbl_ref, q_ref, kv_ref, o_ref,
                  acc_sc, m_sc, l_sc, *, scale, sq, bq, bk):
    # flash math identical to _stacked_kernel (shared
    # _online_softmax_block); only the addressing differs — the
    # (1, 2, 1, 1, bk, d) kv block was fetched through the block table
    ki = pl.program_id(2)
    nk = pl.num_programs(2)
    n_valid = len_ref[pl.program_id(0)]

    @pl.when(ki == 0)
    def _():
        m_sc[:] = jnp.full_like(m_sc, NEG_INF)
        l_sc[:] = jnp.zeros_like(l_sc)
        acc_sc[:] = jnp.zeros_like(acc_sc)

    k_start = ki * bk
    run = k_start < n_valid + sq

    @pl.when(run)
    def _():
        _online_softmax_block(q_ref[0, 0], kv_ref[0, 0, 0, 0],
                              kv_ref[0, 1, 0, 0], n_valid, k_start,
                              acc_sc, m_sc, l_sc,
                              scale=scale, sq=sq, bq=bq, bk=bk)

    @pl.when(ki == nk - 1)
    def _():
        l = l_sc[:]
        o_ref[0, 0] = (acc_sc[:] /
                       jnp.where(l == 0.0, 1.0, l)).astype(o_ref.dtype)


def decode_attention_paged(qt, pool, tables, layer, cache_lens,
                           scale=None):
    """qt: [B, H, Sq, D] (kernel layout); pool: [L, 2, NB, Hk, Bt, D]
    — the ONE shared block pool; tables: [B, Smax/Bt] int32 per-slot
    block tables (sentinel NB for unmapped entries); layer: scalar
    int32 (scalar-prefetch); cache_lens: [B] int32. Returns
    [B, H, Sq, D] — attention of the new queries over the row's
    table-resolved prefix + the just-written new positions."""
    b, h, sq, d = qt.shape
    hk, bt = pool.shape[3], pool.shape[4]
    nb = pool.shape[2]
    nblk = tables.shape[1]
    group = h // hk
    if scale is None:
        scale = d ** -0.5
    if pool.dtype != qt.dtype:
        raise ValueError(
            f"decode_attention_paged: query dtype {qt.dtype} != pool "
            f"dtype {pool.dtype}; gate with paged_is_supported(..., "
            "cache_dtype=...) and use the gather-dense path instead")
    out_dtype = qt.dtype

    qt, bq, grid, kvidx, qidx, _ = _paged_setup(qt, bt, nblk, nb, group)
    lens = cache_lens.astype(jnp.int32).reshape(b)
    lay = jnp.asarray(layer, jnp.int32).reshape(1)
    tbl = tables.astype(jnp.int32)
    out = pl.pallas_call(
        functools.partial(_paged_kernel, scale=float(scale), sq=sq,
                          bq=bq, bk=bt),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, 1, bq, d), qidx),
                pl.BlockSpec((1, 2, 1, 1, bt, d), kvidx),
            ],
            out_specs=pl.BlockSpec((1, 1, bq, d), qidx),
            scratch_shapes=[
                pltpu.VMEM((bq, d), jnp.float32),
                pltpu.VMEM((bq, 1), jnp.float32),
                pltpu.VMEM((bq, 1), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((b, h, bq, d), pool.dtype),
        interpret=_interpret(),
    )(lay, lens, tbl, qt, pool)
    return out[:, :, :sq].astype(out_dtype)


def _paged_i8_kernel(lay_ref, len_ref, tbl_ref, q_ref, kv_ref, kvs_ref,
                     o_ref, acc_sc, m_sc, l_sc, *, scale, sq, bq, bk):
    ki = pl.program_id(2)
    nk = pl.num_programs(2)
    n_valid = len_ref[pl.program_id(0)]

    @pl.when(ki == 0)
    def _():
        m_sc[:] = jnp.full_like(m_sc, NEG_INF)
        l_sc[:] = jnp.zeros_like(l_sc)
        acc_sc[:] = jnp.zeros_like(acc_sc)

    k_start = ki * bk
    run = k_start < n_valid + sq

    @pl.when(run)
    def _():
        q = q_ref[0, 0]
        # int8 -> compute dtype conversion; per-row dequant scales
        # applied column-wise to the score matrix as [1, bk] lane-major
        # tiles — identical discipline to _stacked_i8_kernel
        k = kv_ref[0, 0, 0, 0].astype(q.dtype)
        v = kv_ref[0, 1, 0, 0].astype(q.dtype)
        _online_softmax_block(q, k, v, n_valid, k_start,
                              acc_sc, m_sc, l_sc,
                              scale=scale, sq=sq, bq=bq, bk=bk,
                              k_col_scale=kvs_ref[0, 0, 0, 0],
                              v_col_scale=kvs_ref[0, 1, 0, 0])

    @pl.when(ki == nk - 1)
    def _():
        l = l_sc[:]
        o_ref[0, 0] = (acc_sc[:] /
                       jnp.where(l == 0.0, 1.0, l)).astype(o_ref.dtype)


def decode_attention_paged_i8(qt, pool_i8, pool_scales, tables, layer,
                              cache_lens, scale=None):
    """int8 paged flavor: pool_i8 [L, 2, NB, Hk, Bt, D] int8 with
    per-row absmax scales pool_scales [L, 2, NB, Hk, 1, Bt] fp32 (the
    scales pool mirrors the kv pool block-for-block, so both resolve
    through the SAME table entry). Returns [B, H, Sq, D] in the query
    dtype."""
    b, h, sq, d = qt.shape
    hk, bt = pool_i8.shape[3], pool_i8.shape[4]
    nb = pool_i8.shape[2]
    nblk = tables.shape[1]
    group = h // hk
    if scale is None:
        scale = d ** -0.5
    if pool_i8.dtype != jnp.int8:
        raise ValueError("decode_attention_paged_i8: pool must be int8")
    if pool_scales.shape != pool_i8.shape[:4] + (1, bt):
        raise ValueError(
            "decode_attention_paged_i8: scales must be "
            f"[L, 2, NB, Hk, 1, Bt], got {pool_scales.shape}")
    out_dtype = qt.dtype

    qt, bq, grid, kvidx, qidx, blkf = _paged_setup(qt, bt, nblk, nb,
                                                   group)
    kvsidx = lambda b_, h_, j, lay_r, len_r, tbl_r, g=group: (  # noqa: E731
        lay_r[0], 0, blkf(j, len_r, tbl_r, b_), h_ // g, 0, 0)
    lens = cache_lens.astype(jnp.int32).reshape(b)
    lay = jnp.asarray(layer, jnp.int32).reshape(1)
    tbl = tables.astype(jnp.int32)
    out = pl.pallas_call(
        functools.partial(_paged_i8_kernel, scale=float(scale), sq=sq,
                          bq=bq, bk=bt),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, 1, bq, d), qidx),
                pl.BlockSpec((1, 2, 1, 1, bt, d), kvidx),
                pl.BlockSpec((1, 2, 1, 1, 1, bt), kvsidx),
            ],
            out_specs=pl.BlockSpec((1, 1, bq, d), qidx),
            scratch_shapes=[
                pltpu.VMEM((bq, d), jnp.float32),
                pltpu.VMEM((bq, 1), jnp.float32),
                pltpu.VMEM((bq, 1), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((b, h, bq, d), out_dtype),
        interpret=_interpret(),
    )(lay, lens, tbl, qt, pool_i8, pool_scales)
    return out[:, :, :sq]


# ---------------------------------------------------------------------------
# Flat-stream variant: the token-budget scheduler's FLAT dispatch packs
# every request's segment (a prefill chunk, a decode token + draft
# claim) into ONE ragged [T] token stream instead of the row-aligned
# [B, C] block — T real tokens cost T positions of compute, where the
# row layout paid B x C regardless of packing (a lone long prefill
# wasted (B-1) x C positions per dispatch). This kernel is the
# block-flash attend for that stream: the packer aligns segment starts
# to FLAT_CHUNK so every FLAT_CHUNK-sized query chunk belongs to ONE
# slot, per-chunk (slot, base position, valid count) ride in as
# scalar-prefetch metadata, and each chunk streams its slot's paged KV
# blocks through the block table with block-causal masking — the
# Sq > 1 write-then-attend generalization from the verify step,
# extended to ragged multi-request streams. Pad chunks (slot sentinel)
# carry n == 0: no block runs, l stays 0, the output row is zeroed by
# the l == 0 guard.
# ---------------------------------------------------------------------------

# the packer's segment-start alignment = the kernel's query-chunk size:
# 8 is the fp32 sublane minimum, so the q block (1, FLAT_CHUNK, d)
# tiles legally for every supported dtype
FLAT_CHUNK = 8


def paged_flat_is_supported(t, h, d, pool_shape, dtype,
                            cache_dtype=None) -> bool:
    """Support predicate for decode_attention_paged_flat: stream width
    t must tile into FLAT_CHUNK query chunks; the pool obeys the same
    Bt-sublane and dtype-match rules as the row-aligned paged kernel.
    Int8 pools have their own flavor — gate those with
    paged_flat_i8_is_supported (whose Bt gate is the int8 sublane
    minimum); only pools passing NEITHER predicate take the
    gather-dense fallback (paged_kv.flat_gather_view, the parity
    oracle)."""
    if len(pool_shape) != 6:
        return False
    if t < FLAT_CHUNK or t % FLAT_CHUNK:
        return False
    if d > 256:
        return False
    if pool_shape[3] == 0 or h % pool_shape[3] != 0:
        return False
    bt = pool_shape[4]
    sub = _paged_sublane(cache_dtype if cache_dtype is not None else dtype)
    if bt < sub or bt % sub:
        return False
    if cache_dtype is not None and jnp.dtype(cache_dtype) != jnp.dtype(dtype):
        return False
    return jnp.dtype(dtype) in (jnp.float32, jnp.bfloat16, jnp.float16)


def _paged_flat_kernel(lay_ref, cslot_ref, cbase_ref, cn_ref, tbl_ref,
                       q_ref, kv_ref, o_ref, acc_sc, m_sc, l_sc,
                       *, scale, bq, bk):
    # flash math identical to _paged_kernel; the addressing unit is a
    # QUERY CHUNK instead of a batch row — chunk ci's tokens are the
    # contiguous positions cbase[ci] .. cbase[ci] + cn[ci] - 1 of slot
    # cslot[ci], so the standard causal mask applies with the chunk's
    # base as the prefix length and its valid count as the (dynamic)
    # query count
    ci = pl.program_id(0)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)
    n_valid = cbase_ref[ci]
    sq_dyn = cn_ref[ci]

    @pl.when(ki == 0)
    def _():
        m_sc[:] = jnp.full_like(m_sc, NEG_INF)
        l_sc[:] = jnp.zeros_like(l_sc)
        acc_sc[:] = jnp.zeros_like(acc_sc)

    k_start = ki * bk
    run = (sq_dyn > 0) & (k_start < n_valid + sq_dyn)

    @pl.when(run)
    def _():
        _online_softmax_block(q_ref[0], kv_ref[0, 0, 0, 0],
                              kv_ref[0, 1, 0, 0], n_valid, k_start,
                              acc_sc, m_sc, l_sc,
                              scale=scale, sq=sq_dyn, bq=bq, bk=bk)

    @pl.when(ki == nk - 1)
    def _():
        l = l_sc[:]
        o_ref[0] = (acc_sc[:] /
                    jnp.where(l == 0.0, 1.0, l)).astype(o_ref.dtype)


def decode_attention_paged_flat(q, pool, tables, chunk_slot, chunk_base,
                                chunk_n, layer, scale=None):
    """q: [T, H, D] — the flat token stream's queries, segment starts
    aligned to FLAT_CHUNK so each FLAT_CHUNK query chunk is single-slot;
    pool: [L, 2, NB, Hk, Bt, D]; tables: [B(+sentinel rows ok), Smax/Bt]
    int32; chunk_slot/chunk_base/chunk_n: [T/FLAT_CHUNK] int32 per-chunk
    metadata (slot id CLAMPED in-bounds by the caller, base position of
    the chunk's first token, number of valid tokens — 0 for pad
    chunks). Returns [T, H, D]: token i attends its slot's
    table-resolved positions <= its own position (block-causal; the
    chunk's K/V must already be written — write-then-attend)."""
    t, h, d = q.shape
    hk, bt = pool.shape[3], pool.shape[4]
    nb = pool.shape[2]
    nblk = tables.shape[1]
    group = h // hk
    nc = t // FLAT_CHUNK
    if t % FLAT_CHUNK:
        raise ValueError(
            f"decode_attention_paged_flat: stream width {t} must be a "
            f"multiple of FLAT_CHUNK={FLAT_CHUNK} (gate with "
            "paged_flat_is_supported)")
    if scale is None:
        scale = d ** -0.5
    if pool.dtype != q.dtype:
        raise ValueError(
            f"decode_attention_paged_flat: query dtype {q.dtype} != "
            f"pool dtype {pool.dtype}; gate with paged_flat_is_supported"
            "(..., cache_dtype=...) and use the gather-dense fallback")
    out_dtype = q.dtype
    # [T, H, D] -> [H, T, D]: heads ride their own grid axis, the token
    # chunk is the q block's sublane axis
    qt = jnp.swapaxes(q, 0, 1)
    grid = (nc, h, nblk)

    def _blk(ci, j, cb_r, cn_r, tbl_r, cs_r):
        # last-valid-block clamp per CHUNK (pipeline copy elision, the
        # stacked/paged kernels' trick): the chunk's highest attendable
        # position is cbase + cn - 1; later grid steps re-address that
        # block. Pad chunks (cn == 0) pin to the chunk's base block.
        last = (cb_r[ci] + jnp.maximum(cn_r[ci], 1) - 1) // bt
        return jnp.minimum(tbl_r[cs_r[ci], jnp.minimum(j, last)], nb - 1)

    kvidx = lambda ci, h_, j, lay_r, cs_r, cb_r, cn_r, tbl_r, g=group: (  # noqa: E731
        lay_r[0], 0, _blk(ci, j, cb_r, cn_r, tbl_r, cs_r), h_ // g, 0, 0)
    qidx = lambda ci, h_, j, lay_r, cs_r, cb_r, cn_r, tbl_r: (  # noqa: E731
        h_, ci, 0)
    lay = jnp.asarray(layer, jnp.int32).reshape(1)
    out = pl.pallas_call(
        functools.partial(_paged_flat_kernel, scale=float(scale),
                          bq=FLAT_CHUNK, bk=bt),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=5,
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, FLAT_CHUNK, d), qidx),
                pl.BlockSpec((1, 2, 1, 1, bt, d), kvidx),
            ],
            out_specs=pl.BlockSpec((1, FLAT_CHUNK, d), qidx),
            scratch_shapes=[
                pltpu.VMEM((FLAT_CHUNK, d), jnp.float32),
                pltpu.VMEM((FLAT_CHUNK, 1), jnp.float32),
                pltpu.VMEM((FLAT_CHUNK, 1), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((h, t, d), pool.dtype),
        interpret=_interpret(),
    )(lay, chunk_slot.astype(jnp.int32), chunk_base.astype(jnp.int32),
      chunk_n.astype(jnp.int32), tables.astype(jnp.int32), qt, pool)
    return jnp.swapaxes(out, 0, 1).astype(out_dtype)


def paged_flat_i8_is_supported(t, h, d, pool_shape, dtype) -> bool:
    """Support predicate for decode_attention_paged_flat_i8: the flat
    layout rules (FLAT_CHUNK-tiled stream, head grouping, d <= 256)
    with the int8 pool's sublane gate (Bt % 32 == 0 — the Mosaic
    minimum for an int8 second-to-minor axis); compute dtype is the
    query's. Pools failing this go to the gather-dense fallback
    (flat_gather_view's sc path), which stays the parity oracle."""
    if len(pool_shape) != 6:
        return False
    if t < FLAT_CHUNK or t % FLAT_CHUNK:
        return False
    if d > 256:
        return False
    if pool_shape[3] == 0 or h % pool_shape[3] != 0:
        return False
    bt = pool_shape[4]
    if bt < 32 or bt % 32:
        return False
    return jnp.dtype(dtype) in (jnp.float32, jnp.bfloat16, jnp.float16)


def _paged_flat_i8_kernel(lay_ref, cslot_ref, cbase_ref, cn_ref, tbl_ref,
                          q_ref, kv_ref, kvs_ref, o_ref, acc_sc, m_sc,
                          l_sc, *, scale, bq, bk):
    # _paged_flat_kernel's chunk addressing with _paged_i8_kernel's
    # dequant: int8 KV casts to the compute dtype and the per-row
    # absmax scales apply COLUMN-wise to the score matrix as [1, bk]
    # lane-major tiles (see _online_softmax_block)
    ci = pl.program_id(0)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)
    n_valid = cbase_ref[ci]
    sq_dyn = cn_ref[ci]

    @pl.when(ki == 0)
    def _():
        m_sc[:] = jnp.full_like(m_sc, NEG_INF)
        l_sc[:] = jnp.zeros_like(l_sc)
        acc_sc[:] = jnp.zeros_like(acc_sc)

    k_start = ki * bk
    run = (sq_dyn > 0) & (k_start < n_valid + sq_dyn)

    @pl.when(run)
    def _():
        q = q_ref[0]
        k = kv_ref[0, 0, 0, 0].astype(q.dtype)
        v = kv_ref[0, 1, 0, 0].astype(q.dtype)
        _online_softmax_block(q, k, v, n_valid, k_start,
                              acc_sc, m_sc, l_sc,
                              scale=scale, sq=sq_dyn, bq=bq, bk=bk,
                              k_col_scale=kvs_ref[0, 0, 0, 0],
                              v_col_scale=kvs_ref[0, 1, 0, 0])

    @pl.when(ki == nk - 1)
    def _():
        l = l_sc[:]
        o_ref[0] = (acc_sc[:] /
                    jnp.where(l == 0.0, 1.0, l)).astype(o_ref.dtype)


def decode_attention_paged_flat_i8(q, pool_i8, pool_scales, tables,
                                   chunk_slot, chunk_base, chunk_n,
                                   layer, scale=None):
    """int8 flavor of the flat-stream kernel: pool_i8
    [L, 2, NB, Hk, Bt, D] int8 with mirrored per-row absmax scales
    pool_scales [L, 2, NB, Hk, 1, Bt] fp32 (the scales pool resolves
    through the SAME chunk-clamped table translation block-for-block,
    like the row-aligned decode_attention_paged_i8). q: [T, H, D] in
    the compute dtype; chunk metadata as decode_attention_paged_flat.
    Returns [T, H, D] in the QUERY dtype — the output of a quantized
    pool is fp, never int8."""
    t, h, d = q.shape
    hk, bt = pool_i8.shape[3], pool_i8.shape[4]
    nb = pool_i8.shape[2]
    nblk = tables.shape[1]
    group = h // hk
    nc = t // FLAT_CHUNK
    if t % FLAT_CHUNK:
        raise ValueError(
            f"decode_attention_paged_flat_i8: stream width {t} must be "
            f"a multiple of FLAT_CHUNK={FLAT_CHUNK} (gate with "
            "paged_flat_i8_is_supported)")
    if scale is None:
        scale = d ** -0.5
    if pool_i8.dtype != jnp.int8:
        raise ValueError(
            "decode_attention_paged_flat_i8: pool must be int8")
    if pool_scales.shape != pool_i8.shape[:4] + (1, bt):
        raise ValueError(
            "decode_attention_paged_flat_i8: scales must be "
            f"[L, 2, NB, Hk, 1, Bt], got {pool_scales.shape}")
    out_dtype = q.dtype
    qt = jnp.swapaxes(q, 0, 1)                    # [H, T, D]
    grid = (nc, h, nblk)

    def _blk(ci, j, cb_r, cn_r, tbl_r, cs_r):
        # same per-chunk last-valid-block clamp as the fp flavor
        last = (cb_r[ci] + jnp.maximum(cn_r[ci], 1) - 1) // bt
        return jnp.minimum(tbl_r[cs_r[ci], jnp.minimum(j, last)], nb - 1)

    kvidx = lambda ci, h_, j, lay_r, cs_r, cb_r, cn_r, tbl_r, g=group: (  # noqa: E731
        lay_r[0], 0, _blk(ci, j, cb_r, cn_r, tbl_r, cs_r), h_ // g, 0, 0)
    qidx = lambda ci, h_, j, lay_r, cs_r, cb_r, cn_r, tbl_r: (  # noqa: E731
        h_, ci, 0)
    lay = jnp.asarray(layer, jnp.int32).reshape(1)
    out = pl.pallas_call(
        functools.partial(_paged_flat_i8_kernel, scale=float(scale),
                          bq=FLAT_CHUNK, bk=bt),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=5,
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, FLAT_CHUNK, d), qidx),
                pl.BlockSpec((1, 2, 1, 1, bt, d), kvidx),
                pl.BlockSpec((1, 2, 1, 1, 1, bt), kvidx),
            ],
            out_specs=pl.BlockSpec((1, FLAT_CHUNK, d), qidx),
            scratch_shapes=[
                pltpu.VMEM((FLAT_CHUNK, d), jnp.float32),
                pltpu.VMEM((FLAT_CHUNK, 1), jnp.float32),
                pltpu.VMEM((FLAT_CHUNK, 1), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((h, t, d), out_dtype),
        interpret=_interpret(),
    )(lay, chunk_slot.astype(jnp.int32), chunk_base.astype(jnp.int32),
      chunk_n.astype(jnp.int32), tables.astype(jnp.int32), qt, pool_i8,
      pool_scales)
    return jnp.swapaxes(out, 0, 1)
