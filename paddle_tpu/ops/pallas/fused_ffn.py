"""Fused transformer FFN as one TPU Pallas kernel.

Capability parity: paddle/fluid/operators/fused/fused_feedforward_op.cu
(the training-side fused FFN block the BASELINE north-star names). NOT a
port: one pallas_call computes  out = gelu(x @ W1 + b1) @ W2 + b2  with
the [bm, bf] activation tile living ONLY in VMEM — the [M, F] gelu
intermediate (50 MB at the GPT-2 headline shape) is never written to or
read back from HBM. Grid: (M/bm, F/bf) with the F axis innermost; the
fp32 output accumulator is revisited across F blocks and written once.

Backward (custom_vjp) recomputes the intermediate from x (flash-style
residual discipline: only the INPUTS are saved) and runs the five grad
matmuls as plain jnp — XLA already schedules those well; the fwd fusion
is where the intermediate-traffic win lives. A/B'd against the XLA
composite on TPU before becoming any default (the r3 LayerNorm lesson:
pallas_call is a fusion barrier, composites sometimes win — measure).
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["fused_ffn", "ffn_is_supported"]


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _gelu_tanh(x):
    # GPT-2's approximate gelu, computed in fp32 inside the kernel
    c = math.sqrt(2.0 / math.pi)
    return 0.5 * x * (1.0 + jnp.tanh(c * (x + 0.044715 * x * x * x)))


def _gelu_erf(x):
    # exact gelu (the reference fused_feedforward_op's "gelu")
    return 0.5 * x * (1.0 + jax.lax.erf(x * (2.0 ** -0.5)))


_ACTS = {"gelu_tanh": _gelu_tanh, "gelu": _gelu_erf}


def ffn_is_supported(m, k, f, dtype) -> bool:
    """x: [M, K], W1: [K, F], W2: [F, K]. Lane-dim tiling: K and F must
    be 128-multiples (the bench shapes are: 768/3072, 1024/2816...)."""
    if k % 128 or f % 128:
        return False
    if m < 8:
        return False
    return jnp.dtype(dtype) in (jnp.float32, jnp.bfloat16, jnp.float16)


def _kernel(x_ref, w1_ref, b1_ref, w2_ref, b2_ref, o_ref, acc_sc,
            *, bm, bf, nf, act="gelu_tanh"):
    fi = pl.program_id(1)

    @pl.when(fi == 0)
    def _():
        acc_sc[:] = jnp.zeros_like(acc_sc)

    x = x_ref[...]                                   # [bm, K]
    w1 = w1_ref[...]                                 # [K, bf]
    pre = jax.lax.dot_general(x, w1, (((1,), (0,)), ((), ())),
                              preferred_element_type=jnp.float32)
    pre = pre + b1_ref[...].astype(jnp.float32)      # [bm, bf]
    t = _ACTS[act](pre).astype(x.dtype)
    w2 = w2_ref[...]                                 # [bf, K]
    acc_sc[:] += jax.lax.dot_general(t, w2, (((1,), (0,)), ((), ())),
                                     preferred_element_type=jnp.float32)

    @pl.when(fi == nf - 1)
    def _():
        o_ref[...] = (acc_sc[:] +
                      b2_ref[...].astype(jnp.float32)).astype(o_ref.dtype)


def _fwd_kernel_call(x, w1, b1, w2, b2, bm, bf, act):
    m, k = x.shape
    f = w1.shape[1]
    nf = f // bf
    grid = (m // bm, nf)
    return pl.pallas_call(
        functools.partial(_kernel, bm=bm, bf=bf, nf=nf, act=act),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, k), lambda mi, fi: (mi, 0)),
            pl.BlockSpec((k, bf), lambda mi, fi: (0, fi)),
            pl.BlockSpec((1, bf), lambda mi, fi: (0, fi)),
            pl.BlockSpec((bf, k), lambda mi, fi: (fi, 0)),
            pl.BlockSpec((1, k), lambda mi, fi: (0, 0)),
        ],
        out_specs=pl.BlockSpec((bm, k), lambda mi, fi: (mi, 0)),
        scratch_shapes=[pltpu.VMEM((bm, k), jnp.float32)],
        out_shape=jax.ShapeDtypeStruct((m, k), x.dtype),
        interpret=_interpret(),
    )(x, w1, b1.reshape(1, f), w2, b2.reshape(1, k))


def _pick_bm(m, k, f, bf, dtype):
    """Row-tile: big enough to feed the MXU, small enough that
    x + w1/w2 blocks + fp32 acc fit VMEM (~16 MB budget)."""
    itemsize = jnp.dtype(dtype).itemsize
    for bm in (1024, 512, 256, 128, 64, 32, 16, 8):
        if m % bm:
            continue
        vmem = (bm * k * itemsize          # x tile
                + 2 * k * bf * itemsize    # w1 + w2 blocks
                + bm * bf * 4              # pre/t tile (fp32)
                + bm * k * 4)              # accumulator
        if vmem <= 12 * 1024 * 1024:
            return bm
    return None


@functools.partial(jax.custom_vjp, nondiff_argnums=(5,))
def fused_ffn(x, w1, b1, w2, b2, activation="gelu_tanh"):
    """out = act(x @ w1 + b1) @ w2 + b2 with act in {gelu_tanh, gelu
    (exact/erf)}; x: [..., K] flattened to [M, K] internally. Falls back
    to the XLA composite when shapes don't tile (callers may also gate
    on ffn_is_supported)."""
    out, _ = _fused_ffn_fwd(x, w1, b1, w2, b2, activation)
    return out


def _composite(x2, w1, b1, w2, b2, activation="gelu_tanh"):
    t = _ACTS[activation]((x2 @ w1 + b1).astype(jnp.float32)) \
        .astype(x2.dtype)
    return t @ w2 + b2


def _fused_ffn_fwd(x, w1, b1, w2, b2, activation="gelu_tanh"):
    lead = x.shape[:-1]
    k = x.shape[-1]
    f = w1.shape[1]
    x2 = x.reshape(-1, k)
    m = x2.shape[0]
    # bf must DIVIDE f exactly — nf = f // bf would silently drop the
    # tail columns otherwise (f % 128 == 0 guarantees a divisor exists)
    bf = next((c for c in (512, 256, 128) if f % c == 0), None)
    bm = _pick_bm(m, k, f, bf or 128, x.dtype)
    if not ffn_is_supported(m, k, f, x.dtype) or bm is None or bf is None:
        out = _composite(x2, w1, b1, w2, b2, activation)
    else:
        out = _fwd_kernel_call(x2, w1, b1, w2, b2, bm, bf, activation)
    return out.reshape(*lead, k), (x, w1, b1, w2, b2)


def _fused_ffn_bwd(activation, res, g):
    x, w1, b1, w2, b2 = res
    k = x.shape[-1]
    f = w1.shape[1]
    x2 = x.reshape(-1, k)
    g2 = g.reshape(-1, k)
    # recompute the intermediate (inputs-only residuals); grads as plain
    # XLA matmuls — fp32 accumulation via preferred_element_type
    pre = (jax.lax.dot_general(x2, w1, (((1,), (0,)), ((), ())),
                               preferred_element_type=jnp.float32)
           + b1.astype(jnp.float32))
    t = _ACTS[activation](pre)
    if activation == "gelu_tanh":
        c = math.sqrt(2.0 / math.pi)
        u = c * (pre + 0.044715 * pre ** 3)
        th = jnp.tanh(u)
        dgelu = 0.5 * (1.0 + th) + 0.5 * pre * (1.0 - th * th) * c * (
            1.0 + 3 * 0.044715 * pre ** 2)
    else:   # exact gelu: d/dx = Phi(x) + x*phi(x)
        dgelu = (0.5 * (1.0 + jax.lax.erf(pre * (2.0 ** -0.5)))
                 + pre * jnp.exp(-0.5 * pre * pre)
                 * (1.0 / math.sqrt(2.0 * math.pi)))
    dt = jax.lax.dot_general(g2, w2, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    dpre = dt * dgelu
    dx = jax.lax.dot_general(dpre.astype(x2.dtype), w1,
                             (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    dw1 = jax.lax.dot_general(x2, dpre.astype(x2.dtype),
                              (((0,), (0,)), ((), ())),
                              preferred_element_type=jnp.float32)
    dw2 = jax.lax.dot_general(t.astype(x2.dtype), g2,
                              (((0,), (0,)), ((), ())),
                              preferred_element_type=jnp.float32)
    db1 = jnp.sum(dpre, axis=0)
    db2 = jnp.sum(g2.astype(jnp.float32), axis=0)
    return (dx.astype(x.dtype).reshape(x.shape),
            dw1.astype(w1.dtype), db1.astype(b1.dtype),
            dw2.astype(w2.dtype), db2.astype(b2.dtype))


fused_ffn.defvjp(_fused_ffn_fwd, _fused_ffn_bwd)
