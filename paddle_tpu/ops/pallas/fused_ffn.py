"""Fused transformer FFN as one TPU Pallas kernel.

Capability parity: paddle/fluid/operators/fused/fused_feedforward_op.cu
(the training-side fused FFN block the BASELINE north-star names). NOT a
port: one pallas_call computes  out = gelu(x @ W1 + b1) @ W2 + b2  with
the [bm, bf] activation tile living ONLY in VMEM — the [M, F] gelu
intermediate (50 MB at the GPT-2 headline shape) is never written to or
read back from HBM. Grid: (M/bm, F/bf) with the F axis innermost; the
fp32 output accumulator is revisited across F blocks and written once.

Backward (custom_vjp) recomputes the intermediate from x (flash-style
residual discipline: only the INPUTS are saved). Default: plain-jnp grad
matmuls. Opt-in PADDLE_TPU_FUSED_FFN_BWD=1: a two-kernel Pallas backward
(dx kernel + dw1/dw2/db1 kernel — see the bwd section) that keeps every
[M, F] intermediate (pre/t/dt/dpre, 4 x ~50 MB fp32 at the headline
shape) in VMEM tiles. Both halves A/B'd against the XLA composite on TPU
before becoming any default (the r3 LayerNorm lesson: pallas_call is a
fusion barrier, composites sometimes win — measure).
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["fused_ffn", "ffn_is_supported"]


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _gelu_tanh(x):
    # GPT-2's approximate gelu, computed in fp32 inside the kernel
    c = math.sqrt(2.0 / math.pi)
    return 0.5 * x * (1.0 + jnp.tanh(c * (x + 0.044715 * x * x * x)))


def _gelu_erf(x):
    # exact gelu (the reference fused_feedforward_op's "gelu")
    return 0.5 * x * (1.0 + jax.lax.erf(x * (2.0 ** -0.5)))


_ACTS = {"gelu_tanh": _gelu_tanh, "gelu": _gelu_erf}


def ffn_is_supported(m, k, f, dtype) -> bool:
    """x: [M, K], W1: [K, F], W2: [F, K]. Lane-dim tiling: K and F must
    be 128-multiples (the bench shapes are: 768/3072, 1024/2816...)."""
    if k % 128 or f % 128:
        return False
    if m < 8:
        return False
    return jnp.dtype(dtype) in (jnp.float32, jnp.bfloat16, jnp.float16)


def _kernel(x_ref, w1_ref, b1_ref, w2_ref, b2_ref, o_ref, acc_sc,
            *, bm, bf, nf, act="gelu_tanh"):
    fi = pl.program_id(1)

    @pl.when(fi == 0)
    def _():
        acc_sc[:] = jnp.zeros_like(acc_sc)

    x = x_ref[...]                                   # [bm, K]
    w1 = w1_ref[...]                                 # [K, bf]
    pre = jax.lax.dot_general(x, w1, (((1,), (0,)), ((), ())),
                              preferred_element_type=jnp.float32)
    pre = pre + b1_ref[...].astype(jnp.float32)      # [bm, bf]
    t = _ACTS[act](pre).astype(x.dtype)
    w2 = w2_ref[...]                                 # [bf, K]
    acc_sc[:] += jax.lax.dot_general(t, w2, (((1,), (0,)), ((), ())),
                                     preferred_element_type=jnp.float32)

    @pl.when(fi == nf - 1)
    def _():
        o_ref[...] = (acc_sc[:] +
                      b2_ref[...].astype(jnp.float32)).astype(o_ref.dtype)


def _fwd_kernel_call(x, w1, b1, w2, b2, bm, bf, act):
    m, k = x.shape
    f = w1.shape[1]
    nf = f // bf
    grid = (m // bm, nf)
    return pl.pallas_call(
        functools.partial(_kernel, bm=bm, bf=bf, nf=nf, act=act),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, k), lambda mi, fi: (mi, 0)),
            pl.BlockSpec((k, bf), lambda mi, fi: (0, fi)),
            pl.BlockSpec((1, bf), lambda mi, fi: (0, fi)),
            pl.BlockSpec((bf, k), lambda mi, fi: (fi, 0)),
            pl.BlockSpec((1, k), lambda mi, fi: (0, 0)),
        ],
        out_specs=pl.BlockSpec((bm, k), lambda mi, fi: (mi, 0)),
        scratch_shapes=[pltpu.VMEM((bm, k), jnp.float32)],
        out_shape=jax.ShapeDtypeStruct((m, k), x.dtype),
        interpret=_interpret(),
    )(x, w1, b1.reshape(1, f), w2, b2.reshape(1, k))


def _pick_bm(m, k, f, bf, dtype):
    """Row-tile: big enough to feed the MXU, small enough that
    x + w1/w2 blocks + fp32 acc fit VMEM (~16 MB budget)."""
    itemsize = jnp.dtype(dtype).itemsize
    for bm in (1024, 512, 256, 128, 64, 32, 16, 8):
        if m % bm:
            continue
        vmem = (bm * k * itemsize          # x tile
                + 2 * k * bf * itemsize    # w1 + w2 blocks
                + bm * bf * 4              # pre/t tile (fp32)
                + bm * k * 4)              # accumulator
        if vmem <= 12 * 1024 * 1024:
            return bm
    return None


@functools.partial(jax.custom_vjp, nondiff_argnums=(5,))
def fused_ffn(x, w1, b1, w2, b2, activation="gelu_tanh"):
    """out = act(x @ w1 + b1) @ w2 + b2 with act in {gelu_tanh, gelu
    (exact/erf)}; x: [..., K] flattened to [M, K] internally. Falls back
    to the XLA composite when shapes don't tile (callers may also gate
    on ffn_is_supported)."""
    out, _ = _fused_ffn_fwd(x, w1, b1, w2, b2, activation)
    return out


def _composite(x2, w1, b1, w2, b2, activation="gelu_tanh"):
    t = _ACTS[activation]((x2 @ w1 + b1).astype(jnp.float32)) \
        .astype(x2.dtype)
    return t @ w2 + b2


def _fused_ffn_fwd(x, w1, b1, w2, b2, activation="gelu_tanh"):
    lead = x.shape[:-1]
    k = x.shape[-1]
    f = w1.shape[1]
    x2 = x.reshape(-1, k)
    m = x2.shape[0]
    bf = _pick_bf(f)
    bm = _pick_bm(m, k, f, bf or 128, x.dtype)
    if not ffn_is_supported(m, k, f, x.dtype) or bm is None or bf is None:
        out = _composite(x2, w1, b1, w2, b2, activation)
    else:
        out = _fwd_kernel_call(x2, w1, b1, w2, b2, bm, bf, activation)
    return out.reshape(*lead, k), (x, w1, b1, w2, b2)


def _dgelu(pre, activation):
    if activation == "gelu_tanh":
        c = math.sqrt(2.0 / math.pi)
        u = c * (pre + 0.044715 * pre ** 3)
        th = jnp.tanh(u)
        return 0.5 * (1.0 + th) + 0.5 * pre * (1.0 - th * th) * c * (
            1.0 + 3 * 0.044715 * pre ** 2)
    # exact gelu: d/dx = Phi(x) + x*phi(x)
    return (0.5 * (1.0 + jax.lax.erf(pre * (2.0 ** -0.5)))
            + pre * jnp.exp(-0.5 * pre * pre)
            * (1.0 / math.sqrt(2.0 * math.pi)))


# ---------------------------------------------------------------------------
# Fused BACKWARD (opt-in PADDLE_TPU_FUSED_FFN_BWD=1 — gated on the
# forward's on-chip A/B first, r5 verdict #5). The composite backward
# materializes pre/t/dt/dpre at [M, F] in fp32 (4 x ~50 MB of HBM
# traffic at the GPT-2 headline shape); these kernels recompute the
# [bm, bf] tiles in VMEM instead, reading only x/g row tiles and weight
# blocks. A Pallas TPU output block may only be revisited on CONSECUTIVE
# grid steps, and dx accumulates over F while dw1/dw2/db1 accumulate
# over M — two kernels with opposite inner grid axes:
#   bwd-dx : grid (M/bm, F/bf), F inner, dx_acc revisited per row tile;
#   bwd-dw : grid (F/bf, M/bm), M inner, dw1/dw2/db1 accs per F block.
# Reference: the grad kernels of fused_feedforward_op.cu.
# ---------------------------------------------------------------------------

def _bwd_dx_kernel(x_ref, g_ref, w1_ref, b1_ref, w2_ref, o_ref, acc_sc,
                   *, nf, act):
    fi = pl.program_id(1)

    @pl.when(fi == 0)
    def _():
        acc_sc[:] = jnp.zeros_like(acc_sc)

    x = x_ref[...]                                   # [bm, K]
    g = g_ref[...]                                   # [bm, K]
    pre = jax.lax.dot_general(x, w1_ref[...], (((1,), (0,)), ((), ())),
                              preferred_element_type=jnp.float32)
    pre = pre + b1_ref[...].astype(jnp.float32)      # [bm, bf]
    dt = jax.lax.dot_general(g, w2_ref[...], (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    dpre = (dt * _dgelu(pre, act)).astype(x.dtype)   # [bm, bf]
    acc_sc[:] += jax.lax.dot_general(dpre, w1_ref[...],
                                     (((1,), (1,)), ((), ())),
                                     preferred_element_type=jnp.float32)

    @pl.when(fi == nf - 1)
    def _():
        o_ref[...] = acc_sc[:].astype(o_ref.dtype)


def _bwd_dw_kernel(x_ref, g_ref, w1_ref, b1_ref, w2_ref,
                   dw1_ref, dw2_ref, db1_ref,
                   dw1_sc, dw2_sc, db1_sc, *, nm, act):
    mi = pl.program_id(1)

    @pl.when(mi == 0)
    def _():
        dw1_sc[:] = jnp.zeros_like(dw1_sc)
        dw2_sc[:] = jnp.zeros_like(dw2_sc)
        db1_sc[:] = jnp.zeros_like(db1_sc)

    x = x_ref[...]                                   # [bm, K]
    g = g_ref[...]                                   # [bm, K]
    pre = jax.lax.dot_general(x, w1_ref[...], (((1,), (0,)), ((), ())),
                              preferred_element_type=jnp.float32)
    pre = pre + b1_ref[...].astype(jnp.float32)      # [bm, bf]
    t = _ACTS[act](pre).astype(x.dtype)
    dt = jax.lax.dot_general(g, w2_ref[...], (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    dpre32 = dt * _dgelu(pre, act)
    dpre = dpre32.astype(x.dtype)
    dw1_sc[:] += jax.lax.dot_general(x, dpre, (((0,), (0,)), ((), ())),
                                     preferred_element_type=jnp.float32)
    dw2_sc[:] += jax.lax.dot_general(t, g, (((0,), (0,)), ((), ())),
                                     preferred_element_type=jnp.float32)
    db1_sc[:] += jnp.sum(dpre32, axis=0, keepdims=True)

    @pl.when(mi == nm - 1)
    def _():
        dw1_ref[...] = dw1_sc[:].astype(dw1_ref.dtype)
        dw2_ref[...] = dw2_sc[:].astype(dw2_ref.dtype)
        db1_ref[...] = db1_sc[:].astype(db1_ref.dtype)


def _pick_bf(f):
    """Shared F-tile choice: bf must DIVIDE f exactly (nf = f // bf
    silently drops tail columns otherwise) — fwd and bwd must agree."""
    return next((c for c in (512, 256, 128) if f % c == 0), None)


def _pick_bm_bwd(m, k, bf, dtype, which):
    """Row tile for ONE bwd kernel ('dx' or 'dw') — each pallas_call has
    its own VMEM, so each is budgeted for only its own tiles/scratch."""
    itemsize = jnp.dtype(dtype).itemsize
    for bm in (512, 256, 128, 64, 32, 16, 8):
        if m % bm:
            continue
        vmem = (2 * bm * k * itemsize      # x + g tiles
                + 2 * k * bf * itemsize    # w1 + w2 blocks
                + 3 * bm * bf * 4)         # pre/dt/dpre32 (fp32)
        if which == "dx":
            vmem += bm * bf * itemsize     # dpre cast for the dot
            vmem += bm * k * 4             # dx accumulator
        else:
            vmem += 2 * bm * bf * itemsize  # t + dpre casts
            vmem += 2 * k * bf * 4 + bf * 4  # dw1/dw2/db1 accumulators
        if vmem <= 12 * 1024 * 1024:
            return bm
    return None


def _bwd_kernel_calls(x2, g2, w1, b1, w2, bm_dx, bm_dw, bf, act):
    m, k = x2.shape
    f = w1.shape[1]
    nf = f // bf
    b1r = b1.reshape(1, f)
    bm, nm = bm_dx, m // bm_dx
    dx = pl.pallas_call(
        functools.partial(_bwd_dx_kernel, nf=nf, act=act),
        grid=(nm, nf),
        in_specs=[
            pl.BlockSpec((bm, k), lambda mi, fi: (mi, 0)),
            pl.BlockSpec((bm, k), lambda mi, fi: (mi, 0)),
            pl.BlockSpec((k, bf), lambda mi, fi: (0, fi)),
            pl.BlockSpec((1, bf), lambda mi, fi: (0, fi)),
            pl.BlockSpec((bf, k), lambda mi, fi: (fi, 0)),
        ],
        out_specs=pl.BlockSpec((bm, k), lambda mi, fi: (mi, 0)),
        scratch_shapes=[pltpu.VMEM((bm, k), jnp.float32)],
        out_shape=jax.ShapeDtypeStruct((m, k), x2.dtype),
        interpret=_interpret(),
    )(x2, g2, w1, b1r, w2)
    bm, nm = bm_dw, m // bm_dw
    dw1, dw2, db1 = pl.pallas_call(
        functools.partial(_bwd_dw_kernel, nm=nm, act=act),
        grid=(nf, nm),
        in_specs=[
            pl.BlockSpec((bm, k), lambda fi, mi: (mi, 0)),
            pl.BlockSpec((bm, k), lambda fi, mi: (mi, 0)),
            pl.BlockSpec((k, bf), lambda fi, mi: (0, fi)),
            pl.BlockSpec((1, bf), lambda fi, mi: (0, fi)),
            pl.BlockSpec((bf, k), lambda fi, mi: (fi, 0)),
        ],
        out_specs=[
            pl.BlockSpec((k, bf), lambda fi, mi: (0, fi)),
            pl.BlockSpec((bf, k), lambda fi, mi: (fi, 0)),
            pl.BlockSpec((1, bf), lambda fi, mi: (0, fi)),
        ],
        scratch_shapes=[pltpu.VMEM((k, bf), jnp.float32),
                        pltpu.VMEM((bf, k), jnp.float32),
                        pltpu.VMEM((1, bf), jnp.float32)],
        out_shape=[jax.ShapeDtypeStruct((k, f), w1.dtype),
                   jax.ShapeDtypeStruct((f, k), w2.dtype),
                   jax.ShapeDtypeStruct((1, f), jnp.float32)],
        interpret=_interpret(),
    )(x2, g2, w1, b1r, w2)
    return dx, dw1, dw2, db1.reshape(f)


def _fused_ffn_bwd(activation, res, g):
    import os
    x, w1, b1, w2, b2 = res
    k = x.shape[-1]
    f = w1.shape[1]
    x2 = x.reshape(-1, k)
    g2 = g.reshape(-1, k)
    m = x2.shape[0]
    db2 = jnp.sum(g2.astype(jnp.float32), axis=0)
    bf = _pick_bf(f)
    bm_dx = _pick_bm_bwd(m, k, bf or 128, x.dtype, "dx")
    bm_dw = _pick_bm_bwd(m, k, bf or 128, x.dtype, "dw")
    if (os.environ.get("PADDLE_TPU_FUSED_FFN_BWD") == "1"
            and ffn_is_supported(m, k, f, x.dtype)
            and bm_dx is not None and bm_dw is not None
            and bf is not None):
        dx, dw1, dw2, db1 = _bwd_kernel_calls(x2, g2, w1, b1, w2,
                                              bm_dx, bm_dw, bf,
                                              activation)
        return (dx.reshape(x.shape), dw1, db1.astype(b1.dtype),
                dw2, db2.astype(b2.dtype))
    # composite backward: recompute the intermediate (inputs-only
    # residuals); grads as plain XLA matmuls with fp32 accumulation
    pre = (jax.lax.dot_general(x2, w1, (((1,), (0,)), ((), ())),
                               preferred_element_type=jnp.float32)
           + b1.astype(jnp.float32))
    t = _ACTS[activation](pre)
    dt = jax.lax.dot_general(g2, w2, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    dpre = dt * _dgelu(pre, activation)
    dx = jax.lax.dot_general(dpre.astype(x2.dtype), w1,
                             (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    dw1 = jax.lax.dot_general(x2, dpre.astype(x2.dtype),
                              (((0,), (0,)), ((), ())),
                              preferred_element_type=jnp.float32)
    dw2 = jax.lax.dot_general(t.astype(x2.dtype), g2,
                              (((0,), (0,)), ((), ())),
                              preferred_element_type=jnp.float32)
    db1 = jnp.sum(dpre, axis=0)
    return (dx.astype(x.dtype).reshape(x.shape),
            dw1.astype(w1.dtype), db1.astype(b1.dtype),
            dw2.astype(w2.dtype), db2.astype(b2.dtype))


fused_ffn.defvjp(_fused_ffn_fwd, _fused_ffn_bwd)
