"""paddle.sysconfig. Parity: python/paddle/sysconfig.py :: get_include,
get_lib — paths a C++ extension build needs to find headers/libraries."""
from __future__ import annotations

import os

__all__ = ["get_include", "get_lib"]

_PKG = os.path.dirname(os.path.abspath(__file__))


def get_include() -> str:
    """Directory of C headers shipped with the package (the native runtime's
    plain-C ABI declarations live alongside csrc)."""
    return os.path.join(_PKG, "include")


def get_lib() -> str:
    """Directory containing the framework's compiled shared libraries
    (libpaddle_tpu_runtime.so is built on demand next to its source — see
    paddle_tpu/core/native.py::_lib_path)."""
    return os.path.join(os.path.dirname(_PKG), "csrc")
