"""paddle.reader (legacy reader decorators) + paddle.batch. Parity:
python/paddle/reader/decorator.py :: map_readers, shuffle, buffered, compose,
chain, firstn, cache, xmap_readers and python/paddle/batch.py :: batch.
Generator-composition utilities predating paddle.io; kept for API parity."""
from __future__ import annotations

import itertools
import queue as _queue
import random as _random
import threading

__all__ = ["batch", "map_readers", "shuffle", "buffered", "compose",
           "chain", "firstn", "cache", "xmap_readers"]


class _ReaderError:
    """Wrapper carrying a producer-thread exception to the consumer."""

    def __init__(self, exc):
        self.exc = exc


def batch(reader, batch_size: int, drop_last: bool = False):
    """Compose a sample reader into a batch reader (paddle.batch)."""

    def batch_reader():
        b = []
        for sample in reader():
            b.append(sample)
            if len(b) == batch_size:
                yield b
                b = []
        if b and not drop_last:
            yield b
    return batch_reader


def map_readers(func, *readers):
    """Yield func(*items) zipped across multiple readers."""

    def reader():
        its = [r() for r in readers]
        for items in zip(*its):
            yield func(*items)
    return reader


def shuffle(reader, buf_size: int):
    """Shuffle within a sliding buffer of buf_size samples."""

    def shuffled_reader():
        buf = []
        for sample in reader():
            buf.append(sample)
            if len(buf) >= buf_size:
                _random.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            _random.shuffle(buf)
            yield from buf
    return shuffled_reader


def buffered(reader, size: int):
    """Prefetch up to `size` samples on a producer thread."""

    end = object()

    def buffered_reader():
        q: _queue.Queue = _queue.Queue(maxsize=size)

        def produce():
            try:
                for sample in reader():
                    q.put(sample)
            except BaseException as exc:  # surface in the consumer
                q.put(_ReaderError(exc))
            finally:
                q.put(end)

        t = threading.Thread(target=produce, daemon=True)
        t.start()
        while True:
            sample = q.get()
            if sample is end:
                break
            if isinstance(sample, _ReaderError):
                raise sample.exc
            yield sample
    return buffered_reader


class ComposeNotAligned(ValueError):
    pass


def compose(*readers, check_alignment: bool = True):
    """Zip readers sample-wise into flattened tuples."""

    def _flatten(x):
        if isinstance(x, tuple):
            return x
        return (x,)

    def composed_reader():
        its = [r() for r in readers]
        for items in itertools.zip_longest(*its):
            if check_alignment and any(i is None for i in items):
                raise ComposeNotAligned(
                    "readers produced different numbers of samples")
            yield sum((_flatten(i) for i in items), ())
    return composed_reader


def chain(*readers):
    """Concatenate readers end to end."""

    def chained_reader():
        for r in readers:
            yield from r()
    return chained_reader


def firstn(reader, n: int):
    """Limit a reader to its first n samples."""

    def firstn_reader():
        yield from itertools.islice(reader(), n)
    return firstn_reader


def cache(reader):
    """Materialize the reader once; replay from memory afterwards."""
    data = []
    filled = [False]

    def cache_reader():
        if not filled[0]:
            data.extend(reader())
            filled[0] = True
        yield from data
    return cache_reader


def xmap_readers(mapper, reader, process_num: int, buffer_size: int,
                 order: bool = False):
    """Parallel map over a reader with worker threads (the reference's
    thread pool; order=True preserves input order)."""

    end = object()

    def xreader():
        in_q: _queue.Queue = _queue.Queue(buffer_size)
        out_q: _queue.Queue = _queue.Queue(buffer_size)

        def feed():
            for i, sample in enumerate(reader()):
                in_q.put((i, sample))
            for _ in range(process_num):
                in_q.put(end)

        def work():
            try:
                while True:
                    item = in_q.get()
                    if item is end:
                        break
                    i, sample = item
                    out_q.put((i, mapper(sample)))
            except BaseException as exc:  # propagate to the consumer
                out_q.put(("__error__", exc))
            finally:
                out_q.put(end)

        threading.Thread(target=feed, daemon=True).start()
        workers = [threading.Thread(target=work, daemon=True)
                   for _ in range(process_num)]
        for w in workers:
            w.start()

        done = 0
        if order:
            pending: dict[int, object] = {}
            want = 0
            while done < process_num:
                item = out_q.get()
                if item is end:
                    done += 1
                    continue
                i, mapped = item
                if i == "__error__":
                    raise mapped
                pending[i] = mapped
                while want in pending:
                    yield pending.pop(want)
                    want += 1
            for i in sorted(pending):
                yield pending[i]
        else:
            while done < process_num:
                item = out_q.get()
                if item is end:
                    done += 1
                    continue
                if item[0] == "__error__":
                    raise item[1]
                yield item[1]
    return xreader
