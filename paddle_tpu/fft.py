"""paddle.fft over jnp.fft. Parity: python/paddle/fft.py (fft/ifft/rfft/
irfft + 2d/n variants, fftshift, fftfreq). XLA lowers these to TPU-friendly
FFT HLOs directly."""
from __future__ import annotations

import jax.numpy as jnp

from .tensor.tensor import Tensor, apply_op

__all__ = ["fft", "ifft", "rfft", "irfft", "hfft", "ihfft", "fft2", "ifft2",
           "fftn", "ifftn", "rfft2", "irfft2", "rfftn", "irfftn",
           "hfft2", "ihfft2", "hfftn", "ihfftn",
           "fftfreq", "rfftfreq", "fftshift", "ifftshift"]


def _wrap1(name):
    jfn = getattr(jnp.fft, name)

    def fn(x, n=None, axis=-1, norm="backward", name=None):
        return apply_op(lambda a: jfn(a, n=n, axis=axis, norm=norm), x)
    fn.__name__ = name
    return fn


def _wrap2(name, axes_default=(-2, -1)):
    jfn = getattr(jnp.fft, name)

    def fn(x, s=None, axes=axes_default, norm="backward", name=None):
        return apply_op(lambda a: jfn(a, s=s, axes=axes, norm=norm), x)
    fn.__name__ = name
    return fn


fft = _wrap1("fft")
ifft = _wrap1("ifft")
rfft = _wrap1("rfft")
irfft = _wrap1("irfft")
hfft = _wrap1("hfft")
ihfft = _wrap1("ihfft")
fft2 = _wrap2("fft2")
ifft2 = _wrap2("ifft2")
fftn = _wrap2("fftn", axes_default=None)
ifftn = _wrap2("ifftn", axes_default=None)
rfft2 = _wrap2("rfft2")
irfft2 = _wrap2("irfft2")
rfftn = _wrap2("rfftn", axes_default=None)
irfftn = _wrap2("irfftn", axes_default=None)


def _swap_norm(norm):
    # the standard Hermitian-FFT identity flips the normalization direction:
    # hfft(x, n, norm) == irfft(conj(x), n, swapped(norm)); norm=None means
    # "backward" everywhere in the numpy API, so it must swap too
    if norm is None:
        norm = "backward"
    return {"backward": "forward", "forward": "backward"}.get(norm, norm)


def _wrap_hermitian2(name, real_fn, conj_in, conj_out, axes_default=(-2, -1)):
    def fn(x, s=None, axes=axes_default, norm="backward", name=None):
        def f(a):
            a = jnp.conj(a) if conj_in else a
            out = getattr(jnp.fft, real_fn)(a, s=s, axes=axes,
                                            norm=_swap_norm(norm))
            return jnp.conj(out) if conj_out else out
        return apply_op(f, x)
    fn.__name__ = name
    return fn


# Hermitian-input FFTs with real output (and their inverses) in 2/N dims:
# jnp.fft has no hfft2/hfftn family, so build them from the identities
# hfftn(x) = irfftn(conj(x), swapped norm) and ihfftn(x) = conj(rfftn(x,
# swapped norm)) — the N-d generalization of numpy's own hfft/ihfft.
hfft2 = _wrap_hermitian2("hfft2", "irfft2", True, False)
hfftn = _wrap_hermitian2("hfftn", "irfftn", True, False, axes_default=None)
ihfft2 = _wrap_hermitian2("ihfft2", "rfft2", False, True)
ihfftn = _wrap_hermitian2("ihfftn", "rfftn", False, True, axes_default=None)


def fftfreq(n, d=1.0, dtype=None, name=None):
    return Tensor(jnp.fft.fftfreq(n, d))


def rfftfreq(n, d=1.0, dtype=None, name=None):
    return Tensor(jnp.fft.rfftfreq(n, d))


def fftshift(x, axes=None, name=None):
    return apply_op(lambda a: jnp.fft.fftshift(a, axes=axes), x)


def ifftshift(x, axes=None, name=None):
    return apply_op(lambda a: jnp.fft.ifftshift(a, axes=axes), x)
