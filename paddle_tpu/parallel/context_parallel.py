"""Context parallelism for long sequences: ring attention + Ulysses.

Capability parity: the reference's long-context stack (SURVEY §5.7) — the
2.6-era `sep` hybrid degree in
python/paddle/distributed/fleet/base/topology.py :: HybridCommunicateGroup
(Ulysses-style head-scatter alltoall through attention) and the
ring-flash-attention variants that live in the Paddle ecosystem repos.

TPU-native design (NOT a port): the sequence dim is a named mesh axis
("sep"); both schemes are written against `shard_map` collectives so XLA
schedules the ICI neighbor exchange / all_to_all asynchronously with the
per-chunk compute:

- **Ring attention**: K/V chunks rotate around the sep axis with
  `jax.lax.ppermute` (the natural match for TPU ICI ring topology); each
  step computes blockwise attention of the local Q chunk against the
  visiting K/V chunk and merges the partial results with the numerically
  stable log-sum-exp accumulation (same online-softmax identity as flash
  attention, lifted to the inter-chip level). Exact — not an approximation.
  Differentiable through `lax.scan` + `ppermute` (and each step can be
  rematerialized with `jax.checkpoint`, making activation memory O(S/n)).

- **Ulysses**: `all_to_all` re-shards [B, S/n, H, D] → [B, S, H/n, D] so
  attention itself runs dense per device over full sequence with a head
  slice, then the inverse all_to_all restores sequence sharding. Requires
  heads % sep == 0; preferred when H ≥ sep and sequence fits per-device
  memory after the gather.

Both are called INSIDE shard_map (see `make_ring_attention_fn` /
fleet sep wiring); inputs are the device-local chunks.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["ring_attention", "ulysses_attention", "make_ring_attention_fn",
           "make_ulysses_attention_fn"]

_NEG_INF = -1e30


def _chunk_attn(q, k, v, scale, mask):
    """Blockwise attention returning (out, lse) for one KV chunk.

    q: [B, Sq, H, D]; k, v: [B, Sk, Hk, D] (GQA: H % Hk == 0).
    mask: broadcastable to [Sq, Sk] boolean (True = attend), or None.
    out is the *normalized* chunk output; lse the per-row log-sum-exp —
    the pair merges exactly across chunks. fp32 softmax stats.
    """
    bq, sq, h, d = q.shape
    hk = k.shape[2]
    if h != hk:
        k = jnp.repeat(k, h // hk, axis=2)
        v = jnp.repeat(v, h // hk, axis=2)
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if mask is not None:
        s = jnp.where(mask[None, None], s, _NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    m = jnp.maximum(m, _NEG_INF)          # all-masked rows stay finite
    p = jnp.exp(s - m)
    if mask is not None:
        p = jnp.where(mask[None, None], p, 0.0)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    lse = (m + jnp.log(jnp.where(l == 0.0, 1.0, l)))[..., 0]   # [B,H,Sq]
    lse = jnp.where(l[..., 0] == 0.0, _NEG_INF, lse)
    denom = jnp.where(l == 0.0, 1.0, l)
    o = o / jnp.swapaxes(denom, 1, 2)      # [B,Sq,H,1] broadcast
    return o, lse


def _merge(o_a, lse_a, o_b, lse_b):
    """Merge two normalized partial attentions via their lse (exact)."""
    lse_m = jnp.maximum(lse_a, lse_b)
    # guard fully-masked (-inf-ish) rows
    lse_m = jnp.maximum(lse_m, _NEG_INF)
    wa = jnp.exp(lse_a - lse_m)
    wb = jnp.exp(lse_b - lse_m)
    denom = wa + wb
    denom = jnp.where(denom == 0.0, 1.0, denom)
    lse_new = lse_m + jnp.log(denom)
    wa = (wa / denom)[..., None].swapaxes(1, 2)   # [B,Sq,H,1]
    wb = (wb / denom)[..., None].swapaxes(1, 2)
    return o_a * wa + o_b * wb, lse_new


def _use_ring_kernel(q, k) -> bool:
    """Dispatch the per-step chunk to the Pallas flash kernel on real TPU
    only (PADDLE_TPU_RING_COMPOSITE=1 forces the dense composite).

    On CPU the composite stays the default (interpret-mode pallas is
    orders slower), but PADDLE_TPU_RING_KERNEL_CPU=1 forces the kernel —
    _cp_fn's check_vma=False lifted the jax-0.9 limitation that used to
    make pallas-inside-shard_map impossible on CPU, so the COMBINED
    ring+kernel path is now CPU-testable (r4 weak #3); on-chip
    validation still happens in the session window."""
    import os
    if os.environ.get("PADDLE_TPU_RING_COMPOSITE") == "1":
        return False
    if jax.default_backend() != "tpu" and \
            os.environ.get("PADDLE_TPU_RING_KERNEL_CPU") != "1":
        return False
    # deliberately NOT a blanket except: an ImportError/regression in the
    # kernel module must surface, not silently downgrade every TPU ring
    # step to the O(S^2) dense composite
    from ..ops.pallas.ring_chunk_attention import is_supported
    # is_supported takes kernel layout [B, H, S, D]; ring holds
    # [B, S, H, D]
    qs = (q.shape[0], q.shape[2], q.shape[1], q.shape[3])
    ks = (k.shape[0], k.shape[2], k.shape[1], k.shape[3])
    return is_supported(qs, ks, q.dtype)


def ring_attention(q, k, v, axis_name: str = "sep", causal: bool = False,
                   scale: Optional[float] = None, remat: bool = True):
    """Exact ring attention over a named mesh axis; call inside shard_map.

    q,k,v: device-local [B, S/n, H, D] chunks, sequence sharded over
    `axis_name` in ring order (chunk i on mesh index i). Returns the local
    output chunk [B, S/n, H, D] in q.dtype.
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    n = jax.lax.axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)
    sq = q.shape[1]
    perm = [(i, (i + 1) % n) for i in range(n)]   # kv moves to next rank

    def causal_mask(src):
        # global rows my*sq + r ; cols src*sq + c ; attend iff col <= row
        rows = my * sq + jax.lax.broadcasted_iota(jnp.int32, (sq, sq), 0)
        cols = src * sq + jax.lax.broadcasted_iota(jnp.int32, (sq, sq), 1)
        return cols <= rows

    use_kernel = _use_ring_kernel(q, k)

    def step(carry, t):
        o_acc, lse_acc, k_cur, v_cur = carry
        src = (my - t) % n          # which rank's chunk is visiting

        if use_kernel:
            # Pallas flash chunk (ops/pallas/ring_chunk_attention): the
            # visiting diagonal is the traced offset (my - src) * sq —
            # one compiled kernel serves every ring step; lse is a
            # differentiated output so merge weights backprop exactly
            def compute(q_, k_, v_):
                from ..ops.pallas.ring_chunk_attention import \
                    ring_chunk_attention
                off = (my - src) * sq if causal else k_.shape[1]
                o_t, lse_t = ring_chunk_attention(
                    jnp.swapaxes(q_, 1, 2), jnp.swapaxes(k_, 1, 2),
                    jnp.swapaxes(v_, 1, 2), off, scale)
                return jnp.swapaxes(o_t, 1, 2).astype(jnp.float32), lse_t
        else:
            mask = causal_mask(src) if causal else None

            def compute(q_, k_, v_):
                return _chunk_attn(q_, k_, v_, scale, mask)

        if remat:
            compute = jax.checkpoint(compute)
        o_i, lse_i = compute(q, k_cur, v_cur)
        o_acc, lse_acc = _merge(o_acc, lse_acc, o_i, lse_i)

        def rotate(kv):
            k_, v_ = kv
            return (jax.lax.ppermute(k_, axis_name, perm),
                    jax.lax.ppermute(v_, axis_name, perm))

        # last step's rotation would be discarded — skip the ICI exchange
        k_nxt, v_nxt = jax.lax.cond(t < n - 1, rotate, lambda kv: kv,
                                    (k_cur, v_cur))
        return (o_acc, lse_acc, k_nxt, v_nxt), None

    # initial accumulators must carry the same varying-over-axes type as the
    # per-step outputs (jax>=0.8 vma typing inside shard_map); deriving them
    # from q inherits q's full vma set (e.g. (pp, sep) when nested inside a
    # pipeline shard_map), which a bare pvary over axis_name would not
    zero_q = q.astype(jnp.float32) * 0.0
    o0 = zero_q
    lse0 = jnp.swapaxes(zero_q[..., 0], 1, 2) + _NEG_INF   # [B,H,Sq]
    (o, _, _, _), _ = jax.lax.scan(step, (o0, lse0, k, v),
                                   jnp.arange(n))
    return o.astype(q.dtype)


def ulysses_attention(q, k, v, axis_name: str = "sep", causal: bool = False,
                      scale: Optional[float] = None):
    """Ulysses sequence parallelism: all_to_all seq-shard → head-shard,
    dense attention per device, inverse all_to_all. Call inside shard_map.

    q,k,v: local [B, S/n, H, D]; H % n == 0 required. Exact.
    """
    n = jax.lax.axis_size(axis_name)
    if q.shape[2] % n != 0:
        raise ValueError(f"heads {q.shape[2]} not divisible by sep={n}")
    if k.shape[2] % n != 0:
        raise ValueError(
            f"kv heads {k.shape[2]} not divisible by sep={n}; Ulysses "
            f"re-shards heads across the sep axis — use ring_attention for "
            f"GQA configs with kv_heads < sep")

    def scatter_heads(x):
        # [B, S/n, H, D] -> [B, S, H/n, D]
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                  tiled=True)

    def gather_heads(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                                  tiled=True)

    qh, kh, vh = scatter_heads(q), scatter_heads(k), scatter_heads(v)
    if scale is None:
        scale = q.shape[-1] ** -0.5
    # the per-device attention runs over the FULL sequence — exactly
    # where the dense composite's O(S^2) score materialization hurts
    # (S=16k => gigabytes of [B, H/n, S, S] fp32). Stream the flash
    # kernel instead whenever it tiles (TPU; PADDLE_TPU_ULYSSES_FLASH_CPU
    # =1 exercises the same path in interpret mode for tests), with the
    # dense composite as the untileable-shape fallback.
    import os
    from ..ops.pallas import flash_attention as fa
    use_flash = (jax.default_backend() == "tpu"
                 or os.environ.get("PADDLE_TPU_ULYSSES_FLASH_CPU") == "1")
    if use_flash and os.environ.get(
            "PADDLE_TPU_ULYSSES_COMPOSITE") != "1" and \
            fa.is_supported(qh.shape, qh.dtype):
        o = fa.flash_attention(qh, kh, vh, causal=causal, scale=scale)
        return gather_heads(o.astype(q.dtype))
    sq = qh.shape[1]
    mask = None
    if causal:
        rows = jax.lax.broadcasted_iota(jnp.int32, (sq, sq), 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, (sq, sq), 1)
        mask = cols <= rows
    o, _ = _chunk_attn(qh, kh, vh, scale, mask)
    return gather_heads(o.astype(q.dtype))


def _cp_fn(impl, mesh: Mesh, axis_name: str, causal: bool,
           scale: Optional[float]):
    spec = P(None, axis_name, None, None)

    # The varying-manual-axes static check trips on interpret-mode
    # pallas_call inside shard_map (jax-0.9; the error itself prescribes
    # check_vma=False) — that limitation is interpret-only, so the check
    # stays LIVE on real TPU (it catches wrong out_spec / replication
    # bugs at trace time) and is disabled off-chip, which makes the
    # combined ring+kernel path testable on the CPU mesh (r4 weak #3).
    # PADDLE_TPU_CP_CHECK_VMA=0 force-disables it everywhere — the
    # escape hatch if the first on-chip compile trips it after all.
    import os
    vma = (jax.default_backend() == "tpu"
           and os.environ.get("PADDLE_TPU_CP_CHECK_VMA") != "0")

    @functools.partial(
        shard_map, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=vma)
    def fn(q, k, v):
        return impl(q, k, v, axis_name=axis_name, causal=causal, scale=scale)

    return fn


def make_ring_attention_fn(mesh: Mesh, axis_name: str = "sep",
                           causal: bool = False,
                           scale: Optional[float] = None):
    """Global-view ring attention: takes/returns full [B, S, H, D] arrays
    sharded P(None, axis, None, None); jit-compatible."""
    return _cp_fn(ring_attention, mesh, axis_name, causal, scale)


def make_ulysses_attention_fn(mesh: Mesh, axis_name: str = "sep",
                              causal: bool = False,
                              scale: Optional[float] = None):
    return _cp_fn(ulysses_attention, mesh, axis_name, causal, scale)
