"""Pipeline-parallel execution engine over a named "pp" mesh axis.

Capability parity: python/paddle/distributed/fleet/meta_parallel/
pipeline_parallel.py :: PipelineParallel.train_batch (micro-batch 1F1B) and
pp_utils/p2p_communication.py (stage-to-stage activation passing).

TPU-native design (NOT a port): the reference runs one OS process per stage
and hand-schedules NCCL P2P send/recv. Here every stage is a mesh
coordinate; one SPMD program executes the whole schedule inside a
`lax.scan` of M + P - 1 ticks, with `ppermute` moving activations to the
next stage each tick (the ICI neighbor exchange). Backward falls out of
`jax.grad` through the scan — the reverse-mode schedule is exactly the
pipeline backward pass, and per-tick `jax.checkpoint` gives the 1F1B-class
activation-memory profile (store only stage inputs, recompute inside).
XLA's latency-hiding scheduler overlaps each ppermute with the next tick's
compute; there is no TCPStore/SendRecvMeta machinery to replicate because
shapes are static under jit.

Usage (see tests/test_pipeline_engine.py):
    mesh = Mesh(devs, ("pp",))
    fn = make_gpipe_fn(stage_fn, mesh)   # stage_fn(stage_params, h) -> h
    out = fn(stacked_params, microbatches)     # params: [P, ...] pp-sharded
"""
from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

__all__ = ["gpipe", "gpipe_interleaved", "make_gpipe_fn", "microbatch",
           "unmicrobatch"]


def _pvary(x, axis_name):
    """Mark x as varying over axis_name (pcast where available; pvary on
    older jax)."""
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(x, (axis_name,), to="varying")
    return jax.lax.pvary(x, (axis_name,))


def microbatch(x, num_micro: int):
    """[B, ...] -> [M, B/M, ...]."""
    b = x.shape[0]
    assert b % num_micro == 0, f"batch {b} not divisible by {num_micro}"
    return x.reshape(num_micro, b // num_micro, *x.shape[1:])


def unmicrobatch(x):
    return x.reshape(x.shape[0] * x.shape[1], *x.shape[2:])


def gpipe(stage_fn: Callable, stage_params, x_mb, axis_name: str = "pp",
          remat: bool = True, window: int | str | None = "auto"):
    """Run the micro-batch pipeline schedule; call inside shard_map.

    stage_fn(stage_params, h) -> h : applies ONE stage's layers (an inner
        lax.scan over the stage's layer slice for multi-layer stages).
    stage_params: this device's stage slice (leading stage axis removed).
    x_mb: [M, mb, ...] microbatched stage-0 input (replicated over pp).
    Returns [M, mb, ...] final-stage outputs, identical on every pp rank.

    Activation memory (the 1F1B-class bound the reference's schedule
    exists for): ticks are grouped into `window`-sized blocks, each under
    one jax.checkpoint — backward stores only the BLOCK-BOUNDARY carries
    (one microbatch activation each) and replays a block's ticks when its
    grads are needed. Stored boundary activations = T/W + W peak
    (T = M+P-1 ticks), minimized at W=√T ("auto"). Recompute cost is ≤2
    extra forwards: one for the block replay, one for the per-tick remat
    that stays ON inside blocks so a replayed block holds W tick INPUTS
    rather than W ticks' full within-stage intermediates (for multi-layer
    stages the latter dominates peak memory). The outputs bank leaves the
    scan carry entirely: every tick emits its state as a scan output and
    the last-stage outputs are the contiguous tick slice [P-1, P-1+M) — a
    linear gather that saves no residuals. window=None disables blocking
    (single scan, per-tick remat only); remat=False disables BOTH remat
    levels unless `window` is explicitly set to an int.
    """
    p = jax.lax.axis_size(axis_name)
    i = jax.lax.axis_index(axis_name)
    m = x_mb.shape[0]
    total = m + p - 1
    perm = [(j, (j + 1) % p) for j in range(p)]

    fn = jax.checkpoint(stage_fn) if remat else stage_fn

    state0 = _pvary(jnp.zeros_like(x_mb[0]), axis_name)

    def tick(state, t):
        incoming = jax.lax.ppermute(state, axis_name, perm)
        mb = jax.lax.dynamic_index_in_dim(x_mb, jnp.minimum(t, m - 1), 0,
                                          keepdims=False)
        inp = jnp.where(i == 0, mb, incoming)
        new = fn(stage_params, inp)
        return new, new

    if window == "auto":
        # remat=False means "spend memory for backward speed" — don't
        # silently reintroduce recompute via the block checkpoint
        window = None if not remat else \
            max(int(np.ceil(np.sqrt(total))), 1)
    if window and 1 < window < total:
        n_win = -(-total // window)           # ceil; tail ticks padded
        ts = jnp.arange(n_win * window).reshape(n_win, window)

        @jax.checkpoint
        def run_window(state, t_block):
            return jax.lax.scan(tick, state, t_block)

        _, ys = jax.lax.scan(run_window, state0, ts)
        ys = ys.reshape(n_win * window, *ys.shape[2:])
    else:
        _, ys = jax.lax.scan(tick, state0, jnp.arange(total))
    # device p-1's tick t ≥ p-1 completed microbatch t-(p-1)
    outs = jax.lax.slice_in_dim(ys, p - 1, p - 1 + m, axis=0)
    # broadcast the final-stage outputs to every rank (loss is computed
    # replicated, exactly like the reference's shared-loss broadcast)
    outs = jnp.where(i == p - 1, outs, jnp.zeros_like(outs))
    return jax.lax.psum(outs, axis_name)


def gpipe_interleaved(stage_fn: Callable, chunk_params, x_mb,
                      axis_name: str = "pp", num_chunks: int = 2,
                      remat: bool = True):
    """Interleaved (virtual-pipeline) schedule; call inside shard_map.

    Parity: PipelineParallelWithInterleave (virtual_pp_degree model chunks
    per rank). Layer assignment is the reference's round-robin: of the
    v·P chunks in layer order, stage i holds chunks {i, P+i, 2P+i, ...}.

    TPU-native schedule (single SPMD scan, no P2P processes): microbatches
    are processed in depth-first waves of P. Device 0's emission clock τ
    advances one slot per tick; slot τ of wave w (u = τ - w·v·P) carries
    microbatch m = w·P + u%P at chunk c = u//P. An activation finishing
    chunk c on device P-1 re-enters device 0 exactly when the schedule
    processes (m, c+1) there, so no rank ever buffers more than the one
    in-flight activation — the per-device chunk select is a
    dynamic_index over the local [v, ...] chunk stack. Pipeline bubble is
    P-1 ticks total (vs v·(P-1) for running v sequential gpipe passes),
    matching the interleaved-1F1B bubble reduction. M not divisible by P
    leaves masked tail slots in the last wave; their TICKS are irreducible
    (ring latency), but their compute is skipped via lax.cond in the tick.

    chunk_params: this device's chunks, leading axis v (chunk c = global
        chunk c·P + i). stage_fn(one_chunk_params, h) -> h.
    x_mb: [M, mb, ...] microbatched input, replicated over pp.
    Returns [M, mb, ...] final outputs, identical on every pp rank.
    """
    p = jax.lax.axis_size(axis_name)
    i = jax.lax.axis_index(axis_name)
    m = x_mb.shape[0]
    v = num_chunks
    waves = -(-m // p)                      # ceil
    total = waves * v * p + p - 1
    perm = [(j, (j + 1) % p) for j in range(p)]

    fn = jax.checkpoint(stage_fn) if remat else stage_fn

    state0 = _pvary(jnp.zeros_like(x_mb[0]), axis_name)
    outs0 = _pvary(jnp.zeros_like(x_mb), axis_name)

    def tick(carry, t):
        state, outs = carry
        incoming = jax.lax.ppermute(state, axis_name, perm)
        tau = t - i                          # device-0 emission clock
        w = tau // (v * p)
        u = tau - w * (v * p)
        c = jnp.clip(u // p, 0, v - 1)
        mb_idx = jnp.clip(w * p + u % p, 0, m - 1)
        valid = (tau >= 0) & (tau < waves * v * p) & (w * p + u % p < m)

        inject = (i == 0) & (c == 0)
        mb = jax.lax.dynamic_index_in_dim(x_mb, mb_idx, 0, keepdims=False)
        inp = jnp.where(inject, mb, incoming)
        params_c = jax.tree.map(
            lambda a: jax.lax.dynamic_index_in_dim(a, c, 0, keepdims=False),
            chunk_params)
        # Invalid slots (ramp-up/down + the masked tail when M % P != 0)
        # SKIP the stage compute entirely — a real lax.cond, not a select:
        # inside shard_map the predicate is a per-device scalar, so the
        # false branch is a true no-op that passes the ring value through
        # instead of computing garbage and discarding it. The tail TICKS
        # themselves are irreducible: a chunk wave must span P ticks
        # because that is the ring latency before (mb, c+1) can re-enter
        # device 0, so a "shorter last wave" would ask for activations
        # that have not completed the ring yet.
        new = jax.lax.cond(valid, lambda: fn(params_c, inp),
                           lambda: incoming)

        done = (i == p - 1) & (c == v - 1) & valid
        cur = jax.lax.dynamic_index_in_dim(outs, mb_idx, 0, keepdims=False)
        outs = jax.lax.dynamic_update_index_in_dim(
            outs, jnp.where(done, new, cur), mb_idx, 0)
        return (new, outs), None

    (_, outs), _ = jax.lax.scan(tick, (state0, outs0), jnp.arange(total))
    outs = jnp.where(i == p - 1, outs, jnp.zeros_like(outs))
    return jax.lax.psum(outs, axis_name)


def make_gpipe_fn(stage_fn: Callable, mesh: Mesh, axis_name: str = "pp",
                  remat: bool = True, num_micro: int | None = None,
                  window: int | str | None = "auto"):
    """Global-view pipeline: params [P, ...] sharded over the pp axis,
    x either [M, mb, ...] pre-microbatched or [B, ...] with num_micro set.
    Returns full-batch outputs replicated over pp. jit-compatible.
    `window` passes through to gpipe (block-checkpoint size; None trades
    memory for backward speed)."""

    pspec = P(axis_name)

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(pspec, P()), out_specs=P())
    def run(stacked_params, x_mb):
        local = jax.tree.map(lambda a: a[0], stacked_params)
        out = gpipe(stage_fn, local, x_mb, axis_name=axis_name, remat=remat,
                    window=window)
        return out

    def fn(stacked_params, x):
        x_mb = x if num_micro is None else microbatch(x, num_micro)
        out = run(stacked_params, x_mb)
        return out if num_micro is None else unmicrobatch(out)

    return fn
