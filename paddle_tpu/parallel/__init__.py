"""paddle_tpu.parallel — TPU-native parallel execution utilities.

This is the scaling-book recipe as a library: pick a mesh (fleet topology),
annotate shardings (layers/optimizer set PartitionSpecs), device_put the
state, jit the step — XLA inserts the all-gathers/reduce-scatters/all-reduces
the reference implements as ProcessGroupNCCL calls.

Key entry points:
  apply_shardings(mesh)  — place every persistent tensor per its spec
  shard_batch(x, mesh)   — split the batch over the data axes (dp×sharding)
  make_train_step(...)   — functional jitted train step over sharded state
"""
from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..tensor.tensor import Tensor, persistent_tensors
from .context_parallel import (ring_attention, ulysses_attention,
                               make_ring_attention_fn,
                               make_ulysses_attention_fn)

__all__ = ["apply_shardings", "shard_batch", "data_spec", "current_mesh",
           "init_serving_mesh", "with_spec", "ring_attention",
           "ulysses_attention", "make_ring_attention_fn",
           "make_ulysses_attention_fn"]


def current_mesh() -> Optional[Mesh]:
    from ..distributed.fleet.base.topology import _HYBRID_GROUP
    hcg = _HYBRID_GROUP[0]
    return hcg.mesh if hcg is not None else None


def init_serving_mesh(mp: Optional[int] = None, *,
                      num_heads: Optional[int] = None,
                      ffn_dim: Optional[int] = None,
                      head_dim: Optional[int] = None,
                      weight_quant: Optional[str] = None
                      ) -> Optional[Mesh]:
    """Stand up (or reuse) a pure tensor-parallel mesh for serving:
    dp=pp=sharding=1, mp as given (default: ``PADDLE_SERVING_MESH_MP``;
    unset/0/1 = no mesh — returns whatever mesh is already active).
    Idempotent: if the active mesh already has the requested mp degree
    it is returned as-is; a CONFLICTING active mesh raises instead of
    silently re-initializing fleet under a live engine's feet.

    Pass the model's ``num_heads`` / ``ffn_dim`` to validate the full
    tensor-parallel layout up front: the KV pool and qkv/out-proj shard
    by head and the FFN weights by column over 'mp', so an indivisible
    axis is rejected HERE with an actionable error instead of surfacing
    as a downstream XLA shape failure (or a silently replicated stack).
    With ``weight_quant='int4'`` (plus ``head_dim``) the validation
    extends to the PACKED contracted axes: int4 packs two elements per
    byte, and the row-parallel stacks (out-proj [L, nh*hd/2, E], FFN-2
    [L, ffn/2, E]) split that packed axis over 'mp' — a shard boundary
    must land on a whole byte, so the HALF lengths must divide mp too.

    This is the one-call bring-up a sharded ``ServingEngine`` needs:

        init_serving_mesh(2)          # or PADDLE_SERVING_MESH_MP=2
        eng = ServingEngine(...)      # KV pool AND the stacked weights
                                      # shard over 'mp' (opt out of the
                                      # weight half with
                                      # PADDLE_SERVING_MESH_WEIGHTS=0)
    """
    import os
    if mp is None:
        mp = int(os.environ.get("PADDLE_SERVING_MESH_MP", "0") or 0)
    mp = int(mp)
    mesh = current_mesh()
    if mp <= 1:
        return mesh
    if num_heads is not None and num_heads % mp:
        raise ValueError(
            f"init_serving_mesh(mp={mp}): num_heads={num_heads} is not "
            f"divisible by mp — the qkv/out-proj weights and the KV "
            "pool shard by head over 'mp'; pick mp from the divisors "
            f"of {num_heads}")
    if ffn_dim is not None and ffn_dim % mp:
        raise ValueError(
            f"init_serving_mesh(mp={mp}): ffn_dim={ffn_dim} is not "
            "divisible by mp — the FFN weights shard by column over "
            f"'mp'; pick mp from the divisors of {ffn_dim}")
    if weight_quant == "int4":
        if ffn_dim is not None and (ffn_dim % 2 or (ffn_dim // 2) % mp):
            raise ValueError(
                f"init_serving_mesh(mp={mp}, weight_quant='int4'): "
                f"ffn_dim={ffn_dim} must be even AND its packed half "
                f"{ffn_dim // 2} divisible by mp — the row-parallel "
                "FFN-2 stack shards its int4-PACKED contracted axis, "
                "and a shard boundary must land on a whole byte")
        if num_heads is not None and head_dim is not None:
            hh = num_heads * head_dim
            if hh % 2 or (hh // 2) % mp:
                raise ValueError(
                    f"init_serving_mesh(mp={mp}, weight_quant='int4'): "
                    f"num_heads*head_dim={hh} must be even AND its "
                    f"packed half {hh // 2} divisible by mp — the "
                    "row-parallel out-proj stack shards its "
                    "int4-PACKED contracted axis in whole bytes")
    if mesh is not None:
        have = dict(mesh.shape).get("mp", 1)
        if have == mp:
            return mesh
        raise RuntimeError(
            f"init_serving_mesh(mp={mp}): a mesh with mp={have} is "
            "already active — one process, one hybrid topology (reset "
            "fleet state before re-initializing)")
    if jax.device_count() < mp:
        raise RuntimeError(
            f"init_serving_mesh(mp={mp}) needs >= {mp} devices, found "
            f"{jax.device_count()} — on CPU hosts set XLA_FLAGS="
            f"--xla_force_host_platform_device_count={mp} before the "
            "first jax import")
    if jax.device_count() % mp:
        raise RuntimeError(
            f"init_serving_mesh(mp={mp}): device count "
            f"{jax.device_count()} is not divisible by mp — a ragged "
            "mesh cannot be built; pick mp from the divisors of the "
            "device count (or adjust "
            "--xla_force_host_platform_device_count)")
    from ..distributed import fleet
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": mp,
                               "pp_degree": 1, "sharding_degree": 1}
    fleet.init(is_collective=True, strategy=strategy)
    return current_mesh()


def _valid_spec(arr, spec, mesh: Mesh) -> bool:
    """Spec axes must divide the array dims on this mesh."""
    if spec is None:
        return False
    for dim, names in enumerate(spec):
        if names is None:
            continue
        names = names if isinstance(names, tuple) else (names,)
        size = 1
        for n in names:
            size *= mesh.shape[n]
        if dim >= arr.ndim or arr.shape[dim] % size != 0:
            return False
    return True


def apply_shardings(mesh: Optional[Mesh] = None) -> int:
    """device_put every persistent tensor according to its sharding_spec
    (replicated when unset/indivisible). Returns #sharded tensors."""
    mesh = mesh or current_mesh()
    if mesh is None:
        return 0
    n = 0
    for t in persistent_tensors():
        arr = t._data
        if not hasattr(arr, "shape"):
            continue
        if jnp.issubdtype(arr.dtype, jnp.bool_) and arr.ndim == 0:
            continue
        spec = t.sharding_spec
        if spec is not None and _valid_spec(arr, spec, mesh):
            sh = NamedSharding(mesh, P(*spec))
            n += 1
        else:
            sh = NamedSharding(mesh, P())
        try:
            t._data = jax.device_put(arr, sh)
        except Exception:
            pass
    return n


def data_spec(ndim: int, mesh: Optional[Mesh] = None) -> P:
    """Batch dim sharded over the combined data axes (dp and the ZeRO
    sharding group both consume distinct data, exactly as Fleet does)."""
    return P(("dp", "sharding"), *([None] * (ndim - 1)))


def shard_batch(x, mesh: Optional[Mesh] = None):
    mesh = mesh or current_mesh()
    if mesh is None:
        return x
    arr = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    total = mesh.shape["dp"] * mesh.shape["sharding"]
    if arr.shape[0] % total != 0:
        return x if isinstance(x, Tensor) else Tensor(arr)
    sh = NamedSharding(mesh, data_spec(arr.ndim, mesh))
    out = jax.device_put(arr, sh)
    return Tensor(out) if not isinstance(x, Tensor) else Tensor(out)


def no_mp_mesh() -> bool:
    """Guard for opt-in Pallas fast paths (fused FFN & co.): a
    pallas_call is an SPMD/fusion barrier, so kernels must not receive
    mp-sharded operands (forced replication or partitioning failure).
    Callers route to the XLA composite whenever a model-parallel mesh is
    active. Lives here (a pure mesh query) so consulting it never drags
    in the pallas import chain while the feature flag is off."""
    mesh = current_mesh()
    return mesh is None or dict(mesh.shape).get("mp", 1) < 2


def with_spec(t: Tensor, *spec) -> Tensor:
    """Attach + apply a PartitionSpec to a tensor on the current mesh."""
    t.sharding_spec = P(*spec)
    from ..distributed.auto_parallel.api import bump_placement_generation
    bump_placement_generation()
    mesh = current_mesh()
    if mesh is not None and _valid_spec(t._data, t.sharding_spec, mesh):
        t._data = jax.device_put(t._data,
                                 NamedSharding(mesh, t.sharding_spec))
    return t
