"""Optimizer base + SGD/Momentum/Adam/AdamW/Adagrad/RMSProp/Lamb.

Parity: python/paddle/optimizer/{optimizer,adamw,adam,momentum,sgd,lamb}.py and
the fused AdamW Phi kernel (paddle/phi/kernels/gpu/adamw_kernel.cu ::
AdamwDenseKernel, multi_tensor_adam). TPU-first: updates are pure jnp
expressions; under paddle.jit.to_static the whole param-loop compiles into one
XLA program, which IS the multi-tensor fused form. Supports multi_precision
(bf16 params with fp32 master weights) as in AMP-O2.
"""
from __future__ import annotations

from typing import Iterable, Optional

import jax.numpy as jnp

from ..tensor.tensor import Parameter, Tensor, no_grad, register_persistent
from .lr import LRScheduler

__all__ = ["Optimizer", "SGD", "Momentum", "Adam", "AdamW", "Adafactor",
           "Adagrad", "Adadelta", "RMSProp", "Lamb", "Adamax", "NAdam",
           "RAdam", "ASGD", "Rprop"]


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None, multi_precision=False):
        self._lr = learning_rate
        self._parameter_list = list(parameters) if parameters is not None else None
        self._param_groups = None
        if self._parameter_list and isinstance(self._parameter_list[0], dict):
            self._param_groups = self._parameter_list
            flat = []
            for g in self._param_groups:
                flat.extend(g["params"])
            self._parameter_list = flat
        self._weight_decay = weight_decay
        self._grad_clip = grad_clip
        self._multi_precision = multi_precision
        self._accumulators: dict[str, dict[int, Tensor]] = {}
        self._master_weights: dict[int, Tensor] = {}
        self._step_count = 0
        if self._parameter_list:
            # plain trainable Tensors must live in the persistent registry
            # too: jit.to_static functionalizes persistent state, and an
            # optimizer-updated tensor outside it would trap a tracer
            for p in self._parameter_list:
                if isinstance(p, Tensor):
                    register_persistent(p)

    # ----------------------------------------------------------------- lr
    def get_lr(self) -> float:
        if isinstance(self._lr, LRScheduler):
            return self._lr()
        return float(self._lr)

    def set_lr(self, value: float):
        if isinstance(self._lr, LRScheduler):
            raise RuntimeError("cannot set_lr when using an LRScheduler")
        self._lr = float(value)

    def set_lr_scheduler(self, scheduler):
        self._lr = scheduler

    @property
    def _learning_rate(self):
        return self._lr

    # --------------------------------------------------------- accumulators
    def _acc(self, name: str, p: Parameter, init=None) -> Tensor:
        slot = self._accumulators.setdefault(name, {})
        key = id(p)
        if key in slot and slot[key]._data is None:
            del slot[key]  # dead slot: failed-trace rollback invalidated it
        if key not in slot:
            if init is None:
                arr = jnp.zeros_like(self._master(p)._data)
            else:  # callable init is lazy: only evaluated on first use
                arr = init() if callable(init) else init
            t = Tensor(arr)
            t.persistable = True
            t.name = f"{p.name}_{name}"
            register_persistent(t)
            slot[key] = t
        return slot[key]

    def _seed_master(self, p: Parameter, value) -> Tensor:
        """Create + register the fp32 master slot for ``p`` from ``value``
        (idempotent). The static AMP pass seeds from the pre-cast fp32
        weights; the lazy path below seeds from the current values."""
        key = id(p)
        if (key in self._master_weights
                and self._master_weights[key]._data is None):
            del self._master_weights[key]  # dead: failed-trace rollback
        if key not in self._master_weights:
            t = Tensor(jnp.asarray(value).astype(jnp.float32))
            t.persistable = True
            t.name = f"{p.name}_master"
            register_persistent(t)
            self._master_weights[key] = t
        return self._master_weights[key]

    def _master(self, p: Parameter) -> Tensor:
        """fp32 master weight when multi_precision and p is low-precision."""
        if not self._multi_precision or p.dtype == jnp.float32:
            return p
        return self._seed_master(p, p._data)

    def _params(self) -> list[Parameter]:
        if self._parameter_list is not None:
            return self._parameter_list
        from ..tensor.tensor import persistent_tensors
        return [t for t in persistent_tensors()
                if isinstance(t, Parameter) and t.trainable]

    # ----------------------------------------------------------------- step
    def step(self):
        # robustness hooks on the train-step path: surface a watchdog-
        # detected peer failure as PeerFailureError at the step boundary
        # (instead of entering a doomed collective), and give the fault-
        # injection harness its per-step trigger point. Both are ~free
        # when the watchdog is off / the harness is disarmed.
        from ..distributed.resilience import (check_peer_failure,
                                              notify_progress)
        from ..testing import fault
        check_peer_failure()
        notify_progress()
        fault.inject("step")
        with no_grad():
            # plain Tensors (stop_gradient=False) are optimizable too —
            # the reference accepts any trainable tensor, not just
            # Parameters (python/paddle/optimizer/optimizer.py)
            params_grads = [
                (p, p.grad) for p in self._params()
                if getattr(p, "trainable", not p.stop_gradient)
                and p.grad is not None]
            lr = self.get_lr()
            self._step_count += 1
            if self._should_fuse(params_grads):
                try:
                    self._fused_eager_step(params_grads, lr)
                    return
                except Exception as e:
                    import warnings
                    warnings.warn(
                        f"fused eager optimizer step failed "
                        f"({type(e).__name__}: {e}); falling back to the "
                        f"per-param loop")
                    self._fuse_eager = False     # sticky disable
                    self._purge_tracer_slots()   # drop half-built slots
            self._step_core(params_grads, lr)

    def _purge_tracer_slots(self):
        """A fused trace that failed after lazily creating accumulator/
        master slots leaves them holding escaped tracers — drop those so
        the eager fallback (and every later to_static call) sees only
        concrete state."""
        import jax

        def dead(t):
            # tracer = escaped from this (fused-eager) failure path;
            # None = already killed by the jit failed-trace rollback
            return t._data is None or isinstance(t._data, jax.core.Tracer)

        for slot in self._accumulators.values():
            for k in [k for k, t in slot.items() if dead(t)]:
                del slot[k]
        for k in [k for k, t in self._master_weights.items()
                  if dead(self._master_weights[k])]:
            del self._master_weights[k]

    def _step_core(self, params_grads, lr):
        if self._grad_clip is not None:
            params_grads = self._grad_clip(params_grads)
        for p, g in params_grads:
            # per-param lr scaling from ParamAttr(learning_rate=...)
            scale = getattr(p, "optimize_attr", None)
            p_lr = lr * scale["learning_rate"] if scale else lr
            self._update_param(p, g, p_lr)

    def _should_fuse(self, params_grads) -> bool:
        """Fuse the EAGER step into one compiled program (the reference's
        multi_tensor_adam: one kernel over all params instead of a
        per-param dispatch storm). Inside an outer to_static trace the
        step is already being compiled — run inline there."""
        import jax
        if getattr(self, "_fuse_eager", None) is None:
            # tri-state: None = read env once; False stays sticky after a
            # fallback so a deterministic failure doesn't retrace forever
            import os
            self._fuse_eager = os.environ.get(
                "PADDLE_TPU_FUSE_EAGER_STEP", "1") != "0"
        return bool(self._fuse_eager and params_grads
                    and not isinstance(params_grads[0][0]._data,
                                       jax.core.Tracer)
                    and not isinstance(params_grads[0][1]._data,
                                       jax.core.Tracer))

    def _fused_eager_step(self, params_grads, lr):
        """One jitted program per param-set: grads + lr travel as
        arguments (no retrace when the scheduler moves the lr); state
        writes functionalize through to_static's persistent-state
        machinery, exactly like a compiled train step."""
        key = (tuple(id(p) for p, _ in params_grads), self._hyper_key(
            [p for p, _ in params_grads]))
        cache = getattr(self, "_fused_cache", None)
        if cache is None:
            cache = self._fused_cache = {}
        if len(cache) == 8 and key not in cache:
            # cache-size guard (r3 weak #8): a churning key means some
            # Python-level hyperparameter (clip config, wd groups, per-
            # param lr scales) mutates every step — each step then pays a
            # full retrace. Warn once; keep stepping correctly.
            import warnings
            warnings.warn(
                "fused eager step: 9th distinct (param-set, hyperparam) "
                "signature — per-step hyperparameter churn causes a "
                "retrace every step; set PADDLE_TPU_FUSE_EAGER_STEP=0 or "
                "hold hyperparameters constant between steps",
                UserWarning, stacklevel=3)
        if len(cache) >= 16 and key not in cache:
            # bound host memory under churn: evict the oldest compiled
            # program (insertion order); warn-once above already fired
            del cache[next(iter(cache))]
        fn = cache.get(key)
        if fn is None:
            from ..jit import to_static
            params = [p for p, _ in params_grads]

            def run(grads, lr_t):
                self._step_core(list(zip(params, grads)), lr_t._data)
                return Tensor(jnp.zeros((), jnp.float32))
            fn = cache[key] = to_static(run)
            self._fused_fn = fn          # introspection/debug handle
        fn([g for _, g in params_grads],
           Tensor(jnp.asarray(lr, jnp.float32)))

    def _hyper_key(self, params):
        """Python-level hyperparameters the trace bakes in as constants —
        part of the cache key so mutating them mid-training retraces
        instead of silently keeping stale values (the eager loop re-read
        them every step)."""
        clip = self._grad_clip
        clip_sig = None if clip is None else (
            type(clip).__name__,
            getattr(clip, "clip_norm", None), getattr(clip, "max", None),
            getattr(clip, "min", None), getattr(clip, "clip_value", None))
        lr_scales = tuple(
            (getattr(p, "optimize_attr", None) or {}).get(
                "learning_rate", 1.0) for p in params)
        return (clip_sig, getattr(self, "_wd_coeff", None),
                self._weight_decay if isinstance(self._weight_decay,
                                                 (int, float)) else None,
                lr_scales)

    def _update_param(self, p: Parameter, g: Tensor, lr: float):
        raise NotImplementedError

    def _apply(self, p: Parameter, new_master_value):
        """Write updated fp32 value back to master + model param."""
        m = self._master(p)
        if m is not p:
            m._data = new_master_value
            p._data = new_master_value.astype(p.dtype)
        else:
            p._data = new_master_value.astype(p.dtype)

    def _decayed(self, p, g32, m32):
        """L2-regularizer-style weight decay folded into the gradient
        (Paddle's `weight_decay=L2Decay(...)` semantics for non-AdamW)."""
        # per-param ParamAttr regularizer overrides the optimizer-level one
        # (reference precedence: python/paddle/regularizer.py docstring)
        reg = getattr(p, "regularizer", None)
        wd = self._weight_decay if reg is None else reg
        if wd is None:
            return g32
        reg = wd
        if callable(reg) and not isinstance(reg, float):
            return reg(g32, m32)
        coeff = getattr(reg, "_coeff",
                        getattr(reg, "coeff",
                                reg if isinstance(reg, float) else 0.0))
        return g32 + coeff * m32

    def clear_grad(self, set_to_zero: bool = False):
        for p in self._params():
            p.clear_gradient(set_to_zero)
    clear_gradients = clear_grad

    # ------------------------------------------------------------- state io
    def state_dict(self) -> dict:
        sd: dict = {}
        params = {id(p): name_of(p) for p in self._params()}
        # skip dead slots (_data=None): a failed-trace rollback killed them
        # before they ever held a value — they are semantically absent
        for acc_name, slot in self._accumulators.items():
            for pid, t in slot.items():
                if t._data is not None:
                    sd[f"{params.get(pid, pid)}_{acc_name}"] = t
        for pid, t in self._master_weights.items():
            if t._data is not None:
                sd[f"{params.get(pid, pid)}_master"] = t
        if isinstance(self._lr, LRScheduler):
            sd["LR_Scheduler"] = self._lr.state_dict()
        sd["@step"] = self._step_count
        return sd

    def set_state_dict(self, state_dict: dict):
        params = {name_of(p): p for p in self._params()}
        self._step_count = int(state_dict.get("@step", 0))
        if "LR_Scheduler" in state_dict and isinstance(self._lr, LRScheduler):
            self._lr.set_state_dict(state_dict["LR_Scheduler"])
        for key, val in state_dict.items():
            if key in ("LR_Scheduler", "@step"):
                continue
            for pname, p in params.items():
                if not key.startswith(pname + "_"):
                    continue
                suffix = key[len(pname) + 1:]
                arr = val._data if isinstance(val, Tensor) else jnp.asarray(val)
                if suffix == "master":
                    self._master_weights[id(p)] = Tensor(arr)
                else:
                    self._acc(suffix, p, init=arr)
                break

    set_dict = set_state_dict

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        from ..nn.layer.layers import in_dynamic_mode
        if not in_dynamic_mode():
            # static build: register backward+update for each Executor.run
            # (the reference appends backward + optimizer ops to the program)
            from ..static import default_main_program
            default_main_program()._add_minimize(self, loss)
            return None, None
        loss.backward()
        self.step()
        self.clear_grad()
        return None, None


def name_of(p):
    return p.name


class SGD(Optimizer):
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)

    def _update_param(self, p, g, lr):
        m = self._master(p)
        g32 = self._decayed(p, g._data.astype(jnp.float32), m._data)
        self._apply(p, m._data - lr * g32)


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._momentum = momentum
        self._nesterov = use_nesterov

    def _update_param(self, p, g, lr):
        m = self._master(p)
        g32 = self._decayed(p, g._data.astype(jnp.float32), m._data)
        vel = self._acc("velocity", p)
        v_new = self._momentum * vel._data + g32
        vel._data = v_new
        if self._nesterov:
            upd = g32 + self._momentum * v_new
        else:
            upd = v_new
        self._apply(p, m._data - lr * upd)


class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False,
                 use_multi_tensor=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon

    def _adam_update(self, p, g, lr, decoupled_wd=0.0):
        mw = self._master(p)
        g32 = g._data.astype(jnp.float32)
        if decoupled_wd == 0.0:
            g32 = self._decayed(p, g32, mw._data)
        m = self._acc("moment1", p)
        v = self._acc("moment2", p)
        b1p = self._acc("beta1_pow", p, init=jnp.ones((), jnp.float32))
        b2p = self._acc("beta2_pow", p, init=jnp.ones((), jnp.float32))
        b1p._data = b1p._data * self._beta1
        b2p._data = b2p._data * self._beta2
        m._data = self._beta1 * m._data + (1 - self._beta1) * g32
        v._data = self._beta2 * v._data + (1 - self._beta2) * g32 * g32
        mhat = m._data / (1 - b1p._data)
        vhat = v._data / (1 - b2p._data)
        new = mw._data - lr * (mhat / (jnp.sqrt(vhat) + self._epsilon)
                               + decoupled_wd * mw._data)
        self._apply(p, new)

    def _update_param(self, p, g, lr):
        self._adam_update(p, g, lr, 0.0)


class AdamW(Adam):
    """Decoupled weight decay Adam — the north-star fused adamw kernel.

    Parity: python/paddle/optimizer/adamw.py + AdamwDenseKernel. The
    apply_decay_param_fun predicate matches the reference (skip decay for
    bias/LayerNorm via user fn).
    """

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=0.01,
                 lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 lazy_mode=False, multi_precision=False, name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         None, grad_clip, lazy_mode, multi_precision)
        self._wd_coeff = weight_decay if isinstance(weight_decay, float) else \
            getattr(weight_decay, "_coeff", 0.01)
        self._apply_decay_fn = apply_decay_param_fun
        self._lr_ratio = lr_ratio

    def _update_param(self, p, g, lr):
        wd = self._wd_coeff
        if self._apply_decay_fn is not None and not self._apply_decay_fn(p.name):
            wd = 0.0
        if self._lr_ratio is not None:
            lr = lr * self._lr_ratio(p)
        self._adam_update(p, g, lr, wd)


class Adafactor(Optimizer):
    """Factored-second-moment Adam (Shazeer & Stern 2018).

    The fix the 1B single-chip OOM analysis drives (LLAMA1B_cpu_mesh.json
    / tools/llama_1b.py): AdamW's two full fp32 moments cost 10 GB at
    1.26B params, pushing total state past the 16 GB v5e HBM; Adafactor
    keeps row+col statistics instead (KBs per matrix), so state =
    params (+ optional fp32 master) + ~0. Matrices (and the last two
    axes of higher-rank params, e.g. stacked experts) are factored;
    vectors keep a full second moment (negligible).

    Follows the paper's recommended config: beta2_t = 1 - t^-decay_rate,
    update clipped to clip_threshold by RMS, optional parameter-scaled
    lr (scale_parameter). relative_step is intentionally NOT implemented
    — lr comes from this framework's scheduler machinery like every
    other optimizer here."""

    def __init__(self, learning_rate=1e-3, beta1=None, decay_rate=0.8,
                 epsilon1=1e-30, epsilon2=1e-3, clip_threshold=1.0,
                 scale_parameter=True, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._beta1 = beta1
        self._decay_rate = decay_rate
        self._eps1 = epsilon1
        self._eps2 = epsilon2
        self._clip_threshold = clip_threshold
        self._scale_parameter = scale_parameter

    def _update_param(self, p, g, lr):
        mw = self._master(p)
        g32 = self._decayed(p, g._data.astype(jnp.float32), mw._data)
        step = self._acc("step", p, init=jnp.zeros((), jnp.float32))
        step._data = step._data + 1.0
        t = step._data
        beta2_t = 1.0 - t ** (-self._decay_rate)
        g2 = g32 * g32 + self._eps1

        if p.ndim >= 2:
            # factor the last two axes; leading axes ride along (stacked
            # experts / conv kernels)
            vr = self._acc("vrow", p,
                           init=jnp.zeros(p.shape[:-1], jnp.float32))
            vc = self._acc("vcol", p, init=jnp.zeros(
                tuple(p.shape[:-2]) + (p.shape[-1],), jnp.float32))
            vr._data = beta2_t * vr._data + (1 - beta2_t) * jnp.mean(
                g2, axis=-1)
            vc._data = beta2_t * vc._data + (1 - beta2_t) * jnp.mean(
                g2, axis=-2)
            denom = jnp.mean(vr._data, axis=-1, keepdims=True)
            vhat = (vr._data / jnp.maximum(denom, self._eps1))[..., None] \
                * vc._data[..., None, :]
        else:
            v = self._acc("moment2", p)
            v._data = beta2_t * v._data + (1 - beta2_t) * g2
            vhat = v._data
        u = g32 / jnp.sqrt(jnp.maximum(vhat, self._eps1))
        rms_u = jnp.sqrt(jnp.mean(u * u) + self._eps1)
        u = u / jnp.maximum(1.0, rms_u / self._clip_threshold)
        if self._beta1 is not None:
            m = self._acc("moment1", p)
            m._data = self._beta1 * m._data + (1 - self._beta1) * u
            u = m._data
        alpha = lr
        if self._scale_parameter:
            rms_p = jnp.sqrt(jnp.mean(mw._data * mw._data))
            alpha = lr * jnp.maximum(rms_p, self._eps2)
        self._apply(p, mw._data - alpha * u)


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=False,
                 initial_accumulator_value=0.0, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._epsilon = epsilon
        self._init_acc = initial_accumulator_value

    def _update_param(self, p, g, lr):
        m = self._master(p)
        g32 = self._decayed(p, g._data.astype(jnp.float32), m._data)
        acc = self._acc("moment", p,
                        init=jnp.full_like(m._data, self._init_acc))
        acc._data = acc._data + g32 * g32
        self._apply(p, m._data - lr * g32 / (jnp.sqrt(acc._data) + self._epsilon))


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._epsilon = epsilon
        self._rho = rho

    def _update_param(self, p, g, lr):
        m = self._master(p)
        g32 = self._decayed(p, g._data.astype(jnp.float32), m._data)
        avg_sq = self._acc("_avg_squared_grad", p)
        avg_upd = self._acc("_avg_squared_update", p)
        avg_sq._data = self._rho * avg_sq._data + (1 - self._rho) * g32 * g32
        upd = (jnp.sqrt(avg_upd._data + self._epsilon) /
               jnp.sqrt(avg_sq._data + self._epsilon)) * g32
        avg_upd._data = self._rho * avg_upd._data + (1 - self._rho) * upd * upd
        self._apply(p, m._data - lr * upd)


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._rho = rho
        self._epsilon = epsilon
        self._momentum = momentum
        self._centered = centered

    def _update_param(self, p, g, lr):
        mw = self._master(p)
        g32 = self._decayed(p, g._data.astype(jnp.float32), mw._data)
        ms = self._acc("mean_square", p)
        mom = self._acc("momentum", p)
        ms._data = self._rho * ms._data + (1 - self._rho) * g32 * g32
        denom = ms._data
        if self._centered:
            mg = self._acc("mean_grad", p)
            mg._data = self._rho * mg._data + (1 - self._rho) * g32
            denom = denom - mg._data * mg._data
        mom._data = self._momentum * mom._data + lr * g32 / jnp.sqrt(
            denom + self._epsilon)
        self._apply(p, mw._data - mom._data)


class Lamb(Optimizer):
    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-6, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name,
                         multi_precision)
        self._wd = lamb_weight_decay
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        self._exclude_fn = exclude_from_weight_decay_fn

    def _update_param(self, p, g, lr):
        mw = self._master(p)
        g32 = g._data.astype(jnp.float32)
        m = self._acc("moment1", p)
        v = self._acc("moment2", p)
        b1p = self._acc("beta1_pow", p, init=jnp.ones((), jnp.float32))
        b2p = self._acc("beta2_pow", p, init=jnp.ones((), jnp.float32))
        b1p._data = b1p._data * self._beta1
        b2p._data = b2p._data * self._beta2
        m._data = self._beta1 * m._data + (1 - self._beta1) * g32
        v._data = self._beta2 * v._data + (1 - self._beta2) * g32 * g32
        mhat = m._data / (1 - b1p._data)
        vhat = v._data / (1 - b2p._data)
        wd = self._wd
        if self._exclude_fn is not None and self._exclude_fn(p):
            wd = 0.0
        r = mhat / (jnp.sqrt(vhat) + self._epsilon) + wd * mw._data
        w_norm = jnp.linalg.norm(mw._data)
        r_norm = jnp.linalg.norm(r)
        trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        self._apply(p, mw._data - lr * trust * r)


# ---- round-2 breadth: Adamax, NAdam, RAdam, ASGD, Rprop -------------------
# Parity: python/paddle/optimizer/{adamax,nadam,radam,asgd,rprop}.py.

class Adamax(Optimizer):
    """Adam with infinity-norm second moment (no bias correction on v)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _update_param(self, p, g, lr):
        mw = self._master(p)
        g32 = self._decayed(p, g._data.astype(jnp.float32), mw._data)
        m = self._acc("moment", p)
        u = self._acc("inf_norm", p)
        b1p = self._acc("beta1_pow", p, init=jnp.ones((), jnp.float32))
        b1p._data = b1p._data * self._beta1
        m._data = self._beta1 * m._data + (1 - self._beta1) * g32
        u._data = jnp.maximum(self._beta2 * u._data, jnp.abs(g32))
        new = mw._data - (lr / (1 - b1p._data)) * m._data / (
            u._data + self._epsilon)
        self._apply(p, new)


class NAdam(Optimizer):
    """Adam with Nesterov momentum (reference nadam.py formulas)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, momentum_decay=0.004, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._psi = momentum_decay

    def _update_param(self, p, g, lr):
        mw = self._master(p)
        g32 = self._decayed(p, g._data.astype(jnp.float32), mw._data)
        m = self._acc("moment1", p)
        v = self._acc("moment2", p)
        step = self._acc("step", p, init=jnp.zeros((), jnp.float32))
        mu_prod = self._acc("mu_prod", p, init=jnp.ones((), jnp.float32))
        b2p = self._acc("beta2_pow", p, init=jnp.ones((), jnp.float32))
        step._data = step._data + 1.0
        t = step._data
        mu_t = self._beta1 * (1 - 0.5 * 0.96 ** (t * self._psi))
        mu_next = self._beta1 * (1 - 0.5 * 0.96 ** ((t + 1) * self._psi))
        mu_prod_t = mu_prod._data * mu_t
        mu_prod._data = mu_prod_t
        b2p._data = b2p._data * self._beta2
        m._data = self._beta1 * m._data + (1 - self._beta1) * g32
        v._data = self._beta2 * v._data + (1 - self._beta2) * g32 * g32
        mhat = (mu_next * m._data / (1 - mu_prod_t * mu_next)
                + (1 - mu_t) * g32 / (1 - mu_prod_t))
        vhat = v._data / (1 - b2p._data)
        self._apply(p, mw._data - lr * mhat
                    / (jnp.sqrt(vhat) + self._epsilon))


class RAdam(Optimizer):
    """Rectified Adam: variance-rectification term gates between SGDm and
    Adam (reference radam.py)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _update_param(self, p, g, lr):
        mw = self._master(p)
        g32 = self._decayed(p, g._data.astype(jnp.float32), mw._data)
        m = self._acc("moment1", p)
        v = self._acc("moment2", p)
        step = self._acc("step", p, init=jnp.zeros((), jnp.float32))
        step._data = step._data + 1.0
        t = step._data
        b1p = self._beta1 ** t
        b2p = self._beta2 ** t
        m._data = self._beta1 * m._data + (1 - self._beta1) * g32
        v._data = self._beta2 * v._data + (1 - self._beta2) * g32 * g32
        mhat = m._data / (1 - b1p)
        rho_inf = 2.0 / (1 - self._beta2) - 1.0
        rho_t = rho_inf - 2.0 * t * b2p / (1 - b2p)
        # rectified branch when rho_t > 5 (reference threshold)
        r = jnp.sqrt(jnp.maximum(
            (rho_t - 4) * (rho_t - 2) * rho_inf
            / jnp.maximum((rho_inf - 4) * (rho_inf - 2) * rho_t, 1e-12),
            0.0))
        vhat = jnp.sqrt(v._data / (1 - b2p)) + self._epsilon
        adam_step = r * mhat / vhat
        sgd_step = mhat
        self._apply(p, mw._data - lr * jnp.where(rho_t > 5.0, adam_step,
                                                 sgd_step))


class ASGD(Optimizer):
    """Averaged SGD (reference asgd.py): steps use the MEAN of the last
    `batch_num` gradients via the d/ys recursion (d ← d − ys[i] + g;
    ys[i] ← g), plus a running parameter average for inference."""

    def __init__(self, learning_rate=0.001, batch_num=1, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._batch_num = int(batch_num)

    def _update_param(self, p, g, lr):
        mw = self._master(p)
        g32 = self._decayed(p, g._data.astype(jnp.float32), mw._data)
        n = self._batch_num
        step = self._acc("step", p, init=lambda: jnp.zeros((), jnp.float32))
        avg = self._acc("averaged", p, init=lambda: mw._data)
        d = self._acc("d", p)
        ys = self._acc("ys", p, init=lambda: jnp.zeros(
            (n, *mw._data.shape), jnp.float32))
        t = step._data
        idx = (t % n).astype(jnp.int32)
        d._data = d._data - ys._data[idx] + g32
        ys._data = ys._data.at[idx].set(g32)
        step._data = t + 1.0
        seen = jnp.minimum(t + 1.0, float(n))
        new = mw._data - lr * d._data / seen
        avg._data = avg._data + (new - avg._data) / (t + 1.0)
        self._apply(p, new)

    def averaged_value(self, p):
        return self._acc("averaged", p)


class Rprop(Optimizer):
    """Resilient backprop: per-weight step sizes adapted by grad-sign
    agreement (reference rprop.py)."""

    def __init__(self, learning_rate=0.001, learning_rate_range=(1e-5, 50.0),
                 parameters=None, etas=(0.5, 1.2), grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip,
                         name, multi_precision)
        self._eta_minus, self._eta_plus = etas
        self._lr_min, self._lr_max = learning_rate_range

    def _update_param(self, p, g, lr):
        mw = self._master(p)
        g32 = g._data.astype(jnp.float32)
        prev = self._acc("prev_grad", p)
        # lr (from get_lr) honors schedulers; init only runs on first use
        steps = self._acc("step_size", p,
                          init=lambda: jnp.full_like(mw._data, lr))
        sign = g32 * prev._data
        grow = sign > 0
        shrink = sign < 0
        steps._data = jnp.clip(
            jnp.where(grow, steps._data * self._eta_plus,
                      jnp.where(shrink, steps._data * self._eta_minus,
                                steps._data)),
            self._lr_min, self._lr_max)
        # on sign flip: zero the grad (classic Rprop- variant)
        eff_g = jnp.where(shrink, 0.0, g32)
        prev._data = eff_g
        self._apply(p, mw._data - jnp.sign(eff_g) * steps._data)
