"""paddle.optimizer namespace."""
from . import lr
from .optimizer import (Optimizer, SGD, Momentum, Adam, AdamW, Adagrad,
                        Adadelta, RMSProp, Lamb)


class L2Decay:
    """Parity: paddle.regularizer.L2Decay."""

    def __init__(self, coeff=0.0):
        self._coeff = coeff


class L1Decay:
    def __init__(self, coeff=0.0):
        self._coeff = coeff
