"""paddle.optimizer namespace."""
from . import lr
from .optimizer import (Optimizer, SGD, Momentum, Adam, AdamW, Adafactor,
                        Adagrad, Adadelta, RMSProp, Lamb, Adamax, NAdam,
                        RAdam, ASGD, Rprop)
# single source of truth for regularizers (paddle.regularizer); re-exported
# here for the legacy paddle.optimizer.L1Decay/L2Decay spelling
from ..regularizer import L1Decay, L2Decay
