"""Distributed sharded checkpointing (Orbax-backed, reshard-on-load).

Parity: the reference's auto_parallel dist-checkpoint format +
save_group_sharded_model / dist ckpt reshard-on-load (SURVEY §5.4:
python/paddle/distributed/auto_parallel dist-checkpoint).

TPU-native design: Orbax/TensorStore writes each array's shards from their
owning hosts (no rank-0 gather), `async_save=True` returns while the commit
runs on a background thread (the train loop overlaps the next steps with the
write, the reference's async_save semantics), and restore places every
tensor DIRECTLY onto its current mesh sharding via ArrayRestoreArgs — saved
on mesh A (e.g. dp4), restored on mesh B (dp2×mp2) without a host
round-trip; TensorStore reads only each device's slice.
"""
from __future__ import annotations

import os
import threading
from typing import Optional

import numpy as np

from ..tensor.tensor import Tensor

__all__ = ["save_state_dict", "load_state_dict", "wait_all_async_saves",
           "save_checkpoint", "load_latest", "latest_step"]

_pending: list = []
_pending_lock = threading.Lock()

_COMMIT_MARKER = ".paddle_committed"   # exists <=> the step dir is durable
_LATEST = "LATEST"


class _AsyncHandle:
    """AsyncCheckpointer + a finalize callback that runs EXACTLY ONCE,
    after (and only after) the commit lands — the auto-resume LATEST
    pointer rides on this, so a crash before the join leaves the previous
    pointer intact and the partial dir unmarked (skipped on load)."""

    def __init__(self, ckptr, finalize=None):
        self._ckptr = ckptr
        self._finalize = finalize
        self._done = False
        self._lock = threading.Lock()

    def wait_until_finished(self):
        self._ckptr.wait_until_finished()
        with self._lock:
            if not self._done:
                self._done = True
                if self._finalize is not None:
                    self._finalize()

    def close(self):
        self._ckptr.close()


class _ThreadHandle:
    """Thread-backed async commit for the LOCAL (.pdparams) checkpoint
    format — same join/finalize-exactly-once contract as _AsyncHandle, so
    save_checkpoint's LATEST pointer lands at the wait_all_async_saves
    join on this path too. `commit` runs on a daemon thread against a
    snapshot taken by the CALLER (the write must never race live
    parameter updates)."""

    def __init__(self, commit, finalize=None):
        self._finalize = finalize
        self._err: BaseException | None = None
        self._done = False
        self._lock = threading.Lock()

        def run():
            try:
                commit()
            except BaseException as e:   # re-raised at the join
                self._err = e

        self._t = threading.Thread(target=run, daemon=True,
                                   name="paddle-ckpt-local-async")
        self._t.start()

    def wait_until_finished(self):
        self._t.join()
        if self._err is not None:
            raise self._err
        with self._lock:
            if not self._done:
                self._done = True
                if self._finalize is not None:
                    self._finalize()

    def close(self):
        pass


def _fsync_path(path: str):
    """fsync an existing file (or directory) by path — durability for the
    auto-resume chain: LATEST must never outlive the bytes it points at."""
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _to_arrays(sd):
    return {k: (v._data if isinstance(v, Tensor) else v)
            for k, v in sd.items()}


def _track(ckptr):
    with _pending_lock:
        _pending.append(ckptr)


def wait_all_async_saves():
    """Block until every async save commit has landed (call before exit or
    before reading a checkpoint you just wrote)."""
    with _pending_lock:
        pending, _pending[:] = _pending[:], []
    first_err = None
    for c in pending:                 # join EVERY commit even if one fails
        try:
            c.wait_until_finished()
        except Exception as e:
            if first_err is None:
                first_err = e
        finally:
            try:
                c.close()
            except Exception:
                pass
    if first_err is not None:
        raise first_err


def save_state_dict(state_dict: dict, path: str, process_group=None,
                    coordinator_rank: int = 0, async_save: bool = False,
                    local: bool = False, _finalize=None):
    """Write a (possibly sharded) state dict. async_save=True returns as
    soon as the on-device arrays are snapshot; the serialize/commit runs in
    the background (wait_all_async_saves() to join). `_finalize` (internal,
    save_checkpoint) runs once, strictly after the commit lands.

    local=True writes host-local, WITHOUT cross-process coordination —
    Orbax's save runs a global sync barrier across jax processes, so a
    rank-0-only save of replicated state in a multi-process job would
    wedge the caller (and, worse, wedge it in C where even the watchdog's
    async-raise can't land). The local format is the framework's own
    .pdparams serializer; load_state_dict auto-detects it. async_save is
    honored here too: the host snapshot is taken before returning, the
    pickle write (and _finalize) land at the wait_all_async_saves join."""
    if not local:
        try:
            import orbax.checkpoint as ocp
        except ImportError:
            local = True             # no orbax: same host-local fallback
    if local:
        os.makedirs(path, exist_ok=True)
        from ..framework import io as _io
        # snapshot to host NOW — the async contract is "return once the
        # arrays are captured", and the background pickle must not race
        # the train loop mutating the live tensors
        snap = _io._to_saveable(state_dict)
        target = os.path.join(path, "fallback.pdparams")

        def commit():
            # fsync the payload BEFORE the caller's finalize repoints
            # LATEST — a power loss must not leave a durable-looking
            # pointer at a torn pickle
            _io.save(snap, target)
            _fsync_path(target)

        if async_save:
            _track(_ThreadHandle(commit, finalize=_finalize))
            return
        commit()
        if _finalize is not None:
            _finalize()
        return
    arrays = _to_arrays(state_dict)
    path = os.path.abspath(path)
    if async_save:
        ckptr = ocp.AsyncCheckpointer(ocp.PyTreeCheckpointHandler())
        ckptr.save(path, arrays, force=True)
        _track(_AsyncHandle(ckptr, finalize=_finalize))
        return
    ocp.PyTreeCheckpointer().save(path, arrays, force=True)
    if _finalize is not None:
        _finalize()


def load_state_dict(state_dict: dict, path: str, process_group=None,
                    coordinator_rank: int = 0) -> dict:
    """Restore into the given state_dict skeleton, resharding on load: each
    tensor is materialized directly with its CURRENT sharding (mesh +
    sharding_spec at restore time — not the one it was saved under), so a
    checkpoint from mesh A restores onto mesh B with each device reading
    only its slice. A checkpoint written in the LOCAL format (see
    save_state_dict(local=True) / no-orbax fallback) is auto-detected."""
    try:
        import orbax.checkpoint as ocp
        if os.path.exists(os.path.join(path, "fallback.pdparams")):
            raise ImportError    # local-format dir: use the native reader
    except ImportError:
        from ..framework.io import load
        restored = load(os.path.join(path, "fallback.pdparams"),
                        return_numpy=True)
        for k, t in state_dict.items():
            if k in restored and isinstance(t, Tensor):
                t.set_value(np.asarray(restored[k]))
        return state_dict

    path = os.path.abspath(path)
    ckptr = ocp.PyTreeCheckpointer()
    # restore_args must mirror the CHECKPOINT's tree, not the skeleton's —
    # tolerate grown/shrunk models (extra skeleton keys stay untouched,
    # extra checkpoint keys restore as plain arrays and are ignored below)
    try:
        saved_keys = set(ckptr.metadata(path).item_metadata.tree.keys())
    except Exception:
        saved_keys = set(state_dict.keys())
    restore_args = {}
    for k in saved_keys:
        t = state_dict.get(k)
        sh = getattr(getattr(t, "_data", None), "sharding", None) \
            if isinstance(t, Tensor) else None
        restore_args[k] = ocp.ArrayRestoreArgs(
            sharding=sh, dtype=t._data.dtype) if sh is not None \
            else ocp.RestoreArgs()
    restored = ckptr.restore(path, restore_args=restore_args)
    for k, t in state_dict.items():
        if k not in restored:
            continue
        arr = restored[k]
        if isinstance(t, Tensor):
            # already placed per restore_args sharding — adopt directly
            # (no host round-trip); keep grad/spec metadata
            import jax.numpy as jnp
            t._data = arr if hasattr(arr, "sharding") else jnp.asarray(arr)
        else:
            state_dict[k] = Tensor(np.asarray(arr))
    return state_dict


# --------------------------------------------------------------- auto-resume
def _step_dir(root: str, step: int) -> str:
    return os.path.join(os.path.abspath(root), f"step_{int(step)}")


def _is_committed(root: str, name: str) -> bool:
    return os.path.isfile(os.path.join(root, name, _COMMIT_MARKER))


def save_checkpoint(state_dict: dict, root: str, step: int,
                    async_save: bool = False, keep: int = 0,
                    local: bool = False):
    """Durable, resumable checkpoint: writes `root/step_<step>/`, then —
    strictly AFTER the commit lands — drops a commit marker in the dir and
    atomically repoints `root/LATEST` (tmp + os.replace). A process that
    dies mid-write leaves LATEST on the previous step and the partial dir
    unmarked, so a supervised restart resumes from the last DURABLE step.

    async_save=True: the marker + pointer land when the commit is joined
    (wait_all_async_saves), never before. `keep` > 0 prunes all but the
    newest `keep` committed step dirs. In MULTI-PROCESS jobs either every
    rank calls this (sharded Orbax commit), or ONE rank checkpoints
    replicated state with local=True — a rank-0-only DEFAULT (Orbax) save
    would wedge in Orbax's global sync barrier."""
    root = os.path.abspath(root)
    os.makedirs(root, exist_ok=True)
    d = _step_dir(root, step)

    def finalize():
        with open(os.path.join(d, _COMMIT_MARKER), "w") as f:
            f.write(str(int(step)))
            f.flush()
            os.fsync(f.fileno())
        try:
            _fsync_path(d)       # dirents of marker + payload themselves
        except OSError:
            pass
        tmp = os.path.join(root, f".{_LATEST}.tmp.{os.getpid()}")
        with open(tmp, "w") as f:
            f.write(os.path.basename(d))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(root, _LATEST))
        try:
            _fsync_path(root)    # the rename's directory entry itself
        except OSError:
            pass
        if keep > 0:
            _prune(root, keep)

    save_state_dict(state_dict, d, async_save=async_save, local=local,
                    _finalize=finalize)
    return d


def _committed_steps(root: str):
    import re
    out = []
    try:
        names = os.listdir(root)
    except OSError:
        return out
    for name in names:
        m = re.fullmatch(r"step_(\d+)", name)
        if m:
            out.append((int(m.group(1)), name))
    return sorted(out, reverse=True)


def _prune(root: str, keep: int):
    import shutil
    committed = [(s, n) for s, n in _committed_steps(root)
                 if _is_committed(root, n)]
    for _, name in committed[keep:]:
        shutil.rmtree(os.path.join(root, name), ignore_errors=True)


def latest_step(root: str):
    """The newest durable (committed) step under `root`, or None. Prefers
    the LATEST pointer; falls back to a directory scan when the pointer is
    missing or points at an uncommitted dir."""
    import logging
    root = os.path.abspath(root)
    try:
        with open(os.path.join(root, _LATEST)) as f:
            name = f.read().strip()
        if name and _is_committed(root, name):
            return int(name.rsplit("_", 1)[1])
        if name:
            logging.warning(
                "paddle_tpu.checkpoint: LATEST points at %s which is not "
                "committed — scanning for the newest durable step", name)
    except (OSError, ValueError, IndexError):
        pass
    for step, name in _committed_steps(root):
        if _is_committed(root, name):
            return step
        logging.warning("paddle_tpu.checkpoint: skipping partial/"
                        "uncommitted checkpoint dir %s",
                        os.path.join(root, name))
    return None


def load_latest(state_dict: dict, root: str):
    """Restore `state_dict` from the newest durable checkpoint under
    `root`. Returns the restored step (int) or None when no durable
    checkpoint exists (fresh start). Partial/uncommitted dirs — a crash
    mid-commit — are skipped with a warning, never loaded. A committed
    step whose payload is unreadable anyway (torn disk, lost pages after
    power loss) falls back to the next-newest durable step instead of
    failing every restart attempt."""
    import logging
    root = os.path.abspath(root)
    first = latest_step(root)
    if first is None:
        return None
    order = [first] + [s for s, n in _committed_steps(root)
                       if _is_committed(root, n) and s != first]
    for step in order:
        try:
            load_state_dict(state_dict, _step_dir(root, step))
            return step
        except Exception as e:
            logging.warning(
                "paddle_tpu.checkpoint: committed step_%d payload is "
                "unreadable (%r) — falling back to the previous durable "
                "step", step, e)
    return None
