"""Distributed sharded checkpointing (Orbax-backed, reshard-on-load).

Parity: the reference's auto_parallel dist-checkpoint format + stage-3
save_group_sharded_model (SURVEY §5.4). Orbax writes each array's shards from
their owning hosts and restores onto any new mesh/topology (reshard-on-load),
async-capable — the TPU-native replacement for per-rank pickle shards.
"""
from __future__ import annotations

import os
from typing import Optional

import numpy as np

from ..tensor.tensor import Tensor

__all__ = ["save_state_dict", "load_state_dict"]


def _to_arrays(sd):
    return {k: (v._data if isinstance(v, Tensor) else v) for k, v in sd.items()}


def save_state_dict(state_dict: dict, path: str, process_group=None,
                    coordinator_rank: int = 0, async_save: bool = False):
    try:
        import orbax.checkpoint as ocp
        ckptr = ocp.PyTreeCheckpointer()
        ckptr.save(os.path.abspath(path), _to_arrays(state_dict), force=True)
    except Exception:
        from ..framework.io import save
        save(state_dict, os.path.join(path, "fallback.pdparams"))


def load_state_dict(state_dict: dict, path: str, process_group=None,
                    coordinator_rank: int = 0) -> dict:
    """Restore into the given state_dict skeleton (reshard-on-load: each
    tensor lands with its current sharding_spec placement)."""
    try:
        import orbax.checkpoint as ocp
        ckptr = ocp.PyTreeCheckpointer()
        restored = ckptr.restore(os.path.abspath(path))
    except Exception:
        from ..framework.io import load
        restored = load(os.path.join(path, "fallback.pdparams"),
                        return_numpy=True)
    for k, t in state_dict.items():
        if k in restored:
            arr = restored[k]
            if isinstance(t, Tensor):
                t.set_value(np.asarray(arr))
            else:
                state_dict[k] = Tensor(np.asarray(arr))
    return state_dict
