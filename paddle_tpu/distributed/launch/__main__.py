"""python -m paddle_tpu.distributed.launch — the launcher CLI.

Parity: python/paddle/distributed/launch/ (collective controller): builds the
job context, spawns one process per host-slot with the PADDLE_*/JAX_* env
contract, captures per-rank logs (workerlog.N), restarts on failure up to
--max_restart (elastic semantics; SURVEY §5.3).

TPU-native: one process per HOST (not per chip) — inside each process JAX owns
all local chips; rendezvous is the JAX coordination service, not TCPStore.

Gang supervision (SURVEY §5.3 failure detection): children are POLLED, not
serially wait()ed — the first non-zero exit (a crash, or a watchdog-initiated
exit on a survivor) triggers SIGTERM -> grace -> SIGKILL of the whole gang, a
per-rank failure report (exit code + the failing rank's workerlog tail), and
an exponential-backoff restart with a FRESH master port and
PADDLE_RESTART_COUNT bumped (the elastic generation number — training
companions resume via distributed.checkpoint.load_latest). Each generation
logs to workerlog.N.restartK so post-mortems never interleave generations.
"""
from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import sys
import time

from ..logjson import log_event


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _log_path(log_dir: str, rank: int, attempt: int) -> str:
    """Generation-rotated per-rank log: attempt 0 keeps the classic
    workerlog.N name, restarts get workerlog.N.restartK."""
    name = f"workerlog.{rank}" if attempt == 0 \
        else f"workerlog.{rank}.restart{attempt}"
    return os.path.join(log_dir, name)


def _tail(path: str, n: int = 20) -> str:
    try:
        with open(path, "rb") as f:
            f.seek(0, os.SEEK_END)
            f.seek(max(0, f.tell() - 8192))
            lines = f.read().decode("utf-8", "replace").splitlines()
        return "\n".join(lines[-n:])
    except OSError:
        return "<no log captured>"


def _reap_gang(procs, grace_s: float):
    """SIGTERM every still-running child, give them `grace_s` to unwind
    (flush logs, close stores), then SIGKILL the stragglers. Returns the
    final exit codes (None never: everyone is dead on return)."""
    for p in procs:
        if p.poll() is None:
            try:
                p.send_signal(signal.SIGTERM)
            except OSError:
                pass
    deadline = time.time() + grace_s
    while time.time() < deadline and any(p.poll() is None for p in procs):
        time.sleep(0.1)
    for p in procs:
        if p.poll() is None:
            try:
                p.kill()
            except OSError:
                pass
            p.wait()
    return [p.poll() for p in procs]


def _spawn_gang(args, master, attempt):
    nprocs = args.nproc_per_node
    world = nprocs * args.nnodes
    procs, logs = [], []
    try:
        for local_rank in range(nprocs):
            rank = args.node_rank * nprocs + local_rank
            env = dict(os.environ)
            env.update({
                "PADDLE_TRAINER_ID": str(rank),
                "PADDLE_TRAINERS_NUM": str(world),
                "PADDLE_LOCAL_RANK": str(local_rank),
                "PADDLE_MASTER": master,
                "PADDLE_CURRENT_ENDPOINT": f"127.0.0.1:{_free_port()}",
                "PADDLE_RESTART_COUNT": str(attempt),
                "JAX_PROCESS_ID": str(rank),
                "JAX_NUM_PROCESSES": str(world),
                "JAX_COORDINATOR_ADDRESS": master,
            })
            # flight dumps land next to the workerlogs unless the user
            # pinned a dir — the supervisor's failure report aggregates
            # flightdump.<rank>.<generation>.json from here
            env.setdefault("PADDLE_FLIGHT_DUMP_DIR", args.log_dir)
            logf = open(_log_path(args.log_dir, rank, attempt), "a")
            logs.append(logf)
            # every rank INCLUDING 0 logs to its workerlog: rank 0 hosts
            # the store daemon and is the most failure-prone rank — the
            # failure report must be able to tail its log too
            p = subprocess.Popen(
                [sys.executable, args.training_script] +
                args.training_script_args,
                env=env, stdout=logf, stderr=subprocess.STDOUT)
            p._pd_rank = rank
            procs.append(p)
    except Exception:
        # a mid-loop spawn failure (EMFILE, ENOMEM) must not strand the
        # already-started ranks holding the rendezvous ports
        _reap_gang(procs, getattr(args, "grace_period", 5.0))
        for f in logs:
            f.close()
        raise
    return procs, logs


def _emit_flight_diagnosis(args, attempt, world, stream=None):
    """Aggregate the generation's flight dumps into the cross-rank
    desync verdict and emit it as a ``gang_diagnosis`` event (plain
    mode prints the diagnosis text verbatim — the SAME text
    ``tools/flight_report.py`` prints offline, byte-for-byte; JSON mode
    carries the structured fields for machine ingestion). Ranks whose
    dump is missing or unparsable (crashed before dumping) are NAMED in
    the diagnosis instead of silently omitted. Returns the struct, or
    None when no dumps exist (recorder disabled)."""
    from ..resilience import flight_recorder
    dump_dir = os.environ.get("PADDLE_FLIGHT_DUMP_DIR") or args.log_dir
    # only the ranks THIS supervisor spawned can be expected to dump
    # into this node's dir — remote nodes' ranks dump on their hosts
    local = [args.node_rank * args.nproc_per_node + i
             for i in range(args.nproc_per_node)]
    try:
        text, diag = flight_recorder.diagnose_dir(
            dump_dir, world=world, generation=attempt,
            expected_ranks=local)
    except Exception as e:          # a broken dump must not mask the
        log_event("launch", "gang_diagnosis_error", stream=stream,
                  message=f"launch: flight diagnosis failed: {e!r}",
                  generation=attempt, error=repr(e))
        return None                 # underlying failure report
    if not diag["ranks_with_dump"] and not diag["missing_dump_errors"]:
        return None                 # no recorder output for this gang
    log_event("launch", "gang_diagnosis", stream=stream, message=text,
              generation=attempt, world=world, desync=diag["desync"],
              stragglers=diag["stragglers"], stuck=diag["stuck"],
              ranks_with_dump=diag["ranks_with_dump"],
              ranks_missing_dump=diag["ranks_missing_dump"],
              missing_dump_errors=diag["missing_dump_errors"],
              groups=diag["groups"])
    return diag


def _failure_report(args, procs, attempt) -> str:
    lines = [f"launch: gang failure report (attempt {attempt}):"]
    for p in procs:
        rc = p.poll()
        rank = p._pd_rank
        status = "ok" if rc == 0 else (
            f"signal {-rc}" if rc is not None and rc < 0 else f"exit {rc}")
        lines.append(f"launch:   rank {rank}: {status}")
        if rc not in (0, None):
            tail = _tail(_log_path(args.log_dir, rank, attempt))
            lines.append(f"launch:   --- workerlog tail (rank {rank}) ---")
            lines.extend(f"launch:   | {ln}" for ln in tail.splitlines())
    return "\n".join(lines)


def main():
    parser = argparse.ArgumentParser("paddle_tpu.distributed.launch")
    parser.add_argument("--nproc_per_node", "--nprocs", type=int, default=1)
    parser.add_argument("--nnodes", type=int, default=1)
    parser.add_argument("--node_rank", type=int, default=0)
    parser.add_argument("--master", default=None)
    parser.add_argument("--log_dir", default="log")
    parser.add_argument("--max_restart", type=int, default=0)
    parser.add_argument(
        "--restart_backoff", type=float,
        default=float(os.environ.get("PADDLE_RESTART_BACKOFF_S", "1")),
        help="base of the exponential restart backoff (seconds)")
    parser.add_argument(
        "--grace_period", type=float,
        default=float(os.environ.get("PADDLE_LAUNCH_GRACE_S", "5")),
        help="SIGTERM->SIGKILL grace when tearing down a failed gang")
    parser.add_argument("--devices", "--gpus", default=None,
                        help="accepted for reference-CLI parity; device "
                             "placement is XLA-managed")
    parser.add_argument("--job_id", default="default")
    parser.add_argument("training_script")
    parser.add_argument("training_script_args", nargs=argparse.REMAINDER)
    args = parser.parse_args()

    os.makedirs(args.log_dir, exist_ok=True)
    poll_s = float(os.environ.get("PADDLE_LAUNCH_POLL_S", "0.2"))
    backoff_cap = float(os.environ.get("PADDLE_RESTART_BACKOFF_MAX_S", "30"))

    attempt = 0
    while True:
        # fresh master port per generation (unless pinned by --master):
        # the previous generation's coordinator/TCPStore sockets may
        # linger in TIME_WAIT, and a stale store daemon must never serve
        # the new generation's rendezvous
        master = args.master or f"127.0.0.1:{_free_port()}"
        procs, logs = _spawn_gang(args, master, attempt)
        # JSON-only event (no plain-mode print existed here): the
        # cluster front-end sees each generation start with its master
        log_event("launch", "gang_start", stream=sys.stderr,
                  generation=attempt, master=master,
                  world=args.nproc_per_node * args.nnodes,
                  pids=[p.pid for p in procs])
        first_bad = None
        try:
            while True:
                codes = [p.poll() for p in procs]
                bad = [p for p in procs
                       if p.poll() not in (0, None)]
                if bad:
                    first_bad = bad[0]
                    break
                if all(c == 0 for c in codes):
                    return 0
                time.sleep(poll_s)
            # gang failure: tear down the survivors, then report
            _reap_gang(procs, args.grace_period)
        except KeyboardInterrupt:
            _reap_gang(procs, args.grace_period)
            raise
        finally:
            for f in logs:
                f.close()

        fail_rc = first_bad.poll()
        fail_rc = fail_rc if fail_rc > 0 else 128 - fail_rc  # signal -> 128+N
        log_event("launch", "gang_failure", stream=sys.stderr,
                  message=_failure_report(args, procs, attempt),
                  generation=attempt, failed_rank=first_bad._pd_rank,
                  failed_rc=fail_rc,
                  exit_codes={p._pd_rank: p.poll() for p in procs},
                  log_tail=_tail(_log_path(args.log_dir,
                                           first_bad._pd_rank, attempt)))
        # cross-rank flight diagnosis: name the desynced collective and
        # the straggler rank instead of leaving only the log tail
        _emit_flight_diagnosis(args, attempt,
                               args.nproc_per_node * args.nnodes,
                               stream=sys.stderr)
        attempt += 1
        if attempt > args.max_restart:
            log_event("launch", "restart_budget_exhausted",
                      stream=sys.stderr,
                      message=f"launch: rank {first_bad._pd_rank} failed "
                              f"(rc {fail_rc}); restart budget exhausted "
                              f"({args.max_restart})",
                      generation=attempt - 1,
                      failed_rank=first_bad._pd_rank, failed_rc=fail_rc,
                      max_restart=args.max_restart)
            return fail_rc
        delay = min(args.restart_backoff * (2 ** (attempt - 1)),
                    backoff_cap)
        log_event("launch", "restart", stream=sys.stderr,
                  message=f"launch: restarting (attempt {attempt}/"
                          f"{args.max_restart}) after {delay:.1f}s "
                          f"backoff, fresh master port, "
                          f"PADDLE_RESTART_COUNT={attempt}",
                  generation=attempt, backoff_s=round(delay, 3),
                  max_restart=args.max_restart)
        time.sleep(delay)


if __name__ == "__main__":
    sys.exit(main())
