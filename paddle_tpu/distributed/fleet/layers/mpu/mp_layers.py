"""Tensor-parallel layers.

Parity: python/paddle/distributed/fleet/layers/mpu/mp_layers.py ::
ColumnParallelLinear / RowParallelLinear / VocabParallelEmbedding /
ParallelCrossEntropy (+ mp_ops.py _c_identity/_c_split/_mp_allreduce).

TPU-native design (NOT a NCCL translation): each layer keeps the FULL
parameter annotated with a PartitionSpec on the 'mp' mesh axis; inside a
jitted/pjit step GSPMD shards the weight, runs the local matmul on each
chip's MXU, and inserts the exact all-reduce/all-gather the reference
implements by hand (the identity-fwd/allreduce-bwd pairs fall out of XLA's
transpose rules). Eagerly on one device the layers behave as plain Linear, so
the reference's serial-vs-parallel allclose test pattern holds by
construction.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from .....nn import functional as F
from .....nn.initializer import Constant, XavierNormal, Normal
from .....nn.layer.layers import Layer
from .....tensor.tensor import Parameter, Tensor, apply_op

__all__ = ["ColumnParallelLinear", "RowParallelLinear",
           "VocabParallelEmbedding", "ParallelCrossEntropy"]


def _mesh():
    from ...base.topology import _HYBRID_GROUP
    hcg = _HYBRID_GROUP[0]
    return hcg.mesh if hcg is not None else None


def constraint(x: Tensor, *spec) -> Tensor:
    """with_sharding_constraint on the hybrid mesh (no-op without a mesh)."""
    mesh = _mesh()
    if mesh is None:
        return x
    sh = NamedSharding(mesh, P(*spec))

    def f(a):
        try:
            return jax.lax.with_sharding_constraint(a, sh)
        except Exception:
            return a
    return apply_op(f, x)


def _resolve_init(attr, default):
    from .....nn.layer.common import _resolve_init as r
    return r(attr, default)


class ColumnParallelLinear(Layer):
    """W:[in, out] sharded on out ('mp' axis). gather_output=False leaves the
    activation mp-sharded for a following RowParallelLinear."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=None, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.gather_output = gather_output
        from ...base.topology import _HYBRID_GROUP
        hcg = _HYBRID_GROUP[0]
        self.world_size = (hcg.get_model_parallel_world_size()
                           if hcg is not None else 1)
        w_init, _ = _resolve_init(weight_attr, XavierNormal())
        self.weight = Parameter(w_init((in_features, out_features),
                                       self._dtype))
        self.weight.sharding_spec = P(None, "mp")
        self.weight.split_axis = 1
        self.weight.is_distributed = True
        if has_bias is False:
            self.bias = None
        else:
            self.bias = Parameter(jnp.zeros((out_features,), self._dtype))
            self.bias.sharding_spec = P("mp")
            self.bias.split_axis = 0
            self.bias.is_distributed = True

    def forward(self, x):
        out = F.linear(x, self.weight, self.bias)
        if self.gather_output:
            return constraint(out, *([None] * (out.ndim)))
        # keep last dim sharded over mp
        spec = [None] * (out.ndim - 1) + ["mp"]
        return constraint(out, *spec)


class RowParallelLinear(Layer):
    """W:[in, out] sharded on in ('mp' axis); input arrives mp-sharded on the
    feature dim; XLA inserts the partial-sum all-reduce the reference codes as
    mp_allreduce."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False,
                 fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.input_is_parallel = input_is_parallel
        w_init, _ = _resolve_init(weight_attr, XavierNormal())
        self.weight = Parameter(w_init((in_features, out_features),
                                       self._dtype))
        self.weight.sharding_spec = P("mp", None)
        self.weight.split_axis = 0
        self.weight.is_distributed = True
        if has_bias:
            self.bias = Parameter(jnp.zeros((out_features,), self._dtype))
            self.bias.sharding_spec = P(None)
        else:
            self.bias = None

    def forward(self, x):
        if self.input_is_parallel:
            spec = [None] * (x.ndim - 1) + ["mp"]
            x = constraint(x, *spec)
        out = F.linear(x, self.weight, self.bias)
        return constraint(out, *([None] * out.ndim))


class VocabParallelEmbedding(Layer):
    """Embedding table sharded on the vocab dim over 'mp'."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        w_init, _ = _resolve_init(weight_attr, Normal(0.0, 1.0))
        self.weight = Parameter(w_init((num_embeddings, embedding_dim),
                                       self._dtype))
        self.weight.sharding_spec = P("mp", None)
        self.weight.split_axis = 0
        self.weight.is_distributed = True

    def forward(self, x):
        out = F.embedding(x, self.weight)
        return constraint(out, *([None] * out.ndim))


class ParallelCrossEntropy(Layer):
    """Softmax CE over mp-sharded logits. The reference computes a two-pass
    max/sum reduction across ranks; GSPMD derives the same from the sharded
    log-softmax composite."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label):
        loss = F.cross_entropy(input, label, reduction="none",
                               ignore_index=self.ignore_index)
        return loss
