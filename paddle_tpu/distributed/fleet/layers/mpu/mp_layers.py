"""Tensor-parallel layers.

Parity: python/paddle/distributed/fleet/layers/mpu/mp_layers.py ::
ColumnParallelLinear / RowParallelLinear / VocabParallelEmbedding /
ParallelCrossEntropy (+ mp_ops.py _c_identity/_c_split/_mp_allreduce).

TPU-native design (NOT a NCCL translation): each layer keeps the FULL
parameter annotated with a PartitionSpec on the 'mp' mesh axis; inside a
jitted/pjit step GSPMD shards the weight, runs the local matmul on each
chip's MXU, and inserts the exact all-reduce/all-gather the reference
implements by hand (the identity-fwd/allreduce-bwd pairs fall out of XLA's
transpose rules). Eagerly on one device the layers behave as plain Linear, so
the reference's serial-vs-parallel allclose test pattern holds by
construction.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from .....nn import functional as F
from .....nn.initializer import Constant, XavierNormal, Normal
from .....nn.layer.layers import Layer
from .....tensor.tensor import Parameter, Tensor, apply_op

__all__ = ["ColumnParallelLinear", "RowParallelLinear",
           "VocabParallelEmbedding", "ParallelCrossEntropy"]


def _mesh():
    from ...base.topology import _HYBRID_GROUP
    hcg = _HYBRID_GROUP[0]
    return hcg.mesh if hcg is not None else None


def constraint(x: Tensor, *spec) -> Tensor:
    """with_sharding_constraint on the hybrid mesh (no-op without a mesh)."""
    mesh = _mesh()
    if mesh is None:
        return x
    sh = NamedSharding(mesh, P(*spec))

    def f(a):
        try:
            return jax.lax.with_sharding_constraint(a, sh)
        except Exception:
            # inside a partial-manual shard_map (the compiled pipeline) the
            # concrete mesh's axis types mismatch the context mesh — a bare
            # PartitionSpec binds to the context mesh instead
            try:
                return jax.lax.with_sharding_constraint(a, P(*spec))
            except Exception:
                return a
    return apply_op(f, x)


def _resolve_init(attr, default):
    from .....nn.layer.common import _resolve_init as r
    return r(attr, default)


class ColumnParallelLinear(Layer):
    """W:[in, out] sharded on out ('mp' axis). gather_output=False leaves the
    activation mp-sharded for a following RowParallelLinear."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=None, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.gather_output = gather_output
        from ...base.topology import _HYBRID_GROUP
        hcg = _HYBRID_GROUP[0]
        self.world_size = (hcg.get_model_parallel_world_size()
                           if hcg is not None else 1)
        w_init, _ = _resolve_init(weight_attr, XavierNormal())
        self.weight = Parameter(w_init((in_features, out_features),
                                       self._dtype))
        self.weight.sharding_spec = P(None, "mp")
        self.weight.split_axis = 1
        self.weight.is_distributed = True
        if has_bias is False:
            self.bias = None
        else:
            self.bias = Parameter(jnp.zeros((out_features,), self._dtype))
            self.bias.sharding_spec = P("mp")
            self.bias.split_axis = 0
            self.bias.is_distributed = True

    def forward(self, x):
        out = F.linear(x, self.weight, self.bias)
        if self.gather_output:
            return constraint(out, *([None] * (out.ndim)))
        # keep last dim sharded over mp
        spec = [None] * (out.ndim - 1) + ["mp"]
        return constraint(out, *spec)


class RowParallelLinear(Layer):
    """W:[in, out] sharded on in ('mp' axis); input arrives mp-sharded on the
    feature dim; XLA inserts the partial-sum all-reduce the reference codes as
    mp_allreduce."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False,
                 fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.input_is_parallel = input_is_parallel
        w_init, _ = _resolve_init(weight_attr, XavierNormal())
        self.weight = Parameter(w_init((in_features, out_features),
                                       self._dtype))
        self.weight.sharding_spec = P("mp", None)
        self.weight.split_axis = 0
        self.weight.is_distributed = True
        if has_bias:
            self.bias = Parameter(jnp.zeros((out_features,), self._dtype))
            self.bias.sharding_spec = P(None)
        else:
            self.bias = None

    def forward(self, x):
        if self.input_is_parallel:
            spec = [None] * (x.ndim - 1) + ["mp"]
            x = constraint(x, *spec)
        out = F.linear(x, self.weight, self.bias)
        return constraint(out, *([None] * out.ndim))


class VocabParallelEmbedding(Layer):
    """Embedding table sharded on the vocab dim over 'mp'."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        w_init, _ = _resolve_init(weight_attr, Normal(0.0, 1.0))
        self.weight = Parameter(w_init((num_embeddings, embedding_dim),
                                       self._dtype))
        self.weight.sharding_spec = P("mp", None)
        self.weight.split_axis = 0
        self.weight.is_distributed = True

    def forward(self, x):
        out = F.embedding(x, self.weight)
        return constraint(out, *([None] * out.ndim))


def _vocab_parallel_ce_fn(mesh, vocab, ignore_index):
    """Two-pass vocab-parallel softmax CE over the 'mp' axis inside
    shard_map — the reference's c_softmax_with_cross_entropy semantics
    (local max → cross-rank max, local sum-exp → cross-rank sum, target
    logit fetched from its owner rank). The [N, V] logits stay sharded
    [N, V/mp] per device throughout; only [N, 1] statistics cross the ICI —
    the full-vocab gather GSPMD might otherwise insert (the exact memory
    blow-up the reference op exists to avoid) cannot happen inside
    shard_map's manual region."""
    from jax import shard_map

    mp = mesh.shape["mp"]
    part = vocab // mp
    data_axes = tuple(a for a in ("dp", "sharding", "sep")
                      if a in mesh.shape and mesh.shape[a] > 1)

    def ce(lg, lb):
        # lg: [n_local, V/mp]; lb: [n_local]. fp32 softmax math to match
        # the dense path (loss numerics must not depend on mp degree)
        lg = lg.astype(jnp.float32)
        idx = jax.lax.axis_index("mp")
        # max is for numerical stability only — detach BEFORE pmax (pmax
        # has no differentiation rule; a zero tangent short-circuits it)
        m = jax.lax.pmax(
            jax.lax.stop_gradient(jnp.max(lg, -1, keepdims=True)), "mp")
        z = jax.lax.psum(jnp.sum(jnp.exp(lg - m), -1, keepdims=True), "mp")
        lo = idx * part
        in_range = (lb >= lo) & (lb < lo + part)
        loc = jnp.clip(lb - lo, 0, part - 1)
        tgt_local = jnp.take_along_axis(lg, loc[:, None], -1)[:, 0]
        tgt = jax.lax.psum(jnp.where(in_range, tgt_local, 0.0), "mp")
        loss = m[:, 0] + jnp.log(z[:, 0]) - tgt
        if ignore_index is not None:
            loss = jnp.where(lb == ignore_index, 0.0, loss)
        return loss

    def run(logits2d, labels1d):
        n = logits2d.shape[0]
        bspec = data_axes if data_axes and n % _axes_size(
            mesh, data_axes) == 0 else None
        f = shard_map(ce, mesh=mesh,
                      in_specs=(P(bspec, "mp"), P(bspec)),
                      out_specs=P(bspec))
        return f(logits2d, labels1d)

    return run


def _axes_size(mesh, axes):
    s = 1
    for a in axes:
        s *= mesh.shape[a]
    return s


class ParallelCrossEntropy(Layer):
    """Softmax CE over mp-sharded logits without materializing the full
    vocab per device. Parity: mp_ops.py :: ParallelCrossEntropy /
    c_softmax_with_cross_entropy_op.cu (two-pass max/sum across mp ranks).

    With an active mesh whose mp ≥ 2 (and a divisible vocab) the loss runs
    the shard_map two-pass kernel; otherwise it degrades to dense CE —
    numerically identical either way (the reference's serial-vs-parallel
    contract)."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index
        self._run_cache = {}

    def _run_fn(self, mesh, vocab):
        # cache per (mesh, vocab): a stable callable identity keeps jax's
        # dispatch cache warm across eager steps (no per-call retrace)
        key = (id(mesh), vocab)
        fn = self._run_cache.get(key)
        if fn is None:
            fn = _vocab_parallel_ce_fn(mesh, vocab, self.ignore_index)
            self._run_cache[key] = fn
        return fn

    def forward(self, input, label):
        mesh = _mesh()
        vocab = int(input.shape[-1])
        if mesh is not None and mesh.shape.get("mp", 1) >= 2 and \
                vocab % mesh.shape["mp"] == 0:
            run = self._run_fn(mesh, vocab)
            shape = tuple(input.shape[:-1])

            def f(lg, lb):
                out = run(lg.reshape(-1, vocab),
                          lb.reshape(-1).astype(jnp.int32))
                return out.reshape(shape)
            return apply_op(f, input, label)
        return F.cross_entropy(input, label, reduction="none",
                               ignore_index=self.ignore_index)
