"""fleet.utils.fs — filesystem abstraction for checkpoint tooling.

Parity: python/paddle/distributed/fleet/utils/fs.py :: FS, LocalFS,
HDFSClient. LocalFS is fully functional; HDFSClient requires a hadoop
client binary and degrades to a clear error when absent (zero-egress
environment)."""
from __future__ import annotations

import os
import shutil
import subprocess

__all__ = ["FS", "LocalFS", "HDFSClient"]


class ExecuteError(Exception):
    pass


class FS:
    def ls_dir(self, path):
        raise NotImplementedError

    def is_dir(self, path):
        raise NotImplementedError

    def is_file(self, path):
        raise NotImplementedError

    def is_exist(self, path):
        raise NotImplementedError

    def upload(self, local_path, fs_path):
        raise NotImplementedError

    def download(self, fs_path, local_path):
        raise NotImplementedError

    def mkdirs(self, path):
        raise NotImplementedError

    def delete(self, path):
        raise NotImplementedError

    def mv(self, src, dst, overwrite=False):
        raise NotImplementedError

    def touch(self, path, exist_ok=True):
        raise NotImplementedError


class LocalFS(FS):
    """Local filesystem with the reference's (dirs, files) ls contract."""

    def ls_dir(self, path):
        if not self.is_exist(path):
            return [], []
        dirs, files = [], []
        for name in sorted(os.listdir(path)):
            (dirs if os.path.isdir(os.path.join(path, name))
             else files).append(name)
        return dirs, files

    def is_dir(self, path):
        return os.path.isdir(path)

    def is_file(self, path):
        return os.path.isfile(path)

    def is_exist(self, path):
        return os.path.exists(path)

    def mkdirs(self, path):
        os.makedirs(path, exist_ok=True)

    def delete(self, path):
        if os.path.isdir(path):
            shutil.rmtree(path)
        elif os.path.exists(path):
            os.remove(path)

    def mv(self, src, dst, overwrite=False):
        if not overwrite and os.path.exists(dst):
            raise ExecuteError(f"mv: destination exists: {dst}")
        if overwrite and os.path.exists(dst):
            self.delete(dst)
        shutil.move(src, dst)

    def touch(self, path, exist_ok=True):
        if os.path.exists(path):
            if not exist_ok:
                raise ExecuteError(f"touch: exists: {path}")
            return
        with open(path, "a"):
            pass

    # upload/download are copies on a local fs
    def upload(self, local_path, fs_path):
        if os.path.isdir(local_path):
            shutil.copytree(local_path, fs_path, dirs_exist_ok=True)
        else:
            shutil.copy(local_path, fs_path)

    def download(self, fs_path, local_path):
        self.upload(fs_path, local_path)

    def list_dirs(self, path):
        return self.ls_dir(path)[0]


class HDFSClient(FS):
    """`hadoop fs` CLI wrapper (reference contract). Instantiation checks
    the client exists so failures happen at setup, not mid-checkpoint."""

    def __init__(self, hadoop_home: str, configs=None, time_out=300000,
                 sleep_inter=1000):
        self._bin = os.path.join(hadoop_home, "bin", "hadoop")
        self._configs = configs or {}
        self._timeout_s = float(time_out) / 1000.0
        if not os.path.exists(self._bin):
            raise ExecuteError(
                f"hadoop client not found at {self._bin}; HDFSClient "
                f"requires a hadoop install (unavailable in this "
                f"environment — use LocalFS)")

    def _run(self, *args):
        cmd = [self._bin, "fs"]
        for k, v in self._configs.items():
            cmd += ["-D", f"{k}={v}"]
        cmd += list(args)
        try:
            res = subprocess.run(cmd, capture_output=True, text=True,
                                 timeout=self._timeout_s)
        except subprocess.TimeoutExpired:
            raise ExecuteError(
                f"hadoop {' '.join(args)}: timed out after "
                f"{self._timeout_s:.0f}s")
        if res.returncode != 0:
            raise ExecuteError(f"hadoop {' '.join(args)}: {res.stderr}")
        return res.stdout

    def ls_dir(self, path):
        out = self._run("-ls", path)
        dirs, files = [], []
        for line in out.splitlines():
            parts = line.split()
            if len(parts) < 8:
                continue
            name = os.path.basename(parts[-1])
            (dirs if parts[0].startswith("d") else files).append(name)
        return dirs, files

    def is_exist(self, path):
        try:
            self._run("-test", "-e", path)
            return True
        except ExecuteError:
            return False

    def is_dir(self, path):
        try:
            self._run("-test", "-d", path)
            return True
        except ExecuteError:
            return False

    def is_file(self, path):
        return self.is_exist(path) and not self.is_dir(path)

    def mkdirs(self, path):
        self._run("-mkdir", "-p", path)

    def delete(self, path):
        self._run("-rm", "-r", "-f", path)

    def upload(self, local_path, fs_path):
        self._run("-put", "-f", local_path, fs_path)

    def download(self, fs_path, local_path):
        self._run("-get", fs_path, local_path)

    def mv(self, src, dst, overwrite=False):
        if overwrite and self.is_exist(dst):
            self.delete(dst)
        self._run("-mv", src, dst)

    def touch(self, path, exist_ok=True):
        if self.is_exist(path):
            if not exist_ok:
                raise ExecuteError(f"touch: exists: {path}")
            return
        self._run("-touchz", path)
