"""Activation recompute. Parity:
python/paddle/distributed/fleet/utils/recompute.py :: recompute /
recompute_sequential / RecomputeFunction (PyLayer + RNG-state replay).

Tape-level realization: forward runs under no_grad (zero residual memory);
a single tape node is recorded whose vjp re-runs the function with gradients
enabled and backprops through the sub-tape — parameter gradients accumulate
into .grad exactly as in the reference's RecomputeFunction.backward. RNG
replay is exact because the global PRNG key is snapshotted and restored
(explicit keys — stronger than the reference's CUDA RNG state juggling).
Under paddle.jit.to_static the same code traces into XLA remat regions.
"""
from __future__ import annotations

from typing import Callable

from ....core.rng import get_rng_state, set_rng_state
from ....tensor.tensor import (Tensor, _TapeNode, _tape, enable_grad,
                               is_grad_enabled, no_grad)
from ....autograd.backward_engine import run_backward

__all__ = ["recompute", "recompute_sequential", "RecomputeFunction"]


def recompute(function: Callable, *args, **kwargs):
    kwargs.pop("use_reentrant", None)
    preserve_rng_state = kwargs.pop("preserve_rng_state", True)

    if not is_grad_enabled():
        return function(*args, **kwargs)

    tensor_positions = [i for i, a in enumerate(args) if isinstance(a, Tensor)]
    tensor_inputs = [args[i] for i in tensor_positions]
    rng_snapshot = get_rng_state() if preserve_rng_state else None

    with no_grad():
        out = function(*args, **kwargs)
    multi = isinstance(out, (tuple, list))
    outs_raw = tuple(out) if multi else (out,)
    outs = tuple(Tensor(o._data, stop_gradient=False) for o in outs_raw)
    for o in outs:
        o._is_leaf = False

    def vjp_fn(cots):
        if preserve_rng_state:
            rng_after = get_rng_state()
            set_rng_state(rng_snapshot)
        detached = []
        rebuilt = list(args)
        for i, t in zip(tensor_positions, tensor_inputs):
            d = Tensor(t._data, stop_gradient=t.stop_gradient)
            d._is_leaf = True
            detached.append(d)
            rebuilt[i] = d
        mark = len(_tape.nodes)
        with enable_grad():
            out2 = function(*rebuilt, **kwargs)
        outs2 = tuple(out2) if isinstance(out2, (tuple, list)) else (out2,)
        seeds = [Tensor(c) for c in cots]
        run_backward(list(outs2), seeds, retain_graph=True)
        del _tape.nodes[mark:]
        if preserve_rng_state:
            set_rng_state(rng_after)
        result = []
        for d, t in zip(detached, tensor_inputs):
            result.append(None if d.grad is None else d.grad._data)
        return tuple(result)

    node = _TapeNode(
        inputs=list(tensor_inputs),
        output_ids=[o._uid for o in outs],
        vjp_fn=vjp_fn,
        outputs_meta=[(tuple(o.shape), o.dtype) for o in outs],
    )
    from ....tensor.tensor import _register_node
    _register_node(node, outs)
    return outs if multi else outs[0]


class RecomputeFunction:
    @staticmethod
    def apply(function, *args, **kwargs):
        return recompute(function, *args, **kwargs)


def recompute_sequential(ctx, functions, *args):
    """Parity: recompute_sequential — chunked recompute over a Sequential."""
    segments = ctx.get("segments", 1) if isinstance(ctx, dict) else 1
    if hasattr(functions, "_sub_layers"):
        layers = list(functions._sub_layers.values())
    else:
        layers = list(functions)
    import numpy as np
    parts = np.array_split(np.arange(len(layers)), segments)
    out = args[0] if len(args) == 1 else args

    def run_segment(seg_layers):
        def f(x):
            for l in seg_layers:
                x = l(x)
            return x
        return f

    for part in parts:
        seg = [layers[i] for i in part]
        out = recompute(run_segment(seg), out)
    return out
