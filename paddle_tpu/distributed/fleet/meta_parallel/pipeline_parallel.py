"""Pipeline-parallel execution. Parity:
python/paddle/distributed/fleet/meta_parallel/pipeline_parallel.py ::
PipelineParallel.train_batch (1F1B), PipelineParallelWithInterleave
(+ pp_utils/p2p_communication.py SendRecvMeta handshake).

TPU-native execution model: there are no per-stage OS processes or NCCL P2P
queues. `train_batch` runs the reference's micro-batch schedule — split into
accumulate_steps micro-batches, forward/backward each, accumulate grads, one
optimizer step — which is numerically identical to 1F1B. When the step is
compiled (paddle.jit.to_static over a pp-annotated mesh), stage placement
comes from parameter sharding specs and XLA's latency-hiding scheduler
overlaps the inter-stage transfers; the explicit ppermute ring-schedule
engine for homogeneous decoder stacks lives in
paddle_tpu.parallel.pipeline (GPipe/1F1B over shard_map — see there).
"""
from __future__ import annotations

import jax.numpy as jnp

from ....tensor.tensor import Tensor, no_grad
from .parallel_layers import MetaParallelBase
from .pp_layers import PipelineLayer

__all__ = ["PipelineParallel", "PipelineParallelWithInterleave"]


class PipelineParallel(MetaParallelBase):
    def __init__(self, layers, hcg, strategy):
        super().__init__(layers, hcg, strategy)
        pp_cfg = strategy.hybrid_configs.get("pp_configs", {}) if strategy else {}
        self.accumulate_steps = (
            pp_cfg.get("accumulate_steps", 1) if hasattr(pp_cfg, "get") else 1)
        self.micro_batch_size = (
            pp_cfg.get("micro_batch_size", 1) if hasattr(pp_cfg, "get") else 1)
        self.num_stages = hcg.get_pipe_parallel_world_size()
        self.stage_id = hcg.get_stage_id()
        self.total_loss = None

    def is_pipeline_first_stage(self):
        return self.stage_id == 0

    def is_pipeline_last_stage(self):
        return self.stage_id == self.num_stages - 1

    def _split_micro(self, data):
        if isinstance(data, (list, tuple)):
            xs = [self._split_micro(d) for d in data]
            return list(zip(*xs))
        n = self.accumulate_steps
        b = data.shape[0]
        mb = max(b // n, 1)
        return [data[i * mb:(i + 1) * mb] for i in range(min(n, b // mb))]

    def forward_backward_pipeline(self, data, scaler=None):
        model = self._layers
        micro_batches = self._split_micro(data)
        total = None
        n = len(micro_batches)
        for mb in micro_batches:
            if isinstance(mb, (list, tuple)) and len(mb) == 2:
                x, label = mb
            else:
                x, label = mb, None
            out = model(x) if not isinstance(model, PipelineLayer) else \
                model.forward(x)
            loss = model.loss(out, label) if isinstance(model, PipelineLayer) \
                else out
            scaled = loss / n
            if scaler is not None:
                scaled = scaler.scale(scaled)
            scaled.backward()
            total = loss.detach() if total is None else total + loss.detach()
        self.total_loss = total / n
        return self.total_loss

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        self._layers.train()
        loss = self.forward_backward_pipeline(data, scaler)
        if scaler is not None:
            scaler.step(optimizer)
            scaler.update()
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return loss

    @no_grad()
    def eval_batch(self, data, compute_loss=True):
        self._layers.eval()
        micro_batches = self._split_micro(data)
        total = None
        for mb in micro_batches:
            if isinstance(mb, (list, tuple)) and len(mb) == 2:
                x, label = mb
            else:
                x, label = mb, None
            model = self._layers
            out = model(x)
            loss = model.loss(out, label) if isinstance(model, PipelineLayer) \
                and compute_loss else out
            total = loss.detach() if total is None else total + loss.detach()
        return total / max(len(micro_batches), 1)


class PipelineParallelWithInterleave(PipelineParallel):
    """Virtual-pipeline (interleaved 1F1B) parity: same numerics; chunking is
    a compile-time placement detail on the mesh."""

    def __init__(self, layers, hcg, strategy):
        super().__init__(layers, hcg, strategy)
        self.num_model_chunks = getattr(layers, "_num_virtual_pipeline_stages",
                                        1)
