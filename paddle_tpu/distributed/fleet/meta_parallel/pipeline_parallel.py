"""Pipeline-parallel execution. Parity:
python/paddle/distributed/fleet/meta_parallel/pipeline_parallel.py ::
PipelineParallel.train_batch (1F1B), PipelineParallelWithInterleave
(+ pp_utils/p2p_communication.py SendRecvMeta handshake).

TPU-native execution model: there are no per-stage OS processes or NCCL P2P
queues. When the hybrid mesh has pp ≥ 2 and the PipelineLayer's middle is a
PERIODIC layer stack (homogeneous period-1 transformers, or period-k
patterns like MoE-every-k / wide-narrow alternations), `train_batch`
compiles the WHOLE schedule into one SPMD program: the
stage bodies are stacked on a leading pp axis, `shard_map` places one stage
per pp rank, and the `lax.scan`-of-`ppermute` engine in
paddle_tpu.parallel.pipeline runs the micro-batch schedule (GPipe fill-drain;
interleaved virtual chunks for PipelineParallelWithInterleave). Activation
passing is the ppermute ICI neighbor exchange — shapes are static under jit
so there is no SendRecvMeta handshake to replicate. Embedding/head layers
outside the homogeneous run execute under GSPMD (replicated over pp, sharded
over mp/dp per their annotations) before/after the pipelined section.

Fallback (no mesh, pp == 1, or a body with no usable periodic run): the
reference's
micro-batch loop — split into accumulate_steps micro-batches,
forward/backward each, accumulate grads, one optimizer step — which is
numerically identical to 1F1B.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ....tensor.tensor import Tensor, no_grad, _tape
from .parallel_layers import MetaParallelBase
from .pp_layers import PipelineLayer

__all__ = ["PipelineParallel", "PipelineParallelWithInterleave"]


class _NotPipelineable(Exception):
    pass


def _param_sig(layer):
    """Structural identity for 'same stage body' detection: class (the
    forward fn) + parameter shapes/dtypes. Param shapes alone are not
    enough — a stem Linear and a residual block can share shapes. For
    PARAM-LESS layers the class name alone is not enough either: two
    _FnLayers wrapping different callables (relu vs silu) or two Dropouts
    with different rates would collide and chunk_apply would silently run
    the template's behavior for both — so include config scalars and the
    wrapped-callable identity (distinct lambdas never match: conservative
    by construction)."""
    params = tuple((tuple(p.shape), str(p.dtype))
                   for p in layer.parameters())
    cfg = tuple(sorted((k, str(v)) for k, v in vars(layer).items()
                       if isinstance(v, (int, float, bool, str))))
    fn = getattr(layer, "_fn", None)
    # cfg applies to PARAM-BEARING layers too: same class + same shapes but
    # a different behavior flag (e.g. act='relu' vs 'gelu') must not match,
    # or chunk_apply would run the template's forward for both positions
    return (type(layer).__qualname__, params, cfg,
            None if fn is None else id(fn))


def _find_body(layers, slots):
    """Longest run of consecutive layers whose parameter-signature sequence
    is PERIODIC (period k ≤ 4; k=1 is the homogeneous case), usable length
    a multiple of slots·k so every stage holds whole patterns
    (slots = pp_degree · virtual chunks). Periodic bodies cover the
    reference's non-uniform stacks — MoE-every-k blocks, Linear/Activation
    alternations — that a strict homogeneity test would reject.
    Returns (start, end, period)."""
    sigs = [_param_sig(l) for l in layers]
    n = len(layers)
    best = None          # (usable_len, -period, start)
    for k in (1, 2, 3, 4):
        i = 0
        while i < n:
            j = i + k
            while j < n and sigs[j] == sigs[j - k]:
                j += 1
            run = j - i
            unit = slots * k
            usable = (run // unit) * unit
            # at least one position must carry params (something to stack)
            if usable >= unit and any(sigs[i + t][1] for t in range(k)):
                cand = (usable, -k, i)
                if best is None or cand > best:
                    best = cand
            i = i + 1 if run < unit else j
    if best is None:
        raise _NotPipelineable(
            f"no periodic layer run of length divisible by {slots}")
    usable, neg_k, start = best
    return start, start + usable, -neg_k


def _substitute(params, arrays):
    old = [p._data for p in params]
    for p, a in zip(params, arrays):
        p._data = a
    return old


def _layer_params(layer):
    """Layer params INCLUDING tied weights hidden behind _SharedForward's
    unregistered reference (pp_layers keeps it out of parameters() to avoid
    double registration — but the jit step must receive the shared weight
    as an argument, not bake it in as a trace-time constant)."""
    ref = getattr(layer, "_shared_layer_ref", None)
    if ref:
        return list(ref[0].parameters())
    return list(layer.parameters())


def _apply_seq(layers, x):
    """Apply a layer sequence (params already substituted by the caller).
    x: raw array (or tuple of Tensors) -> raw array."""
    h = x if isinstance(x, tuple) else Tensor(x)
    with no_grad():
        for lay in layers:
            h = lay(*h) if isinstance(h, tuple) else lay(h)
    return h._data if isinstance(h, Tensor) else h


class PipelineParallel(MetaParallelBase):
    def __init__(self, layers, hcg, strategy):
        super().__init__(layers, hcg, strategy)
        pp_cfg = strategy.hybrid_configs.get("pp_configs", {}) if strategy else {}
        self.accumulate_steps = (
            pp_cfg.get("accumulate_steps", 1) if hasattr(pp_cfg, "get") else 1)
        self.micro_batch_size = (
            pp_cfg.get("micro_batch_size", 1) if hasattr(pp_cfg, "get") else 1)
        # remat window for the compiled schedule (gpipe block checkpointing);
        # "auto" = sqrt(T), None = store every tick input (faster backward)
        self.remat_window = (
            pp_cfg.get("remat_window", "auto") if hasattr(pp_cfg, "get")
            else "auto")
        self.num_stages = hcg.get_pipe_parallel_world_size()
        self.stage_id = hcg.get_stage_id()
        self.num_virtual = 1
        self.total_loss = None
        self._pp_cache = {}

    def is_pipeline_first_stage(self):
        return self.stage_id == 0

    def is_pipeline_last_stage(self):
        return self.stage_id == self.num_stages - 1

    # ------------------------------------------------------------ compiled pp
    def _mesh(self):
        mesh = getattr(self._hcg, "mesh", None)
        if mesh is not None and dict(mesh.shape).get("pp", 1) >= 2:
            return mesh
        return None

    def _partition(self):
        """Split run_function into (prologue, body, epilogue, period); the
        body is the periodic stack that gets pipelined over pp (round-robin
        chunked for virtual pp)."""
        layers = list(self._layers.run_function)
        slots = self.num_stages * self.num_virtual
        b0, b1, period = _find_body(layers, slots)
        return layers[:b0], layers[b0:b1], layers[b1:], period

    def _build_step(self, mesh, key):
        from ....parallel.pipeline import (gpipe, gpipe_interleaved,
                                           microbatch, unmicrobatch)
        from jax.sharding import PartitionSpec as P
        from jax import shard_map

        pro, body, epi, period = self._partition()
        pp, v = self.num_stages, self.num_virtual
        lc = len(body) // (pp * v)          # layers per chunk (k | lc)
        reps = lc // period                 # pattern repeats per chunk
        templates = body[:period]           # one live layer per position
        tpar = [list(t.parameters()) for t in templates]
        # every param the prologue/epilogue touch — including tied weights
        # reached via _SharedForward — deduped so each Parameter is exactly
        # one jit argument (a tied weight used in both gets one grad slot
        # covering both uses); body params travel separately as the stacked
        # pp-sharded argument
        body_ids = {id(p) for lay in body for p in lay.parameters()}
        seq_params, seen = [], set()
        for lay in list(pro) + list(epi):
            for p in _layer_params(lay):
                if id(p) not in seen and id(p) not in body_ids:
                    seen.add(id(p))
                    seq_params.append(p)
        model = self._layers
        micro = self.accumulate_steps
        data_axes = tuple(a for a in ("dp", "sharding") if a in mesh.shape)

        def stack_body():
            """Per pattern-position t, per param k: stacks of the layers at
            that position -> [P, v, reps, ...]. Global chunk g = c·P + i
            (reference round-robin) holds layers [g·Lc, (g+1)·Lc); since
            period | Lc, layer index i has position i % period."""
            out = []
            for t in range(period):
                pos_layers = body[t::period]
                pos = []
                for k in range(len(tpar[t])):
                    a = jnp.stack([lay.parameters()[k]._data
                                   for lay in pos_layers])
                    a = a.reshape(v, pp, reps, *a.shape[1:])
                    pos.append(jnp.moveaxis(a, 1, 0))
                out.append(pos)
            return out

        def chunk_apply(chunk_arrays, h):
            # chunk_arrays: [position][param] leaves with leading `reps`
            def one(h, rep_arrays):
                for t, template in enumerate(templates):
                    old = _substitute(tpar[t], rep_arrays[t])
                    try:
                        with no_grad():
                            h = template(Tensor(h))._data
                    finally:
                        _substitute(tpar[t], old)
                return h, None
            h, _ = jax.lax.scan(one, h, chunk_arrays)
            return h

        # shard the micro-batch dim over the data axes only when it divides
        # (else replicate — correct, just less parallel)
        data_world = 1
        for a in data_axes:
            data_world *= mesh.shape[a]
        mb_size = key[0][0] // max(micro, 1)
        shard_mb = bool(data_axes) and data_world > 1 and \
            mb_size % data_world == 0

        # HYBRID COMPOSITION (mp×pp×sharding in ONE program): only the pp
        # axis is manual (ppermute schedule); mp/sharding/dp stay GSPMD-
        # auto inside the shard_map, so the TP layers' sharding constraints
        # keep working inside stage bodies and the body params keep their
        # at-rest specs ('mp' from Column/RowParallel, 'sharding' from
        # stage 3) — XLA inserts the per-use all-gathers and the grad
        # reduce-scatters the reference's GroupShardedStage3 hooks code by
        # hand. Stacked body param k of pattern position t is
        # [P, v, reps, *shape]: P consumed by the manual pp spec,
        # [v, reps] replicated, then the param's own spec.
        def _stacked_spec(p):
            from ....parallel import _valid_spec
            sp = getattr(p, "sharding_spec", None)
            if sp is None or not _valid_spec(p._data, sp, mesh):
                return None
            return P(None, None, *sp)
        stacked_specs = [[_stacked_spec(p) for p in pos] for pos in tpar]

        @functools.partial(shard_map, mesh=mesh,
                           in_specs=(P("pp"), P()), out_specs=P(),
                           axis_names={"pp"}, check_vma=False)
        def run_pipe(stacked, h_mb):
            # bare PartitionSpecs bind to the CONTEXT mesh (pp is Manual
            # inside this shard_map) — a concrete-mesh NamedSharding here
            # would mismatch axis types and fail to trace
            local = jax.tree.map(lambda a: a[0], stacked)   # [v, reps, ...]
            local = [
                [a if sp is None else
                 jax.lax.with_sharding_constraint(a, sp)
                 for a, sp in zip(pos, pos_specs)]
                for pos, pos_specs in zip(local, stacked_specs)]
            if shard_mb:
                h_mb = jax.lax.with_sharding_constraint(
                    h_mb, P(None, data_axes,
                            *([None] * (h_mb.ndim - 2))))
            if v == 1:
                local = jax.tree.map(lambda a: a[0], local)
                return gpipe(chunk_apply, local, h_mb,
                             window=self.remat_window)
            return gpipe_interleaved(chunk_apply, local, h_mb, num_chunks=v)

        from ....nn.layer.layers import substitute_param_arrays

        def pure_step(seq_arrays, stacked, x, y, scale):
            _tape.nodes.clear()
            with substitute_param_arrays(seq_params, seq_arrays):
                h = _apply_seq(pro, x)
                h_mb = microbatch(h, micro)
                out = unmicrobatch(run_pipe(stacked, h_mb))
                out = _apply_seq(epi, out)
                with no_grad():
                    loss = model.loss(Tensor(out),
                                      None if y is None else Tensor(y))
            loss = loss._data if isinstance(loss, Tensor) else loss
            loss = jnp.mean(loss)
            _tape.nodes.clear()
            return loss * scale, loss

        grad_fn = jax.jit(jax.value_and_grad(pure_step, argnums=(0, 1),
                                             has_aux=True))
        self._pp_cache[key] = (grad_fn, stack_body, seq_params, body, period)
        return self._pp_cache[key]

    def _compiled_pipeline(self, x, y, scaler):
        mesh = self._mesh()
        x_arr = x._data if isinstance(x, Tensor) else jnp.asarray(x)
        y_arr = None if y is None else (
            y._data if isinstance(y, Tensor) else jnp.asarray(y))
        if x_arr.shape[0] % max(self.accumulate_steps, 1) != 0:
            raise _NotPipelineable("batch not divisible by accumulate_steps")
        key = (tuple(x_arr.shape), str(x_arr.dtype),
               None if y_arr is None else tuple(y_arr.shape))
        entry = self._pp_cache.get(key) or self._build_step(mesh, key)
        grad_fn, stack_body, seq_params, body, period = entry

        scale = jnp.asarray(1.0 if scaler is None else scaler._scale,
                            jnp.float32)
        seq_arrays = [p._data for p in seq_params]
        stacked = stack_body()
        (_, loss), (g_seq, g_stack) = grad_fn(
            seq_arrays, stacked, x_arr, y_arr, scale)

        def add_grad(p, g):
            g = g.astype(p._data.dtype)
            p.grad = Tensor(g) if p.grad is None else Tensor(p.grad._data + g)

        for p, g in zip(seq_params, g_seq):
            add_grad(p, g)
        pp, v = self.num_stages, self.num_virtual
        lc = len(body) // (pp * v)
        reps = lc // period
        for t in range(period):
            pos_layers = body[t::period]    # ordered (chunk g, repeat r)
            for k, gs in enumerate(g_stack[t]):
                # [P, v, reps, ...] -> [g·reps + r, ...] inverse of stack
                flat = jnp.moveaxis(gs, 0, 1).reshape(pp * v * reps,
                                                      *gs.shape[3:])
                for li, lay in enumerate(pos_layers):
                    add_grad(lay.parameters()[k], flat[li])
        self._pp_cache["_ran"] = True
        return Tensor(loss)

    # ------------------------------------------------------------- schedules
    def _split_micro(self, data):
        if isinstance(data, (list, tuple)):
            xs = [self._split_micro(d) for d in data]
            return list(zip(*xs))
        n = self.accumulate_steps
        b = data.shape[0]
        mb = max(b // n, 1)
        return [data[i * mb:(i + 1) * mb] for i in range(min(n, b // mb))]

    def forward_backward_pipeline(self, data, scaler=None):
        if isinstance(data, (list, tuple)) and len(data) == 2:
            x, label = data
        else:
            x, label = data, None
        if self._mesh() is not None and isinstance(self._layers,
                                                   PipelineLayer) and \
                not getattr(self, "_pp_disabled", False):
            try:
                self.total_loss = self._compiled_pipeline(x, label, scaler)
                return self.total_loss
            except _NotPipelineable:
                pass
            except Exception as e:
                if self._pp_cache.get("_ran"):
                    raise  # steady-state failure is a real error — surface it
                # first build/trace failed (e.g. tuple inter-stage
                # activations the compiled engine doesn't handle yet):
                # fall back to the numerically-identical micro-batch loop
                import warnings
                warnings.warn(
                    f"pipeline compile failed ({type(e).__name__}: {e}); "
                    f"falling back to sequential micro-batch schedule")
                self._pp_disabled = True
        model = self._layers
        micro_batches = self._split_micro(data)
        total = None
        n = len(micro_batches)
        for mb in micro_batches:
            if isinstance(mb, (list, tuple)) and len(mb) == 2:
                x, label = mb
            else:
                x, label = mb, None
            out = model(x) if not isinstance(model, PipelineLayer) else \
                model.forward(x)
            loss = model.loss(out, label) if isinstance(model, PipelineLayer) \
                else out
            scaled = loss / n
            if scaler is not None:
                scaled = scaler.scale(scaled)
            scaled.backward()
            total = loss.detach() if total is None else total + loss.detach()
        self.total_loss = total / n
        return self.total_loss

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        self._layers.train()
        loss = self.forward_backward_pipeline(data, scaler)
        if scaler is not None:
            scaler.step(optimizer)
            scaler.update()
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return loss

    @no_grad()
    def eval_batch(self, data, compute_loss=True):
        self._layers.eval()
        micro_batches = self._split_micro(data)
        total = None
        for mb in micro_batches:
            if isinstance(mb, (list, tuple)) and len(mb) == 2:
                x, label = mb
            else:
                x, label = mb, None
            model = self._layers
            out = model(x)
            loss = model.loss(out, label) if isinstance(model, PipelineLayer) \
                and compute_loss else out
            total = loss.detach() if total is None else total + loss.detach()
        return total / max(len(micro_batches), 1)


class PipelineParallelWithInterleave(PipelineParallel):
    """Interleaved (virtual-pipeline) schedule: layers assigned to stages
    round-robin in chunks; executed by
    parallel.pipeline.gpipe_interleaved's wave schedule (bubble P-1 vs the
    sequential v·(P-1))."""

    def __init__(self, layers, hcg, strategy):
        super().__init__(layers, hcg, strategy)
        self.num_virtual = max(
            int(getattr(layers, "_num_virtual_pipeline_stages", None) or 2), 1)
