"""GroupSharded (ZeRO) stages 1/2/3 over the 'sharding' mesh axis.

Parity:
  stage1: fleet/meta_optimizers/dygraph_optimizer/dygraph_sharding_optimizer.py
          :: DygraphShardingOptimizer (optimizer states sharded)
  stage2: fleet/meta_parallel/sharding/group_sharded_stage2.py +
          group_sharded_optimizer_stage2.py (+ grads sharded; GradStorage)
  stage3: fleet/meta_parallel/sharding/group_sharded_stage3.py (+ params
          sharded at rest, allgather-on-use, reduce-scatter grads)

TPU-native realization (the SURVEY §7 hard-part-3 design): sharding is a
PLACEMENT property, not a buffer-management protocol. Each stage annotates a
deeper set of tensors with PartitionSpec('sharding') on their largest axis:
  stage1 → optimizer moments (+ master weights)
  stage2 → + gradients (reduce-scatter falls out of GSPMD when the grad spec
             is sharded while params are replicated)
  stage3 → + parameters at rest (XLA inserts the pre-use all-gather and
             frees the gathered buffer after use — the reference's per-layer
             hook machinery, done by the compiler's liveness analysis)
Under `paddle.jit.to_static` the train step compiles against these specs;
eagerly on one device all stages are numerically the unsharded step, which is
exactly the reference's serial-vs-sharded allclose contract.
"""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .....nn.layer.layers import Layer
from .....tensor.tensor import Parameter, Tensor

__all__ = ["GroupShardedStage2", "GroupShardedStage3",
           "GroupShardedOptimizerStage2", "DygraphShardingOptimizer",
           "shard_spec_for", "annotate_optimizer_sharding"]


def shard_spec_for(t, axis_name: str = "sharding"):
    """Pick the largest axis to shard; None if too small/indivisible."""
    shape = tuple(t.shape)
    if not shape:
        return None
    ax = max(range(len(shape)), key=lambda i: shape[i])
    spec = [None] * len(shape)
    spec[ax] = axis_name
    return P(*spec)


def augment_spec_for(t, axis_name: str = "sharding"):
    """Stage-3 spec COMPOSED with an existing one (e.g. a TP param whose
    'mp' axis the ColumnParallel layer already claims): add axis_name on
    the largest still-unsharded dim. Returns the combined spec, or None if
    every dim is taken/0-d (caller keeps the original)."""
    prior = getattr(t, "sharding_spec", None)
    shape = tuple(t.shape)
    if not shape:
        return None
    prior = list(prior) if prior is not None else [None] * len(shape)
    prior += [None] * (len(shape) - len(prior))
    degree = 1
    try:
        from .....parallel import current_mesh
        mesh = current_mesh()
        if mesh is not None:
            degree = mesh.shape.get(axis_name, 1)
    except Exception:
        pass
    free = [i for i in range(len(shape))
            if prior[i] is None and (degree == 1 or shape[i] % degree == 0)]
    if not free:
        return None
    ax = max(free, key=lambda i: shape[i])
    prior[ax] = axis_name
    return P(*prior)


def annotate_optimizer_sharding(optimizer, axis_name: str = "sharding"):
    """Mark future + existing accumulators/masters for sharded placement."""
    optimizer._sharding_axis = axis_name
    for slot in optimizer._accumulators.values():
        for t in slot.values():
            if t._data is not None:   # skip failed-trace-rollback corpses
                t.sharding_spec = shard_spec_for(t, axis_name)
    for t in optimizer._master_weights.values():
        if t._data is not None:
            t.sharding_spec = shard_spec_for(t, axis_name)
    orig_acc = optimizer._acc

    def acc(name, p, init=None):
        t = orig_acc(name, p, init)
        if t.sharding_spec is None and t.ndim > 0:
            t.sharding_spec = shard_spec_for(t, axis_name)
        return t
    optimizer._acc = acc
    orig_master = optimizer._master

    def master(p):
        t = orig_master(p)
        if t is not p and t.sharding_spec is None:
            t.sharding_spec = shard_spec_for(t, axis_name)
        return t
    optimizer._master = master
    return optimizer


class DygraphShardingOptimizer:
    """Stage 1: optimizer-state sharding. Wraps any Optimizer."""

    def __init__(self, optimizer, hcg=None):
        self._inner = annotate_optimizer_sharding(optimizer)
        self._hcg = hcg

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def step(self):
        self._inner.step()

    def clear_grad(self, *a, **k):
        self._inner.clear_grad(*a, **k)

    def state_dict(self):
        return self._inner.state_dict()

    def set_state_dict(self, sd):
        return self._inner.set_state_dict(sd)


class GroupShardedOptimizerStage2(DygraphShardingOptimizer):
    """Stage 2 optimizer: gradients sharded over the 'sharding' axis during
    the accumulation phase (the reference's GradStorage reduce-scatter),
    realized as placement: `reshard_grads()` annotates + device_puts each
    grad to its sharded layout, so between backward and step each device
    holds 1/N of every grad at rest. step() reshards then updates."""

    def __init__(self, params, optim, group=None, offload=False, device="tpu",
                 **kw):
        if offload:
            raise NotImplementedError(
                "GroupShardedOptimizerStage2(offload=True): CPU offload is "
                "not implemented on the TPU backend (HBM-resident sharded "
                "state is the design; see group_sharded.py docstring)")
        super().__init__(optim)
        self._params = list(params)

    def reshard_grads(self) -> int:
        """Place every accumulated grad sharded-at-rest; returns #sharded.
        Placement itself delegates to parallel.with_spec — the one
        validate-then-device_put implementation — so stage-2 grads follow
        the same rules (and the same failure tolerance) as every other
        tensor."""
        import jax
        from .....parallel import current_mesh, with_spec
        if current_mesh() is None:
            return 0
        n = 0
        for p in self._params:
            g = p.grad
            if g is None or isinstance(g._data, jax.core.Tracer):
                continue
            spec = g.sharding_spec or shard_spec_for(g)
            if spec is None:
                continue
            before = g._data
            try:
                with_spec(g, *spec)
            except Exception:
                continue
            if g._data is not before:
                n += 1
        return n

    def step(self):
        self.reshard_grads()
        self._inner.step()


class GroupShardedStage2(Layer):
    """Stage-2 model wrapper. Knob semantics on TPU: `buffer_max_size`
    (GradStorage bucketing) and comm/calc overlap are obviated — XLA fuses
    and schedules collectives; they are accepted for API parity and
    ignored. `offload` is NOT supported and raises (see optimizer)."""

    _warned_ignored = False

    def __init__(self, layer, sharding_optimizer, group=None, sync_buffers=False,
                 buffer_max_size=2 ** 23, auto_refresh_trainable=True,
                 device="tpu", dp_group=None):
        super().__init__()
        if ((sync_buffers or buffer_max_size != 2 ** 23)
                and not GroupShardedStage2._warned_ignored):
            GroupShardedStage2._warned_ignored = True
            import warnings
            warnings.warn(
                "GroupShardedStage2: buffer_max_size/sync_buffers are "
                "accepted for API parity but ignored on TPU — XLA fuses "
                "gradient collectives and schedules overlap itself",
                UserWarning, stacklevel=2)
        self._layers = layer
        self._sharding_optimizers = (sharding_optimizer
                                     if isinstance(sharding_optimizer, list)
                                     else [sharding_optimizer])

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, sd, *a, **k):
        return self._layers.set_state_dict(sd, *a, **k)

    def parameters(self, *a, **k):
        return self._layers.parameters(*a, **k)

    def named_parameters(self, *a, **k):
        return self._layers.named_parameters(*a, **k)

    def to(self, *a, **k):
        self._layers.to(*a, **k)
        return self

    def clear_gradients(self):
        self._layers.clear_gradients()


class GroupShardedStage3(Layer):
    """Stage 3: parameters sharded at rest over the 'sharding' axis."""

    def __init__(self, layer, optimizer=None, group=None, sync_buffers=False,
                 device="tpu", segment_size=2 ** 20, pretrain_sync_models=True,
                 offload=False, sync_comm=False, dp_group=None,
                 exclude_layer=None):
        super().__init__()
        if offload:
            raise NotImplementedError(
                "GroupShardedStage3(offload=True): CPU offload is not "
                "implemented on the TPU backend — parameters rest sharded "
                "in HBM; a user porting reference offload configs must "
                "drop the flag rather than silently lose the behavior")
        self._layers = layer
        self._optimizer = optimizer
        for _, p in layer.named_parameters():
            if p.ndim == 0:
                continue
            if p.sharding_spec is None:
                p.sharding_spec = shard_spec_for(p)
            elif "sharding" not in str(p.sharding_spec):
                # TP param: compose ZeRO-3 with the existing 'mp' axis so
                # the at-rest shard is 1/(mp·sharding) per device
                combined = augment_spec_for(p)
                if combined is not None:
                    p.sharding_spec = combined
        if optimizer is not None:
            annotate_optimizer_sharding(optimizer)

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def get_all_parameters(self, convert2cpu: bool = False):
        """Reference: regather every param slice once for save. On the mesh
        the full value is recoverable by dropping the sharding constraint —
        state_dict tensors are already logically full."""
        return list(self._layers.parameters())

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, sd, *a, **k):
        return self._layers.set_state_dict(sd, *a, **k)

    def parameters(self, *a, **k):
        return self._layers.parameters(*a, **k)

    def named_parameters(self, *a, **k):
        return self._layers.named_parameters(*a, **k)

    def clear_gradients(self):
        self._layers.clear_gradients()
